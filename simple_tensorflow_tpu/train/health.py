"""stf.train.health: the training-side surface of the numerics-health
plane (stf.debug.numerics; docs/DEBUG.md "Training health").

The plane itself lives in the Session — plans that look like training
steps are auto-instrumented with device-side NumericSummary taps
whenever the resolved mode is not "off", fused windows included. This
module adds the hook-driving layer on top:

- :class:`NumericsHealthHook` — periodic health logging (global grad
  norm, update ratio, nonfinite tap counts) from the process
  :class:`~simple_tensorflow_tpu.debug.numerics.HealthPlane`, plus an
  end-of-training summary. The hook only READS the plane, so it votes
  an unbounded fusion window (``until_next_trigger``): health riding
  inside the fused program is the whole point — the hook must never be
  the reason a window splits.
- ``MonitoredTrainingSession`` auto-installs one when the resolved
  numerics mode (ConfigProto > STF_NUMERICS > process default) is not
  "off" and the caller did not pass their own.
"""

from __future__ import annotations

import os
import sys
from typing import Any, Callable, Dict, Optional

from .session_run_hook import SessionRunHook


def resolved_mode(config=None) -> str:
    """The numerics mode a Session built with ``config`` will run
    under. sys.modules-guarded like the Session's own resolution: when
    debug.numerics was never imported, the env var alone decides, so a
    mode-"off" training job never pays the import."""
    mode = getattr(config, "numerics", None) if config is not None \
        else None
    if mode is not None:
        return mode
    mod = sys.modules.get("simple_tensorflow_tpu.debug.numerics")
    if mod is not None:
        return mod.get_numerics_mode()
    env = os.environ.get("STF_NUMERICS", "").strip().lower()
    return env if env in ("metrics", "raise", "dump") else "off"


class NumericsHealthHook(SessionRunHook):
    """Log the numerics-health plane's view of training every
    ``every_n_steps`` OBSERVED steps (plane steps, not hook run
    boundaries — a fused window advances many at once), and summarize
    at end().

    The hook is read-only: instrumentation, metrics, /trainz, raising
    and dumping all happen inside the Session regardless of whether
    this hook is installed. What it adds is a human-readable heartbeat
    in the training log and a final anomaly recap."""

    def __init__(self, every_n_steps: int = 100,
                 log_fn: Optional[Callable[[str], None]] = None):
        if every_n_steps < 1:
            raise ValueError(
                f"every_n_steps must be >= 1, got {every_n_steps}")
        self._every_n = int(every_n_steps)
        self._log_fn = log_fn
        self._last_logged = 0

    def _log(self, msg: str) -> None:
        if self._log_fn is not None:
            self._log_fn(msg)
            return
        from ..platform import tf_logging as logging

        logging.info("%s", msg)

    @staticmethod
    def _plane_info() -> Dict[str, Any]:
        from ..debug import numerics as numerics_mod

        return numerics_mod.get_plane().info()

    def begin(self):
        info = self._plane_info()
        self._last_logged = int(info["steps_observed"])

    @staticmethod
    def _format_entry(entry: Dict[str, Any]) -> str:
        parts = [f"numerics health @ step {entry['step']}"]
        if entry.get("grad_norm") is not None:
            parts.append(f"grad_norm={entry['grad_norm']:.6g}")
        if entry.get("update_ratio") is not None:
            parts.append(f"update_ratio={entry['update_ratio']:.6g}")
        parts.append(f"max_abs={entry['max_abs']:.6g}")
        if entry.get("nonfinite_taps"):
            parts.append(f"NONFINITE_TAPS={entry['nonfinite_taps']}")
        return " ".join(parts)

    def after_run(self, run_context, run_values):
        info = self._plane_info()
        steps = int(info["steps_observed"])
        if steps - self._last_logged < self._every_n or \
                not info["history"]:
            return
        self._last_logged = steps
        self._log(self._format_entry(info["history"][-1]))

    def end(self, session):
        info = self._plane_info()
        msg = (f"numerics health: observed {info['steps_observed']} "
               f"steps, {info['anomalies']} anomalies, "
               f"{len(info['taps'])} taps, mode={info['mode']}")
        last = info.get("last_anomaly")
        if last:
            msg += (f"; last anomaly at step {last['step']} "
                    f"({len(last['taps'])} taps)")
            if last.get("dump_root"):
                msg += f", dump at {last['dump_root']}"
        self._log(msg)

    def until_next_trigger(self, global_step):
        # the plane observes INSIDE the fused window; this hook must
        # never be the reason a window splits
        return 1 << 30
