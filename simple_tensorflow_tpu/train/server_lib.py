"""Cluster definition + Server (ref: tensorflow/python/training/server_lib.py,
core/distributed_runtime/rpc/grpc_server_lib.cc).

TPU-native: the reference runs a grpc master/worker per process with
explicit Send/Recv partitioning; on TPU pods the runtime is SPMD — every
host runs the same program and XLA moves data over ICI/DCN. ``Server`` here
bootstraps that: it calls jax.distributed.initialize with
coordinator/process info derived from the ClusterSpec, after which
stf.parallel meshes span all hosts' devices. There is no parameter-server
role; "ps" jobs in a ClusterSpec are rejected with guidance (use fsdp
sharding instead).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Union


class ClusterSpec:
    """(ref: server_lib.py:189 ``class ClusterSpec``)."""

    def __init__(self, cluster):
        if isinstance(cluster, dict):
            self._cluster = {job: (dict(enumerate(tasks))
                                   if isinstance(tasks, list) else dict(tasks))
                             for job, tasks in cluster.items()}
        elif isinstance(cluster, ClusterSpec):
            self._cluster = {j: dict(t) for j, t in cluster._cluster.items()}
        else:
            raise TypeError("cluster must be dict or ClusterSpec")

    def as_dict(self):
        return {job: [t for _, t in sorted(tasks.items())]
                for job, tasks in self._cluster.items()}

    @property
    def jobs(self):
        return list(self._cluster)

    def num_tasks(self, job_name):
        return len(self._cluster[job_name])

    def task_indices(self, job_name):
        return sorted(self._cluster[job_name])

    def task_address(self, job_name, task_index):
        return self._cluster[job_name][task_index]

    def job_tasks(self, job_name):
        return [t for _, t in sorted(self._cluster[job_name].items())]

    def __bool__(self):
        return bool(self._cluster)

    def __eq__(self, other):
        return isinstance(other, ClusterSpec) and \
            self._cluster == other._cluster

    def as_cluster_def(self):
        return self.as_dict()


class ServerDef:
    def __init__(self, cluster, job_name, task_index, protocol):
        self.cluster = cluster
        self.job_name = job_name
        self.task_index = task_index
        self.protocol = protocol


class Server:
    """(ref: server_lib.py:42 ``class Server``) → jax.distributed bootstrap.

    start() initializes the jax distributed runtime (coordinator = task 0 of
    the 'worker' job); join() blocks forever like the reference's grpc
    server join.
    """

    _started = False
    _coordinator = None  # address Session("grpc://…") targets check against

    def __init__(self, server_or_cluster_def, job_name=None, task_index=None,
                 protocol=None, config=None, start=True):
        if isinstance(server_or_cluster_def, (dict, ClusterSpec)):
            cluster = ClusterSpec(server_or_cluster_def)
        else:
            raise TypeError("need ClusterSpec or dict")
        if "ps" in cluster.jobs:
            raise ValueError(
                "Parameter-server clusters do not exist on TPU: all state is "
                "sharded across workers via stf.parallel (fsdp/tp axes). "
                "Define only a 'worker' job.")
        self._cluster = cluster
        self._job_name = job_name or "worker"
        self._task_index = task_index or 0
        self._config = config
        if start:
            self.start()

    @property
    def server_def(self):
        return ServerDef(self._cluster, self._job_name, self._task_index,
                         "grpc+icidcn")

    @property
    def target(self):
        """Session target; stf Sessions are process-local (SPMD), the target
        string is informational."""
        return f"stf://{self._job_name}:{self._task_index}"

    def start(self):
        if Server._started:
            return
        workers = self._cluster.job_tasks(self._job_name)
        n = len(workers)
        if n <= 1:
            Server._started = True
            Server._coordinator = workers[0] if workers else None
            return
        import jax

        coordinator = workers[0]
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=n,
                process_id=self._task_index)
            Server._started = True
            Server._coordinator = coordinator
        except Exception as e:  # pragma: no cover - needs real multi-host
            raise RuntimeError(
                f"jax.distributed.initialize failed for {coordinator}: {e}")

    def join(self):
        import time

        while True:
            time.sleep(3600)

    @staticmethod
    def create_local_server(config=None, start=True):
        return Server({"worker": ["localhost:0"]}, job_name="worker",
                      task_index=0, config=config, start=start)
