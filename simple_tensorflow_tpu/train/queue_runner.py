"""QueueRunner (ref: tensorflow/python/training/queue_runner_impl.py)."""

from __future__ import annotations

import threading

from ..framework import errors
from ..platform import sync as _sync
from ..framework import graph as ops_mod
from .coordinator import Coordinator

GraphKeys = ops_mod.GraphKeys


class QueueRunner:
    """(ref: queue_runner_impl.py:34 ``class QueueRunner``)."""

    def __init__(self, queue=None, enqueue_ops=None, close_op=None,
                 cancel_op=None, queue_closed_exception_types=None,
                 queue_runner_def=None, import_scope=None):
        self._queue = queue
        self._enqueue_ops = list(enqueue_ops or [])
        self._close_op = close_op
        self._exceptions = queue_closed_exception_types or (
            errors.OutOfRangeError, errors.CancelledError)
        self._runs = 0
        self._lock = _sync.Lock("train/queue_runner",
                                rank=_sync.RANK_STATE)
        self._exceptions_raised = []

    @property
    def queue(self):
        return self._queue

    @property
    def enqueue_ops(self):
        return self._enqueue_ops

    @property
    def exceptions_raised(self):
        return self._exceptions_raised

    @property
    def name(self):
        return self._queue.name if self._queue is not None else "queue_runner"

    def _run(self, sess, enqueue_op, coord):
        try:
            while True:
                if coord and coord.should_stop():
                    break
                try:
                    sess.run(enqueue_op)
                except self._exceptions:
                    break
        except Exception as e:  # noqa: BLE001
            with self._lock:
                self._exceptions_raised.append(e)
            if coord:
                coord.request_stop(e)
        finally:
            if self._queue is not None:
                self._queue._host_close()

    def _close_on_stop(self, coord):
        """(ref: queue_runner_impl.py ``_close_on_stop``): when the
        coordinator stops, cancel pending enqueues so runner threads
        blocked on a FULL queue wake with CancelledError instead of
        hanging past the join grace period."""
        coord.wait_for_stop()
        if self._queue is not None:
            self._queue._host_close(cancel_pending=True)

    def create_threads(self, sess, coord=None, daemon=False, start=False):
        threads = [threading.Thread(target=self._run,
                                    args=(sess, op, coord), daemon=daemon,
                                    name=f"stf_queue_runner_{i}")
                   for i, op in enumerate(self._enqueue_ops)]
        if coord:
            # daemon regardless: it parks in wait_for_stop forever when
            # the coordinator is never stopped; it must not keep the
            # process alive
            threads.append(threading.Thread(target=self._close_on_stop,
                                            args=(coord,), daemon=True,
                                            name="stf_queue_runner_closer"))
            for t in threads:
                coord.register_thread(t)
        if start:
            for t in threads:
                t.start()
        return threads


def add_queue_runner(qr, collection=GraphKeys.QUEUE_RUNNERS):
    ops_mod.get_default_graph().add_to_collection(collection, qr)


def start_queue_runners(sess=None, coord=None, daemon=True, start=True,
                        collection=GraphKeys.QUEUE_RUNNERS):
    """(ref: queue_runner_impl.py:387)."""
    from ..client.session import get_default_session

    sess = sess or get_default_session()
    if sess is None:
        raise ValueError("start_queue_runners needs a session")
    threads = []
    for qr in ops_mod.get_default_graph().get_collection(collection):
        threads.extend(qr.create_threads(sess, coord=coord, daemon=daemon,
                                         start=start))
    return threads
