"""Slot variable creation (ref: tensorflow/python/training/slot_creator.py).

Slots inherit the primary variable's sharding so optimizer state is laid out
on the mesh exactly like its parameter (the FSDP/ZeRO property falls out)."""

from __future__ import annotations

from ..framework import graph as ops_mod
from ..ops import array_ops
from ..ops import variables as variables_mod


def create_slot(primary, val, name, colocate_with_primary=True):
    v = variables_mod.Variable(
        val, trainable=False,
        name=f"{primary.var_name}/{name}")
    if primary.sharding is not None:
        v.set_sharding(primary.sharding)
    return v


def create_slot_with_initializer(primary, initializer, shape, dtype, name,
                                 colocate_with_primary=True):
    sh = [int(d) for d in shape.as_list()] if hasattr(shape, "as_list") \
        else [int(d) for d in shape]

    def init():
        try:
            return initializer(sh, dtype=dtype)
        except TypeError:
            return initializer(sh)

    v = variables_mod.Variable(init, trainable=False,
                               name=f"{primary.var_name}/{name}", dtype=dtype)
    if primary.sharding is not None:
        v.set_sharding(primary.sharding)
    return v


def create_zeros_slot(primary, name, dtype=None, colocate_with_primary=True):
    dtype = dtype or primary.dtype.base_dtype
    val = array_ops.zeros([int(d) for d in primary.shape.as_list()],
                          dtype=dtype)
    return create_slot(primary, val, name, colocate_with_primary)
