"""Slot variable creation (ref: tensorflow/python/training/slot_creator.py).

Slots inherit the primary variable's sharding so optimizer state is laid out
on the mesh exactly like its parameter (the FSDP/ZeRO property falls out).

Mixed-precision policy: optimizer STATE for low-precision float params
(bf16/f16/fp8) is kept in float32 — accumulating momenta or Adam second
moments in bf16 (8-bit mantissa) silently loses small updates and wrecks
the effective step size; the reference never hits this because it trains
f32, but bf16 params are the TPU default here. Update math upcasts to f32
and only the final delta rounds back (see train/optimizers.py)."""

from __future__ import annotations

from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from ..ops import array_ops
from ..ops import variables as variables_mod

_LOW_PRECISION = ("bfloat16", "float16", "float8_e4m3fn", "float8_e5m2")


def update_dtype(var):
    """Slot/update compute dtype for ``var``: f32 for low-precision float
    params, the param dtype otherwise."""
    d = var.dtype.base_dtype
    return dtypes_mod.float32 if d.name in _LOW_PRECISION else d


def create_slot(primary, val, name, colocate_with_primary=True):
    v = variables_mod.Variable(
        val, trainable=False,
        name=f"{primary.var_name}/{name}")
    # HBM-ledger class marker (stf.telemetry.memory): slot state
    # accounts as optimizer_slots, not generic device state
    v._mem_class = "optimizer_slots"
    if primary.sharding is not None:
        v.set_sharding(primary.sharding)
    return v


def create_slot_with_initializer(primary, initializer, shape, dtype, name,
                                 colocate_with_primary=True):
    sh = [int(d) for d in shape.as_list()] if hasattr(shape, "as_list") \
        else [int(d) for d in shape]

    def init():
        try:
            return initializer(sh, dtype=dtype)
        except TypeError:
            return initializer(sh)

    v = variables_mod.Variable(init, trainable=False,
                               name=f"{primary.var_name}/{name}", dtype=dtype)
    v._mem_class = "optimizer_slots"
    if primary.sharding is not None:
        v.set_sharding(primary.sharding)
    return v


def create_zeros_slot(primary, name, dtype=None, colocate_with_primary=True):
    dtype = dtype or update_dtype(primary)
    val = array_ops.zeros([int(d) for d in primary.shape.as_list()],
                          dtype=dtype)
    return create_slot(primary, val, name, colocate_with_primary)
