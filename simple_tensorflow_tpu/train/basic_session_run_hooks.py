"""Standard hooks (ref: tensorflow/python/training/basic_session_run_hooks.py)."""

from __future__ import annotations

import time

import numpy as np

from ..framework import errors
from ..platform import tf_logging as logging
from . import session_run_hook
from . import training_util

SessionRunHook = session_run_hook.SessionRunHook
SessionRunArgs = session_run_hook.SessionRunArgs


class SecondOrStepTimer:
    """(ref: basic_session_run_hooks.py:48)."""

    def __init__(self, every_secs=None, every_steps=None):
        if (every_secs is None) == (every_steps is None):
            raise ValueError("exactly one of every_secs/every_steps required")
        self._every_secs = every_secs
        self._every_steps = every_steps
        self._last_time = None
        self._last_step = None

    def should_trigger_for_step(self, step):
        if self._last_step is None:
            return True
        if step == self._last_step:
            return False
        if self._every_secs is not None:
            return time.time() >= self._last_time + self._every_secs
        return step >= self._last_step + self._every_steps

    def update_last_triggered_step(self, step):
        now = time.time()
        elapsed_secs = None if self._last_time is None else now - self._last_time
        elapsed_steps = None if self._last_step is None else step - self._last_step
        self._last_time, self._last_step = now, step
        return elapsed_secs, elapsed_steps

    def last_triggered_step(self):
        return self._last_step

    @property
    def every_steps(self):
        return self._every_steps

    def steps_until_trigger(self, step):
        """Steps until this timer next fires — the hook's fusion-window
        vote (session_run_hook.SessionRunHook.until_next_trigger). 1
        when time-based (a wall-clock trigger cannot be predicted in
        steps) or when the timer has never fired (it wants the next
        boundary). The returned window ENDS at the trigger step —
        CheckpointSaver/StepCounter/SummarySaver observe the boundary
        value and fuse onward. ProfilerHook aligns differently (its
        window must START at the trigger so the whole window is traced)
        and implements its own vote."""
        if self._every_steps is None or self._last_step is None:
            return 1
        return max(1, self._last_step + self._every_steps - step)


class StopAtStepHook(SessionRunHook):
    """(ref: basic_session_run_hooks.py:331)."""

    def __init__(self, num_steps=None, last_step=None):
        if (num_steps is None) == (last_step is None):
            raise ValueError("exactly one of num_steps/last_step required")
        self._num_steps = num_steps
        self._last_step = last_step
        self._global_step_tensor = None

    def begin(self):
        self._global_step_tensor = training_util.get_global_step()
        if self._global_step_tensor is None:
            raise RuntimeError("Global step must be created for StopAtStepHook")

    def after_create_session(self, session, coord):
        if self._last_step is None:
            gs = int(np.asarray(session.run(self._global_step_tensor._ref)))
            self._last_step = gs + self._num_steps

    def before_run(self, run_context):
        return SessionRunArgs(self._global_step_tensor._ref)

    def after_run(self, run_context, run_values):
        gs = int(np.asarray(run_values.results))
        if gs >= self._last_step:
            run_context.request_stop()

    def until_next_trigger(self, global_step):
        # a fused window must not overshoot the stop step
        if self._last_step is None:
            return 1
        return max(1, self._last_step - global_step)


class CheckpointSaverHook(SessionRunHook):
    """(ref: basic_session_run_hooks.py:404).

    Saves are ASYNC by default (``save_async=True``, stf.checkpoint):
    trigger steps pay only the barrier snapshot — donation-safe device
    copies + host state — and the ``stf_ckpt_writer`` thread commits
    while the next fused window runs. ``end()`` (and a blocking save)
    drains the writer, so every checkpoint is durable before the
    session closes. Fusion votes are unchanged: windows still split
    exactly at save boundaries. ``save_async=False`` or a non-native
    Saver backend restores the in-line blocking behavior."""

    def __init__(self, checkpoint_dir, save_secs=None, save_steps=None,
                 saver=None, checkpoint_basename="model.ckpt", scaffold=None,
                 listeners=None, save_async=True):
        import os

        self._checkpoint_dir = checkpoint_dir
        self._save_path = os.path.join(checkpoint_dir, checkpoint_basename)
        self._saver = saver
        self._scaffold = scaffold
        self._timer = SecondOrStepTimer(every_secs=save_secs,
                                        every_steps=save_steps)
        self._listeners = listeners or []
        self._save_async = save_async
        self._async_engine = None

    def begin(self):
        self._global_step_tensor = training_util.get_global_step()
        if self._global_step_tensor is None:
            raise RuntimeError("Global step required for CheckpointSaverHook")
        for l in self._listeners:
            l.begin()

    def _get_saver(self):
        if self._saver is not None:
            return self._saver
        if self._scaffold is not None and self._scaffold.saver is not None:
            return self._scaffold.saver
        from .saver import Saver

        self._saver = Saver()
        return self._saver

    def after_create_session(self, session, coord):
        self._save(session, int(np.asarray(
            session.run(self._global_step_tensor._ref))))

    def before_run(self, run_context):
        return SessionRunArgs(self._global_step_tensor._ref)

    def after_run(self, run_context, run_values):
        step = int(np.asarray(run_values.results))
        if self._timer.should_trigger_for_step(step):
            self._timer.update_last_triggered_step(step)
            self._save(run_context.session, step)

    def until_next_trigger(self, global_step):
        # checkpoints at step boundaries inside a fused window force the
        # window to split at the save step
        return self._timer.steps_until_trigger(global_step)

    def end(self, session):
        # final save is BLOCKING: the process may exit right after, so
        # the writer queue must be drained before end() returns
        self._save(session, int(np.asarray(
            session.run(self._global_step_tensor._ref))),
            blocking=True)

    def _engine_for(self, saver):
        """The async engine for this hook's saver, or None when saves
        should go through ``saver.save`` directly (save_async=False, a
        non-native backend, or a backend="async" saver that already is
        its own engine)."""
        if not self._save_async:
            return None
        if getattr(saver, "_backend", None) != "native":
            return None
        if self._async_engine is None:
            from ..checkpoint.manager import AsyncSaverEngine

            self._async_engine = AsyncSaverEngine(saver)
        return self._async_engine

    def _save(self, session, step, blocking=False):
        for l in self._listeners:
            l.before_save(session, step)
        saver = self._get_saver()
        engine = self._engine_for(saver)
        if engine is not None:
            engine.save(session, self._save_path, global_step=step)
            if blocking:
                engine.wait_until_finished()
        else:
            saver.save(session, self._save_path, global_step=step)
            if blocking and hasattr(saver, "wait_until_finished"):
                saver.wait_until_finished()
        for l in self._listeners:
            l.after_save(session, step)


class CheckpointSaverListener:
    def begin(self):
        pass

    def before_save(self, session, global_step_value):
        pass

    def after_save(self, session, global_step_value):
        pass

    def end(self, session, global_step_value):
        pass


class StepCounterHook(SessionRunHook):
    """(ref: basic_session_run_hooks.py:547) — also reports steps/sec,
    and closes the perf loop: MFU plus measured-over-predicted step time
    from the static cost model over the caller's fetches
    (framework/cost_model.predicted_vs_measured + utils/perf; MFU per
    Kumar et al., arXiv:1909.09756). ``last_perf`` keeps the latest
    report for programmatic consumers."""

    def __init__(self, every_n_steps=100, every_n_secs=None, output_dir=None,
                 summary_writer=None, report_mfu=True):
        self._timer = SecondOrStepTimer(every_secs=every_n_secs,
                                        every_steps=every_n_steps
                                        if every_n_secs is None else None)
        self._summary_writer = summary_writer
        self._output_dir = output_dir
        self._report_mfu = report_mfu
        self._est_cache = None  # (key, CostEstimate): graph walk done once
        self.last_steps_per_sec = None
        self.last_perf = None

    def begin(self):
        self._global_step_tensor = training_util.get_global_step()
        if self._summary_writer is None and self._output_dir:
            from ..summary.writer.writer import FileWriter

            self._summary_writer = FileWriter(self._output_dir)

    def before_run(self, run_context):
        return SessionRunArgs(self._global_step_tensor._ref)

    def until_next_trigger(self, global_step):
        # only needs global_step at its reporting boundary: a fused
        # window up to the next report keeps steps/sec exact (steps are
        # counted from the global_step delta, not from run calls)
        return self._timer.steps_until_trigger(global_step)

    def _perf_report(self, run_context, sec_per_step):
        """Best-effort: the caller's fetches drive the cost model; a
        fetch the model can't cost must never break the training loop."""
        try:
            from ..framework import cost_model
            from ..framework import graph as ops_mod
            from ..utils import nest

            items = [f for f in nest.flatten(run_context.original_args.fetches)
                     if isinstance(f, (ops_mod.Tensor, ops_mod.Operation))
                     or hasattr(f, "_ref")]
            if not items:
                return None
            # the static estimate is a full graph walk — cache it per
            # (fetches, rewrite_version) so every trigger only pays the
            # measured-side arithmetic
            graph = run_context.session.graph
            key = (tuple(id(i) for i in items),
                   getattr(graph, "_rewrite_version", 0))
            if self._est_cache is None or self._est_cache[0] != key:
                self._est_cache = (key, cost_model.estimate(items))
            return cost_model.predicted_vs_measured(
                items, measured_seconds=sec_per_step,
                est=self._est_cache[1])
        except Exception:
            return None

    def after_run(self, run_context, run_values):
        step = int(np.asarray(run_values.results))
        if self._timer.should_trigger_for_step(step):
            secs, steps = self._timer.update_last_triggered_step(step)
            if secs is not None and secs > 0:
                self.last_steps_per_sec = steps / secs
                logging.info("global_step/sec: %.4g", self.last_steps_per_sec)
                perf_report = (self._perf_report(run_context, secs / steps)
                               if self._report_mfu else None)
                if perf_report is not None:
                    self.last_perf = perf_report
                    logging.info(
                        "perf: mfu=%.4g measured/predicted=%.3g",
                        perf_report.get("mfu", 0.0),
                        perf_report.get("measured_over_predicted", 0.0))
                if self._summary_writer is not None:
                    self._summary_writer.add_summary_value(
                        "global_step/sec", self.last_steps_per_sec, step)
                    if perf_report is not None:
                        if "mfu" in perf_report:
                            self._summary_writer.add_summary_value(
                                "perf/mfu", perf_report["mfu"], step)
                        if "measured_over_predicted" in perf_report:
                            self._summary_writer.add_summary_value(
                                "perf/measured_over_predicted",
                                perf_report["measured_over_predicted"],
                                step)


class LoggingTensorHook(SessionRunHook):
    """(ref: basic_session_run_hooks.py:167)."""

    def __init__(self, tensors, every_n_iter=None, every_n_secs=None,
                 at_end=False, formatter=None):
        if isinstance(tensors, dict):
            self._tag_order = list(tensors)
            self._tensors = tensors
        else:
            self._tag_order = [getattr(t, "name", str(i))
                               for i, t in enumerate(tensors)]
            self._tensors = dict(zip(self._tag_order, tensors))
        self._formatter = formatter
        self._timer = SecondOrStepTimer(every_secs=every_n_secs,
                                        every_steps=every_n_iter)
        self._at_end = at_end
        self._iter = 0

    def before_run(self, run_context):
        self._should_log = self._timer.should_trigger_for_step(self._iter)
        if self._should_log:
            return SessionRunArgs(self._tensors)
        return None

    def after_run(self, run_context, run_values):
        if self._should_log:
            self._timer.update_last_triggered_step(self._iter)
            vals = run_values.results
            if self._formatter:
                logging.info(self._formatter(vals))
            else:
                logging.info(", ".join(
                    f"{tag} = {vals[tag]}" for tag in self._tag_order))
        self._iter += 1

    def end(self, session):
        if self._at_end:
            vals = session.run(self._tensors)
            logging.info(", ".join(
                f"{tag} = {vals[tag]}" for tag in self._tag_order))


class NanLossDuringTrainingError(RuntimeError):
    pass


class NanTensorHook(SessionRunHook):
    """(ref: basic_session_run_hooks.py:635)."""

    def __init__(self, loss_tensor, fail_on_nan_loss=True):
        self._loss_tensor = loss_tensor
        self._fail = fail_on_nan_loss

    def before_run(self, run_context):
        return SessionRunArgs(self._loss_tensor)

    def after_run(self, run_context, run_values):
        if np.isnan(np.asarray(run_values.results)).any():
            if self._fail:
                raise NanLossDuringTrainingError("NaN loss during training.")
            logging.warning("NaN loss; stopping training.")
            run_context.request_stop()


class SummarySaverHook(SessionRunHook):
    """(ref: basic_session_run_hooks.py:683)."""

    def __init__(self, save_steps=None, save_secs=None, output_dir=None,
                 summary_writer=None, scaffold=None, summary_op=None):
        self._summary_op = summary_op
        self._scaffold = scaffold
        self._output_dir = output_dir
        self._summary_writer = summary_writer
        self._timer = SecondOrStepTimer(every_secs=save_secs,
                                        every_steps=save_steps)

    def begin(self):
        self._global_step_tensor = training_util.get_global_step()
        if self._summary_writer is None and self._output_dir:
            from ..summary.writer.writer import FileWriter

            self._summary_writer = FileWriter(self._output_dir)

    def _get_op(self):
        if self._summary_op is not None:
            return self._summary_op
        if self._scaffold is not None:
            return self._scaffold.summary_op
        from ..summary import summary as summary_mod

        return summary_mod.merge_all()

    def before_run(self, run_context):
        op = self._get_op()
        self._should = (op is not None and
                        self._timer.should_trigger_for_step(
                            self._timer.last_triggered_step() or 0) or
                        self._timer.last_triggered_step() is None)
        fetches = {"step": self._global_step_tensor._ref}
        if self._should and op is not None:
            fetches["summary"] = op
        return SessionRunArgs(fetches)

    def after_run(self, run_context, run_values):
        step = int(np.asarray(run_values.results["step"]))
        if "summary" in run_values.results and self._summary_writer:
            if self._timer.should_trigger_for_step(step):
                self._timer.update_last_triggered_step(step)
                self._summary_writer.add_summary(
                    run_values.results["summary"], step)

    def until_next_trigger(self, global_step):
        # summaries evaluate at the window boundary; a save step inside
        # the window splits it (also: a summary fetch makes the plan a
        # host sink, so the boundary step itself runs unfused)
        return self._timer.steps_until_trigger(global_step)

    def end(self, session):
        if self._summary_writer:
            self._summary_writer.flush()


class GlobalStepWaiterHook(SessionRunHook):
    """(ref: basic_session_run_hooks.py:775)."""

    def __init__(self, wait_until_step):
        self._wait_until_step = wait_until_step

    def begin(self):
        self._global_step_tensor = training_util.get_global_step()

    def until_next_trigger(self, global_step):
        return 1 << 30  # waits BEFORE runs; no per-step observation

    def before_run(self, run_context):
        if self._wait_until_step <= 0:
            return None
        while True:
            gs = int(np.asarray(run_context.session.run(
                self._global_step_tensor._ref)))
            if gs >= self._wait_until_step:
                return None
            time.sleep(0.5)


class FinalOpsHook(SessionRunHook):
    """(ref: basic_session_run_hooks.py:812)."""

    def __init__(self, final_ops, final_ops_feed_dict=None):
        self._final_ops = final_ops
        self._feed = final_ops_feed_dict
        self.final_ops_values = None

    def until_next_trigger(self, global_step):
        return 1 << 30  # only acts at end()

    def end(self, session):
        if self._final_ops is not None:
            self.final_ops_values = session.run(self._final_ops,
                                                feed_dict=self._feed)


class FeedFnHook(SessionRunHook):
    def __init__(self, feed_fn):
        self._feed_fn = feed_fn

    def before_run(self, run_context):
        return SessionRunArgs(fetches=None, feed_dict=self._feed_fn())


class ProfilerHook(SessionRunHook):
    """(ref: basic_session_run_hooks.py:846): requests a
    ``SOFTWARE_TRACE`` run on trigger steps and writes the resulting
    step-stats timeline as ``timeline-<step>.json`` chrome traces
    (load in Perfetto / chrome://tracing). Logs the traced step's MFU
    from the executable's XLA cost analysis when available.
    ``use_jax_profiler=True`` additionally wraps trigger steps in a
    jax.profiler trace (the XLA-kernel-level view)."""

    def __init__(self, save_steps=None, save_secs=None,
                 output_dir="", show_dataflow=True, show_memory=False,
                 use_jax_profiler=False):
        self._output_dir = output_dir or "."
        self._timer = SecondOrStepTimer(every_secs=save_secs,
                                        every_steps=save_steps)
        self._show_dataflow = show_dataflow
        self._show_memory = show_memory
        self._use_jax_profiler = use_jax_profiler
        self._jax_tracing = False
        self._request_summary = False
        self._next_step = None
        self.last_trace_path = None

    def begin(self):
        self._global_step_tensor = training_util.get_global_step()
        self._next_step = None

    def until_next_trigger(self, global_step):
        # ISSUE 8 satellite: the profiler's window must START at its
        # trigger so the run it arms (SOFTWARE_TRACE via before_run) is
        # one whole fused window — previously the armed trigger either
        # vanished into an untraced window or silently forced a single
        # unfused step. Away from the trigger, vote the distance to the
        # step BEFORE it (the next window then begins exactly at the
        # trigger); at the trigger (or before any trigger), vote the
        # full cadence. run_steps records the window's spans + per-op
        # attribution under SOFTWARE_TRACE, and _save annotates the
        # timeline with the window's global-step range.
        every = self._timer.every_steps
        if every is None:
            return 1  # time-based: a wall-clock trigger is unpredictable
        last = self._timer.last_triggered_step()
        next_step = global_step + 1  # first step of the window voted on
        if last is None or next_step >= last + every:
            return every
        return last + every - next_step

    def before_run(self, run_context):
        self._request_summary = (
            self._next_step is None
            or self._timer.should_trigger_for_step(self._next_step))
        opts = None
        if self._request_summary:
            from ..client.session import RunOptions

            opts = RunOptions(trace_level=RunOptions.SOFTWARE_TRACE)
            if self._use_jax_profiler and not self._jax_tracing:
                import jax

                try:
                    jax.profiler.start_trace(self._output_dir)
                    self._jax_tracing = True
                except Exception:
                    pass
        return SessionRunArgs(self._global_step_tensor._ref, options=opts)

    def after_run(self, run_context, run_values):
        step = int(np.asarray(run_values.results))
        if self._request_summary:
            # anchor the cadence at the traced WINDOW'S START, not its
            # end: with update-at-end, save_steps=N under fusion would
            # stretch the real period to ~2N-1 (N to the next trigger
            # PLUS the window the timer just swallowed). Anchored at the
            # start, trace windows begin exactly every N steps.
            start = step
            md = run_values.run_metadata
            fusion = (getattr(md, "step_stats", None) or {}).get(
                "loop_fusion") or {}
            if fusion.get("fused") and fusion.get("n_steps"):
                start = step - int(fusion["n_steps"]) + 1
            self._timer.update_last_triggered_step(start)
            if run_values.run_metadata is not None:
                self._save(step, run_values.run_metadata)
            if self._jax_tracing:
                import jax

                try:
                    jax.profiler.stop_trace()
                except Exception:
                    pass
                self._jax_tracing = False
        self._next_step = step + 1

    def _save(self, step, run_metadata):
        import os

        from ..client.timeline import Timeline

        os.makedirs(self._output_dir, exist_ok=True)
        path = os.path.join(self._output_dir, f"timeline-{step}.json")
        stats0 = getattr(run_metadata, "step_stats", None)
        fusion = (stats0 or {}).get("loop_fusion") or {}
        if fusion.get("fused") and fusion.get("n_steps"):
            # the trace covers a fused window ending at `step`: annotate
            # the timeline with the window's global-step range so the
            # reader knows which steps the one fused bar spans
            n = int(fusion["n_steps"])
            stats0["window_steps"] = [step - n + 1, step]
        with open(path, "w") as f:
            f.write(Timeline(run_metadata).generate_chrome_trace_format(
                show_dataflow=self._show_dataflow,
                show_memory=self._show_memory))
        self.last_trace_path = path
        stats = getattr(run_metadata, "step_stats", None) or {}
        cost = getattr(run_metadata, "cost_graph", None) or {}
        wall = stats.get("wall_time_s")
        if wall and cost.get("flops"):
            from ..utils import perf

            logging.info(
                "ProfilerHook step %d: wall=%.4gs xla_flops=%.3g "
                "mfu=%.4g trace=%s", step, wall, cost["flops"],
                perf.mfu(cost["flops"], wall), path)
        else:
            logging.info("ProfilerHook step %d: trace=%s", step, path)
