"""LR schedules (ref: tensorflow/python/training/learning_rate_decay.py).

Schedules are graph expressions of global_step, so the LR computation lives
inside the compiled step (no host round-trip per step).
"""

from __future__ import annotations

import math

from ..framework import graph as ops_mod
from ..ops import math_ops, array_ops, control_flow_ops


def _step_float(global_step):
    gs = global_step._ref if hasattr(global_step, "_ref") else global_step
    return math_ops.cast(gs, "float32")


def exponential_decay(learning_rate, global_step, decay_steps, decay_rate,
                      staircase=False, name=None):
    """(ref: learning_rate_decay.py:30)."""
    lr = ops_mod.convert_to_tensor(learning_rate, dtype="float32")
    p = _step_float(global_step) / float(decay_steps)
    if staircase:
        p = math_ops.floor(p)
    return math_ops.multiply(
        lr, math_ops.pow(ops_mod.convert_to_tensor(float(decay_rate)), p),
        name=name)


def piecewise_constant(x, boundaries, values, name=None):
    """(ref: learning_rate_decay.py ``piecewise_constant``)."""
    step = _step_float(x)
    out = ops_mod.convert_to_tensor(float(values[0]))
    for b, v in zip(boundaries, values[1:]):
        out = array_ops.where(
            math_ops.greater(step, ops_mod.convert_to_tensor(float(b))),
            ops_mod.convert_to_tensor(float(v)), out)
    return out


def polynomial_decay(learning_rate, global_step, decay_steps,
                     end_learning_rate=0.0001, power=1.0, cycle=False,
                     name=None):
    lr = ops_mod.convert_to_tensor(float(learning_rate))
    end_lr = ops_mod.convert_to_tensor(float(end_learning_rate))
    step = _step_float(global_step)
    ds = ops_mod.convert_to_tensor(float(decay_steps))
    if cycle:
        mult = math_ops.maximum(ops_mod.convert_to_tensor(1.0),
                                math_ops.ceil(step / ds))
        ds = ds * mult
    else:
        step = math_ops.minimum(step, ds)
    frac = math_ops.pow(1.0 - step / ds,
                        ops_mod.convert_to_tensor(float(power)))
    return math_ops.add((lr - end_lr) * frac, end_lr, name=name)


def natural_exp_decay(learning_rate, global_step, decay_steps, decay_rate,
                      staircase=False, name=None):
    lr = ops_mod.convert_to_tensor(float(learning_rate))
    p = _step_float(global_step) / float(decay_steps)
    if staircase:
        p = math_ops.floor(p)
    return math_ops.multiply(
        lr, math_ops.exp(-float(decay_rate) * p), name=name)


def inverse_time_decay(learning_rate, global_step, decay_steps, decay_rate,
                       staircase=False, name=None):
    lr = ops_mod.convert_to_tensor(float(learning_rate))
    p = _step_float(global_step) / float(decay_steps)
    if staircase:
        p = math_ops.floor(p)
    return math_ops.divide(lr, 1.0 + float(decay_rate) * p, name=name)


def cosine_decay(learning_rate, global_step, decay_steps, alpha=0.0,
                 name=None):
    lr = ops_mod.convert_to_tensor(float(learning_rate))
    step = math_ops.minimum(_step_float(global_step), float(decay_steps))
    frac = step / float(decay_steps)
    cos = 0.5 * (1.0 + math_ops.cos(
        ops_mod.convert_to_tensor(math.pi) * frac))
    return math_ops.multiply(lr, (1 - alpha) * cos + alpha, name=name)


def cosine_decay_restarts(learning_rate, global_step, first_decay_steps,
                          t_mul=2.0, m_mul=1.0, alpha=0.0, name=None):
    # single-cycle approximation beyond first restart boundary
    return cosine_decay(learning_rate, global_step, first_decay_steps, alpha,
                        name)


def linear_cosine_decay(learning_rate, global_step, decay_steps,
                        num_periods=0.5, alpha=0.0, beta=0.001, name=None):
    lr = ops_mod.convert_to_tensor(float(learning_rate))
    step = math_ops.minimum(_step_float(global_step), float(decay_steps))
    frac = step / float(decay_steps)
    linear = 1.0 - frac
    cos = math_ops.cos(ops_mod.convert_to_tensor(
        2.0 * math.pi * num_periods) * frac)
    return math_ops.multiply(
        lr, (alpha + linear) * (0.5 * (1.0 + cos)) + beta, name=name)
