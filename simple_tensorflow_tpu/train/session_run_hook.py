"""SessionRunHook protocol (ref: tensorflow/python/training/session_run_hook.py)."""

from __future__ import annotations

import collections


class SessionRunHook:
    def begin(self):
        pass

    def after_create_session(self, session, coord):
        pass

    def before_run(self, run_context):
        return None

    def after_run(self, run_context, run_values):
        pass

    def end(self, session):
        pass

    def until_next_trigger(self, global_step):
        """How many further training steps this hook tolerates before it
        must observe a run boundary — the hook's vote on the multi-step
        fusion window (docs/PERFORMANCE.md): a MonitoredSession driving
        ``Session.run_steps`` caps every window at the minimum vote, so
        a hook that triggers at step K still observes exactly at K.
        Return 1 (the conservative default) to see every step; step-
        periodic hooks return the distance to their next trigger; hooks
        with no per-step needs return a large value."""
        return 1


SessionRunArgs = collections.namedtuple(
    "SessionRunArgs", ["fetches", "feed_dict", "options"])
SessionRunArgs.__new__.__defaults__ = (None, None)

SessionRunValues = collections.namedtuple(
    "SessionRunValues", ["results", "options", "run_metadata"])


class SessionRunContext:
    def __init__(self, original_args, session):
        self._original_args = original_args
        self._session = session
        self._stop_requested = False

    @property
    def original_args(self):
        return self._original_args

    @property
    def session(self):
        return self._session

    @property
    def stop_requested(self):
        return self._stop_requested

    def request_stop(self):
        self._stop_requested = True
