"""SessionRunHook protocol (ref: tensorflow/python/training/session_run_hook.py)."""

from __future__ import annotations

import collections


class SessionRunHook:
    def begin(self):
        pass

    def after_create_session(self, session, coord):
        pass

    def before_run(self, run_context):
        return None

    def after_run(self, run_context, run_values):
        pass

    def end(self, session):
        pass


SessionRunArgs = collections.namedtuple(
    "SessionRunArgs", ["fetches", "feed_dict", "options"])
SessionRunArgs.__new__.__defaults__ = (None, None)

SessionRunValues = collections.namedtuple(
    "SessionRunValues", ["results", "options", "run_metadata"])


class SessionRunContext:
    def __init__(self, original_args, session):
        self._original_args = original_args
        self._session = session
        self._stop_requested = False

    @property
    def original_args(self):
        return self._original_args

    @property
    def session(self):
        return self._session

    @property
    def stop_requested(self):
        return self._stop_requested

    def request_stop(self):
        self._stop_requested = True
