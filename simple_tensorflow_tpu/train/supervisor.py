"""Supervisor (ref: tensorflow/python/training/supervisor.py) — legacy
training harness predating MonitoredTrainingSession; kept for parity and
implemented on top of the same pieces."""

from __future__ import annotations

import contextlib
import os
import time

from ..framework import graph as ops_mod
from ..ops import variables as variables_mod
from ..client.session import Session
from . import training_util
from .coordinator import Coordinator
from .queue_runner import start_queue_runners
from .saver import Saver, latest_checkpoint

USE_DEFAULT = 0


class Supervisor:
    """(ref: supervisor.py:36 ``class Supervisor``)."""

    def __init__(self, graph=None, ready_op=USE_DEFAULT,
                 ready_for_local_init_op=USE_DEFAULT, is_chief=True,
                 init_op=USE_DEFAULT, init_feed_dict=None,
                 local_init_op=USE_DEFAULT, logdir=None, summary_op=USE_DEFAULT,
                 saver=USE_DEFAULT, global_step=USE_DEFAULT,
                 save_summaries_secs=120, save_model_secs=600,
                 recovery_wait_secs=30, stop_grace_secs=120,
                 checkpoint_basename="model.ckpt", session_manager=None,
                 summary_writer=USE_DEFAULT, init_fn=None):
        self._graph = graph or ops_mod.get_default_graph()
        self._is_chief = is_chief
        self._logdir = logdir
        self._save_model_secs = save_model_secs
        self._checkpoint_basename = checkpoint_basename
        self._coord = Coordinator()
        self._init_fn = init_fn
        self._init_feed_dict = init_feed_dict
        with ops_mod._as_current(self._graph):
            self._init_op = (variables_mod.global_variables_initializer()
                             if init_op is USE_DEFAULT else init_op)
            self._saver = Saver() if saver is USE_DEFAULT else saver
            self._global_step = (training_util.get_global_step(self._graph)
                                 if global_step is USE_DEFAULT else global_step)
        self._last_save = 0.0

    @property
    def coord(self):
        return self._coord

    @property
    def saver(self):
        return self._saver

    @property
    def global_step(self):
        return self._global_step

    @property
    def session_manager(self):
        from .monitored_session import SessionManager

        return SessionManager(graph=self._graph)

    def prepare_or_wait_for_session(self, master="", config=None,
                                    wait_for_checkpoint=False,
                                    max_wait_secs=7200,
                                    start_standard_services=True):
        """(ref: supervisor.py:650)."""
        sess = Session(master, graph=self._graph, config=config)
        restored = False
        if self._logdir:
            path = latest_checkpoint(self._logdir)
            if path:
                self._saver.restore(sess, path)
                restored = True
        if not restored and self._init_op is not None:
            sess.run(self._init_op, feed_dict=self._init_feed_dict)
        if self._init_fn:
            self._init_fn(sess)
        if start_standard_services:
            self.start_standard_services(sess)
        self._sess = sess
        return sess

    def start_standard_services(self, sess):
        return start_queue_runners(sess, coord=self._coord)

    def start_queue_runners(self, sess, queue_runners=None):
        return start_queue_runners(sess, coord=self._coord)

    @contextlib.contextmanager
    def managed_session(self, master="", config=None,
                        start_standard_services=True,
                        close_summary_writer=True):
        """(ref: supervisor.py:908 ``managed_session``)."""
        sess = self.prepare_or_wait_for_session(
            master, config, start_standard_services=start_standard_services)
        try:
            yield sess
        except Exception as e:  # noqa: BLE001
            self._coord.request_stop(e)
        finally:
            try:
                if self._is_chief and self._logdir and self._saver:
                    self._saver.save(
                        sess, os.path.join(self._logdir,
                                           self._checkpoint_basename),
                        global_step=self._global_step)
            except Exception:
                pass
            self.stop()
            sess.close()
        self._coord.raise_requested_exception()

    def should_stop(self):
        return self._coord.should_stop()

    def request_stop(self, ex=None):
        self._coord.request_stop(ex)

    def stop(self, threads=None, close_summary_writer=True):
        self._coord.request_stop()
        try:
            self._coord.join(threads, stop_grace_period_secs=2,
                             ignore_live_threads=True)
        except Exception:
            pass

    def summary_computed(self, sess, summary, global_step=None):
        pass

    def loop(self, timer_interval_secs, target, args=None, kwargs=None):
        from .coordinator import LooperThread

        return LooperThread.loop(self._coord, timer_interval_secs, target,
                                 args, kwargs)

    def maybe_save(self, sess):
        now = time.time()
        if (self._is_chief and self._logdir and
                now - self._last_save > self._save_model_secs):
            self._last_save = now
            self._saver.save(sess, os.path.join(self._logdir,
                                                self._checkpoint_basename),
                             global_step=self._global_step)
