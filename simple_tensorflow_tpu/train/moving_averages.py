"""ExponentialMovingAverage (ref: tensorflow/python/training/moving_averages.py)."""

from __future__ import annotations

from ..framework import graph as ops_mod
from ..ops import control_flow_ops, math_ops, state_ops
from ..ops import variables as variables_mod
from . import slot_creator

GraphKeys = ops_mod.GraphKeys


def assign_moving_average(variable, value, decay, zero_debias=True, name=None):
    """(ref: moving_averages.py:32)."""
    decay_t = ops_mod.convert_to_tensor(decay,
                                        dtype=variable.dtype.base_dtype)
    one = ops_mod.convert_to_tensor(1.0, dtype=variable.dtype.base_dtype)
    delta = (variable._ref - value) * (one - decay_t)
    return state_ops.assign_sub(variable._ref, delta, name=name)


class ExponentialMovingAverage:
    """(ref: moving_averages.py:268 ``class ExponentialMovingAverage``)."""

    def __init__(self, decay, num_updates=None, zero_debias=False,
                 name="ExponentialMovingAverage"):
        self._decay = decay
        self._num_updates = num_updates
        self._name = name
        self._averages = {}

    @property
    def name(self):
        return self._name

    def apply(self, var_list=None):
        if var_list is None:
            var_list = variables_mod.trainable_variables()
        g = ops_mod.get_default_graph()
        updates = []
        for var in var_list:
            if var not in self._averages:
                avg = slot_creator.create_slot(
                    var, var.initialized_value(), self._name)
                self._averages[var] = avg
                g.add_to_collection(GraphKeys.MOVING_AVERAGE_VARIABLES, var)
        decay = ops_mod.convert_to_tensor(float(self._decay))
        if self._num_updates is not None:
            n = math_ops.cast(
                self._num_updates._ref if hasattr(self._num_updates, "_ref")
                else self._num_updates, "float32")
            decay = math_ops.minimum(decay, (1.0 + n) / (10.0 + n))
        for var in var_list:
            avg = self._averages[var]
            d = math_ops.cast(decay, var.dtype.base_dtype)
            updates.append(assign_moving_average(avg, var._ref, d).op)
        return control_flow_ops.group(*updates, name=self._name)

    def average(self, var):
        return self._averages.get(var)

    def average_name(self, var):
        return var.var_name + "/" + self._name

    def variables_to_restore(self, moving_avg_variables=None):
        out = {}
        if moving_avg_variables is None:
            moving_avg_variables = list(self._averages)
        for var in moving_avg_variables:
            out[self.average_name(var)] = self._averages.get(var, var)
        return out
