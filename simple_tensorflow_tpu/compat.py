"""Compat helpers (ref: tensorflow/python/util/compat.py)."""

from __future__ import annotations

import numbers

import numpy as np


def as_bytes(bytes_or_text, encoding="utf-8"):
    if isinstance(bytes_or_text, str):
        return bytes_or_text.encode(encoding)
    if isinstance(bytes_or_text, bytes):
        return bytes_or_text
    raise TypeError(f"Expected str/bytes, got {type(bytes_or_text)}")


def as_text(bytes_or_text, encoding="utf-8"):
    if isinstance(bytes_or_text, bytes):
        return bytes_or_text.decode(encoding)
    if isinstance(bytes_or_text, str):
        return bytes_or_text
    raise TypeError(f"Expected str/bytes, got {type(bytes_or_text)}")


as_str = as_text
as_str_any = lambda v: v if isinstance(v, str) else str(v)  # noqa: E731

integral_types = (numbers.Integral, np.integer)
real_types = (numbers.Real, np.integer, np.floating)
complex_types = (numbers.Complex, np.number)
bytes_or_text_types = (bytes, str)


def forward_compatible(year, month, day):
    return True
