"""Compiler utilities: AOT compile + executable cache (ref compiler/aot)."""

from . import aot  # noqa: F401
