"""AOT compilation + executable cache
(ref: tensorflow/compiler/aot — tfcompile turns a frozen subgraph into a
standalone object file).

TPU-native, AOT = lower the fetch subgraph to one XLA program ahead of
Session.run and persist the compiled executable, so process restart skips
the (20-40s) TPU compile. Two layers:
- ``compile_fetches``: graph -> pure fn -> jax.jit(...).lower().compile(),
  returning an AotExecutable with HLO text, cost analysis, and a stable
  cache key.
- ``compile_step``: AOT-compile an already-planned Session step for ONE
  concrete feed-shape bucket (state avals from the live variable store),
  returning an AotStepExecutable the session's device dispatch calls in
  place of the jit path. ``stf.serving.ModelServer`` warms one per batch
  bucket at load so the first request of every bucket shape skips the
  trace+compile (ref: the reference's Servable warmup,
  tensorflow_serving/servables).
- ``enable_persistent_cache``: turns on jax's compilation cache directory,
  the PJRT-level equivalent of tfcompile's ahead-of-time object files —
  keyed by HLO, shared across processes.
"""

from __future__ import annotations

import hashlib
import os
from typing import Any, Dict, List, Optional, Sequence

from ..framework import graph as ops_mod
from ..framework import lowering as lowering_mod


_persistent_cache_dir: Optional[str] = None


def enable_persistent_cache(cache_dir: str) -> None:
    """Persist compiled executables under ``cache_dir`` (survives process
    restarts; subsequent compiles of the same HLO are disk hits)."""
    import jax

    global _persistent_cache_dir
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # cache everything, however fast the compile was
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    _persistent_cache_dir = cache_dir


def persistent_cache_dir() -> Optional[str]:
    """The enabled persistent-cache directory, or None. The kernel
    registry persists its micro-autotune verdicts alongside it
    (stf.kernels; docs/PERFORMANCE.md)."""
    return _persistent_cache_dir


class _CompiledBundle:
    """Shared introspection over a (lowered, compiled) XLA pair."""

    def __init__(self, compiled, lowered, key):
        self._compiled = compiled
        self._lowered = lowered
        self.cache_key = key

    @property
    def hlo_text(self) -> str:
        return self._lowered.as_text()

    def cost_analysis(self) -> Dict[str, Any]:
        """XLA's estimate: flops, bytes accessed — feeds stf.utils.perf."""
        ca = self._compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        return dict(ca) if ca else {}

    def memory_analysis(self):
        return self._compiled.memory_analysis()


class AotExecutable(_CompiledBundle):
    """A compiled fetch subgraph: call with feed values in declared order."""

    def __init__(self, compiled, lowered, feed_tensors, fetch_tensors, key):
        super().__init__(compiled, lowered, key)
        self.feed_tensors = list(feed_tensors)
        self.fetch_tensors = list(fetch_tensors)

    def __call__(self, *feed_values):
        if len(feed_values) != len(self.feed_tensors):
            raise ValueError(
                f"expected {len(self.feed_tensors)} feeds "
                f"({[t.name for t in self.feed_tensors]}), "
                f"got {len(feed_values)}")
        out = self._compiled(*feed_values)
        return out


def feed_signature(feed_args: Dict[str, Any]):
    """Stable key for one concrete feed-shape bucket: sorted (name,
    shape, dtype) triples. ``feed_args`` values may be numpy arrays,
    jax.Arrays, or ShapeDtypeStructs — anything with .shape/.dtype
    (never forces a device transfer)."""
    return tuple(sorted(
        (name, tuple(getattr(v, "shape", ())),
         str(getattr(v, "dtype", type(v).__name__)))
        for name, v in feed_args.items()))


class AotStepExecutable(_CompiledBundle):
    """An already-planned Session step, AOT-compiled for one feed-shape
    bucket. Call-compatible with the step's jitted function
    (``(state, feed_args, rng_key, rng_ctr)``), so the session's device
    dispatch (client/session.py ``_call_step_executable``) uses it
    transparently when the execution's ``feed_signature`` matches.
    State is donated exactly like the jit path — the caller commits the
    returned state dict back to the variable store."""

    def __init__(self, compiled, lowered, feed_avals, key):
        super().__init__(compiled, lowered, key)
        self.feed_avals = dict(feed_avals)
        self.feed_signature = feed_signature(feed_avals)

    def __call__(self, state, feed_args, rng_key, rng_ctr):
        return self._compiled(state, feed_args, rng_key, rng_ctr)


def compile_step(jitted, state: Dict[str, Any],
                 feed_avals: Dict[str, Any], rng_key,
                 rng_ctr) -> AotStepExecutable:
    """AOT-compile a planned step for one feed-shape bucket.

    ``jitted`` is the step's jax.jit function; ``state`` the CURRENT
    variable store (concrete arrays — only their avals matter, nothing
    executes); ``feed_avals`` maps feed tensor name ->
    jax.ShapeDtypeStruct of the bucket shape. With a persistent compile
    cache enabled (``enable_persistent_cache`` /
    ConfigProto(compile_cache_dir=...)), process restarts disk-hit
    these compiles — AOT warmup after the first deploy costs reads,
    not compiles."""
    lowered = jitted.lower(dict(state), dict(feed_avals), rng_key, rng_ctr)
    key = hashlib.sha256(lowered.as_text().encode()).hexdigest()[:16]
    compiled = lowered.compile()
    return AotStepExecutable(compiled, lowered, feed_avals, key)


def compile_fetches(fetches, feeds: Sequence[ops_mod.Tensor],
                    graph: Optional[ops_mod.Graph] = None,
                    static_args: Optional[Dict] = None) -> AotExecutable:
    """AOT-compile ``fetches`` as a pure function of ``feeds``.

    Variables are baked at their initializer values are NOT supported here —
    AOT programs are pure (the tfcompile model: frozen graphs). Feed every
    runtime input explicitly.
    """
    import jax

    fetch_list = fetches if isinstance(fetches, (list, tuple)) else [fetches]
    g = graph or fetch_list[0].graph
    feed_list = list(feeds)
    fed_set = set(feed_list)
    target_ops = [t.op for t in fetch_list]
    pruned = lowering_mod.prune(target_ops, fed_set)
    for op in pruned:
        if op.op_def.is_stateful and op.type not in ("Placeholder",):
            raise ValueError(
                f"AOT subgraph contains stateful op {op.name} ({op.type}); "
                "AOT programs must be pure — freeze variables first "
                "(ref tfcompile freezes the graph)")

    def fn(*feed_values):
        ctx = lowering_mod.LoweringContext(state={}, rng_root=None)
        for t, v in zip(feed_list, feed_values):
            ctx.env[t] = v
        lowering_mod.execute_ops(ctx, pruned, fed=fed_set)
        return tuple(ctx.env[t] for t in fetch_list)

    for t in feed_list:
        # Validate BEFORE building ShapeDtypeStructs: unknown-rank shapes
        # would crash in as_list() with an unfriendly error, and a static
        # scalar (as_list() == []) is perfectly valid.
        if t.shape.rank is None or any(d is None for d in t.shape.as_list()):
            raise ValueError(
                f"AOT feed {t.name} has unknown shape {t.shape}; XLA AOT "
                "needs fully static shapes")
    args = [jax.ShapeDtypeStruct(
        tuple(t.shape.as_list()), t.dtype.as_numpy_dtype)
        for t in feed_list]
    lowered = jax.jit(fn).lower(*args)
    key = hashlib.sha256(lowered.as_text().encode()).hexdigest()[:16]
    compiled = lowered.compile()
    return AotExecutable(compiled, lowered, feed_list, fetch_list, key)
