"""AOT compilation + executable cache
(ref: tensorflow/compiler/aot — tfcompile turns a frozen subgraph into a
standalone object file).

TPU-native, AOT = lower the fetch subgraph to one XLA program ahead of
Session.run and persist the compiled executable, so process restart skips
the (20-40s) TPU compile. Two layers:
- ``compile_fetches``: graph -> pure fn -> jax.jit(...).lower().compile(),
  returning an AotExecutable with HLO text, cost analysis, and a stable
  cache key.
- ``enable_persistent_cache``: turns on jax's compilation cache directory,
  the PJRT-level equivalent of tfcompile's ahead-of-time object files —
  keyed by HLO, shared across processes.
"""

from __future__ import annotations

import hashlib
import os
from typing import Any, Dict, List, Optional, Sequence

from ..framework import graph as ops_mod
from ..framework import lowering as lowering_mod


def enable_persistent_cache(cache_dir: str) -> None:
    """Persist compiled executables under ``cache_dir`` (survives process
    restarts; subsequent compiles of the same HLO are disk hits)."""
    import jax

    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # cache everything, however fast the compile was
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)


class AotExecutable:
    """A compiled fetch subgraph: call with feed values in declared order."""

    def __init__(self, compiled, lowered, feed_tensors, fetch_tensors, key):
        self._compiled = compiled
        self._lowered = lowered
        self.feed_tensors = list(feed_tensors)
        self.fetch_tensors = list(fetch_tensors)
        self.cache_key = key

    def __call__(self, *feed_values):
        if len(feed_values) != len(self.feed_tensors):
            raise ValueError(
                f"expected {len(self.feed_tensors)} feeds "
                f"({[t.name for t in self.feed_tensors]}), "
                f"got {len(feed_values)}")
        out = self._compiled(*feed_values)
        return out

    @property
    def hlo_text(self) -> str:
        return self._lowered.as_text()

    def cost_analysis(self) -> Dict[str, Any]:
        """XLA's estimate: flops, bytes accessed — feeds stf.utils.perf."""
        ca = self._compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        return dict(ca) if ca else {}

    def memory_analysis(self):
        return self._compiled.memory_analysis()


def compile_fetches(fetches, feeds: Sequence[ops_mod.Tensor],
                    graph: Optional[ops_mod.Graph] = None,
                    static_args: Optional[Dict] = None) -> AotExecutable:
    """AOT-compile ``fetches`` as a pure function of ``feeds``.

    Variables are baked at their initializer values are NOT supported here —
    AOT programs are pure (the tfcompile model: frozen graphs). Feed every
    runtime input explicitly.
    """
    import jax

    fetch_list = fetches if isinstance(fetches, (list, tuple)) else [fetches]
    g = graph or fetch_list[0].graph
    feed_list = list(feeds)
    fed_set = set(feed_list)
    target_ops = [t.op for t in fetch_list]
    pruned = lowering_mod.prune(target_ops, fed_set)
    for op in pruned:
        if op.op_def.is_stateful and op.type not in ("Placeholder",):
            raise ValueError(
                f"AOT subgraph contains stateful op {op.name} ({op.type}); "
                "AOT programs must be pure — freeze variables first "
                "(ref tfcompile freezes the graph)")

    def fn(*feed_values):
        ctx = lowering_mod.LoweringContext(state={}, rng_root=None)
        for t, v in zip(feed_list, feed_values):
            ctx.env[t] = v
        lowering_mod.execute_ops(ctx, pruned, fed=fed_set)
        return tuple(ctx.env[t] for t in fetch_list)

    for t in feed_list:
        # Validate BEFORE building ShapeDtypeStructs: unknown-rank shapes
        # would crash in as_list() with an unfriendly error, and a static
        # scalar (as_list() == []) is perfectly valid.
        if t.shape.rank is None or any(d is None for d in t.shape.as_list()):
            raise ValueError(
                f"AOT feed {t.name} has unknown shape {t.shape}; XLA AOT "
                "needs fully static shapes")
    args = [jax.ShapeDtypeStruct(
        tuple(t.shape.as_list()), t.dtype.as_numpy_dtype)
        for t in feed_list]
    lowered = jax.jit(fn).lower(*args)
    key = hashlib.sha256(lowered.as_text().encode()).hexdigest()[:16]
    compiled = lowered.compile()
    return AotExecutable(compiled, lowered, feed_list, fetch_list, key)
