"""Platform shims (ref: tensorflow/python/platform)."""
