"""Named, rank-ordered locks with a process-global lock-order witness
(ref: absl::Mutex's deadlock detector — absl/synchronization/mutex.cc,
DeadlockCheck() — and the FreeBSD witness(4) lock-order verifier).

Every lock in the stf runtime is created through this module instead of
raw ``threading.Lock()``:

    _lock = sync.Lock("serving/batcher_outputs", rank=sync.RANK_STATE)

A lock has a *name* (stable identity; many instances may share one
name — e.g. every monitoring cell lock is ``monitoring/cell``) and a
*rank* (lower rank = acquired first / outer). The witness maintains:

- **held stacks** — per-thread list of currently-held locks with the
  acquisition site (file:line), visible cross-thread so watchdog/
  ``/flightz``/``/syncz`` dumps can say what a wedged thread holds;
- **witness graph** — a digraph over lock *names*: edge A→B is recorded
  the first time any thread acquires B while holding A, with both
  sites. A cycle means a *potential* deadlock — reported (metric +
  flight-recorder event + one-time log) even if the deadlock never
  actually fires;
- **wait-for graph** — during a *contended* blocking acquire the
  waiting thread is parked in a global map; thread→owner edges form
  the wait-for graph, whose cycles are *live* deadlocks (surfaced by
  the watchdog's wedge dump);
- **rank violations** — acquiring a lock whose declared rank is
  strictly lower than a lock already held (outer-after-inner) is
  recorded, never raised.

Hot path: one extra try-acquire plus ~two frame-attribute reads on the
uncontended path (``f_lineno`` must be read eagerly — it mutates as the
frame executes). ``STF_LOCK_WITNESS=0`` (or ``set_witness_enabled(
False)``) reduces a sync.Lock to a plain lock plus one attribute check.

Import discipline: this module is **stdlib-only** — ``platform.
monitoring`` builds its own locks from it, so it cannot import
monitoring back. Monitoring registers the ``/stf/sync/*`` families at
its import end and injects them via :func:`bind_metrics`; the flight
recorder is reached lazily through ``sys.modules`` only.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "Lock", "RLock", "Condition",
    "RANK_LIFECYCLE", "RANK_SESSION", "RANK_ENGINE", "RANK_QUEUE",
    "RANK_STATE", "RANK_TELEMETRY", "RANK_METRICS", "LEAF",
    "set_witness_enabled", "witness_enabled", "reset_witness",
    "witness_snapshot", "potential_deadlocks", "all_held_locks",
    "wait_graph", "bind_metrics", "known_locks",
]

# Rank bands: lower = outer (acquired first). Equal ranks may nest in
# either order (e.g. two STATE locks guarding unrelated objects); only
# a *strictly lower* rank acquired while a higher one is held is a
# violation. LEAF locks must never have another sync lock taken under
# them.
RANK_LIFECYCLE = 100   # server/session open-close, writer caches
RANK_SESSION = 200     # Session/Graph state
RANK_ENGINE = 300      # pipeline runs, checkpoint manager
RANK_QUEUE = 400       # ring buffers, TF queue ops, accumulators
RANK_STATE = 500       # small per-object state (futures, registries)
RANK_TELEMETRY = 600   # recorder/tracing/ledger
RANK_METRICS = 700     # monitoring registry + family locks
LEAF = 900             # monitoring cells — nothing nests under these

_CONTENTION_SLOW_S = 1e-4  # waits shorter than this skip the sampler

_enabled = os.environ.get("STF_LOCK_WITNESS", "1").strip().lower() \
    not in ("0", "false", "off")
# Optional per-process acquire counter for bench pinning (cheap enough
# to keep a plain int bumped without a lock: CPython int += under GIL
# loses increments only under contention, and the bench arms are
# single-purpose).
_count_acquires = False
_acquire_count = 0

_tls = threading.local()

# The witness's own mutable state is guarded by ONE raw lock. Rule:
# never acquire a sync.Lock, emit a metric, or touch the flight
# recorder while holding it — collect, release, then report.
_global_lock = threading.Lock()

# name -> {"rank": int, "instances": int, "blocking_ok": bool}
_locks: Dict[str, Dict[str, Any]] = {}
# (holder_name, acquired_name) -> (holder_site, acquired_site) of the
# first observation, each a raw (filename, lineno) tuple.
_edges: Dict[Tuple[str, str], Tuple[Tuple[str, int],
                                    Tuple[str, int]]] = {}
# adjacency over names, for cycle detection
_succ: Dict[str, set] = {}
# cycles already reported, keyed by the canonicalised name tuple
_reported_cycles: Dict[Tuple[str, ...], Dict[str, Any]] = {}
_rank_violations: List[Dict[str, Any]] = []
# (acquired_name, held_name) pairs already recorded as violations —
# dedupe so a hot inverted pair reports once, not per acquisition, and
# so the lock-free fast path below can skip it
_violation_pairs: set = set()
_MAX_VIOLATIONS = 64

# thread ident -> the SAME list object as that thread's TLS held stack
# (entries: (lock, name, rank, site) tuples — immutable, cheapest to
# build on the hot path); other threads only snapshot it.
_held_by_thread: Dict[int, List[list]] = {}
_thread_names: Dict[int, str] = {}
# thread ident -> (lock_name, site_tuple, since_monotonic) while
# parked in a contended blocking acquire.
_waiting: Dict[int, Tuple[str, Tuple[str, int], float]] = {}

# Metric hooks injected by platform.monitoring (bind_metrics). Each is
# a plain callable; None until monitoring has imported.
_m_contention: Optional[Callable[[str], None]] = None
_m_wait: Optional[Callable[[str, float], None]] = None
_m_cycle: Optional[Callable[[str], None]] = None
_m_violation: Optional[Callable[[str], None]] = None
_m_edges: Optional[Callable[[int], None]] = None


def bind_metrics(contention: Callable[[str], None],
                 wait: Callable[[str, float], None],
                 cycle: Callable[[str], None],
                 violation: Callable[[str], None],
                 edges: Callable[[int], None]) -> None:
    """Called once by platform.monitoring at its import end, injecting
    the ``/stf/sync/*`` cell-update callables (sync cannot import
    monitoring — monitoring's own locks come from here)."""
    global _m_contention, _m_wait, _m_cycle, _m_violation, _m_edges
    _m_contention, _m_wait = contention, wait
    _m_cycle, _m_violation, _m_edges = cycle, violation, edges
    _m_edges(len(_edges))


def set_witness_enabled(on: bool) -> None:
    global _enabled
    _enabled = bool(on)


def witness_enabled() -> bool:
    return _enabled


def _set_count_acquires(on: bool) -> int:
    """Bench hook: toggle acquire counting; returns the running count."""
    global _count_acquires, _acquire_count
    _count_acquires = bool(on)
    return _acquire_count


def _held() -> List[list]:
    """This thread's held-lock stack, creating + globally registering
    it on first use."""
    st = getattr(_tls, "held", None)
    if st is None:
        st = _tls.held = []
        ident = threading.get_ident()
        with _global_lock:
            _held_by_thread[ident] = st
            _thread_names[ident] = threading.current_thread().name
    return st


def _fmt(site: Tuple[str, int]) -> str:
    """Sites are kept as raw (filename, lineno) tuples on the hot path
    — ``f_lineno`` must be read eagerly (it mutates as the frame
    executes) but the string is only built at report time."""
    return f"{site[0]}:{site[1]}"


def _flight_event(kind: str, **fields) -> None:
    """Best-effort flight-recorder event via sys.modules — the witness
    must never be what first imports telemetry."""
    rec_mod = sys.modules.get("simple_tensorflow_tpu.telemetry.recorder")
    if rec_mod is None:
        return
    try:
        rec_mod.get_recorder().record(kind, **fields)
    except Exception:  # noqa: BLE001 — forensics never break the app
        pass


def _record_edges(held: List[list], name: str, rank: int,
                  site: str) -> None:
    """Witness the acquisition of ``name`` while ``held`` locks are
    held: rank check + new-edge insertion + cycle detection. Reports
    (metrics/flight events/log) are emitted AFTER _global_lock is
    released; a TLS guard stops report side-effects from re-entering
    edge recording."""
    if getattr(_tls, "reporting", False):
        return
    # Lock-free fast path (the steady-state hot path): every held->name
    # edge already witnessed, every rank inversion already recorded.
    # Reads race benignly under the GIL — a stale miss only sends this
    # one acquisition down the slow path below.
    for entry in held:
        h_name = entry[1]
        if h_name == name:
            continue
        s = _succ.get(h_name)
        if s is None or name not in s:
            break
        if rank < entry[2] and (name, h_name) not in _violation_pairs:
            break
    else:
        return
    new_cycles = []
    new_violation = None
    with _global_lock:
        for entry in held:
            h_name, h_rank, h_site = entry[1], entry[2], entry[3]
            if h_name == name:
                continue
            if rank < h_rank and (name, h_name) not in _violation_pairs:
                _violation_pairs.add((name, h_name))
                v = {
                    "acquired": name, "acquired_rank": rank,
                    "acquired_site": _fmt(site), "held": h_name,
                    "held_rank": h_rank, "held_site": _fmt(h_site),
                    "thread": threading.current_thread().name,
                }
                if len(_rank_violations) < _MAX_VIOLATIONS:
                    _rank_violations.append(v)
                if new_violation is None:
                    new_violation = v
            key = (h_name, name)
            if key in _edges:
                continue
            _edges[key] = (h_site, site)
            _succ.setdefault(h_name, set()).add(name)
            # New edge h_name->name: a cycle through it exists iff
            # h_name is reachable from name.
            cyc = _find_path(name, h_name)
            if cyc is not None:
                cycle_names = tuple(cyc)  # name .. h_name
                canon = _canonical(cycle_names)
                if canon not in _reported_cycles:
                    report = _cycle_report(cycle_names)
                    _reported_cycles[canon] = report
                    new_cycles.append(report)
        n_edges = len(_edges)
    # --- side-effects outside _global_lock ---
    _tls.reporting = True
    try:
        if _m_edges is not None:
            _m_edges(n_edges)
        if new_violation is not None:
            if _m_violation is not None:
                _m_violation(name)
            _flight_event(
                "lock_rank_violation", lock=name, rank=rank,
                site=new_violation["acquired_site"],
                held=new_violation["held"],
                held_rank=new_violation["held_rank"],
                held_site=new_violation["held_site"])
        for report in new_cycles:
            if _m_cycle is not None:
                _m_cycle(report["key"])
            _flight_event("potential_deadlock", cycle=report["key"],
                          edges=report["edges"])
            print(f"[stf.sync] POTENTIAL DEADLOCK: lock-order cycle "
                  f"{report['key']}:", file=sys.stderr)
            for e in report["edges"]:
                print(f"[stf.sync]   {e['from']} (held at "
                      f"{e['from_site']}) -> {e['to']} (acquired at "
                      f"{e['to_site']})", file=sys.stderr)
    finally:
        _tls.reporting = False


def _find_path(src: str, dst: str) -> Optional[List[str]]:
    """DFS over _succ from src to dst; returns the node path
    [src, ..., dst] or None. Caller holds _global_lock."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in _succ.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _canonical(names: Tuple[str, ...]) -> Tuple[str, ...]:
    """Rotate a cycle's name tuple to start at its min element so the
    same cycle discovered from different edges dedups."""
    i = names.index(min(names))
    return names[i:] + names[:i]


def _cycle_report(cycle_names: Tuple[str, ...]) -> Dict[str, Any]:
    """Edge list (with both sites) for a name cycle. Caller holds
    _global_lock."""
    edges = []
    n = len(cycle_names)
    for i in range(n):
        a, b = cycle_names[i], cycle_names[(i + 1) % n]
        sites = _edges.get((a, b))
        edges.append({
            "from": a, "from_site": _fmt(sites[0]) if sites else "?",
            "to": b, "to_site": _fmt(sites[1]) if sites else "?"})
    return {"key": " -> ".join(_canonical(cycle_names)
                               + (_canonical(cycle_names)[0],)),
            "cycle": list(_canonical(cycle_names)), "edges": edges}


class Lock:
    """Named, ranked drop-in for ``threading.Lock``.

    ``blocking_ok=True`` declares that blocking calls under this lock
    are by-design (e.g. checkpoint writer lifecycle serialising stop()
    against submit()); tools/runtime_lint.py honours the flag so the
    lint allowlist stays empty while the exemption lives in reviewed
    code.
    """

    __slots__ = ("_lock", "name", "rank", "blocking_ok")

    _factory = staticmethod(threading.Lock)

    def __init__(self, name: str, rank: int = RANK_STATE, *,
                 blocking_ok: bool = False):
        self._lock = self._factory()
        self.name = name
        self.rank = rank
        self.blocking_ok = blocking_ok
        with _global_lock:
            info = _locks.get(name)
            if info is None:
                _locks[name] = {"rank": rank, "instances": 1,
                                "blocking_ok": blocking_ok}
            else:
                info["instances"] += 1
                if info["rank"] != rank:
                    # Same name must mean same rank — first wins, note
                    # the conflict rather than raising on import paths.
                    info.setdefault("rank_conflicts", set()).add(rank)

    def acquire(self, blocking: bool = True, timeout: float = -1,
                *, _depth: int = 1) -> bool:
        # _depth: stack distance to the frame blamed as the acquisition
        # site — 1 for direct acquire()/`with lock:` (__enter__ is an
        # alias, not a wrapper), 2 when Condition delegates.
        if not _enabled:
            return self._lock.acquire(blocking, timeout)
        if _count_acquires:
            global _acquire_count
            _acquire_count += 1
        try:
            held = _tls.held  # inlined _held(): this IS the hot path
        except AttributeError:
            held = _held()
        f = sys._getframe(_depth)
        site = (f.f_code.co_filename, f.f_lineno)
        if held:
            _record_edges(held, self.name, self.rank, site)
        if self._lock.acquire(False):
            held.append((self, self.name, self.rank, site))
            return True
        if not blocking:
            return False
        # Contended slow path: park in the wait-for graph, time the
        # wait, export contention.
        ident = threading.get_ident()
        t0 = time.monotonic()
        with _global_lock:
            _waiting[ident] = (self.name, site, t0)
        try:
            got = self._lock.acquire(True, timeout)
        finally:
            with _global_lock:
                _waiting.pop(ident, None)
        if got:
            held.append((self, self.name, self.rank, site))
            wait_s = time.monotonic() - t0
            if wait_s >= _CONTENTION_SLOW_S and not getattr(
                    _tls, "reporting", False):
                _tls.reporting = True
                try:
                    if _m_contention is not None:
                        _m_contention(self.name)
                    if _m_wait is not None:
                        _m_wait(self.name, wait_s)
                finally:
                    _tls.reporting = False
        return got

    def release(self) -> None:
        if _enabled:
            try:
                held = _tls.held
            except AttributeError:
                held = _held()
            for i in range(len(held) - 1, -1, -1):
                if held[i][0] is self:
                    del held[i]
                    break
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    # Aliased, not delegated: acquire() reads sys._getframe(1) for the
    # acquisition site, and the alias keeps the caller exactly one
    # frame up. (`with lock as x` binds True, like threading.Lock.)
    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()

    def __repr__(self):
        return (f"<stf.sync.{type(self).__name__} {self.name!r} "
                f"rank={self.rank}>")


class RLock(Lock):
    """Named, ranked drop-in for ``threading.RLock``. Reentrant
    acquisition records no new witness edges (absl does the same — a
    lock cannot deadlock against itself on one thread)."""

    __slots__ = ("_count",)

    _factory = staticmethod(threading.RLock)

    def __init__(self, name: str, rank: int = RANK_STATE, *,
                 blocking_ok: bool = False):
        super().__init__(name, rank, blocking_ok=blocking_ok)
        self._count = 0  # depth for the OWNING thread only

    def acquire(self, blocking: bool = True, timeout: float = -1,
                *, _depth: int = 1) -> bool:
        if not _enabled:
            if self._lock.acquire(blocking, timeout):
                self._count += 1
                return True
            return False
        held = _held()
        for entry in held:
            if entry[0] is self:
                # Reentry on the owning thread: cannot block, no edges.
                self._lock.acquire()
                self._count += 1
                return True
        f = sys._getframe(_depth)
        site = (f.f_code.co_filename, f.f_lineno)
        if _count_acquires:
            global _acquire_count
            _acquire_count += 1
        if held:
            _record_edges(held, self.name, self.rank, site)
        if self._lock.acquire(False):
            self._count += 1
            held.append((self, self.name, self.rank, site))
            return True
        if not blocking:
            return False
        ident = threading.get_ident()
        t0 = time.monotonic()
        with _global_lock:
            _waiting[ident] = (self.name, site, t0)
        try:
            got = self._lock.acquire(True, timeout)
        finally:
            with _global_lock:
                _waiting.pop(ident, None)
        if got:
            self._count += 1
            held.append((self, self.name, self.rank, site))
            wait_s = time.monotonic() - t0
            if wait_s >= _CONTENTION_SLOW_S and not getattr(
                    _tls, "reporting", False):
                _tls.reporting = True
                try:
                    if _m_contention is not None:
                        _m_contention(self.name)
                    if _m_wait is not None:
                        _m_wait(self.name, wait_s)
                finally:
                    _tls.reporting = False
        return got

    def release(self) -> None:
        self._count -= 1
        if _enabled and self._count == 0:
            held = _held()
            for i in range(len(held) - 1, -1, -1):
                if held[i][0] is self:
                    del held[i]
                    break
        self._lock.release()

    def locked(self) -> bool:
        return self._count > 0

    __enter__ = acquire  # same frame-depth aliasing as Lock
    __exit__ = Lock.__exit__


class Condition:
    """``threading.Condition`` over a sync.Lock. ``wait()`` releases
    the lock, so the held-stack entry (and wait-for-graph ownership)
    is suspended for the duration and restored on wakeup — otherwise a
    parked waiter would look like a holder in wedge dumps.

    Multiple Conditions may share one sync.Lock (ring buffers'
    not_empty/not_full); a standalone ``Condition(name=...)`` creates
    its own internal lock.
    """

    __slots__ = ("_sync_lock", "_cond")

    def __init__(self, lock: Optional[Lock] = None, *,
                 name: str = "sync/anon_condition",
                 rank: int = RANK_QUEUE):
        if lock is None:
            lock = Lock(name, rank)
        self._sync_lock = lock
        # The raw condition shares the sync lock's INNER primitive so
        # acquire/release bookkeeping stays in the wrapper.
        self._cond = threading.Condition(lock._lock)

    @property
    def lock(self) -> Lock:
        return self._sync_lock

    def acquire(self, blocking: bool = True, timeout: float = -1):
        # _depth=2: blame the frame calling the Condition, not this one
        return self._sync_lock.acquire(blocking, timeout, _depth=2)

    def release(self):
        self._sync_lock.release()

    def __enter__(self):
        self._sync_lock.acquire(_depth=2)
        return self

    def __exit__(self, *exc):
        self._sync_lock.release()

    def _suspend(self) -> Optional[list]:
        if not _enabled:
            return None
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is self._sync_lock:
                return held.pop(i)
        return None

    def _resume(self, entry: Optional[list]) -> None:
        if entry is not None:
            _held().append(entry)

    def wait(self, timeout: Optional[float] = None) -> bool:
        entry = self._suspend()
        try:
            return self._cond.wait(timeout)
        finally:
            self._resume(entry)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        entry = self._suspend()
        try:
            return self._cond.wait_for(predicate, timeout)
        finally:
            self._resume(entry)

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()

    def __repr__(self):
        return f"<stf.sync.Condition over {self._sync_lock!r}>"


def leaf_lock(name: str) -> "threading.Lock":
    """A NAMED leaf lock that is exempt from witness bookkeeping: the
    returned object is a raw ``threading.Lock`` — C-speed acquire, no
    held-stack entry, no lock-order edges, no wait-for node. The name
    is registered (``known_locks()`` / ``/syncz`` show it with
    ``leaf: true``) so the lock stays discoverable, but the dynamic
    witness cannot see it.

    Contract: a leaf critical section must not acquire ANY lock and
    must not block — enforced at review time by
    ``tools/runtime_lint.py`` (``nested-under-leaf`` +
    ``blocking-under-lock``); since the witness is blind here, the
    static rule is the only guard, which is why it has no escape
    flag. Reserve this for nanosecond-scale critical sections on the
    hottest paths (metric cells: one integer add per request/step),
    where even the witness's tuple-append fast path would multiply the
    cost of the work being guarded."""
    with _global_lock:
        info = _locks.get(name)
        if info is None:
            _locks[name] = {"rank": LEAF, "instances": 1,
                            "blocking_ok": False, "leaf": True}
        else:
            info["instances"] += 1
            info["leaf"] = True
    return threading.Lock()


# ---------------------------------------------------------------------------
# Introspection surfaces (watchdog, /syncz, conftest, tests)


def known_locks() -> Dict[str, Dict[str, Any]]:
    with _global_lock:
        return {name: {"rank": info["rank"],
                       "instances": info["instances"],
                       "blocking_ok": info["blocking_ok"],
                       "leaf": info.get("leaf", False)}
                for name, info in _locks.items()}


def all_held_locks() -> Dict[str, List[Dict[str, Any]]]:
    """Per-thread held locks, cross-thread view. Dead threads' entries
    are pruned as a side effect. Keyed ``"name (ident)"``."""
    live = {t.ident: t.name for t in threading.enumerate()}
    out: Dict[str, List[Dict[str, Any]]] = {}
    with _global_lock:
        for ident in list(_held_by_thread):
            if ident not in live:
                del _held_by_thread[ident]
                _thread_names.pop(ident, None)
                continue
            st = _held_by_thread[ident]
            if not st:
                continue
            out[f"{live[ident]} ({ident})"] = [
                {"lock": e[1], "rank": e[2], "site": _fmt(e[3])}
                for e in list(st)]
    return out


def held_by_ident() -> Dict[int, List[Dict[str, Any]]]:
    """Like :func:`all_held_locks` but keyed by thread ident — the
    flight recorder joins this against ``sys._current_frames()`` for
    per-thread held-locks in wedge dumps."""
    live = {t.ident for t in threading.enumerate()}
    out: Dict[int, List[Dict[str, Any]]] = {}
    with _global_lock:
        for ident, st in _held_by_thread.items():
            if ident in live and st:
                out[ident] = [{"lock": e[1], "rank": e[2],
                               "site": _fmt(e[3])} for e in list(st)]
    return out


def wait_graph() -> Dict[str, Any]:
    """The live wait-for graph: per waiting thread, which lock it
    wants, who holds that lock (by lock-name match against held
    stacks), and any thread-level cycle (= a REAL deadlock)."""
    live = {t.ident: t.name for t in threading.enumerate()}
    with _global_lock:
        waiting = dict(_waiting)
        holders: Dict[str, List[int]] = {}
        for ident, st in _held_by_thread.items():
            if ident not in live:
                continue
            for e in list(st):
                holders.setdefault(e[1], []).append(ident)
    edges = []
    adj: Dict[int, set] = {}
    for ident, (lock_name, site, since) in waiting.items():
        for owner in holders.get(lock_name, ()):
            if owner == ident:
                continue
            edges.append({
                "waiter": live.get(ident, str(ident)),
                "waiter_ident": ident, "lock": lock_name,
                "site": _fmt(site),
                "waited_s": round(time.monotonic() - since, 3),
                "owner": live.get(owner, str(owner)),
                "owner_ident": owner,
            })
            adj.setdefault(ident, set()).add(owner)
    # Cycle detection over thread idents (colour DFS).
    cycles: List[List[str]] = []
    colour: Dict[int, int] = {}

    def visit(node: int, path: List[int]) -> None:
        colour[node] = 1
        path.append(node)
        for nxt in adj.get(node, ()):
            c = colour.get(nxt, 0)
            if c == 0:
                visit(nxt, path)
            elif c == 1:
                cyc = path[path.index(nxt):] + [nxt]
                cycles.append([live.get(i, str(i)) for i in cyc])
        path.pop()
        colour[node] = 2

    for node in list(adj):
        if colour.get(node, 0) == 0:
            visit(node, [])
    return {"edges": edges, "cycles": cycles,
            "deadlocked": bool(cycles)}


def potential_deadlocks() -> List[Dict[str, Any]]:
    """All lock-order cycles the witness has ever observed (deduped)."""
    with _global_lock:
        return [dict(r) for r in _reported_cycles.values()]


def rank_violations() -> List[Dict[str, Any]]:
    with _global_lock:
        return [dict(v) for v in _rank_violations]


def witness_snapshot() -> Dict[str, Any]:
    """The /syncz payload (minus wait_graph/held, which the endpoint
    adds live)."""
    with _global_lock:
        edges = [{"from": a, "to": b, "from_site": _fmt(s[0]),
                  "to_site": _fmt(s[1])}
                 for (a, b), s in _edges.items()]
        locks = {name: {"rank": info["rank"],
                        "instances": info["instances"],
                        "blocking_ok": info["blocking_ok"],
                        "leaf": info.get("leaf", False)}
                 for name, info in _locks.items()}
        cycles = [dict(r) for r in _reported_cycles.values()]
        violations = [dict(v) for v in _rank_violations]
    return {"enabled": _enabled, "locks": locks, "edges": edges,
            "potential_deadlocks": cycles,
            "rank_violations": violations}


def reset_witness() -> None:
    """Drop accumulated edges/cycles/violations (tests). Lock registry
    and held stacks are left alone — they reflect live objects."""
    with _global_lock:
        _edges.clear()
        _succ.clear()
        _reported_cycles.clear()
        del _rank_violations[:]
        _violation_pairs.clear()
    if _m_edges is not None:
        _m_edges(0)
