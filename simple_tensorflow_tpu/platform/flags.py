"""Minimal flags (ref: tensorflow/python/platform/flags.py)."""

from __future__ import annotations

import argparse
import sys


class _FlagValues:
    def __init__(self):
        self.__dict__["_parser"] = argparse.ArgumentParser(add_help=False)
        self.__dict__["_parsed"] = None

    def _ensure_parsed(self):
        if self._parsed is None:
            parsed, _ = self._parser.parse_known_args(sys.argv[1:])
            self.__dict__["_parsed"] = parsed

    def __getattr__(self, name):
        self._ensure_parsed()
        return getattr(self._parsed, name)

    def __setattr__(self, name, value):
        self._ensure_parsed()
        setattr(self._parsed, name, value)


FLAGS = _FlagValues()


def _define(flag_type, name, default, help):  # noqa: A002
    FLAGS.__dict__["_parsed"] = None
    if flag_type is bool:
        FLAGS._parser.add_argument(f"--{name}", default=default,
                                   type=lambda s: s.lower() in
                                   ("1", "true", "yes"), help=help)
    else:
        FLAGS._parser.add_argument(f"--{name}", default=default,
                                   type=flag_type, help=help)


def DEFINE_string(name, default, help):  # noqa: A002
    _define(str, name, default, help)


def DEFINE_integer(name, default, help):  # noqa: A002
    _define(int, name, default, help)


def DEFINE_float(name, default, help):  # noqa: A002
    _define(float, name, default, help)


def DEFINE_boolean(name, default, help):  # noqa: A002
    _define(bool, name, default, help)


DEFINE_bool = DEFINE_boolean
