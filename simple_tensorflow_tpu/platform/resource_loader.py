"""Resource loader (ref: tensorflow/python/platform/resource_loader.py)."""

from __future__ import annotations

import os


def get_data_files_path():
    return os.path.dirname(os.path.abspath(__file__))


def get_root_dir_with_all_resources():
    return get_data_files_path()


def load_resource(path):
    with open(os.path.join(get_data_files_path(), path), "rb") as f:
        return f.read()


def get_path_to_datafile(path):
    return os.path.join(get_data_files_path(), path)


def readahead_file_path(path, readahead="128M"):
    return path


def load_op_library(library_filename):
    """(ref: framework/load_library.py ``load_op_library``). Custom ops in
    stf register through the Python op_registry
    (simple_tensorflow_tpu.framework.op_registry.register) rather than
    REGISTER_OP static initializers; this loads the shared object (so C
    code can use the stf C API in runtime_cc/stf_c.h) and returns a
    minimal namespace."""
    import ctypes
    import types

    lib = ctypes.CDLL(library_filename, mode=ctypes.RTLD_GLOBAL)
    mod = types.SimpleNamespace()
    mod._lib = lib
    return mod


def load_file_system_library(library_filename):
    """(ref: ``load_file_system_library``): same loading mechanics; stf
    file IO plugs in via lib/io/file_io.py registration instead."""
    return load_op_library(library_filename)
