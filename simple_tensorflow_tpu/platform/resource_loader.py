"""Resource loader (ref: tensorflow/python/platform/resource_loader.py)."""

from __future__ import annotations

import os


def get_data_files_path():
    return os.path.dirname(os.path.abspath(__file__))


def get_root_dir_with_all_resources():
    return get_data_files_path()


def load_resource(path):
    with open(os.path.join(get_data_files_path(), path), "rb") as f:
        return f.read()


def get_path_to_datafile(path):
    return os.path.join(get_data_files_path(), path)


def readahead_file_path(path, readahead="128M"):
    return path
