"""App runner (ref: tensorflow/python/platform/app.py)."""

from __future__ import annotations

import sys


def run(main=None, argv=None):
    main = main or sys.modules["__main__"].main
    argv = argv if argv is not None else sys.argv
    sys.exit(main(argv))
