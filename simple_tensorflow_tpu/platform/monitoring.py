"""stf.monitoring: process-global metrics + lightweight tracing
(ref: tensorflow/core/lib/monitoring/{counter,gauge,sampler,
percentile_sampler}.h, python/eager/monitoring.py).

Two halves, both thread-safe and dependency-free (importable from any
layer without cycles):

Metrics — a process-global registry of named metric families. Each
family owns labeled cells, created on demand:

    runs = monitoring.Counter("/stf/session/runs", "session.run calls")
    runs.get_cell().increase_by(1)
    misses = monitoring.Counter("/stf/session/executable_cache/misses",
                                "cache misses", "reason")
    misses.get_cell("new_fetch_feed_signature").increase_by(1)

``export()`` renders the whole registry as a nested dict (stable,
JSON-able), ``to_json()`` dumps it, and ``to_prometheus()`` emits the
Prometheus text exposition format so a scrape endpoint is one
``web.Response(monitoring.to_prometheus())`` away.

Tracing — ``traceme(name, **meta)`` is a context manager recording a
span into every *active* per-thread trace buffer. With no buffer
installed it costs one thread-local read (cheap enough to leave in hot
paths, the reference's TraceMe contract). Session.run installs a buffer
for the duration of a traced run (``RunOptions.SOFTWARE_TRACE``) and
drains it into ``RunMetadata.step_stats`` — the source of the
chrome-trace timeline (client/timeline.py).
"""

from __future__ import annotations

import bisect
import json
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import sync as _sync

__all__ = [
    "Counter", "IntGauge", "StringGauge", "BoolGauge",
    "Sampler", "PercentileSampler",
    "ExponentialBuckets", "ExplicitBuckets",
    "export", "to_json", "to_prometheus",
    "get_metric", "unregister", "reset_registry",
    "traceme", "trace_collection", "TraceBuffer", "tracing_active",
    "record_span",
    "WindowedRate",
]

_registry_lock = _sync.Lock("monitoring/registry",
                            rank=_sync.RANK_METRICS)
_registry: Dict[str, "Metric"] = {}


# ---------------------------------------------------------------------------
# buckets
# ---------------------------------------------------------------------------

class Buckets:
    """Bucket boundaries for Sampler histograms: ``boundaries[i]`` is the
    inclusive upper edge of bucket i (Prometheus ``le``); a final +inf
    bucket is implicit."""

    def __init__(self, boundaries: Sequence[float]):
        bs = [float(b) for b in boundaries]
        if any(b2 <= b1 for b1, b2 in zip(bs, bs[1:])):
            raise ValueError(f"bucket boundaries must increase: {bs}")
        self.boundaries: List[float] = bs


def ExponentialBuckets(scale: float, growth_factor: float,
                       bucket_count: int) -> Buckets:
    """(ref: monitoring/sampler.h ``Buckets::Exponential``): boundaries
    scale, scale*growth, scale*growth^2, ... — bucket_count edges."""
    if scale <= 0 or growth_factor <= 1 or bucket_count < 1:
        raise ValueError(
            f"ExponentialBuckets(scale={scale}, growth_factor="
            f"{growth_factor}, bucket_count={bucket_count}): need "
            "scale>0, growth_factor>1, bucket_count>=1")
    return Buckets([scale * growth_factor ** i for i in range(bucket_count)])


def ExplicitBuckets(boundaries: Sequence[float]) -> Buckets:
    """(ref: monitoring/sampler.h ``Buckets::Explicit``)."""
    return Buckets(boundaries)


# ---------------------------------------------------------------------------
# cells
# ---------------------------------------------------------------------------

class CounterCell:
    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0
        self._lock = _sync.leaf_lock("monitoring/cell")

    def increase_by(self, value: int = 1):
        if value < 0:
            raise ValueError(f"Counter can only increase (got {value})")
        with self._lock:
            self._value += int(value)

    def value(self) -> int:
        with self._lock:
            return self._value


class GaugeCell:
    __slots__ = ("_value", "_lock")

    def __init__(self, default):
        self._value = default
        self._lock = _sync.leaf_lock("monitoring/cell")

    def set(self, value):
        with self._lock:
            self._value = value

    def value(self):
        with self._lock:
            return self._value


class SamplerCell:
    """Histogram cell: counts per exponential/explicit bucket + sum."""

    __slots__ = ("_buckets", "_counts", "_sum", "_count", "_min", "_max",
                 "_lock")

    def __init__(self, buckets: Buckets):
        self._buckets = buckets
        self._counts = [0] * (len(buckets.boundaries) + 1)
        self._sum = 0.0
        self._count = 0
        self._min = float("inf")
        self._max = float("-inf")
        self._lock = _sync.leaf_lock("monitoring/cell")

    def add(self, value: float):
        v = float(value)
        # bisect_left: a sample equal to an edge counts at-or-below it
        # (Prometheus ``le`` semantics; matches the reference sampler)
        idx = bisect.bisect_left(self._buckets.boundaries, v)
        with self._lock:
            self._counts[idx] += 1
            self._sum += v
            self._count += 1
            self._min = min(self._min, v)
            self._max = max(self._max, v)

    def value(self) -> Dict[str, Any]:
        """Histogram snapshot; ``buckets`` maps upper-edge -> count (the
        final bucket's edge is +inf)."""
        with self._lock:
            edges = self._buckets.boundaries + [float("inf")]
            return {
                "count": self._count,
                "sum": self._sum,
                "min": self._min if self._count else None,
                "max": self._max if self._count else None,
                "buckets": list(zip(edges, list(self._counts))),
            }


class PercentileSamplerCell:
    """Ring buffer of recent samples -> on-demand percentiles
    (ref: monitoring/percentile_sampler.h; the reference also keeps a
    bounded sample set and computes percentiles at harvest time)."""

    __slots__ = ("_percentiles", "_samples", "_max_samples", "_next",
                 "_sum", "_count", "_lock")

    def __init__(self, percentiles: Sequence[float], max_samples: int):
        self._percentiles = list(percentiles)
        self._samples: List[float] = []
        self._max_samples = max_samples
        self._next = 0
        self._sum = 0.0
        self._count = 0
        self._lock = _sync.leaf_lock("monitoring/cell")

    def add(self, value: float):
        v = float(value)
        with self._lock:
            if len(self._samples) < self._max_samples:
                self._samples.append(v)
            else:
                self._samples[self._next] = v
                self._next = (self._next + 1) % self._max_samples
            self._sum += v
            self._count += 1

    def value(self) -> Dict[str, Any]:
        with self._lock:
            samples = sorted(self._samples)
            count, total = self._count, self._sum
        out: Dict[str, Any] = {"count": count, "sum": total,
                               "percentiles": {}}
        if samples:
            n = len(samples)
            for p in self._percentiles:
                idx = min(n - 1, max(0, int(round(p / 100.0 * (n - 1)))))
                out["percentiles"][p] = samples[idx]
        return out


# ---------------------------------------------------------------------------
# metric families
# ---------------------------------------------------------------------------

def _join_labels(key: Tuple[str, ...]) -> str:
    """Cell key tuple -> export()-dict key. '|' separates label values;
    values containing '|' or '\\' are escaped so distinct tuples never
    collide (``_split_labels`` is the inverse)."""
    return "|".join(v.replace("\\", "\\\\").replace("|", "\\|")
                    for v in key)


def _split_labels(s: str) -> List[str]:
    parts: List[str] = []
    cur: List[str] = []
    i = 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            cur.append(s[i + 1])
            i += 2
            continue
        if c == "|":
            parts.append("".join(cur))
            cur = []
            i += 1
            continue
        cur.append(c)
        i += 1
    parts.append("".join(cur))
    return parts


class Metric:
    """A named family of labeled cells. Registering two metrics under one
    name is an error (the reference's AlreadyExists) — except that
    re-creating a family with the identical type/labels returns the
    existing one, so module reloads and test re-imports stay idempotent."""

    metric_type = "Metric"

    def __init__(self, name: str, description: str, *label_names: str):
        self.name = name
        self.description = description
        self.label_names = tuple(label_names)
        self._cells: Dict[Tuple, Any] = {}
        self._lock = _sync.leaf_lock("monitoring/family")
        with _registry_lock:
            existing = _registry.get(name)
            if existing is not None:
                if (type(existing) is not type(self)
                        or existing.label_names != self.label_names
                        or not self._same_shape(existing)):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(existing).__name__}"
                        f"{existing.label_names} with a different "
                        "shape (type/labels/buckets/percentiles) — "
                        "names are process-global")
                # adopt the existing family's cells: same name, same
                # shape -> same metric
                self._cells = existing._cells
                self._lock = existing._lock
            _registry[name] = self

    def _new_cell(self):
        raise NotImplementedError

    def _same_shape(self, existing) -> bool:
        """Subclasses with extra configuration (buckets, percentiles)
        override to veto cell adoption on mismatch."""
        return True

    def get_cell(self, *labels: str):
        if len(labels) != len(self.label_names):
            raise ValueError(
                f"metric {self.name!r} takes {len(self.label_names)} "
                f"label(s) {self.label_names}, got {labels!r}")
        key = tuple(str(v) for v in labels)
        with self._lock:
            cell = self._cells.get(key)
            if cell is None:
                cell = self._cells[key] = self._new_cell()
            return cell

    def cells(self) -> Dict[Tuple, Any]:
        with self._lock:
            return dict(self._cells)

    def snapshot(self) -> Dict[str, Any]:
        return {
            "type": self.metric_type,
            "description": self.description,
            "labels": list(self.label_names),
            "cells": {_join_labels(k): c.value()
                      for k, c in self.cells().items()},
        }


class Counter(Metric):
    """(ref: monitoring/counter.h)."""

    metric_type = "Counter"

    def _new_cell(self):
        return CounterCell()


class IntGauge(Metric):
    """(ref: monitoring/gauge.h ``Gauge<int64>``)."""

    metric_type = "IntGauge"

    def _new_cell(self):
        return GaugeCell(0)


class StringGauge(Metric):
    metric_type = "StringGauge"

    def _new_cell(self):
        return GaugeCell("")


class BoolGauge(Metric):
    metric_type = "BoolGauge"

    def _new_cell(self):
        return GaugeCell(False)


class Sampler(Metric):
    """(ref: monitoring/sampler.h): histogram over fixed buckets."""

    metric_type = "Sampler"

    def __init__(self, name: str, buckets: Buckets, description: str,
                 *label_names: str):
        self.buckets = buckets
        super().__init__(name, description, *label_names)

    def _same_shape(self, existing) -> bool:
        return existing.buckets.boundaries == self.buckets.boundaries

    def _new_cell(self):
        return SamplerCell(self.buckets)


class PercentileSampler(Metric):
    """(ref: monitoring/percentile_sampler.h). Labels are positional
    like every other metric family; percentiles/max_samples are
    keyword-only so ``PercentileSampler(name, desc, "label")`` can never
    silently bind a label name as the percentile list."""

    metric_type = "PercentileSampler"

    def __init__(self, name: str, description: str, *label_names: str,
                 percentiles: Sequence[float] = (25.0, 50.0, 90.0, 99.0),
                 max_samples: int = 1024):
        self.percentiles = list(percentiles)
        self.max_samples = int(max_samples)
        super().__init__(name, description, *label_names)

    def _same_shape(self, existing) -> bool:
        return (existing.percentiles == self.percentiles
                and existing.max_samples == self.max_samples)

    def _new_cell(self):
        return PercentileSamplerCell(self.percentiles, self.max_samples)


# ---------------------------------------------------------------------------
# registry export
# ---------------------------------------------------------------------------

def get_metric(name: str) -> Optional[Metric]:
    with _registry_lock:
        return _registry.get(name)


def unregister(name: str):
    with _registry_lock:
        _registry.pop(name, None)


def reset_registry():
    """Drop every registered family — tests only; library metrics
    re-register on next module import, not after this call."""
    with _registry_lock:
        _registry.clear()


class WindowedRate:
    """Sliding-window event-rate estimator (events/sec over the last
    ``window_s`` seconds), feeding gauge-style metrics whose value must
    reflect CURRENT load, not lifetime averages — the
    ``/stf/serving/qps`` gauge is the canonical user. Thread-safe;
    O(1) amortized per event (per-second coarse buckets, not a
    per-event deque)."""

    __slots__ = ("_window_s", "_lock", "_buckets")

    def __init__(self, window_s: float = 10.0):
        self._window_s = max(1.0, float(window_s))
        self._lock = _sync.leaf_lock("monitoring/windowed_rate")
        self._buckets: Dict[int, int] = {}

    def add(self, n: int = 1, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        sec = int(now)
        with self._lock:
            self._buckets[sec] = self._buckets.get(sec, 0) + n
            self._evict(now)

    def _evict(self, now: float) -> None:
        horizon = int(now - self._window_s) - 1
        if len(self._buckets) > self._window_s + 2:
            for sec in [s for s in self._buckets if s <= horizon]:
                del self._buckets[sec]

    def rate(self, now: Optional[float] = None) -> float:
        """Events/sec over the trailing window (0.0 when idle)."""
        now = time.monotonic() if now is None else now
        lo = now - self._window_s
        with self._lock:
            total = sum(c for s, c in self._buckets.items() if s + 1 > lo)
        return total / self._window_s


def export() -> Dict[str, Any]:
    """The whole registry as {metric_name: snapshot} (nested dict of
    plain Python scalars — JSON-able as-is)."""
    with _registry_lock:
        metrics = list(_registry.items())
    return {name: m.snapshot() for name, m in sorted(metrics)}


def to_json(**dumps_kwargs) -> str:
    """Strict-JSON dump of ``export()``: non-finite floats (the +inf
    final bucket edge) become strings, since json.dumps would otherwise
    emit the nonstandard ``Infinity`` token no RFC-8259 parser accepts."""

    def _sanitize(o):
        if isinstance(o, dict):
            return {k: _sanitize(v) for k, v in o.items()}
        if isinstance(o, (list, tuple)):
            return [_sanitize(v) for v in o]
        if isinstance(o, float) and (o != o or o in (float("inf"),
                                                     float("-inf"))):
            return str(o)
        return o

    return json.dumps(_sanitize(export()), default=str, **dumps_kwargs)


def _prom_name(name: str) -> str:
    """Metric-name sanitization for the ``/stf/...`` path style:
    every non-[a-zA-Z0-9_] character becomes ``_``, leading/trailing
    runs are stripped, and a name left empty or starting with a digit
    gets a ``_`` prefix (the exposition format's name grammar is
    ``[a-zA-Z_:][a-zA-Z0-9_:]*``; we never emit ``:`` — it is reserved
    for recording rules)."""
    out = "".join(c if c.isalnum() or c == "_" else "_" for c in name)
    out = out.strip("_")
    if not out:
        return "_"
    if out[0].isdigit():
        out = "_" + out
    return out


def _prom_label_value(v) -> str:
    """Escape per the exposition format: backslash, double quote, and
    newline inside label values."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _prom_help(text: str) -> str:
    """HELP escaping: backslash and newline only (quotes stay literal
    in HELP text per the exposition format)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _prom_float(v: float) -> str:
    """Sample-value rendering: finite floats as repr, non-finites as
    the exposition tokens ``+Inf``/``-Inf``/``NaN``."""
    v = float(v)
    if v != v:
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    return repr(v)


def to_prometheus() -> str:
    """Prometheus text exposition format (version 0.0.4). Counters and
    gauges map directly; Samplers map to the native histogram type
    (CUMULATIVE ``_bucket`` series ending in ``le="+Inf"`` whose count
    equals ``_count``); PercentileSamplers map to summary quantiles;
    StringGauges become info-style series (``value="..."`` label,
    sample 1). Iterates the live cells with their tuple label keys, so
    label VALUES — including empty strings and values containing the
    export() separator — round-trip exactly."""
    with _registry_lock:
        metrics = sorted(_registry.items())
    lines: List[str] = []
    for name, m in metrics:
        pname = _prom_name(name)
        labels = m.label_names

        def _labelstr(key: Tuple[str, ...], extra: str = "") -> str:
            parts = [f'{ln}="{_prom_label_value(lv)}"'
                     for ln, lv in zip(labels, key)]
            if extra:
                parts.append(extra)
            return "{" + ",".join(parts) + "}" if parts else ""

        lines.append(f"# HELP {pname} {_prom_help(m.description)}")
        typ = m.metric_type
        cells = sorted(m.cells().items())
        if typ == "Counter":
            lines.append(f"# TYPE {pname} counter")
            for key, cell in cells:
                lines.append(f"{pname}{_labelstr(key)} {cell.value()}")
        elif typ in ("IntGauge", "BoolGauge"):
            lines.append(f"# TYPE {pname} gauge")
            for key, cell in cells:
                lines.append(f"{pname}{_labelstr(key)} {int(cell.value())}")
        elif typ == "StringGauge":
            lines.append(f"# TYPE {pname} gauge")
            for key, cell in cells:
                extra = f'value="{_prom_label_value(cell.value())}"'
                lines.append(f"{pname}{_labelstr(key, extra)} 1")
        elif typ == "Sampler":
            lines.append(f"# TYPE {pname} histogram")
            for key, cell in cells:
                v = cell.value()
                cum = 0
                for edge, count in v["buckets"]:
                    cum += count
                    extra = f'le="{_prom_float(edge)}"'
                    lines.append(
                        f"{pname}_bucket{_labelstr(key, extra)} {cum}")
                lines.append(f"{pname}_sum{_labelstr(key)} "
                             f"{_prom_float(v['sum'])}")
                lines.append(f"{pname}_count{_labelstr(key)} {v['count']}")
        elif typ == "PercentileSampler":
            lines.append(f"# TYPE {pname} summary")
            for key, cell in cells:
                v = cell.value()
                for p, q in v["percentiles"].items():
                    extra = f'quantile="{_prom_float(p / 100.0)}"'
                    lines.append(
                        f"{pname}{_labelstr(key, extra)} {_prom_float(q)}")
                lines.append(f"{pname}_sum{_labelstr(key)} "
                             f"{_prom_float(v['sum'])}")
                lines.append(f"{pname}_count{_labelstr(key)} {v['count']}")
        else:  # unknown family type: emit nothing but the HELP line
            lines.append(f"# TYPE {pname} untyped")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# tracing
# ---------------------------------------------------------------------------

class TraceBuffer:
    """Span sink. Spans are dicts {name, start_s (perf_counter), dur_s,
    tid (OS thread id), meta}. Appends are locked so spawned worker
    threads can share a buffer installed by their parent."""

    def __init__(self):
        self.spans: List[Dict[str, Any]] = []
        self._lock = _sync.leaf_lock("monitoring/trace_buffer")

    def append(self, span: Dict[str, Any]):
        with self._lock:
            self.spans.append(span)

    def drain(self) -> List[Dict[str, Any]]:
        with self._lock:
            out, self.spans = self.spans, []
        return out

    def __len__(self):
        return len(self.spans)


_trace_local = threading.local()


def _sinks() -> List[TraceBuffer]:
    sinks = getattr(_trace_local, "sinks", None)
    if sinks is None:
        sinks = _trace_local.sinks = []
    return sinks


def tracing_active() -> bool:
    return bool(getattr(_trace_local, "sinks", None))


def active_trace_buffers() -> List[TraceBuffer]:
    """The collections installed on the CURRENT thread. Worker threads
    (e.g. stf.data pipeline stages) enter ``trace_collection(buf)`` for
    each of these so their spans land in the parent's trace — sinks are
    per-thread, a spawned thread starts with none."""
    return list(getattr(_trace_local, "sinks", None) or [])


class trace_collection:
    """Install ``buffer`` as an active per-thread span sink for the
    duration of the ``with`` block; nested collections stack (each span
    lands in every active buffer)."""

    def __init__(self, buffer: Optional[TraceBuffer] = None):
        self.buffer = buffer if buffer is not None else TraceBuffer()

    def __enter__(self) -> TraceBuffer:
        _sinks().append(self.buffer)
        return self.buffer

    def __exit__(self, *exc):
        sinks = _sinks()
        if self.buffer in sinks:
            sinks.remove(self.buffer)
        return False


def record_span(name: str, start_s: float, dur_s: float, **meta):
    """Manually record a span (for phases that can't wrap a ``with``
    block). No-op when no collection is active on this thread."""
    sinks = getattr(_trace_local, "sinks", None)
    if sinks:
        span = {"name": name, "start_s": start_s, "dur_s": dur_s,
                "tid": threading.get_ident(), "meta": meta}
        for s in sinks:
            s.append(span)


class traceme:
    """Span context-manager (ref: profiler TraceMe). Free when no
    collection is active on this thread. ``meta`` keys land in the
    span's ``meta`` dict (rendered as chrome-trace ``args``)."""

    __slots__ = ("name", "meta", "_t0", "_sinks")

    def __init__(self, name: str, **meta):
        self.name = name
        self.meta = meta
        self._sinks = None

    def __enter__(self):
        sinks = getattr(_trace_local, "sinks", None)
        if sinks:
            self._sinks = list(sinks)
            self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._sinks:
            dur = time.perf_counter() - self._t0
            span = {"name": self.name, "start_s": self._t0, "dur_s": dur,
                    "tid": threading.get_ident(), "meta": self.meta}
            for s in self._sinks:
                s.append(span)
        return False


# ---------------------------------------------------------------------------
# /stf/sync/* — the lock-witness plane's own metrics. Created HERE (not
# in platform.sync) because sync is stdlib-only — monitoring's own
# locks come from it, so the import can only run this direction. The
# families register at import time (the docs/OBSERVABILITY.md drift
# gate requires it) and the cell-update callables are injected into
# sync, which calls them outside its internal lock with a reentrancy
# guard set.
# ---------------------------------------------------------------------------

_sync_contentions = Counter(
    "/stf/sync/contentions",
    "Contended sync.Lock acquisitions (waits >= 100us)", "lock")
_sync_wait_seconds = Sampler(
    "/stf/sync/contention_wait_seconds",
    ExponentialBuckets(1e-4, 4.0, 10),
    "Seconds spent blocked on contended sync.Lock acquires", "lock")
_sync_potential_deadlocks = Counter(
    "/stf/sync/potential_deadlocks",
    "Lock-order cycles observed by the witness (potential deadlocks, "
    "deduped by cycle)", "cycle")
_sync_rank_violations = Counter(
    "/stf/sync/rank_violations",
    "Acquisitions of a lower-ranked lock while holding a higher-ranked "
    "one", "lock")
_sync_witness_edges = IntGauge(
    "/stf/sync/witness_edges",
    "Distinct lock-order edges in the witness graph")

_sync.bind_metrics(
    contention=lambda lock:
        _sync_contentions.get_cell(lock).increase_by(1),
    wait=lambda lock, s: _sync_wait_seconds.get_cell(lock).add(s),
    cycle=lambda key:
        _sync_potential_deadlocks.get_cell(key).increase_by(1),
    violation=lambda lock:
        _sync_rank_violations.get_cell(lock).increase_by(1),
    edges=lambda n: _sync_witness_edges.get_cell().set(n),
)
