"""Test utilities (ref: tensorflow/python/framework/test_util.py,
python/platform/test.py): TestCase with session helper + assertAllClose."""

from __future__ import annotations

import contextlib
import tempfile
import unittest

import numpy as np


class TestCase(unittest.TestCase):
    """(ref: test_util.py:282 ``class TensorFlowTestCase``)."""

    def setUp(self):
        super().setUp()
        from ..framework import graph as ops_mod

        ops_mod.reset_default_graph()
        self._cached_session = None

    def tearDown(self):
        if self._cached_session is not None:
            self._cached_session.close()
            self._cached_session = None
        super().tearDown()

    @contextlib.contextmanager
    def test_session(self, graph=None, config=None, use_gpu=False,
                     force_gpu=False):
        from ..client.session import Session

        if self._cached_session is None:
            self._cached_session = Session(graph=graph, config=config)
        with self._cached_session.as_default() as sess:
            yield sess

    session = test_session

    def get_temp_dir(self):
        if not hasattr(self, "_tmpdir"):
            self._tmpdir = tempfile.mkdtemp()
        return self._tmpdir

    def _as_np(self, x):
        return np.asarray(x)

    def assertAllClose(self, a, b, rtol=1e-6, atol=1e-6, msg=None):
        np.testing.assert_allclose(self._as_np(a).astype(np.float64),
                                   self._as_np(b).astype(np.float64),
                                   rtol=rtol, atol=atol, err_msg=msg or "")

    def assertAllCloseAccordingToType(self, a, b, rtol=1e-6, atol=1e-6,
                                      float_rtol=1e-6, float_atol=1e-6,
                                      half_rtol=1e-3, half_atol=1e-3,
                                      bfloat16_rtol=1e-2, bfloat16_atol=1e-2):
        a = self._as_np(a)
        if a.dtype == np.float16:
            rtol, atol = half_rtol, half_atol
        elif str(a.dtype) == "bfloat16":
            rtol, atol = bfloat16_rtol, bfloat16_atol
        self.assertAllClose(a, b, rtol=rtol, atol=atol)

    def assertAllEqual(self, a, b, msg=None):
        np.testing.assert_array_equal(self._as_np(a), self._as_np(b),
                                      err_msg=msg or "")

    def assertArrayNear(self, farray1, farray2, err):
        for f1, f2 in zip(farray1, farray2):
            self.assertTrue(abs(f1 - f2) <= err)

    def assertNear(self, f1, f2, err, msg=None):
        self.assertTrue(abs(f1 - f2) <= err, msg)

    def assertShapeEqual(self, np_array, tensor):
        self.assertEqual(list(np_array.shape), tensor.shape.as_list())

    def assertDeviceEqual(self, d1, d2):
        self.assertEqual(str(d1), str(d2))

    @contextlib.contextmanager
    def assertRaisesOpError(self, expected_err_re):
        from ..framework import errors

        with self.assertRaisesRegex(errors.OpError, expected_err_re):
            yield


def main(argv=None):
    unittest.main()


def is_built_with_cuda():
    return False


def is_gpu_available(cuda_only=False, min_cuda_compute_capability=None):
    return False


def gpu_device_name():
    return ""


def get_temp_dir():
    return tempfile.mkdtemp()
