"""Logging shim (ref: tensorflow/python/platform/tf_logging.py)."""

from __future__ import annotations

import logging as _logging
import sys

_logger = _logging.getLogger("stf")
if not _logger.handlers:
    _h = _logging.StreamHandler(sys.stderr)
    _h.setFormatter(_logging.Formatter(
        "%(asctime)s %(levelname).1s stf] %(message)s"))
    _logger.addHandler(_h)
    _logger.setLevel(_logging.INFO)

DEBUG = _logging.DEBUG
INFO = _logging.INFO
WARN = _logging.WARNING
ERROR = _logging.ERROR
FATAL = _logging.CRITICAL

debug = _logger.debug
info = _logger.info
warn = _logger.warning
warning = _logger.warning
error = _logger.error
fatal = _logger.critical
log = _logger.log


def set_verbosity(level):
    _logger.setLevel(level)


def get_verbosity():
    return _logger.level


def log_first_n(level, msg, n, *args):
    log(level, msg, *args)


def log_every_n(level, msg, n, *args):
    log(level, msg, *args)


def flush():
    for h in _logger.handlers:
        h.flush()
