"""stf.metrics (ref: tensorflow/python/ops/metrics_impl.py).

Reference semantics: each metric returns (value, update_op) backed by local
accumulator variables; run update_op per batch, read value at the end.
"""

from __future__ import annotations

from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from ..ops import array_ops, math_ops, state_ops
from ..ops import variables as variables_mod

GraphKeys = ops_mod.GraphKeys


def _metric_variable(shape, name):
    from ..ops import array_ops as ao

    return variables_mod.Variable(
        ao.zeros(shape, dtype="float32"), trainable=False, name=name,
        collections=[GraphKeys.LOCAL_VARIABLES, "metric_variables"])


def mean(values, weights=None, metrics_collections=None,
         updates_collections=None, name=None):
    """(ref: metrics_impl.py:232 ``mean``)."""
    with ops_mod.name_scope(name, "mean"):
        values = math_ops.cast(ops_mod.convert_to_tensor(values), "float32")
        total = _metric_variable([], "total")
        count = _metric_variable([], "count")
        if weights is not None:
            w = math_ops.cast(ops_mod.convert_to_tensor(weights), "float32")
            values = values * w
            num = math_ops.reduce_sum(w * array_ops.ones_like(values))
        else:
            num = math_ops.cast(array_ops.size(values), "float32")
        upd_total = state_ops.assign_add(total._ref,
                                         math_ops.reduce_sum(values))
        upd_count = state_ops.assign_add(count._ref, num)
        value = total._ref / math_ops.maximum(
            count._ref, ops_mod.convert_to_tensor(1e-12))
        update_op = upd_total / math_ops.maximum(
            upd_count, ops_mod.convert_to_tensor(1e-12))
        if metrics_collections:
            ops_mod.add_to_collections(metrics_collections, value)
        if updates_collections:
            ops_mod.add_to_collections(updates_collections, update_op)
        return value, update_op


def accuracy(labels, predictions, weights=None, metrics_collections=None,
             updates_collections=None, name=None):
    """(ref: metrics_impl.py:372 ``accuracy``)."""
    with ops_mod.name_scope(name, "accuracy"):
        labels = ops_mod.convert_to_tensor(labels)
        predictions = ops_mod.convert_to_tensor(predictions)
        if predictions.dtype.base_dtype != labels.dtype.base_dtype:
            predictions = math_ops.cast(predictions, labels.dtype.base_dtype)
        is_correct = math_ops.cast(math_ops.equal(predictions, labels),
                                   "float32")
        return mean(is_correct, weights, metrics_collections,
                    updates_collections)


def _confusion_counts(labels, predictions, weights):
    labels = math_ops.cast(ops_mod.convert_to_tensor(labels), "bool")
    predictions = math_ops.cast(ops_mod.convert_to_tensor(predictions), "bool")

    def count(cond):
        c = math_ops.cast(cond, "float32")
        if weights is not None:
            c = c * math_ops.cast(ops_mod.convert_to_tensor(weights),
                                  "float32")
        return math_ops.reduce_sum(c)

    tp = count(math_ops.logical_and(predictions, labels))
    fp = count(math_ops.logical_and(predictions, math_ops.logical_not(labels)))
    fn = count(math_ops.logical_and(math_ops.logical_not(predictions), labels))
    tn = count(math_ops.logical_and(math_ops.logical_not(predictions),
                                    math_ops.logical_not(labels)))
    return tp, fp, fn, tn


def _ratio_metric(name_default, num_keys, den_keys):
    def metric(labels, predictions, weights=None, metrics_collections=None,
               updates_collections=None, name=None):
        with ops_mod.name_scope(name, name_default):
            tp_v = _metric_variable([], "tp")
            fp_v = _metric_variable([], "fp")
            fn_v = _metric_variable([], "fn")
            tn_v = _metric_variable([], "tn")
            tp, fp, fn, tn = _confusion_counts(labels, predictions, weights)
            upds = {"tp": state_ops.assign_add(tp_v._ref, tp),
                    "fp": state_ops.assign_add(fp_v._ref, fp),
                    "fn": state_ops.assign_add(fn_v._ref, fn),
                    "tn": state_ops.assign_add(tn_v._ref, tn)}
            cur = {"tp": tp_v._ref, "fp": fp_v._ref, "fn": fn_v._ref,
                   "tn": tn_v._ref}

            def ratio(vals):
                num = math_ops.add_n([vals[k] for k in num_keys]) \
                    if len(num_keys) > 1 else vals[num_keys[0]]
                den = math_ops.add_n([vals[k] for k in den_keys]) \
                    if len(den_keys) > 1 else vals[den_keys[0]]
                return num / math_ops.maximum(
                    den, ops_mod.convert_to_tensor(1e-12))

            value = ratio(cur)
            update_op = ratio(upds)
            if metrics_collections:
                ops_mod.add_to_collections(metrics_collections, value)
            if updates_collections:
                ops_mod.add_to_collections(updates_collections, update_op)
            return value, update_op

    return metric


precision = _ratio_metric("precision", ("tp",), ("tp", "fp"))
recall = _ratio_metric("recall", ("tp",), ("tp", "fn"))


def true_positives(labels, predictions, weights=None, **kw):
    with ops_mod.name_scope(None, "true_positives"):
        v = _metric_variable([], "tp_count")
        tp, _, _, _ = _confusion_counts(labels, predictions, weights)
        return v._ref, state_ops.assign_add(v._ref, tp)


def false_positives(labels, predictions, weights=None, **kw):
    with ops_mod.name_scope(None, "false_positives"):
        v = _metric_variable([], "fp_count")
        _, fp, _, _ = _confusion_counts(labels, predictions, weights)
        return v._ref, state_ops.assign_add(v._ref, fp)


def false_negatives(labels, predictions, weights=None, **kw):
    with ops_mod.name_scope(None, "false_negatives"):
        v = _metric_variable([], "fn_count")
        _, _, fn, _ = _confusion_counts(labels, predictions, weights)
        return v._ref, state_ops.assign_add(v._ref, fn)


def true_negatives(labels, predictions, weights=None, **kw):
    with ops_mod.name_scope(None, "true_negatives"):
        v = _metric_variable([], "tn_count")
        _, _, _, tn = _confusion_counts(labels, predictions, weights)
        return v._ref, state_ops.assign_add(v._ref, tn)


def auc(labels, predictions, weights=None, num_thresholds=200,
        metrics_collections=None, updates_collections=None,
        curve="ROC", name=None):
    """(ref: metrics_impl.py:586 ``auc``): Riemann-sum AUC over thresholds."""
    with ops_mod.name_scope(name, "auc"):
        labels = math_ops.cast(ops_mod.convert_to_tensor(labels), "float32")
        predictions = math_ops.cast(ops_mod.convert_to_tensor(predictions),
                                    "float32")
        kepsilon = 1e-7
        thresholds = [(i + 1) * 1.0 / (num_thresholds - 1)
                      for i in range(num_thresholds - 2)]
        thresholds = [0.0 - kepsilon] + thresholds + [1.0 + kepsilon]
        tp_v = _metric_variable([num_thresholds], "tp")
        fp_v = _metric_variable([num_thresholds], "fp")
        fn_v = _metric_variable([num_thresholds], "fn")
        tn_v = _metric_variable([num_thresholds], "tn")
        import numpy as np

        from ..framework import constant_op

        th = constant_op.constant(
            np.asarray(thresholds, dtype=np.float32).reshape(-1, 1))
        p_flat = array_ops.reshape(predictions, [1, -1])
        l_flat = array_ops.reshape(labels, [1, -1])
        pred_pos = math_ops.cast(math_ops.greater(p_flat, th), "float32")
        lab_pos = l_flat
        tp = math_ops.reduce_sum(pred_pos * lab_pos, axis=1)
        fp = math_ops.reduce_sum(pred_pos * (1 - lab_pos), axis=1)
        fn = math_ops.reduce_sum((1 - pred_pos) * lab_pos, axis=1)
        tn = math_ops.reduce_sum((1 - pred_pos) * (1 - lab_pos), axis=1)
        upd = [state_ops.assign_add(tp_v._ref, tp),
               state_ops.assign_add(fp_v._ref, fp),
               state_ops.assign_add(fn_v._ref, fn),
               state_ops.assign_add(tn_v._ref, tn)]

        def compute(tp, fp, fn, tn):
            eps = ops_mod.convert_to_tensor(kepsilon)
            if curve == "PR":
                prec = tp / math_ops.maximum(tp + fp, eps)
                rec = tp / math_ops.maximum(tp + fn, eps)
                x, y = rec, prec
            else:
                fpr = fp / math_ops.maximum(fp + tn, eps)
                tpr = tp / math_ops.maximum(tp + fn, eps)
                x, y = fpr, tpr
            dx = x[:num_thresholds - 1] - x[1:]
            my = (y[:num_thresholds - 1] + y[1:]) / 2.0
            return math_ops.reduce_sum(dx * my)

        value = compute(tp_v._ref, fp_v._ref, fn_v._ref, tn_v._ref)
        update_op = compute(*upd)
        return value, update_op


def mean_iou(labels, predictions, num_classes, weights=None,
             metrics_collections=None, updates_collections=None, name=None):
    """(ref: metrics_impl.py:937 ``mean_iou``)."""
    with ops_mod.name_scope(name, "mean_iou"):
        cm_v = _metric_variable([num_classes, num_classes], "confusion")
        labels_f = array_ops.reshape(math_ops.cast(
            ops_mod.convert_to_tensor(labels), "int32"), [-1])
        preds_f = array_ops.reshape(math_ops.cast(
            ops_mod.convert_to_tensor(predictions), "int32"), [-1])
        idx = labels_f * num_classes + preds_f
        counts = math_ops.unsorted_segment_sum(
            array_ops.ones_like(math_ops.cast(idx, "float32")), idx,
            num_classes * num_classes)
        cm = array_ops.reshape(counts, [num_classes, num_classes])
        upd = state_ops.assign_add(cm_v._ref, cm)

        def iou(cm_t):
            row = math_ops.reduce_sum(cm_t, axis=0)
            col = math_ops.reduce_sum(cm_t, axis=1)
            diag = array_ops.matrix_diag_part(cm_t)
            denom = row + col - diag
            eps = ops_mod.convert_to_tensor(1e-12)
            valid = math_ops.cast(math_ops.greater(denom, eps), "float32")
            ious = diag / math_ops.maximum(denom, eps)
            return math_ops.reduce_sum(ious * valid) / math_ops.maximum(
                math_ops.reduce_sum(valid), ops_mod.convert_to_tensor(1.0))

        return iou(cm_v._ref), iou(upd)


def root_mean_squared_error(labels, predictions, weights=None,
                            metrics_collections=None,
                            updates_collections=None, name=None):
    with ops_mod.name_scope(name, "rmse"):
        value, update = mean(math_ops.squared_difference(
            math_ops.cast(ops_mod.convert_to_tensor(predictions), "float32"),
            math_ops.cast(ops_mod.convert_to_tensor(labels), "float32")),
            weights)
        return math_ops.sqrt(value), math_ops.sqrt(update)


def mean_absolute_error(labels, predictions, weights=None,
                        metrics_collections=None, updates_collections=None,
                        name=None):
    with ops_mod.name_scope(name, "mae"):
        return mean(math_ops.abs(math_ops.subtract(
            math_ops.cast(ops_mod.convert_to_tensor(predictions), "float32"),
            math_ops.cast(ops_mod.convert_to_tensor(labels), "float32"))),
            weights)


def percentage_below(values, threshold, weights=None, **kw):
    values = math_ops.cast(ops_mod.convert_to_tensor(values), "float32")
    below = math_ops.cast(math_ops.less(
        values, ops_mod.convert_to_tensor(float(threshold))), "float32")
    return mean(below, weights)


# -- round-4 parity fills (the rest of ref metrics_impl.py) ------------------

def mean_squared_error(labels, predictions, weights=None,
                       metrics_collections=None, updates_collections=None,
                       name=None):
    """(ref: metrics_impl.py ``mean_squared_error``)."""
    with ops_mod.name_scope(name, "mse"):
        return mean(math_ops.squared_difference(
            math_ops.cast(ops_mod.convert_to_tensor(predictions),
                          "float32"),
            math_ops.cast(ops_mod.convert_to_tensor(labels), "float32")),
            weights, metrics_collections, updates_collections)


def mean_relative_error(labels, predictions, normalizer, weights=None,
                        metrics_collections=None, updates_collections=None,
                        name=None):
    """(ref: metrics_impl.py ``mean_relative_error``)."""
    with ops_mod.name_scope(name, "mean_relative_error"):
        labels = math_ops.cast(ops_mod.convert_to_tensor(labels),
                               "float32")
        predictions = math_ops.cast(
            ops_mod.convert_to_tensor(predictions), "float32")
        norm = math_ops.cast(ops_mod.convert_to_tensor(normalizer),
                             "float32")
        rel = math_ops.abs(predictions - labels) / math_ops.maximum(
            math_ops.abs(norm), ops_mod.convert_to_tensor(1e-12))
        return mean(rel, weights, metrics_collections,
                    updates_collections)


def mean_cosine_distance(labels, predictions, dim, weights=None,
                         metrics_collections=None, updates_collections=None,
                         name=None):
    """(ref: metrics_impl.py ``mean_cosine_distance``): 1 - cos similarity
    along ``dim`` (inputs assumed unit-normalized, ref contract)."""
    with ops_mod.name_scope(name, "mean_cosine_distance"):
        labels = math_ops.cast(ops_mod.convert_to_tensor(labels),
                               "float32")
        predictions = math_ops.cast(
            ops_mod.convert_to_tensor(predictions), "float32")
        sim = math_ops.reduce_sum(labels * predictions, axis=dim)
        return mean(1.0 - sim, weights, metrics_collections,
                    updates_collections)


def mean_tensor(values, weights=None, metrics_collections=None,
                updates_collections=None, name=None):
    """(ref: metrics_impl.py ``mean_tensor``): elementwise running mean —
    the accumulators keep the VALUE's shape."""
    with ops_mod.name_scope(name, "mean_tensor"):
        values = math_ops.cast(ops_mod.convert_to_tensor(values),
                               "float32")
        shape = [int(d) for d in values.shape.as_list()]
        total = _metric_variable(shape, "total_tensor")
        count = _metric_variable(shape, "count_tensor")
        ones = array_ops.ones_like(values)
        if weights is not None:
            w = math_ops.cast(ops_mod.convert_to_tensor(weights),
                              "float32")
            values = values * w
            ones = ones * w
        upd_t = state_ops.assign_add(total._ref, values)
        upd_c = state_ops.assign_add(count._ref, ones)
        eps = ops_mod.convert_to_tensor(1e-12)
        value = total._ref / math_ops.maximum(count._ref, eps)
        update_op = upd_t / math_ops.maximum(upd_c, eps)
        if metrics_collections:
            ops_mod.add_to_collections(metrics_collections, value)
        if updates_collections:
            ops_mod.add_to_collections(updates_collections, update_op)
        return value, update_op


def mean_per_class_accuracy(labels, predictions, num_classes, weights=None,
                            metrics_collections=None,
                            updates_collections=None, name=None):
    """(ref: metrics_impl.py ``mean_per_class_accuracy``)."""
    with ops_mod.name_scope(name, "mean_per_class_accuracy"):
        total_v = _metric_variable([num_classes], "per_class_total")
        correct_v = _metric_variable([num_classes], "per_class_correct")
        labels_f = array_ops.reshape(math_ops.cast(
            ops_mod.convert_to_tensor(labels), "int32"), [-1])
        preds_f = array_ops.reshape(math_ops.cast(
            ops_mod.convert_to_tensor(predictions), "int32"), [-1])
        ones = array_ops.ones_like(math_ops.cast(labels_f, "float32"))
        if weights is not None:
            ones = ones * array_ops.reshape(math_ops.cast(
                ops_mod.convert_to_tensor(weights), "float32"), [-1])
        is_correct = math_ops.cast(math_ops.equal(labels_f, preds_f),
                                   "float32") * ones
        totals = math_ops.unsorted_segment_sum(ones, labels_f, num_classes)
        corrects = math_ops.unsorted_segment_sum(is_correct, labels_f,
                                                 num_classes)
        upd_t = state_ops.assign_add(total_v._ref, totals)
        upd_c = state_ops.assign_add(correct_v._ref, corrects)

        def compute(tot, cor):
            eps = ops_mod.convert_to_tensor(1e-12)
            valid = math_ops.cast(math_ops.greater(tot, eps), "float32")
            acc = cor / math_ops.maximum(tot, eps)
            return math_ops.reduce_sum(acc * valid) / math_ops.maximum(
                math_ops.reduce_sum(valid),
                ops_mod.convert_to_tensor(1.0))

        return compute(total_v._ref, correct_v._ref), compute(upd_t, upd_c)


def _thresholded_counts(labels, predictions, thresholds, weights):
    import numpy as np

    from ..framework import constant_op

    labels = math_ops.cast(ops_mod.convert_to_tensor(labels), "float32")
    predictions = math_ops.cast(ops_mod.convert_to_tensor(predictions),
                                "float32")
    th = constant_op.constant(
        np.asarray(list(thresholds), np.float32).reshape(-1, 1))
    p = array_ops.reshape(predictions, [1, -1])
    l_ = array_ops.reshape(labels, [1, -1])
    if weights is not None:
        w = array_ops.reshape(math_ops.cast(
            ops_mod.convert_to_tensor(weights), "float32"), [1, -1])
    else:
        w = array_ops.ones_like(l_)
    pred_pos = math_ops.cast(math_ops.greater(p, th), "float32")
    tp = math_ops.reduce_sum(pred_pos * l_ * w, axis=1)
    fp = math_ops.reduce_sum(pred_pos * (1 - l_) * w, axis=1)
    fn = math_ops.reduce_sum((1 - pred_pos) * l_ * w, axis=1)
    tn = math_ops.reduce_sum((1 - pred_pos) * (1 - l_) * w, axis=1)
    return tp, fp, fn, tn


def _at_thresholds(which):
    def metric(labels, predictions, thresholds, weights=None,
               metrics_collections=None, updates_collections=None,
               name=None):
        with ops_mod.name_scope(name, f"{which}_at_thresholds"):
            n = len(list(thresholds))
            tp_v = _metric_variable([n], "tp")
            fp_v = _metric_variable([n], "fp")
            fn_v = _metric_variable([n], "fn")
            tn_v = _metric_variable([n], "tn")
            tp, fp, fn, tn = _thresholded_counts(labels, predictions,
                                                 thresholds, weights)
            upd = {"tp": state_ops.assign_add(tp_v._ref, tp),
                   "fp": state_ops.assign_add(fp_v._ref, fp),
                   "fn": state_ops.assign_add(fn_v._ref, fn),
                   "tn": state_ops.assign_add(tn_v._ref, tn)}
            cur = {"tp": tp_v._ref, "fp": fp_v._ref, "fn": fn_v._ref,
                   "tn": tn_v._ref}

            def ratio(v):
                eps = ops_mod.convert_to_tensor(1e-12)
                if which == "precision":
                    return v["tp"] / math_ops.maximum(v["tp"] + v["fp"],
                                                      eps)
                return v["tp"] / math_ops.maximum(v["tp"] + v["fn"], eps)

            value, update_op = ratio(cur), ratio(upd)
            if metrics_collections:
                ops_mod.add_to_collections(metrics_collections, value)
            if updates_collections:
                ops_mod.add_to_collections(updates_collections, update_op)
            return value, update_op

    return metric


precision_at_thresholds = _at_thresholds("precision")
recall_at_thresholds = _at_thresholds("recall")


def _at_operating_point(fix_which):
    """sensitivity_at_specificity / specificity_at_sensitivity (ref:
    metrics_impl.py): sweep thresholds, pick the one whose fixed metric is
    closest to the target, report the other there."""

    def metric(labels, predictions, target, weights=None,
               num_thresholds=200, metrics_collections=None,
               updates_collections=None, name=None):
        with ops_mod.name_scope(name, f"at_{fix_which}"):
            kepsilon = 1e-7
            thresholds = [(i + 1) * 1.0 / (num_thresholds - 1)
                          for i in range(num_thresholds - 2)]
            thresholds = [0.0 - kepsilon] + thresholds + [1.0 + kepsilon]
            n = len(thresholds)
            tp_v = _metric_variable([n], "tp")
            fp_v = _metric_variable([n], "fp")
            fn_v = _metric_variable([n], "fn")
            tn_v = _metric_variable([n], "tn")
            tp, fp, fn, tn = _thresholded_counts(labels, predictions,
                                                 thresholds, weights)
            upd = [state_ops.assign_add(tp_v._ref, tp),
                   state_ops.assign_add(fp_v._ref, fp),
                   state_ops.assign_add(fn_v._ref, fn),
                   state_ops.assign_add(tn_v._ref, tn)]

            def compute(tp, fp, fn, tn):
                eps = ops_mod.convert_to_tensor(kepsilon)
                sens = tp / math_ops.maximum(tp + fn, eps)
                spec = tn / math_ops.maximum(tn + fp, eps)
                fixed = spec if fix_which == "specificity" else sens
                other = sens if fix_which == "specificity" else spec
                best = math_ops.argmin(
                    math_ops.abs(fixed
                                 - ops_mod.convert_to_tensor(
                                     float(target))), 0)
                from ..ops import array_ops as ao

                return ao.gather(other, best)

            return compute(tp_v._ref, fp_v._ref, fn_v._ref, tn_v._ref), \
                compute(*upd)

    return metric


sensitivity_at_specificity = _at_operating_point("specificity")
specificity_at_sensitivity = _at_operating_point("sensitivity")


def _in_top_k_hits(labels, predictions, k):
    """hit[i] = 1 if labels[i] is among the top-k predictions of row i."""
    labels_i = array_ops.reshape(math_ops.cast(
        ops_mod.convert_to_tensor(labels), "int32"), [-1])
    predictions = math_ops.cast(
        ops_mod.convert_to_tensor(predictions), "float32")
    from ..ops import nn_ops

    hits = nn_ops.in_top_k(predictions, labels_i, k)
    return math_ops.cast(hits, "float32")


def recall_at_k(labels, predictions, k, weights=None,
                metrics_collections=None, updates_collections=None,
                name=None, class_id=None):
    """(ref: metrics_impl.py ``recall_at_k``, single-label case: the
    fraction of examples whose true class is in the top-k). With
    ``class_id`` set, restricted to examples whose label IS that class
    (ref per-class recall)."""
    with ops_mod.name_scope(name, f"recall_at_{k}"):
        hits = _in_top_k_hits(labels, predictions, k)
        if class_id is not None:
            labels_i = array_ops.reshape(math_ops.cast(
                ops_mod.convert_to_tensor(labels), "int32"), [-1])
            mask = math_ops.cast(
                math_ops.equal(labels_i,
                               ops_mod.convert_to_tensor(int(class_id))),
                "float32")
            weights = mask if weights is None else mask * math_ops.cast(
                ops_mod.convert_to_tensor(weights), "float32")
        return mean(hits, weights, metrics_collections,
                    updates_collections)


def sparse_precision_at_k(labels, predictions, k, weights=None,
                          metrics_collections=None,
                          updates_collections=None, name=None,
                          class_id=None):
    """(ref: metrics_impl.py ``sparse_precision_at_k``, single-label:
    hits/k per example). With ``class_id``: among examples whose top-k
    CONTAINS the class, the fraction whose label IS it (ref per-class
    precision@k)."""
    with ops_mod.name_scope(name, f"precision_at_{k}"):
        if class_id is None:
            hits = _in_top_k_hits(labels, predictions, k) / float(k)
            return mean(hits, weights, metrics_collections,
                        updates_collections)
        from ..ops import nn_ops

        predictions = math_ops.cast(
            ops_mod.convert_to_tensor(predictions), "float32")
        labels_i = array_ops.reshape(math_ops.cast(
            ops_mod.convert_to_tensor(labels), "int32"), [-1])
        _v, idx = nn_ops.top_k(predictions, k)
        cid = ops_mod.convert_to_tensor(int(class_id))
        in_topk = math_ops.cast(math_ops.reduce_any(
            math_ops.equal(idx, cid), axis=1), "float32")
        correct = math_ops.cast(math_ops.equal(labels_i, cid), "float32")
        w = in_topk if weights is None else in_topk * math_ops.cast(
            ops_mod.convert_to_tensor(weights), "float32")
        return mean(correct, w, metrics_collections, updates_collections)


def sparse_average_precision_at_k(labels, predictions, k, weights=None,
                                  metrics_collections=None,
                                  updates_collections=None, name=None):
    """(ref: metrics_impl.py ``sparse_average_precision_at_k``,
    single-label: precision at the hit rank, 0 on miss)."""
    with ops_mod.name_scope(name, f"average_precision_at_{k}"):
        predictions = math_ops.cast(
            ops_mod.convert_to_tensor(predictions), "float32")
        labels_i = array_ops.reshape(math_ops.cast(
            ops_mod.convert_to_tensor(labels), "int32"), [-1])
        from ..ops import nn_ops

        _vals, idx = nn_ops.top_k(predictions, k)
        matches = math_ops.cast(
            math_ops.equal(idx, array_ops.expand_dims(labels_i, 1)),
            "float32")
        import numpy as np

        from ..framework import constant_op

        inv_rank = constant_op.constant(
            (1.0 / np.arange(1, k + 1)).astype(np.float32))
        ap = math_ops.reduce_sum(matches * inv_rank, axis=1)
        return mean(ap, weights, metrics_collections, updates_collections)
