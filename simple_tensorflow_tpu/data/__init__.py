"""stf.data: input pipeline (replaces ref queue-based input,
python/training/input.py; Dataset API surface like later TF).

TPU-native: the pipeline runs on the host (numpy), with a background
prefetch thread double-buffering batches onto the device so input never
blocks the step (the role of the reference's QueueRunners + staging areas).
"""

from .dataset import Dataset, Iterator, TFRecordDataset, make_one_shot_iterator
