"""stf.data: input pipeline (replaces ref queue-based input,
python/training/input.py; Dataset API surface like later TF).

TPU-native: the pipeline runs on the host (numpy), compiled into a
parallel stage pipeline (see ``stf.data.pipeline``): sharded C++
TFRecord reads, shared-pool parallel ``map``, ``interleave``,
autotuned ``prefetch`` — with a background device-prefetch stage
double-buffering batches onto the accelerator so input never blocks the
step (the role of the reference's QueueRunners + staging areas).
``stf.data.AUTOTUNE`` lets the per-pipeline autotuner size stage
parallelism from stall-time gauges (docs/DATA.md).
"""

from . import pipeline
from .dataset import (AUTOTUNE, Dataset, Iterator, TFRecordDataset,
                      make_one_shot_iterator)
