"""Parallel host input-pipeline engine for ``stf.data``.

(ref: the reference's tf.data runtime — core/kernels/data/*_dataset_op.cc,
model.cc AUTOTUNE — replacing this repo's lazy nested-generator iteration.)

A Dataset chain records a linear graph of ``Node`` specs; iteration
*compiles* that chain into a stage pipeline:

- **Sequential stages** (filter/take/shuffle/batch/...) stay plain
  generators fused into whichever thread consumes them — zero overhead,
  byte-identical to the pre-engine nested-generator semantics.
- **Async stages** decouple through bounded ``RingBuffer``s with
  backpressure and run on worker threads: ``prefetch`` (one staging
  thread), ``map(num_parallel_calls=...)`` (a shared process-wide task
  pool; ordered mode preserves the exact sequential element order,
  unordered mode emits completion-order), ``interleave`` (per-slot
  puller threads), and sharded ``TFRecordDataset(num_parallel_reads=...)``
  reads (per-shard reader threads delivering *chunks* straight from the
  C++ batch record reader, emitted in strict shard order so the parallel
  stream is byte-identical to the sequential one).
- ``AUTOTUNE`` stages start small and a per-pipeline autotuner thread
  resizes their parallelism (and prefetch ring capacity) from stall-time
  and buffer-occupancy gauges.

Every async stage reports ``/stf/data/*`` metrics (see
docs/OBSERVABILITY.md) and hands its worker threads the creating
thread's active traceme collections, so shard-read/map spans land in the
same timeline as the Session's ``host_stage``/``device_execute`` spans —
pipeline-bound vs device-bound is visible in one trace.

Error contract: any stage exception (source, map_func, record
corruption) propagates to the consuming thread at the position the
element would have occupied; end-of-data is only ever reported after a
clean source exhaustion (the pre-engine ``prefetch`` swallowed worker
exceptions into silent end-of-data).
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from collections import deque
from typing import Any, Callable, List, Optional, Sequence

from ..platform import monitoring
from ..platform import sync as _sync

# every constructed PipelineIterator, while alive (test leak hygiene:
# tests/conftest.py asserts these are all closed after each module)
live_iterators: "weakref.WeakSet" = weakref.WeakSet()

# Sentinel accepted by map/interleave/prefetch/num_parallel_reads: "let
# the autotuner pick and adjust" (same spelling as tf.data.AUTOTUNE).
AUTOTUNE = -1

# Ceiling the autotuner may grow an AUTOTUNE prefetch ring to. ALSO an
# arena-safety bound: prefetch_to_device sizes its ArenaPool as
# ring-max + in-flight margin, so a recycled slot can never still be
# queued in the ring — change it only through this constant.
PREFETCH_AUTOTUNE_MAX = 16

_DONE = object()

# distinct from _DONE: a timed RingBuffer.get that elapsed with the
# buffer still open and empty (the serving batcher's batch-close path —
# "no more requests arrived inside batch_timeout_ms" is not end-of-stream)
TIMED_OUT = object()


class _Error:
    """Wraps an exception crossing a ring buffer / future boundary so it
    re-raises in the consuming thread at the right stream position."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


# ---------------------------------------------------------------------------
# metrics (process-global; registration is idempotent)
# ---------------------------------------------------------------------------

_elements = monitoring.Counter(
    "/stf/data/elements",
    "Elements emitted by each async pipeline stage", "stage")
_stalls = monitoring.Counter(
    "/stf/data/stall_micros",
    "Microseconds a stage boundary spent blocked: produce = waiting for "
    "downstream buffer space, consume = waiting for upstream data",
    "stage", "kind")
_occupancy = monitoring.IntGauge(
    "/stf/data/buffer_occupancy",
    "Elements currently buffered in a stage's output ring", "stage")
_parallelism_gauge = monitoring.IntGauge(
    "/stf/data/parallelism",
    "Live worker parallelism of a stage (AUTOTUNE resizes it)", "stage")
_autotune_adjustments = monitoring.Counter(
    "/stf/data/autotune_adjustments",
    "AUTOTUNE parallelism/capacity resize decisions", "stage", "direction")
_records_read = monitoring.Counter(
    "/stf/data/records_read", "TFRecords delivered by sharded readers")
_pipelines_started = monitoring.Counter(
    "/stf/data/pipelines_started",
    "Pipeline iterations begun, by execution mode", "mode")


# ---------------------------------------------------------------------------
# shared worker pool (element-level tasks: map_func calls, batch parses)
# ---------------------------------------------------------------------------

_pool_lock = _sync.Lock("data/worker_pool",
                        rank=_sync.RANK_LIFECYCLE)
_pool = None
_pool_size = 0


def worker_pool():
    """Process-wide thread pool for element-level tasks. Stream-scoped
    workers (shard readers, interleave slot pullers, prefetch stagers)
    run on dedicated per-stage threads instead — a long-lived producer
    parked in a bounded pool would deadlock the element tasks behind it.
    Size: STF_DATA_WORKERS or 2*cpu (min 4, max 32)."""
    global _pool, _pool_size
    with _pool_lock:
        if _pool is None:
            import concurrent.futures as cf

            n = int(os.environ.get("STF_DATA_WORKERS", "0") or 0)
            if n <= 0:
                n = min(32, max(4, 2 * (os.cpu_count() or 2)))
            _pool_size = n
            _pool = cf.ThreadPoolExecutor(
                max_workers=n, thread_name_prefix="stf_data_worker")
        return _pool


def pool_size() -> int:
    worker_pool()
    return _pool_size


# ---------------------------------------------------------------------------
# per-stage bookkeeping
# ---------------------------------------------------------------------------

class StageStats:
    """Metric cells for one pipeline stage + cheap unsynchronized
    mirrors the autotuner reads without touching the registry locks."""

    __slots__ = ("name", "elements", "_produce", "_consume",
                 "occupancy", "parallelism", "elements_n",
                 "produce_micros", "consume_micros")

    def __init__(self, name: str):
        self.name = name
        self.elements = _elements.get_cell(name)
        self._produce = _stalls.get_cell(name, "produce")
        self._consume = _stalls.get_cell(name, "consume")
        self.occupancy = _occupancy.get_cell(name)
        self.parallelism = _parallelism_gauge.get_cell(name)
        self.elements_n = 0
        self.produce_micros = 0
        self.consume_micros = 0

    def count(self, n: int = 1):
        self.elements.increase_by(n)
        self.elements_n += n

    def stall(self, kind: str, seconds: float):
        us = int(seconds * 1e6)
        if us <= 0:
            return
        if kind == "produce":
            self._produce.increase_by(us)
            self.produce_micros += us
        else:
            self._consume.increase_by(us)
            self.consume_micros += us


class RingBuffer:
    """Bounded buffer between stages. ``put`` blocks while full (the
    backpressure edge), ``get`` blocks while empty; ``close`` wakes every
    waiter (puts start returning False, gets drain then report _DONE).
    Capacity is live-adjustable (AUTOTUNE prefetch grows it).

    Both operations take an optional ``timeout`` (seconds): a timed
    ``put`` that cannot find space returns False with the buffer still
    open (distinguish via ``closed``); a timed ``get`` that finds no
    item returns the module's ``TIMED_OUT`` sentinel. The serving
    admission queue (stf.serving.batcher) runs on exactly this:
    deadline-bounded backpressure on submit, batch-timeout on drain."""

    def __init__(self, capacity: int, stats: Optional[StageStats] = None):
        self._dq: deque = deque()
        self.capacity = max(1, int(capacity))
        self._mutex = _sync.Lock("data/ring_buffer",
                                 rank=_sync.RANK_QUEUE)
        self._not_empty = _sync.Condition(self._mutex)
        self._not_full = _sync.Condition(self._mutex)
        self._closed = False
        self._stats = stats

    @property
    def closed(self) -> bool:
        return self._closed

    @staticmethod
    def _wait(cond, deadline):
        if deadline is None:
            cond.wait(0.1)
            return True
        remaining = deadline - time.perf_counter()
        if remaining <= 0:
            return False
        cond.wait(min(remaining, 0.1))
        return True

    def put(self, item, timeout: Optional[float] = None) -> bool:
        with self._not_full:
            if self._closed:
                return False
            if len(self._dq) >= self.capacity:
                deadline = None if timeout is None \
                    else time.perf_counter() + timeout
                t0 = time.perf_counter()
                while len(self._dq) >= self.capacity and not self._closed:
                    if not self._wait(self._not_full, deadline):
                        break
                if self._stats is not None:
                    self._stats.stall("produce", time.perf_counter() - t0)
                if self._closed or len(self._dq) >= self.capacity:
                    return False
            self._dq.append(item)
            if self._stats is not None:
                self._stats.occupancy.set(len(self._dq))
            self._not_empty.notify()
            return True

    def get(self, timeout: Optional[float] = None):
        """Next item; _DONE when closed and drained (cancellation path —
        producers signal normal end-of-stream by putting _DONE); with a
        ``timeout``, TIMED_OUT when it elapses with the buffer open."""
        with self._not_empty:
            if not self._dq:
                deadline = None if timeout is None \
                    else time.perf_counter() + timeout
                t0 = time.perf_counter()
                while not self._dq and not self._closed:
                    if not self._wait(self._not_empty, deadline):
                        break
                if self._stats is not None:
                    self._stats.stall("consume", time.perf_counter() - t0)
                if not self._dq:
                    return _DONE if self._closed else TIMED_OUT
            item = self._dq.popleft()
            if self._stats is not None:
                self._stats.occupancy.set(len(self._dq))
            self._not_full.notify()
            return item

    def get_available(self, max_items: int) -> list:
        """Pop up to ``max_items`` WITHOUT blocking (possibly none) in
        ONE lock acquisition — the serving batcher coalesces a burst of
        queued requests this way instead of paying a condition-variable
        round-trip per element."""
        out: list = []
        if max_items <= 0:
            return out
        with self._not_empty:
            while self._dq and len(out) < max_items:
                out.append(self._dq.popleft())
            if out:
                if self._stats is not None:
                    self._stats.occupancy.set(len(self._dq))
                self._not_full.notify_all()
        return out

    def set_capacity(self, capacity: int):
        with self._not_full:
            self.capacity = max(1, int(capacity))
            self._not_full.notify_all()

    def close(self):
        with self._mutex:
            self._closed = True
            self._not_empty.notify_all()
            self._not_full.notify_all()

    def __len__(self):
        with self._mutex:
            return len(self._dq)


class _Knob:
    """One autotunable quantity (a stage's worker window or a ring's
    capacity). ``value`` is read by the stage on every scheduling
    decision, so autotuner writes take effect immediately."""

    __slots__ = ("stats", "value", "lo", "hi", "ring",
                 "_last_elems", "_last_consume", "_last_produce")

    def __init__(self, stats: StageStats, value: int, lo: int, hi: int,
                 ring: Optional[RingBuffer] = None):
        self.stats = stats
        self.value = value
        self.lo = lo
        self.hi = max(lo, hi)
        self.ring = ring  # when set, autotune resizes ring capacity too
        self._last_elems = 0
        self._last_consume = 0
        self._last_produce = 0
        stats.parallelism.set(value)

    def tick(self):
        """One autotune step: stall-per-element since the last tick
        decides the direction. A stage whose consumers wait long per
        element is the bottleneck -> widen; a stage that mostly waits on
        downstream buffer space overprovisions -> narrow."""
        st = self.stats
        d_elems = st.elements_n - self._last_elems
        d_consume = st.consume_micros - self._last_consume
        d_produce = st.produce_micros - self._last_produce
        self._last_elems = st.elements_n
        self._last_consume = st.consume_micros
        self._last_produce = st.produce_micros
        if d_elems <= 0 and d_consume <= 0:
            return
        wait_per_elem = d_consume / max(1, d_elems)
        produce_per_elem = d_produce / max(1, d_elems)
        if (wait_per_elem > 200.0 and produce_per_elem < wait_per_elem
                and self.value < self.hi):
            self.value += 1
            _autotune_adjustments.get_cell(st.name, "up").increase_by(1)
        elif wait_per_elem < 20.0 and self.value > self.lo:
            self.value -= 1
            _autotune_adjustments.get_cell(st.name, "down").increase_by(1)
        else:
            return
        st.parallelism.set(self.value)
        if self.ring is not None:
            self.ring.set_capacity(self.value)


class PipelineRun:
    """Shared state of one pipeline iteration: cancellation, dedicated
    stage threads, buffers to close, autotune knobs, and the creating
    thread's traceme collections (installed into every stage thread so
    worker spans land in the caller's trace)."""

    AUTOTUNE_INTERVAL_S = 0.05

    def __init__(self):
        self.cancel = threading.Event()
        self._threads: List[threading.Thread] = []
        self._buffers: List[RingBuffer] = []
        self._knobs: List[_Knob] = []
        self._trace_sinks = monitoring.active_trace_buffers()
        self._closed = False
        self._autotune_started = False
        self._lock = _sync.Lock("data/pipeline_run",
                                rank=_sync.RANK_ENGINE)

    def spawn(self, name: str, fn: Callable[[], None]) -> threading.Thread:
        sinks = self._trace_sinks

        def run():
            import contextlib

            from ..telemetry import recorder as _flight

            rec = _flight.get_recorder()
            rec.record("data_stage", stage=name, action="start")
            with contextlib.ExitStack() as stack:
                for b in sinks:
                    stack.enter_context(monitoring.trace_collection(b))
                try:
                    fn()
                except Exception as e:
                    # stage bodies forward their own errors through
                    # buffers; anything escaping here is a bug in the
                    # engine itself — don't kill the process thread pool
                    rec.record("data_stage", stage=name, action="error",
                               error_type=type(e).__name__,
                               message=str(e)[:300])
                    if not self.cancel.is_set():
                        raise
                finally:
                    rec.record("data_stage", stage=name, action="exit")

        t = threading.Thread(target=run, name=f"stf_data_{name}",
                             daemon=True)
        with self._lock:
            self._threads.append(t)
        t.start()
        return t

    def register_buffer(self, buf: RingBuffer) -> RingBuffer:
        with self._lock:
            self._buffers.append(buf)
        return buf

    def register_knob(self, knob: _Knob) -> _Knob:
        # Knobs register lazily, from inside stage generator bodies on
        # their first element — NOT at pipeline build — so the autotuner
        # thread must start on first registration rather than once after
        # compile (when the knob list is still empty).
        with self._lock:
            self._knobs.append(knob)
            start = not self._autotune_started and not self._closed
            self._autotune_started = self._autotune_started or start
        if start:
            self._start_autotuner()
        return knob

    def _start_autotuner(self):
        def tune():
            while not self.cancel.wait(self.AUTOTUNE_INTERVAL_S):
                for knob in list(self._knobs):
                    knob.tick()

        self.spawn("autotune", tune)

    def close(self):
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self.cancel.set()
        for b in self._buffers:
            b.close()


class PipelineIterator:
    """Iterator over a compiled pipeline. ``close()`` (also driven by
    GC and end-of-stream) cancels stage threads and releases buffers —
    checkpoint restore replaces iterators mid-stream, so shutdown must
    not wait for sources to drain.

    Live instances register in ``live_iterators`` (a WeakSet) so test
    hygiene fixtures can assert every iterator a test created was
    closed (an unclosed iterator pins its stage threads and ring
    buffers until GC happens to run)."""

    def __init__(self, run: PipelineRun, gen):
        self._run = run
        self._gen = gen
        live_iterators.add(self)

    def __iter__(self):
        return self

    def __next__(self):
        if self._gen is None:
            raise StopIteration
        try:
            return next(self._gen)
        except StopIteration:
            self.close()
            raise
        except BaseException:
            self.close()
            raise

    @property
    def closed(self) -> bool:
        return self._run is None and self._gen is None

    def close(self):
        run, gen = self._run, self._gen
        self._run = None
        self._gen = None
        if run is not None:
            run.close()
        if gen is not None:
            try:
                gen.close()
            except Exception:
                pass

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# node spec (built by Dataset transforms, compiled here)
# ---------------------------------------------------------------------------

class Node:
    """One stage spec in a Dataset chain. ``kind`` selects the executor;
    ``args`` carry the transform payload. ``alloc_pool`` (batch-like
    nodes only) is installed by ``prefetch_to_device`` so batches
    assemble directly into C++ arena staging buffers."""

    __slots__ = ("kind", "parent", "args", "alloc_pool")

    def __init__(self, kind: str, parent: Optional["Node"], args: tuple):
        self.kind = kind
        self.parent = parent
        self.args = args
        self.alloc_pool = None


def _chain(node: Node) -> List[Node]:
    out = []
    while node is not None:
        out.append(node)
        node = node.parent
    out.reverse()
    return out


def _is_parallel(node: Node) -> bool:
    if node.kind == "prefetch":
        return True
    if node.kind == "pmap":
        return True
    if node.kind == "interleave":
        return node.args[3] is not None  # num_parallel_calls
    if node.kind == "tfrecord":
        return node.args[2] is not None  # num_parallel_reads
    return False


def chain_is_parallel(node: Node) -> bool:
    return any(_is_parallel(n) for n in _chain(node))


def _resolve(n, default: int, cap: int):
    """num_parallel_* value -> (initial, lo, hi, autotuned)."""
    if n == AUTOTUNE:
        return min(default, cap), 1, cap, True
    n = int(n)
    return min(n, cap), min(n, cap), min(n, cap), False


# -- stage executors ---------------------------------------------------------

def _source_iter(node: Node):
    (factory,) = node.args
    return iter(factory())


def _zip_iter(node: Node):
    (datasets,) = node.args
    its = [iter(d) for d in datasets]
    try:
        while True:
            row = []
            for it in its:
                try:
                    row.append(next(it))
                except StopIteration:
                    return
            yield tuple(row)
    finally:
        for it in its:
            if hasattr(it, "close"):
                it.close()


def _seq_iter(node: Node, up):
    apply_fn = node.args[0]
    return apply_fn(up)


def _repeat_iter(run: Optional[PipelineRun], node: Node, up):
    """Epoch 0 consumes the already-compiled upstream iterator; later
    epochs recompile the upstream chain in the SAME execution mode
    (parallel upstream stages re-spin per epoch). ``yield from``
    delegates close() into the per-epoch PipelineIterator (PEP 380), so
    cancelling mid-epoch tears the epoch's stage threads down."""
    (count,) = node.args
    n = 0
    it = up
    while count is None or n < count:
        yield from it
        n += 1
        if count is None or n < count:
            it = build_iterator(node.parent, sequential=(run is None),
                                _count=False)


def _batch_iter(node: Node, up):
    batch_size, drop_remainder, stack_fn = node.args
    pool = node.alloc_pool
    buf = []
    for x in up:
        buf.append(x)
        if len(buf) == batch_size:
            yield _assemble(stack_fn, buf, pool)
            buf = []
    if buf and not drop_remainder:
        yield _assemble(stack_fn, buf, pool)


class ArenaBatch:
    """A batch assembled directly in a C++ arena slot; carried through
    prefetch rings to ``prefetch_to_device``, which transfers ``value``
    and recycles ``slot`` once the DMA completes (no intermediate host
    copy between batch assembly and the device transfer)."""

    __slots__ = ("value", "slot")

    def __init__(self, value, slot):
        self.value = value
        self.slot = slot


def _assemble(stack_fn, rows, pool):
    if pool is None:
        return stack_fn(rows, None)
    slot, arena = pool.acquire()

    def alloc(shape, dtype):
        return arena.alloc_ndarray(shape, dtype)

    return ArenaBatch(stack_fn(rows, alloc), slot)


def _prefetch_iter(run: PipelineRun, node: Node, up, label: str):
    (capacity,) = node.args
    stats = StageStats(label)
    # an explicit buffer_size is honored exactly (the 16 cap bounds only
    # AUTOTUNE growth — a user asking for prefetch(64) gets 64 slots)
    if capacity is None:
        capacity = 2
    if capacity == AUTOTUNE:
        cap0, lo, hi, autotuned = 2, 1, PREFETCH_AUTOTUNE_MAX, True
    else:
        cap0 = int(capacity)
        lo = hi = cap0
        autotuned = False
    ring = run.register_buffer(RingBuffer(cap0, stats))
    if autotuned:
        run.register_knob(_Knob(stats, cap0, lo, hi, ring=ring))
    else:
        stats.parallelism.set(cap0)

    def work():
        try:
            for x in up:
                if not ring.put(x):
                    return
            ring.put(_DONE)
        except BaseException as e:  # noqa: BLE001 — satellite: NEVER
            # convert a source error into silent end-of-data
            ring.put(_Error(e))

    run.spawn(f"{label}_stage", work)
    while True:
        item = ring.get()
        if item is _DONE:
            return
        if isinstance(item, _Error):
            raise item.exc
        stats.count()
        yield item


def _call_guarded(fn, x):
    try:
        return fn(x)
    except BaseException as e:  # noqa: BLE001 — re-raised at position
        return _Error(e)


def _pmap_ordered_iter(run: PipelineRun, node: Node, up, label: str):
    fn, n, _det = node.args
    stats = StageStats(label)
    pool = worker_pool()
    value, lo, hi, autotuned = _resolve(n, 2, pool_size())
    knob = _Knob(stats, value, lo, hi)
    if autotuned:
        run.register_knob(knob)
    futures: deque = deque()
    exhausted = False
    upstream_exc = None
    while True:
        while (not exhausted and len(futures) < knob.value
               and not run.cancel.is_set()):
            try:
                x = next(up)
            except StopIteration:
                exhausted = True
                break
            except BaseException as e:  # noqa: BLE001 — at-position
                # contract: elements already mapped are delivered first,
                # the upstream error raises at the position it occupies
                exhausted = True
                upstream_exc = e
                break
            futures.append(pool.submit(_call_guarded, fn, x))
        if not futures:
            if upstream_exc is not None:
                raise upstream_exc
            return
        f = futures.popleft()
        t0 = time.perf_counter()
        res = f.result()
        stats.stall("consume", time.perf_counter() - t0)
        if isinstance(res, _Error):
            raise res.exc
        stats.count()
        yield res


def _pmap_unordered_iter(run: PipelineRun, node: Node, up, label: str):
    fn, n, _det = node.args
    stats = StageStats(label)
    pool = worker_pool()
    value, lo, hi, autotuned = _resolve(n, 2, pool_size())
    knob = _Knob(stats, value, lo, hi)
    if autotuned:
        run.register_knob(knob)
    ring = run.register_buffer(RingBuffer(max(2, 2 * hi), stats))
    cv = _sync.Condition(name="data/pmap_inflight",
                         rank=_sync.RANK_QUEUE)
    inflight = [0]

    def on_done(fut):
        # Runs on a shared-pool worker thread, so it must NEVER block:
        # a callback parked in ring.put holds a pool slot, and with
        # enough of them parked a second pool-using stage can never run
        # — permanent deadlock (the worker_pool invariant). inflight is
        # released by the CONSUMER as it takes each item, so ring
        # occupancy <= inflight <= hi < capacity and this put cannot
        # hit backpressure.
        ring.put(fut.result())  # _call_guarded: never raises

    def feed():
        err = None
        try:
            for x in up:
                with cv:
                    while (inflight[0] >= knob.value
                           and not run.cancel.is_set()):
                        cv.wait(0.1)
                    if run.cancel.is_set():
                        return
                    inflight[0] += 1
                pool.submit(_call_guarded, fn, x).add_done_callback(on_done)
        except BaseException as e:  # noqa: BLE001 — held until in-flight
            # results drain: already-mapped elements are delivered, the
            # upstream error follows at its stream position
            err = e
        with cv:
            while inflight[0] > 0 and not run.cancel.is_set():
                cv.wait(0.1)
        ring.put(_Error(err) if err is not None else _DONE)

    run.spawn(f"{label}_feeder", feed)
    while True:
        item = ring.get()
        if item is _DONE:
            return
        if isinstance(item, _Error):
            raise item.exc
        with cv:
            inflight[0] -= 1
            cv.notify_all()
        stats.count()
        yield item


def _tfrecord_iter(run: Optional[PipelineRun], node: Node, label: str):
    files, open_chunks, num_parallel_reads = node.args
    rec_cell = _records_read.get_cell()
    if run is None or num_parallel_reads is None:
        # sequential: shard after shard through the (chunked) reader
        for f in files:
            for chunk in open_chunks(f):
                rec_cell.increase_by(len(chunk))
                yield from chunk
        return
    stats = StageStats(label)
    value, lo, hi, autotuned = _resolve(
        num_parallel_reads, 4, min(16, max(1, len(files))))
    knob = _Knob(stats, value, lo, hi)
    if autotuned:
        run.register_knob(knob)
    queues: dict = {}

    def start_reader(i: int):
        q = run.register_buffer(RingBuffer(8, stats))  # 8 chunks in flight
        queues[i] = q

        def work():
            with monitoring.traceme("data_read_shard", file=files[i]):
                try:
                    for chunk in open_chunks(files[i]):
                        if not q.put(chunk):
                            return
                    q.put(_DONE)
                except BaseException as e:  # noqa: BLE001
                    q.put(_Error(e))

        run.spawn(f"{label}_shard{i}", work)

    next_to_start = 0
    for i in range(len(files)):
        # strict shard order out; parallelism = reading ahead of the
        # consumption point, so the stream matches sequential exactly
        while (next_to_start < len(files)
               and next_to_start < i + max(1, knob.value)):
            start_reader(next_to_start)
            next_to_start += 1
        q = queues.pop(i)
        while True:
            item = q.get()
            if item is _DONE:
                break
            if isinstance(item, _Error):
                raise item.exc
            rec_cell.increase_by(len(item))
            stats.count(len(item))
            yield from item


class _InterleaveSlot:
    """One open inner dataset in the interleave cycle; parallel slots
    prefetch through a puller thread + ring, sequential slots iterate
    inline. Both expose the same next()/close() so the cycle algorithm
    (and therefore the emitted order) is identical."""

    def __init__(self, inner, run, stats, parallel, label, idx):
        self._it = iter(inner)
        self._ring = None
        if parallel and run is not None:
            ring = run.register_buffer(RingBuffer(8, stats))
            it = self._it

            def work():
                try:
                    for v in it:
                        if not ring.put(v):
                            return
                    ring.put(_DONE)
                except BaseException as e:  # noqa: BLE001
                    ring.put(_Error(e))

            run.spawn(f"{label}_slot{idx}", work)
            self._ring = ring

    def next(self):
        if self._ring is None:
            return next(self._it)
        item = self._ring.get()
        if item is _DONE:
            raise StopIteration
        if isinstance(item, _Error):
            raise item.exc
        return item

    def close(self):
        if self._ring is not None:
            self._ring.close()
        it = self._it
        self._it = None
        if hasattr(it, "close"):
            try:
                it.close()
            except Exception:
                pass


def _interleave_iter(run: Optional[PipelineRun], node: Node, up,
                     label: str):
    """Deterministic cycle interleave (both modes emit the SAME order):
    round-robin over up to cycle_length open inner datasets taking
    block_length elements per visit; an exhausted slot is removed and a
    fresh inner dataset (from the next input element) joins at the end
    of the cycle. num_parallel_calls only adds per-slot prefetch."""
    map_func, cycle_length, block_length, n = node.args
    stats = StageStats(label) if run is not None else None
    parallel_budget = 0
    knob = None
    if n is not None and run is not None:
        value, lo, hi, autotuned = _resolve(
            n, 2, min(int(cycle_length), pool_size()))
        knob = _Knob(stats, value, lo, hi)
        if autotuned:
            run.register_knob(knob)
        parallel_budget = value
    slots: List[_InterleaveSlot] = []
    upstream_live = True
    opened = [0]

    def refill():
        nonlocal upstream_live
        while upstream_live and len(slots) < cycle_length:
            try:
                x = next(up)
            except StopIteration:
                upstream_live = False
                return
            budget = knob.value if knob is not None else parallel_budget
            par = (n is not None and run is not None
                   and sum(1 for s in slots if s._ring is not None)
                   < budget)
            slots.append(_InterleaveSlot(map_func(x), run, stats, par,
                                         label, opened[0]))
            opened[0] += 1

    idx = 0
    try:
        refill()
        while slots:
            if idx >= len(slots):
                idx = 0
            slot = slots[idx]
            emitted = 0
            exhausted = False
            # no stall timing around slot.next(): a parallel slot's ring
            # already records its blocked-wait into these stats, and a
            # sequential slot's next() is inner-dataset COMPUTE, not
            # stall — timing it here would double-count the former and
            # feed the autotuner a phantom bottleneck for the latter
            while emitted < block_length:
                try:
                    v = slot.next()
                except StopIteration:
                    exhausted = True
                    break
                if stats is not None:
                    stats.count()
                emitted += 1
                yield v
            if exhausted:
                slot.close()
                del slots[idx]
                refill()
            else:
                idx += 1
    finally:
        for s in slots:
            s.close()


# ---------------------------------------------------------------------------
# compile + run
# ---------------------------------------------------------------------------

def build_iterator(node: Node, sequential: bool = False,
                   _count: bool = True):
    """Compile a Dataset chain into an iterator. ``sequential=True``
    forces the pre-engine nested-generator semantics (no threads, no
    metrics) — the reference stream for determinism tests and the
    fallback for externally-driven factories. ``_count=False`` keeps
    internal recompiles (repeat epochs, get_next spec probes) out of
    /stf/data/pipelines_started, which counts LOGICAL iterations."""
    chain = _chain(node)
    parallel = (not sequential) and any(_is_parallel(c) for c in chain)
    if _count:
        _pipelines_started.get_cell(
            "parallel" if parallel else "sequential").increase_by(1)
    run = PipelineRun() if parallel else None
    counts: dict = {}
    it = None
    for c in chain:
        label = f"{c.kind}:{counts.setdefault(c.kind, 0)}"
        counts[c.kind] += 1
        if c.kind == "source":
            it = _source_iter(c)
        elif c.kind == "zip":
            it = _zip_iter(c)
        elif c.kind == "tfrecord":
            it = _tfrecord_iter(run, c, label)
        elif c.kind == "seq":
            it = _seq_iter(c, it)
        elif c.kind == "repeat":
            it = _repeat_iter(run, c, it)
        elif c.kind == "batch":
            it = _batch_iter(c, it)
        elif c.kind == "pmap":
            if run is None or c.args[1] == 1:
                fn = c.args[0]
                it = map(fn, it)
            elif c.args[2]:  # deterministic (ordered)
                it = _pmap_ordered_iter(run, c, it, label)
            else:
                it = _pmap_unordered_iter(run, c, it, label)
        elif c.kind == "interleave":
            it = _interleave_iter(run, c, it, label)
        elif c.kind == "prefetch":
            if run is None:
                pass  # sequential build: prefetch is a no-op pass-through
            else:
                it = _prefetch_iter(run, c, it, label)
        else:
            raise ValueError(f"unknown pipeline stage kind {c.kind!r}")
    if run is None:
        return it
    return PipelineIterator(run, _root_gen(it))


def _root_gen(it):
    """Top-level generator so PipelineIterator.close() can unwind the
    whole fused stage stack with one gen.close()."""
    for x in it:
        yield x
