"""Host-side Dataset pipeline with device prefetch.

Replaces the reference's queue-based input pipeline
(ref: python/training/input.py, core/kernels/fifo_queue.cc). Each
transformation both (a) keeps a sequential generator composition — the
semantic ground truth — and (b) records a stage ``Node``; iteration
compiles the chain through ``stf.data.pipeline`` into a parallel stage
pipeline whenever any stage asks for parallelism (``num_parallel_reads``,
``map(num_parallel_calls=...)``, ``interleave``, ``prefetch``), else runs
the zero-thread sequential composition. Ordered parallel stages emit the
byte-identical element stream of the sequential chain (docs/DATA.md
determinism contract). ``prefetch_to_device`` double-buffers batches into
HBM on a background thread so the TPU step never waits on input.
Graph integration: ``iterator.get_next()`` returns host-source ops feeding
the compiled step, exactly where the reference's dequeue ops sat.
"""

from __future__ import annotations

from typing import Callable, Iterable, List, Optional

import numpy as np

from ..framework import dtypes as dtypes_mod
from ..framework import errors
from ..framework import graph as ops_mod
from ..framework import op_registry
from ..framework import tensor_shape as shape_mod
from . import pipeline as pipeline_mod
from .pipeline import AUTOTUNE, Node

__all__ = ["Dataset", "TFRecordDataset", "Iterator", "AUTOTUNE",
           "make_one_shot_iterator"]


def _check_parallel_arg(n, what):
    if n is None:
        return None
    n = int(n)
    if n == AUTOTUNE:
        return AUTOTUNE
    if n < 1:
        raise ValueError(
            f"{what} must be a positive int or stf.data.AUTOTUNE, got {n}")
    return n


class Dataset:
    """Composable host pipeline. Each instance carries a re-iterable
    sequential generator factory plus a stage-graph node; parallel
    stages execute through the stf.data.pipeline engine."""

    def __init__(self, gen_factory: Callable[[], Iterable],
                 element_spec=None, node: Optional[Node] = None):
        self._factory = gen_factory
        self._node = node if node is not None else Node(
            "source", None, (gen_factory,))
        self.element_spec = element_spec

    def _derive(self, node: Node) -> "Dataset":
        """New Dataset one stage downstream; the sequential factory is
        the forced-sequential compile of the same node chain."""
        return Dataset(
            lambda: pipeline_mod.build_iterator(node, sequential=True),
            node=node)

    # -- sources -------------------------------------------------------------
    @staticmethod
    def from_tensor_slices(tensors):
        if isinstance(tensors, dict):
            if not tensors:
                raise ValueError("from_tensor_slices: empty dict")
            arrays = {k: np.asarray(v) for k, v in tensors.items()}
            lengths = {k: a.shape[0] if a.ndim else None
                       for k, a in arrays.items()}
            if None in lengths.values() or len(set(lengths.values())) > 1:
                raise ValueError(
                    f"from_tensor_slices: incompatible leading dimensions "
                    f"{lengths}")
            n = next(iter(lengths.values()))

            def gen_dict():
                for i in range(n):
                    yield {k: a[i] for k, a in arrays.items()}

            return Dataset(gen_dict)
        if isinstance(tensors, (list, tuple)):
            arrays = tuple(np.asarray(t) for t in tensors)

            def gen():
                for i in range(arrays[0].shape[0]):
                    yield tuple(a[i] for a in arrays)

            return Dataset(gen)
        arr = np.asarray(tensors)

        def gen_single():
            for i in range(arr.shape[0]):
                yield arr[i]

        return Dataset(gen_single)

    @staticmethod
    def from_tensors(tensors):
        def gen():
            yield tensors

        return Dataset(gen)

    @staticmethod
    def from_generator(generator, output_types=None, output_shapes=None):
        return Dataset(lambda: generator())

    @staticmethod
    def range(*args):
        def gen():
            yield from (np.int64(i) for i in range(*args))

        return Dataset(gen)

    @staticmethod
    def zip(datasets):
        node = Node("zip", None, (tuple(datasets),))
        return Dataset(
            lambda: pipeline_mod.build_iterator(node, sequential=True),
            node=node)

    # -- transforms ----------------------------------------------------------
    def _seq(self, apply_fn: Callable) -> "Dataset":
        """Chain a sequential stage: ``apply_fn(upstream_iter)`` yields
        the transformed stream (fused inline by the pipeline engine)."""
        return self._derive(Node("seq", self._node, (apply_fn,)))

    def map(self, map_func, num_parallel_calls=None, deterministic=None):
        """Element-wise transform. ``num_parallel_calls`` > 1 (or
        AUTOTUNE) runs ``map_func`` on the shared stf.data worker pool;
        ``deterministic`` (default True) preserves the sequential
        element order exactly — ``deterministic=False`` emits results in
        completion order for extra throughput when order is irrelevant."""
        num_parallel_calls = _check_parallel_arg(
            num_parallel_calls, "map: num_parallel_calls")
        if deterministic is None:
            deterministic = True
        if num_parallel_calls is not None and num_parallel_calls != 1:
            return self._derive(Node(
                "pmap", self._node,
                (map_func, num_parallel_calls, bool(deterministic))))

        def apply(it):
            for x in it:
                yield map_func(x)

        return self._seq(apply)

    def interleave(self, map_func, cycle_length=2, block_length=1,
                   num_parallel_calls=None):
        """(ref: the reference's ParallelInterleaveDataset.) Maps each
        input element to a dataset and interleaves their elements:
        round-robin over ``cycle_length`` open inner datasets, taking
        ``block_length`` elements per visit; an exhausted inner dataset
        is removed and the next input element's dataset joins at the end
        of the cycle. ``num_parallel_calls`` prefetches that many inner
        datasets on worker threads WITHOUT changing the emitted order
        (the determinism contract in docs/DATA.md)."""
        cycle_length = int(cycle_length)
        block_length = int(block_length)
        if cycle_length < 1 or block_length < 1:
            raise ValueError(
                f"interleave: cycle_length/block_length must be >= 1, got "
                f"{cycle_length}/{block_length}")
        num_parallel_calls = _check_parallel_arg(
            num_parallel_calls, "interleave: num_parallel_calls")
        return self._derive(Node(
            "interleave", self._node,
            (map_func, cycle_length, block_length, num_parallel_calls)))

    def filter(self, predicate):
        def apply(it):
            for x in it:
                if predicate(x):
                    yield x

        return self._seq(apply)

    def batch(self, batch_size, drop_remainder=True):
        """drop_remainder defaults True: XLA needs static batch shapes."""
        return self._derive(Node(
            "batch", self._node,
            (int(batch_size), drop_remainder, _stack_batch)))

    def padded_batch(self, batch_size, padded_shapes=None,
                     padding_values=None, drop_remainder=True):
        """Batch variable-length elements, padding each component to a
        common shape (ref: the ``dynamic_pad=True`` mode of
        ``python/training/input.py batch`` — same contract, pipeline
        form).

        ``padded_shapes`` mirrors the element structure; dims that are
        None/-1 pad to the longest element IN THAT BATCH. On TPU prefer
        fully static ``padded_shapes``: every distinct batch shape is a
        separate XLA compile, so max-in-batch padding trades compile-
        cache hits for bytes. ``padding_values`` defaults to 0 (b"" for
        string components).
        """
        def stack(rows, alloc):
            return _pad_batch(rows, padded_shapes, padding_values)

        return self._derive(Node(
            "batch", self._node, (int(batch_size), drop_remainder, stack)))

    def parse_example(self, features, num_parallel_calls=None):
        """Parse serialized tf.Example elements into feature dicts
        (ref: the `parse_example` stage of the reference input pipeline,
        core/util/example_proto_fast_parsing.cc).

        Batch-aware: applied AFTER ``.batch(n)`` it parses the whole
        batch in one native C++ call (all-dense float32/int64 FixedLen
        specs, and all RaggedFeature specs — padded values plus a
        ``<name>_lengths`` vector, ~10x the per-record Python path);
        applied before batching it parses records one at a time. Prefer
        ``TFRecordDataset(...).batch(n).parse_example(spec)``.

        ``num_parallel_calls`` > 1 (or AUTOTUNE) runs the parse on the
        shared stf.data worker pool as a threaded pipeline stage
        (order-preserving, same contract as ``map``).
        """
        from ..ops import parsing_ops

        num_parallel_calls = _check_parallel_arg(
            num_parallel_calls, "parse_example: num_parallel_calls")

        def as_proto_bytes(s):
            # latin-1 is byte-preserving, so a str that carries proto
            # bytes round-trips; real pipelines carry bytes already
            return s.encode("latin1") if isinstance(s, str) else bytes(s)

        # RaggedFeature parses to static padded arrays, so it stacks
        # fine either side of .batch(); only the COO VarLen triple
        # needs batch-level parsing
        has_varlen = any(isinstance(s, parsing_ops.VarLenFeature)
                         for s in features.values())

        def parse_one(x):
            if isinstance(x, (bytes, np.bytes_, str, np.str_)):
                if has_varlen:
                    raise ValueError(
                        "Dataset.parse_example with VarLenFeature "
                        "needs batched elements (its output is a "
                        "batch-level COO triple): call "
                        ".batch(n).parse_example(spec), and do not "
                        "re-batch the parsed sparse values.")
                parsed = parsing_ops.parse_example_py(
                    [as_proto_bytes(x)], features)
                return {k: v[0] if not isinstance(v, tuple) else v
                        for k, v in parsed.items()}
            return parsing_ops.parse_example_py(
                [as_proto_bytes(s) for s in
                 np.ravel(np.asarray(x, dtype=object))],
                features)

        if num_parallel_calls is not None and num_parallel_calls != 1:
            return self._derive(Node(
                "pmap", self._node, (parse_one, num_parallel_calls, True)))

        def apply(it):
            for x in it:
                yield parse_one(x)

        return self._seq(apply)

    def unbatch(self):
        def apply(it):
            for x in it:
                if isinstance(x, dict):
                    arrays = {k: np.asarray(v) for k, v in x.items()}
                    n = next(iter(arrays.values())).shape[0]
                    for i in range(n):
                        yield {k: a[i] for k, a in arrays.items()}
                    continue
                arrs = x if isinstance(x, tuple) else (x,)
                for i in range(np.asarray(arrs[0]).shape[0]):
                    row = tuple(np.asarray(a)[i] for a in arrs)
                    yield row if isinstance(x, tuple) else row[0]

        return self._seq(apply)

    def shuffle(self, buffer_size, seed=None, reshuffle_each_iteration=True):
        rng_box = [np.random.RandomState(seed)]

        def apply(it):
            rng = rng_box[0] if not reshuffle_each_iteration else \
                np.random.RandomState(rng_box[0].randint(1 << 31))
            buf = []
            for x in it:
                buf.append(x)
                if len(buf) >= buffer_size:
                    i = rng.randint(len(buf))
                    buf[i], buf[-1] = buf[-1], buf[i]
                    yield buf.pop()
            rng.shuffle(buf)
            yield from buf

        return self._seq(apply)

    def repeat(self, count=None):
        return self._derive(Node("repeat", self._node, (count,)))

    def take(self, count):
        def apply(it):
            for i, x in enumerate(it):
                if i >= count:
                    return
                yield x

        return self._seq(apply)

    def skip(self, count):
        def apply(it):
            for i, x in enumerate(it):
                if i >= count:
                    yield x

        return self._seq(apply)

    def prefetch(self, buffer_size=2):
        """Decouple producer from consumer through a bounded ring buffer
        filled by a background stage thread. ``buffer_size=AUTOTUNE``
        lets the autotuner grow the ring (up to 16) while consumers
        stall. Source/worker errors propagate to the consuming thread
        at the position they occurred — never silent end-of-data."""
        if buffer_size != AUTOTUNE:
            buffer_size = int(buffer_size)
            if buffer_size < 1:
                raise ValueError(
                    f"prefetch: buffer_size must be >= 1 or AUTOTUNE, "
                    f"got {buffer_size}")
        return self._derive(Node("prefetch", self._node, (buffer_size,)))

    def superbatch(self, n, drop_remainder=True):
        """Stack ``n`` consecutive elements (typically batches) along a
        new leading axis — the N-step "superbatch" that
        ``Session.run_steps(stacked_feeds=...)`` consumes (docs/
        PERFORMANCE.md): one host->device transfer then feeds N fused
        training steps. Component structure (tuple/dict) is preserved;
        with ``drop_remainder`` (default, XLA needs static shapes) a
        trailing short window is dropped."""
        return self._derive(Node(
            "batch", self._node, (int(n), drop_remainder, _stack_batch)))

    def prefetch_to_device(self, buffer_size=2, sharding=None,
                           arena_staging=None, superbatch=None):
        """Prefetch + jax.device_put so batches are already in HBM (with the
        given NamedSharding on a mesh) when the step consumes them.

        superbatch: stack every N consecutive elements into one
        N-leading-dim superbatch BEFORE staging, so each device transfer
        carries the feeds of one fused ``Session.run_steps(n=N)`` window
        (the staging work lands in a ``superbatch_stage`` traceme span).

        arena_staging: assemble each host batch in 64-byte-aligned
        reusable C++ arena buffers — the pinned-staging pattern (ref
        core/common_runtime/gpu/gpu_host_allocator.h): aligned source
        buffers let the transfer engine DMA directly and the pool
        removes per-batch malloc churn. When the chain ends in a
        batch/superbatch stage, that stage STACKS DIRECTLY INTO the
        arena slot (no intermediate host copy between batch assembly
        and the device transfer); otherwise each element is staged with
        one copy. A slot recycles only after its device transfer
        completes (block_until_ready barrier). Default (None): on for
        TPU backends when the native runtime is built. Forced OFF on
        CPU backends regardless of the flag — CPU device_put zero-copy
        ALIASES aligned host buffers (measured), so recycled arena
        memory would corrupt live arrays."""
        base = self.superbatch(superbatch) if superbatch else self

        def gen():
            import jax

            from ..platform import monitoring
            from ..runtime import native

            cpu = jax.default_backend() == "cpu"
            use_arena = arena_staging
            if use_arena is None:
                use_arena = native.available() and not cpu
            elif use_arena and cpu:
                from ..platform import tf_logging as logging

                logging.warning(
                    "prefetch_to_device: arena_staging disabled on the CPU "
                    "backend (device_put aliases host buffers there)")
                use_arena = False
            # slots must exceed the max batches in flight between the
            # batch stage and the device transfer: assembly(1) + the
            # prefetch ring (AUTOTUNE grows it to the shared cap) +
            # consumer(1)
            ring_cap = (pipeline_mod.PREFETCH_AUTOTUNE_MAX
                        if buffer_size == AUTOTUNE else int(buffer_size))
            pool = (native.ArenaPool(slots=ring_cap + 3)
                    if use_arena and native.available() else None)
            # zero-copy handoff: the terminal batch/superbatch stage
            # assembles straight into an arena slot; elements arrive as
            # pipeline.ArenaBatch carrying the slot to recycle. The
            # node is CLONED so the user's dataset (possibly iterated
            # elsewhere without a device transfer) is never flagged.
            # Only stack fns that accept the allocator qualify —
            # padded_batch shares the "batch" node kind but pads into
            # its own buffers, so it takes the pool.stage() copy path.
            staged = base
            if (pool is not None and base._node.kind == "batch"
                    and getattr(base._node.args[2], "supports_alloc",
                                False)):
                clone = Node("batch", base._node.parent, base._node.args)
                clone.alloc_pool = pool
                staged = base._derive(clone)
            src = iter(staged.prefetch(buffer_size))
            import contextlib

            from ..telemetry import memory as _memory_mod

            ledger = _memory_mod.get_ledger()
            # HBM ledger (ISSUE 13): the staged batch in flight
            # accounts as class "staged_feed" — one rolling entry per
            # pipeline, updated to the latest staged batch's bytes
            # (released when the iterator closes); the arrays also
            # register as transients so reconcile() attributes them
            mem_token = ledger.register(
                "prefetch_to_device", 0, _memory_mod.CLASS_STAGED,
                "prefetch")
            try:
                for x in src:
                    slot = None
                    if isinstance(x, pipeline_mod.ArenaBatch):
                        x, slot = x.value, x.slot
                    # the superbatch_stage span marks multi-step staging
                    # only — a plain prefetch stays span-free so traces
                    # don't suggest superbatching that isn't happening
                    with (monitoring.traceme("superbatch_stage",
                                             n_steps=superbatch)
                          if superbatch else contextlib.nullcontext()):
                        if pool is not None and slot is None:
                            x = pool.stage(x)
                        if isinstance(x, tuple):
                            out = tuple(jax.device_put(a, sharding)
                                        for a in x)
                        else:
                            out = jax.device_put(x, sharding)
                        if pool is not None:
                            pool.mark_in_flight(out, slot=slot)
                    nbytes = (sum(getattr(a, "nbytes", 0) for a in out)
                              if isinstance(out, tuple)
                              else getattr(out, "nbytes", 0))
                    ledger.update(mem_token, nbytes)
                    ledger.track_transient(out)
                    yield out
            finally:
                ledger.release(mem_token)
                if hasattr(src, "close"):
                    src.close()

        return Dataset(gen)

    def cache(self):
        box: List = []

        def apply(it):
            if box:
                yield from box[0]
                return
            items = []
            for x in it:
                items.append(x)
                yield x
            box.append(items)

        return self._seq(apply)

    # -- consumption ---------------------------------------------------------
    def __iter__(self):
        if pipeline_mod.chain_is_parallel(self._node):
            return pipeline_mod.build_iterator(self._node)
        return iter(self._factory())

    def as_numpy_iterator(self):
        return iter(self)

    def make_one_shot_iterator(self):
        return Iterator(self)

    def make_initializable_iterator(self):
        return Iterator(self, initializable=True)


def _stack_one(vals, alloc=None):
    # bytes/str rows must stack as OBJECT arrays: numpy's fixed-width
    # 'S' dtype zero-pads and strips trailing NULs, which corrupts
    # serialized protos (a TFRecord batch is the common case here)
    if isinstance(vals[0], (bytes, str, np.bytes_, np.str_)):
        out = np.empty(len(vals), dtype=object)
        out[:] = vals
        return out
    arrs = [np.asarray(v) for v in vals]
    if alloc is not None and arrs[0].dtype.kind not in "OSUV":
        out = alloc((len(arrs),) + arrs[0].shape, arrs[0].dtype)
        return np.stack(arrs, out=out)
    return np.stack(arrs)


def _pad_one(vals, padded_shape, padding_value):
    """Stack a list of np arrays, padding every dim to a common target."""
    if isinstance(vals[0], (bytes, str, np.bytes_, np.str_)):
        return _stack_one(vals)  # strings batch as object arrays, no pad
    arrs = [np.asarray(v) for v in vals]
    rank = arrs[0].ndim
    if any(a.ndim != rank for a in arrs):
        raise ValueError(
            f"padded_batch: rank mismatch within batch: "
            f"{[a.shape for a in arrs]}")
    if rank == 0:
        return np.stack(arrs)
    maxdims = [max(a.shape[d] for a in arrs) for d in range(rank)]
    if padded_shape is not None:
        padded_shape = list(padded_shape)
        if len(padded_shape) != rank:
            raise ValueError(
                f"padded_shapes rank {len(padded_shape)} != element rank "
                f"{rank}")
        target = []
        for d, (want, got) in enumerate(zip(padded_shape, maxdims)):
            want = -1 if want is None else int(want)
            if want == -1:
                target.append(got)
            elif want < got:
                raise ValueError(
                    f"padded_batch: element dim {d} is {got}, larger than "
                    f"padded shape {want}")
            else:
                target.append(want)
    else:
        target = maxdims
    kind = arrs[0].dtype.kind
    if kind in ("O", "S", "U"):
        # string components pad with b""/"" as documented; build an
        # OBJECT array — numpy's fixed-width 'S'/'U' would truncate or
        # NUL-pad longer entries (same hazard as _stack_one)
        if padding_value is None:
            padding_value = "" if kind == "U" else b""
        out = np.empty([len(arrs)] + target, dtype=object)
        out[...] = padding_value
    else:
        pv = 0 if padding_value is None else padding_value
        out = np.full([len(arrs)] + target, pv, dtype=arrs[0].dtype)
    for i, a in enumerate(arrs):
        out[(i,) + tuple(slice(0, s) for s in a.shape)] = a
    return out


def _pad_batch(rows, padded_shapes, padding_values):
    """Pad+stack rows preserving tuple/dict element structure."""
    def comp(getter, shape, value):
        return _pad_one([getter(r) for r in rows], shape, value)

    if isinstance(rows[0], tuple):
        n = len(rows[0])
        shapes = padded_shapes if padded_shapes is not None else [None] * n
        values = padding_values if padding_values is not None else [None] * n
        return tuple(comp(lambda r, i=i: r[i], shapes[i], values[i])
                     for i in range(n))
    if isinstance(rows[0], dict):
        shapes = padded_shapes or {}
        values = padding_values or {}
        return {k: comp(lambda r, k=k: r[k], shapes.get(k),
                        values.get(k)) for k in rows[0]}
    return _pad_one(rows, padded_shapes, padding_values)


def _stack_batch(rows, alloc=None):
    if isinstance(rows[0], tuple):
        return tuple(_stack_one([r[i] for r in rows], alloc)
                     for i in range(len(rows[0])))
    if isinstance(rows[0], dict):
        return {k: _stack_one([r[k] for r in rows], alloc) for k in rows[0]}
    return _stack_one(rows, alloc)


# prefetch_to_device may hand this stack fn an arena allocator; stack fns
# without the flag (padded_batch's padding stack) get the element-wise
# pool.stage() copy instead of a wasted arena slot
_stack_batch.supports_alloc = True


class TFRecordDataset(Dataset):
    """(ref: reader ops core/kernels/record_yielder +
    python TFRecordDataset). Uses the native C++ record reader when
    built; ``num_parallel_reads`` (int or AUTOTUNE) fans the read out
    over file shards on reader threads that deliver record CHUNKS from
    the batched C++ call, emitted in strict shard order — the parallel
    stream is byte-identical to the sequential one."""

    def __init__(self, filenames, compression_type=None, buffer_size=None,
                 num_parallel_reads=None):
        if isinstance(filenames, (str, bytes)):
            filenames = [filenames]
        files = [f.decode() if isinstance(f, bytes) else str(f)
                 for f in filenames]
        comp = compression_type
        if isinstance(comp, bytes):
            comp = comp.decode()
        comp = (comp or "").upper()
        if comp not in ("", "GZIP"):
            # the seed silently ignored this arg and read garbage-
            # adjacent framing for compressed containers it can't parse
            raise errors.UnimplementedError(
                None, None,
                f"TFRecordDataset: compression_type={comp!r} is not "
                "supported (supported: None/'' and 'GZIP')")
        if buffer_size is not None:
            buffer_size = int(buffer_size)
            if buffer_size <= 0:
                raise ValueError(
                    f"TFRecordDataset: buffer_size must be > 0, got "
                    f"{buffer_size}")
        num_parallel_reads = _check_parallel_arg(
            num_parallel_reads, "TFRecordDataset: num_parallel_reads")
        if num_parallel_reads == 1:
            num_parallel_reads = None

        def open_chunks(path):
            from ..lib.io.tf_record import tf_record_chunks

            return tf_record_chunks(path, compression=comp,
                                    buffer_size=buffer_size)

        node = Node("tfrecord", None,
                    (files, open_chunks, num_parallel_reads))
        super().__init__(
            lambda: pipeline_mod.build_iterator(node, sequential=True),
            node=node)


_ITER_COUNT = [0]


def iterator_registry(graph=None):
    """The name -> Iterator map of ``graph``'s root graph (default: the
    default graph). Graph-scoped, NOT process-global: a graph owns the
    iterators its IteratorGetNext ops name, so dropping the graph
    (reset_default_graph) releases them — and with them the pipeline
    stage threads and ring buffers their streams pin. A process-global
    registry kept every iterator (one-shot iterators have no other
    reference) alive for the life of the process."""
    g = graph if graph is not None else ops_mod.get_default_graph()
    while getattr(g, "outer_graph", None) is not None:
        g = g.outer_graph
    return g._scoped_state.setdefault("__data_iterators__", {})


class Iterator:
    """Graph-facing iterator: get_next() returns host-source tensors that
    pull the next element during each Session.run (the reference's
    dequeue). Replacing the underlying stream (initializer / checkpoint
    restore) closes any parallel pipeline backing the old one."""

    def __init__(self, dataset: Dataset, initializable=False):
        self._dataset = dataset
        self._it = None if initializable else iter(dataset)
        _ITER_COUNT[0] += 1
        self._name = f"dataset_iterator_{_ITER_COUNT[0]}"
        iterator_registry()[self._name] = self
        self._peek = None
        self._spec = None
        self._keys = None
        self._structure = "single"
        self._position = 0  # elements yielded; checkpointed by Saver

    def close(self):
        """Release the underlying stream (and any pipeline stage
        threads/buffers backing it). The iterator stays restorable:
        initializer / restore_state builds a fresh stream."""
        self._replace_stream(None)

    def _replace_stream(self, new_it):
        old, self._it = self._it, new_it
        if old is not None and hasattr(old, "close"):
            old.close()

    def _next_value(self):
        if self._it is None:
            raise errors.FailedPreconditionError(
                None, None, "Iterator not initialized; run initializer")
        try:
            val = next(self._it)
            self._position += 1
            return val
        except StopIteration:
            raise errors.OutOfRangeError(None, None, "End of sequence")

    # -- checkpointable position (SURVEY §5 data-pipeline resume) ------------
    @property
    def name(self):
        return self._name

    def save_state(self):
        return {"position": self._position}

    def restore_state(self, state):
        """Re-create the underlying stream and skip forward to the saved
        position. Deterministic pipelines (the stf.data design: pure
        generator composition, seeded shuffles, ORDERED parallel stages)
        reproduce the exact element stream, so skip-forward == resume —
        including with parallel stages active (docs/DATA.md)."""
        pos = int(state.get("position", 0))
        self._replace_stream(iter(self._dataset))
        for _ in range(pos):
            try:
                next(self._it)
            except StopIteration:
                break
        self._position = pos

    @property
    def initializer(self):
        g = ops_mod.get_default_graph()
        return g.create_op("IteratorInit", [],
                           attrs={"iterator": self._name}, name="iter_init",
                           output_specs=[])

    def get_next(self, name=None):
        # Peek one element to type the outputs (shape/dtype spec). The
        # sequential compile is enough for a spec probe (same element
        # types either way) and spins up no stage threads; _count=False
        # keeps the probe out of /stf/data/pipelines_started.
        if self._spec is None:
            probe_it = pipeline_mod.build_iterator(
                self._dataset._node, sequential=True, _count=False)
            try:
                first = next(probe_it)
            finally:
                if hasattr(probe_it, "close"):
                    probe_it.close()
            if isinstance(first, dict):
                self._keys = sorted(first)
                items = [first[k] for k in self._keys]
                self._structure = "dict"
            elif isinstance(first, tuple):
                self._keys = None
                items = list(first)
                self._structure = "tuple"
            else:
                self._keys = None
                items = [first]
                self._structure = "single"
            self._spec = [(np.asarray(x).shape, np.asarray(x).dtype)
                          for x in items]
        g = ops_mod.get_default_graph()
        specs = [(shape_mod.TensorShape(list(sh)), dtypes_mod.as_dtype(dt))
                 for sh, dt in self._spec]
        op = g.create_op("IteratorGetNext", [],
                         attrs={"iterator": self._name},
                         name=name or "IteratorGetNext", output_specs=specs)
        outs = list(op.outputs)
        if self._structure == "dict":
            return dict(zip(self._keys, outs))
        if self._structure == "tuple":
            return tuple(outs)
        return outs[0]


def _lower_get_next(ctx, op, inputs):
    it = iterator_registry(op.graph)[op.attrs["iterator"]]
    val = it._next_value()
    if isinstance(val, dict):
        items = [val[k] for k in it._keys]
    elif isinstance(val, tuple):
        items = list(val)
    else:
        items = [val]
    return [np.asarray(x) for x in items]


def _lower_iter_init(ctx, op, inputs):
    it = iterator_registry(op.graph)[op.attrs["iterator"]]
    it._replace_stream(iter(it._dataset))
    return []


op_registry.register("IteratorGetNext", lower=_lower_get_next,
                     is_stateful=True, runs_on_host=True, n_outputs=None)
op_registry.register("IteratorInit", lower=_lower_iter_init,
                     is_stateful=True, runs_on_host=True, n_outputs=0)


def make_one_shot_iterator(dataset):
    return Iterator(dataset)
