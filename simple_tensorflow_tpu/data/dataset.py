"""Host-side Dataset pipeline with device prefetch.

Replaces the reference's queue-based input pipeline
(ref: python/training/input.py, core/kernels/fifo_queue.cc) with a
generator-composition design; ``prefetch_to_device`` double-buffers batches
into HBM on a background thread so the TPU step never waits on input.
Graph integration: ``iterator.get_next()`` returns host-source ops feeding
the compiled step, exactly where the reference's dequeue ops sat.
"""

from __future__ import annotations

import queue as py_queue
import threading
from typing import Callable, Iterable, List, Optional

import numpy as np

from ..framework import dtypes as dtypes_mod
from ..framework import errors
from ..framework import graph as ops_mod
from ..framework import op_registry
from ..framework import tensor_shape as shape_mod


class Dataset:
    """Composable host pipeline; each transformation wraps a generator
    factory (re-iterable)."""

    def __init__(self, gen_factory: Callable[[], Iterable], element_spec=None):
        self._factory = gen_factory
        self.element_spec = element_spec

    # -- sources -------------------------------------------------------------
    @staticmethod
    def from_tensor_slices(tensors):
        if isinstance(tensors, dict):
            if not tensors:
                raise ValueError("from_tensor_slices: empty dict")
            arrays = {k: np.asarray(v) for k, v in tensors.items()}
            lengths = {k: a.shape[0] if a.ndim else None
                       for k, a in arrays.items()}
            if None in lengths.values() or len(set(lengths.values())) > 1:
                raise ValueError(
                    f"from_tensor_slices: incompatible leading dimensions "
                    f"{lengths}")
            n = next(iter(lengths.values()))

            def gen_dict():
                for i in range(n):
                    yield {k: a[i] for k, a in arrays.items()}

            return Dataset(gen_dict)
        if isinstance(tensors, (list, tuple)):
            arrays = tuple(np.asarray(t) for t in tensors)

            def gen():
                for i in range(arrays[0].shape[0]):
                    yield tuple(a[i] for a in arrays)

            return Dataset(gen)
        arr = np.asarray(tensors)

        def gen_single():
            for i in range(arr.shape[0]):
                yield arr[i]

        return Dataset(gen_single)

    @staticmethod
    def from_tensors(tensors):
        def gen():
            yield tensors

        return Dataset(gen)

    @staticmethod
    def from_generator(generator, output_types=None, output_shapes=None):
        return Dataset(lambda: generator())

    @staticmethod
    def range(*args):
        def gen():
            yield from (np.int64(i) for i in range(*args))

        return Dataset(gen)

    @staticmethod
    def zip(datasets):
        def gen():
            its = [iter(d) for d in datasets]
            while True:
                try:
                    yield tuple(next(it) for it in its)
                except StopIteration:
                    return

        return Dataset(gen)

    # -- transforms ----------------------------------------------------------
    def map(self, map_func, num_parallel_calls=None):
        src = self._factory

        if num_parallel_calls and num_parallel_calls > 1:
            def gen():
                import concurrent.futures as cf

                with cf.ThreadPoolExecutor(num_parallel_calls) as ex:
                    it = iter(src())
                    pending = []
                    try:
                        for _ in range(num_parallel_calls * 2):
                            pending.append(ex.submit(map_func, next(it)))
                    except StopIteration:
                        it = None
                    while pending:
                        yield pending.pop(0).result()
                        if it is not None:
                            try:
                                pending.append(ex.submit(map_func, next(it)))
                            except StopIteration:
                                it = None

            return Dataset(gen)

        def gen_seq():
            for x in src():
                yield map_func(x)

        return Dataset(gen_seq)

    def filter(self, predicate):
        src = self._factory

        def gen():
            for x in src():
                if predicate(x):
                    yield x

        return Dataset(gen)

    def batch(self, batch_size, drop_remainder=True):
        """drop_remainder defaults True: XLA needs static batch shapes."""
        return Dataset(_batched(self._factory, batch_size, drop_remainder,
                                _stack_batch))

    def padded_batch(self, batch_size, padded_shapes=None,
                     padding_values=None, drop_remainder=True):
        """Batch variable-length elements, padding each component to a
        common shape (ref: the ``dynamic_pad=True`` mode of
        ``python/training/input.py batch`` — same contract, pipeline
        form).

        ``padded_shapes`` mirrors the element structure; dims that are
        None/-1 pad to the longest element IN THAT BATCH. On TPU prefer
        fully static ``padded_shapes``: every distinct batch shape is a
        separate XLA compile, so max-in-batch padding trades compile-
        cache hits for bytes. ``padding_values`` defaults to 0 (b"" for
        string components).
        """
        return Dataset(_batched(
            self._factory, batch_size, drop_remainder,
            lambda rows: _pad_batch(rows, padded_shapes, padding_values)))

    def parse_example(self, features):
        """Parse serialized tf.Example elements into feature dicts
        (ref: the `parse_example` stage of the reference input pipeline,
        core/util/example_proto_fast_parsing.cc).

        Batch-aware: applied AFTER ``.batch(n)`` it parses the whole
        batch in one native C++ call (all-dense float32/int64 specs,
        ~10x the per-record Python path); applied before batching it
        parses records one at a time. Prefer
        ``TFRecordDataset(...).batch(n).parse_example(spec)``.
        """
        from ..ops import parsing_ops

        src = self._factory

        def as_proto_bytes(s):
            # latin-1 is byte-preserving, so a str that carries proto
            # bytes round-trips; real pipelines carry bytes already
            return s.encode("latin1") if isinstance(s, str) else bytes(s)

        has_varlen = any(not isinstance(s, parsing_ops.FixedLenFeature)
                         for s in features.values())

        def gen():
            for x in src():
                if isinstance(x, (bytes, np.bytes_, str, np.str_)):
                    if has_varlen:
                        raise ValueError(
                            "Dataset.parse_example with VarLenFeature "
                            "needs batched elements (its output is a "
                            "batch-level COO triple): call "
                            ".batch(n).parse_example(spec), and do not "
                            "re-batch the parsed sparse values.")
                    parsed = parsing_ops.parse_example_py(
                        [as_proto_bytes(x)], features)
                    yield {k: v[0] if not isinstance(v, tuple) else v
                           for k, v in parsed.items()}
                else:
                    yield parsing_ops.parse_example_py(
                        [as_proto_bytes(s) for s in
                         np.ravel(np.asarray(x, dtype=object))],
                        features)

        return Dataset(gen)

    def unbatch(self):
        src = self._factory

        def gen():
            for x in src():
                if isinstance(x, dict):
                    arrays = {k: np.asarray(v) for k, v in x.items()}
                    n = next(iter(arrays.values())).shape[0]
                    for i in range(n):
                        yield {k: a[i] for k, a in arrays.items()}
                    continue
                arrs = x if isinstance(x, tuple) else (x,)
                for i in range(np.asarray(arrs[0]).shape[0]):
                    row = tuple(np.asarray(a)[i] for a in arrs)
                    yield row if isinstance(x, tuple) else row[0]

        return Dataset(gen)

    def shuffle(self, buffer_size, seed=None, reshuffle_each_iteration=True):
        src = self._factory
        rng_box = [np.random.RandomState(seed)]

        def gen():
            rng = rng_box[0] if not reshuffle_each_iteration else \
                np.random.RandomState(rng_box[0].randint(1 << 31))
            buf = []
            for x in src():
                buf.append(x)
                if len(buf) >= buffer_size:
                    i = rng.randint(len(buf))
                    buf[i], buf[-1] = buf[-1], buf[i]
                    yield buf.pop()
            rng.shuffle(buf)
            yield from buf

        return Dataset(gen)

    def repeat(self, count=None):
        src = self._factory

        def gen():
            n = 0
            while count is None or n < count:
                yield from src()
                n += 1

        return Dataset(gen)

    def take(self, count):
        src = self._factory

        def gen():
            for i, x in enumerate(src()):
                if i >= count:
                    return
                yield x

        return Dataset(gen)

    def skip(self, count):
        src = self._factory

        def gen():
            for i, x in enumerate(src()):
                if i >= count:
                    yield x

        return Dataset(gen)

    def prefetch(self, buffer_size=2):
        """Background-thread prefetch (the C++ runtime's prefetcher is used
        by prefetch_to_device)."""
        src = self._factory

        def gen():
            q: py_queue.Queue = py_queue.Queue(maxsize=buffer_size)
            DONE = object()

            def worker():
                try:
                    for x in src():
                        q.put(x)
                finally:
                    q.put(DONE)

            t = threading.Thread(target=worker, daemon=True)
            t.start()
            while True:
                x = q.get()
                if x is DONE:
                    return
                yield x

        return Dataset(gen)

    def superbatch(self, n, drop_remainder=True):
        """Stack ``n`` consecutive elements (typically batches) along a
        new leading axis — the N-step "superbatch" that
        ``Session.run_steps(stacked_feeds=...)`` consumes (docs/
        PERFORMANCE.md): one host->device transfer then feeds N fused
        training steps. Component structure (tuple/dict) is preserved;
        with ``drop_remainder`` (default, XLA needs static shapes) a
        trailing short window is dropped."""
        return Dataset(_batched(self._factory, n, drop_remainder,
                                _stack_batch))

    def prefetch_to_device(self, buffer_size=2, sharding=None,
                           arena_staging=None, superbatch=None):
        """Prefetch + jax.device_put so batches are already in HBM (with the
        given NamedSharding on a mesh) when the step consumes them.

        superbatch: stack every N consecutive elements into one
        N-leading-dim superbatch BEFORE staging, so each device transfer
        carries the feeds of one fused ``Session.run_steps(n=N)`` window
        (the staging work lands in a ``superbatch_stage`` traceme span).

        arena_staging: copy each host batch into 64-byte-aligned reusable
        C++ arena buffers before the device transfer — the pinned-staging
        pattern (ref core/common_runtime/gpu/gpu_host_allocator.h):
        aligned source buffers let the transfer engine DMA directly and
        the pool removes per-batch malloc churn. A slot recycles only
        after its device transfer completes (block_until_ready barrier).
        Default (None): on for TPU backends when the native runtime is
        built. Forced OFF on CPU backends regardless of the flag — CPU
        device_put zero-copy ALIASES aligned host buffers (measured), so
        recycled arena memory would corrupt live arrays."""
        base = self.superbatch(superbatch) if superbatch else self
        src = base.prefetch(buffer_size)._factory

        def gen():
            import jax

            from ..platform import monitoring
            from ..runtime import native

            cpu = jax.default_backend() == "cpu"
            use_arena = arena_staging
            if use_arena is None:
                use_arena = native.available() and not cpu
            elif use_arena and cpu:
                from ..platform import tf_logging as logging

                logging.warning(
                    "prefetch_to_device: arena_staging disabled on the CPU "
                    "backend (device_put aliases host buffers there)")
                use_arena = False
            pool = (native.ArenaPool(slots=buffer_size + 2)
                    if use_arena and native.available() else None)
            import contextlib

            for x in src():
                # the superbatch_stage span marks multi-step staging
                # only — a plain prefetch stays span-free so traces
                # don't suggest superbatching that isn't happening
                with (monitoring.traceme("superbatch_stage",
                                         n_steps=superbatch)
                      if superbatch else contextlib.nullcontext()):
                    if pool is not None:
                        x = pool.stage(x)
                    if isinstance(x, tuple):
                        out = tuple(jax.device_put(a, sharding) for a in x)
                    else:
                        out = jax.device_put(x, sharding)
                    if pool is not None:
                        pool.mark_in_flight(out)
                yield out

        return Dataset(gen)

    def cache(self):
        src = self._factory
        box: List = []

        def gen():
            if box:
                yield from box[0]
                return
            items = []
            for x in src():
                items.append(x)
                yield x
            box.append(items)

        return Dataset(gen)

    # -- consumption ---------------------------------------------------------
    def __iter__(self):
        return iter(self._factory())

    def as_numpy_iterator(self):
        return iter(self)

    def make_one_shot_iterator(self):
        return Iterator(self)

    def make_initializable_iterator(self):
        return Iterator(self, initializable=True)


def _stack_one(vals):
    # bytes/str rows must stack as OBJECT arrays: numpy's fixed-width
    # 'S' dtype zero-pads and strips trailing NULs, which corrupts
    # serialized protos (a TFRecord batch is the common case here)
    if isinstance(vals[0], (bytes, str, np.bytes_, np.str_)):
        out = np.empty(len(vals), dtype=object)
        out[:] = vals
        return out
    return np.stack([np.asarray(v) for v in vals])


def _batched(src, batch_size, drop_remainder, stack_fn):
    """Shared buffering loop behind batch()/padded_batch()."""
    def gen():
        buf = []
        for x in src():
            buf.append(x)
            if len(buf) == batch_size:
                yield stack_fn(buf)
                buf = []
        if buf and not drop_remainder:
            yield stack_fn(buf)

    return gen


def _pad_one(vals, padded_shape, padding_value):
    """Stack a list of np arrays, padding every dim to a common target."""
    if isinstance(vals[0], (bytes, str, np.bytes_, np.str_)):
        return _stack_one(vals)  # strings batch as object arrays, no pad
    arrs = [np.asarray(v) for v in vals]
    rank = arrs[0].ndim
    if any(a.ndim != rank for a in arrs):
        raise ValueError(
            f"padded_batch: rank mismatch within batch: "
            f"{[a.shape for a in arrs]}")
    if rank == 0:
        return np.stack(arrs)
    maxdims = [max(a.shape[d] for a in arrs) for d in range(rank)]
    if padded_shape is not None:
        padded_shape = list(padded_shape)
        if len(padded_shape) != rank:
            raise ValueError(
                f"padded_shapes rank {len(padded_shape)} != element rank "
                f"{rank}")
        target = []
        for d, (want, got) in enumerate(zip(padded_shape, maxdims)):
            want = -1 if want is None else int(want)
            if want == -1:
                target.append(got)
            elif want < got:
                raise ValueError(
                    f"padded_batch: element dim {d} is {got}, larger than "
                    f"padded shape {want}")
            else:
                target.append(want)
    else:
        target = maxdims
    kind = arrs[0].dtype.kind
    if kind in ("O", "S", "U"):
        # string components pad with b""/"" as documented; build an
        # OBJECT array — numpy's fixed-width 'S'/'U' would truncate or
        # NUL-pad longer entries (same hazard as _stack_one)
        if padding_value is None:
            padding_value = "" if kind == "U" else b""
        out = np.empty([len(arrs)] + target, dtype=object)
        out[...] = padding_value
    else:
        pv = 0 if padding_value is None else padding_value
        out = np.full([len(arrs)] + target, pv, dtype=arrs[0].dtype)
    for i, a in enumerate(arrs):
        out[(i,) + tuple(slice(0, s) for s in a.shape)] = a
    return out


def _pad_batch(rows, padded_shapes, padding_values):
    """Pad+stack rows preserving tuple/dict element structure."""
    def comp(getter, shape, value):
        return _pad_one([getter(r) for r in rows], shape, value)

    if isinstance(rows[0], tuple):
        n = len(rows[0])
        shapes = padded_shapes if padded_shapes is not None else [None] * n
        values = padding_values if padding_values is not None else [None] * n
        return tuple(comp(lambda r, i=i: r[i], shapes[i], values[i])
                     for i in range(n))
    if isinstance(rows[0], dict):
        shapes = padded_shapes or {}
        values = padding_values or {}
        return {k: comp(lambda r, k=k: r[k], shapes.get(k),
                        values.get(k)) for k in rows[0]}
    return _pad_one(rows, padded_shapes, padding_values)


def _stack_batch(rows):
    if isinstance(rows[0], tuple):
        return tuple(_stack_one([r[i] for r in rows])
                     for i in range(len(rows[0])))
    if isinstance(rows[0], dict):
        return {k: _stack_one([r[k] for r in rows]) for k in rows[0]}
    return _stack_one(rows)


class TFRecordDataset(Dataset):
    """(ref: reader ops core/kernels/record_yielder +
    python TFRecordDataset). Uses the native C++ record reader when built."""

    def __init__(self, filenames, compression_type=None, buffer_size=None,
                 num_parallel_reads=None):
        if isinstance(filenames, str):
            filenames = [filenames]
        files = list(filenames)

        def gen():
            from ..lib.io.tf_record import tf_record_iterator

            for f in files:
                yield from tf_record_iterator(f)

        super().__init__(gen)


_ITER_COUNT = [0]


class Iterator:
    """Graph-facing iterator: get_next() returns host-source tensors that
    pull the next element during each Session.run (the reference's dequeue)."""

    def __init__(self, dataset: Dataset, initializable=False):
        self._dataset = dataset
        self._it = None if initializable else iter(dataset)
        _ITER_COUNT[0] += 1
        self._name = f"dataset_iterator_{_ITER_COUNT[0]}"
        _ITERATORS[self._name] = self
        self._peek = None
        self._spec = None
        self._keys = None
        self._structure = "single"
        self._position = 0  # elements yielded; checkpointed by Saver

    def _next_value(self):
        if self._it is None:
            raise errors.FailedPreconditionError(
                None, None, "Iterator not initialized; run initializer")
        try:
            val = next(self._it)
            self._position += 1
            return val
        except StopIteration:
            raise errors.OutOfRangeError(None, None, "End of sequence")

    # -- checkpointable position (SURVEY §5 data-pipeline resume) ------------
    @property
    def name(self):
        return self._name

    def save_state(self):
        return {"position": self._position}

    def restore_state(self, state):
        """Re-create the underlying generator and skip forward to the saved
        position. Deterministic pipelines (the stf.data design: pure
        generator composition, seeded shuffles) reproduce the exact element
        stream, so skip-forward == resume."""
        pos = int(state.get("position", 0))
        self._it = iter(self._dataset)
        for _ in range(pos):
            try:
                next(self._it)
            except StopIteration:
                break
        self._position = pos

    @property
    def initializer(self):
        g = ops_mod.get_default_graph()
        return g.create_op("IteratorInit", [],
                           attrs={"iterator": self._name}, name="iter_init",
                           output_specs=[])

    def get_next(self, name=None):
        # Peek one element to type the outputs (shape/dtype spec).
        if self._spec is None:
            probe_it = iter(self._dataset)
            first = next(probe_it)
            if isinstance(first, dict):
                self._keys = sorted(first)
                items = [first[k] for k in self._keys]
                self._structure = "dict"
            elif isinstance(first, tuple):
                self._keys = None
                items = list(first)
                self._structure = "tuple"
            else:
                self._keys = None
                items = [first]
                self._structure = "single"
            self._spec = [(np.asarray(x).shape, np.asarray(x).dtype)
                          for x in items]
        g = ops_mod.get_default_graph()
        specs = [(shape_mod.TensorShape(list(sh)), dtypes_mod.as_dtype(dt))
                 for sh, dt in self._spec]
        op = g.create_op("IteratorGetNext", [],
                         attrs={"iterator": self._name},
                         name=name or "IteratorGetNext", output_specs=specs)
        outs = list(op.outputs)
        if self._structure == "dict":
            return dict(zip(self._keys, outs))
        if self._structure == "tuple":
            return tuple(outs)
        return outs[0]


_ITERATORS = {}


def _lower_get_next(ctx, op, inputs):
    it = _ITERATORS[op.attrs["iterator"]]
    val = it._next_value()
    if isinstance(val, dict):
        items = [val[k] for k in it._keys]
    elif isinstance(val, tuple):
        items = list(val)
    else:
        items = [val]
    return [np.asarray(x) for x in items]


def _lower_iter_init(ctx, op, inputs):
    it = _ITERATORS[op.attrs["iterator"]]
    it._it = iter(it._dataset)
    return []


op_registry.register("IteratorGetNext", lower=_lower_get_next,
                     is_stateful=True, runs_on_host=True, n_outputs=None)
op_registry.register("IteratorInit", lower=_lower_iter_init,
                     is_stateful=True, runs_on_host=True, n_outputs=0)


def make_one_shot_iterator(dataset):
    return Iterator(dataset)
