"""Loss functions (ref: tensorflow/python/ops/losses/losses_impl.py)."""

from __future__ import annotations

from ..framework import graph as ops_mod
from ..ops import array_ops, math_ops, nn_ops

GraphKeys = ops_mod.GraphKeys


class Reduction:
    """(ref: losses_impl.py:25 ``class Reduction``)."""

    NONE = "none"
    SUM = "weighted_sum"
    MEAN = "weighted_mean"
    SUM_BY_NONZERO_WEIGHTS = "weighted_sum_by_nonzero_weights"
    SUM_OVER_BATCH_SIZE = "weighted_sum_over_batch_size"
    SUM_OVER_NONZERO_WEIGHTS = SUM_BY_NONZERO_WEIGHTS
    DEFAULT = SUM_BY_NONZERO_WEIGHTS

    @classmethod
    def all(cls):
        return (cls.NONE, cls.SUM, cls.MEAN, cls.SUM_BY_NONZERO_WEIGHTS,
                cls.SUM_OVER_BATCH_SIZE)

    @classmethod
    def validate(cls, key):
        if key not in cls.all():
            raise ValueError(f"Invalid Reduction: {key}")


def compute_weighted_loss(losses, weights=1.0, scope=None,
                          loss_collection=GraphKeys.LOSSES,
                          reduction=Reduction.SUM_BY_NONZERO_WEIGHTS):
    """(ref: losses_impl.py:147)."""
    Reduction.validate(reduction)
    losses = ops_mod.convert_to_tensor(losses)
    losses_f = math_ops.cast(losses, "float32")
    weights_t = ops_mod.convert_to_tensor(weights, dtype="float32")
    weighted = losses_f * weights_t
    if reduction == Reduction.NONE:
        loss = weighted
    else:
        total = math_ops.reduce_sum(weighted)
        if reduction == Reduction.SUM:
            loss = total
        elif reduction == Reduction.MEAN:
            denom = math_ops.reduce_sum(
                weights_t * array_ops.ones_like(losses_f))
            loss = total / math_ops.maximum(
                denom, ops_mod.convert_to_tensor(1e-12))
        elif reduction == Reduction.SUM_BY_NONZERO_WEIGHTS:
            nz = math_ops.reduce_sum(math_ops.cast(
                math_ops.not_equal(weights_t * array_ops.ones_like(losses_f),
                                   ops_mod.convert_to_tensor(0.0)), "float32"))
            loss = total / math_ops.maximum(
                nz, ops_mod.convert_to_tensor(1.0))
        elif reduction == Reduction.SUM_OVER_BATCH_SIZE:
            n = array_ops.size(losses_f)
            loss = total / math_ops.cast(n, "float32")
    loss = math_ops.cast(loss, losses.dtype.base_dtype)
    if loss_collection:
        ops_mod.add_to_collection(loss_collection, loss)
    return loss


def absolute_difference(labels, predictions, weights=1.0, scope=None,
                        loss_collection=GraphKeys.LOSSES,
                        reduction=Reduction.SUM_BY_NONZERO_WEIGHTS):
    with ops_mod.name_scope(scope, "absolute_difference"):
        return compute_weighted_loss(
            math_ops.abs(math_ops.subtract(predictions, labels)), weights,
            scope, loss_collection, reduction)


def mean_squared_error(labels, predictions, weights=1.0, scope=None,
                       loss_collection=GraphKeys.LOSSES,
                       reduction=Reduction.SUM_BY_NONZERO_WEIGHTS):
    """(ref: losses_impl.py:627)."""
    with ops_mod.name_scope(scope, "mean_squared_error"):
        return compute_weighted_loss(
            math_ops.squared_difference(predictions, labels), weights, scope,
            loss_collection, reduction)


def log_loss(labels, predictions, weights=1.0, epsilon=1e-7, scope=None,
             loss_collection=GraphKeys.LOSSES,
             reduction=Reduction.SUM_BY_NONZERO_WEIGHTS):
    with ops_mod.name_scope(scope, "log_loss"):
        labels = ops_mod.convert_to_tensor(labels)
        predictions = ops_mod.convert_to_tensor(
            predictions, dtype=labels.dtype.base_dtype)
        eps = ops_mod.convert_to_tensor(epsilon,
                                        dtype=labels.dtype.base_dtype)
        losses = -labels * math_ops.log(predictions + eps) - \
            (1 - labels) * math_ops.log(1 - predictions + eps)
        return compute_weighted_loss(losses, weights, scope, loss_collection,
                                     reduction)


def hinge_loss(labels, logits, weights=1.0, scope=None,
               loss_collection=GraphKeys.LOSSES,
               reduction=Reduction.SUM_BY_NONZERO_WEIGHTS):
    with ops_mod.name_scope(scope, "hinge_loss"):
        labels = ops_mod.convert_to_tensor(labels)
        logits = ops_mod.convert_to_tensor(logits,
                                           dtype=labels.dtype.base_dtype)
        all_ones = array_ops.ones_like(labels)
        labels = 2 * labels - all_ones
        losses = nn_ops.relu(all_ones - labels * logits)
        return compute_weighted_loss(losses, weights, scope, loss_collection,
                                     reduction)


def huber_loss(labels, predictions, weights=1.0, delta=1.0, scope=None,
               loss_collection=GraphKeys.LOSSES,
               reduction=Reduction.SUM_BY_NONZERO_WEIGHTS):
    """(ref: losses_impl.py:394)."""
    with ops_mod.name_scope(scope, "huber_loss"):
        labels = ops_mod.convert_to_tensor(labels)
        predictions = ops_mod.convert_to_tensor(
            predictions, dtype=labels.dtype.base_dtype)
        error = math_ops.subtract(predictions, labels)
        abs_error = math_ops.abs(error)
        delta_t = ops_mod.convert_to_tensor(delta,
                                            dtype=labels.dtype.base_dtype)
        quadratic = math_ops.minimum(abs_error, delta_t)
        linear = abs_error - quadratic
        losses = 0.5 * quadratic * quadratic + delta_t * linear
        return compute_weighted_loss(losses, weights, scope, loss_collection,
                                     reduction)


def cosine_distance(labels, predictions, axis=None, weights=1.0, scope=None,
                    loss_collection=GraphKeys.LOSSES,
                    reduction=Reduction.SUM_BY_NONZERO_WEIGHTS, dim=None):
    if dim is not None and axis is None:
        axis = dim
    with ops_mod.name_scope(scope, "cosine_distance"):
        labels = ops_mod.convert_to_tensor(labels)
        predictions = ops_mod.convert_to_tensor(
            predictions, dtype=labels.dtype.base_dtype)
        radial_diffs = math_ops.multiply(predictions, labels)
        losses = 1 - math_ops.reduce_sum(radial_diffs, axis=axis,
                                         keepdims=True)
        return compute_weighted_loss(losses, weights, scope, loss_collection,
                                     reduction)


def mean_pairwise_squared_error(labels, predictions, weights=1.0, scope=None,
                                loss_collection=GraphKeys.LOSSES):
    with ops_mod.name_scope(scope, "mean_pairwise_squared_error"):
        labels = ops_mod.convert_to_tensor(labels)
        predictions = ops_mod.convert_to_tensor(
            predictions, dtype=labels.dtype.base_dtype)
        diffs = math_ops.subtract(predictions, labels)
        axes = list(range(1, len(diffs.shape)))
        sum_sq = math_ops.reduce_sum(math_ops.square(diffs), axis=axes)
        n = 1.0
        for a in axes:
            n *= diffs.shape[a].value
        sum_d = math_ops.reduce_sum(diffs, axis=axes)
        per_ex = 2.0 * (sum_sq / n - math_ops.square(sum_d / n))
        loss = math_ops.reduce_mean(per_ex) * ops_mod.convert_to_tensor(
            weights, dtype="float32")
        ops_mod.add_to_collection(loss_collection, loss)
        return loss


def sigmoid_cross_entropy(multi_class_labels, logits, weights=1.0,
                          label_smoothing=0, scope=None,
                          loss_collection=GraphKeys.LOSSES,
                          reduction=Reduction.SUM_BY_NONZERO_WEIGHTS):
    with ops_mod.name_scope(scope, "sigmoid_cross_entropy_loss"):
        logits = ops_mod.convert_to_tensor(logits)
        labels = ops_mod.convert_to_tensor(multi_class_labels,
                                           dtype=logits.dtype.base_dtype)
        if label_smoothing > 0:
            labels = labels * (1 - label_smoothing) + 0.5 * label_smoothing
        losses = nn_ops.sigmoid_cross_entropy_with_logits(labels=labels,
                                                          logits=logits)
        return compute_weighted_loss(losses, weights, scope, loss_collection,
                                     reduction)


def softmax_cross_entropy(onehot_labels, logits, weights=1.0,
                          label_smoothing=0, scope=None,
                          loss_collection=GraphKeys.LOSSES,
                          reduction=Reduction.SUM_BY_NONZERO_WEIGHTS):
    """(ref: losses_impl.py:707)."""
    with ops_mod.name_scope(scope, "softmax_cross_entropy_loss"):
        logits = ops_mod.convert_to_tensor(logits)
        labels = ops_mod.convert_to_tensor(onehot_labels,
                                           dtype=logits.dtype.base_dtype)
        if label_smoothing > 0:
            num_classes = labels.shape[-1].value
            labels = labels * (1 - label_smoothing) + \
                label_smoothing / num_classes
        losses = nn_ops.softmax_cross_entropy_with_logits(labels=labels,
                                                          logits=logits)
        return compute_weighted_loss(losses, weights, scope, loss_collection,
                                     reduction)


def sparse_softmax_cross_entropy(labels, logits, weights=1.0, scope=None,
                                 loss_collection=GraphKeys.LOSSES,
                                 reduction=Reduction.SUM_BY_NONZERO_WEIGHTS):
    with ops_mod.name_scope(scope, "sparse_softmax_cross_entropy_loss"):
        losses = nn_ops.sparse_softmax_cross_entropy_with_logits(
            labels=labels, logits=logits)
        return compute_weighted_loss(losses, weights, scope, loss_collection,
                                     reduction)


def add_loss(loss, loss_collection=GraphKeys.LOSSES):
    if loss_collection:
        ops_mod.add_to_collection(loss_collection, loss)


def get_losses(scope=None, loss_collection=GraphKeys.LOSSES):
    return ops_mod.get_collection(loss_collection, scope)


def get_regularization_losses(scope=None):
    return ops_mod.get_collection(GraphKeys.REGULARIZATION_LOSSES, scope)


def get_regularization_loss(scope=None, name="total_regularization_loss"):
    losses = get_regularization_losses(scope)
    if losses:
        return math_ops.add_n(losses, name=name)
    from ..ops import array_ops as ao

    return ao.zeros([], dtype="float32")


def get_total_loss(add_regularization_losses=True, name="total_loss"):
    losses = get_losses()
    if add_regularization_losses:
        losses += get_regularization_losses()
    if not losses:
        raise ValueError("No losses collected")
    return math_ops.add_n(losses, name=name)
