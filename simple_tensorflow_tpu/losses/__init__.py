"""stf.losses (ref: tensorflow/python/ops/losses/losses_impl.py)."""

from .losses_impl import (
    Reduction, absolute_difference, compute_weighted_loss, cosine_distance,
    hinge_loss, huber_loss, log_loss, mean_pairwise_squared_error,
    mean_squared_error, sigmoid_cross_entropy, softmax_cross_entropy,
    sparse_softmax_cross_entropy, add_loss, get_losses,
    get_regularization_loss, get_regularization_losses, get_total_loss,
)
