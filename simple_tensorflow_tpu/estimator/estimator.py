"""Estimator (ref: tensorflow/python/estimator/estimator.py).

The model_fn/input_fn/EstimatorSpec contract of the reference, running on
MonitoredTrainingSession; on a mesh the input batches shard over 'dp'
automatically (see stf.parallel).
"""

from __future__ import annotations

import collections
import os

import numpy as np

from ..framework import graph as ops_mod
from ..ops import variables as variables_mod
from ..platform import tf_logging as logging
from .. import train as train_mod


class ModeKeys:
    TRAIN = "train"
    EVAL = "eval"
    PREDICT = "infer"


class EstimatorSpec(
        collections.namedtuple(
            "EstimatorSpec",
            ["mode", "predictions", "loss", "train_op", "eval_metric_ops",
             "export_outputs", "training_chief_hooks", "training_hooks",
             "scaffold", "evaluation_hooks"])):
    """(ref: python/estimator/model_fn.py ``EstimatorSpec``)."""

    def __new__(cls, mode, predictions=None, loss=None, train_op=None,
                eval_metric_ops=None, export_outputs=None,
                training_chief_hooks=None, training_hooks=None, scaffold=None,
                evaluation_hooks=None):
        if mode == ModeKeys.TRAIN and train_op is None:
            raise ValueError("train mode needs train_op")
        if mode == ModeKeys.EVAL and loss is None:
            raise ValueError("eval mode needs loss")
        return super().__new__(cls, mode, predictions, loss, train_op,
                               eval_metric_ops or {}, export_outputs,
                               training_chief_hooks or [],
                               training_hooks or [], scaffold,
                               evaluation_hooks or [])


class RunConfig:
    """(ref: python/estimator/run_config.py)."""

    def __init__(self, model_dir=None, tf_random_seed=None,
                 save_summary_steps=100, save_checkpoints_steps=None,
                 save_checkpoints_secs=600, keep_checkpoint_max=5,
                 log_step_count_steps=100, session_config=None):
        self.model_dir = model_dir
        self.tf_random_seed = tf_random_seed
        self.save_summary_steps = save_summary_steps
        self.save_checkpoints_steps = save_checkpoints_steps
        self.save_checkpoints_secs = (save_checkpoints_secs
                                      if save_checkpoints_steps is None
                                      else None)
        self.keep_checkpoint_max = keep_checkpoint_max
        self.log_step_count_steps = log_step_count_steps
        self.session_config = session_config
        self.is_chief = True


class Estimator:
    """(ref: python/estimator/estimator.py:103 ``class Estimator``)."""

    def __init__(self, model_fn, model_dir=None, config=None, params=None,
                 warm_start_from=None):
        self._model_fn = model_fn
        self._config = config or RunConfig()
        self._model_dir = model_dir or self._config.model_dir or "/tmp/stf_model"
        self._params = params or {}

    @property
    def model_dir(self):
        return self._model_dir

    @property
    def config(self):
        return self._config

    @property
    def params(self):
        return dict(self._params)

    def _call_model_fn(self, features, labels, mode):
        import inspect

        kwargs = {}
        sig = inspect.signature(self._model_fn).parameters
        if "labels" in sig:
            kwargs["labels"] = labels
        if "mode" in sig:
            kwargs["mode"] = mode
        if "params" in sig:
            kwargs["params"] = self._params
        if "config" in sig:
            kwargs["config"] = self._config
        spec = self._model_fn(features=features, **kwargs)
        if not isinstance(spec, EstimatorSpec):
            raise ValueError("model_fn must return EstimatorSpec")
        return spec

    def train(self, input_fn, hooks=None, steps=None, max_steps=None,
              saving_listeners=None):
        """(ref: estimator.py:302 ``train``)."""
        g = ops_mod.Graph()
        with g.as_default():
            if self._config.tf_random_seed is not None:
                g.seed = self._config.tf_random_seed
            gs = train_mod.get_or_create_global_step(g)
            features, labels = _call_input_fn(input_fn)
            spec = self._call_model_fn(features, labels, ModeKeys.TRAIN)
            all_hooks = list(hooks or []) + list(spec.training_hooks)
            if steps is not None:
                all_hooks.append(train_mod.StopAtStepHook(num_steps=steps))
            elif max_steps is not None:
                all_hooks.append(train_mod.StopAtStepHook(last_step=max_steps))
            with train_mod.MonitoredTrainingSession(
                    is_chief=True, checkpoint_dir=self._model_dir,
                    scaffold=spec.scaffold, hooks=all_hooks,
                    save_checkpoint_secs=self._config.save_checkpoints_secs,
                    save_checkpoint_steps=self._config.save_checkpoints_steps,
                    save_summaries_steps=self._config.save_summary_steps,
                    log_step_count_steps=self._config.log_step_count_steps
            ) as sess:
                while not sess.should_stop():
                    sess.run(spec.train_op)
        return self

    def evaluate(self, input_fn, steps=None, hooks=None, checkpoint_path=None,
                 name=None):
        """(ref: estimator.py:386 ``evaluate``)."""
        g = ops_mod.Graph()
        with g.as_default():
            gs = train_mod.get_or_create_global_step(g)
            features, labels = _call_input_fn(input_fn)
            spec = self._call_model_fn(features, labels, ModeKeys.EVAL)
            ckpt = checkpoint_path or train_mod.latest_checkpoint(
                self._model_dir)
            update_ops = {k: v[1] for k, v in spec.eval_metric_ops.items()}
            value_ops = {k: v[0] for k, v in spec.eval_metric_ops.items()}
            value_ops["loss"] = spec.loss
            from ..train.evaluation import _evaluate_once

            eval_steps = steps or 1
            results_box = {}

            class _EvalHook(train_mod.SessionRunHook):
                def __init__(self):
                    self._n = 0

                def before_run(self, run_context):
                    return train_mod.SessionRunArgs(update_ops)

                def after_run(self, run_context, run_values):
                    self._n += 1
                    if self._n >= eval_steps:
                        run_context.request_stop()

            final = _evaluate_once(
                ckpt, scaffold=spec.scaffold,
                eval_ops=update_ops or spec.loss,
                final_ops=value_ops, hooks=list(hooks or []) + [_EvalHook()])
            out = {k: np.asarray(v) for k, v in (final or {}).items()}
            out["global_step"] = train_mod.global_step(
                _tmp_session(g), gs) if False else out.get("global_step", 0)
            return out

    def predict(self, input_fn, predict_keys=None, hooks=None,
                checkpoint_path=None, yield_single_examples=True):
        """(ref: estimator.py:463 ``predict``)."""
        g = ops_mod.Graph()
        with g.as_default():
            train_mod.get_or_create_global_step(g)
            features, _ = _call_input_fn(input_fn, expect_labels=False)
            spec = self._call_model_fn(features, None, ModeKeys.PREDICT)
            preds = spec.predictions
            ckpt = checkpoint_path or train_mod.latest_checkpoint(
                self._model_dir)
            from ..client.session import Session
            from ..framework import errors

            with Session(graph=g) as sess:
                sess.run(variables_mod.global_variables_initializer())
                if ckpt:
                    train_mod.Saver().restore(sess, ckpt)
                while True:
                    try:
                        batch = sess.run(preds)
                    except errors.OutOfRangeError:
                        return
                    if yield_single_examples:
                        if isinstance(batch, dict):
                            n = len(next(iter(batch.values())))
                            for i in range(n):
                                yield {k: v[i] for k, v in batch.items()}
                        else:
                            for row in batch:
                                yield row
                    else:
                        yield batch

    def export_savedmodel(self, export_dir_base, serving_input_receiver_fn,
                          assets_extra=None, as_text=False,
                          checkpoint_path=None, strip_default_attrs=False):
        """(ref: estimator.py:511 ``export_savedmodel``). Builds the PREDICT
        graph from ``serving_input_receiver_fn``, restores the latest (or
        given) checkpoint, and writes a timestamped SavedModel under
        ``export_dir_base``. Returns the export directory path."""
        import time

        from .. import saved_model as sm

        g = ops_mod.Graph()
        with g.as_default():
            train_mod.get_or_create_global_step(g)
            receiver = serving_input_receiver_fn()
            if isinstance(receiver, ServingInputReceiver):
                features = receiver.features
                receiver_tensors = receiver.receiver_tensors
            else:  # bare (features, receiver_tensors) pair
                features, receiver_tensors = receiver
            spec = self._call_model_fn(features, None, ModeKeys.PREDICT)
            outputs = spec.export_outputs or spec.predictions
            if outputs is None:
                raise ValueError(
                    "model_fn PREDICT mode returned neither export_outputs "
                    "nor predictions")
            if not isinstance(outputs, dict):
                outputs = {"output": outputs}
            ckpt = checkpoint_path or train_mod.latest_checkpoint(
                self._model_dir)
            if not ckpt:
                # exporting initializer values would persist a wrong model
                # (ref estimator raises "Couldn't find trained model")
                raise ValueError(
                    f"Couldn't find trained model at {self._model_dir} to "
                    "export (train first, or pass checkpoint_path)")
            from ..client.session import Session

            with Session(graph=g) as sess:
                sess.run(variables_mod.global_variables_initializer())
                train_mod.Saver().restore(sess, ckpt)
                export_dir = os.path.join(
                    export_dir_base, str(int(time.time())))
                while os.path.exists(export_dir):  # unique timestamped dir
                    export_dir += "_1"
                sm.simple_save(sess, export_dir, inputs=receiver_tensors,
                               outputs=outputs)
        return export_dir


class ServingInputReceiver(
        collections.namedtuple("ServingInputReceiver",
                               ["features", "receiver_tensors"])):
    """(ref: python/estimator/export/export.py ``ServingInputReceiver``).
    features: what the model_fn consumes; receiver_tensors: the fed
    placeholders of the serving signature (often the same tensors)."""


def build_raw_serving_input_receiver_fn(features):
    """(ref: export.py ``build_raw_serving_input_receiver_fn``): the
    features dict (of placeholders-to-be) IS the serving interface."""
    def serving_input_receiver_fn():
        from ..ops import array_ops

        receiver = {}
        for name, spec in features.items():
            if isinstance(spec, ops_mod.Tensor):
                # build a FRESH placeholder from the tensor's signature:
                # reusing the tensor itself would wire the export graph to
                # a producer in the caller's graph, which serializes into
                # a SavedModel referencing a node that doesn't exist in it
                receiver[name] = array_ops.placeholder(
                    spec.dtype.base_dtype, spec.shape.as_list()
                    if spec.shape.rank is not None else None, name=name)
            else:  # (shape, dtype) spec
                shape, dtype = spec
                receiver[name] = array_ops.placeholder(dtype, shape,
                                                       name=name)
        return ServingInputReceiver(dict(receiver), dict(receiver))

    return serving_input_receiver_fn


def _call_input_fn(input_fn, expect_labels=True):
    res = input_fn()
    if hasattr(res, "make_one_shot_iterator"):
        it = res.make_one_shot_iterator()
        res = it.get_next()
    if isinstance(res, tuple) and len(res) == 2:
        return res
    return res, None


def _tmp_session(g):
    from ..client.session import Session

    return Session(graph=g)


class inputs:
    """numpy_input_fn (ref: python/estimator/inputs/numpy_io.py)."""

    @staticmethod
    def numpy_input_fn(x, y=None, batch_size=128, num_epochs=1, shuffle=True,
                      queue_capacity=1000, num_threads=1):
        from ..data.dataset import Dataset

        def input_fn():
            if isinstance(x, dict):
                keys = sorted(x)
                arrays = tuple(np.asarray(x[k]) for k in keys)
                data = arrays + ((np.asarray(y),) if y is not None else ())
                ds = Dataset.from_tensor_slices(data)

                def pack(row):
                    feats = {k: row[i] for i, k in enumerate(keys)}
                    if y is not None:
                        return feats, row[-1]
                    return feats

                ds = ds.map(pack)
            else:
                data = (np.asarray(x), np.asarray(y)) if y is not None \
                    else np.asarray(x)
                ds = Dataset.from_tensor_slices(data)
            if shuffle:
                ds = ds.shuffle(queue_capacity)
            ds = ds.repeat(num_epochs).batch(batch_size)
            return ds

        return input_fn
