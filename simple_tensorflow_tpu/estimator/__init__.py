"""stf.estimator (ref: tensorflow/python/estimator)."""

from .estimator import (Estimator, EstimatorSpec, ModeKeys, RunConfig,
                        ServingInputReceiver,
                        build_raw_serving_input_receiver_fn, inputs)
