"""stf.estimator (ref: tensorflow/python/estimator)."""

from .estimator import (Estimator, EstimatorSpec, ModeKeys, RunConfig,
                        inputs)
