"""tfdbg-style interactive analyzer CLI (ref:
python/debug/cli/analyzer_cli.py ``DebugAnalyzer`` and the curses UI in
python/debug/cli/curses_ui.py).

TPU-native shape: dumps are plain host-side .npy files written by
``DumpingDebugWrapperSession`` (debug/wrappers.py) — there is nothing to
attach to on the device, so the CLI is a dependency-free line REPL over
``DebugDumpDir`` instead of a curses screen. Every command is also
available programmatically via ``AnalyzerCLI.run_command`` (that is what
the tests drive), and ``python -m simple_tensorflow_tpu.debug.cli
<dump_root>`` opens the interactive prompt.

Command set mirrors the reference analyzer:

  lt   [pattern] [-r RUN]     list dumped tensors
  pt   NAME [-r RUN] [-s SLICE]  print a tensor (optionally sliced)
  ni   NODE                   node info from the graph (needs --graph)
  li   NODE                   list inputs of a node
  lo   NODE                   list consumers of a node
  runs                        list run ids
  nan                         find tensors containing inf/nan
  help / exit
"""

from __future__ import annotations

import fnmatch
import shlex
import sys
from typing import List, Optional

import numpy as np

from .analyzer import DebugDumpDir


class CommandError(Exception):
    pass


class AnalyzerCLI:
    """Command interpreter over a dump dir (+ optional graph for node
    topology commands)."""

    def __init__(self, dump_dir, graph=None):
        # accept a path as well as a DebugDumpDir (the CLI main() and
        # programmatic users otherwise diverge on the entry type)
        self._dump = (dump_dir if isinstance(dump_dir, DebugDumpDir)
                      else DebugDumpDir(str(dump_dir)))
        self._graph = graph

    # -- helpers -------------------------------------------------------------
    def _pick_run(self, args) -> Optional[int]:
        if "-r" in args:
            i = args.index("-r")
            try:
                run = int(args[i + 1])
            except (IndexError, ValueError):
                raise CommandError("-r needs an integer run id")
            del args[i:i + 2]
            return run
        return None

    def _node(self, name):
        if self._graph is None:
            raise CommandError(
                "no graph attached; construct AnalyzerCLI(dump, graph=g) "
                "or pass --graph to the CLI")
        try:
            return self._graph.get_operation_by_name(name.split(":")[0])
        except (KeyError, ValueError):
            raise CommandError(f"node {name!r} not found in graph")

    # -- commands ------------------------------------------------------------
    def _op_of(self, tensor_name: str):
        """Graph op behind a dumped tensor name, when a graph is
        attached (best-effort: dumps outlive graphs)."""
        if self._graph is None:
            return None
        try:
            return self._graph.get_operation_by_name(
                tensor_name.split(":")[0])
        except (KeyError, ValueError):
            return None

    @staticmethod
    def _annotate(op) -> str:
        """`` <- OpType [effects] @ file:line`` suffix from the op's
        declared effect set and captured creation traceback
        (stf.analysis op-source attribution)."""
        from ..analysis import op_effects

        eff = op_effects(op).describe()
        out = f"  <- {op.type}"
        if eff != "pure":
            out += f" [{eff}]"
        if op.source_site:
            out += f" @ {op.source_site}"
        return out

    def cmd_lt(self, args: List[str]) -> str:
        run = self._pick_run(args)
        pattern = args[0] if args else "*"
        names = [n for n in self._dump.dumped_tensor_names(run)
                 if fnmatch.fnmatch(n, pattern)]
        if not names:
            return "(no dumped tensors match)"
        rows = []
        for n in sorted(names):
            data = self._dump.watch_key_to_data(n, run)
            d = data[-1]
            flag = " !nan/inf" if d.flagged_inf_or_nan else ""
            row = f"{n}  shape={d.shape} dtype={d.dtype}{flag}"
            op = self._op_of(n)
            if op is not None:
                row += self._annotate(op)
            rows.append(row)
        return "\n".join(rows)

    def cmd_pt(self, args: List[str]) -> str:
        run = self._pick_run(args)
        if not args:
            raise CommandError("pt needs a tensor name")
        name = args[0]
        sl = None
        if "-s" in args:
            i = args.index("-s")
            try:
                sl = args[i + 1]
            except IndexError:
                raise CommandError("-s needs a slice, e.g. [0:2,3]")
        data = self._dump.watch_key_to_data(name, run)
        if not data:
            raise CommandError(f"tensor {name!r} was not dumped")
        d = data[-1]
        v = d.get_tensor()
        if sl:
            try:
                v = eval("v" + sl, {"v": v})  # noqa: S307 — slice literal
            except Exception as e:
                raise CommandError(f"bad slice {sl!r}: {e}")
        stats = d.stats()
        head = (f"{name}  shape={d.shape} dtype={d.dtype} "
                f"min={stats['min']:.6g} max={stats['max']:.6g} "
                f"mean={stats['mean']:.6g} nan={stats['nan']} "
                f"inf={stats['inf']}")
        return head + "\n" + np.array2string(np.asarray(v), threshold=100)

    def cmd_ni(self, args: List[str]) -> str:
        if not args:
            raise CommandError("ni needs a node name")
        op = self._node(args[0])
        from ..analysis import op_effects

        lines = [f"node: {op.name}", f"  op: {op.type}",
                 f"  device: {op.device or '(device stage)'}",
                 f"  effects: {op_effects(op).describe()}"]
        if op.traceback:
            lines.append("  created at:")
            lines += [f"    {fn}:{ln} in {name}"
                      for fn, ln, name in op.traceback[:4]]
        if op.attrs:
            show = {k: v for k, v in list(op.attrs.items())[:8]}
            lines.append(f"  attrs: {show}")
        lines.append(f"  inputs ({len(op.inputs)}):")
        lines += [f"    {t.name} {t.dtype.name}{list(t.shape) if t.shape.rank is not None else ''}"
                  for t in op.inputs]
        outs = [f"    {t.name} {t.dtype.name}" for t in op.outputs]
        lines.append(f"  outputs ({len(op.outputs)}):")
        lines += outs
        if op.control_inputs:
            lines.append("  control inputs: "
                         + ", ".join(c.name for c in op.control_inputs))
        return "\n".join(lines)

    def cmd_li(self, args: List[str]) -> str:
        op = self._node(args[0] if args else "")
        return "\n".join(t.name for t in op.inputs) or "(no inputs)"

    def cmd_lo(self, args: List[str]) -> str:
        op = self._node(args[0] if args else "")
        consumers = []
        for t in op.outputs:
            consumers += [c.name for c in t.consumers()]
        return "\n".join(sorted(set(consumers))) or "(no consumers)"

    def cmd_runs(self, args: List[str]) -> str:
        return "\n".join(f"run_{r}" for r in self._dump.runs) \
            or "(no runs)"

    def cmd_nan(self, args: List[str]) -> str:
        bad = self._dump.find_inf_or_nan()
        if not bad:
            return "no inf/nan tensors found"
        return "\n".join(f"{d.tensor_name}  (dir {d.run_dir})"
                         for d in bad)

    def cmd_help(self, args: List[str]) -> str:
        return (
            "commands:\n"
            "  lt [pattern] [-r RUN]      list dumped tensors\n"
            "  pt NAME [-r RUN] [-s [i:j]]  print tensor (+stats)\n"
            "  ni NODE                    node info (graph required)\n"
            "  li NODE                    node inputs\n"
            "  lo NODE                    node consumers\n"
            "  runs                       list run ids\n"
            "  nan                        find inf/nan tensors\n"
            "  exit                       leave")

    # -- dispatch ------------------------------------------------------------
    def run_command(self, line: str) -> str:
        parts = shlex.split(line.strip())
        if not parts:
            return ""
        cmd, args = parts[0], parts[1:]
        aliases = {"list_tensors": "lt", "print_tensor": "pt",
                   "node_info": "ni", "list_inputs": "li",
                   "list_outputs": "lo", "find_inf_or_nan": "nan"}
        cmd = aliases.get(cmd, cmd)
        fn = getattr(self, f"cmd_{cmd}", None)
        if fn is None:
            raise CommandError(f"unknown command {cmd!r}; try 'help'")
        return fn(list(args))

    def interactive(self, stdin=None, stdout=None):
        stdin = stdin or sys.stdin
        stdout = stdout or sys.stdout
        stdout.write("stf debug analyzer — 'help' for commands\n")
        while True:
            stdout.write("tfdbg> ")
            stdout.flush()
            line = stdin.readline()
            if not line or line.strip() in ("exit", "quit"):
                return
            try:
                out = self.run_command(line)
            except CommandError as e:
                out = f"error: {e}"
            stdout.write(out + "\n")


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(prog="stf.debug.cli")
    p.add_argument("dump_root")
    p.add_argument("--graph", default=None,
                   help="optional GraphDef JSON (graph_io) for ni/li/lo")
    ns = p.parse_args(argv)
    graph = None
    if ns.graph:
        import json

        from ..framework import graph as graph_mod
        from ..framework import graph_io

        with open(ns.graph) as f:
            gd = json.load(f)
        graph = graph_mod.Graph()
        with graph.as_default():
            graph_io.import_graph_def(gd, name="")
    AnalyzerCLI(DebugDumpDir(ns.dump_root), graph=graph).interactive()


if __name__ == "__main__":
    main()
