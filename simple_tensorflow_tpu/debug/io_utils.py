"""Debug tensor sinks: publish watched tensors to URLs
(ref: tensorflow/core/debug/debug_io_utils.{h,cc},
debug_service.proto, debug_gateway.cc).

The reference streams watched tensors to ``file://`` and ``grpc://``
targets so a debugger in another process can observe a running training
job. TPU-native equivalent:

- ``file://<dir>`` — one subdirectory per run with .npy dumps and a
  manifest (same layout as DumpingDebugWrapperSession).
- ``tcp://host:port`` — the grpc:// role: a length-prefixed stream of
  (JSON header, npy payload) events over a socket to a live reader in
  another process. The reader side is :class:`DebugListener` (in-process
  thread) or ``python -m simple_tensorflow_tpu.debug.io_utils --listen``
  (subprocess / remote host).

Wire format, one event::

    uint32 header_len (little-endian) | header JSON (utf-8) | payload
    header = {"name", "run_index", "wall_time", "nbytes"}
    payload = numpy .npy bytes (self-describing dtype + shape)

A zero header_len is the end-of-stream marker.
"""

from __future__ import annotations

import io
import json
import os
import socket
import struct
import threading
import time
from typing import Any, Dict, List, Optional
from urllib.parse import urlparse

import numpy as np


class DebugSink:
    """Publish interface (ref: debug_io_utils.h ``DebugIO::PublishDebugTensor``)."""

    def publish(self, run_index: int, name: str, value) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class FileSink(DebugSink):
    def __init__(self, root: str):
        self._root = root
        os.makedirs(root, exist_ok=True)
        self._manifests: Dict[int, Dict[str, Any]] = {}

    def publish(self, run_index, name, value, **meta):
        run_dir = os.path.join(self._root, f"run_{run_index}")
        os.makedirs(run_dir, exist_ok=True)
        safe = name.replace("/", "_").replace(":", "_")
        arr = np.asarray(value)
        np.save(os.path.join(run_dir, safe + ".npy"), arr)
        man = self._manifests.setdefault(run_index, {})
        man[name] = {"file": safe + ".npy", **meta}
        with open(os.path.join(run_dir, "manifest.json"), "w") as f:
            json.dump({"time": time.time(), "tensors": man}, f, indent=1)


class SocketSink(DebugSink):
    """Streams events to a live reader (the grpc:// role; ref:
    debug_service.proto ``EventListener.SendEvents``)."""

    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout)

    def publish(self, run_index, name, value):
        arr = np.asarray(value)
        buf = io.BytesIO()
        np.save(buf, arr, allow_pickle=False)
        payload = buf.getvalue()
        header = json.dumps({
            "name": name, "run_index": int(run_index),
            "wall_time": time.time(), "nbytes": len(payload),
        }).encode()
        msg = struct.pack("<I", len(header)) + header + payload
        self._sock.sendall(msg)

    def close(self):
        try:
            self._sock.sendall(struct.pack("<I", 0))  # end-of-stream
            self._sock.close()
        except OSError:
            pass


def sink_for_url(url: str) -> DebugSink:
    """(ref: debug_io_utils.cc ``DebugIO::PublishDebugTensor`` URL
    dispatch — file:// and grpc:// there; file:// and tcp:// here)."""
    p = urlparse(url)
    if p.scheme == "file":
        return FileSink(p.path)
    if p.scheme in ("tcp", "grpc"):
        return SocketSink(p.hostname, int(p.port))
    raise ValueError(
        f"unsupported debug URL {url!r}: use file:///dir or tcp://host:port")


def publish_debug_tensor(sinks: List[DebugSink], run_index: int,
                         name: str, value) -> None:
    for s in sinks:
        s.publish(run_index, name, value)


# ---------------------------------------------------------------------------
# reader side
# ---------------------------------------------------------------------------

def _read_exact(conn, n):
    data = b""
    while len(data) < n:
        chunk = conn.recv(n - len(data))
        if not chunk:
            raise ConnectionError("debug stream truncated")
        data += chunk
    return data


def read_event_stream(conn):
    """Yield (header_dict, np.ndarray) until end-of-stream."""
    while True:
        raw = _read_exact(conn, 4)
        (hlen,) = struct.unpack("<I", raw)
        if hlen == 0:
            return
        header = json.loads(_read_exact(conn, hlen))
        payload = _read_exact(conn, header["nbytes"])
        arr = np.load(io.BytesIO(payload), allow_pickle=False)
        yield header, arr


class DebugListener:
    """In-process reader: accept one sender, collect events on a thread
    (ref: debug/grpc_debug_server.py ``EventListenerBaseServicer``)."""

    def __init__(self, host="127.0.0.1", port=0):
        self._server = socket.socket()
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((host, port))
        self._server.listen(1)
        self.port = self._server.getsockname()[1]
        self.events: List[Any] = []
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="stf_debug_dump_server")
        self._thread.start()

    def _serve(self):
        try:
            conn, _ = self._server.accept()
            for header, arr in read_event_stream(conn):
                self.events.append((header, arr))
            conn.close()
        except (OSError, ConnectionError):
            pass

    def wait(self, timeout=30.0):
        self._thread.join(timeout)

    def close(self):
        try:
            self._server.close()
        except OSError:
            pass


def _listen_main(port: int, out_dir: Optional[str]) -> None:
    """Subprocess reader CLI: write every received event to out_dir and a
    summary JSONL on stdout."""
    server = socket.socket()
    server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    server.bind(("127.0.0.1", port))
    server.listen(1)
    print(json.dumps({"listening": server.getsockname()[1]}), flush=True)
    conn, _ = server.accept()
    n = 0
    for header, arr in read_event_stream(conn):
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)
            safe = header["name"].replace("/", "_").replace(":", "_")
            np.save(os.path.join(
                out_dir, f"run{header['run_index']}_{safe}.npy"), arr)
        print(json.dumps({"name": header["name"],
                          "run_index": header["run_index"],
                          "shape": list(arr.shape),
                          "dtype": str(arr.dtype),
                          "mean": float(np.mean(arr))
                          if arr.dtype.kind in "fiu" and arr.size else None}),
              flush=True)
        n += 1
    print(json.dumps({"done": n}), flush=True)


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--listen", type=int, required=True,
                    help="port to listen on (0 = ephemeral, printed)")
    ap.add_argument("--out", default=None, help="dir for received .npy")
    args = ap.parse_args()
    _listen_main(args.listen, args.out)
