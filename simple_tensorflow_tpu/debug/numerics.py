"""stf.debug.numerics: the training numerics-health plane.

A NaN in step 40k of a fused window classically surfaces as a diverged
loss curve days later. This module makes it a first-class observable:
Session plans that look like training steps (device ops writing
variables) are auto-instrumented with device-side ``NumericSummary``
taps (ops/numerics.py) over gradients, optimizer updates, the loss, and
any activation matched by a name-pattern selector. Each tap reduces to
a packed ``[nonfinite_count, max_abs, l2_norm, zero_fraction]`` float32
vector INSIDE the compiled program; the packed health tensor is one
tiny extra device fetch that threads fused ``lax.scan`` windows
unchanged — fusion is never broken for health (the old
``numeric_check_op`` fusion blocker is retired by this plane).

Modes (ConfigProto(numerics=...) > ``STF_NUMERICS`` env > process
default via :func:`set_numerics_mode`):

- ``off``      — no instrumentation (default).
- ``metrics``  — per-step health feeds the ``/stf/train/*`` metric
  family and the ``/trainz`` telemetry endpoint (history ring +
  last-anomaly report).
- ``raise``    — metrics, plus a structured ``InvalidArgumentError``
  naming the first nonfinite tap, its producing op, and the op's
  user-code creation traceback. Detection is AFTER the step's state
  commit (that is what makes the plane near-free); recovery is
  checkpoint restore, which is bit-exact for deterministic plans.
- ``dump``     — raise, plus the one-shot **first-bad-op bisector**:
  the failing plan is re-executed eagerly (op-at-a-time, outside jit)
  from the retained pre-step state, the earliest op producing a
  nonfinite from all-finite inputs is localized exactly (for fused
  windows the offending step is replayed first), and its input/output
  tensors are written as a tfdbg-style dump directory
  (``run_0/<tensor>.npy`` + manifest) readable by
  ``debug/analyzer.py`` and ``tools/health_inspect.py`` — plus a
  flight-recorder ``numeric`` event carrying the health snapshot.

See docs/DEBUG.md for the dump format and CLI walkthrough.
"""

from __future__ import annotations

import collections
import json
import os
import re
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..framework import errors
from ..ops.numerics import STAT_NAMES, STATS_WIDTH
from ..platform import monitoring
from ..platform import sync as _sync

MODES = ("off", "metrics", "raise", "dump")

# Tap-count ceiling per plan: each tap is ~4 flops/element on device
# plus 16 fetched bytes — a transformer's every activation would be
# noise; truncation is LOGGED (no silent caps).
MAX_TAPS = 64

_process_mode: Optional[str] = None
_mode_lock = _sync.Lock("numerics/mode", rank=_sync.RANK_STATE)


def set_numerics_mode(mode: Optional[str]) -> Optional[str]:
    """Set the process-default numerics mode (None re-enables the
    ``STF_NUMERICS`` environment variable); returns the previous
    setting."""
    global _process_mode
    if mode is not None and mode not in MODES:
        raise ValueError(f"numerics mode must be one of {MODES} or None, "
                         f"got {mode!r}")
    with _mode_lock:
        prev = _process_mode
        _process_mode = mode
    return prev


def get_numerics_mode() -> str:
    """Resolved process-default mode: set_numerics_mode() if set, else
    ``STF_NUMERICS``, else "off"."""
    with _mode_lock:
        if _process_mode is not None:
            return _process_mode
    env = os.environ.get("STF_NUMERICS", "").strip().lower()
    return env if env in MODES else "off"


def resolve_mode(config) -> str:
    """The mode one Session runs under: ConfigProto(numerics=...) wins,
    else the process default."""
    m = getattr(config, "numerics", None) if config is not None else None
    return m if m in MODES else get_numerics_mode()


# ---------------------------------------------------------------------------
# /stf/train/* metric family (docs/OBSERVABILITY.md "Training health")
# ---------------------------------------------------------------------------

_metric_health_steps = monitoring.Counter(
    "/stf/train/health_steps",
    "training steps observed by the numerics-health plane (one count "
    "per step, fused or not)")
_metric_nonfinite = monitoring.Counter(
    "/stf/train/nonfinite_events",
    "tap observations containing NaN/Inf, by tap kind "
    "(gradient|update|loss|activation)", "kind")
_metric_grad_norm = monitoring.Sampler(
    "/stf/train/grad_norm",
    monitoring.ExponentialBuckets(1e-8, 10.0, 20),
    "global gradient L2 norm per observed step (sqrt of the sum of "
    "squared per-tap norms over gradient taps)")
_metric_update_ratio = monitoring.Sampler(
    "/stf/train/update_ratio",
    monitoring.ExponentialBuckets(1e-8, 10.0, 20),
    "global optimizer-update norm / global gradient norm per observed "
    "step (recorded only when both tap kinds exist)")


# ---------------------------------------------------------------------------
# the process-global health plane (/trainz's data source)
# ---------------------------------------------------------------------------

class HealthPlane:
    """Per-process training-health state: a bounded per-step history
    ring plus the last-anomaly report. One instance per process (like
    the flight recorder) — /trainz renders exactly this object."""

    HISTORY = 256

    def __init__(self):
        self._lock = _sync.Lock("numerics/health_plane",
                                rank=_sync.RANK_TELEMETRY)
        self._history = collections.deque(maxlen=self.HISTORY)
        self._steps = 0
        self._anomalies = 0
        self.last_anomaly: Optional[Dict[str, Any]] = None
        self.taps: List[Dict[str, Any]] = []

    def set_taps(self, tap_table: List[Dict[str, Any]]) -> None:
        with self._lock:
            self.taps = list(tap_table)

    def record_step(self, tap_table: Sequence[Dict[str, Any]],
                    stats: np.ndarray, step: int,
                    window_index: Optional[int] = None
                    ) -> Optional[Dict[str, Any]]:
        """Observe one step's packed health tensor (``[T, 4]``). Updates
        metrics + history; returns the anomaly record when any tap saw
        a nonfinite, else None."""
        stats = np.asarray(stats, dtype=np.float64).reshape(
            len(tap_table), STATS_WIDTH)
        grad_sq = upd_sq = 0.0
        has_grad = has_upd = False
        bad: List[Dict[str, Any]] = []
        for tap, row in zip(tap_table, stats):
            if tap["kind"] == "gradient":
                grad_sq += row[2] ** 2
                has_grad = True
            elif tap["kind"] == "update":
                upd_sq += row[2] ** 2
                has_upd = True
            if row[0] > 0:
                bad.append({**tap, "nonfinite_count": int(row[0]),
                            "max_abs": float(row[1]),
                            "l2_norm": float(row[2]),
                            "zero_fraction": float(row[3])})
        _metric_health_steps.get_cell().increase_by(1)
        grad_norm = float(np.sqrt(grad_sq)) if has_grad else None
        if grad_norm is not None:
            _metric_grad_norm.get_cell().add(grad_norm)
        upd_norm = float(np.sqrt(upd_sq)) if has_upd else None
        ratio = None
        if grad_norm is not None and upd_norm is not None:
            ratio = upd_norm / max(grad_norm, 1e-12)
            _metric_update_ratio.get_cell().add(ratio)
        entry = {
            "step": int(step), "time": time.time(),
            "nonfinite_taps": len(bad),
            "grad_norm": grad_norm, "update_norm": upd_norm,
            "update_ratio": ratio,
            "max_abs": float(np.max(stats[:, 1])) if stats.size else 0.0,
        }
        if window_index is not None:
            entry["window_index"] = int(window_index)
        anomaly = None
        if bad:
            for b in bad:
                _metric_nonfinite.get_cell(b["kind"]).increase_by(1)
            anomaly = {"step": int(step), "time": entry["time"],
                       "taps": bad}
            if window_index is not None:
                anomaly["window_index"] = int(window_index)
        with self._lock:
            self._steps += 1
            self._history.append(entry)
            if anomaly is not None:
                self._anomalies += 1
                self.last_anomaly = anomaly
        return anomaly

    def note_forensics(self, **fields) -> None:
        """Attach bisector results (first bad op, dump dir) to the
        last-anomaly report so /trainz shows where the dump went."""
        with self._lock:
            if self.last_anomaly is not None:
                self.last_anomaly.update(fields)

    def info(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "mode": get_numerics_mode(),
                "steps_observed": self._steps,
                "anomalies": self._anomalies,
                "taps": list(self.taps),
                "history": list(self._history),
                "last_anomaly": self.last_anomaly,
            }

    def reset(self) -> None:
        with self._lock:
            self._history.clear()
            self._steps = 0
            self._anomalies = 0
            self.last_anomaly = None
            self.taps = []


_plane: Optional[HealthPlane] = None
_plane_lock = _sync.Lock("numerics/plane_init",
                         rank=_sync.RANK_STATE)


def get_plane() -> HealthPlane:
    global _plane
    with _plane_lock:
        if _plane is None:
            _plane = HealthPlane()
        return _plane


def trainz_info() -> Dict[str, Any]:
    """The /trainz payload (telemetry/server.py)."""
    return get_plane().info()


# ---------------------------------------------------------------------------
# tap selection + plan instrumentation (the auto-instrumentation pass)
# ---------------------------------------------------------------------------

def _writes_variable(op) -> bool:
    from ..analysis.effects import op_effects

    return any(w.startswith("var_name=")
               for w in op_effects(op).writes)


def _float_tensor(t) -> bool:
    try:
        return t.dtype.is_floating
    except Exception:
        return False


def select_taps(pruned, fed_set, fetch_tensors, alias, const_env,
                patterns=()) -> List[Tuple[Any, str]]:
    """Choose the tensors the plane watches, as (tensor, kind) pairs in
    deterministic plan order. Kinds: gradient (SymbolicGradient
    outputs), update (float operands of variable-writing device ops),
    loss (scalar float fetches), activation (op-name regex matches —
    the match_partition_rules idiom)."""
    def rsv(t):
        return alias.get(t, t)

    taps: List[Tuple[Any, str]] = []
    seen = set()

    def add(t, kind):
        t = rsv(t)
        if (t in seen or t in fed_set or t in const_env
                or not _float_tensor(t) or t.op.op_def.runs_on_host
                or t.op.type in ("NumericSummary", "Const"))\
                or t.op.name.startswith("numerics_health"):
            return
        seen.add(t)
        taps.append((t, kind))

    compiled = [re.compile(p) for p in (patterns or ())]
    for op in pruned:
        if op.op_def.runs_on_host:
            continue
        if op.type == "SymbolicGradient":
            for o in op.outputs:
                add(o, "gradient")
        if _writes_variable(op):
            for t in op.inputs:
                if rsv(t).op.type != "VariableV2":
                    add(t, "update")
        if compiled and any(p.search(op.name) for p in compiled):
            for o in op.outputs:
                add(o, "activation")
    for t in fetch_tensors:
        r = rsv(t)
        if r.shape.rank == 0 and not r.op.op_def.runs_on_host:
            add(r, "loss")
    return taps


def instrument_plan(graph, pruned, fed_set, fetch_tensors, alias,
                    const_env, patterns=()):
    """The auto-instrumentation pass over one pruned plan. Returns
    ``(new_pruned, tap_table, health_tensor)`` — or
    ``(pruned, None, None)`` when the plan is not training-shaped (no
    device op writes a variable) or nothing is tappable. Created graph
    ops are cached in the graph's scoped state (the autoshard-
    constraints idiom) so re-planning reuses them."""
    if not any(not op.op_def.runs_on_host and _writes_variable(op)
               for op in pruned):
        return pruned, None, None
    taps = select_taps(pruned, fed_set, fetch_tensors, alias, const_env,
                       patterns)
    if not taps:
        return pruned, None, None
    if len(taps) > MAX_TAPS:
        from ..platform import tf_logging as logging

        logging.warning(
            "numerics: plan has %d tappable tensors; watching the first "
            "%d (raise debug.numerics.MAX_TAPS or narrow numerics_taps "
            "patterns to change the set)", len(taps), MAX_TAPS)
        taps = taps[:MAX_TAPS]

    from ..framework import dtypes as dtypes_mod
    from ..framework import tensor_shape as shape_mod

    reg = graph._scoped_state.setdefault("__numerics_taps__", {})
    summaries = []
    tap_table: List[Dict[str, Any]] = []
    for t, kind in taps:
        sop = reg.get(t)
        if sop is None:
            sop = graph.create_op(
                "NumericSummary", [t], attrs={},
                name=f"numerics_health/summary_{len(reg)}",
                output_specs=[(shape_mod.TensorShape([STATS_WIDTH]),
                               dtypes_mod.float32)])
            reg[t] = sop
        summaries.append(sop)
        tap_table.append({"name": t.name, "kind": kind,
                          "op": t.op.name, "op_type": t.op.type,
                          "site": t.op.source_site})
    pack_reg = graph._scoped_state.setdefault("__numerics_packs__", {})
    pack_key = tuple(t.name for t, _ in taps)
    pack = pack_reg.get(pack_key)
    if pack is None:
        pack = graph.create_op(
            "Pack", [s.outputs[0] for s in summaries], attrs={"axis": 0},
            name="numerics_health/pack",
            output_specs=[(shape_mod.TensorShape([len(taps),
                                                  STATS_WIDTH]),
                           dtypes_mod.float32)])
        pack_reg[pack_key] = pack
    in_plan = set(pruned)
    new_ops = [op for op in summaries if op not in in_plan]
    if pack not in in_plan:
        new_ops.append(pack)
    # appended at the END of the plan: every tap input is produced
    # earlier, so topo order holds, and env values are read by Tensor
    # key — later variable writes can never alias a tap's value
    return list(pruned) + new_ops, tap_table, pack.outputs[0]


# ---------------------------------------------------------------------------
# anomaly surfacing: structured raise, bisector, dump writer
# ---------------------------------------------------------------------------

def _format_site(tap: Dict[str, Any]) -> str:
    site = tap.get("site")
    return f" (created at {site})" if site else ""


def format_anomaly(anomaly: Dict[str, Any],
                   extra: str = "") -> str:
    lines = [f"numerics: nonfinite values detected at step "
             f"{anomaly['step']}"
             + (f" (fused window index {anomaly['window_index']})"
                if "window_index" in anomaly else "") + ":"]
    for b in anomaly["taps"][:8]:
        lines.append(
            f"  tap {b['name']} [{b['kind']}] from op {b['op']} "
            f"({b['op_type']}): {b['nonfinite_count']} nonfinite, "
            f"max_abs={b['max_abs']:.6g}{_format_site(b)}")
    if len(anomaly["taps"]) > 8:
        lines.append(f"  ... and {len(anomaly['taps']) - 8} more taps")
    lines.append("state through this step is committed; restore the "
                 "last checkpoint to recover")
    if extra:
        lines.append(extra)
    return "\n".join(lines)


def _to_float_np(v) -> Optional[np.ndarray]:
    if v is None:
        return None
    arr = np.asarray(v)
    if arr.dtype.kind == "f":
        return arr
    if "float" in str(arr.dtype):  # bfloat16 & friends (ml_dtypes)
        return arr.astype(np.float32)
    return None


def _all_finite(v) -> bool:
    arr = _to_float_np(v)
    if arr is None:
        return True
    return bool(np.all(np.isfinite(arr)))


def _eager_execute(session, step, feed_args, state, rng_key, run_idx):
    """Re-execute one step's device plan eagerly (op-at-a-time, outside
    jit) so every op's concrete outputs are observable in the env."""
    import jax
    import jax.numpy as jnp

    from ..framework import lowering as lowering_mod

    rng = (jax.random.fold_in(rng_key, np.uint32(run_idx))
           if rng_key is not None else None)
    ctx = lowering_mod.LoweringContext(dict(state), rng_root=rng,
                                       session=session)
    ctx.alias = step.alias
    ctx.func_plans = step.func_plans
    for t, v in step.const_env.items():
        if t.dtype.name != "string":
            ctx.env[t] = jnp.asarray(v)
    for t in step.feed_tensors:
        ctx.env[t] = feed_args[t.name]
    lowering_mod.execute_ops(ctx, step.device_ops,
                             fed=set(step.feed_tensors))
    return ctx


def first_bad_op(device_ops, ctx, feed_tensors=()):
    """Walk the eagerly-executed plan in topo order; the FIRST op whose
    float outputs contain a nonfinite while every float input is finite
    is where the poison entered. A nonfinite FEED short-circuits to the
    placeholder op (the poison arrived from outside the program).
    Returns (op, inputs, outputs) with (tensor, value) pairs, or
    (None, [], [])."""
    for t in feed_tensors:
        v = ctx.env.get(t)
        if not _all_finite(v):
            return t.op, [], [(t, v)]
    for op in device_ops:
        outs = [(o, ctx.env[o]) for o in op.outputs if o in ctx.env]
        if not outs or all(_all_finite(v) for _, v in outs):
            continue
        ins = []
        for t in op.inputs:
            t = ctx.alias.get(t, t) if ctx.alias else t
            ins.append((t, ctx.env.get(t)))
        if all(_all_finite(v) for _, v in ins):
            return op, ins, outs
    return None, [], []


def default_dump_root() -> str:
    root = os.environ.get("STF_NUMERICS_DUMP_ROOT")
    if root:
        os.makedirs(root, exist_ok=True)
        return tempfile.mkdtemp(prefix="numerics_", dir=root)
    return tempfile.mkdtemp(prefix="stf_numerics_")


def write_dump(dump_root, bad_op, ins, outs, anomaly,
               window_index=None) -> str:
    """Write the bisector's findings as a tfdbg-style dump dir
    (run_0/<tensor>.npy + manifest.json — the exact layout
    debug/analyzer.py DebugDumpDir reads) plus a bisect_report.json."""
    from .io_utils import FileSink

    sink = FileSink(dump_root)
    for t, v in list(ins) + list(outs):
        arr = _to_float_np(v)
        if arr is None:
            if v is None:
                continue
            arr = np.asarray(v)
            flagged = False
        else:
            flagged = not bool(np.all(np.isfinite(arr)))
        sink.publish(0, t.name, arr, has_inf_or_nan=flagged)
    report = {
        "first_bad_op": bad_op.name if bad_op is not None else None,
        "op_type": bad_op.type if bad_op is not None else None,
        "site": bad_op.source_site if bad_op is not None else None,
        "traceback": [list(f) for f in (bad_op.traceback or ())][:10]
        if bad_op is not None else [],
        "inputs": [t.name for t, _ in ins],
        "outputs": [t.name for t, _ in outs],
        "anomaly": anomaly,
    }
    if window_index is not None:
        report["window_index"] = int(window_index)
    with open(os.path.join(dump_root, "bisect_report.json"), "w") as f:
        json.dump(report, f, indent=1, default=str)
    return dump_root


def bisect_and_dump(session, step, feed_args, state, rng_key, run_idx,
                    anomaly) -> Tuple[Optional[Any], Optional[str]]:
    """dump-mode forensics for a plain (unfused) step: re-execute
    eagerly from the retained pre-step state, localize the first bad
    op, write the dump dir. Returns (bad_op, dump_root)."""
    ctx = _eager_execute(session, step, feed_args, state, rng_key,
                         run_idx)
    bad_op, ins, outs = first_bad_op(step.device_ops, ctx,
                                     step.feed_tensors)
    root = default_dump_root()
    write_dump(root, bad_op, ins, outs, anomaly)
    return bad_op, root


def bisect_window_and_dump(session, step, const_args, xs_args, pre_state,
                           rng_key, ctrs, bad_index, anomaly
                           ) -> Tuple[Optional[Any], Optional[str]]:
    """dump-mode forensics for a fused window: eagerly replay steps
    0..bad_index from the retained window-entry state (same fold_in
    counters, same per-step feed slices — bit-compatible with the scan
    body), then bisect the offending step."""
    state = dict(pre_state)
    ctx = None
    for i in range(int(bad_index) + 1):
        feed_args = {}
        for name, v in const_args.items():
            feed_args[name] = v
        for name, v in xs_args.items():
            feed_args[name] = v[i]
        ctx = _eager_execute(session, step, feed_args, state, rng_key,
                             int(ctrs[i]))
        if i < int(bad_index):
            state = dict(ctx.state)
    bad_op, ins, outs = first_bad_op(step.device_ops, ctx,
                                     step.feed_tensors)
    root = default_dump_root()
    write_dump(root, bad_op, ins, outs, anomaly,
               window_index=int(bad_index))
    return bad_op, root


def raise_anomaly(anomaly, bad_op=None, dump_root=None):
    extra = ""
    if bad_op is not None:
        site = f" (created at {bad_op.source_site})" \
            if bad_op.source_site else ""
        extra = (f"first bad op: {bad_op.name} ({bad_op.type}){site}")
    if dump_root:
        extra += f"\ndump written to {dump_root} — inspect with "\
                 f"`python -m simple_tensorflow_tpu.tools."\
                 f"health_inspect {dump_root}`"
    raise errors.InvalidArgumentError(
        None, None, format_anomaly(anomaly, extra))
