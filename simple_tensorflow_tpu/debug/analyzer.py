"""Offline analyzer for DumpingDebugWrapperSession dump directories
(ref: tensorflow/python/debug/lib/debug_data.py ``DebugDumpDir``,
python/debug/cli/analyzer_cli.py — the analysis layer over tfdbg dumps).

The reference's tfdbg pairs a dump format with an interactive CLI; here
the dump directory (run_<n>/<tensor>.npy + manifest.json) is analyzed by
:class:`DebugDumpDir` (list/query/filter tensors across runs) plus a
non-interactive CLI: ``python -m simple_tensorflow_tpu.debug.analyzer
--dump_root d [--run N] [--tensor t] [--filter has_inf_or_nan]``.
"""

from __future__ import annotations

import argparse
import fnmatch
import json
import os
import sys
from typing import Callable, Dict, List, Optional

import numpy as np

from .wrappers import has_inf_or_nan


class DebugTensorDatum:
    """One dumped tensor (ref: debug_data.py ``DebugTensorDatum``)."""

    def __init__(self, run_dir: str, tensor_name: str, meta: dict):
        self.tensor_name = tensor_name
        self.run_dir = run_dir
        self._file = meta["file"]
        self.flagged_inf_or_nan = bool(meta.get("has_inf_or_nan"))
        self._value = None

    def load_tensor(self) -> np.ndarray:
        """Read the dump from disk WITHOUT caching (predicate sweeps over
        big dump roots must not pin everything in memory)."""
        return np.load(os.path.join(self.run_dir, self._file),
                       allow_pickle=False)

    def get_tensor(self) -> np.ndarray:
        if self._value is None:
            self._value = self.load_tensor()
        return self._value

    @property
    def shape(self):
        return tuple(self.get_tensor().shape)

    @property
    def dtype(self):
        return self.get_tensor().dtype

    def stats(self) -> Dict[str, float]:
        v = np.asarray(self.get_tensor(), np.float64)
        finite = v[np.isfinite(v)] if v.size else v
        return {
            "size": int(v.size),
            "nan": int(np.isnan(v).sum()),
            "inf": int(np.isinf(v).sum()),
            "min": float(finite.min()) if finite.size else float("nan"),
            "max": float(finite.max()) if finite.size else float("nan"),
            "mean": float(finite.mean()) if finite.size else float("nan"),
        }


class DebugDumpDir:
    """All runs under one dump root (ref: debug_data.py:510
    ``DebugDumpDir``)."""

    def __init__(self, dump_root: str):
        if not os.path.isdir(dump_root):
            raise ValueError(f"dump root {dump_root!r} does not exist")
        self.dump_root = dump_root
        self._runs: Dict[int, Dict[str, DebugTensorDatum]] = {}
        for entry in sorted(os.listdir(dump_root)):
            if not entry.startswith("run_"):
                continue
            run_dir = os.path.join(dump_root, entry)
            manifest = os.path.join(run_dir, "manifest.json")
            if not os.path.isfile(manifest):
                continue
            with open(manifest) as f:
                doc = json.load(f)
            try:
                n = int(entry.split("_", 1)[1])
            except ValueError:
                continue  # stray dir (run_backup, run_1_old): not a run
            self._runs[n] = {
                name: DebugTensorDatum(run_dir, name, meta)
                for name, meta in doc.get("tensors", {}).items()}

    @property
    def runs(self) -> List[int]:
        return sorted(self._runs)

    @property
    def size(self) -> int:
        return sum(len(t) for t in self._runs.values())

    def dumped_tensor_names(self, run: Optional[int] = None) -> List[str]:
        if run is not None:
            return sorted(self._runs.get(run, {}))
        names = set()
        for t in self._runs.values():
            names.update(t)
        return sorted(names)

    def watch_key_to_data(self, tensor_name: str,
                          run: Optional[int] = None
                          ) -> List[DebugTensorDatum]:
        """All dumps of one tensor (ordered by run)."""
        runs = [run] if run is not None else self.runs
        return [self._runs[r][tensor_name] for r in runs
                if tensor_name in self._runs.get(r, {})]

    def get_tensor(self, tensor_name: str, run: int) -> np.ndarray:
        return self._runs[run][tensor_name].get_tensor()

    def find(self, predicate: Callable[[str, np.ndarray], bool],
             first_n: int = 0,
             run: Optional[int] = None) -> List[DebugTensorDatum]:
        """Data matching ``predicate(name, value)`` (ref: debug_data.py
        ``DebugDumpDir.find`` — the tensor-filter hook the CLI's
        ``lt -f has_inf_or_nan`` uses). Tensors are loaded WITHOUT the
        per-datum cache — a predicate sweep over a multi-GB dump root
        must not pin the whole set in memory."""
        out = []
        runs = self._select_runs(run)
        for r in runs:
            for name, datum in sorted(self._runs[r].items()):
                if predicate(name, datum.load_tensor()):
                    out.append(datum)
                    if first_n and len(out) >= first_n:
                        return out
        return out

    def find_inf_or_nan(self, first_n: int = 0,
                        run: Optional[int] = None
                        ) -> List[DebugTensorDatum]:
        """Uses the per-tensor flag precomputed in the dump manifests —
        no tensor files are read (a dump root can hold GBs)."""
        out = []
        for r in self._select_runs(run):
            for _, datum in sorted(self._runs[r].items()):
                if datum.flagged_inf_or_nan:
                    out.append(datum)
                    if first_n and len(out) >= first_n:
                        return out
        return out

    def _select_runs(self, run: Optional[int]) -> List[int]:
        if run is None:
            return self.runs
        if run not in self._runs:
            raise ValueError(f"run {run} not in dump root "
                             f"(have {self.runs})")
        return [run]

    def query(self, pattern: str) -> List[str]:
        """Glob over dumped tensor names."""
        return [n for n in self.dumped_tensor_names()
                if fnmatch.fnmatch(n, pattern)]


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dump_root", required=True)
    ap.add_argument("--run", type=int, default=None)
    ap.add_argument("--tensor", default=None,
                    help="print stats/values for one tensor")
    ap.add_argument("--filter", default=None, choices=["has_inf_or_nan"],
                    help="list only tensors matching the filter")
    ap.add_argument("--print_values", action="store_true")
    args = ap.parse_args()

    dd = DebugDumpDir(args.dump_root)
    out = sys.stdout
    if args.run is not None and args.run not in dd.runs:
        print(f"error: run {args.run} not in dump root "
              f"(have {dd.runs})", file=sys.stderr)
        sys.exit(2)
    if args.tensor:
        for datum in dd.watch_key_to_data(args.tensor, run=args.run):
            print(f"{datum.tensor_name} [{datum.run_dir}] "
                  f"dtype={datum.dtype} shape={list(datum.shape)} "
                  f"{datum.stats()}", file=out)
            if args.print_values:
                print(datum.get_tensor(), file=out)
        return
    if args.filter == "has_inf_or_nan":
        hits = dd.find_inf_or_nan(run=args.run)
        for d in hits:
            print(f"{d.tensor_name} [{d.run_dir}] {d.stats()}", file=out)
        print(f"# {len(hits)} tensors with inf/nan", file=out)
        return
    for run in ([args.run] if args.run is not None else dd.runs):
        for name in dd.dumped_tensor_names(run):
            print(f"run_{run}  {name}", file=out)
    print(f"# {dd.size} dumps in {len(dd.runs)} runs", file=out)


if __name__ == "__main__":
    main()
