"""stf.debug: tfdbg equivalent (ref: tensorflow/python/debug).

The reference wraps Session to intercept per-node tensors. Here the unit of
execution is one XLA program, so debugging hooks differently:
- DumpingDebugWrapperSession: fetches every *graph-visible* tensor of the
  pruned step (op outputs) by adding them as extra fetches and dumps npy
  files per run — the analog of tfdbg's dump mode.
- add_check_numerics_ops / enable_check_numerics: jax debug_nans-style
  host-callback checks on every floating tensor.
- watch list: name-filtered subsets.
- DebugDumpDir (debug/analyzer.py): offline analysis of dump dirs —
  list/query/filter (has_inf_or_nan) across runs, with a CLI
  (`python -m simple_tensorflow_tpu.debug.analyzer`) — the analog of
  tfdbg's analyzer/CLI layer (ref python/debug/lib + cli).
- numerics (debug/numerics.py): the training numerics-health plane —
  device-side NumericSummary taps, /stf/train/* metrics + /trainz,
  first-bad-op bisector + tfdbg-style dumps (docs/DEBUG.md).
"""

from .analyzer import DebugDumpDir, DebugTensorDatum
from .cli import AnalyzerCLI
from .io_utils import (DebugListener, DebugSink, FileSink, SocketSink,
                       publish_debug_tensor, sink_for_url)
from .numerics import (HealthPlane, get_numerics_mode, get_plane,
                       set_numerics_mode, trainz_info)
from .wrappers import (DumpingDebugWrapperSession, LocalCLIDebugWrapperSession,
                       TensorWatch, add_check_numerics_ops,
                       has_inf_or_nan)
