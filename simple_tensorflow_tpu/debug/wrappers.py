"""Debug session wrappers (ref: tensorflow/python/debug/wrappers/framework.py,
dumping_wrapper.py)."""

from __future__ import annotations

import fnmatch
import json
import os
import time

import numpy as np

from ..framework import graph as ops_mod
from ..framework import lowering as lowering_mod
from ..platform import tf_logging as logging


def has_inf_or_nan(datum_name, value):
    """(ref: python/debug/lib/debug_data.py ``has_inf_or_nan``)."""
    arr = np.asarray(value)
    if arr.dtype.kind not in "fc":
        return False
    return bool(np.isnan(arr).any() or np.isinf(arr).any())


class TensorWatch:
    def __init__(self, pattern="*"):
        self.pattern = pattern

    def match(self, name):
        return fnmatch.fnmatch(name, self.pattern)


class _WrapperBase:
    def __init__(self, sess):
        self._sess = sess

    @property
    def graph(self):
        return self._sess.graph

    def __getattr__(self, item):
        return getattr(self._sess, item)

    def _watched_tensors(self, fetches, feed_dict, watches):
        g = self._sess.graph
        mapper_elements = []
        from ..client.session import _FetchMapper

        m = _FetchMapper(g, fetches)
        targets = [e for e in m.elements]
        target_ops = [e if isinstance(e, ops_mod.Operation) else e.op
                      for e in targets]
        fed = set()
        if feed_dict:
            for k in feed_dict:
                fed.add(g.as_graph_element(k, True, False))
        pruned = lowering_mod.prune(target_ops, fed)
        out = []
        for op in pruned:
            if op.op_def.runs_on_host:
                continue
            for t in op.outputs:
                if t.dtype.name == "string":
                    continue
                if any(w.match(t.name) for w in watches):
                    out.append(t)
        return out


class DumpingDebugWrapperSession(_WrapperBase):
    """(ref: python/debug/wrappers/dumping_wrapper.py). Dumps every watched
    tensor of every run to <dump_root>/run_<n>/<tensor>.npy + manifest,
    and — via ``debug_urls`` — to remote sinks (``tcp://host:port``
    streams to a reader in another process; ref debug_io_utils.cc)."""

    def __init__(self, sess, session_root, watch_fn=None, log_usage=False,
                 debug_urls=()):
        super().__init__(sess)
        self._root = session_root
        os.makedirs(session_root, exist_ok=True)
        self._run_counter = 0
        self._watches = [TensorWatch("*")]
        from . import io_utils

        self._sinks = [io_utils.sink_for_url(u) for u in debug_urls]

    def add_tensor_filter(self, name, fn):
        pass

    def close(self):
        for s in self._sinks:
            s.close()
        self._sess.close()

    def run(self, fetches, feed_dict=None, options=None, run_metadata=None):
        watched = self._watched_tensors(fetches, feed_dict, self._watches)
        self._run_counter += 1
        run_dir = os.path.join(self._root, f"run_{self._run_counter}")
        os.makedirs(run_dir, exist_ok=True)
        # options/run_metadata forward to the wrapped session: a traced
        # run through the wrapper must still produce step stats
        result = self._sess.run({"__fetches__": fetches,
                                 "__watched__": watched},
                                feed_dict=feed_dict, options=options,
                                run_metadata=run_metadata)
        manifest = {}
        for t, v in zip(watched, result["__watched__"]):
            safe = t.name.replace("/", "_").replace(":", "_")
            path = os.path.join(run_dir, safe + ".npy")
            np.save(path, np.asarray(v))
            manifest[t.name] = {
                "file": safe + ".npy",
                "has_inf_or_nan": has_inf_or_nan(t.name, v),
            }
            for s in self._sinks:
                s.publish(self._run_counter, t.name, v)
        with open(os.path.join(run_dir, "manifest.json"), "w") as f:
            json.dump({"time": time.time(), "tensors": manifest}, f, indent=1)
        return result["__fetches__"]


class LocalCLIDebugWrapperSession(_WrapperBase):
    """(ref: python/debug/wrappers/local_cli_wrapper.py). Non-interactive
    variant: logs watched tensor stats; breaks on inf/nan."""

    def __init__(self, sess, dump_root=None, log_usage=False,
                 break_on_nan=True):
        super().__init__(sess)
        self._watches = [TensorWatch("*")]
        self._break_on_nan = break_on_nan

    def run(self, fetches, feed_dict=None, options=None, run_metadata=None):
        watched = self._watched_tensors(fetches, feed_dict, self._watches)
        result = self._sess.run({"__fetches__": fetches,
                                 "__watched__": watched},
                                feed_dict=feed_dict, options=options,
                                run_metadata=run_metadata)
        bad = []
        for t, v in zip(watched, result["__watched__"]):
            if has_inf_or_nan(t.name, v):
                bad.append(t.name)
        if bad:
            msg = f"inf/nan detected in: {bad[:10]}"
            if self._break_on_nan:
                from ..framework import errors

                raise errors.InvalidArgumentError(None, None, msg)
            logging.warning(msg)
        return result["__fetches__"]


def add_check_numerics_ops():
    """(ref: python/ops/numerics.py ``add_check_numerics_ops``): returns a
    group of CheckNumerics on all float tensors in the graph."""
    from ..ops import array_ops, control_flow_ops

    g = ops_mod.get_default_graph()
    checks = []
    for op in g.get_operations():
        if op.op_def.runs_on_host:
            continue
        for t in op.outputs:
            if t.dtype.is_floating:
                checks.append(array_ops.check_numerics(
                    t, f"found bad value in {t.name}").op)
    return control_flow_ops.group(*checks, name="check_numerics_all")
