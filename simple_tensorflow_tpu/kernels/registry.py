"""Process-global kernel registry: Pallas vs XLA routing at lowering time.

The Pallas kernels (ops/pallas/) and their stock-XLA lowerings are two
implementations of the same op contract. This registry is the single
place that decides, per (op type, shapes, dtypes, backend), which one a
lowering emits — the TPU-native analogue of the reference's per-device
kernel registry (ref: tensorflow/core/framework/op_kernel.cc kernel
dispatch by KernelDef priority), upgraded with the cost-model gating the
TPU-v3 MLPerf submissions used to decide hand-tuned kernel vs compiler
output (1909.09756 §"performance optimizations").

Three modes (``STF_PALLAS`` env / ``stf.kernels.set_mode`` /
``ConfigProto(kernel_registry=...)``):

  off    the registry is inert — every op lowers exactly as it did
         before the registry existed (the fused graph ops keep their
         Pallas kernels, composed ops keep their jnp lowerings, the
         optimizer tail stays per-variable assigns).
  auto   (default) eligibility checks, then a static cost-model gate
         (roofline pricing of both lowerings, framework/cost_model.py
         accounting), then — for shapes the gate cannot confidently
         price, or always under ``STF_KERNEL_AUTOTUNE=1`` — a measured
         micro-autotune: the first call on an ungated shape times both
         lowerings and persists the verdict alongside the persistent
         compile cache (compiler.aot.enable_persistent_cache). A
         measured verdict always overrides the static gate: auto mode
         never picks a lowering the autotune measured slower.
  force  the Pallas implementation for every eligible op (interpret
         mode off-TPU, so the whole tier runs under tier-1 CPU tests).

Every decision increments exactly one of ``/stf/kernels/routed{op}``
(Pallas chosen) or ``/stf/kernels/fallback{op, reason}`` (XLA chosen),
so the counters explain every non-routed call. Decisions are cached per
(op, key, mode, backend) — a given executable always retraces to the
same routing.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..platform import monitoring
from ..platform import sync as _sync

MODES = ("off", "auto", "force")

metric_routed = monitoring.Counter(
    "/stf/kernels/routed",
    "lowering decisions that chose the Pallas kernel", "op")
metric_fallback = monitoring.Counter(
    "/stf/kernels/fallback",
    "lowering decisions that chose the stock XLA lowering", "op", "reason")
metric_autotune_runs = monitoring.Counter(
    "/stf/kernels/autotune_runs",
    "micro-autotune measurements (both lowerings timed once per "
    "ungated (op, shape, dtype, backend) key)", "op")

# -- mode ---------------------------------------------------------------------

_state = threading.local()          # per-thread activation (Session lowering)
_mode_override: Optional[str] = None
_lock = _sync.RLock("kernels/registry", rank=_sync.RANK_STATE)


def _env_mode() -> str:
    """Resolve the process-default mode from the environment.

    STF_PALLAS=0 is the documented kill switch (registry inert, pre-PR
    lowerings); STF_PALLAS=force pins every eligible op to Pallas;
    anything else (or unset) is auto. STF_KERNELS=off|auto|force is the
    explicit spelling of the same knob and wins when both are set.
    """
    v = os.environ.get("STF_KERNELS")
    if v in MODES:
        return v
    p = os.environ.get("STF_PALLAS")
    if p is not None:
        p = p.strip().lower()
        if p in ("0", "off", "false", "no"):
            return "off"
        if p == "force":
            return "force"
    return "auto"


def set_mode(mode: Optional[str]) -> None:
    """Set the process-default routing mode (None = back to the env
    default). Affects decisions made by FUTURE traces only: an
    already-compiled executable keeps the routing it was traced with."""
    global _mode_override
    if mode is not None and mode not in MODES:
        raise ValueError(f"kernel registry mode must be one of {MODES}, "
                         f"got {mode!r}")
    _mode_override = mode


def default_mode() -> str:
    return _mode_override if _mode_override is not None else _env_mode()


def current_mode() -> str:
    """The mode in effect for decisions on this thread: an active
    lowering's ConfigProto(kernel_registry=...) scope if one is open,
    else the process default."""
    m = getattr(_state, "mode", None)
    return m if m is not None else default_mode()


class activate:
    """Context manager: pin the decision mode for this thread while a
    Session lowers (framework/lowering.py execute_ops wraps its trace
    loop in one, carrying ConfigProto(kernel_registry=...)). ``None``
    leaves the current/default mode in effect. Re-entrant."""

    def __init__(self, mode: Optional[str]):
        if mode is not None and mode not in MODES:
            raise ValueError(f"kernel registry mode must be one of {MODES}, "
                             f"got {mode!r}")
        self._mode = mode
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_state, "mode", None)
        if self._mode is not None:
            _state.mode = self._mode
        return self

    def __exit__(self, *exc):
        _state.mode = self._prev
        return False


def backend() -> str:
    import jax

    return jax.default_backend()


# -- kernel definitions -------------------------------------------------------

class KernelDef:
    """One routable op type.

    impls: {"pallas": fn, "xla": fn} — call-compatible implementations
      (same positional arrays, same static kwargs, same outputs).
    legacy: which impl the op lowered through BEFORE the registry
      existed; ``off`` mode always picks it.
    eligible(key) -> None (Pallas-capable) or a fallback reason string
      (``ineligible_*``). Force mode still honors ineligibility — an
      implementation that cannot express the call cannot be forced.
    cost_gate(key, backend) -> (verdict|None, reason): the static gate.
      None verdict = uncertain, measure (auto mode).
    make_case(key) -> (args, kwargs): representative concrete inputs
      for the micro-autotune (never called for ineligible keys).
    """

    __slots__ = ("op_type", "impls", "legacy", "eligible", "cost_gate",
                 "make_case", "graph_key", "doc")

    def __init__(self, op_type, impls, legacy, eligible=None,
                 cost_gate=None, make_case=None, graph_key=None, doc=""):
        assert legacy in ("pallas", "xla")
        self.op_type = op_type
        self.impls = dict(impls)
        self.legacy = legacy
        self.eligible = eligible or (lambda key: None)
        self.cost_gate = cost_gate or (lambda key, backend: (None, "unpriced"))
        self.make_case = make_case
        self.graph_key = graph_key
        self.doc = doc


_KERNELS: Dict[str, KernelDef] = {}


def register_kernel(op_type: str, **kw) -> KernelDef:
    kd = KernelDef(op_type, **kw)
    _KERNELS[op_type] = kd
    return kd


def kernel_types() -> List[str]:
    return sorted(_KERNELS)


def has_kernel(op_type: str) -> bool:
    return op_type in _KERNELS


# -- keys ---------------------------------------------------------------------

def aval_key(*arrays, **statics) -> Tuple:
    """Canonical decision key: (shape, dtype) per array (None entries
    skipped) + sorted perf-relevant statics. Works on tracers, jax
    arrays, numpy arrays, and ShapeDtypeStructs alike."""
    parts: List[Any] = []
    for a in arrays:
        if a is None:
            parts.append(None)
        else:
            parts.append((tuple(getattr(a, "shape", ())),
                          str(getattr(a, "dtype", "?"))))
    for k in sorted(statics):
        parts.append((k, statics[k]))
    return tuple(parts)


# -- autotune cache -----------------------------------------------------------

# (op_type, key, backend) -> {"verdict", "pallas_s", "xla_s"}
_measured: Dict[Tuple, Dict[str, Any]] = {}
_measured_loaded_from: Optional[str] = None
_AUTOTUNE_FILE = "stf_kernel_autotune.json"


def _autotune_forced() -> bool:
    return os.environ.get("STF_KERNEL_AUTOTUNE", "") == "1"


def _cache_file() -> Optional[str]:
    """Persist verdicts alongside the persistent compile cache (PR 5):
    the same directory that makes process restarts disk-hit their XLA
    compiles makes them skip re-measuring."""
    try:
        from ..compiler import aot

        d = aot.persistent_cache_dir()
    except Exception:
        return None
    if not d:
        return None
    return os.path.join(d, _AUTOTUNE_FILE)


def _load_persisted() -> None:
    global _measured_loaded_from
    path = _cache_file()
    if path is None or path == _measured_loaded_from:
        return
    _measured_loaded_from = path
    try:
        with open(path) as f:
            raw = json.load(f)
    except (OSError, ValueError):
        return
    def _tuplify(x):
        if isinstance(x, list):
            return tuple(_tuplify(v) for v in x)
        return x

    for rec in raw.get("verdicts", []):
        try:
            k = (rec["op"], _tuplify(rec["key"]), rec["backend"])
            _measured.setdefault(k, {
                "verdict": rec["verdict"],
                "pallas_s": rec.get("pallas_s"),
                "xla_s": rec.get("xla_s"),
            })
        except (KeyError, TypeError):
            continue


def _persist() -> None:
    path = _cache_file()
    if path is None:
        return
    recs = []
    for (op, key, bk), v in _measured.items():
        recs.append({"op": op, "key": _jsonable(key), "backend": bk,
                     "verdict": v["verdict"], "pallas_s": v.get("pallas_s"),
                     "xla_s": v.get("xla_s")})
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump({"verdicts": recs}, f)
        os.replace(tmp, path)
    except OSError:
        try:
            os.remove(tmp)
        except OSError:
            pass


def _jsonable(part):
    if isinstance(part, tuple):
        return [_jsonable(x) for x in part]
    return part


def _time_thunk(fn, args, kwargs) -> float:
    """Best-of-N wall time of ``fn(*args, **kwargs)`` under jit (the
    first call pays trace+compile and is excluded)."""
    import jax

    jfn = jax.jit(lambda *a: fn(*a, **kwargs))
    jax.block_until_ready(jfn(*args))  # compile + warm
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        jax.block_until_ready(jfn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def _measure(kd: KernelDef, key, bk: str) -> str:
    """Micro-autotune: time both lowerings on representative inputs,
    persist the verdict. Called at most once per (op, key, backend)."""
    cache_key = (kd.op_type, key, bk)
    hit = _measured.get(cache_key)
    if hit is not None:
        return hit["verdict"]
    if kd.make_case is None:
        # nothing to measure with: defer to the static gate's lean
        v, _ = kd.cost_gate(key, bk)
        return v or ("xla" if bk != "tpu" else "pallas")
    metric_autotune_runs.get_cell(kd.op_type).increase_by(1)
    args, kwargs = kd.make_case(key)
    try:
        t_p = _time_thunk(kd.impls["pallas"], args, kwargs)
        t_x = _time_thunk(kd.impls["xla"], args, kwargs)
    except Exception:  # noqa: BLE001 — measurement must never sink a trace
        verdict = "xla" if bk != "tpu" else "pallas"
        _measured[cache_key] = {"verdict": verdict, "pallas_s": None,
                                "xla_s": None}
        return verdict
    verdict = "pallas" if t_p <= t_x else "xla"
    _measured[cache_key] = {"verdict": verdict, "pallas_s": t_p,
                            "xla_s": t_x}
    _persist()
    return verdict


def measured_verdicts() -> Dict[Tuple, Dict[str, Any]]:
    """The in-process autotune cache (bench/introspection)."""
    return dict(_measured)


def record_measurement(op_type: str, key, pallas_s: float,
                       xla_s: float) -> str:
    """Feed an externally-timed (pallas, xla) pair into the autotune
    cache — the bench row records its per-kernel timings through this,
    so auto-mode decisions afterwards follow the measurement (the
    'never pick a lowering the autotune measured slower' contract).
    Returns the resulting verdict. Cached decisions are invalidated for
    this op so the next decide() re-reads the cache."""
    verdict = "pallas" if pallas_s <= xla_s else "xla"
    _measured[(op_type, key, backend())] = {
        "verdict": verdict, "pallas_s": float(pallas_s),
        "xla_s": float(xla_s)}
    _persist()
    with _lock:
        for k in [k for k in _decisions if k[0] == op_type and k[1] == key]:
            del _decisions[k]
    return verdict


def clear_measurements() -> None:
    _measured.clear()


# -- decisions ----------------------------------------------------------------

# (op_type, key, mode, backend) -> (impl_name, reason): the same trace
# signature always routes the same way within a process
_decisions: Dict[Tuple, Tuple[str, str]] = {}


def decide(op_type: str, key, mode: Optional[str] = None,
           count: bool = True) -> Tuple[str, str]:
    """Route one call: returns (impl_name, reason) with impl_name in
    {"pallas", "xla"}. Increments exactly one routed/fallback counter
    per call (``count=False`` for offline reports)."""
    kd = _KERNELS[op_type]
    mode = mode or current_mode()
    bk = backend()
    cache_key = (op_type, key, mode, bk)
    with _lock:
        hit = _decisions.get(cache_key)
    if hit is None:
        # compute OUTSIDE the lock: the uncached path may run the
        # micro-autotune (two compiles + timed executions) and must not
        # stall every other thread's routing decisions; racing threads
        # at worst measure redundantly, and first-publish wins so the
        # cached decision stays stable
        computed = _decide_uncached(kd, key, mode, bk)
        with _lock:
            hit = _decisions.setdefault(cache_key, computed)
    impl, reason = hit
    if count:
        if impl == "pallas":
            metric_routed.get_cell(op_type).increase_by(1)
        else:
            metric_fallback.get_cell(op_type, reason).increase_by(1)
    return hit


def _decide_uncached(kd: KernelDef, key, mode: str, bk: str):
    if mode == "off":
        return (kd.legacy, "mode_off")
    inel = kd.eligible(key)
    if inel:
        return ("xla", inel)
    if mode == "force":
        return ("pallas", "forced")
    # auto: measured verdict wins over everything else
    _load_persisted()
    m = _measured.get((kd.op_type, key, bk))
    if m is not None:
        return (m["verdict"], "autotune")
    verdict, reason = kd.cost_gate(key, bk)
    if verdict is None or _autotune_forced():
        return (_measure(kd, key, bk), "autotune")
    return (verdict, reason)


def select(op_type: str, key, mode: Optional[str] = None) -> Callable:
    """decide() and hand back the chosen implementation callable."""
    impl, _ = decide(op_type, key, mode=mode)
    return _KERNELS[op_type].impls[impl]


def decisions_snapshot() -> List[Dict[str, Any]]:
    with _lock:
        return [{"op": op, "key": repr(key), "mode": mode,
                 "backend": bk, "impl": impl, "reason": reason}
                for (op, key, mode, bk), (impl, reason)
                in sorted(_decisions.items(), key=lambda kv: kv[0][0])]


def clear_decisions() -> None:
    """Forget cached routing decisions (tests / after set_mode). Does
    NOT retrace already-compiled executables."""
    with _lock:
        _decisions.clear()


def _backend_if_initialized() -> Optional[str]:
    """The jax backend WITHOUT triggering backend init (a /statusz
    scrape must never be what first brings a TPU runtime up)."""
    import sys

    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        from jax._src import xla_bridge

        if not xla_bridge.backends_are_initialized():
            return None
    except Exception:  # noqa: BLE001 — private API moved: best effort
        pass
    try:
        return jax.default_backend()
    except Exception:  # noqa: BLE001
        return None


def snapshot() -> Dict[str, Any]:
    """Registry state for /statusz and bench artifacts."""
    routed = {labels[0]: cell.value()
              for labels, cell in metric_routed.cells().items()}
    fallback = {f"{labels[0]}:{labels[1]}": cell.value()
                for labels, cell in metric_fallback.cells().items()}
    autotune = {labels[0]: cell.value()
                for labels, cell in metric_autotune_runs.cells().items()}
    return {
        "mode": default_mode(),
        "backend": _backend_if_initialized(),
        "kernels": kernel_types(),
        "routed": routed,
        "fallback": fallback,
        "autotune_runs": autotune,
        "measured": {f"{op}|{bk}": v["verdict"]
                     for (op, _k, bk), v in _measured.items()},
    }


# -- offline routing report (graph_lint --kernels; zoo gate) ------------------

def routing_report(ops, mode: Optional[str] = None,
                   backend_name: Optional[str] = None) -> List[Dict[str, Any]]:
    """Static per-op routing verdicts for a (possibly imported) graph:
    one record per op whose type has a registered kernel —
    ``verdict`` in {"routed", "fallback", "autotune"} — plus aggregate
    ``no-kernel`` counts for everything else. Never measures: keys the
    static gate cannot price report verdict "autotune" (decided on
    first live call)."""
    mode = mode or current_mode()
    bk = backend_name or backend()
    records: List[Dict[str, Any]] = []
    no_kernel: Dict[str, int] = {}
    for op in ops:
        kd = _KERNELS.get(op.type)
        if kd is None:
            no_kernel[op.type] = no_kernel.get(op.type, 0) + 1
            continue
        if kd.graph_key is None:
            records.append({"op": op.name, "type": op.type,
                            "verdict": "fallback",
                            "reason": "no_graph_key"})
            continue
        try:
            key = kd.graph_key(op)
        except Exception:  # noqa: BLE001 — report, don't raise
            key = None
        if key is None:
            records.append({"op": op.name, "type": op.type,
                            "verdict": "fallback",
                            "reason": "unknown_shape"})
            continue
        if mode == "off":
            impl, reason = kd.legacy, "mode_off"
        else:
            inel = kd.eligible(key)
            if inel:
                impl, reason = "xla", inel
            elif mode == "force":
                impl, reason = "pallas", "forced"
            else:
                m = _measured.get((kd.op_type, key, bk))
                if m is not None:
                    impl, reason = m["verdict"], "autotune"
                else:
                    impl, reason = kd.cost_gate(key, bk)
                    if impl is None:
                        records.append({"op": op.name, "type": op.type,
                                        "verdict": "autotune",
                                        "reason": "unmeasured"})
                        continue
        records.append({"op": op.name, "type": op.type,
                        "verdict": "routed" if impl == "pallas"
                        else "fallback", "reason": reason})
    for t, n in sorted(no_kernel.items()):
        records.append({"type": t, "verdict": "no-kernel", "count": n})
    return records


# -- shared gating helpers ----------------------------------------------------

def roofline_gate(flops: float, pallas_bytes: float, xla_bytes: float,
                  bk: str, margin: float = 1.25) -> Tuple[Optional[str], str]:
    """Price both lowerings with the PR 1 cost-model roofline (seconds =
    max(flops/peak_flops, bytes/peak_bw), utils/perf chip numbers) and
    pick the clearly-faster one; within ``margin`` the gate abstains and
    the micro-autotune decides.

    Off-TPU the Pallas kernels run in interpret mode — each grid program
    executes as traced jnp calls, orders of magnitude off the roofline —
    so the gate confidently falls back (reason ``interpret_backend``);
    a measured verdict still overrides (decide() consults the autotune
    cache first)."""
    if bk != "tpu":
        return ("xla", "interpret_backend")
    from ..utils import perf

    peak_flops, peak_bw = perf.chip_spec()
    t_pallas = max(flops / max(peak_flops, 1.0),
                   pallas_bytes / max(peak_bw, 1.0))
    t_xla = max(flops / max(peak_flops, 1.0),
                xla_bytes / max(peak_bw, 1.0))
    if t_xla > margin * t_pallas:
        return ("pallas", "cost_model")
    if t_pallas > margin * t_xla:
        return ("xla", "cost_model")
    return (None, "cost_model_uncertain")
