"""stf.kernels — the Pallas/XLA kernel routing tier.

Infrastructure lives in :mod:`.registry`; the actual kernel
registrations live next to the op lowerings that use them (the same
placement contract as sharding rules and effects): ops/pallas/__init__
registers the fused attention/layer-norm/xent/quant-matmul pairs,
ops/nn_ops.py the composed softmax-xent route, train/optimizers.py the
fused optimizer updates.

Quick reference (docs/PERFORMANCE.md "kernel tier"):

    stf.kernels.set_mode("force")          # pin Pallas everywhere
    STF_PALLAS=0                           # kill switch: pre-registry
                                           # lowerings exactly
    ConfigProto(kernel_registry="auto")    # per-Session mode
    /stf/kernels/{routed,fallback,autotune_runs}   # counters
"""

from .registry import (MODES, activate, aval_key, backend, clear_decisions,
                       clear_measurements, current_mode, decide,
                       decisions_snapshot, default_mode, has_kernel,
                       kernel_types, measured_verdicts, metric_autotune_runs,
                       metric_fallback, metric_routed, register_kernel,
                       roofline_gate, routing_report, select, set_mode,
                       snapshot)

__all__ = [
    "MODES", "activate", "aval_key", "backend", "clear_decisions",
    "clear_measurements", "current_mode", "decide", "decisions_snapshot",
    "default_mode", "has_kernel", "kernel_types", "measured_verdicts",
    "register_kernel", "roofline_gate", "routing_report", "select",
    "set_mode", "snapshot",
]
