"""stf.summary (ref: tensorflow/python/summary)."""

from .summary import (
    scalar, histogram, image, audio, text, tensor_summary, merge, merge_all,
)
from .writer.writer import FileWriter, FileWriterCache, EventsWriter
from .summary_iterator import summary_iterator
from . import tensorboard_logging


def get_summary_description(node_def):
    """(ref: summary.py ``get_summary_description``): the serialized
    SummaryDescription of a summary op node. Our summary ops carry the
    type tag in attrs."""
    op = node_def
    tag = getattr(op, "type", None) or getattr(op, "op", "")
    return {"type_hint": {"ScalarSummary": "scalar",
                          "HistogramSummary": "histogram",
                          "ImageSummary": "image",
                          "AudioSummary": "audio"}.get(tag, "")}


_PLUGIN_ASSETS = {}


class PluginAsset:
    """(ref: summary/plugin_asset.py): named blob written next to event
    files for TensorBoard plugins."""

    plugin_name = None

    def assets(self):
        return {}


def get_plugin_asset(plugin_asset_cls, graph=None):
    from ..framework import graph as ops_mod

    g = graph or ops_mod.get_default_graph()
    key = (id(g), plugin_asset_cls.plugin_name)
    if key not in _PLUGIN_ASSETS:
        _PLUGIN_ASSETS[key] = plugin_asset_cls()
    return _PLUGIN_ASSETS[key]


def get_all_plugin_assets(graph=None):
    from ..framework import graph as ops_mod

    g = graph or ops_mod.get_default_graph()
    return [v for (gid, _), v in _PLUGIN_ASSETS.items() if gid == id(g)]
