"""stf.summary (ref: tensorflow/python/summary)."""

from .summary import (
    scalar, histogram, image, audio, text, tensor_summary, merge, merge_all,
)
from .writer.writer import FileWriter, FileWriterCache, EventsWriter
from .summary_iterator import summary_iterator
from . import tensorboard_logging
