from .writer import FileWriter, FileWriterCache, EventsWriter
