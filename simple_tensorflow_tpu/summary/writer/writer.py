"""Event-file writer (ref: tensorflow/core/util/events_writer.cc,
python/summary/writer/writer.py).

Writes TensorBoard-compatible event files: TFRecord-framed protobuf-wire
Event messages (wall_time=1 double, step=2 int64, file_version=3,
summary=5). Async: a background thread drains a queue, like the
reference's EventFileWriter.
"""

from __future__ import annotations

import os
import queue
import socket
import threading
import time

from ...lib.io.tf_record import TFRecordWriter
from ...lib.proto import Writer as ProtoWriter
from ...platform import sync as _sync


def _encode_event(wall_time, step=None, file_version=None, summary_bytes=None,
                  graph_bytes=None, tagged_run_metadata=None):
    w = ProtoWriter()
    w.double_always(1, wall_time)
    if step:
        w.varint(2, step)
    if file_version:
        w.bytes_(3, file_version)
    if graph_bytes:
        w.bytes_(4, graph_bytes)
    if summary_bytes:
        w.bytes_(5, summary_bytes)
    if tagged_run_metadata is not None:  # Event.tagged_run_metadata = 8
        # (event.proto: 6 is the deprecated LogMessage, 7 session_log)
        w.message(8, tagged_run_metadata)
    return w.tobytes()


class EventsWriter:
    """(ref: core/util/events_writer.cc)."""

    def __init__(self, file_prefix):
        self._filename = (f"{file_prefix}.out.tfevents."
                          f"{int(time.time())}.{socket.gethostname()}")
        os.makedirs(os.path.dirname(self._filename) or ".", exist_ok=True)
        self._writer = TFRecordWriter(self._filename)
        self._writer.write(_encode_event(time.time(),
                                         file_version="brain.Event:2"))

    @property
    def filename(self):
        return self._filename

    def write_event(self, event_bytes):
        self._writer.write(event_bytes)

    def flush(self):
        self._writer.flush()

    def close(self):
        self._writer.close()


class FileWriter:
    """(ref: python/summary/writer/writer.py:268 ``class FileWriter``)."""

    def __init__(self, logdir, graph=None, max_queue=10, flush_secs=120,
                 filename_suffix=None, session=None):
        self._logdir = logdir
        os.makedirs(logdir, exist_ok=True)
        self._events_writer = EventsWriter(os.path.join(logdir, "events"))
        self._queue: "queue.Queue" = queue.Queue(maxsize=max_queue)
        self._flush_secs = flush_secs
        self._closed = False
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name="stf_summary_writer")
        self._worker.start()
        if graph is not None:
            self.add_graph(graph)

    def get_logdir(self):
        return self._logdir

    def _run(self):
        last_flush = time.time()
        while True:
            try:
                item = self._queue.get(timeout=0.2)
            except queue.Empty:
                item = None
            if item is self._SENTINEL:
                self._events_writer.flush()
                return
            if item is not None:
                self._events_writer.write_event(item)
            if time.time() - last_flush > self._flush_secs:
                self._events_writer.flush()
                last_flush = time.time()

    _SENTINEL = object()

    def add_event(self, event_bytes):
        if not self._closed:
            self._queue.put(event_bytes)

    def add_summary(self, summary, global_step=None):
        """(ref: writer.py:92 ``add_summary``). ``summary`` is the bytes
        fetched from a summary op."""
        if summary is None:
            return
        if hasattr(summary, "tobytes"):
            summary = summary.tobytes()
        if isinstance(summary, str):
            summary = summary.encode("latin-1")
        self.add_event(_encode_event(time.time(),
                                     step=int(global_step or 0),
                                     summary_bytes=bytes(summary)))

    def add_summary_value(self, tag, value, global_step=None):
        """Convenience: write one scalar directly (StepCounterHook)."""
        v = ProtoWriter()
        v.bytes_(1, tag)
        v.float32_always(2, float(value))
        s = ProtoWriter()
        s.message(1, v)
        self.add_event(_encode_event(time.time(), step=int(global_step or 0),
                                     summary_bytes=s.tobytes()))

    def add_graph(self, graph, global_step=None):
        try:
            import json

            from ...framework import graph_io

            gd = json.dumps(graph_io.graph_to_graphdef(graph)).encode()
            self.add_event(_encode_event(time.time(),
                                         step=int(global_step or 0),
                                         graph_bytes=gd))
        except Exception:
            pass

    def add_session_log(self, session_log, global_step=None):
        pass

    def add_run_metadata(self, run_metadata, tag, global_step=None):
        """(ref: writer.py:154 ``add_run_metadata``). Our RunMetadata is
        dict-shaped (step_stats + cost_graph), so the Event's
        ``tagged_run_metadata.run_metadata`` bytes carry JSON rather
        than a RunMetadata proto — same envelope, readable payload."""
        if run_metadata is None:
            return
        import json

        payload = {
            "step_stats": getattr(run_metadata, "step_stats", None) or {},
            "cost_graph": getattr(run_metadata, "cost_graph", None) or {},
        }
        inner = ProtoWriter()
        inner.bytes_(1, tag)  # TaggedRunMetadata.tag
        inner.bytes_(2, json.dumps(payload, default=str).encode())
        self.add_event(_encode_event(time.time(),
                                     step=int(global_step or 0),
                                     tagged_run_metadata=inner))

    def flush(self):
        deadline = time.time() + 5
        while not self._queue.empty() and time.time() < deadline:
            time.sleep(0.01)
        self._events_writer.flush()

    def close(self):
        if not self._closed:
            self._closed = True
            self._queue.put(self._SENTINEL)
            self._worker.join(timeout=5)
            self._events_writer.close()

    def reopen(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class FileWriterCache:
    """(ref: python/summary/writer/writer_cache.py)."""

    _cache = {}
    _lock = _sync.Lock("summary/writer_cache",
                       rank=_sync.RANK_LIFECYCLE)

    @staticmethod
    def get(logdir):
        with FileWriterCache._lock:
            if logdir not in FileWriterCache._cache:
                FileWriterCache._cache[logdir] = FileWriter(logdir)
            return FileWriterCache._cache[logdir]

    @staticmethod
    def clear():
        with FileWriterCache._lock:
            for w in FileWriterCache._cache.values():
                w.close()
            FileWriterCache._cache.clear()
