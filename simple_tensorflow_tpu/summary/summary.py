"""Summary ops (ref: tensorflow/python/summary/summary.py,
core/framework/summary.proto).

Summary ops are host-sink ops (Session post-host stage): the device program
computes the watched tensors; serialization to protobuf-wire Summary bytes
happens on the host. ``sess.run(merged)`` returns bytes TensorBoard-ready,
exactly like the reference.
"""

from __future__ import annotations

import numpy as np

from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from ..framework import op_registry
from ..framework import tensor_shape as shape_mod
from ..lib.proto import Writer

GraphKeys = ops_mod.GraphKeys


def _summary_value(tag: str, **kw) -> bytes:
    v = Writer()
    v.bytes_(1, tag)
    if "simple_value" in kw:
        v.float32_always(2, kw["simple_value"])
    if "histo" in kw:
        v.message(5, kw["histo"])
    if "image" in kw:
        v.message(4, kw["image"])
    if "audio" in kw:
        v.message(6, kw["audio"])
    if "tensor_bytes" in kw:
        v.bytes_(8, kw["tensor_bytes"])
    return v.tobytes()


def _wrap_summary(values: list) -> bytes:
    w = Writer()
    for val in values:
        w.bytes_(1, val)
    return w.tobytes()


def _histogram_proto(arr: np.ndarray) -> Writer:
    """(ref: core/lib/histogram/histogram.cc bucket scheme)."""
    arr = np.asarray(arr, dtype=np.float64).ravel()
    w = Writer()
    if arr.size == 0:
        return w
    w.double_always(1, float(np.min(arr)))
    w.double_always(2, float(np.max(arr)))
    w.double_always(3, float(arr.size))
    w.double_always(4, float(np.sum(arr)))
    w.double_always(5, float(np.sum(arr * arr)))
    # reference-style exponential buckets
    limits = [-1e-12, 1e-12]
    v = 1e-12
    while v < 1e20:
        v *= 1.1
        limits.append(v)
    neg = [-l for l in limits if l > 0]
    edges = sorted(set(neg + limits))
    counts, _ = np.histogram(arr, bins=np.asarray([-1e308] + edges + [1e308]))
    keep_limits, keep_counts = [], []
    bounds = edges + [1e308]
    for i, c in enumerate(counts):
        if c > 0:
            keep_limits.append(bounds[min(i, len(bounds) - 1)])
            keep_counts.append(float(c))
    w.packed_doubles(6, keep_limits)
    w.packed_doubles(7, keep_counts)
    return w


def _lower_scalar_summary(ctx, op, inputs):
    val = float(np.asarray(inputs[0]).reshape(()))
    return [_wrap_summary([_summary_value(op.attrs["tag"],
                                          simple_value=val)])]


def _lower_histogram_summary(ctx, op, inputs):
    histo = _histogram_proto(np.asarray(inputs[0]))
    return [_wrap_summary([_summary_value(op.attrs["tag"], histo=histo)])]


def _lower_image_summary(ctx, op, inputs):
    from ..lib import png

    images = np.asarray(inputs[0])
    vals = []
    n = min(op.attrs.get("max_outputs", 3), images.shape[0])
    for i in range(n):
        img = images[i]
        if img.dtype in (np.float32, np.float64) or str(img.dtype) == "bfloat16":
            img = np.clip(np.asarray(img, np.float32) * 255.0, 0, 255
                          ).astype(np.uint8)
        h, w_, c = img.shape
        iw = Writer()
        iw.varint_always(1, h).varint_always(2, w_).varint_always(3, c)
        iw.bytes_(4, png.encode(img))
        tag = op.attrs["tag"]
        vals.append(_summary_value(f"{tag}/image/{i}" if n > 1
                                   else f"{tag}/image", image=iw))
    return [_wrap_summary(vals)]


def _lower_audio_summary(ctx, op, inputs):
    audio = np.asarray(inputs[0])
    sr = float(op.attrs.get("sample_rate", 44100))
    vals = []
    n = min(op.attrs.get("max_outputs", 3), audio.shape[0])
    for i in range(n):
        aw = Writer()
        aw.float32_always(1, sr)
        clip = np.asarray(audio[i], np.float32)
        if clip.ndim == 1:
            clip = clip[:, None]
        aw.varint_always(2, clip.shape[1])
        aw.varint_always(3, clip.shape[0])
        from ..lib import wav

        aw.bytes_(4, wav.encode(clip, int(sr)))
        aw.bytes_(5, "audio/wav")
        vals.append(_summary_value(f"{op.attrs['tag']}/audio/{i}", audio=aw))
    return [_wrap_summary(vals)]


def _lower_text_summary(ctx, op, inputs):
    arr = np.asarray(inputs[0])
    tw = Writer()
    # TensorProto with string values (field 8 string_val) + dtype (1) DT_STRING=7
    tw.varint_always(1, 7)
    for s in np.ravel(arr):
        tw.bytes_(8, s if isinstance(s, bytes) else str(s).encode())
    val = Writer()
    val.bytes_(1, op.attrs["tag"])
    val.message(8, tw)
    # plugin metadata for the text plugin
    md = Writer()
    pd = Writer()
    pd.bytes_(1, "text")
    md.message(1, pd)
    val.message(9, md)
    return [_wrap_summary([val.tobytes()])]


def _lower_merge_summary(ctx, op, inputs):
    w = Writer()
    parts = []
    from ..lib.proto import parse

    for buf in inputs:
        if buf is None:
            continue
        fields = parse(bytes(buf))
        parts.extend(fields.get(1, []))
    return [_wrap_summary(parts)]


for _n, _fn in [("ScalarSummary", _lower_scalar_summary),
                ("HistogramSummary", _lower_histogram_summary),
                ("ImageSummary", _lower_image_summary),
                ("AudioSummary", _lower_audio_summary),
                ("TextSummary", _lower_text_summary),
                ("MergeSummary", _lower_merge_summary)]:
    op_registry.register(_n, lower=_fn, is_stateful=True, runs_on_host=True)


def _summary_op(op_type, tag, tensor, collections, attrs=None, name=None):
    g = ops_mod.get_default_graph()
    t = ops_mod.convert_to_tensor(tensor)
    a = {"tag": str(tag)}
    a.update(attrs or {})
    node = g.create_op(op_type, [t], attrs=a, name=name or op_type,
                       output_specs=[(shape_mod.scalar(), dtypes_mod.string)])
    out = node.outputs[0]
    for c in (collections if collections is not None
              else [GraphKeys.SUMMARIES]):
        g.add_to_collection(c, out)
    return out


def scalar(name, tensor, collections=None, family=None):
    """(ref: summary.py:70 ``scalar``)."""
    tag = f"{family}/{name}" if family else name
    return _summary_op("ScalarSummary", tag, tensor, collections, name=name)


def histogram(name, values, collections=None, family=None):
    tag = f"{family}/{name}" if family else name
    return _summary_op("HistogramSummary", tag, values, collections,
                       name=name)


def image(name, tensor, max_outputs=3, collections=None, family=None):
    tag = f"{family}/{name}" if family else name
    return _summary_op("ImageSummary", tag, tensor, collections,
                       attrs={"max_outputs": max_outputs}, name=name)


def audio(name, tensor, sample_rate, max_outputs=3, collections=None,
          family=None):
    tag = f"{family}/{name}" if family else name
    sr = sample_rate
    if isinstance(sr, ops_mod.Tensor):
        from ..framework import constant_op

        sr = float(constant_op.constant_value(sr))
    return _summary_op("AudioSummary", tag, tensor, collections,
                       attrs={"max_outputs": max_outputs, "sample_rate": sr},
                       name=name)


def text(name, tensor, collections=None):
    return _summary_op("TextSummary", name, tensor, collections, name=name)


def tensor_summary(name, tensor, summary_description=None, collections=None):
    return _summary_op("TextSummary", name, tensor, collections, name=name)


def merge(inputs, collections=None, name=None):
    """(ref: summary.py:232 ``merge``)."""
    g = ops_mod.get_default_graph()
    node = g.create_op("MergeSummary", list(inputs), attrs={},
                       name=name or "MergeSummary",
                       output_specs=[(shape_mod.scalar(), dtypes_mod.string)])
    out = node.outputs[0]
    if collections:
        for c in collections:
            g.add_to_collection(c, out)
    return out


def merge_all(key=GraphKeys.SUMMARIES, scope=None):
    """(ref: summary.py:262 ``merge_all``)."""
    summaries = ops_mod.get_collection(key, scope)
    if not summaries:
        return None
    return merge(summaries)


def get_summary_description(node_def):
    return ""
