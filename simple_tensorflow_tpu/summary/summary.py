"""Summary ops (ref: tensorflow/python/summary/summary.py,
core/framework/summary.proto).

Summary ops are host-sink ops (Session post-host stage): the device program
computes the watched tensors; serialization to protobuf-wire Summary bytes
happens on the host. ``sess.run(merged)`` returns bytes TensorBoard-ready,
exactly like the reference.
"""

from __future__ import annotations

import numpy as np

from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from ..framework import op_registry
from ..framework import tensor_shape as shape_mod
from ..lib.proto import Writer

GraphKeys = ops_mod.GraphKeys


def _summary_value(tag: str, **kw) -> bytes:
    v = Writer()
    v.bytes_(1, tag)
    if "simple_value" in kw:
        v.float32_always(2, kw["simple_value"])
    if "histo" in kw:
        v.message(5, kw["histo"])
    if "image" in kw:
        v.message(4, kw["image"])
    if "audio" in kw:
        v.message(6, kw["audio"])
    if "tensor_bytes" in kw:
        v.bytes_(8, kw["tensor_bytes"])
    return v.tobytes()


def _wrap_summary(values: list) -> bytes:
    w = Writer()
    for val in values:
        w.bytes_(1, val)
    return w.tobytes()


def _reference_edges():
    # reference-style exponential buckets (ref: core/lib/histogram/
    # histogram.cc InitDefaultBuckets) — value-INDEPENDENT, which is
    # what makes device-side bucketing possible: the grid is a compile-
    # time constant, only counts move
    limits = [-1e-12, 1e-12]
    v = 1e-12
    while v < 1e20:
        v *= 1.1
        limits.append(v)
    neg = [-l for l in limits if l > 0]
    return sorted(set(neg + limits))


_EDGES = _reference_edges()
_N_BINS = len(_EDGES) + 1
# packed layout of a HistogramBucketCounts vector:
# [min, max, count, sum, sum_sq, bucket_counts...]
_PACKED_WIDTH = 5 + _N_BINS


def _emit_histo(w: Writer, mn, mx, num, sm, sm_sq, counts) -> Writer:
    w.double_always(1, float(mn))
    w.double_always(2, float(mx))
    w.double_always(3, float(num))
    w.double_always(4, float(sm))
    w.double_always(5, float(sm_sq))
    keep_limits, keep_counts = [], []
    bounds = _EDGES + [1e308]
    for i, c in enumerate(counts):
        if c > 0:
            keep_limits.append(bounds[min(i, len(bounds) - 1)])
            keep_counts.append(float(c))
    w.packed_doubles(6, keep_limits)
    w.packed_doubles(7, keep_counts)
    return w


def _histogram_proto(arr: np.ndarray) -> Writer:
    """(ref: core/lib/histogram/histogram.cc bucket scheme)."""
    arr = np.asarray(arr, dtype=np.float64).ravel()
    w = Writer()
    if arr.size == 0:
        return w
    counts, _ = np.histogram(arr, bins=np.asarray([-1e308] + _EDGES + [1e308]))
    return _emit_histo(w, np.min(arr), np.max(arr), arr.size, np.sum(arr),
                       np.sum(arr * arr), counts)


def _histogram_proto_from_packed(vec: np.ndarray) -> Writer:
    """Rebuild the Summary histogram from a device-computed
    HistogramBucketCounts vector — the host never sees the full
    tensor, only ``_PACKED_WIDTH`` floats."""
    vec = np.asarray(vec, dtype=np.float64).ravel()
    w = Writer()
    if vec.size < _PACKED_WIDTH or vec[2] == 0:
        return w
    return _emit_histo(w, vec[0], vec[1], vec[2], vec[3], vec[4],
                       vec[5:5 + _N_BINS])


def _lower_scalar_summary(ctx, op, inputs):
    val = float(np.asarray(inputs[0]).reshape(()))
    return [_wrap_summary([_summary_value(op.attrs["tag"],
                                          simple_value=val)])]


def _lower_histogram_summary(ctx, op, inputs):
    if op.attrs.get("from_buckets"):
        histo = _histogram_proto_from_packed(np.asarray(inputs[0]))
    else:
        # legacy path (imported GraphDefs predating device-side
        # bucketing): the full tensor reaches the host
        histo = _histogram_proto(np.asarray(inputs[0]))
    return [_wrap_summary([_summary_value(op.attrs["tag"], histo=histo)])]


def _lower_image_summary(ctx, op, inputs):
    from ..lib import png

    images = np.asarray(inputs[0])
    vals = []
    n = min(op.attrs.get("max_outputs", 3), images.shape[0])
    for i in range(n):
        img = images[i]
        if img.dtype in (np.float32, np.float64) or str(img.dtype) == "bfloat16":
            img = np.clip(np.asarray(img, np.float32) * 255.0, 0, 255
                          ).astype(np.uint8)
        h, w_, c = img.shape
        iw = Writer()
        iw.varint_always(1, h).varint_always(2, w_).varint_always(3, c)
        iw.bytes_(4, png.encode(img))
        tag = op.attrs["tag"]
        vals.append(_summary_value(f"{tag}/image/{i}" if n > 1
                                   else f"{tag}/image", image=iw))
    return [_wrap_summary(vals)]


def _lower_audio_summary(ctx, op, inputs):
    audio = np.asarray(inputs[0])
    sr = float(op.attrs.get("sample_rate", 44100))
    vals = []
    n = min(op.attrs.get("max_outputs", 3), audio.shape[0])
    for i in range(n):
        aw = Writer()
        aw.float32_always(1, sr)
        clip = np.asarray(audio[i], np.float32)
        if clip.ndim == 1:
            clip = clip[:, None]
        aw.varint_always(2, clip.shape[1])
        aw.varint_always(3, clip.shape[0])
        from ..lib import wav

        aw.bytes_(4, wav.encode(clip, int(sr)))
        aw.bytes_(5, "audio/wav")
        vals.append(_summary_value(f"{op.attrs['tag']}/audio/{i}", audio=aw))
    return [_wrap_summary(vals)]


def _lower_text_summary(ctx, op, inputs):
    arr = np.asarray(inputs[0])
    tw = Writer()
    # TensorProto with string values (field 8 string_val) + dtype (1) DT_STRING=7
    tw.varint_always(1, 7)
    for s in np.ravel(arr):
        tw.bytes_(8, s if isinstance(s, bytes) else str(s).encode())
    val = Writer()
    val.bytes_(1, op.attrs["tag"])
    val.message(8, tw)
    # plugin metadata for the text plugin
    md = Writer()
    pd = Writer()
    pd.bytes_(1, "text")
    md.message(1, pd)
    val.message(9, md)
    return [_wrap_summary([val.tobytes()])]


def _lower_merge_summary(ctx, op, inputs):
    w = Writer()
    parts = []
    from ..lib.proto import parse

    for buf in inputs:
        if buf is None:
            continue
        fields = parse(bytes(buf))
        parts.extend(fields.get(1, []))
    return [_wrap_summary(parts)]


# host_sink_pure: summary serialization only OBSERVES device values
# (bytes out, nothing fed back into the step), so loop_safety may defer
# it to after a fused window instead of splitting the window
for _n, _fn in [("ScalarSummary", _lower_scalar_summary),
                ("HistogramSummary", _lower_histogram_summary),
                ("ImageSummary", _lower_image_summary),
                ("AudioSummary", _lower_audio_summary),
                ("TextSummary", _lower_text_summary),
                ("MergeSummary", _lower_merge_summary)]:
    op_registry.register(_n, lower=_fn, is_stateful=True, runs_on_host=True,
                         host_sink_pure=True)


def _histogram_bucket_counts_pure(x):
    import jax.numpy as jnp

    xf = jnp.asarray(x).astype(jnp.float32).ravel()
    if xf.size == 0:
        return jnp.zeros((_PACKED_WIDTH,), jnp.float32)
    edges = jnp.asarray(_EDGES, jnp.float32)
    idx = jnp.searchsorted(edges, xf, side="right")
    counts = jnp.zeros((_N_BINS,), jnp.float32).at[idx].add(1.0)
    head = jnp.stack([jnp.min(xf), jnp.max(xf),
                      jnp.asarray(float(xf.size), jnp.float32),
                      jnp.sum(xf), jnp.sum(xf * xf)])
    return jnp.concatenate([head, counts])


def _histogram_bucket_counts_infer(graph, attrs, input_tensors):
    return [(shape_mod.TensorShape([_PACKED_WIDTH]), dtypes_mod.float32)]


op_registry.register("HistogramBucketCounts",
                     pure_fn=_histogram_bucket_counts_pure,
                     infer_fn=_histogram_bucket_counts_infer,
                     effects=op_registry.Effects())


def _histogram_bucket_counts_sharding(op, in_specs, ctx):
    s = in_specs[0]
    if s:
        axes = tuple(sorted({a for dim in s for a in dim}))
        if axes:
            ctx.collective(
                "all-reduce", axes, 4.0 * _PACKED_WIDTH,
                note="histogram bucket counts over sharded input",
                tensor_name=op.outputs[0].name)
    return [((),)]


op_registry.register_sharding_rule("HistogramBucketCounts",
                                   _histogram_bucket_counts_sharding)


def _summary_op(op_type, tag, tensor, collections, attrs=None, name=None):
    g = ops_mod.get_default_graph()
    t = ops_mod.convert_to_tensor(tensor)
    a = {"tag": str(tag)}
    a.update(attrs or {})
    node = g.create_op(op_type, [t], attrs=a, name=name or op_type,
                       output_specs=[(shape_mod.scalar(), dtypes_mod.string)])
    out = node.outputs[0]
    for c in (collections if collections is not None
              else [GraphKeys.SUMMARIES]):
        g.add_to_collection(c, out)
    return out


def scalar(name, tensor, collections=None, family=None):
    """(ref: summary.py:70 ``scalar``)."""
    tag = f"{family}/{name}" if family else name
    return _summary_op("ScalarSummary", tag, tensor, collections, name=name)


def histogram(name, values, collections=None, family=None):
    tag = f"{family}/{name}" if family else name
    v = ops_mod.convert_to_tensor(values)
    if v.dtype.is_floating or v.dtype.is_integer:
        # bucketize on device: the host stage fetches _PACKED_WIDTH
        # floats instead of the full tensor, and the summary op becomes
        # a pure observer of a tiny device value — fused windows under
        # SummarySaverHook no longer split on histogram traffic
        g = ops_mod.get_default_graph()
        counts = g.create_op(
            "HistogramBucketCounts", [v], attrs={},
            name=(name or "Histogram") + "_buckets",
            output_specs=[(shape_mod.TensorShape([_PACKED_WIDTH]),
                           dtypes_mod.float32)]).outputs[0]
        return _summary_op("HistogramSummary", tag, counts, collections,
                           attrs={"from_buckets": True}, name=name)
    return _summary_op("HistogramSummary", tag, v, collections,
                       name=name)


def image(name, tensor, max_outputs=3, collections=None, family=None):
    tag = f"{family}/{name}" if family else name
    return _summary_op("ImageSummary", tag, tensor, collections,
                       attrs={"max_outputs": max_outputs}, name=name)


def audio(name, tensor, sample_rate, max_outputs=3, collections=None,
          family=None):
    tag = f"{family}/{name}" if family else name
    sr = sample_rate
    if isinstance(sr, ops_mod.Tensor):
        from ..framework import constant_op

        sr = float(constant_op.constant_value(sr))
    return _summary_op("AudioSummary", tag, tensor, collections,
                       attrs={"max_outputs": max_outputs, "sample_rate": sr},
                       name=name)


def text(name, tensor, collections=None):
    return _summary_op("TextSummary", name, tensor, collections, name=name)


def tensor_summary(name, tensor, summary_description=None, collections=None):
    return _summary_op("TextSummary", name, tensor, collections, name=name)


def merge(inputs, collections=None, name=None):
    """(ref: summary.py:232 ``merge``)."""
    g = ops_mod.get_default_graph()
    node = g.create_op("MergeSummary", list(inputs), attrs={},
                       name=name or "MergeSummary",
                       output_specs=[(shape_mod.scalar(), dtypes_mod.string)])
    out = node.outputs[0]
    if collections:
        for c in collections:
            g.add_to_collection(c, out)
    return out


def merge_all(key=GraphKeys.SUMMARIES, scope=None):
    """(ref: summary.py:262 ``merge_all``)."""
    summaries = ops_mod.get_collection(key, scope)
    if not summaries:
        return None
    return merge(summaries)


def get_summary_description(node_def):
    return ""
