"""tensorboard_logging (ref: tensorflow/python/training/tensorboard_logging.py):
mirror log messages into the event file as well as stderr."""

from __future__ import annotations

import time

from ..platform import tf_logging as logging

DEBUG = "DEBUG"
INFO = "INFO"
WARN = "WARN"
ERROR = "ERROR"
FATAL = "FATAL"

_levels = [DEBUG, INFO, WARN, ERROR, FATAL]
_summary_writer = None
_verbosity = WARN


def set_summary_writer(summary_writer):
    global _summary_writer
    _summary_writer = summary_writer


def set_verbosity(verbosity):
    global _verbosity
    if verbosity not in _levels:
        raise ValueError(f"bad level {verbosity}")
    _verbosity = verbosity


def _log(level, message, *args):
    msg = message % args if args else message
    getattr(logging, level.lower() if level != FATAL else "fatal",
            logging.info)(msg)
    if _summary_writer and _levels.index(level) >= _levels.index(_verbosity):
        from ..lib.proto import Writer

        w = Writer()
        lw = Writer()
        lw.varint_always(1, _levels.index(level) * 10)
        lw.bytes_(2, msg)
        w.message(6, lw)  # LogMessage field in Event
        from .writer.writer import _encode_event

        _summary_writer.add_event(_encode_event(time.time()) + w.tobytes())


def debug(message, *args):
    _log(DEBUG, message, *args)


def info(message, *args):
    _log(INFO, message, *args)


def warn(message, *args):
    _log(WARN, message, *args)


def error(message, *args):
    _log(ERROR, message, *args)


def fatal(message, *args):
    _log(FATAL, message, *args)
