"""Read event files back (ref: tensorflow/python/summary/summary_iterator.py)."""

from __future__ import annotations

from ..lib.io.tf_record import tf_record_iterator
from ..lib.proto import parse


class Event:
    """Decoded Event proto (fields mirroring core/util/event.proto)."""

    def __init__(self, raw: bytes):
        f = parse(raw)
        self.wall_time = f.get(1, [0.0])[0]
        self.step = f.get(2, [0])[0]
        self.file_version = (f[3][0].decode() if 3 in f else None)
        self.graph_def = f.get(4, [None])[0]
        self.summary = SummaryProto(f[5][0]) if 5 in f else None


class SummaryProto:
    def __init__(self, raw: bytes):
        f = parse(raw)
        self.value = [SummaryValue(v) for v in f.get(1, [])]


class SummaryValue:
    def __init__(self, raw: bytes):
        f = parse(raw)
        self.tag = f[1][0].decode() if 1 in f else ""
        self.simple_value = f.get(2, [None])[0]
        self.histo = HistogramProto(f[5][0]) if 5 in f else None
        self.image = f.get(4, [None])[0]

    def HasField(self, name):
        return getattr(self, name, None) is not None


class HistogramProto:
    def __init__(self, raw: bytes):
        import struct

        f = parse(raw)
        self.min = f.get(1, [0.0])[0]
        self.max = f.get(2, [0.0])[0]
        self.num = f.get(3, [0.0])[0]
        self.sum = f.get(4, [0.0])[0]
        self.sum_squares = f.get(5, [0.0])[0]

        def unpack(field):
            if field not in f:
                return []
            buf = f[field][0]
            if isinstance(buf, bytes):
                return list(struct.unpack(f"<{len(buf)//8}d", buf))
            return f[field]

        self.bucket_limit = unpack(6)
        self.bucket = unpack(7)


def summary_iterator(path):
    """(ref: summary_iterator.py:27 ``summary_iterator``)."""
    for record in tf_record_iterator(path):
        yield Event(record)
