"""Image ops (ref: tensorflow/python/ops/image_ops_impl.py,
core/kernels/{resize_bilinear_op,adjust_contrast_op,colorspace_op,...}.cc).

Device ops use jax.image / jnp (MXU/VPU friendly, fused by XLA); PNG/JPEG
codecs run in the host stage (the reference pins decode ops to CPU too).
"""

from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtypes as dtypes_mod
from ..framework import errors as errors_mod
from ..framework import graph as ops_mod
from ..framework import op_registry
from ..framework import random_seed as random_seed_mod
from ..framework import tensor_shape as shape_mod
from .op_util import make_op, unary
from . import math_ops, array_ops

ResizeMethod = type("ResizeMethod", (), {
    "BILINEAR": 0, "NEAREST_NEIGHBOR": 1, "BICUBIC": 2, "AREA": 3})


# -- registrations -----------------------------------------------------------

_METHOD_NAME = {0: "bilinear", 1: "nearest", 2: "cubic", 3: "linear"}


def _resize_impl(images, size=None, method=0, align_corners=False):
    batched = images.ndim == 4
    if not batched:
        images = images[None]
    b, h, w, c = images.shape
    out = jax.image.resize(images.astype(jnp.float32),
                           (b, size[0], size[1], c),
                           method=_METHOD_NAME.get(method, "bilinear"))
    if method == 1:
        out = out.astype(images.dtype)
    if not batched:
        out = out[0]
    return out


op_registry.register_pure("ResizeImages", _resize_impl)
op_registry.register_pure("ResizeBilinear",
                          lambda x, size=None, align_corners=False:
                          _resize_impl(x, size, 0, align_corners))
op_registry.register_pure("ResizeNearestNeighbor",
                          lambda x, size=None, align_corners=False:
                          _resize_impl(x, size, 1, align_corners))
op_registry.register_pure(
    "RGBToGrayscale", lambda x: jnp.sum(
        x.astype(jnp.float32) * jnp.asarray([0.2989, 0.587, 0.114]),
        axis=-1, keepdims=True).astype(x.dtype))
op_registry.register_pure(
    "GrayscaleToRGB", lambda x: jnp.tile(x, (1,) * (x.ndim - 1) + (3,)))


def _rgb_to_hsv(x):
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    mx = jnp.maximum(jnp.maximum(r, g), b)
    mn = jnp.minimum(jnp.minimum(r, g), b)
    diff = mx - mn
    safe = jnp.where(diff > 0, diff, 1.0)
    h = jnp.where(
        mx == r, (g - b) / safe % 6.0,
        jnp.where(mx == g, (b - r) / safe + 2.0, (r - g) / safe + 4.0)) / 6.0
    h = jnp.where(diff > 0, h, 0.0)
    s = jnp.where(mx > 0, diff / jnp.where(mx > 0, mx, 1.0), 0.0)
    return jnp.stack([h, s, mx], axis=-1)


def _hsv_to_rgb(x):
    h, s, v = x[..., 0], x[..., 1], x[..., 2]
    i = jnp.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    i = i.astype(jnp.int32) % 6
    r = jnp.choose(i, [v, q, p, p, t, v], mode="clip")
    g = jnp.choose(i, [t, v, v, q, p, p], mode="clip")
    b = jnp.choose(i, [p, p, t, v, v, q], mode="clip")
    return jnp.stack([r, g, b], axis=-1)


op_registry.register_pure("RGBToHSV", _rgb_to_hsv)
op_registry.register_pure("HSVToRGB", _hsv_to_rgb)
op_registry.register_pure(
    "AdjustBrightness", lambda x, delta=0.0: (
        x.astype(jnp.float32) + delta).astype(x.dtype))
op_registry.register_pure(
    "AdjustContrast", lambda x, contrast_factor=1.0: (
        (x.astype(jnp.float32) -
         jnp.mean(x.astype(jnp.float32), axis=(-3, -2), keepdims=True)) *
        contrast_factor +
        jnp.mean(x.astype(jnp.float32), axis=(-3, -2), keepdims=True)
    ).astype(x.dtype))


def _adjust_hue(x, delta=0.0):
    hsv = _rgb_to_hsv(x.astype(jnp.float32))
    h = (hsv[..., 0] + delta) % 1.0
    return _hsv_to_rgb(jnp.stack([h, hsv[..., 1], hsv[..., 2]],
                                 axis=-1)).astype(x.dtype)


def _adjust_saturation(x, factor=1.0):
    hsv = _rgb_to_hsv(x.astype(jnp.float32))
    s = jnp.clip(hsv[..., 1] * factor, 0.0, 1.0)
    return _hsv_to_rgb(jnp.stack([hsv[..., 0], s, hsv[..., 2]],
                                 axis=-1)).astype(x.dtype)


op_registry.register_pure("AdjustHue", _adjust_hue)
op_registry.register_pure("AdjustSaturation", _adjust_saturation)
op_registry.register_pure(
    "PerImageStandardization", lambda x: (
        (x.astype(jnp.float32) -
         jnp.mean(x.astype(jnp.float32), axis=(-3, -2, -1), keepdims=True)) /
        jnp.maximum(jnp.std(x.astype(jnp.float32), axis=(-3, -2, -1),
                            keepdims=True),
                    1.0 / jnp.sqrt(jnp.asarray(
                        float(np.prod(x.shape[-3:])), jnp.float32)))))
op_registry.register_pure("FlipLeftRight", lambda x: jnp.flip(x, axis=-2))
op_registry.register_pure("FlipUpDown", lambda x: jnp.flip(x, axis=-3))
op_registry.register_pure("Rot90", lambda x, k=1: jnp.rot90(
    x, k=k, axes=(-3, -2)))
op_registry.register_pure(
    "CropToBoundingBox",
    lambda x, offset_height=0, offset_width=0, target_height=0,
    target_width=0: x[..., offset_height:offset_height + target_height,
                      offset_width:offset_width + target_width, :])


def _pad_to_bbox(x, offset_height=0, offset_width=0, target_height=0,
                 target_width=0):
    h, w = x.shape[-3], x.shape[-2]
    pads = [(0, 0)] * (x.ndim - 3) + [
        (offset_height, target_height - h - offset_height),
        (offset_width, target_width - w - offset_width), (0, 0)]
    return jnp.pad(x, pads)


op_registry.register_pure("PadToBoundingBox", _pad_to_bbox)


def _random_flip(key, op, inputs):
    import jax as _jax

    x = inputs[0]
    axis = op.attrs["axis"]
    flip = _jax.random.bernoulli(key, 0.5)
    return [jnp.where(flip, jnp.flip(x, axis=axis), x)]


op_registry.register("RandomFlip",
                     lower=lambda ctx, op, inputs: _random_flip(
                         ctx.rng_for(op), op, inputs),
                     effects=op_registry.Effects(rng=True))


def _central_crop_impl(x, fraction=1.0):
    h, w = x.shape[-3], x.shape[-2]
    ch = int(h * fraction)
    cw = int(w * fraction)
    oh = (h - ch) // 2
    ow = (w - cw) // 2
    return x[..., oh:oh + ch, ow:ow + cw, :]


op_registry.register_pure("CentralCrop", _central_crop_impl)

op_registry.register_pure(
    "ConvertImageDtype", lambda x, dtype=None, saturate=False:
    _convert_dtype_impl(x, dtype, saturate))


def _convert_dtype_impl(x, dtype, saturate):
    target = dtype.np_dtype
    if np.issubdtype(np.dtype(target), np.floating):
        if jnp.issubdtype(x.dtype, jnp.integer):
            return (x.astype(jnp.float32) /
                    float(np.iinfo(np.dtype(x.dtype)).max)).astype(target)
        return x.astype(target)
    # float -> int
    if jnp.issubdtype(x.dtype, jnp.floating):
        mx = float(np.iinfo(target).max)
        return jnp.clip(x * (mx + 0.5), 0, mx).astype(target)
    return x.astype(target)


# -- host codecs -------------------------------------------------------------

def _lower_decode_png(ctx, op, inputs):
    from ..lib import png as png_lib

    raw = inputs[0]
    if hasattr(raw, "item"):
        raw = raw.item() if raw.ndim == 0 else raw.ravel()[0]
    if isinstance(raw, str):
        raw = raw.encode("latin-1")
    return [png_lib.decode(bytes(raw))]


def _lower_encode_png(ctx, op, inputs):
    from ..lib import png as png_lib

    return [np.asarray(png_lib.encode(np.asarray(inputs[0])), dtype=object)]


op_registry.register("DecodePng", lower=_lower_decode_png, is_stateful=True,
                     runs_on_host=True)
op_registry.register("EncodePng", lower=_lower_encode_png, is_stateful=True,
                     runs_on_host=True)


def _jpeg_bytes(x) -> bytes:
    v = x.item() if hasattr(x, "item") else x
    return v if isinstance(v, bytes) else bytes(v, "latin-1")


def _lower_decode_jpeg(ctx, op, inputs):
    """Host-stage JPEG decode via PIL (the reference uses libjpeg,
    core/kernels/decode_jpeg_op.cc; ImageNet-style pipelines are JPEG)."""
    try:
        from PIL import Image
    except ImportError as e:
        raise NotImplementedError(
            "decode_jpeg needs Pillow on the host (pip install pillow); "
            "alternatively store datasets as PNG/TFRecord-raw, or decode "
            "with stf.py_func + your own codec.") from e
    import io as _io

    img = Image.open(_io.BytesIO(_jpeg_bytes(inputs[0])))
    channels = op.attrs.get("channels", 0) or 0
    if channels == 1:
        img = img.convert("L")
    elif channels == 3:
        img = img.convert("RGB")
    elif img.mode not in ("L", "RGB"):
        img = img.convert("RGB")
    arr = np.asarray(img, dtype=np.uint8)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    return [arr]


def _lower_encode_jpeg(ctx, op, inputs):
    try:
        from PIL import Image
    except ImportError as e:
        raise NotImplementedError(
            "encode_jpeg needs Pillow on the host (pip install pillow).") \
            from e
    import io as _io

    arr = np.asarray(inputs[0], dtype=np.uint8)
    img = Image.fromarray(arr[:, :, 0] if arr.shape[-1] == 1 else arr)
    buf = _io.BytesIO()
    img.save(buf, format="JPEG", quality=int(op.attrs.get("quality", 95)))
    return [np.asarray(buf.getvalue(), dtype=object)]


op_registry.register("DecodeJpeg", lower=_lower_decode_jpeg,
                     is_stateful=True, runs_on_host=True)
op_registry.register("EncodeJpeg", lower=_lower_encode_jpeg,
                     is_stateful=True, runs_on_host=True)


# -- public API --------------------------------------------------------------

def resize_images(images, size, method=ResizeMethod.BILINEAR,
                  align_corners=False):
    """(ref: image_ops_impl.py:684 ``resize_images``)."""
    x = ops_mod.convert_to_tensor(images)
    from ..framework import constant_op

    if isinstance(size, ops_mod.Tensor):
        size = constant_op.constant_value(size)
    size = tuple(int(s) for s in np.ravel(size))
    return make_op("ResizeImages", [x], attrs={"size": size,
                                               "method": int(method),
                                               "align_corners": align_corners})


def resize_bilinear(images, size, align_corners=False, name=None):
    return resize_images(images, size, ResizeMethod.BILINEAR, align_corners)


def resize_nearest_neighbor(images, size, align_corners=False, name=None):
    return resize_images(images, size, ResizeMethod.NEAREST_NEIGHBOR,
                         align_corners)


def resize_image_with_crop_or_pad(image, target_height, target_width):
    x = ops_mod.convert_to_tensor(image)
    h = x.shape[-3].value
    w = x.shape[-2].value
    if h > target_height:
        x = crop_to_bounding_box(x, (h - target_height) // 2, 0,
                                 target_height, w)
        h = target_height
    if w > target_width:
        x = crop_to_bounding_box(x, 0, (w - target_width) // 2, h,
                                 target_width)
        w = target_width
    if h < target_height or w < target_width:
        x = pad_to_bounding_box(x, (target_height - h) // 2,
                                (target_width - w) // 2, target_height,
                                target_width)
    return x


def rgb_to_grayscale(images, name=None):
    return unary("RGBToGrayscale", images, name)


def grayscale_to_rgb(images, name=None):
    return unary("GrayscaleToRGB", images, name)


def rgb_to_hsv(images, name=None):
    return unary("RGBToHSV", images, name)


def hsv_to_rgb(images, name=None):
    return unary("HSVToRGB", images, name)


def adjust_brightness(image, delta):
    return unary("AdjustBrightness", image, attrs={"delta": float(delta)})


def adjust_contrast(images, contrast_factor):
    return unary("AdjustContrast", images,
                 attrs={"contrast_factor": float(contrast_factor)})


def adjust_hue(image, delta, name=None):
    return unary("AdjustHue", image, name, attrs={"delta": float(delta)})


def adjust_saturation(image, saturation_factor, name=None):
    return unary("AdjustSaturation", image, name,
                 attrs={"factor": float(saturation_factor)})


def adjust_gamma(image, gamma=1, gain=1):
    x = ops_mod.convert_to_tensor(image)
    return math_ops.multiply(
        math_ops.pow(math_ops.cast(x, "float32"),
                     ops_mod.convert_to_tensor(float(gamma))),
        ops_mod.convert_to_tensor(float(gain)))


def per_image_standardization(image):
    return unary("PerImageStandardization", image)


def flip_left_right(image):
    return unary("FlipLeftRight", image)


def flip_up_down(image):
    return unary("FlipUpDown", image)


def rot90(image, k=1, name=None):
    return unary("Rot90", image, name, attrs={"k": int(k)})


def transpose_image(image):
    x = ops_mod.convert_to_tensor(image)
    if x.shape.rank == 4:
        return array_ops.transpose(x, [0, 2, 1, 3])
    return array_ops.transpose(x, [1, 0, 2])


def random_flip_left_right(image, seed=None):
    return _random_flip_op(image, -2, seed)


def random_flip_up_down(image, seed=None):
    return _random_flip_op(image, -3, seed)


def _random_flip_op(image, axis, seed):
    x = ops_mod.convert_to_tensor(image)
    g = ops_mod.get_default_graph()
    graph_seed, op_seed = random_seed_mod.get_seed(seed)
    op = g.create_op("RandomFlip", [x],
                     attrs={"axis": axis, "seed": op_seed,
                            "_graph_seed": graph_seed},
                     name="random_flip",
                     output_specs=[(x.shape, x.dtype)])
    return op.outputs[0]


def random_brightness(image, max_delta, seed=None):
    from . import random_ops

    delta = random_ops.random_uniform([], -max_delta, max_delta, seed=seed)
    x = ops_mod.convert_to_tensor(image)
    return math_ops.cast(math_ops.add(math_ops.cast(x, "float32"), delta),
                         x.dtype.base_dtype)


def random_contrast(image, lower, upper, seed=None):
    from . import random_ops

    factor = random_ops.random_uniform([], lower, upper, seed=seed)
    x = ops_mod.convert_to_tensor(image)
    xf = math_ops.cast(x, "float32")
    mean = math_ops.reduce_mean(xf, axis=[-3, -2], keepdims=True)
    return math_ops.cast((xf - mean) * factor + mean, x.dtype.base_dtype)


def crop_to_bounding_box(image, offset_height, offset_width, target_height,
                         target_width):
    return unary("CropToBoundingBox", image,
                 attrs={"offset_height": int(offset_height),
                        "offset_width": int(offset_width),
                        "target_height": int(target_height),
                        "target_width": int(target_width)})


def pad_to_bounding_box(image, offset_height, offset_width, target_height,
                        target_width):
    return unary("PadToBoundingBox", image,
                 attrs={"offset_height": int(offset_height),
                        "offset_width": int(offset_width),
                        "target_height": int(target_height),
                        "target_width": int(target_width)})


def central_crop(image, central_fraction):
    return unary("CentralCrop", image,
                 attrs={"fraction": float(central_fraction)})


def convert_image_dtype(image, dtype, saturate=False, name=None):
    x = ops_mod.convert_to_tensor(image)
    dt = dtypes_mod.as_dtype(dtype)
    if x.dtype.base_dtype == dt:
        return x
    return unary("ConvertImageDtype", x, name,
                 attrs={"dtype": dt, "saturate": saturate})


def decode_png(contents, channels=0, dtype=dtypes_mod.uint8, name=None):
    t = ops_mod.convert_to_tensor(contents)
    g = ops_mod.get_default_graph()
    op = g.create_op("DecodePng", [t], attrs={"channels": channels},
                     name=name or "DecodePng",
                     output_specs=[(shape_mod.TensorShape([None, None, None]),
                                    dtypes_mod.as_dtype(dtype))])
    return op.outputs[0]


def encode_png(image, compression=-1, name=None):
    t = ops_mod.convert_to_tensor(image)
    g = ops_mod.get_default_graph()
    op = g.create_op("EncodePng", [t], attrs={},
                     name=name or "EncodePng",
                     output_specs=[(shape_mod.scalar(), dtypes_mod.string)])
    return op.outputs[0]


def decode_jpeg(contents, channels=0, ratio=1, fancy_upscaling=True,
                try_recover_truncated=False, acceptable_fraction=1,
                dct_method="", name=None):
    t = ops_mod.convert_to_tensor(contents)
    g = ops_mod.get_default_graph()
    op = g.create_op("DecodeJpeg", [t], attrs={"channels": channels},
                     name=name or "DecodeJpeg",
                     output_specs=[(shape_mod.TensorShape([None, None, None]),
                                    dtypes_mod.uint8)])
    return op.outputs[0]


def encode_jpeg(image, format="", quality=95, progressive=False,
                optimize_size=False, chroma_downsampling=True,
                density_unit="in", x_density=300, y_density=300,
                xmp_metadata="", name=None):
    """(ref: python/ops/image_ops_impl.py ``encode_jpeg``,
    core/kernels/encode_jpeg_op.cc)."""
    t = ops_mod.convert_to_tensor(image)
    g = ops_mod.get_default_graph()
    op = g.create_op("EncodeJpeg", [t], attrs={"quality": int(quality)},
                     name=name or "EncodeJpeg",
                     output_specs=[(shape_mod.scalar(), dtypes_mod.string)])
    return op.outputs[0]


def _lower_decode_image(ctx, op, inputs):
    """Sniff the container by magic bytes and route to the right decoder
    (ref: core/kernels/decode_image_op.cc does the same)."""
    data = _jpeg_bytes(inputs[0])
    if data[:3] == b"\xff\xd8\xff":
        return _lower_decode_jpeg(ctx, op, inputs)
    if data[:8] == b"\x89PNG\r\n\x1a\n":
        return _lower_decode_png(ctx, op, inputs)
    if data[:3] == b"GIF" or data[:2] == b"BM":
        return _lower_decode_jpeg(ctx, op, inputs)  # PIL handles both
    raise errors_mod.InvalidArgumentError(
        None, op, "decode_image: unrecognized image container (expected "
        "JPEG/PNG/GIF/BMP magic bytes)")


op_registry.register("DecodeImage", lower=_lower_decode_image,
                     is_stateful=True, runs_on_host=True)


def decode_image(contents, channels=None, name=None):
    t = ops_mod.convert_to_tensor(contents)
    g = ops_mod.get_default_graph()
    op = g.create_op("DecodeImage", [t], attrs={"channels": channels or 0},
                     name=name or "DecodeImage",
                     output_specs=[(shape_mod.TensorShape([None, None, None]),
                                    dtypes_mod.uint8)])
    return op.outputs[0]


def random_crop(value, size, seed=None, name=None):
    from . import random_ops

    return random_ops.random_crop(value, size, seed, name)


def total_variation(images, name=None):
    x = ops_mod.convert_to_tensor(images)
    dh = x[..., 1:, :, :] - x[..., :-1, :, :]
    dw = x[..., :, 1:, :] - x[..., :, :-1, :]
    axes = list(range(1, x.shape.rank)) if x.shape.rank == 4 else None
    return math_ops.reduce_sum(math_ops.abs(dh), axis=axes) + \
        math_ops.reduce_sum(math_ops.abs(dw), axis=axes)


def _lower_sample_distorted_bbox(ctx, op, inputs):
    """Host-stage crop-geometry sampler (the reference runs this on CPU
    too — ref core/kernels/sample_distorted_bounding_box_op.cc). Output
    SIZE is data-dependent by design, so this op can only feed host-side
    consumers (decode→crop pipelines); the device graph sees the cropped
    tensor after a static resize, exactly like the reference's input
    pipeline."""
    image_size = np.asarray(inputs[0]).ravel()
    boxes = np.asarray(inputs[1], dtype=np.float32).reshape(-1, 4)
    h, w = int(image_size[0]), int(image_size[1])
    depth = int(image_size[2]) if image_size.size > 2 else 1
    min_cov = float(op.attrs.get("min_object_covered", 0.1))
    ar_lo, ar_hi = op.attrs.get("aspect_ratio_range", (0.75, 1.33))
    area_lo, area_hi = op.attrs.get("area_range", (0.05, 1.0))
    attempts = int(op.attrs.get("max_attempts", 100))
    use_whole = bool(op.attrs.get("use_image_if_no_bounding_boxes", False))
    if boxes.size == 0:
        if not use_whole:
            raise errors_mod.InvalidArgumentError(
                None, None,
                "sample_distorted_bounding_box: no bounding boxes supplied "
                "and use_image_if_no_bounding_boxes=False")
        boxes = np.array([[0.0, 0.0, 1.0, 1.0]], np.float32)
    # RNG state lives in graph-scoped storage: dies with the graph, never
    # shared across graph rebuilds, and a fresh seed attr always takes
    # effect (Operation uses __slots__, so no per-op attribute).
    rngs = op.graph._scoped_state.setdefault("__sdbb_rngs__", {})
    rng = rngs.get(op.name)
    if rng is None:
        seeds = op.attrs.get("seeds")  # (graph_seed, op_seed) or None
        rng = np.random.RandomState(
            None if seeds is None else (seeds[0] * 0x9E3779B9 + seeds[1])
            % (2 ** 32))
        rngs[op.name] = rng
    best = None
    for _ in range(attempts):
        ar = rng.uniform(ar_lo, ar_hi)
        area = rng.uniform(area_lo, area_hi) * h * w
        cw = int(round(np.sqrt(area * ar)))
        ch = int(round(np.sqrt(area / ar)))
        if cw < 1 or ch < 1 or cw > w or ch > h:
            continue
        y0 = rng.randint(0, h - ch + 1)
        x0 = rng.randint(0, w - cw + 1)
        crop = np.array([y0 / h, x0 / w, (y0 + ch) / h, (x0 + cw) / w],
                        np.float32)
        # min_object_covered: the crop must contain at least this fraction
        # of some input box's area
        iy = np.maximum(0.0, np.minimum(crop[2], boxes[:, 2])
                        - np.maximum(crop[0], boxes[:, 0]))
        ix = np.maximum(0.0, np.minimum(crop[3], boxes[:, 3])
                        - np.maximum(crop[1], boxes[:, 1]))
        cover = iy * ix / np.maximum(
            (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1]), 1e-9)
        if min_cov == 0.0 or np.any(cover >= min_cov):
            best = (y0, x0, ch, cw, crop)
            break
    if best is None:
        best = (0, 0, h, w, np.array([0, 0, 1, 1], np.float32))
    y0, x0, ch, cw, crop = best
    begin = np.array([y0, x0, 0], np.int64)
    size = np.array([ch, cw, depth], np.int64)
    return [begin, size, crop.reshape(1, 1, 4)]


op_registry.register("SampleDistortedBoundingBox",
                     lower=_lower_sample_distorted_bbox,
                     is_stateful=True, runs_on_host=True, n_outputs=3)


def sample_distorted_bounding_box(image_size, bounding_boxes, seed=None,
                                  min_object_covered=0.1,
                                  aspect_ratio_range=(0.75, 1.33),
                                  area_range=(0.05, 1.0), max_attempts=100,
                                  use_image_if_no_bounding_boxes=False,
                                  name=None, **kwargs):
    """(ref: image_ops_impl.py ``sample_distorted_bounding_box``,
    core/kernels/sample_distorted_bounding_box_op.cc). Host-stage op: the
    sampled begin/size feed host-side slice+resize in the input pipeline
    (crop geometry is data-dependent, so it cannot live in the XLA step)."""
    g = ops_mod.get_default_graph()
    inputs = [ops_mod.convert_to_tensor(image_size, dtype="int32"),
              ops_mod.convert_to_tensor(bounding_boxes, dtype="float32")]
    g_seed, op_seed = random_seed_mod.get_seed(seed)
    seeds = (None if g_seed is None and op_seed is None
             else (int(g_seed or 0), int(op_seed or 0)))
    op = g.create_op(
        "SampleDistortedBoundingBox", inputs,
        attrs={"seeds": seeds,
               "min_object_covered": float(min_object_covered),
               "aspect_ratio_range": tuple(aspect_ratio_range),
               "area_range": tuple(area_range),
               "max_attempts": int(max_attempts),
               "use_image_if_no_bounding_boxes":
                   bool(use_image_if_no_bounding_boxes)},
        name=name or "SampleDistortedBoundingBox",
        output_specs=[
            (shape_mod.TensorShape([3]), dtypes_mod.int64),
            (shape_mod.TensorShape([3]), dtypes_mod.int64),
            (shape_mod.TensorShape([1, 1, 4]), dtypes_mod.float32)])
    return op.outputs[0], op.outputs[1], op.outputs[2]


def _nms_host(boxes, scores, max_output_size=0, iou_threshold=0.5):
    boxes = np.asarray(boxes, np.float32)
    scores = np.asarray(scores, np.float32)
    order = np.argsort(-scores, kind="stable")
    keep = []
    y1, x1, y2, x2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = np.maximum(y2 - y1, 0) * np.maximum(x2 - x1, 0)
    for i in order:
        ok = True
        for j in keep:
            iy = (min(y2[i], y2[j]) - max(y1[i], y1[j]))
            ix = (min(x2[i], x2[j]) - max(x1[i], x1[j]))
            inter = max(iy, 0.0) * max(ix, 0.0)
            union = area[i] + area[j] - inter
            if union > 0 and inter / union > iou_threshold:
                ok = False
                break
        if ok:
            keep.append(int(i))
            if len(keep) >= max_output_size:
                break
    return np.asarray(keep, np.int32)


op_registry.register(
    "NonMaxSuppression",
    lower=lambda ctx, op, inputs: [_nms_host(
        inputs[0], inputs[1], op.attrs["max_output_size"],
        op.attrs["iou_threshold"])],
    is_stateful=True, runs_on_host=True, n_outputs=1)


def non_max_suppression(boxes, scores, max_output_size, iou_threshold=0.5,
                        name=None):
    """Greedy IoU suppression (ref: core/kernels/non_max_suppression_op.cc
    — a CPU kernel there too). Host stage: the output length is
    data-dependent, which XLA cannot express; fixed-size padded on-device
    NMS is available by padding the result with stf.pad."""
    b = ops_mod.convert_to_tensor(boxes, dtype=dtypes_mod.float32)
    s = ops_mod.convert_to_tensor(scores, dtype=dtypes_mod.float32)
    g = ops_mod.get_default_graph()
    op = g.create_op(
        "NonMaxSuppression", [b, s],
        attrs={"max_output_size": int(max_output_size),
               "iou_threshold": float(iou_threshold)},
        name=name or "NonMaxSuppression",
        output_specs=[(shape_mod.TensorShape([None]), dtypes_mod.int32)])
    return op.outputs[0]


def _draw_boxes_impl(images, boxes):
    """Paint 1-px box borders (ref: core/kernels/draw_bounding_box_op.cc;
    colors cycle through the reference's palette, first = red)."""
    imgs = images.astype(jnp.float32)
    b, h, w, _c = imgs.shape
    palette = jnp.asarray([[1.0, 0.0, 0.0], [0.0, 1.0, 0.0],
                           [0.0, 0.0, 1.0], [1.0, 1.0, 0.0]])
    ys = jnp.arange(h, dtype=jnp.float32)[None, :, None] / max(h - 1, 1)
    xs = jnp.arange(w, dtype=jnp.float32)[None, None, :] / max(w - 1, 1)
    out = imgs
    n_boxes = boxes.shape[1]
    for k in range(n_boxes):
        y1, x1, y2, x2 = (boxes[:, k, 0][:, None, None],
                          boxes[:, k, 1][:, None, None],
                          boxes[:, k, 2][:, None, None],
                          boxes[:, k, 3][:, None, None])
        px = 1.0 / max(h - 1, 1)
        py = 1.0 / max(w - 1, 1)
        inside = ((ys >= y1) & (ys <= y2) & (xs >= x1) & (xs <= x2))
        border = inside & ((jnp.abs(ys - y1) <= px) | (jnp.abs(ys - y2) <= px)
                           | (jnp.abs(xs - x1) <= py)
                           | (jnp.abs(xs - x2) <= py))
        color = palette[k % palette.shape[0]]
        scale = (255.0 if images.dtype != jnp.float32
                 and jnp.issubdtype(images.dtype, jnp.integer) else 1.0)
        out = jnp.where(border[..., None], color * scale, out)
    return out.astype(images.dtype)


op_registry.register_pure("DrawBoundingBoxes", _draw_boxes_impl)


def draw_bounding_boxes(images, boxes, name=None):
    """images [B,H,W,C] float; boxes [B,N,4] normalized (y1,x1,y2,x2)."""
    x = ops_mod.convert_to_tensor(images)
    bx = ops_mod.convert_to_tensor(boxes, dtype=dtypes_mod.float32)
    return make_op("DrawBoundingBoxes", [x, bx], name=name)


def resize_area(images, size, align_corners=False, name=None):
    """(ref: image_ops resize AREA method — approximated by the linear
    antialiased resize, the same family of averaging filters)."""
    return resize_images(images, size, ResizeMethod.AREA)


def resize_bicubic(images, size, align_corners=False, name=None):
    return resize_images(images, size, ResizeMethod.BICUBIC)


def random_hue(image, max_delta, seed=None):
    """(ref: image_ops.py ``random_hue``)."""
    if max_delta < 0 or max_delta > 0.5:
        raise ValueError("max_delta must be in [0, 0.5]")
    from . import random_ops

    delta = random_ops.random_uniform([], -max_delta, max_delta, seed=seed)
    return _adjust_hue_dynamic(image, delta)


def random_saturation(image, lower, upper, seed=None):
    """(ref: image_ops.py ``random_saturation``)."""
    if lower < 0 or lower >= upper:
        raise ValueError("need 0 <= lower < upper")
    from . import random_ops

    factor = random_ops.random_uniform([], lower, upper, seed=seed)
    return _adjust_saturation_dynamic(image, factor)


op_registry.register_pure(
    "AdjustHueDyn",
    lambda x, delta: _hsv_shift(x, delta, None))
op_registry.register_pure(
    "AdjustSaturationDyn",
    lambda x, factor: _hsv_shift(x, None, factor))


def _hsv_shift(x, delta, factor):
    xf = x.astype(jnp.float32)
    scale = (255.0 if jnp.issubdtype(x.dtype, jnp.integer) else 1.0)
    hsv = _rgb_to_hsv(xf / scale)
    h, s, v = hsv[..., 0], hsv[..., 1], hsv[..., 2]
    if delta is not None:
        h = jnp.mod(h + delta, 1.0)
    if factor is not None:
        s = jnp.clip(s * factor, 0.0, 1.0)
    out = _hsv_to_rgb(jnp.stack([h, s, v], axis=-1)) * scale
    return out.astype(x.dtype)


def _adjust_hue_dynamic(image, delta_t):
    x = ops_mod.convert_to_tensor(image)
    return make_op("AdjustHueDyn", [x, delta_t], name="adjust_hue_dyn")


def _adjust_saturation_dynamic(image, factor_t):
    x = ops_mod.convert_to_tensor(image)
    return make_op("AdjustSaturationDyn", [x, factor_t],
                   name="adjust_sat_dyn")


def _crop_and_resize_impl(image, boxes, box_ind, crop_size=None,
                          method="bilinear", extrapolation_value=0.0):
    """Per-box bilinear crop (ref: core/kernels/crop_and_resize_op.cc).
    Static crop_size + vmap over boxes: one fused XLA program."""
    ch, cw = crop_size
    imgs = image.astype(jnp.float32)
    _b, h, w, _c = imgs.shape

    def one(box, ind):
        y1, x1, y2, x2 = box[0], box[1], box[2], box[3]
        ys = (y1 * (h - 1)
              + jnp.arange(ch, dtype=jnp.float32)
              * (y2 - y1) * (h - 1) / max(ch - 1, 1))
        xs = (x1 * (w - 1)
              + jnp.arange(cw, dtype=jnp.float32)
              * (x2 - x1) * (w - 1) / max(cw - 1, 1))
        img = imgs[ind]
        y0 = jnp.clip(jnp.floor(ys), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xs), 0, w - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1).astype(jnp.int32)
        x1i = jnp.clip(x0 + 1, 0, w - 1).astype(jnp.int32)
        y0i, x0i = y0.astype(jnp.int32), x0.astype(jnp.int32)
        wy = (ys - y0)[:, None, None]
        wx = (xs - x0)[None, :, None]
        tl = img[y0i][:, x0i]
        tr = img[y0i][:, x1i]
        bl = img[y1i][:, x0i]
        br = img[y1i][:, x1i]
        out = (tl * (1 - wy) * (1 - wx) + tr * (1 - wy) * wx
               + bl * wy * (1 - wx) + br * wy * wx)
        inb = (((ys >= 0) & (ys <= h - 1))[:, None, None]
               & ((xs >= 0) & (xs <= w - 1))[None, :, None])
        return jnp.where(inb, out, extrapolation_value)

    return jax.vmap(one)(boxes, box_ind)


op_registry.register_pure("CropAndResize", _crop_and_resize_impl)


def crop_and_resize(image, boxes, box_ind, crop_size, method="bilinear",
                    extrapolation_value=0.0, name=None):
    """image [B,H,W,C]; boxes [N,4] normalized; box_ind [N] -> [N,ch,cw,C]."""
    x = ops_mod.convert_to_tensor(image)
    b = ops_mod.convert_to_tensor(boxes, dtype=dtypes_mod.float32)
    bi = ops_mod.convert_to_tensor(box_ind, dtype=dtypes_mod.int32)
    n = b.shape[0].value
    c = x.shape[3].value
    g = ops_mod.get_default_graph()
    op = g.create_op(
        "CropAndResize", [x, b, bi],
        attrs={"crop_size": (int(crop_size[0]), int(crop_size[1])),
               "method": method,
               "extrapolation_value": float(extrapolation_value)},
        name=name or "CropAndResize",
        output_specs=[(shape_mod.TensorShape(
            [n, int(crop_size[0]), int(crop_size[1]), c]),
            dtypes_mod.float32)])
    return op.outputs[0]


def _extract_glimpse_impl(images, offsets, size=None, centered=True,
                          normalized=True):
    """(ref: core/kernels/attention_ops.cc ExtractGlimpse) — fixed-size
    windows around per-image offsets; out-of-bounds filled with zeros
    (the reference fills with noise; zeros keep the op deterministic)."""
    gh, gw = size
    imgs = images.astype(jnp.float32)
    _b, h, w, _c = imgs.shape

    def one(img, off):
        oy, ox = off[0], off[1]
        if normalized:
            oy = oy * h
            ox = ox * w
        if centered:
            oy = (oy + h) / 2.0
            ox = (ox + w) / 2.0
        y0 = oy - gh / 2.0
        x0 = ox - gw / 2.0
        ys = (y0 + jnp.arange(gh, dtype=jnp.float32)).astype(jnp.int32)
        xs = (x0 + jnp.arange(gw, dtype=jnp.float32)).astype(jnp.int32)
        inb = (((ys >= 0) & (ys < h))[:, None, None]
               & ((xs >= 0) & (xs < w))[None, :, None])
        ysc = jnp.clip(ys, 0, h - 1)
        xsc = jnp.clip(xs, 0, w - 1)
        return jnp.where(inb, img[ysc][:, xsc], 0.0)

    return jax.vmap(one)(imgs, offsets.astype(jnp.float32))


op_registry.register_pure("ExtractGlimpse", _extract_glimpse_impl)


def extract_glimpse(input, size, offsets, centered=True,  # noqa: A002
                    normalized=True, uniform_noise=False, name=None):
    x = ops_mod.convert_to_tensor(input)
    off = ops_mod.convert_to_tensor(offsets, dtype=dtypes_mod.float32)
    b = x.shape[0].value
    c = x.shape[3].value
    g = ops_mod.get_default_graph()
    op = g.create_op(
        "ExtractGlimpse", [x, off],
        attrs={"size": (int(size[0]), int(size[1])),
               "centered": bool(centered),
               "normalized": bool(normalized)},
        name=name or "ExtractGlimpse",
        output_specs=[(shape_mod.TensorShape(
            [b, int(size[0]), int(size[1]), c]), dtypes_mod.float32)])
    return op.outputs[0]


def _lower_decode_gif(ctx, op, inputs):
    """Host GIF decode via PIL (ref: core/kernels/decode_gif_op.cc);
    returns all frames [num_frames, H, W, 3]."""
    from PIL import Image, ImageSequence
    import io as _io

    img = Image.open(_io.BytesIO(_jpeg_bytes(inputs[0])))
    frames = [np.asarray(f.convert("RGB"), np.uint8)
              for f in ImageSequence.Iterator(img)]
    return [np.stack(frames)]


op_registry.register("DecodeGif", lower=_lower_decode_gif,
                     is_stateful=True, runs_on_host=True)


def decode_gif(contents, name=None):
    x = ops_mod.convert_to_tensor(contents)
    g = ops_mod.get_default_graph()
    op = g.create_op(
        "DecodeGif", [x], attrs={}, name=name or "DecodeGif",
        output_specs=[(shape_mod.TensorShape([None, None, None, 3]),
                       dtypes_mod.uint8)])
    return op.outputs[0]
