"""Initializers (ref: tensorflow/python/ops/init_ops.py).

Same surface as the reference; each initializer returns a graph tensor built
from random/constant ops, so initialization runs on-device inside the
variables-init XLA program (the reference materializes on CPU then copies).
"""

from __future__ import annotations

import math

import numpy as np

from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from ..framework import constant_op
from . import random_ops


class Initializer:
    def __call__(self, shape, dtype=None, partition_info=None):
        raise NotImplementedError

    def get_config(self):
        return {}


class Zeros(Initializer):
    def __init__(self, dtype=dtypes_mod.float32):
        self.dtype = dtypes_mod.as_dtype(dtype)

    def __call__(self, shape, dtype=None, partition_info=None):
        from . import array_ops

        return array_ops.zeros(shape, dtype or self.dtype)


class Ones(Initializer):
    def __init__(self, dtype=dtypes_mod.float32):
        self.dtype = dtypes_mod.as_dtype(dtype)

    def __call__(self, shape, dtype=None, partition_info=None):
        from . import array_ops

        return array_ops.ones(shape, dtype or self.dtype)


class Constant(Initializer):
    def __init__(self, value=0, dtype=dtypes_mod.float32, verify_shape=False):
        self.value = value
        self.dtype = dtypes_mod.as_dtype(dtype)

    def __call__(self, shape, dtype=None, partition_info=None):
        dt = dtypes_mod.as_dtype(dtype or self.dtype)
        arr = np.asarray(self.value, dtype=dt.np_dtype)
        if arr.shape == ():
            arr = np.full(tuple(int(s) for s in shape), arr, dtype=dt.np_dtype)
        else:
            arr = arr.reshape(tuple(int(s) for s in shape))
        return constant_op.constant(arr)


class RandomUniform(Initializer):
    def __init__(self, minval=-0.05, maxval=0.05, seed=None,
                 dtype=dtypes_mod.float32):
        self.minval, self.maxval, self.seed = minval, maxval, seed
        self.dtype = dtypes_mod.as_dtype(dtype)

    def __call__(self, shape, dtype=None, partition_info=None):
        return random_ops.random_uniform(shape, self.minval, self.maxval,
                                         dtype or self.dtype, seed=self.seed)


class RandomNormal(Initializer):
    def __init__(self, mean=0.0, stddev=1.0, seed=None, dtype=dtypes_mod.float32):
        self.mean, self.stddev, self.seed = mean, stddev, seed
        self.dtype = dtypes_mod.as_dtype(dtype)

    def __call__(self, shape, dtype=None, partition_info=None):
        return random_ops.random_normal(shape, self.mean, self.stddev,
                                        dtype or self.dtype, seed=self.seed)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, stddev=1.0, seed=None, dtype=dtypes_mod.float32):
        self.mean, self.stddev, self.seed = mean, stddev, seed
        self.dtype = dtypes_mod.as_dtype(dtype)

    def __call__(self, shape, dtype=None, partition_info=None):
        return random_ops.truncated_normal(shape, self.mean, self.stddev,
                                           dtype or self.dtype, seed=self.seed)


class UniformUnitScaling(Initializer):
    def __init__(self, factor=1.0, seed=None, dtype=dtypes_mod.float32):
        self.factor, self.seed = factor, seed
        self.dtype = dtypes_mod.as_dtype(dtype)

    def __call__(self, shape, dtype=None, partition_info=None):
        input_size = 1.0
        for dim in shape[:-1]:
            input_size *= float(dim)
        maxv = math.sqrt(3 / max(1.0, input_size)) * self.factor
        return random_ops.random_uniform(shape, -maxv, maxv,
                                         dtype or self.dtype, seed=self.seed)


class VarianceScaling(Initializer):
    """(ref: init_ops.py ``variance_scaling_initializer``)."""

    def __init__(self, scale=1.0, mode="fan_in", distribution="truncated_normal",
                 seed=None, dtype=dtypes_mod.float32):
        if mode not in ("fan_in", "fan_out", "fan_avg"):
            raise ValueError(f"bad mode {mode}")
        self.scale, self.mode, self.distribution = scale, mode, distribution
        self.seed = seed
        self.dtype = dtypes_mod.as_dtype(dtype)

    def __call__(self, shape, dtype=None, partition_info=None):
        fan_in, fan_out = _compute_fans(shape)
        scale = self.scale
        if self.mode == "fan_in":
            scale /= max(1.0, fan_in)
        elif self.mode == "fan_out":
            scale /= max(1.0, fan_out)
        else:
            scale /= max(1.0, (fan_in + fan_out) / 2.0)
        if self.distribution in ("truncated_normal", "normal"):
            stddev = math.sqrt(scale) / 0.87962566103423978
            return random_ops.truncated_normal(shape, 0.0, stddev,
                                               dtype or self.dtype, self.seed)
        limit = math.sqrt(3.0 * scale)
        return random_ops.random_uniform(shape, -limit, limit,
                                         dtype or self.dtype, self.seed)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, seed=None, dtype=dtypes_mod.float32):
        self.gain, self.seed = gain, seed
        self.dtype = dtypes_mod.as_dtype(dtype)

    def __call__(self, shape, dtype=None, partition_info=None):
        dt = dtypes_mod.as_dtype(dtype or self.dtype)
        shape = tuple(int(s) for s in shape)
        if len(shape) < 2:
            raise ValueError("Orthogonal init needs rank >= 2")
        rng = np.random.RandomState(self.seed if self.seed is not None else 0)
        num_rows = int(np.prod(shape[:-1]))
        num_cols = shape[-1]
        a = rng.normal(size=(max(num_rows, num_cols), min(num_rows, num_cols)))
        q, r = np.linalg.qr(a)
        q *= np.sign(np.diag(r))
        if num_rows < num_cols:
            q = q.T
        return constant_op.constant(
            (self.gain * q[:num_rows, :num_cols]).reshape(shape)
            .astype(dt.np_dtype))


class Identity(Initializer):
    def __init__(self, gain=1.0, dtype=dtypes_mod.float32):
        self.gain = gain
        self.dtype = dtypes_mod.as_dtype(dtype)

    def __call__(self, shape, dtype=None, partition_info=None):
        dt = dtypes_mod.as_dtype(dtype or self.dtype)
        if len(shape) != 2:
            raise ValueError("Identity init needs rank 2")
        return constant_op.constant(
            self.gain * np.eye(int(shape[0]), int(shape[1]),
                               dtype=dt.np_dtype))


def _compute_fans(shape):
    shape = [int(s) for s in shape]
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = 1
    for dim in shape[:-2]:
        receptive *= dim
    return shape[-2] * receptive, shape[-1] * receptive


# reference-style lowercase aliases
zeros_initializer = Zeros
ones_initializer = Ones
constant_initializer = Constant
random_uniform_initializer = RandomUniform
random_normal_initializer = RandomNormal
truncated_normal_initializer = TruncatedNormal
uniform_unit_scaling_initializer = UniformUnitScaling
orthogonal_initializer = Orthogonal
identity_initializer = Identity


def variance_scaling_initializer(scale=1.0, mode="fan_in",
                                 distribution="truncated_normal", seed=None,
                                 dtype=dtypes_mod.float32):
    return VarianceScaling(scale, mode, distribution, seed, dtype)


def glorot_uniform_initializer(seed=None, dtype=dtypes_mod.float32):
    return VarianceScaling(1.0, "fan_avg", "uniform", seed, dtype)


def glorot_normal_initializer(seed=None, dtype=dtypes_mod.float32):
    return VarianceScaling(1.0, "fan_avg", "truncated_normal", seed, dtype)


def he_uniform_initializer(seed=None, dtype=dtypes_mod.float32):
    return VarianceScaling(2.0, "fan_in", "uniform", seed, dtype)


def he_normal_initializer(seed=None, dtype=dtypes_mod.float32):
    return VarianceScaling(2.0, "fan_in", "truncated_normal", seed, dtype)


xavier_initializer = glorot_uniform_initializer
