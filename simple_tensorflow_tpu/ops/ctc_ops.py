"""CTC loss/decoder (ref: tensorflow/python/ops/ctc_ops.py,
core/kernels/ctc_loss_op.cc).

TPU-native CTC: dense-label forward algorithm in log space via lax.scan
(differentiable through jax autodiff) — no SparseTensor labels; pass dense
labels with a padding value and label_length.
"""

from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from ..framework import op_registry
from ..framework import tensor_shape as shape_mod
from .op_util import make_op

NEG_INF = -1e30


def _ctc_loss_impl(logits, labels, logit_lengths=None, label_lengths=None,
                   blank_index=0):
    """logits: [T, B, C]; labels: [B, L] dense."""
    T, B, C = logits.shape
    L = labels.shape[1]
    if label_lengths is None:
        label_lengths = jnp.full((B,), L, dtype=jnp.int32)
    logprobs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    # extended labels: blank, l1, blank, l2, ..., blank  (length 2L+1)
    ext = jnp.full((B, 2 * L + 1), blank_index, dtype=jnp.int32)
    ext = ext.at[:, 1::2].set(labels.astype(jnp.int32))
    S = 2 * L + 1
    # repeat mask: ext[s] == ext[s-2]
    same_as_prev2 = jnp.concatenate(
        [jnp.zeros((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)

    alpha0 = jnp.full((B, S), NEG_INF)
    alpha0 = alpha0.at[:, 0].set(logprobs[0, jnp.arange(B), ext[:, 0]])
    alpha0 = alpha0.at[:, 1].set(jnp.where(
        1 < 2 * label_lengths + 1,
        logprobs[0, jnp.arange(B), ext[:, 1]], NEG_INF))

    def step(alpha, lp_t):
        prev1 = jnp.concatenate([jnp.full((B, 1), NEG_INF), alpha[:, :-1]],
                                axis=1)
        prev2 = jnp.concatenate([jnp.full((B, 2), NEG_INF), alpha[:, :-2]],
                                axis=1)
        prev2 = jnp.where(same_as_prev2, NEG_INF, prev2)
        tot = jnp.logaddexp(alpha, jnp.logaddexp(prev1, prev2))
        emit = jnp.take_along_axis(lp_t, ext, axis=1)
        return tot + emit, None

    def scan_step(carry, x):
        t, alpha = carry
        lp_t = x
        new_alpha, _ = step(alpha, lp_t)
        # time masking: past logit_length, keep alpha
        keep = (t >= logit_lengths)[:, None] if logit_lengths is not None \
            else jnp.zeros((B, 1), bool)
        new_alpha = jnp.where(keep, alpha, new_alpha)
        return (t + 1, new_alpha), None

    (_, alpha_T), _ = jax.lax.scan(scan_step, (1, alpha0), logprobs[1:])
    ll = label_lengths if label_lengths is not None else jnp.full((B,), L)
    end1 = 2 * ll - 1
    end2 = 2 * ll
    idxB = jnp.arange(B)
    final = jnp.logaddexp(alpha_T[idxB, end1], alpha_T[idxB, end2])
    return -final


op_registry.register_pure("CTCLossDense", _ctc_loss_impl)


def ctc_loss(labels, inputs, sequence_length, label_length=None,
             preprocess_collapse_repeated=False, ctc_merge_repeated=True,
             time_major=True, blank_index=0, name=None):
    """Dense-label CTC (see module docstring; the reference takes a
    SparseTensor, ref ctc_ops.py:32)."""
    from ..framework.sparse_tensor import SparseTensor
    from . import sparse_ops, array_ops, math_ops

    logits = ops_mod.convert_to_tensor(inputs)
    if not time_major:
        logits = array_ops.transpose(logits, [1, 0, 2])
    if isinstance(labels, SparseTensor):
        dense = sparse_ops.sparse_tensor_to_dense(labels, default_value=-1)
        lab_len = math_ops.reduce_sum(
            math_ops.cast(math_ops.greater_equal(
                dense, array_ops.zeros_like(dense)), "int32"), axis=1)
        labels_t = math_ops.maximum(dense, array_ops.zeros_like(dense))
    else:
        labels_t = ops_mod.convert_to_tensor(labels)
        lab_len = (ops_mod.convert_to_tensor(label_length)
                   if label_length is not None else None)
    seq_len = ops_mod.convert_to_tensor(sequence_length)
    inputs_list = [logits, math_ops.cast(labels_t, "int32")]
    return make_op("CTCLossDense", inputs_list +
                   [math_ops.cast(seq_len, "int32")] +
                   ([math_ops.cast(lab_len, "int32")] if lab_len is not None else []),
                   attrs={"blank_index": blank_index}, name=name)


def _greedy_impl(logits, seq_len, merge_repeated=True, blank_index=0):
    best = jnp.argmax(logits, axis=-1)  # [T, B]
    return best.astype(jnp.int64)


op_registry.register_pure("CTCGreedyDecode", _greedy_impl)


def ctc_greedy_decoder(inputs, sequence_length, merge_repeated=True,
                       blank_index=0, name=None):
    """Returns the dense per-frame argmax path [T, B] (the reference returns
    a SparseTensor of collapsed paths; collapse host-side, it is inherently
    dynamic-shape)."""
    logits = ops_mod.convert_to_tensor(inputs)
    seq_len = ops_mod.convert_to_tensor(sequence_length)
    path = make_op("CTCGreedyDecode", [logits, seq_len],
                   attrs={"merge_repeated": merge_repeated,
                          "blank_index": blank_index}, name=name)
    return path
