"""CTC loss/decoder (ref: tensorflow/python/ops/ctc_ops.py,
core/kernels/ctc_loss_op.cc).

TPU-native CTC: dense-label forward algorithm in log space via lax.scan
(differentiable through jax autodiff) — no SparseTensor labels; pass dense
labels with a padding value and label_length.
"""

from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from ..framework import op_registry
from ..framework import tensor_shape as shape_mod
from .op_util import make_op

NEG_INF = -1e30


def _ctc_loss_impl(logits, labels, logit_lengths=None, label_lengths=None,
                   blank_index=0):
    """logits: [T, B, C]; labels: [B, L] dense."""
    T, B, C = logits.shape
    L = labels.shape[1]
    if label_lengths is None:
        label_lengths = jnp.full((B,), L, dtype=jnp.int32)
    logprobs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    # extended labels: blank, l1, blank, l2, ..., blank  (length 2L+1)
    ext = jnp.full((B, 2 * L + 1), blank_index, dtype=jnp.int32)
    ext = ext.at[:, 1::2].set(labels.astype(jnp.int32))
    S = 2 * L + 1
    # repeat mask: ext[s] == ext[s-2]
    same_as_prev2 = jnp.concatenate(
        [jnp.zeros((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)

    alpha0 = jnp.full((B, S), NEG_INF)
    alpha0 = alpha0.at[:, 0].set(logprobs[0, jnp.arange(B), ext[:, 0]])
    alpha0 = alpha0.at[:, 1].set(jnp.where(
        1 < 2 * label_lengths + 1,
        logprobs[0, jnp.arange(B), ext[:, 1]], NEG_INF))

    def step(alpha, lp_t):
        prev1 = jnp.concatenate([jnp.full((B, 1), NEG_INF), alpha[:, :-1]],
                                axis=1)
        prev2 = jnp.concatenate([jnp.full((B, 2), NEG_INF), alpha[:, :-2]],
                                axis=1)
        prev2 = jnp.where(same_as_prev2, NEG_INF, prev2)
        tot = jnp.logaddexp(alpha, jnp.logaddexp(prev1, prev2))
        emit = jnp.take_along_axis(lp_t, ext, axis=1)
        return tot + emit, None

    def scan_step(carry, x):
        t, alpha = carry
        lp_t = x
        new_alpha, _ = step(alpha, lp_t)
        # time masking: past logit_length, keep alpha
        keep = (t >= logit_lengths)[:, None] if logit_lengths is not None \
            else jnp.zeros((B, 1), bool)
        new_alpha = jnp.where(keep, alpha, new_alpha)
        return (t + 1, new_alpha), None

    (_, alpha_T), _ = jax.lax.scan(scan_step, (1, alpha0), logprobs[1:])
    ll = label_lengths if label_lengths is not None else jnp.full((B,), L)
    end1 = 2 * ll - 1
    end2 = 2 * ll
    idxB = jnp.arange(B)
    final = jnp.logaddexp(alpha_T[idxB, end1], alpha_T[idxB, end2])
    return -final


op_registry.register_pure("CTCLossDense", _ctc_loss_impl)


def ctc_loss(labels, inputs, sequence_length, label_length=None,
             preprocess_collapse_repeated=False, ctc_merge_repeated=True,
             time_major=True, blank_index=0, name=None):
    """Dense-label CTC (see module docstring; the reference takes a
    SparseTensor, ref ctc_ops.py:32)."""
    from ..framework.sparse_tensor import SparseTensor
    from . import sparse_ops, array_ops, math_ops

    logits = ops_mod.convert_to_tensor(inputs)
    if not time_major:
        logits = array_ops.transpose(logits, [1, 0, 2])
    if isinstance(labels, SparseTensor):
        dense = sparse_ops.sparse_tensor_to_dense(labels, default_value=-1)
        lab_len = math_ops.reduce_sum(
            math_ops.cast(math_ops.greater_equal(
                dense, array_ops.zeros_like(dense)), "int32"), axis=1)
        labels_t = math_ops.maximum(dense, array_ops.zeros_like(dense))
    else:
        labels_t = ops_mod.convert_to_tensor(labels)
        lab_len = (ops_mod.convert_to_tensor(label_length)
                   if label_length is not None else None)
    seq_len = ops_mod.convert_to_tensor(sequence_length)
    inputs_list = [logits, math_ops.cast(labels_t, "int32")]
    return make_op("CTCLossDense", inputs_list +
                   [math_ops.cast(seq_len, "int32")] +
                   ([math_ops.cast(lab_len, "int32")] if lab_len is not None else []),
                   attrs={"blank_index": blank_index}, name=name)


def _greedy_impl(logits, seq_len, merge_repeated=True, blank_index=0):
    best = jnp.argmax(logits, axis=-1)  # [T, B]
    return best.astype(jnp.int64)


op_registry.register_pure("CTCGreedyDecode", _greedy_impl)


def ctc_greedy_decoder(inputs, sequence_length, merge_repeated=True,
                       blank_index=0, name=None):
    """Returns the dense per-frame argmax path [T, B] (the reference returns
    a SparseTensor of collapsed paths; collapse host-side, it is inherently
    dynamic-shape)."""
    logits = ops_mod.convert_to_tensor(inputs)
    seq_len = ops_mod.convert_to_tensor(sequence_length)
    path = make_op("CTCGreedyDecode", [logits, seq_len],
                   attrs={"merge_repeated": merge_repeated,
                          "blank_index": blank_index}, name=name)
    return path


def _beam_search_impl(logits, seq_len, beam_width, top_paths, blank,
                      merge_repeated=False):
    """Host CTC prefix beam search (ref: core/util/ctc/
    ctc_beam_search.h — a CPU kernel in the reference too; decode lengths
    are data-dependent)."""
    T, B, C = logits.shape
    logp = logits - _logsumexp(logits)
    results = []
    for b in builtins.range(B):
        # beams: prefix tuple -> (logp_blank, logp_nonblank)
        beams = {(): (0.0, -np.inf)}
        for t in builtins.range(int(seq_len[b])):
            new = {}
            lp = logp[t, b]
            for prefix, (pb, pnb) in beams.items():
                total = np.logaddexp(pb, pnb)
                # extend with blank
                nb_pb, nb_pnb = new.get(prefix, (-np.inf, -np.inf))
                new[prefix] = (np.logaddexp(nb_pb, total + lp[blank]),
                               nb_pnb)
                for c in builtins.range(C):
                    if c == blank:
                        continue
                    np_prefix = prefix + (c,)
                    e_pb, e_pnb = new.get(np_prefix, (-np.inf, -np.inf))
                    if prefix and prefix[-1] == c:
                        # repeat: must cross a blank to extend
                        new[np_prefix] = (e_pb,
                                          np.logaddexp(e_pnb,
                                                       pb + lp[c]))
                        # same-prefix repeat merge
                        s_pb, s_pnb = new.get(prefix, (-np.inf, -np.inf))
                        new[prefix] = (s_pb,
                                       np.logaddexp(s_pnb, pnb + lp[c]))
                    else:
                        new[np_prefix] = (e_pb,
                                          np.logaddexp(e_pnb,
                                                       total + lp[c]))
            beams = dict(sorted(
                new.items(),
                key=lambda kv: -np.logaddexp(kv[1][0], kv[1][1])
            )[:beam_width])
        ranked = sorted(beams.items(),
                        key=lambda kv: -np.logaddexp(kv[1][0], kv[1][1]))

        def _collapse(p):
            if not merge_repeated:
                return list(p)
            out = []
            for c in p:
                if not out or out[-1] != c:
                    out.append(c)
            return out

        paths = [(_collapse(p), float(np.logaddexp(*v)))
                 for p, v in ranked[:top_paths]]
        while builtins.len(paths) < top_paths:
            paths.append(([], -np.inf))
        results.append(paths)
    # COO sparse outputs per path rank (ref output contract)
    out = []
    for k in builtins.range(top_paths):
        indices, values = [], []
        max_len = 0
        for b in builtins.range(B):
            seq = results[b][k][0]
            max_len = builtins.max(max_len, builtins.len(seq))
            for j, c in builtins.enumerate(seq):
                indices.append((b, j))
                values.append(c)
        out.append((np.asarray(indices, np.int64).reshape(-1, 2),
                    np.asarray(values, np.int64),
                    np.asarray([B, max_len], np.int64)))
    log_probs = np.asarray(
        [[results[b][k][1] for k in builtins.range(top_paths)]
         for b in builtins.range(B)], np.float32)
    flat = []
    for idx, vals, shp in out:
        flat += [idx, vals, shp]
    return flat + [log_probs]


def _logsumexp(x):
    m = x.max(axis=-1, keepdims=True)
    return m + np.log(np.exp(x - m).sum(axis=-1, keepdims=True))


def _lower_ctc_beam(ctx, op, inputs):
    return _beam_search_impl(np.asarray(inputs[0], np.float32),
                             np.asarray(inputs[1]),
                             op.attrs["beam_width"],
                             op.attrs["top_paths"],
                             op.attrs["blank_index"],
                             op.attrs.get("merge_repeated", False))


op_registry.register("CTCBeamSearch", lower=_lower_ctc_beam,
                     is_stateful=True, runs_on_host=True, n_outputs=None)


def ctc_beam_search_decoder(inputs, sequence_length, beam_width=100,
                            top_paths=1, merge_repeated=True,
                            blank_index=None, name=None):
    """(ref: ctc_ops.py ``ctc_beam_search_decoder``): returns
    (decoded COO triples list, log_probabilities [B, top_paths]).
    Host stage — decode lengths are data-dependent."""
    from ..framework import tensor_shape as shape_mod
    from ..framework.sparse_tensor import SparseTensor

    logits = ops_mod.convert_to_tensor(inputs)
    seq_len = ops_mod.convert_to_tensor(sequence_length)
    B = logits.shape[1].value
    blank = (blank_index if blank_index is not None
             else int(logits.shape[2].value) - 1)
    g = ops_mod.get_default_graph()
    specs = []
    for _ in builtins.range(top_paths):
        specs += [(shape_mod.TensorShape([None, 2]), dtypes_mod.int64),
                  (shape_mod.TensorShape([None]), dtypes_mod.int64),
                  (shape_mod.TensorShape([2]), dtypes_mod.int64)]
    specs.append((shape_mod.TensorShape([B, top_paths]),
                  dtypes_mod.float32))
    op = g.create_op("CTCBeamSearch", [logits, seq_len],
                     attrs={"beam_width": int(beam_width),
                            "top_paths": int(top_paths),
                            "blank_index": blank,
                            "merge_repeated": bool(merge_repeated)},
                     name=name or "CTCBeamSearch", output_specs=specs)
    outs = list(op.outputs)
    decoded = []
    for k in builtins.range(top_paths):
        decoded.append(SparseTensor(outs[3 * k], outs[3 * k + 1],
                                    outs[3 * k + 2]))
    return decoded, outs[-1]
