"""Linear algebra ops (ref: tensorflow/python/ops/linalg_ops.py,
core/kernels/{cholesky_op,qr_op_impl,svd_op_impl,determinant_op,
matrix_inverse_op,matrix_solve_op}.cc)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework import graph as ops_mod
from ..framework import op_registry
from .op_util import make_op, unary

op_registry.register_pure("Cholesky", jnp.linalg.cholesky)
op_registry.register_pure("MatrixDeterminant", jnp.linalg.det)
op_registry.register_pure("LogMatrixDeterminant",
                          lambda x: list(jnp.linalg.slogdet(x)), n_outputs=2)
op_registry.register_pure("MatrixInverse", lambda x, adjoint=False:
                          jnp.linalg.inv(jnp.swapaxes(jnp.conj(x), -1, -2)
                                         if adjoint else x))
op_registry.register_pure("MatrixSolve", lambda a, b, adjoint=False:
                          jnp.linalg.solve(jnp.swapaxes(jnp.conj(a), -1, -2)
                                           if adjoint else a, b))
op_registry.register_pure(
    "MatrixTriangularSolve", lambda a, b, lower=True, adjoint=False:
    jax.scipy.linalg.solve_triangular(a, b, lower=lower,
                                      trans=2 if adjoint else 0))
op_registry.register_pure("Qr", lambda x, full_matrices=False:
                          list(jnp.linalg.qr(
                              x, mode="complete" if full_matrices else "reduced")),
                          n_outputs=2)
op_registry.register_pure("Svd", lambda x, full_matrices=False, compute_uv=True:
                          _svd_impl(x, full_matrices, compute_uv),
                          n_outputs=None)
op_registry.register_pure("SelfAdjointEigV2", lambda x, compute_v=True:
                          _eigh_impl(x, compute_v), n_outputs=None)
op_registry.register_pure("MatrixSolveLs",
                          lambda a, b, l2_regularizer=0.0, fast=True:
                          _lstsq_impl(a, b, l2_regularizer))
def _cholesky_grad_impl(l, grad):
    """Reverse-mode Cholesky: given L = chol(A) and L̄, return the
    SYMMETRIZED Ā (ref: core/ops/linalg_grad: CholeskyGrad; Murray 2016
    "Differentiation of the Cholesky decomposition" eq. 8-10):
    P = Φ(Lᵀ L̄) with Φ = tril with halved diagonal; Ā = L⁻ᵀ P L⁻¹,
    symmetrized. (Round-5 conformance sweep replaced a pass-through
    stub here — validated against central differences and jax.grad.)"""
    lt_lbar = jnp.swapaxes(l, -1, -2) @ grad
    n = l.shape[-1]
    diag = jnp.diagonal(lt_lbar, axis1=-2, axis2=-1)
    p = jnp.tril(lt_lbar) - 0.5 * jnp.eye(n, dtype=l.dtype) \
        * diag[..., :, None]
    # solve L^T X = P  -> X = L^{-T} P ; then solve X L = Abar -> X L^{-1}
    x = jax.scipy.linalg.solve_triangular(jnp.swapaxes(l, -1, -2), p,
                                          lower=False)
    abar = jax.scipy.linalg.solve_triangular(
        jnp.swapaxes(l, -1, -2), jnp.swapaxes(x, -1, -2), lower=False)
    abar = jnp.swapaxes(abar, -1, -2)
    return 0.5 * (abar + jnp.swapaxes(abar, -1, -2))


op_registry.register_pure("CholeskyGrad", _cholesky_grad_impl)
op_registry.register_pure("MatrixExponential", jax.scipy.linalg.expm)


def _svd_impl(x, full_matrices, compute_uv):
    if compute_uv:
        u, s, vt = jnp.linalg.svd(x, full_matrices=full_matrices)
        # TF returns (s, u, v) with v NOT transposed.
        return [s, u, jnp.swapaxes(vt, -1, -2)]
    s = jnp.linalg.svd(x, compute_uv=False)
    return [s]


def _eigh_impl(x, compute_v):
    w, v = jnp.linalg.eigh(x)
    if compute_v:
        return [w, v]
    return [w]


def _lstsq_impl(a, b, l2):
    at = jnp.swapaxes(a, -1, -2)
    gram = at @ a + l2 * jnp.eye(a.shape[-1], dtype=a.dtype)
    return jnp.linalg.solve(gram, at @ b)


def cholesky(input, name=None):  # noqa: A002
    return unary("Cholesky", input, name)


def matrix_determinant(input, name=None):  # noqa: A002
    return unary("MatrixDeterminant", input, name)


det = matrix_determinant


def log_matrix_determinant(input, name=None):  # noqa: A002
    x = ops_mod.convert_to_tensor(input)
    sign, logdet = make_op("LogMatrixDeterminant", [x], name=name, n_out=2)
    return sign, logdet


def matrix_inverse(input, adjoint=False, name=None):  # noqa: A002
    return unary("MatrixInverse", input, name, attrs={"adjoint": adjoint})


def matrix_solve(matrix, rhs, adjoint=False, name=None):
    a = ops_mod.convert_to_tensor(matrix)
    b = ops_mod.convert_to_tensor(rhs, dtype=a.dtype.base_dtype)
    return make_op("MatrixSolve", [a, b], attrs={"adjoint": adjoint}, name=name)


def matrix_triangular_solve(matrix, rhs, lower=True, adjoint=False, name=None):
    a = ops_mod.convert_to_tensor(matrix)
    b = ops_mod.convert_to_tensor(rhs, dtype=a.dtype.base_dtype)
    return make_op("MatrixTriangularSolve", [a, b],
                   attrs={"lower": lower, "adjoint": adjoint}, name=name)


def matrix_solve_ls(matrix, rhs, l2_regularizer=0.0, fast=True, name=None):
    a = ops_mod.convert_to_tensor(matrix)
    b = ops_mod.convert_to_tensor(rhs, dtype=a.dtype.base_dtype)
    return make_op("MatrixSolveLs", [a, b],
                   attrs={"l2_regularizer": float(l2_regularizer),
                          "fast": fast}, name=name)


def qr(input, full_matrices=False, name=None):  # noqa: A002
    x = ops_mod.convert_to_tensor(input)
    q, r = make_op("Qr", [x], attrs={"full_matrices": full_matrices},
                   name=name, n_out=2)
    return q, r


def svd(tensor, full_matrices=False, compute_uv=True, name=None):
    x = ops_mod.convert_to_tensor(tensor)
    if compute_uv:
        s, u, v = make_op("Svd", [x], attrs={"full_matrices": full_matrices,
                                             "compute_uv": True},
                          name=name, n_out=3)
        return s, u, v
    (s,) = make_op("Svd", [x], attrs={"full_matrices": full_matrices,
                                      "compute_uv": False}, name=name, n_out=1)
    return s


def self_adjoint_eig(tensor, name=None):
    x = ops_mod.convert_to_tensor(tensor)
    e, v = make_op("SelfAdjointEigV2", [x], attrs={"compute_v": True},
                   name=name, n_out=2)
    return e, v


def self_adjoint_eigvals(tensor, name=None):
    x = ops_mod.convert_to_tensor(tensor)
    e = make_op("SelfAdjointEigV2", [x], attrs={"compute_v": False},
                name=name, n_out=1)
    return e


def matrix_exponential(input, name=None):  # noqa: A002
    return unary("MatrixExponential", input, name)


def norm(tensor, ord="euclidean", axis=None, keepdims=False, name=None,  # noqa: A002
         keep_dims=None):
    from . import math_ops

    if keep_dims is not None:
        keepdims = keep_dims
    x = ops_mod.convert_to_tensor(tensor)
    if ord in ("euclidean", 2, 2.0, "fro"):
        return math_ops.sqrt(math_ops.reduce_sum(
            math_ops.square(x), axis=axis, keepdims=keepdims), name=name)
    if ord in (1, 1.0):
        return math_ops.reduce_sum(math_ops.abs(x), axis=axis,
                                   keepdims=keepdims, name=name)
    if ord in (float("inf"), "inf"):
        return math_ops.reduce_max(math_ops.abs(x), axis=axis,
                                   keepdims=keepdims, name=name)
    raise ValueError(f"unsupported norm order {ord}")


def eye(*args, **kwargs):
    from . import array_ops

    return array_ops.eye(*args, **kwargs)


op_registry.register_pure(
    "CholeskySolve",
    lambda chol, rhs: __import__("jax").scipy.linalg.cho_solve(
        (chol, True), rhs))
def cholesky_solve(chol, rhs, name=None):
    """(ref: math_ops/linalg ``cholesky_solve``): solve A x = rhs given
    chol = cholesky(A) (lower)."""
    c = ops_mod.convert_to_tensor(chol)
    r = ops_mod.convert_to_tensor(rhs, dtype=c.dtype.base_dtype)
    return make_op("CholeskySolve", [c, r], name=name)
