"""Example-proto parsing ops (ref: tensorflow/python/ops/parsing_ops.py,
core/kernels/example_parsing_ops.cc).

Parsing runs in the Session's host stage (strings never enter XLA — the
reference pins these kernels to CPU for the same reason); the parsed dense
tensors are then fed into the compiled step like any other feed.
"""

from __future__ import annotations

import numpy as np

from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from ..framework import op_registry
from ..framework import sparse_tensor as sparse_mod
from ..framework import tensor_shape as shape_mod
from ..lib import example as example_mod
from ..platform import monitoring
from .op_util import make_op

# native = one C call per batch (example_parse.cc); python = per-record
# wire parsing — the classic input-pipeline bottleneck this counter
# makes visible (docs/DATA.md)
_parse_batches = monitoring.Counter(
    "/stf/data/parse_example_batches",
    "parse_example batch calls by parser path", "path")
_parse_records = monitoring.Counter(
    "/stf/data/parse_example_records",
    "Example protos parsed by parser path", "path")
_ragged_truncated = monitoring.Counter(
    "/stf/data/ragged_truncated_values",
    "Values dropped from RaggedFeature rows longer than max_len",
    "feature")


class FixedLenFeature:
    """(ref: parsing_ops.py ``FixedLenFeature``)."""

    def __init__(self, shape, dtype, default_value=None):
        self.shape = list(shape)
        self.dtype = dtypes_mod.as_dtype(dtype)
        self.default_value = default_value


class VarLenFeature:
    """(ref: parsing_ops.py ``VarLenFeature``). Parses to a dense padded
    tensor + length vector on TPU (COO SparseTensor needs dynamic shapes
    XLA can't compile); `parse_example` returns a SparseTensorValue-like
    triple via host stage."""

    def __init__(self, dtype):
        self.dtype = dtypes_mod.as_dtype(dtype)


class RaggedFeature:
    """Varlen feature parsed to a PADDED dense [batch, max_len] tensor
    plus a ``<name>_lengths`` int64 [batch] vector (ISSUE 19; DATA.md
    "ragged/varlen parsing contract") — the XLA-friendly form feeding
    ``embedding_bag`` pooled lookups, unlike VarLenFeature's dynamic COO
    triple. Rows longer than ``max_len`` are TRUNCATED (counted in
    /stf/data/ragged_truncated_values, never an error); absent features
    parse as length 0. Padding slots hold ``pad_value`` (-1 by
    convention for id features — embedding_bag masks them out).
    Batches of all-float32/int64 ragged specs parse in one C++ call
    (runtime_cc StfParseExamplesRagged)."""

    def __init__(self, dtype, max_len, pad_value=-1):
        self.dtype = dtypes_mod.as_dtype(dtype)
        if self.dtype not in (dtypes_mod.float32, dtypes_mod.int64):
            raise TypeError(
                f"RaggedFeature supports float32/int64, got {self.dtype}")
        self.max_len = int(max_len)
        if self.max_len <= 0:
            raise ValueError("RaggedFeature max_len must be positive")
        self.pad_value = pad_value


def _feature_values(feature, dtype):
    if dtype == dtypes_mod.string:
        return (np.asarray(feature.bytes_list.value, dtype=object)
                if feature.bytes_list else np.asarray([], dtype=object))
    if dtype.is_floating:
        return (np.asarray(feature.float_list.value, np.float32)
                if feature.float_list else np.zeros((0,), np.float32))
    return (np.asarray(feature.int64_list.value, np.int64)
            if feature.int64_list else np.zeros((0,), np.int64))


def _parse_examples_fast(serialized, features):
    """C++ batch fast path (ref core/util/example_proto_fast_parsing.cc):
    all-FixedLen float32/int64 specs parse in ONE native call into dense
    numpy buffers. Returns None when the spec mix doesn't qualify (string
    or VarLen features) or the native runtime isn't available."""
    from ..runtime import native

    specs = []
    for name in sorted(features):
        spec = features[name]
        if not isinstance(spec, FixedLenFeature):
            return None
        if spec.dtype == dtypes_mod.float32:
            kind = 0
        elif spec.dtype == dtypes_mod.int64:
            kind = 1
        else:
            return None
        specs.append((name, spec, kind,
                      int(np.prod(spec.shape)) if spec.shape else 1))
    # the native parser caps at 64 dense features per call
    if not specs or len(specs) > 64 or not native.available():
        return None
    serialized = [bytes(s) for s in serialized]
    try:
        arrays, missing = native.parse_examples_dense(
            serialized, [s[0] for s in specs], [s[2] for s in specs],
            [s[3] for s in specs])
    except RuntimeError:
        return None
    out = {}
    for f, (name, spec, _kind, size) in enumerate(specs):
        arr = arrays[f]
        miss = missing[:, f]
        if miss.any():
            if spec.default_value is None:
                bad = int(np.argmax(miss))
                raise ValueError(
                    f"feature {name!r} missing and no default "
                    f"(example {bad})")
            default = np.ravel(np.asarray(spec.default_value,
                                          arr.dtype))
            if default.shape[0] == 1 and size > 1:
                default = np.repeat(default, size)
            if default.shape[0] != size:
                raise ValueError(
                    f"feature {name!r}: default_value has "
                    f"{default.shape[0]} values, expected {size}")
            arr[miss] = default
        out[name] = arr.reshape([len(serialized)] + list(spec.shape or []))
    return out


def _finish_ragged(name, spec, arr, true_lens):
    """Clamp lengths to the cap, account truncations, and normalize the
    pad value (shared by the native and slow ragged paths)."""
    over = true_lens - spec.max_len
    n_trunc = int(over[over > 0].sum())
    if n_trunc:
        _ragged_truncated.get_cell(name).increase_by(n_trunc)
    lens = np.minimum(true_lens, spec.max_len).astype(np.int64)
    pad = spec.pad_value if spec.dtype == dtypes_mod.int64 else 0.0
    mask = np.arange(spec.max_len)[None, :] >= lens[:, None]
    arr[mask] = pad
    return arr, lens


def _parse_ragged(serialized, specs):
    """RaggedFeature batch parse -> ({name: padded, name_lengths: lens},
    path). One native C++ call when available, else the Python wire
    path."""
    from ..runtime import native

    names = sorted(specs)
    out = {}
    if len(names) <= 64 and native.ragged_parse_available():
        serialized = [bytes(s) for s in serialized]
        kinds = [0 if specs[n].dtype == dtypes_mod.float32 else 1
                 for n in names]
        caps = [specs[n].max_len for n in names]
        try:
            arrays, lengths = native.parse_examples_ragged(
                serialized, names, kinds, caps)
        except RuntimeError:
            arrays = None
        if arrays is not None:
            for f, n in enumerate(names):
                arr, lens = _finish_ragged(n, specs[n], arrays[f],
                                           lengths[:, f])
                out[n] = arr
                out[n + "_lengths"] = lens
            return out, "native"
    batch = [example_mod.Example.FromString(bytes(s)) for s in serialized]
    for n in names:
        spec = specs[n]
        pad = spec.pad_value if spec.dtype == dtypes_mod.int64 else 0.0
        arr = np.full((len(batch), spec.max_len), pad,
                      spec.dtype.as_numpy_dtype)
        true_lens = np.zeros((len(batch),), np.int64)
        for i, ex in enumerate(batch):
            f = ex.features.feature.get(n)
            vals = (_feature_values(f, spec.dtype) if f is not None
                    else np.zeros((0,), spec.dtype.as_numpy_dtype))
            true_lens[i] = len(vals)
            k = min(len(vals), spec.max_len)
            arr[i, :k] = vals[:k]
        arr, lens = _finish_ragged(n, spec, arr, true_lens)
        out[n] = arr
        out[n + "_lengths"] = lens
    return out, "python"


def parse_example_py(serialized, features):
    """Host parser: list[bytes] -> {name: ndarray or (indices,values,shape)}.

    FixedLenFeature -> dense [batch] + shape; VarLenFeature -> COO
    triple; RaggedFeature -> padded dense [batch, max_len] plus a
    ``<name>_lengths`` vector. All-dense float32/int64 FixedLen specs
    and all RaggedFeature specs take the native C++ batch fast paths
    (one C call each per batch); /stf/data/parse_example_* counters
    record which path served each batch.
    """
    with monitoring.traceme("parse_example_batch", n=len(serialized)):
        ragged = {k: v for k, v in features.items()
                  if isinstance(v, RaggedFeature)}
        rest = {k: v for k, v in features.items()
                if not isinstance(v, RaggedFeature)}
        out = {}
        path = None
        if ragged:
            rout, path = _parse_ragged(serialized, ragged)
            out.update(rout)
        if rest:
            fast = _parse_examples_fast(serialized, rest)
            path = "python" if fast is None else "native"
            out.update(fast if fast is not None
                       else _parse_example_slow(serialized, rest))
        if path is None:
            path = "python"
        _parse_batches.get_cell(path).increase_by(1)
        _parse_records.get_cell(path).increase_by(len(serialized))
        return out


def _parse_example_slow(serialized, features):
    batch = [example_mod.Example.FromString(bytes(s)) for s in serialized]
    out = {}
    for name, spec in features.items():
        if isinstance(spec, FixedLenFeature):
            n = int(np.prod(spec.shape)) if spec.shape else 1
            rows = []
            for ex in batch:
                f = ex.features.feature.get(name)
                vals = (_feature_values(f, spec.dtype) if f is not None
                        else np.zeros((0,),))
                if len(vals) == 0:
                    if spec.default_value is None:
                        raise ValueError(
                            f"feature {name!r} missing and no default")
                    vals = np.ravel(np.asarray(spec.default_value))
                    if vals.shape[0] == 1 and n > 1:
                        vals = np.repeat(vals, n)
                if len(vals) != n:
                    raise ValueError(
                        f"feature {name!r}: got {len(vals)} values, "
                        f"expected {n}")
                rows.append(np.reshape(vals, spec.shape))
            arr = np.stack(rows) if rows else np.zeros([0] + spec.shape)
            if spec.dtype != dtypes_mod.string:
                arr = arr.astype(spec.dtype.as_numpy_dtype)
            out[name] = arr
        elif isinstance(spec, VarLenFeature):
            indices, values = [], []
            max_len = 0
            for i, ex in enumerate(batch):
                f = ex.features.feature.get(name)
                vals = (_feature_values(f, spec.dtype) if f is not None
                        else np.zeros((0,)))
                max_len = max(max_len, len(vals))
                for j, v in enumerate(vals):
                    indices.append((i, j))
                    values.append(v)
            idx = (np.asarray(indices, np.int64) if indices
                   else np.zeros((0, 2), np.int64))
            if spec.dtype == dtypes_mod.string:
                val = np.asarray(values, dtype=object)
            else:
                val = np.asarray(values,
                                 dtype=spec.dtype.as_numpy_dtype)
            out[name] = (idx, val,
                         np.asarray([len(batch), max_len], np.int64))
        else:
            raise TypeError(f"unsupported feature spec {type(spec)}")
    return out


# -- graph ops (host stage) -------------------------------------------------

def _register_parse_op():
    def lower(ctx, op, inputs):
        (serialized,) = inputs
        feats = op.attrs["_features"]
        single = op.attrs.get("_single", False)
        parsed = parse_example_py(np.ravel(np.asarray(serialized, object)),
                                  feats)
        flat = []
        for name in sorted(feats):
            v = parsed[name]
            if isinstance(v, tuple):
                flat.extend(v)
            elif single:  # strip the synthetic batch dim on host
                flat.append(v[0])
            else:
                flat.append(v)
            if isinstance(feats[name], RaggedFeature):
                lens = parsed[name + "_lengths"]
                flat.append(lens[0] if single else lens)
        return flat

    op_registry.register("ParseExample", lower=lower, is_stateful=True,
                         runs_on_host=True, n_outputs=None)


_register_parse_op()


def _parse_example_graph(serialized, features, name, single):
    serialized = ops_mod.convert_to_tensor(serialized)
    g = ops_mod.get_default_graph()
    batch = serialized.shape[0] if serialized.shape.rank else None
    specs = []
    names = sorted(features)
    for n in names:
        spec = features[n]
        if isinstance(spec, FixedLenFeature):
            lead = [] if single else [batch]
            specs.append((shape_mod.TensorShape(lead + spec.shape),
                          spec.dtype))
        elif isinstance(spec, RaggedFeature):
            lead = [] if single else [batch]
            specs.append((shape_mod.TensorShape(lead + [spec.max_len]),
                          spec.dtype))
            specs.append((shape_mod.TensorShape(lead), dtypes_mod.int64))
        else:  # VarLen -> indices, values, dense_shape
            specs.append((shape_mod.TensorShape([None, 2]), dtypes_mod.int64))
            specs.append((shape_mod.TensorShape([None]), spec.dtype))
            specs.append((shape_mod.TensorShape([2]), dtypes_mod.int64))
    op = g.create_op("ParseExample", [serialized],
                     attrs={"_features": features, "_single": single},
                     name=name or "ParseExample", output_specs=specs)
    out = {}
    i = 0
    for n in names:
        spec = features[n]
        if isinstance(spec, FixedLenFeature):
            out[n] = op.outputs[i]
            i += 1
        elif isinstance(spec, RaggedFeature):
            out[n] = op.outputs[i]
            out[n + "_lengths"] = op.outputs[i + 1]
            i += 2
        else:
            out[n] = sparse_mod.SparseTensor(op.outputs[i], op.outputs[i + 1],
                                             op.outputs[i + 2])
            i += 3
    return out


def parse_example(serialized, features, name=None, example_names=None):
    """(ref: parsing_ops.py:358 ``parse_example``). serialized: 1-D string
    tensor. Returns {name: Tensor} for FixedLen and {name: SparseTensor}
    for VarLen features."""
    return _parse_example_graph(serialized, features, name, single=False)


def parse_single_example(serialized, features, name=None):
    """(ref: parsing_ops.py ``parse_single_example``): scalar serialized.
    The batch-dim handling happens inside the host parse op (np.ravel makes
    the scalar a batch of one; host-op outputs cannot feed device ops in
    the two-stage execution model)."""
    serialized = ops_mod.convert_to_tensor(serialized)
    return _parse_example_graph(serialized, features, name, single=True)


def decode_raw(bytes_tensor, out_type, little_endian=True, name=None):
    """(ref: parsing_ops.py ``decode_raw``): bytes -> numeric vector."""
    out_type = dtypes_mod.as_dtype(out_type)

    def host_fn(vals, _dtype=out_type):
        flat = np.ravel(np.asarray(vals, dtype=object))
        rows = [np.frombuffer(
            v if isinstance(v, bytes) else str(v).encode(),
            dtype=_dtype.as_numpy_dtype) for v in flat]
        n = {len(r) for r in rows}
        if len(n) > 1:
            raise ValueError("decode_raw: records have unequal lengths")
        arr = (np.stack(rows) if rows
               else np.zeros((0, 0), _dtype.as_numpy_dtype))
        return arr.reshape(np.asarray(vals, dtype=object).shape + (-1,))

    op_type = f"DecodeRaw_{out_type.name}_{little_endian}"
    if not op_registry.exists(op_type):
        def lower(ctx, op, inputs, fn=host_fn):
            return [fn(inputs[0])]

        op_registry.register(op_type, lower=lower, is_stateful=True,
                             runs_on_host=True)
    bytes_tensor = ops_mod.convert_to_tensor(bytes_tensor)
    g = ops_mod.get_default_graph()
    in_shape = (bytes_tensor.shape.as_list()
                if bytes_tensor.shape.rank is not None else None)
    out_shape = shape_mod.TensorShape(
        (in_shape + [None]) if in_shape is not None else None)
    op = g.create_op(op_type, [bytes_tensor], name=name or "DecodeRaw",
                     output_specs=[(out_shape, out_type)])
    return op.outputs[0]


# -- round-4 parity fills ----------------------------------------------------

class FixedLenSequenceFeature:
    """(ref: parsing_ops.py ``FixedLenSequenceFeature``): a variable
    number of fixed-shape rows; parse pads to the batch max (the TPU
    static-shape analog of the reference's row-ragged parse)."""

    def __init__(self, shape, dtype, allow_missing=False,
                 default_value=None):
        self.shape = list(shape)
        self.dtype = dtypes_mod.as_dtype(dtype)
        self.allow_missing = allow_missing
        self.default_value = default_value


class SparseFeature:
    """(ref: parsing_ops.py ``SparseFeature``): (index_key, value_key)
    feature pair parsed into one SparseTensor triple."""

    def __init__(self, index_key, value_key, dtype, size,
                 already_sorted=False):
        self.index_key = index_key
        self.value_key = value_key
        self.dtype = dtypes_mod.as_dtype(dtype)
        self.size = int(size)
        self.already_sorted = already_sorted


def decode_csv(records, record_defaults, field_delim=",", name=None):
    """(ref: parsing_ops.py ``decode_csv``, core/kernels/decode_csv_op.cc).
    Host stage (strings). Returns one tensor per column."""
    recs = ops_mod.convert_to_tensor(records, dtype=dtypes_mod.string)
    col_dtypes = []
    defaults = []
    for d in record_defaults:
        arr = np.asarray(d).ravel()
        if arr.dtype == object or arr.dtype.kind in "US":
            col_dtypes.append(dtypes_mod.string)
        else:
            col_dtypes.append(dtypes_mod.as_dtype(arr.dtype))
        defaults.append(arr[0] if arr.size else None)
    g = ops_mod.get_default_graph()
    op = g.create_op(
        "DecodeCSV", [recs],
        attrs={"_defaults": tuple(defaults),
               "_dtypes": tuple(d.name for d in col_dtypes),
               "field_delim": field_delim},
        name=name or "DecodeCSV",
        output_specs=[(recs.shape, dt) for dt in col_dtypes])
    return list(op.outputs)


def _lower_decode_csv(ctx, op, inputs):
    import csv as _csv
    import io as _io

    recs = np.ravel(np.asarray(inputs[0], dtype=object))
    defaults = op.attrs["_defaults"]
    dtype_names = op.attrs["_dtypes"]
    builtins_len = len(dtype_names)
    cols = [[] for _ in dtype_names]
    for r in recs:
        s = r.decode() if isinstance(r, bytes) else str(r)
        rows = list(_csv.reader(_io.StringIO(s),
                                delimiter=op.attrs["field_delim"]))
        # empty record = all fields empty -> defaults (ref kernel behavior)
        row = rows[0] if rows else [""] * builtins_len

        if len(row) != len(cols):
            raise ValueError(
                f"decode_csv: record has {len(row)} fields, expected "
                f"{len(cols)}: {s!r}")
        for i, field in enumerate(row):
            if field == "":
                if defaults[i] is None:
                    raise ValueError(
                        f"decode_csv: field {i} empty and no default")
                cols[i].append(defaults[i])
            else:
                cols[i].append(field)
    out = []
    for vals, dt_name in zip(cols, dtype_names):
        dt = dtypes_mod.as_dtype(dt_name)
        if dt == dtypes_mod.string:
            out.append(np.asarray(vals, dtype=object))
        elif dt.is_integer:
            out.append(np.asarray([int(v) for v in vals], dt.np_dtype))
        else:
            out.append(np.asarray([float(v) for v in vals], dt.np_dtype))
    shape = np.asarray(inputs[0], dtype=object).shape
    return [o.reshape(shape) for o in out]


op_registry.register("DecodeCSV", lower=_lower_decode_csv,
                     is_stateful=True, runs_on_host=True, n_outputs=None)


def parse_tensor(serialized, out_type, name=None):
    """(ref: parsing_ops.py ``parse_tensor``): TensorProto wire decode.
    Our GraphDef serializes tensors as npy bytes (graph_io), so this
    accepts that representation."""
    x = ops_mod.convert_to_tensor(serialized, dtype=dtypes_mod.string)
    dt = dtypes_mod.as_dtype(out_type)
    g = ops_mod.get_default_graph()
    op = g.create_op("ParseTensor", [x], attrs={"out_type": dt.name},
                     name=name or "ParseTensor",
                     output_specs=[(shape_mod.TensorShape(None), dt)])
    return op.outputs[0]


def _lower_parse_tensor(ctx, op, inputs):
    import io as _io

    raw = inputs[0]
    v = raw.item() if hasattr(raw, "item") and getattr(
        raw, "ndim", 1) == 0 else raw
    if isinstance(v, str):
        v = v.encode("latin1")
    arr = np.load(_io.BytesIO(v), allow_pickle=False)
    want = dtypes_mod.as_dtype(op.attrs["out_type"])
    if arr.dtype != want.np_dtype:
        raise ValueError(
            f"parse_tensor: serialized dtype {arr.dtype} != requested "
            f"{want.name}")
    return [arr]


op_registry.register("ParseTensor", lower=_lower_parse_tensor,
                     is_stateful=True, runs_on_host=True, n_outputs=1)


def serialize_tensor(tensor, name=None):
    """Inverse of parse_tensor (npy wire)."""
    x = ops_mod.convert_to_tensor(tensor)
    g = ops_mod.get_default_graph()
    op = g.create_op("SerializeTensor", [x], attrs={},
                     name=name or "SerializeTensor",
                     output_specs=[(shape_mod.scalar(),
                                    dtypes_mod.string)])
    return op.outputs[0]


def _lower_serialize_tensor(ctx, op, inputs):
    import io as _io

    buf = _io.BytesIO()
    np.save(buf, np.asarray(inputs[0]), allow_pickle=False)
    return [np.asarray(buf.getvalue(), dtype=object)]


op_registry.register("SerializeTensor", lower=_lower_serialize_tensor,
                     is_stateful=True, runs_on_host=True, n_outputs=1)


def decode_json_example(json_examples, name=None):
    """(ref: parsing_ops.py ``decode_json_example``): JSON-mapped Example
    protos re-encoded to binary Example wire (host stage)."""
    x = ops_mod.convert_to_tensor(json_examples, dtype=dtypes_mod.string)
    g = ops_mod.get_default_graph()
    op = g.create_op("DecodeJSONExample", [x], attrs={},
                     name=name or "DecodeJSONExample",
                     output_specs=[(x.shape, dtypes_mod.string)])
    return op.outputs[0]


def _lower_decode_json_example(ctx, op, inputs):
    import json as _json

    from ..lib.example import make_example

    def one(s):
        if isinstance(s, bytes):
            s = s.decode()
        d = _json.loads(s)
        feats = {}
        for name, feat in d.get("features", {}).get("feature",
                                                    {}).items():
            if "floatList" in feat:
                feats[name] = [float(v)
                               for v in feat["floatList"]["value"]]
            elif "int64List" in feat:
                feats[name] = [int(v) for v in feat["int64List"]["value"]]
            elif "bytesList" in feat:
                import base64 as _b64

                feats[name] = [_b64.b64decode(v)
                               for v in feat["bytesList"]["value"]]
        return make_example(**feats).SerializeToString()

    arr = np.asarray(inputs[0], dtype=object)
    out = np.vectorize(one, otypes=[object])(arr) if arr.shape else \
        np.asarray(one(arr.item()), dtype=object)
    return [out]


op_registry.register("DecodeJSONExample", lower=_lower_decode_json_example,
                     is_stateful=True, runs_on_host=True, n_outputs=1)
