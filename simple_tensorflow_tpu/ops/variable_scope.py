"""variable_scope / get_variable (ref: tensorflow/python/ops/variable_scope.py).

Same reuse semantics as the reference: scopes form a path, get_variable
creates or (with reuse=True) returns the existing variable of that full
name; AUTO_REUSE creates on first use. Custom getters and partitioners are
supported; a partitioner here attaches a sharding hint instead of physically
splitting (the TPU-native equivalent — the mesh shards the single logical
array, see stf.parallel).
"""

from __future__ import annotations

import contextlib
import threading

import numpy as np
from typing import Callable, Optional

from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from ..framework import tensor_shape as shape_mod
from . import init_ops
from . import variables as variables_mod

AUTO_REUSE = "auto_reuse"


class _VarStoreKey:
    VARS = "__variable_store__"
    SCOPE = "__variable_scope_stack__"


def _graph_vars(g) -> dict:
    # Variables (and thus the get_variable store) always belong to the root
    # graph, even when called while tracing a cond/while/scan body.
    root = g
    while isinstance(root, ops_mod.FuncGraph):
        root = root.outer_graph
    return root._scoped_state.setdefault(_VarStoreKey.VARS, {})


def _scope_stack(g) -> list:
    root = g
    while isinstance(root, ops_mod.FuncGraph):
        root = root.outer_graph
    st = root._scoped_state.get(_VarStoreKey.SCOPE)
    if st is None:
        st = [VariableScope("", None)]
        root._scoped_state[_VarStoreKey.SCOPE] = st
    return st


class VariableScope:
    def __init__(self, name, parent, reuse=False, initializer=None,
                 regularizer=None, caching_device=None, partitioner=None,
                 custom_getter=None, dtype=None):
        self._name = name
        self._reuse = reuse
        self._initializer = initializer
        self._regularizer = regularizer
        self._partitioner = partitioner
        self._custom_getter = custom_getter
        self._dtype = dtype or dtypes_mod.float32

    @property
    def name(self):
        return self._name

    @property
    def original_name_scope(self):
        return self._name + "/" if self._name else ""

    @property
    def reuse(self):
        return self._reuse

    @property
    def initializer(self):
        return self._initializer

    @property
    def regularizer(self):
        return self._regularizer

    @property
    def partitioner(self):
        return self._partitioner

    @property
    def custom_getter(self):
        return self._custom_getter

    @property
    def dtype(self):
        return self._dtype

    def reuse_variables(self):
        self._reuse = True

    def set_initializer(self, initializer):
        self._initializer = initializer

    def set_partitioner(self, partitioner):
        self._partitioner = partitioner

    def get_variable(self, name, **kwargs):
        return get_variable(name, **kwargs)


def get_variable_scope() -> VariableScope:
    return _scope_stack(ops_mod.get_default_graph())[-1]


def get_variable(name, shape=None, dtype=None, initializer=None,
                 regularizer=None, trainable=True, collections=None,
                 caching_device=None, partitioner=None, validate_shape=True,
                 use_resource=None, custom_getter=None, constraint=None):
    """(ref: variable_scope.py:988 ``get_variable``)."""
    g = ops_mod.get_default_graph()
    scope = get_variable_scope()
    full_name = f"{scope.name}/{name}" if scope.name else name
    store = _graph_vars(g)

    getter = custom_getter or scope.custom_getter

    def _true_getter(name=full_name, shape=shape, dtype=dtype,
                     initializer=initializer, regularizer=regularizer,
                     trainable=trainable, collections=collections,
                     partitioner=partitioner, constraint=constraint, **_):
        reuse = scope.reuse
        if name in store:
            if reuse is False:
                raise ValueError(
                    f"Variable {name} already exists, disallowed. Did you "
                    "mean to set reuse=True or reuse=stf.AUTO_REUSE in "
                    "VarScope?")
            v = store[name]
            if shape is not None and not v.shape.is_compatible_with(shape):
                raise ValueError(
                    f"Trying to share variable {name}, but specified shape "
                    f"{shape} and found shape {v.shape}.")
            return v
        if reuse is True:
            raise ValueError(
                f"Variable {name} does not exist, or was not created with "
                "stf.get_variable(). Did you mean to set reuse=None in "
                "VarScope?")
        dt = dtypes_mod.as_dtype(dtype or scope.dtype)
        init = initializer if initializer is not None else scope.initializer
        if init is None:
            if dt.is_floating:
                init = init_ops.glorot_uniform_initializer(dtype=dt)
            elif dt.is_integer or dt.is_bool:
                init = init_ops.Zeros(dtype=dt)
            else:
                raise ValueError(f"No default initializer for dtype {dt}")
        if callable(init) and not isinstance(init, ops_mod.Tensor):
            if shape is None:
                raise ValueError(f"Shape of variable {name} must be known")
            sh = [int(d) for d in shape_mod.as_shape(shape).as_list()]

            def init_val():
                try:
                    return init(sh, dtype=dt)
                except TypeError:
                    return init(sh)
        else:
            init_val = init
        var_cls = (variables_mod.ResourceVariable if use_resource
                   else variables_mod.Variable)
        v = var_cls(
            initial_value=init_val, trainable=trainable,
            collections=collections, validate_shape=validate_shape,
            name=name + "/", dtype=dt, constraint=constraint)
        # name + "/" -> exact-name convention so the store key matches.
        store[name] = v
        part = partitioner or scope.partitioner
        if part is not None:
            v._op.attrs["partition_hint"] = part
        reg = regularizer if regularizer is not None else scope.regularizer
        if reg is not None:
            with ops_mod.name_scope(name + "/Regularizer"):
                loss = reg(v._ref)
            if loss is not None:
                g.add_to_collection(ops_mod.GraphKeys.REGULARIZATION_LOSSES,
                                    loss)
        return v

    if getter is not None:
        return getter(_true_getter, name=full_name, shape=shape, dtype=dtype,
                      initializer=initializer, regularizer=regularizer,
                      trainable=trainable, collections=collections,
                      partitioner=partitioner, constraint=constraint)
    return _true_getter()


@contextlib.contextmanager
def variable_scope(name_or_scope, default_name=None, values=None,
                   initializer=None, regularizer=None, caching_device=None,
                   partitioner=None, custom_getter=None, reuse=None,
                   dtype=None, auxiliary_name_scope=True):
    """(ref: variable_scope.py:1615 ``variable_scope``)."""
    g = ops_mod.get_default_graph()
    stack = _scope_stack(g)
    parent = stack[-1]
    if isinstance(name_or_scope, VariableScope):
        new_name = name_or_scope.name
        base = name_or_scope
    else:
        if name_or_scope is None:
            name_or_scope = default_name
        new_name = f"{parent.name}/{name_or_scope}" if parent.name \
            else name_or_scope
        base = None
    scope = VariableScope(
        new_name, parent,
        reuse=(reuse if reuse is not None
               else (base.reuse if base else parent.reuse)),
        initializer=(initializer if initializer is not None
                     else (base.initializer if base else parent.initializer)),
        regularizer=(regularizer if regularizer is not None
                     else (base.regularizer if base else parent.regularizer)),
        partitioner=(partitioner if partitioner is not None
                     else (base.partitioner if base else parent.partitioner)),
        custom_getter=(custom_getter if custom_getter is not None
                       else (base.custom_getter if base
                             else parent.custom_getter)),
        dtype=(dtype if dtype is not None
               else (base.dtype if base else parent.dtype)))
    stack.append(scope)
    try:
        if auxiliary_name_scope and not isinstance(name_or_scope, VariableScope):
            with g.name_scope(name_or_scope):
                yield scope
        else:
            yield scope
    finally:
        stack.pop()


@contextlib.contextmanager
def variable_op_scope(values, name_or_scope, default_name=None, **kwargs):
    with variable_scope(name_or_scope, default_name=default_name,
                        **kwargs) as vs:
        yield vs


def no_regularizer(_):
    return None


def fixed_size_partitioner(num_shards, axis=0):
    """Partitioner → sharding hint (see class docstring)."""

    def partitioner(shape=None, dtype=None):
        return {"axis": axis, "num_shards": num_shards}

    return partitioner


def variable_axis_size_partitioner(max_shard_bytes, axis=0, bytes_per_string=16,
                                   max_shards=None):
    def partitioner(shape=None, dtype=None):
        return {"axis": axis, "max_shard_bytes": max_shard_bytes}

    return partitioner


def min_max_variable_partitioner(max_partitions=1, axis=0,
                                 min_slice_size=256 << 10, bytes_per_string_element=16):
    def partitioner(shape=None, dtype=None):
        return {"axis": axis, "max_partitions": max_partitions}

    return partitioner


def get_local_variable(name, shape=None, dtype=None, initializer=None,
                       regularizer=None, trainable=False, collections=None,
                       **kwargs):
    """(ref: variable_scope.py ``get_local_variable``): a non-trainable
    variable in the LOCAL_VARIABLES collection."""
    collections = list(collections or []) + [
        ops_mod.GraphKeys.LOCAL_VARIABLES]
    return get_variable(name, shape=shape, dtype=dtype,
                        initializer=initializer, regularizer=regularizer,
                        trainable=False, collections=collections, **kwargs)
