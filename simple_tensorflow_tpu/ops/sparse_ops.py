"""Sparse ops (ref: tensorflow/python/ops/sparse_ops.py,
core/kernels/sparse_*.cc).

TPU-native: SparseTensors are fixed-capacity COO (see
framework/sparse_tensor.py); ops lower to dense scatters/gathers, which XLA
fuses — TPU has no sparse execution units, so dense-backed is the honest
fast path (the reference's CPU sparse kernels don't vectorize either).
Padding rows (index < 0) are masked out.
"""

from __future__ import annotations

import builtins
import numpy as np

import jax.numpy as jnp

from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from ..framework import op_registry
from ..framework import tensor_shape as shape_mod
from ..framework import constant_op
from ..framework.sparse_tensor import SparseTensor
from .op_util import make_op


def _static_dense_shape(sp: SparseTensor):
    v = constant_op.constant_value(sp.dense_shape)
    if v is None:
        raise ValueError("SparseTensor dense_shape must be static on TPU")
    return tuple(int(d) for d in v)


def _sparse_to_dense_impl(indices, values, default_value=0, shape=None,
                          validate_indices=True):
    out = jnp.full(shape, default_value, dtype=values.dtype)
    valid = jnp.all(indices >= 0, axis=-1)
    safe_idx = jnp.maximum(indices, 0)
    vals = jnp.where(valid, values, out[builtins.tuple(
        safe_idx[..., k] for k in builtins.range(indices.shape[-1]))])
    return out.at[builtins.tuple(
        safe_idx[..., k] for k in builtins.range(indices.shape[-1]))].set(vals)


op_registry.register_pure("SparseToDense", _sparse_to_dense_impl)


def sparse_to_dense(sparse_indices, output_shape, sparse_values,
                    default_value=0, validate_indices=True, name=None):
    idx = ops_mod.convert_to_tensor(sparse_indices, dtype=dtypes_mod.int64)
    vals = ops_mod.convert_to_tensor(sparse_values)
    from .array_ops import _static_shape_arg

    sh = _static_shape_arg(output_shape, "sparse_to_dense")
    return make_op("SparseToDense", [idx, vals],
                   attrs={"default_value": default_value, "shape": sh},
                   name=name)


def sparse_tensor_to_dense(sp_input, default_value=0, validate_indices=True,
                           name=None):
    sh = _static_dense_shape(sp_input)
    return make_op("SparseToDense", [sp_input.indices, sp_input.values],
                   attrs={"default_value": default_value, "shape": sh},
                   name=name)


def sparse_tensor_dense_matmul(sp_a, b, adjoint_a=False, adjoint_b=False,
                               name=None):
    from . import math_ops

    dense_a = sparse_tensor_to_dense(sp_a)
    return math_ops.matmul(dense_a, ops_mod.convert_to_tensor(b),
                           transpose_a=adjoint_a, transpose_b=adjoint_b,
                           name=name)


def sparse_add(a, b, thresh=0, name=None):
    """(ref: sparse_ops.py ``sparse_add``). sparse+sparse with build-time
    constant indices returns a SparseTensor over the index union (static
    nnz — the TPU shape rule); otherwise falls back to the dense sum."""
    from . import math_ops

    if isinstance(a, SparseTensor) and isinstance(b, SparseTensor):
        ia = constant_op.constant_value(a.indices)
        va = constant_op.constant_value(a.values)
        ib = constant_op.constant_value(b.indices)
        vb = constant_op.constant_value(b.values)
        sa = _static_dense_shape(a)
        if all(x is not None for x in (ia, va, ib, vb, sa)):
            acc = {}
            for idx, v in zip(np.asarray(ia), np.asarray(va)):
                acc[tuple(int(i) for i in idx)] = acc.get(
                    tuple(int(i) for i in idx), 0) + v
            for idx, v in zip(np.asarray(ib), np.asarray(vb)):
                acc[tuple(int(i) for i in idx)] = acc.get(
                    tuple(int(i) for i in idx), 0) + v
            items = sorted((k, v) for k, v in acc.items()
                           if abs(v) > thresh)
            new_idx = np.asarray([k for k, _ in items], np.int64).reshape(
                len(items), len(sa))
            new_val = np.asarray([v for _, v in items])
            return SparseTensor(new_idx, new_val, list(sa))
    da = sparse_tensor_to_dense(a) if isinstance(a, SparseTensor) else a
    db = sparse_tensor_to_dense(b) if isinstance(b, SparseTensor) else b
    return math_ops.add(da, db, name=name)


def sparse_reduce_sum(sp_input, axis=None, keep_dims=False,
                      reduction_axes=None, name=None):
    from . import math_ops

    return math_ops.reduce_sum(sparse_tensor_to_dense(sp_input),
                               axis=axis if axis is not None else reduction_axes,
                               keepdims=keep_dims, name=name)


def sparse_retain(sp_input, to_retain):
    v = constant_op.constant_value(ops_mod.convert_to_tensor(to_retain))
    iv = constant_op.constant_value(sp_input.indices)
    vv = constant_op.constant_value(sp_input.values)
    if v is None or iv is None or vv is None:
        raise ValueError("sparse_retain needs static inputs on TPU")
    keep = np.asarray(v, dtype=bool)
    return SparseTensor(constant_op.constant(iv[keep]),
                        constant_op.constant(vv[keep]),
                        sp_input.dense_shape)


def sparse_reorder(sp_input, name=None):
    iv = constant_op.constant_value(sp_input.indices)
    vv = constant_op.constant_value(sp_input.values)
    if iv is None or vv is None:
        return sp_input  # already canonical in our construction
    order = np.lexsort(tuple(iv[:, k] for k in range(iv.shape[1] - 1, -1, -1)))
    return SparseTensor(constant_op.constant(iv[order]),
                        constant_op.constant(vv[order]),
                        sp_input.dense_shape)


def sparse_slice(sp_input, start, size, name=None):
    """(ref: python/ops/sparse_ops.py ``sparse_slice``,
    core/kernels/sparse_slice_op.cc). Construction-time COO transform (the
    TPU-safe regime used by retain/reorder above): keeps entries inside the
    [start, start+size) window and rebases their indices."""
    iv = constant_op.constant_value(sp_input.indices)
    vv = constant_op.constant_value(sp_input.values)
    shp = constant_op.constant_value(sp_input.dense_shape)
    if iv is None or vv is None or shp is None:
        raise NotImplementedError(
            "sparse_slice on runtime-valued SparseTensors: convert with "
            "sparse_tensor_to_dense and slice densely on TPU")
    start_a = np.asarray(start, dtype=np.int64)
    size_a = np.asarray(size, dtype=np.int64)
    out_shape = np.minimum(np.asarray(shp, np.int64) - start_a, size_a)
    out_shape = np.maximum(out_shape, 0)
    keep = np.all((iv >= start_a) & (iv < start_a + size_a), axis=1)
    return SparseTensor(constant_op.constant(iv[keep] - start_a),
                        constant_op.constant(vv[keep]),
                        constant_op.constant(out_shape))


def sparse_concat(axis, sp_inputs, name=None, expand_nonconcat_dim=False):
    """(ref: python/ops/sparse_ops.py ``sparse_concat``,
    core/kernels/sparse_concat_op.cc). COO concat along ``axis`` with index
    offsetting; non-concat dims must match unless expand_nonconcat_dim."""
    ivs, vvs, shps = [], [], []
    for sp in sp_inputs:
        iv = constant_op.constant_value(sp.indices)
        vv = constant_op.constant_value(sp.values)
        shp = constant_op.constant_value(sp.dense_shape)
        if iv is None or vv is None or shp is None:
            raise NotImplementedError(
                "sparse_concat on runtime-valued SparseTensors: convert "
                "with sparse_tensor_to_dense and concat densely on TPU")
        ivs.append(np.asarray(iv, np.int64).reshape(-1, len(shp)))
        vvs.append(np.asarray(vv))
        shps.append(np.asarray(shp, np.int64))
    rank = len(shps[0])
    axis = axis if axis >= 0 else axis + rank
    others = [d for d in range(rank) if d != axis]
    for shp in shps[1:]:
        if not expand_nonconcat_dim and any(shp[d] != shps[0][d]
                                            for d in others):
            raise ValueError(
                f"sparse_concat: non-concat dims differ {shps[0]} vs {shp}; "
                "pass expand_nonconcat_dim=True")
    out_shape = np.array(shps[0])
    out_shape[axis] = sum(int(s[axis]) for s in shps)
    for d in others:
        out_shape[d] = max(int(s[d]) for s in shps)
    offset = 0
    out_iv, out_vv = [], []
    for iv, vv, shp in zip(ivs, vvs, shps):
        shifted = iv.copy()
        shifted[:, axis] += offset
        offset += int(shp[axis])
        out_iv.append(shifted)
        out_vv.append(vv)
    iv_all = np.concatenate(out_iv, axis=0)
    vv_all = np.concatenate(out_vv, axis=0)
    order = np.lexsort(tuple(iv_all[:, k] for k in range(rank - 1, -1, -1)))
    return SparseTensor(constant_op.constant(iv_all[order]),
                        constant_op.constant(vv_all[order]),
                        constant_op.constant(out_shape))


def sparse_placeholder(dtype, shape=None, name=None):
    from . import array_ops

    if shape is None:
        raise ValueError("sparse_placeholder on TPU needs a static shape")
    nnz = int(np.prod([int(s) for s in shape]))
    idx = array_ops.placeholder(dtypes_mod.int64, [None, len(shape)],
                                name=(name or "sparse") + "_indices")
    vals = array_ops.placeholder(dtype, [None],
                                 name=(name or "sparse") + "_values")
    return SparseTensor(idx, vals, constant_op.constant(
        np.asarray(shape, dtype=np.int64)))


def sparse_mask(a, mask_indices, name=None):
    from ..framework.indexed_slices import IndexedSlices

    iv = constant_op.constant_value(a.indices)
    mv = constant_op.constant_value(ops_mod.convert_to_tensor(mask_indices))
    if iv is None or mv is None:
        raise ValueError("sparse_mask needs static indices on TPU")
    keep = ~np.isin(iv, mv)
    from . import array_ops

    pos = np.nonzero(keep)[0]
    return IndexedSlices(
        array_ops.gather(a.values, constant_op.constant(pos.astype(np.int32))),
        constant_op.constant(iv[keep]), a.dense_shape)
