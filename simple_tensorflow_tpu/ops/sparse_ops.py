"""Sparse ops (ref: tensorflow/python/ops/sparse_ops.py,
core/kernels/sparse_*.cc).

TPU-native: SparseTensors are fixed-capacity COO (see
framework/sparse_tensor.py); ops lower to dense scatters/gathers, which XLA
fuses — TPU has no sparse execution units, so dense-backed is the honest
fast path (the reference's CPU sparse kernels don't vectorize either).
Padding rows (index < 0) are masked out.
"""

from __future__ import annotations

import builtins
import numpy as np

import jax.numpy as jnp

from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from ..framework import op_registry
from ..framework import tensor_shape as shape_mod
from ..framework import constant_op
from ..framework.sparse_tensor import SparseTensor
from .op_util import make_op


def _static_dense_shape(sp: SparseTensor):
    v = constant_op.constant_value(sp.dense_shape)
    if v is None:
        raise ValueError("SparseTensor dense_shape must be static on TPU")
    return tuple(int(d) for d in v)


def _sparse_to_dense_impl(indices, values, default_value=0, shape=None,
                          validate_indices=True):
    out = jnp.full(shape, default_value, dtype=values.dtype)
    valid = jnp.all(indices >= 0, axis=-1)
    safe_idx = jnp.maximum(indices, 0)
    vals = jnp.where(valid, values, out[builtins.tuple(
        safe_idx[..., k] for k in builtins.range(indices.shape[-1]))])
    return out.at[builtins.tuple(
        safe_idx[..., k] for k in builtins.range(indices.shape[-1]))].set(vals)


op_registry.register_pure("SparseToDense", _sparse_to_dense_impl)


def sparse_to_dense(sparse_indices, output_shape, sparse_values,
                    default_value=0, validate_indices=True, name=None):
    idx = ops_mod.convert_to_tensor(sparse_indices, dtype=dtypes_mod.int64)
    vals = ops_mod.convert_to_tensor(sparse_values)
    from .array_ops import _static_shape_arg

    sh = _static_shape_arg(output_shape, "sparse_to_dense")
    return make_op("SparseToDense", [idx, vals],
                   attrs={"default_value": default_value, "shape": sh},
                   name=name)


def sparse_tensor_to_dense(sp_input, default_value=0, validate_indices=True,
                           name=None):
    sh = _static_dense_shape(sp_input)
    return make_op("SparseToDense", [sp_input.indices, sp_input.values],
                   attrs={"default_value": default_value, "shape": sh},
                   name=name)


def sparse_tensor_dense_matmul(sp_a, b, adjoint_a=False, adjoint_b=False,
                               name=None):
    from . import math_ops

    dense_a = sparse_tensor_to_dense(sp_a)
    return math_ops.matmul(dense_a, ops_mod.convert_to_tensor(b),
                           transpose_a=adjoint_a, transpose_b=adjoint_b,
                           name=name)


def sparse_add(a, b, thresh=0, name=None):
    """(ref: sparse_ops.py ``sparse_add``). sparse+sparse with build-time
    constant indices returns a SparseTensor over the index union (static
    nnz — the TPU shape rule); otherwise falls back to the dense sum."""
    from . import math_ops

    if isinstance(a, SparseTensor) and isinstance(b, SparseTensor):
        ia = constant_op.constant_value(a.indices)
        va = constant_op.constant_value(a.values)
        ib = constant_op.constant_value(b.indices)
        vb = constant_op.constant_value(b.values)
        sa = _static_dense_shape(a)
        if all(x is not None for x in (ia, va, ib, vb, sa)):
            acc = {}
            for idx, v in zip(np.asarray(ia), np.asarray(va)):
                acc[tuple(int(i) for i in idx)] = acc.get(
                    tuple(int(i) for i in idx), 0) + v
            for idx, v in zip(np.asarray(ib), np.asarray(vb)):
                acc[tuple(int(i) for i in idx)] = acc.get(
                    tuple(int(i) for i in idx), 0) + v
            items = sorted((k, v) for k, v in acc.items()
                           if abs(v) > thresh)
            new_idx = np.asarray([k for k, _ in items], np.int64).reshape(
                len(items), len(sa))
            new_val = np.asarray([v for _, v in items])
            return SparseTensor(new_idx, new_val, list(sa))
    da = sparse_tensor_to_dense(a) if isinstance(a, SparseTensor) else a
    db = sparse_tensor_to_dense(b) if isinstance(b, SparseTensor) else b
    return math_ops.add(da, db, name=name)


def sparse_reduce_sum(sp_input, axis=None, keep_dims=False,
                      reduction_axes=None, name=None):
    from . import math_ops

    return math_ops.reduce_sum(sparse_tensor_to_dense(sp_input),
                               axis=axis if axis is not None else reduction_axes,
                               keepdims=keep_dims, name=name)


def sparse_retain(sp_input, to_retain):
    v = constant_op.constant_value(ops_mod.convert_to_tensor(to_retain))
    iv = constant_op.constant_value(sp_input.indices)
    vv = constant_op.constant_value(sp_input.values)
    if v is None or iv is None or vv is None:
        raise ValueError("sparse_retain needs static inputs on TPU")
    keep = np.asarray(v, dtype=bool)
    return SparseTensor(constant_op.constant(iv[keep]),
                        constant_op.constant(vv[keep]),
                        sp_input.dense_shape)


def sparse_reorder(sp_input, name=None):
    iv = constant_op.constant_value(sp_input.indices)
    vv = constant_op.constant_value(sp_input.values)
    if iv is None or vv is None:
        return sp_input  # already canonical in our construction
    order = np.lexsort(tuple(iv[:, k] for k in range(iv.shape[1] - 1, -1, -1)))
    return SparseTensor(constant_op.constant(iv[order]),
                        constant_op.constant(vv[order]),
                        sp_input.dense_shape)


def sparse_slice(sp_input, start, size, name=None):
    """(ref: python/ops/sparse_ops.py ``sparse_slice``,
    core/kernels/sparse_slice_op.cc). Construction-time COO transform (the
    TPU-safe regime used by retain/reorder above): keeps entries inside the
    [start, start+size) window and rebases their indices."""
    iv = constant_op.constant_value(sp_input.indices)
    vv = constant_op.constant_value(sp_input.values)
    shp = constant_op.constant_value(sp_input.dense_shape)
    if iv is None or vv is None or shp is None:
        raise NotImplementedError(
            "sparse_slice on runtime-valued SparseTensors: convert with "
            "sparse_tensor_to_dense and slice densely on TPU")
    start_a = np.asarray(start, dtype=np.int64)
    size_a = np.asarray(size, dtype=np.int64)
    out_shape = np.minimum(np.asarray(shp, np.int64) - start_a, size_a)
    out_shape = np.maximum(out_shape, 0)
    keep = np.all((iv >= start_a) & (iv < start_a + size_a), axis=1)
    return SparseTensor(constant_op.constant(iv[keep] - start_a),
                        constant_op.constant(vv[keep]),
                        constant_op.constant(out_shape))


def sparse_concat(axis, sp_inputs, name=None, expand_nonconcat_dim=False):
    """(ref: python/ops/sparse_ops.py ``sparse_concat``,
    core/kernels/sparse_concat_op.cc). COO concat along ``axis`` with index
    offsetting; non-concat dims must match unless expand_nonconcat_dim."""
    ivs, vvs, shps = [], [], []
    for sp in sp_inputs:
        iv = constant_op.constant_value(sp.indices)
        vv = constant_op.constant_value(sp.values)
        shp = constant_op.constant_value(sp.dense_shape)
        if iv is None or vv is None or shp is None:
            raise NotImplementedError(
                "sparse_concat on runtime-valued SparseTensors: convert "
                "with sparse_tensor_to_dense and concat densely on TPU")
        ivs.append(np.asarray(iv, np.int64).reshape(-1, len(shp)))
        vvs.append(np.asarray(vv))
        shps.append(np.asarray(shp, np.int64))
    rank = len(shps[0])
    axis = axis if axis >= 0 else axis + rank
    others = [d for d in range(rank) if d != axis]
    for shp in shps[1:]:
        if not expand_nonconcat_dim and any(shp[d] != shps[0][d]
                                            for d in others):
            raise ValueError(
                f"sparse_concat: non-concat dims differ {shps[0]} vs {shp}; "
                "pass expand_nonconcat_dim=True")
    out_shape = np.array(shps[0])
    out_shape[axis] = sum(int(s[axis]) for s in shps)
    for d in others:
        out_shape[d] = max(int(s[d]) for s in shps)
    offset = 0
    out_iv, out_vv = [], []
    for iv, vv, shp in zip(ivs, vvs, shps):
        shifted = iv.copy()
        shifted[:, axis] += offset
        offset += int(shp[axis])
        out_iv.append(shifted)
        out_vv.append(vv)
    iv_all = np.concatenate(out_iv, axis=0)
    vv_all = np.concatenate(out_vv, axis=0)
    order = np.lexsort(tuple(iv_all[:, k] for k in range(rank - 1, -1, -1)))
    return SparseTensor(constant_op.constant(iv_all[order]),
                        constant_op.constant(vv_all[order]),
                        constant_op.constant(out_shape))


def sparse_placeholder(dtype, shape=None, name=None):
    from . import array_ops

    if shape is None:
        raise ValueError("sparse_placeholder on TPU needs a static shape")
    nnz = int(np.prod([int(s) for s in shape]))
    idx = array_ops.placeholder(dtypes_mod.int64, [None, len(shape)],
                                name=(name or "sparse") + "_indices")
    vals = array_ops.placeholder(dtype, [None],
                                 name=(name or "sparse") + "_values")
    return SparseTensor(idx, vals, constant_op.constant(
        np.asarray(shape, dtype=np.int64)))


def sparse_mask(a, mask_indices, name=None):
    from ..framework.indexed_slices import IndexedSlices

    iv = constant_op.constant_value(a.indices)
    mv = constant_op.constant_value(ops_mod.convert_to_tensor(mask_indices))
    if iv is None or mv is None:
        raise ValueError("sparse_mask needs static indices on TPU")
    keep = ~np.isin(iv, mv)
    from . import array_ops

    pos = np.nonzero(keep)[0]
    return IndexedSlices(
        array_ops.gather(a.values, constant_op.constant(pos.astype(np.int32))),
        constant_op.constant(iv[keep]), a.dense_shape)


# -- round-4 completion: the rest of the reference sparse family ------------
# (ref: python/ops/sparse_ops.py sparse_reshape/split/transpose/
#  fill_empty_rows/reset_shape/to_indicator/merge/softmax/maximum/minimum/
#  reduce_sum_sparse; kernels core/kernels/sparse_*_op.cc).
# Idiom of this file: indices/shape are construction-time static (the
# TPU-safe regime); VALUES may be runtime tensors — value transforms
# lower to segment ops over the static index structure.

def _static_coo(sp, what):
    iv = constant_op.constant_value(sp.indices)
    shp = constant_op.constant_value(sp.dense_shape)
    if iv is None or shp is None:
        raise ValueError(
            f"{what} needs static indices/dense_shape on TPU (runtime "
            "sparsity patterns are data-dependent shapes; densify with "
            "sparse_tensor_to_dense instead)")
    return np.asarray(iv, np.int64), np.asarray(shp, np.int64)


def sparse_reshape(sp_input, shape, name=None):
    iv, shp = _static_coo(sp_input, "sparse_reshape")
    new_shape = np.asarray(
        constant_op.constant_value(ops_mod.convert_to_tensor(shape)),
        np.int64)
    if (new_shape == -1).any():
        known = np.prod(new_shape[new_shape >= 0])
        new_shape = new_shape.copy()
        new_shape[new_shape == -1] = int(np.prod(shp) // max(known, 1))
    lin = np.ravel_multi_index(tuple(iv.T), tuple(shp)) if iv.size else \
        np.zeros((0,), np.int64)
    new_idx = (np.stack(np.unravel_index(lin, tuple(new_shape)), axis=1)
               if lin.size else np.zeros((0, len(new_shape)), np.int64))
    return SparseTensor(constant_op.constant(new_idx),
                        sp_input.values,
                        constant_op.constant(new_shape))


def sparse_transpose(sp_input, perm=None, name=None):
    iv, shp = _static_coo(sp_input, "sparse_transpose")
    if perm is None:
        perm = list(builtins.range(len(shp)))[::-1]
    perm = [int(p) for p in perm]
    new_idx = iv[:, perm]
    order = np.lexsort(tuple(new_idx[:, k]
                             for k in builtins.range(
                                 new_idx.shape[1] - 1, -1, -1)))
    from . import array_ops

    return SparseTensor(
        constant_op.constant(new_idx[order]),
        array_ops.gather(sp_input.values,
                         constant_op.constant(order.astype(np.int32))),
        constant_op.constant(shp[perm]))


def sparse_split(sp_input=None, num_split=1, axis=0, name=None,
                 split_dim=None):
    if split_dim is not None:
        axis = split_dim
    iv, shp = _static_coo(sp_input, "sparse_split")
    axis = int(axis)
    size = int(shp[axis])
    per = -(-size // int(num_split))  # ceil (ref: sizes differ by <=1)
    out = []
    for i in builtins.range(int(num_split)):
        start = np.zeros(len(shp), np.int64)
        start[axis] = i * per
        sz = shp.copy()
        sz[axis] = builtins.min(per, size - i * per)
        out.append(sparse_slice(sp_input, start, sz))
    return out


def sparse_fill_empty_rows(sp_input, default_value, name=None):
    iv, shp = _static_coo(sp_input, "sparse_fill_empty_rows")
    from . import array_ops

    n_rows = int(shp[0])
    present = np.zeros(n_rows, bool)
    if iv.size:
        present[np.unique(iv[:, 0])] = True
    empty = ~present
    add_rows = np.nonzero(empty)[0]
    add_idx = np.zeros((len(add_rows), iv.shape[1]), np.int64)
    add_idx[:, 0] = add_rows
    new_idx = np.concatenate([iv, add_idx], axis=0)
    order = np.lexsort(tuple(new_idx[:, k] for k in
                             builtins.range(new_idx.shape[1] - 1, -1, -1)))
    default_t = ops_mod.convert_to_tensor(
        default_value, dtype=sp_input.values.dtype.base_dtype)
    fill = array_ops.fill([len(add_rows)], default_t) if len(add_rows) \
        else array_ops.zeros([0], dtype=sp_input.values.dtype.base_dtype)
    vals = array_ops.concat([sp_input.values, fill], axis=0)
    vals = array_ops.gather(vals,
                            constant_op.constant(order.astype(np.int32)))
    return (SparseTensor(constant_op.constant(new_idx[order]), vals,
                         sp_input.dense_shape),
            constant_op.constant(empty))


def sparse_reset_shape(sp_input, new_shape=None, name=None):
    iv, shp = _static_coo(sp_input, "sparse_reset_shape")
    if new_shape is None:  # tighten to the bounding box
        tight = (iv.max(axis=0) + 1 if iv.size
                 else np.zeros(len(shp), np.int64))
        return SparseTensor(sp_input.indices, sp_input.values,
                            constant_op.constant(tight.astype(np.int64)))
    ns = np.asarray(constant_op.constant_value(
        ops_mod.convert_to_tensor(new_shape)), np.int64)
    if iv.size and (iv.max(axis=0) >= ns).any():
        raise ValueError("new_shape is smaller than existing indices")
    return SparseTensor(sp_input.indices, sp_input.values,
                        constant_op.constant(ns))


def sparse_to_indicator(sp_input, vocab_size, name=None):
    """bool [d0..dn-2, vocab_size]: the VALUES are ids (ref semantics)."""
    iv, shp = _static_coo(sp_input, "sparse_to_indicator")
    from . import array_ops, math_ops

    lead = [int(s) for s in shp[:-1]]
    out_shape = lead + [int(vocab_size)]
    if not iv.size:
        return array_ops.zeros(out_shape, dtype=dtypes_mod.bool_)
    rows = (np.ravel_multi_index(tuple(iv[:, :-1].T), tuple(lead))
            if len(lead) > 1 else iv[:, 0])
    ids = math_ops.cast(sp_input.values, dtypes_mod.int32)
    flat_idx = (math_ops.cast(constant_op.constant(
        rows.astype(np.int32) * int(vocab_size)), dtypes_mod.int32) + ids)
    dense = array_ops.scatter_nd(
        array_ops.expand_dims(flat_idx, 1),
        array_ops.ones_like(ids, dtype=dtypes_mod.int32),
        [int(np.prod(lead)) * int(vocab_size)])
    return array_ops.reshape(math_ops.greater(dense, 0), out_shape)


def sparse_merge(sp_ids, sp_values, vocab_size, name=None,
                 already_sorted=False):
    """(ref: sparse_ops.py ``sparse_merge``): ids become the last dim."""
    iv, shp = _static_coo(sp_ids, "sparse_merge")
    ids_v = constant_op.constant_value(sp_ids.values)
    if ids_v is None:
        raise ValueError("sparse_merge needs static ids on TPU")
    new_idx = np.concatenate(
        [iv[:, :1], np.asarray(ids_v, np.int64)[:, None]], axis=1)
    order = (np.arange(len(new_idx)) if already_sorted
             else np.lexsort((new_idx[:, 1], new_idx[:, 0])))
    from . import array_ops

    vals = array_ops.gather(sp_values.values,
                            constant_op.constant(order.astype(np.int32)))
    return SparseTensor(
        constant_op.constant(new_idx[order]), vals,
        constant_op.constant(np.asarray([shp[0], vocab_size], np.int64)))


def _register_segment_value_op():
    def impl(values, segment_ids=None, n_segments=1, mode="softmax"):
        import jax

        sums = jax.ops.segment_sum
        seg = jnp.asarray(segment_ids)
        if mode == "softmax":
            vmax = jax.ops.segment_max(values, seg, n_segments)
            e = jnp.exp(values - vmax[seg])
            denom = sums(e, seg, n_segments)
            return e / denom[seg]
        raise ValueError(mode)

    op_registry.register_pure("SparseSegmentValueTransform", impl)


_register_segment_value_op()


def sparse_softmax(sp_input, name=None):
    """Softmax over the nonzero entries of each row (ref:
    sparse_ops.py ``sparse_softmax``). Indices static, values runtime:
    lowers to segment max/sum over the static row structure."""
    iv, shp = _static_coo(sp_input, "sparse_softmax")
    lead = iv[:, :-1]
    if lead.size:
        rows, seg = np.unique(lead, axis=0, return_inverse=True)
        n_seg = len(rows)
    else:
        seg, n_seg = np.zeros((0,), np.int64), 1
    g = ops_mod.get_default_graph()
    v = sp_input.values
    op = g.create_op("SparseSegmentValueTransform", [v],
                     attrs={"segment_ids": tuple(int(s) for s in seg),
                            "n_segments": int(n_seg), "mode": "softmax"},
                     name=name or "sparse_softmax",
                     output_specs=[(v.shape, v.dtype)])
    return SparseTensor(sp_input.indices, op.outputs[0],
                        sp_input.dense_shape)


def _sparse_binary(a, b, fn_name, name):
    ia, sa = _static_coo(a, fn_name)
    ib, sb = _static_coo(b, fn_name)
    if not np.array_equal(sa, sb):
        raise ValueError(f"{fn_name}: dense shapes differ ({sa} vs {sb})")
    union, inv = np.unique(np.concatenate([ia, ib], axis=0), axis=0,
                           return_inverse=True)
    n = len(union)
    from . import array_ops, math_ops

    inv_a, inv_b = inv[:len(ia)], inv[len(ia):]

    def densify(sp, pos):
        dense = array_ops.scatter_nd(
            constant_op.constant(pos.astype(np.int32)[:, None]),
            sp.values, [n])
        return dense

    da = densify(a, inv_a)
    db = densify(b, inv_b)
    out = (math_ops.maximum(da, db) if fn_name == "sparse_maximum"
           else math_ops.minimum(da, db))
    return SparseTensor(constant_op.constant(union), out,
                        a.dense_shape)


def sparse_maximum(sp_a, sp_b, name=None):
    return _sparse_binary(sp_a, sp_b, "sparse_maximum", name)


def sparse_minimum(sp_a, sp_b, name=None):
    return _sparse_binary(sp_a, sp_b, "sparse_minimum", name)


def sparse_reduce_sum_sparse(sp_input, axis=None, keep_dims=False,
                             reduction_axes=None, name=None):
    """Reduce and RE-SPARSIFY (ref: sparse_ops.py
    ``sparse_reduce_sum_sparse``): output indices derive from the static
    input structure; values are runtime segment sums."""
    iv, shp = _static_coo(sp_input, "sparse_reduce_sum_sparse")
    axes = axis if axis is not None else reduction_axes
    if axes is None:
        axes = list(builtins.range(len(shp)))
    if not isinstance(axes, (list, tuple)):
        axes = [axes]
    axes = sorted(int(a) % len(shp) for a in axes)
    keep_axes = [d for d in builtins.range(len(shp)) if d not in axes]
    from . import array_ops

    if not keep_axes:
        from . import math_ops

        total = math_ops.reduce_sum(sp_input.values)
        return SparseTensor(
            constant_op.constant(np.zeros((1, 0), np.int64)),
            array_ops.reshape(total, [1]),
            constant_op.constant(np.zeros((0,), np.int64)))
    kept = iv[:, keep_axes]
    uniq, seg = np.unique(kept, axis=0, return_inverse=True)
    n_seg = len(uniq)
    g = ops_mod.get_default_graph()
    v = sp_input.values
    op = g.create_op(
        "SegmentSumStatic", [v],
        attrs={"segment_ids": tuple(int(s) for s in seg),
               "n_segments": int(n_seg)},
        name=name or "sparse_reduce_sum_sparse",
        output_specs=[(shape_mod.TensorShape([n_seg]), v.dtype)])
    new_shape = shp[keep_axes]
    if keep_dims:
        full = uniq
        pads = []
        ki = 0
        cols = []
        for d in builtins.range(len(shp)):
            if d in keep_axes:
                cols.append(full[:, ki])
                ki += 1
            else:
                cols.append(np.zeros(len(full), np.int64))
        full = np.stack(cols, axis=1) if len(full) else \
            np.zeros((0, len(shp)), np.int64)
        new_shape = shp.copy()
        new_shape[axes] = 1
        return SparseTensor(constant_op.constant(full), op.outputs[0],
                            constant_op.constant(new_shape))
    return SparseTensor(constant_op.constant(uniq), op.outputs[0],
                        constant_op.constant(new_shape))


op_registry.register_pure(
    "SegmentSumStatic",
    lambda values, segment_ids=(), n_segments=1: __import__("jax").ops
    .segment_sum(values, jnp.asarray(np.asarray(segment_ids, np.int32)),
                 n_segments))
