"""Functional ops: map_fn, scan, foldl/foldr, py_func.

(ref: tensorflow/python/ops/functional_ops.py, script_ops.py). The reference
implements these on top of its dynamic while_loop + TensorArray; on TPU they
lower directly to lax.scan — which IS the differentiable loop on XLA, so
gradients flow through scan/map_fn/foldl (dynamic_rnn builds on this).
py_func lowers to jax.pure_callback: host python embedded in the compiled
step (the reference's py_func runs in the CPU executor thread).
"""

from __future__ import annotations

import builtins
from typing import Callable

import numpy as np

from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from ..framework import lowering as lowering_mod
from ..framework import op_registry
from ..framework import optimizer as optimizer_mod
from ..framework import tensor_shape as shape_mod
from .control_flow_ops import _flatten, _pack_like


def _leading_dim(t):
    """Static trip count of a scan/map: the elems' leading dim."""
    sh = t.shape
    return sh[0].value if sh.rank else None

Tensor = ops_mod.Tensor
FuncGraph = ops_mod.FuncGraph


def _build_fn_graph(fn, arg_specs, name):
    """Trace ``fn`` into a FuncGraph with inputs given by (shape, dtype)."""
    g = ops_mod.get_default_graph()
    fg = FuncGraph(name, outer_graph=g)
    with ops_mod._as_current(fg):
        args = [fg.add_input(dt, sh, f"arg{i}")
                for i, (sh, dt) in enumerate(arg_specs)]
        res = fn(*args) if len(args) > 1 else fn(args[0])
        flat = [ops_mod.convert_to_tensor(t) for t in _flatten(res)]
        fg.outputs = flat
    return fg, res


def _elem_spec(t: Tensor):
    if t.shape.rank is None:
        raise ValueError(f"map/scan input {t.name} needs known rank")
    return (shape_mod.TensorShape(t.shape.as_list()[1:]), t.dtype)


def map_fn(fn, elems, dtype=None, parallel_iterations=None, back_prop=True,
           swap_memory=False, infer_shape=True, name=None):
    """(ref: functional_ops.py ``map_fn``) → lax.scan over the leading axis
    (XLA vectorizes/pipelines the loop; use stf.vectorized_map/jax.vmap via
    layers for embarrassingly parallel maps)."""
    single = not isinstance(elems, (list, builtins.tuple))
    elems_flat = [ops_mod.convert_to_tensor(e) for e in _flatten(elems)]
    g = ops_mod.get_default_graph()
    with g.name_scope(name or "map"):
        def wrapper(*args):
            packed = args[0] if single else _pack_like(elems, builtins.list(args))
            return fn(packed)

        fg, res_struct = _build_fn_graph(
            wrapper, [_elem_spec(e) for e in elems_flat], "map_body")
        caps = [outer for outer, _ in fg.captures]
        n = elems_flat[0].shape[0].value
        if n is None:
            raise ValueError("map_fn needs static leading dim on TPU")
        out_specs = [(shape_mod.TensorShape([n] + o.shape.as_list()), o.dtype)
                     for o in fg.outputs]
        op = g.create_op("MapFn", elems_flat + caps,
                         attrs={"body": fg, "n_elems": len(elems_flat)},
                         name="map_op", output_specs=out_specs)
    outs = builtins.list(op.outputs)
    if len(outs) == 1 and not isinstance(res_struct, (list, builtins.tuple, dict)):
        return outs[0]
    return _pack_like(res_struct, outs)


def _lower_map(ctx, op, inputs):
    import jax

    n = op.attrs["n_elems"]
    fg = op.attrs["body"]
    xs = builtins.tuple(inputs[:n])
    caps = builtins.list(inputs[n:])

    def step(carry, x):
        outs = lowering_mod.lower_func_graph(ctx, fg, builtins.list(x), caps)
        return carry, builtins.tuple(outs)

    _, ys = jax.lax.scan(step, 0, xs)
    return builtins.list(ys)


op_registry.register("MapFn", lower=_lower_map, n_outputs=None)

# PassManager anatomy (inputs = elems + captures); the body runs once
# per element, so capture-only subexpressions hoist out of it
optimizer_mod.register_function_op(
    "MapFn", mode="loop",
    bodies=lambda a, n: [
        dict(attr="body", start=a["n_elems"], count=n - a["n_elems"],
             hoist=True, count_attr=None)],
    trip=lambda a, inputs: _leading_dim(inputs[0]) if inputs else None)


def scan(fn, elems, initializer=None, parallel_iterations=10, back_prop=True,
         swap_memory=False, infer_shape=True, name=None):
    """(ref: functional_ops.py ``scan``) → lax.scan, differentiable."""
    single = not isinstance(elems, (list, builtins.tuple))
    elems_flat = [ops_mod.convert_to_tensor(e) for e in _flatten(elems)]
    n = elems_flat[0].shape[0].value
    if n is None:
        raise ValueError("scan needs static leading dim on TPU")
    g = ops_mod.get_default_graph()
    with g.name_scope(name or "scan"):
        if initializer is None:
            # first element is the initial accumulator (reference semantics)
            from . import array_ops

            init_struct = _pack_like(
                elems, [e[0] for e in elems_flat]) if not single \
                else elems_flat[0][0]
            rest = [e[1:] for e in elems_flat]
            out = scan(fn, _pack_like(elems, rest) if not single else rest[0],
                       initializer=init_struct, name="scan_rest")
            flat_out = _flatten(out)
            full = [array_ops.concat(
                [array_ops.expand_dims(i, 0), o], axis=0)
                for i, o in zip(_flatten(init_struct), flat_out)]
            return _pack_like(out, full) if isinstance(out, (list, builtins.tuple)) \
                else full[0]
        init_flat = [ops_mod.convert_to_tensor(i) for i in _flatten(initializer)]
        n_carry = len(init_flat)

        def wrapper(*args):
            carry = _pack_like(initializer, builtins.list(args[:n_carry]))
            x = args[n_carry] if single else _pack_like(
                elems, builtins.list(args[n_carry:]))
            return fn(carry, x)

        specs = [(i.shape, i.dtype) for i in init_flat] + \
                [_elem_spec(e) for e in elems_flat]
        fg, res_struct = _build_fn_graph(wrapper, specs, "scan_body")
        if len(fg.outputs) != n_carry:
            raise ValueError("scan fn must return a structure like initializer")
        caps = [outer for outer, _ in fg.captures]
        out_specs = [(shape_mod.TensorShape([n] + o.shape.as_list()), o.dtype)
                     for o in fg.outputs]
        op = g.create_op("Scan", init_flat + elems_flat + caps,
                         attrs={"body": fg, "n_carry": n_carry,
                                "n_elems": len(elems_flat)},
                         name="scan_op", output_specs=out_specs)
    outs = builtins.list(op.outputs)
    return _pack_like(initializer, outs) if len(outs) > 1 else outs[0]


def _lower_scan(ctx, op, inputs):
    import jax

    nc = op.attrs["n_carry"]
    ne = op.attrs["n_elems"]
    fg = op.attrs["body"]
    init = builtins.tuple(inputs[:nc])
    xs = builtins.tuple(inputs[nc:nc + ne])
    caps = builtins.list(inputs[nc + ne:])

    def step(carry, x):
        outs = lowering_mod.lower_func_graph(
            ctx, fg, builtins.list(carry) + builtins.list(x), caps)
        return builtins.tuple(outs), builtins.tuple(outs)

    _, ys = jax.lax.scan(step, init, xs)
    return builtins.list(ys)


op_registry.register("Scan", lower=_lower_scan, n_outputs=None)

# inputs = carry-init + elems + captures
optimizer_mod.register_function_op(
    "Scan", mode="loop",
    bodies=lambda a, n: [
        dict(attr="body", start=a["n_carry"] + a["n_elems"],
             count=n - a["n_carry"] - a["n_elems"], hoist=True,
             count_attr=None)],
    trip=lambda a, inputs: (_leading_dim(inputs[a["n_carry"]])
                            if len(inputs) > a["n_carry"] else None))


def foldl(fn, elems, initializer=None, parallel_iterations=10, back_prop=True,
          swap_memory=False, name=None):
    """(ref: functional_ops.py ``foldl``) → lax.scan carry."""
    single = not isinstance(elems, (list, builtins.tuple))
    elems_flat = [ops_mod.convert_to_tensor(e) for e in _flatten(elems)]
    if initializer is None:
        init = elems_flat[0][0] if single else _pack_like(
            elems, [e[0] for e in elems_flat])
        rest = [e[1:] for e in elems_flat]
        return foldl(fn, _pack_like(elems, rest) if not single else rest[0],
                     initializer=init, name=name)
    init_flat = [ops_mod.convert_to_tensor(i) for i in _flatten(initializer)]
    n_carry = len(init_flat)
    g = ops_mod.get_default_graph()
    with g.name_scope(name or "foldl"):
        def wrapper(*args):
            carry = _pack_like(initializer, builtins.list(args[:n_carry]))
            x = args[n_carry] if single else _pack_like(
                elems, builtins.list(args[n_carry:]))
            return fn(carry, x)

        specs = [(i.shape, i.dtype) for i in init_flat] + \
                [_elem_spec(e) for e in elems_flat]
        fg, _ = _build_fn_graph(wrapper, specs, "foldl_body")
        caps = [outer for outer, _ in fg.captures]
        out_specs = [(o.shape, o.dtype) for o in fg.outputs]
        op = g.create_op("Foldl", init_flat + elems_flat + caps,
                         attrs={"body": fg, "n_carry": n_carry,
                                "n_elems": len(elems_flat)},
                         name="foldl_op", output_specs=out_specs)
    outs = builtins.list(op.outputs)
    return _pack_like(initializer, outs) if len(outs) > 1 else outs[0]


def _lower_foldl(ctx, op, inputs):
    import jax

    nc = op.attrs["n_carry"]
    ne = op.attrs["n_elems"]
    fg = op.attrs["body"]
    init = builtins.tuple(inputs[:nc])
    xs = builtins.tuple(inputs[nc:nc + ne])
    caps = builtins.list(inputs[nc + ne:])

    def step(carry, x):
        outs = lowering_mod.lower_func_graph(
            ctx, fg, builtins.list(carry) + builtins.list(x), caps)
        return builtins.tuple(outs), None

    final, _ = jax.lax.scan(step, init, xs)
    return builtins.list(final)


op_registry.register("Foldl", lower=_lower_foldl, n_outputs=None)

optimizer_mod.register_function_op(
    "Foldl", mode="loop",
    bodies=lambda a, n: [
        dict(attr="body", start=a["n_carry"] + a["n_elems"],
             count=n - a["n_carry"] - a["n_elems"], hoist=True,
             count_attr=None)],
    trip=lambda a, inputs: (_leading_dim(inputs[a["n_carry"]])
                            if len(inputs) > a["n_carry"] else None))


def foldr(fn, elems, initializer=None, parallel_iterations=10, back_prop=True,
          swap_memory=False, name=None):
    from . import array_ops

    single = not isinstance(elems, (list, builtins.tuple))
    rev = [array_ops.reverse(ops_mod.convert_to_tensor(e), [0])
           for e in _flatten(elems)]
    return foldl(fn, _pack_like(elems, rev) if not single else rev[0],
                 initializer=initializer, name=name or "foldr")


# -- py_func -----------------------------------------------------------------

def py_func(func, inp, Tout, stateful=True, name=None):
    """(ref: python/ops/script_ops.py ``py_func``) → jax.pure_callback: the
    python function runs on the host inside the compiled step."""
    g = ops_mod.get_default_graph()
    inp_t = [ops_mod.convert_to_tensor(x) for x in inp]
    single = not isinstance(Tout, (list, builtins.tuple))
    touts = [Tout] if single else builtins.list(Tout)
    touts = [dtypes_mod.as_dtype(t) for t in touts]
    op = g.create_op(
        "PyFunc", inp_t,
        attrs={"func": func, "touts": builtins.tuple(touts),
               "stateful": stateful},
        name=name or "PyFunc",
        output_specs=[(shape_mod.TensorShape(None), t) for t in touts])
    return op.outputs[0] if single else builtins.list(op.outputs)


def _lower_py_func(ctx, op, inputs):
    import jax

    func = op.attrs["func"]
    touts = op.attrs["touts"]

    out_shapes = []
    for o in op.outputs:
        if not o.shape.is_fully_defined():
            raise ValueError(
                f"py_func output {o.name}: set_shape() a static shape before "
                "use (XLA needs static callback result shapes).")
        out_shapes.append(jax.ShapeDtypeStruct(builtins.tuple(o.shape.as_list()),
                                               o.dtype.np_dtype))

    def cb(*args):
        res = func(*[np.asarray(a) for a in args])
        if not isinstance(res, (list, builtins.tuple)):
            res = [res]
        return builtins.tuple(
            np.asarray(r, dtype=t.np_dtype) for r, t in zip(res, touts))

    out = jax.pure_callback(cb, builtins.tuple(out_shapes), *inputs)
    return builtins.list(out)


op_registry.register("PyFunc", lower=_lower_py_func,
                     effects=op_registry.Effects(io=True), n_outputs=None)


# ---------------------------------------------------------------------------
# sharding propagation rules (stf.analysis.sharding; ISSUE 6)
# ---------------------------------------------------------------------------

from ..analysis import sharding as _shard  # noqa: E402

_shard.register_rules(_shard.make_loop_rule("scan"), "Scan")
_shard.register_rules(_shard.make_loop_rule("map"), "MapFn")
_shard.register_rules(_shard.make_loop_rule("fold"), "Foldl", "Foldr")
