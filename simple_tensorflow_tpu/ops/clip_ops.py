"""Gradient/value clipping (ref: tensorflow/python/ops/clip_ops.py)."""

from __future__ import annotations

from ..framework import graph as ops_mod
from ..framework.indexed_slices import IndexedSlices
from . import math_ops
from .op_util import make_op


def clip_by_value(t, clip_value_min, clip_value_max, name=None):
    x = ops_mod.convert_to_tensor(t)
    lo = ops_mod.convert_to_tensor(clip_value_min, dtype=x.dtype.base_dtype)
    hi = ops_mod.convert_to_tensor(clip_value_max, dtype=x.dtype.base_dtype)
    return make_op("ClipByValue", [x, lo, hi], name=name)


def clip_by_norm(t, clip_norm, axes=None, name=None):
    x = ops_mod.convert_to_tensor(t)
    l2 = math_ops.sqrt(math_ops.reduce_sum(math_ops.square(x), axis=axes,
                                           keepdims=True))
    clip_norm_t = ops_mod.convert_to_tensor(clip_norm,
                                            dtype=x.dtype.base_dtype)
    scale = clip_norm_t / math_ops.maximum(l2, clip_norm_t)
    return math_ops.multiply(x, scale, name=name)


def global_norm(t_list, name=None):
    half_squared = []
    from . import nn_ops

    for t in t_list:
        if t is None:
            continue
        if isinstance(t, IndexedSlices):
            t = t.values
        half_squared.append(nn_ops.l2_loss(math_ops.cast(
            ops_mod.convert_to_tensor(t), "float32")))
    return math_ops.sqrt(
        math_ops.multiply(math_ops.add_n(half_squared),
                          ops_mod.convert_to_tensor(2.0)), name=name)


def clip_by_global_norm(t_list, clip_norm, use_norm=None, name=None):
    """(ref: clip_ops.py:201 ``clip_by_global_norm``)."""
    if use_norm is None:
        use_norm = global_norm(t_list)
    clip_norm_t = ops_mod.convert_to_tensor(clip_norm, dtype="float32")
    scale = clip_norm_t / math_ops.maximum(use_norm, clip_norm_t)
    clipped = []
    for t in t_list:
        if t is None:
            clipped.append(None)
        elif isinstance(t, IndexedSlices):
            clipped.append(IndexedSlices(
                t.values * math_ops.cast(scale, t.values.dtype.base_dtype),
                t.indices, t.dense_shape))
        else:
            t = ops_mod.convert_to_tensor(t)
            clipped.append(t * math_ops.cast(scale, t.dtype.base_dtype))
    return clipped, use_norm


def clip_by_average_norm(t, clip_norm, name=None):
    x = ops_mod.convert_to_tensor(t)
    from . import array_ops

    n = math_ops.cast(array_ops.size(x), x.dtype.base_dtype)
    l2 = math_ops.sqrt(math_ops.reduce_sum(math_ops.square(x))) / n
    clip_norm_t = ops_mod.convert_to_tensor(clip_norm, dtype=x.dtype.base_dtype)
    scale = clip_norm_t / math_ops.maximum(l2, clip_norm_t)
    return math_ops.multiply(x, scale, name=name)
