"""Candidate samplers (ref: tensorflow/python/ops/candidate_sampling_ops.py,
core/kernels/candidate_sampler_ops.cc, core/lib/random/distribution_sampler).

Functional-RNG reimplementation: samplers draw from the per-step key stream
like other random ops.
"""

from __future__ import annotations

import numpy as np

from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from ..framework import op_registry
from ..framework import random_seed as random_seed_mod
from ..framework import tensor_shape as shape_mod


def _sampler_op(op_type, true_classes, num_true, num_sampled, unique,
                range_max, seed, name, extra=None):
    g = ops_mod.get_default_graph()
    graph_seed, op_seed = random_seed_mod.get_seed(seed)
    true_classes = ops_mod.convert_to_tensor(true_classes,
                                             dtype=dtypes_mod.int64)
    attrs = {"num_true": int(num_true), "num_sampled": int(num_sampled),
             "unique": bool(unique), "range_max": int(range_max),
             "seed": op_seed, "_graph_seed": graph_seed}
    attrs.update(extra or {})
    batch = true_classes.shape[0].value
    op = g.create_op(
        op_type, [true_classes], attrs=attrs, name=name or op_type,
        output_specs=[
            (shape_mod.TensorShape([num_sampled]), dtypes_mod.int64),
            (shape_mod.TensorShape([batch, num_true]), dtypes_mod.float32),
            (shape_mod.TensorShape([num_sampled]), dtypes_mod.float32)])
    return op.outputs[0], op.outputs[1], op.outputs[2]


def _expected(counts_fn, ids, num_tries, range_max):
    import jax.numpy as jnp

    p = counts_fn(ids)
    # probability each id appears at least once in num_tries draws
    return -jnp.expm1(num_tries * jnp.log1p(-p))


def _make_sampler(log_uniform):
    def lower(ctx, op, inputs):
        import jax
        import jax.numpy as jnp

        key = ctx.rng_for(op)
        a = op.attrs
        n, rmax = a["num_sampled"], a["range_max"]
        if log_uniform:
            def prob(ids):
                idsf = ids.astype(jnp.float32)
                return (jnp.log((idsf + 2.0) / (idsf + 1.0)) /
                        jnp.log(rmax + 1.0))
        else:
            def prob(ids):
                return jnp.full(ids.shape, 1.0 / rmax, jnp.float32)

        if a["unique"] and rmax > (1 << 25):
            # Gumbel top-k materializes a [range_max] array; past ~32M
            # ids that is too much HBM for a sampler, so fall back to
            # with-replacement — LOUDLY, since it relaxes the unique
            # contract (the reference's rejection sampler has the same
            # asymptotic problem in its expected-tries bound).
            from ..platform import tf_logging as logging

            logging.warning(
                "%s: unique=True with range_max=%d > 2^25 falls back to "
                "with-replacement sampling (duplicate candidates "
                "possible)", op.type, rmax)
        if a["unique"] and rmax <= (1 << 25):
            # sampling WITHOUT replacement (the unique=True contract;
            # round-5 conformance sweep caught the with-replacement bug):
            # Gumbel top-k over the whole range draws exactly from the
            # target distribution without replacement, in one fused XLA
            # top_k — no rejection loop (ref: candidate_sampler_ops.cc
            # Unique samplers).
            ids_all = jnp.arange(rmax, dtype=jnp.int64)
            logits = jnp.log(prob(ids_all)) if log_uniform \
                else jnp.zeros((rmax,), jnp.float32)
            gumbel = jax.random.gumbel(key, (rmax,))
            _, sampled = jax.lax.top_k(logits + gumbel, n)
            sampled = sampled.astype(jnp.int64)
        elif log_uniform:
            u = jax.random.uniform(key, (n,))
            sampled = (jnp.exp(u * jnp.log(rmax + 1.0)) - 1.0) \
                .astype(jnp.int64)
            sampled = jnp.clip(sampled, 0, rmax - 1)
        else:
            sampled = jax.random.randint(key, (n,), 0, rmax) \
                .astype(jnp.int64)

        true_classes = inputs[0]
        num_tries = n
        true_exp = _expected(prob, true_classes, num_tries, rmax) if a["unique"] \
            else prob(true_classes) * n
        samp_exp = _expected(prob, sampled, num_tries, rmax) if a["unique"] \
            else prob(sampled) * n
        return [sampled, true_exp.astype(jnp.float32),
                samp_exp.astype(jnp.float32)]

    return lower


op_registry.register("UniformCandidateSampler",
                     lower=_make_sampler(log_uniform=False),
                     effects=op_registry.Effects(rng=True), n_outputs=3)
op_registry.register("LogUniformCandidateSampler",
                     lower=_make_sampler(log_uniform=True),
                     effects=op_registry.Effects(rng=True), n_outputs=3)


def uniform_candidate_sampler(true_classes, num_true, num_sampled, unique,
                              range_max, seed=None, name=None):
    return _sampler_op("UniformCandidateSampler", true_classes, num_true,
                       num_sampled, unique, range_max, seed, name)


def log_uniform_candidate_sampler(true_classes, num_true, num_sampled, unique,
                                  range_max, seed=None, name=None):
    return _sampler_op("LogUniformCandidateSampler", true_classes, num_true,
                       num_sampled, unique, range_max, seed, name)


def learned_unigram_candidate_sampler(true_classes, num_true, num_sampled,
                                      unique, range_max, seed=None, name=None):
    # Degrades to uniform (the reference learns counts server-side).
    return uniform_candidate_sampler(true_classes, num_true, num_sampled,
                                     unique, range_max, seed, name)


def fixed_unigram_candidate_sampler(true_classes, num_true, num_sampled,
                                    unique, range_max, vocab_file="",
                                    distortion=1.0, num_reserved_ids=0,
                                    num_shards=1, shard=0, unigrams=(),
                                    seed=None, name=None):
    return uniform_candidate_sampler(true_classes, num_true, num_sampled,
                                     unique, range_max, seed, name)


def all_candidate_sampler(true_classes, num_true, num_sampled, unique,
                          seed=None, name=None):
    return uniform_candidate_sampler(true_classes, num_true, num_sampled,
                                     unique, num_sampled, seed, name)


def _lower_accidental_hits(ctx, op, inputs):
    import jax.numpy as jnp

    true_classes, sampled = inputs
    batch = true_classes.shape[0]
    n = sampled.shape[0]
    # [batch, n]: sampled candidate j collides with a true label of row i
    hit = jnp.any(sampled[None, :, None] == true_classes[:, None, :], axis=2)
    indices = jnp.repeat(jnp.arange(batch, dtype=jnp.int32), n)
    ids = jnp.tile(jnp.arange(n, dtype=jnp.int64), batch)
    weights = jnp.where(jnp.reshape(hit, (-1,)),
                        jnp.float32(-1e37), jnp.float32(0.0))
    return [indices, ids, weights]


op_registry.register("ComputeAccidentalHits", lower=_lower_accidental_hits,
                     n_outputs=3)


def compute_accidental_hits(true_classes, sampled_candidates, num_true,
                            seed=None, name=None):
    """(ref: candidate_sampling_ops.py:343, core/kernels/
    candidate_sampler_ops.cc ComputeAccidentalHits).

    TPU-native STATIC-shape variant: the reference emits only the colliding
    (row, sampled-position) pairs — a dynamic count XLA cannot shape. We
    emit EVERY (row, position) pair (batch*num_sampled of them) with weight
    -1e37 on collisions and 0.0 elsewhere. Downstream use is
    scatter-add of weights into sampled logits, where the extra zero
    entries are no-ops — same math, static shape.
    """
    g = ops_mod.get_default_graph()
    true_classes = ops_mod.convert_to_tensor(true_classes,
                                             dtype=dtypes_mod.int64)
    sampled_candidates = ops_mod.convert_to_tensor(sampled_candidates,
                                                   dtype=dtypes_mod.int64)
    batch = true_classes.shape[0].value
    n = sampled_candidates.shape[0].value
    total = None if batch is None or n is None else batch * n
    op = g.create_op(
        "ComputeAccidentalHits", [true_classes, sampled_candidates],
        attrs={"num_true": int(num_true)},
        name=name or "ComputeAccidentalHits",
        output_specs=[
            (shape_mod.TensorShape([total]), dtypes_mod.int32),
            (shape_mod.TensorShape([total]), dtypes_mod.int64),
            (shape_mod.TensorShape([total]), dtypes_mod.float32)])
    return op.outputs[0], op.outputs[1], op.outputs[2]
