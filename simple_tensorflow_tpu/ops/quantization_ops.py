"""Quantization op family (ref: core/ops/array_ops.cc:4490
``QuantizeV2``/``Dequantize``, :4892 ``FakeQuantWithMinMax*``, kernels
core/kernels/{quantize_op,dequantize_op,fake_quant_ops}.cc and the
nudging math in fake_quant_ops_functor.h).

TPU-native: every op here is a pure device op — elementwise affine maps
and clamps that XLA fuses into neighbouring kernels (on the reference
these were standalone CPU kernels). Fake-quant ops carry custom VJPs
(straight-through estimator, range-gradient routing to min/max), so QAT
training works through ``stf.gradients`` unchanged. Serving int8 routes
through the Pallas ``quantized_matmul`` (ops/fused_ops.py).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from ..framework import op_registry
from .op_util import make_op

_QRANGE = {
    "qint8": (-128, 127), "int8": (-128, 127),
    "quint8": (0, 255), "uint8": (0, 255),
    "qint16": (-32768, 32767), "quint16": (0, 65535),
    "qint32": (-2**31, 2**31 - 1), "int32": (-2**31, 2**31 - 1),
}


# ---------------------------------------------------------------------------
# QuantizeV2 / Dequantize
# ---------------------------------------------------------------------------

def _quantize_v2_impl(x, min_range, max_range, T="qint8",
                      mode="MIN_COMBINED"):
    lo, hi = _QRANGE[T]
    np_dt = dtypes_mod.as_dtype(T).np_dtype
    # guard against degenerate ranges (ref kernel separates by epsilon)
    rng = jnp.maximum(max_range - min_range, 1e-6)
    if mode == "MIN_COMBINED":
        scale = (hi - lo) / rng
        q = (x - min_range) * scale
        if lo != 0:  # signed: center the band (ref doc: out -= (range+1)/2)
            q = q - (hi - lo + 1) / 2.0
        q = jnp.clip(jnp.round(q), lo, hi)
    elif mode == "MIN_FIRST":
        steps = hi - lo + 1
        range_adjust = steps / (steps - 1.0)
        range_scale = steps / (rng * range_adjust)
        q = (jnp.round(x * range_scale)
             - jnp.round(min_range * range_scale) + lo)
        q = jnp.clip(q, lo, hi)
    else:
        raise ValueError(f"Unknown quantize mode {mode!r}")
    return [q.astype(np_dt),
            jnp.asarray(min_range, jnp.float32),
            jnp.asarray(max_range, jnp.float32)]


def _dequantize_impl(q, min_range, max_range, T="qint8",
                     mode="MIN_COMBINED"):
    lo, hi = _QRANGE[T]
    rng = jnp.maximum(max_range - min_range, 1e-6)
    qf = q.astype(jnp.float32)
    if mode == "MIN_COMBINED":
        if lo != 0:
            qf = qf + (hi - lo + 1) / 2.0
        return qf * (rng / (hi - lo)) + min_range
    if mode == "MIN_FIRST":
        steps = hi - lo + 1
        range_adjust = steps / (steps - 1.0)
        range_scale = (rng * range_adjust) / steps
        return (qf - lo) * range_scale + min_range
    raise ValueError(f"Unknown quantize mode {mode!r}")


op_registry.register_pure("QuantizeV2", _quantize_v2_impl, n_outputs=3)
op_registry.register_pure("Dequantize", _dequantize_impl)


def quantize_v2(input, min_range, max_range, T=dtypes_mod.qint8,  # noqa: A002
                mode="MIN_COMBINED", name=None):
    """float → quantized + the (possibly adjusted) range actually used
    (ref: core/ops/array_ops.cc:4490)."""
    x = ops_mod.convert_to_tensor(input, dtype=dtypes_mod.float32)
    mn = ops_mod.convert_to_tensor(min_range, dtype=dtypes_mod.float32)
    mx = ops_mod.convert_to_tensor(max_range, dtype=dtypes_mod.float32)
    dt = dtypes_mod.as_dtype(T)
    g = ops_mod.get_default_graph()
    from ..framework import tensor_shape as shape_mod

    op = g.create_op(
        "QuantizeV2", [x, mn, mx], attrs={"T": dt.name, "mode": mode},
        name=name or "QuantizeV2",
        output_specs=[(x.shape, dt),
                      (shape_mod.scalar(), dtypes_mod.float32),
                      (shape_mod.scalar(), dtypes_mod.float32)])
    return op.outputs[0], op.outputs[1], op.outputs[2]


quantize = quantize_v2  # tf.quantize alias


def dequantize(input, min_range, max_range, mode="MIN_COMBINED",  # noqa: A002
               name=None):
    """quantized → float (ref: core/ops/array_ops.cc ``Dequantize``)."""
    x = ops_mod.convert_to_tensor(input)
    mn = ops_mod.convert_to_tensor(min_range, dtype=dtypes_mod.float32)
    mx = ops_mod.convert_to_tensor(max_range, dtype=dtypes_mod.float32)
    g = ops_mod.get_default_graph()
    op = g.create_op("Dequantize", [x, mn, mx],
                     attrs={"T": x.dtype.name, "mode": mode},
                     name=name or "Dequantize",
                     output_specs=[(x.shape, dtypes_mod.float32)])
    return op.outputs[0]


# ---------------------------------------------------------------------------
# FakeQuant (QAT) — nudged-range quantize/dequantize with custom VJPs
# ---------------------------------------------------------------------------

def _nudge(min_v, max_v, num_bits, narrow_range):
    """(nudged_min, nudged_max, scale) so that real zero maps exactly to a
    quantized step (ref: fake_quant_ops_functor.h ``Nudge``)."""
    quant_min = 1.0 if narrow_range else 0.0
    quant_max = float(2 ** num_bits - 1)
    # ref guards min<=0<=max by clamping the range to contain zero
    min_v = jnp.minimum(min_v, 0.0)
    max_v = jnp.maximum(max_v, 0.0)
    scale = (max_v - min_v) / (quant_max - quant_min)
    scale = jnp.maximum(scale, 1e-9)
    zero_point_from_min = quant_min - min_v / scale
    nudged_zero_point = jnp.clip(jnp.round(zero_point_from_min),
                                 quant_min, quant_max)
    nudged_min = (quant_min - nudged_zero_point) * scale
    nudged_max = (quant_max - nudged_zero_point) * scale
    return nudged_min, nudged_max, scale


def _fake_quant_fwd_math(x, nudged_min, nudged_max, scale):
    clamped = jnp.clip(x, nudged_min, nudged_max)
    return (jnp.round((clamped - nudged_min) / scale) * scale
            + nudged_min)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def _fake_quant_args(x, min_v, max_v, num_bits, narrow_range):
    nmin, nmax, scale = _nudge(jnp.float32(min_v), jnp.float32(max_v),
                               num_bits, narrow_range)
    return _fake_quant_fwd_math(x, nmin, nmax, scale)


def _fq_args_fwd(x, min_v, max_v, num_bits, narrow_range):
    return _fake_quant_args(x, min_v, max_v, num_bits, narrow_range), x


def _fq_args_bwd(min_v, max_v, num_bits, narrow_range, x, g):
    nmin, nmax, _ = _nudge(jnp.float32(min_v), jnp.float32(max_v),
                           num_bits, narrow_range)
    inside = (x >= nmin) & (x <= nmax)
    return (jnp.where(inside, g, 0.0),)


_fake_quant_args.defvjp(_fq_args_fwd, _fq_args_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fake_quant_vars(x, min_v, max_v, num_bits, narrow_range):
    nmin, nmax, scale = _nudge(min_v, max_v, num_bits, narrow_range)
    return _fake_quant_fwd_math(x, nmin, nmax, scale)


def _fq_vars_fwd(x, min_v, max_v, num_bits, narrow_range):
    return (_fake_quant_vars(x, min_v, max_v, num_bits, narrow_range),
            (x, min_v, max_v))


def _fq_vars_bwd(num_bits, narrow_range, res, g):
    x, min_v, max_v = res
    nmin, nmax, _ = _nudge(min_v, max_v, num_bits, narrow_range)
    below, above = x < nmin, x > nmax
    inside = ~below & ~above
    # ref FakeQuantWithMinMaxVarsGradient: input grad gated to the range;
    # min/max receive the gradient mass that fell off their side
    gx = jnp.where(inside, g, 0.0)
    gmin = jnp.sum(jnp.where(below, g, 0.0)).astype(min_v.dtype)
    gmax = jnp.sum(jnp.where(above, g, 0.0)).astype(max_v.dtype)
    return gx, gmin, gmax


_fake_quant_vars.defvjp(_fq_vars_fwd, _fq_vars_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fake_quant_per_channel(x, min_v, max_v, num_bits, narrow_range):
    # min/max have shape [d] = x.shape[-1]; broadcast over leading dims
    nmin, nmax, scale = _nudge(min_v, max_v, num_bits, narrow_range)
    return _fake_quant_fwd_math(x, nmin, nmax, scale)


def _fq_pc_fwd(x, min_v, max_v, num_bits, narrow_range):
    return (_fake_quant_per_channel(x, min_v, max_v, num_bits,
                                    narrow_range), (x, min_v, max_v))


def _fq_pc_bwd(num_bits, narrow_range, res, g):
    x, min_v, max_v = res
    nmin, nmax, _ = _nudge(min_v, max_v, num_bits, narrow_range)
    below, above = x < nmin, x > nmax
    inside = ~below & ~above
    axes = tuple(range(x.ndim - 1))
    gx = jnp.where(inside, g, 0.0)
    gmin = jnp.sum(jnp.where(below, g, 0.0), axis=axes).astype(min_v.dtype)
    gmax = jnp.sum(jnp.where(above, g, 0.0), axis=axes).astype(max_v.dtype)
    return gx, gmin, gmax


_fake_quant_per_channel.defvjp(_fq_pc_fwd, _fq_pc_bwd)


op_registry.register_pure(
    "FakeQuantWithMinMaxArgs",
    lambda x, min=-6.0, max=6.0, num_bits=8, narrow_range=False:  # noqa: A002
    _fake_quant_args(x, float(min), float(max), int(num_bits),
                     bool(narrow_range)))
op_registry.register_pure(
    "FakeQuantWithMinMaxVars",
    lambda x, mn, mx, num_bits=8, narrow_range=False:
    _fake_quant_vars(x, mn, mx, int(num_bits), bool(narrow_range)))
op_registry.register_pure(
    "FakeQuantWithMinMaxVarsPerChannel",
    lambda x, mn, mx, num_bits=8, narrow_range=False:
    _fake_quant_per_channel(x, mn, mx, int(num_bits), bool(narrow_range)))


def fake_quant_with_min_max_args(inputs, min=-6.0, max=6.0,  # noqa: A002
                                 num_bits=8, narrow_range=False, name=None):
    """(ref: core/ops/array_ops.cc:4892). Static clamp range; gradient is
    the straight-through estimator gated to [min, max]."""
    x = ops_mod.convert_to_tensor(inputs, dtype=dtypes_mod.float32)
    return make_op("FakeQuantWithMinMaxArgs", [x],
                   attrs={"min": float(min), "max": float(max),
                          "num_bits": int(num_bits),
                          "narrow_range": bool(narrow_range)}, name=name)


def fake_quant_with_min_max_vars(inputs, min, max, num_bits=8,  # noqa: A002
                                 narrow_range=False, name=None):
    """(ref: core/ops/array_ops.cc:4924). min/max are tensors (usually
    Variables) — their gradients collect the clipped mass, so the range
    TRAINS during QAT."""
    x = ops_mod.convert_to_tensor(inputs, dtype=dtypes_mod.float32)
    mn = ops_mod.convert_to_tensor(min, dtype=dtypes_mod.float32)
    mx = ops_mod.convert_to_tensor(max, dtype=dtypes_mod.float32)
    return make_op("FakeQuantWithMinMaxVars", [x, mn, mx],
                   attrs={"num_bits": int(num_bits),
                          "narrow_range": bool(narrow_range)}, name=name)


def fake_quant_with_min_max_vars_per_channel(inputs, min, max,  # noqa: A002
                                             num_bits=8, narrow_range=False,
                                             name=None):
    """(ref: core/ops/array_ops.cc FakeQuantWithMinMaxVarsPerChannel):
    per-output-channel ranges (last axis)."""
    x = ops_mod.convert_to_tensor(inputs, dtype=dtypes_mod.float32)
    mn = ops_mod.convert_to_tensor(min, dtype=dtypes_mod.float32)
    mx = ops_mod.convert_to_tensor(max, dtype=dtypes_mod.float32)
    return make_op("FakeQuantWithMinMaxVarsPerChannel", [x, mn, mx],
                   attrs={"num_bits": int(num_bits),
                          "narrow_range": bool(narrow_range)}, name=name)


# explicit gradient entry points for API parity (the custom VJPs above are
# what stf.gradients uses; these expose the same math directly,
# ref: array_ops.py:73-78 @@fake_quant_*_gradient)

def fake_quant_with_min_max_args_gradient(gradients, inputs, min=-6.0,  # noqa: A002
                                          max=6.0, num_bits=8,  # noqa: A002
                                          narrow_range=False, name=None):
    g = ops_mod.convert_to_tensor(gradients, dtype=dtypes_mod.float32)
    x = ops_mod.convert_to_tensor(inputs, dtype=dtypes_mod.float32)
    return make_op("FakeQuantArgsGrad", [g, x],
                   attrs={"min": float(min), "max": float(max),
                          "num_bits": int(num_bits),
                          "narrow_range": bool(narrow_range)}, name=name)


def fake_quant_with_min_max_vars_gradient(gradients, inputs, min, max,  # noqa: A002
                                          num_bits=8, narrow_range=False,
                                          name=None):
    g = ops_mod.convert_to_tensor(gradients, dtype=dtypes_mod.float32)
    x = ops_mod.convert_to_tensor(inputs, dtype=dtypes_mod.float32)
    mn = ops_mod.convert_to_tensor(min, dtype=dtypes_mod.float32)
    mx = ops_mod.convert_to_tensor(max, dtype=dtypes_mod.float32)
    from ..framework import tensor_shape as shape_mod

    gr = ops_mod.get_default_graph()
    op = gr.create_op(
        "FakeQuantVarsGrad", [g, x, mn, mx],
        attrs={"num_bits": int(num_bits),
               "narrow_range": bool(narrow_range)},
        name=name or "FakeQuantVarsGrad",
        output_specs=[(x.shape, dtypes_mod.float32),
                      (shape_mod.scalar(), dtypes_mod.float32),
                      (shape_mod.scalar(), dtypes_mod.float32)])
    return op.outputs[0], op.outputs[1], op.outputs[2]


def _fq_args_grad_impl(g, x, min=-6.0, max=6.0, num_bits=8,  # noqa: A002
                       narrow_range=False):
    nmin, nmax, _ = _nudge(jnp.float32(min), jnp.float32(max),
                           int(num_bits), bool(narrow_range))
    return jnp.where((x >= nmin) & (x <= nmax), g, 0.0)


def _fq_vars_grad_impl(g, x, mn, mx, num_bits=8, narrow_range=False):
    return list(_fq_vars_bwd(int(num_bits), bool(narrow_range),
                             (x, mn, mx), g))


op_registry.register_pure("FakeQuantArgsGrad", _fq_args_grad_impl)
op_registry.register_pure("FakeQuantVarsGrad", _fq_vars_grad_impl,
                          n_outputs=3)


def quantized_concat(concat_dim, values, input_mins, input_maxes,
                     name=None):
    """(ref: array_ops.cc ``QuantizedConcat``): dequantize each piece with
    its own range, concat, requantize into the combined range."""
    from . import array_ops, math_ops

    deq = [dequantize(v, mn, mx)
           for v, mn, mx in zip(values, input_mins, input_maxes)]
    out = array_ops.concat(deq, axis=concat_dim, name=name)
    out_min = math_ops.reduce_min(array_ops.stack(
        [ops_mod.convert_to_tensor(m, dtype=dtypes_mod.float32)
         for m in input_mins]))
    out_max = math_ops.reduce_max(array_ops.stack(
        [ops_mod.convert_to_tensor(m, dtype=dtypes_mod.float32)
         for m in input_maxes]))
    q, _, _ = quantize_v2(out, out_min, out_max,
                          ops_mod.convert_to_tensor(values[0]).dtype)
    return q, out_min, out_max


def fake_quant_with_min_max_vars_per_channel_gradient(
        gradients, inputs, min, max, num_bits=8,  # noqa: A002
        narrow_range=False, name=None):
    """Explicit per-channel gradient entry point (ref: array_ops.py
    @@fake_quant_with_min_max_vars_per_channel_gradient)."""
    g = ops_mod.convert_to_tensor(gradients, dtype=dtypes_mod.float32)
    x = ops_mod.convert_to_tensor(inputs, dtype=dtypes_mod.float32)
    mn = ops_mod.convert_to_tensor(min, dtype=dtypes_mod.float32)
    mx = ops_mod.convert_to_tensor(max, dtype=dtypes_mod.float32)
    from ..framework import tensor_shape as shape_mod

    gr = ops_mod.get_default_graph()
    op = gr.create_op(
        "FakeQuantPerChannelGrad", [g, x, mn, mx],
        attrs={"num_bits": int(num_bits),
               "narrow_range": bool(narrow_range)},
        name=name or "FakeQuantPerChannelGrad",
        output_specs=[(x.shape, dtypes_mod.float32),
                      (mn.shape, dtypes_mod.float32),
                      (mx.shape, dtypes_mod.float32)])
    return op.outputs[0], op.outputs[1], op.outputs[2]


op_registry.register_pure(
    "FakeQuantPerChannelGrad",
    lambda g, x, mn, mx, num_bits=8, narrow_range=False:
    list(_fq_pc_bwd(int(num_bits), bool(narrow_range), (x, mn, mx), g)),
    n_outputs=3)
