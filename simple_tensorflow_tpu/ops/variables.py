"""stf.Variable (ref: tensorflow/python/ops/variables.py ``class Variable``).

A Variable is graph metadata + a named slot in the Session's device-resident
VariableStore. The graph holds a stateful ``VariableV2`` read op (its output
is the "ref" tensor, as in TF-1.0) and an initializer ``Assign`` op. Values
live as jax.Arrays on the TPU, donated back into each step's XLA program, so
updates are in-place in HBM. Sharding metadata (set by stf.parallel scopes)
travels on the variable and becomes the state buffer's NamedSharding.
"""

from __future__ import annotations

from typing import Optional

from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from ..framework import tensor_shape as shape_mod
from . import state_ops

GraphKeys = ops_mod.GraphKeys


class Variable:
    def __init__(self, initial_value=None, trainable=True, collections=None,
                 validate_shape=True, name=None, dtype=None,
                 expected_shape=None, caching_device=None,
                 variable_def=None, import_scope=None, constraint=None):
        if initial_value is None:
            raise ValueError("initial_value must be specified.")
        g = ops_mod._root_graph()  # variables live in the root graph
        self._graph = g
        self._constraint = constraint
        self._save_slice_info = None
        self._root_ctx = ops_mod._as_current(g)
        with self._root_ctx, g.name_scope(name or "Variable") as scope:
            base = scope[:-1] if scope else g.unique_name("Variable")
            if callable(initial_value):
                with g.name_scope("Initializer"):
                    initial_value = initial_value()
            self._initial_value = ops_mod.convert_to_tensor(
                initial_value, dtype=dtype, name="initial_value")
            if validate_shape and not self._initial_value.shape.is_fully_defined():
                raise ValueError(
                    f"initial_value for {base} must have fully defined shape, "
                    f"got {self._initial_value.shape}. Pass validate_shape=False "
                    "to defer (NB: XLA still needs static shapes at run time).")
            dt = self._initial_value.dtype.base_dtype
            shape = self._initial_value.shape
            self._var_name = base
            var_op = g.create_op(
                "VariableV2", [],
                attrs={"var_name": base, "dtype": dt, "shape": shape,
                       "trainable": trainable, "sharding": None,
                       "container": g._container},
                name=base + "/" if scope else base,  # exact-name convention
                output_specs=[(shape, dt._ref)])
            self._ref = var_op.outputs[0]
            self._op = var_op
            with g.name_scope("Assign"):
                self._initializer_op = state_ops.assign(
                    self._ref, self._initial_value,
                    validate_shape=validate_shape).op
            read_op = g.create_op(
                "ReadVariable", [], attrs={"var_name": base},
                name=base + "/read" + "/",
                output_specs=[(shape, dt)])
            self._snapshot = read_op.outputs[0]

        if collections is None:
            collections = [GraphKeys.GLOBAL_VARIABLES]
        if trainable and GraphKeys.TRAINABLE_VARIABLES not in collections:
            collections = list(collections) + [GraphKeys.TRAINABLE_VARIABLES]
        g.add_to_collections(collections, self)
        self._trainable = trainable
        # Store-name registry (Session resolves shardings through it) and the
        # active shard_variables_along scope, if any.
        g._scoped_state.setdefault("__vars_by_store_name__", {})[base] = self
        from ..parallel import api as _papi

        _papi.maybe_apply_variable_sharding(self)

    # -- identity ------------------------------------------------------------
    @property
    def name(self) -> str:
        return self._ref.name

    @property
    def var_name(self) -> str:
        """Store key (op name, no ':0')."""
        return self._var_name

    @property
    def op(self):
        return self._op

    @property
    def graph(self):
        return self._graph

    @property
    def dtype(self):
        return self._ref.dtype

    @property
    def shape(self):
        return self._ref.shape

    def get_shape(self):
        return self._ref.shape

    @property
    def trainable(self):
        return self._trainable

    @property
    def initial_value(self):
        return self._initial_value

    @property
    def initializer(self):
        return self._initializer_op

    @property
    def constraint(self):
        return self._constraint

    @property
    def device(self):
        return self._op.device

    # -- sharding (TPU-native extension) -------------------------------------
    @property
    def sharding(self):
        return self._op.attrs.get("sharding")

    def set_sharding(self, spec):
        """Attach a PartitionSpec-like sharding; the Session places the
        state buffer with it (see stf/parallel)."""
        self._op.attrs["sharding"] = spec

    # -- value access --------------------------------------------------------
    def value(self):
        return self._snapshot

    def read_value(self):
        """Fresh read op: under `control_dependencies([assign])` it observes
        the write (deref-at-use, TF-1.0 ref semantics)."""
        g = ops_mod.get_default_graph()
        op = g.create_op("ReadVariable", [],
                         attrs={"var_name": self._var_name}, name="read",
                         output_specs=[(self.shape, self.dtype.base_dtype)])
        return op.outputs[0]

    def _grad_anchor(self):
        """Tensor that stf.gradients differentiates when a Variable is passed
        as an x (the ref read; ref gradients_impl handles this the same way)."""
        return self._ref

    def initialized_value(self):
        with ops_mod.get_default_graph().control_dependencies(
                [self._initializer_op]):
            return self.read_value()

    def eval(self, session=None):
        return self._ref.eval(session=session)

    # -- mutation ------------------------------------------------------------
    def assign(self, value, use_locking=False, read_value=True):
        return state_ops.assign(self._ref, value)

    def assign_add(self, delta, use_locking=False, read_value=True):
        return state_ops.assign_add(self._ref, delta)

    def assign_sub(self, delta, use_locking=False, read_value=True):
        return state_ops.assign_sub(self._ref, delta)

    def scatter_sub(self, sparse_delta, use_locking=False):
        from ..framework.indexed_slices import IndexedSlices

        assert isinstance(sparse_delta, IndexedSlices)
        return state_ops.scatter_sub(self._ref, sparse_delta.indices,
                                     sparse_delta.values)

    def load(self, value, session=None):
        """Directly set the store value (host path, no graph op)."""
        from ..client.session import get_default_session

        session = session or get_default_session()
        if session is None:
            raise ValueError("No session for Variable.load")
        session._variable_store.load(self._var_name, value, self)

    def count_up_to(self, limit):
        return state_ops.count_up_to(self._ref, limit)

    # -- graph element protocol ---------------------------------------------
    def _as_graph_element(self):
        return self._ref

    def to_proto(self, export_scope=None):
        return {
            "variable_name": self.name,
            "initial_value_name": self._initial_value.name,
            "initializer_name": self._initializer_op.name,
            "snapshot_name": self._snapshot.name,
            "trainable": self._trainable,
        }

    @classmethod
    def from_proto(cls, proto, import_scope=None, graph=None):
        """Rebind a Variable wrapper to ALREADY-IMPORTED graph ops
        (ref: variables.py ``Variable.from_proto``). Used by
        import_meta_graph / SavedModel load so Saver.restore finds the
        variables again; creates NO new ops."""
        g = graph or ops_mod.get_default_graph()

        def _scoped(name):
            return f"{import_scope}/{name}" if import_scope else name

        self = cls.__new__(cls)
        ref = g.as_graph_element(_scoped(proto["variable_name"]),
                                 allow_tensor=True, allow_operation=False)
        self._graph = g
        self._ref = ref
        self._op = ref.op
        self._var_name = ref.op.attrs.get(
            "var_name", _scoped(proto["variable_name"]).split(":")[0])
        self._trainable = bool(proto.get("trainable", True))
        self._constraint = None
        self._save_slice_info = None
        self._initializer_op = g.as_graph_element(
            _scoped(proto["initializer_name"]),
            allow_tensor=False, allow_operation=True)
        self._snapshot = g.as_graph_element(
            _scoped(proto["snapshot_name"]),
            allow_tensor=True, allow_operation=False)
        try:
            self._initial_value = g.as_graph_element(
                _scoped(proto["initial_value_name"]),
                allow_tensor=True, allow_operation=False)
        except (KeyError, ValueError):
            self._initial_value = None
        g._scoped_state.setdefault("__vars_by_store_name__",
                                   {})[self._var_name] = self
        return self

    @property
    def _shared_name(self):
        return self._var_name

    def __repr__(self):
        return (f"<stf.Variable '{self.name}' shape={self.shape} "
                f"dtype={self.dtype.base_dtype.name}>")

    # Arithmetic on variables delegates to the snapshot tensor; operator
    # overloads installed by math_ops cover Tensor, so convert first.


def _variable_conversion(value, dtype=None, name=None):
    t = value._ref
    if dtype is not None and not dtypes_mod.as_dtype(dtype).is_compatible_with(t.dtype):
        return NotImplemented
    return t


ops_mod.register_tensor_conversion_function(Variable, _variable_conversion)


# -- module-level helpers (ref: variables.py bottom half) --------------------

def global_variables():
    return ops_mod.get_default_graph().get_collection(GraphKeys.GLOBAL_VARIABLES)


def all_variables():
    return global_variables()


def local_variables():
    return ops_mod.get_default_graph().get_collection(GraphKeys.LOCAL_VARIABLES)


def model_variables():
    return ops_mod.get_default_graph().get_collection(GraphKeys.MODEL_VARIABLES)


def trainable_variables():
    return ops_mod.get_default_graph().get_collection(GraphKeys.TRAINABLE_VARIABLES)


def moving_average_variables():
    return ops_mod.get_default_graph().get_collection(
        GraphKeys.MOVING_AVERAGE_VARIABLES)


def variables_initializer(var_list, name="init"):
    from . import control_flow_ops

    if not var_list:
        return control_flow_ops.no_op(name=name)
    return control_flow_ops.group(*[v.initializer for v in var_list], name=name)


def initialize_variables(var_list, name="init"):
    return variables_initializer(var_list, name)


def global_variables_initializer():
    return variables_initializer(global_variables(), "init")


def initialize_all_variables():
    return global_variables_initializer()


def local_variables_initializer():
    return variables_initializer(local_variables(), "init_local")


def initialize_local_variables():
    return local_variables_initializer()


def is_variable_initialized(variable):
    return state_ops.is_variable_initialized(variable._ref)


def assert_variables_initialized(var_list=None):
    from . import control_flow_ops

    if var_list is None:
        var_list = global_variables() + local_variables()
    checks = [state_ops.is_variable_initialized(v._ref) for v in var_list]
    if not checks:
        return None
    from . import math_ops, array_ops, logging_ops

    stacked = array_ops.stack(checks)
    return logging_ops.Assert(math_ops.reduce_all(stacked),
                              ["Uninitialized variables"], name="assert_initialized")


def report_uninitialized_variables(var_list=None, name="report_uninitialized_variables"):
    from . import array_ops

    if var_list is None:
        var_list = global_variables() + local_variables()
    g = ops_mod.get_default_graph()
    op = g.create_op(
        "ReportUninitialized", [],
        attrs={"var_names": tuple(v._var_name for v in var_list)},
        name=name,
        output_specs=[(shape_mod.TensorShape([None]), dtypes_mod.string)])
    return op.outputs[0]


def _lower_report_uninitialized(ctx, op, inputs):
    import numpy as np

    names = [n for n in op.attrs["var_names"] if not ctx.var_exists(n)]
    return [np.asarray(names, dtype=object)]


from ..framework import op_registry  # noqa: E402

op_registry.register("ReportUninitialized", lower=_lower_report_uninitialized,
                     runs_on_host=True,
                     effects=op_registry.Effects(io=True))


class ResourceVariable(Variable):
    """Resource-semantics variable (ref:
    python/ops/resource_variable_ops.py:36 ``class ResourceVariable``).

    stf Variables already HAVE resource semantics — state lives in the
    Session's VariableStore keyed by name, reads are deref-at-use, and
    there is no ref-tensor aliasing to race on (the reference needed a
    separate class to escape TF-1 ref-variable aliasing; the functional
    JAX substrate never had it). This subclass therefore only exposes the
    resource API surface: ``handle``, ``sparse_read``, and the
    read-after-write guarantee of ``assign(...).op`` + ``read_value()``
    under control deps (already tested in test_variables.py).
    """

    @property
    def handle(self):
        """The store-keyed ref tensor doubles as the resource handle."""
        return self._ref

    def sparse_read(self, indices, name=None):
        """Gather rows from the current value (ref:
        resource_variable_ops.py ``sparse_read``)."""
        from . import array_ops

        return array_ops.gather(self.read_value(), indices, name=name)

    def gather_nd(self, indices, name=None):
        from . import array_ops

        return array_ops.gather_nd(self.read_value(), indices, name=name)


def is_resource_variable(var) -> bool:
    """(ref: resource_variable_ops.py ``is_resource_variable``). True for
    ResourceVariable instances; plain stf Variables share the semantics
    but keep the TF-1 API type."""
    return isinstance(var, ResourceVariable)


class PartitionedVariable:
    """A variable split along one axis (ref: python/ops/partitioned_variables.py).
    On TPU the natural form is a single logical array with a NamedSharding;
    this class keeps the reference's list-of-slices API while the backing
    store is the sharded array."""

    def __init__(self, name, shape, dtype, variable_list, partitions):
        self._name = name
        self._shape = shape_mod.as_shape(shape)
        self._dtype = dtype
        self._vars = list(variable_list)
        self._partitions = partitions

    @property
    def name(self):
        return self._name

    def __iter__(self):
        return iter(self._vars)

    def __len__(self):
        return len(self._vars)

    def as_tensor(self):
        from . import array_ops

        axis = next((i for i, p in enumerate(self._partitions) if p > 1), 0)
        return array_ops.concat([v._ref for v in self._vars], axis=axis,
                                name=self._name + "/concat")

    def _as_graph_element(self):
        return self.as_tensor()
