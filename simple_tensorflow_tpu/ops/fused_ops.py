"""Graph-level entry points for the Pallas fused kernels (stf.nn.fused_*).

Importing this module registers the Pallas-backed op types
(FlashAttention, FusedLayerNorm, FusedSoftmaxXent, QuantMatMul) with the
op registry, so Session lowering picks up the fused TPU kernels. It is
imported from stf.nn, i.e. `import simple_tensorflow_tpu` is enough.
"""

from __future__ import annotations

from ..framework import graph as ops_mod
from ..framework import random_seed as random_seed_mod
from ..framework import tensor_shape as shape_mod
from . import pallas as _pallas  # noqa: F401  (registers the op types)


def fused_attention(q, k, v, *, bias=None, dropout_rate=0.0, causal=False,
                    sm_scale=None, seed=None, name=None):
    """Flash attention over (batch, heads, seq, head_dim) tensors.

    bias: optional additive score bias broadcast over heads/queries —
    (batch, kv_seq) or (batch, 1, 1, kv_seq), the padding-mask shape;
    constant under differentiation. dropout_rate > 0 applies attention-
    probability dropout inside the kernel (drawn from the op's RNG
    stream, replayed exactly in the backward pass); the graph seed and
    optional op ``seed`` fold into that stream exactly like
    ``stf.nn.dropout`` (random_seed.get_seed), so
    ``stf.set_random_seed`` pins the mask — independent of which
    implementation the kernel registry routes to (stf.kernels).
    """
    g = ops_mod.get_default_graph()
    q = ops_mod.convert_to_tensor(q)
    k = ops_mod.convert_to_tensor(k)
    v = ops_mod.convert_to_tensor(v)
    inputs = [q, k, v]
    if bias is not None:
        inputs.append(ops_mod.convert_to_tensor(bias))
    attrs = {"causal": bool(causal), "sm_scale": sm_scale}
    op_type = "FlashAttention"
    if dropout_rate and float(dropout_rate) > 0.0:
        op_type = "FlashAttentionDropout"
        attrs["dropout_rate"] = float(dropout_rate)
        graph_seed, op_seed = random_seed_mod.get_seed(seed)
        attrs["seed"] = op_seed
        attrs["_graph_seed"] = graph_seed
    op = g.create_op(op_type, inputs, attrs=attrs,
                     name=name or "flash_attention",
                     output_specs=[(q.shape, q.dtype)])
    return op.outputs[0]


def fused_bias_dropout_residual(x, residual, bias=None, *, rate,
                                seed=None, name=None):
    """Fused transformer-block tail: ``residual + dropout(x + bias)``.

    x/residual: same-shape activations; bias: optional (features,)
    vector added before the dropout. rate == 0 builds the plain
    composed ops (no RNG effect); rate > 0 builds one
    FusedDropoutBiasResidual op whose counter-based mask is drawn from
    the op's per-step RNG stream (graph/op seeds fold in exactly like
    ``stf.nn.dropout``) and regenerated — never materialized — in the
    backward pass. Routed Pallas/XLA through stf.kernels; both
    implementations produce bit-identical output from the same seed.
    """
    g = ops_mod.get_default_graph()
    x = ops_mod.convert_to_tensor(x)
    residual = ops_mod.convert_to_tensor(residual)
    if bias is not None:
        bias = ops_mod.convert_to_tensor(bias, dtype=x.dtype.base_dtype)
    if not rate or float(rate) <= 0.0:
        out = x + bias + residual if bias is not None else x + residual
        return out
    graph_seed, op_seed = random_seed_mod.get_seed(seed)
    inputs = [x, residual] + ([bias] if bias is not None else [])
    op = g.create_op(
        "FusedDropoutBiasResidual", inputs,
        attrs={"rate": float(rate), "seed": op_seed,
               "_graph_seed": graph_seed},
        name=name or "fused_dropout_residual",
        output_specs=[(x.shape, x.dtype)])
    return op.outputs[0]


def fused_layer_norm(x, gamma, beta, *, eps=1e-6, name=None):
    """Fused layer norm over the last axis; gamma/beta: (features,)."""
    g = ops_mod.get_default_graph()
    x = ops_mod.convert_to_tensor(x)
    gamma = ops_mod.convert_to_tensor(gamma)
    beta = ops_mod.convert_to_tensor(beta)
    op = g.create_op("FusedLayerNorm", [x, gamma, beta],
                     attrs={"eps": float(eps)},
                     name=name or "fused_layer_norm",
                     output_specs=[(x.shape, x.dtype)])
    return op.outputs[0]


def fused_softmax_cross_entropy(logits, labels, *, label_smoothing=0.0,
                                name=None):
    """Fused sparse softmax xent; logits (..., vocab), labels (...,) int.
    label_smoothing folds soft-target training into the same streamed
    kernel pass (no dense one-hot / log_softmax materialization)."""
    from ..framework import dtypes as dtypes_mod

    g = ops_mod.get_default_graph()
    logits = ops_mod.convert_to_tensor(logits)
    labels = ops_mod.convert_to_tensor(labels)
    out_shape = (logits.shape[:-1] if logits.shape.rank is not None
                 else shape_mod.TensorShape(None))
    op = g.create_op("FusedSoftmaxXent", [logits, labels],
                     attrs={"label_smoothing": float(label_smoothing)},
                     name=name or "fused_softmax_xent",
                     output_specs=[(out_shape, dtypes_mod.float32)])
    return op.outputs[0]


def quantized_matmul(x, wq, w_scale, *, name=None):
    """x @ dequant(wq): x (m,k) float, wq (k,n) int8, w_scale (n,) f32."""
    g = ops_mod.get_default_graph()
    x = ops_mod.convert_to_tensor(x)
    wq = ops_mod.convert_to_tensor(wq)
    w_scale = ops_mod.convert_to_tensor(w_scale)
    m = x.shape[0] if x.shape.rank is not None else None
    n = wq.shape[1] if wq.shape.rank is not None else None
    op = g.create_op("QuantMatMul", [x, wq, w_scale],
                     name=name or "quant_matmul",
                     output_specs=[(shape_mod.TensorShape([m, n]), x.dtype)])
    return op.outputs[0]


# ---------------------------------------------------------------------------
# sharding propagation rules (stf.analysis.sharding; ISSUE 6)
# ---------------------------------------------------------------------------

from ..analysis import sharding as _shard  # noqa: E402


def _flash_attention_rule(op, in_specs, ctx):
    # (B, H, S, D): batch/head sharding flows through (GSPMD partitions
    # attention per batch/head shard); a sharded seq or head_dim would
    # need ring/halo communication the fused kernel does not do, so
    # those dims are consumed gathered (ring_attention is the sp path).
    sq = in_specs[0]
    if sq is None:
        return [None]
    joined = sq
    for s in in_specs[1:3]:
        if s is not None and len(s) == len(sq):
            joined = ctx.join(joined, s)
    out = tuple(e if d < 2 else ()
                for d, e in enumerate(joined or sq))
    for i in range(min(3, len(in_specs))):
        s = in_specs[i]
        if s is not None and len(s) == len(out) and s != out:
            ctx.require(i, out)
    return [out]


_shard.register_rules(_flash_attention_rule, "FlashAttention",
                      "FlashAttentionDropout")


def _fused_layer_norm_rule(op, in_specs, ctx):
    # normalizes the last (feature) axis: x's spec is preserved; a
    # sharded feature dim costs an all-reduce of the per-row mean/var
    # (2 floats/row); gamma/beta must match x's feature sharding
    sx = in_specs[0]
    if sx is None or not sx:
        return [sx for _ in op.outputs]
    red = tuple(a for a in sx[-1] if ctx.mesh_axes.get(a, 1) > 1)
    if red:
        out_t = op.outputs[0]
        dims = _shard._dims_of(out_t)
        feat = (dims[-1] or 1) if dims else 1
        ctx.collective(
            "all-reduce", red,
            2.0 * _shard.tensor_bytes(out_t) / max(feat, 1)
            / ctx.shard_factor(sx),
            note="layer-norm stats over sharded feature dim",
            tensor_name=out_t.name)
    for i in (1, 2):
        if i < len(in_specs) and in_specs[i] is not None \
                and len(in_specs[i]) == 1 and in_specs[i][0] != sx[-1]:
            ctx.require(i, (sx[-1],))
    return [sx for _ in op.outputs]


_shard.register_rules(_fused_layer_norm_rule, "FusedLayerNorm")
_shard.register_rules(_shard.make_last_dim_reduce_rule(),
                      "FusedSoftmaxXent")
_shard.register_rules(_shard.matmul_rule, "QuantMatMul")


def _dropout_residual_rule(op, in_specs, ctx):
    # elementwise over x/residual (bias replicated along the feature
    # sharding): join the two activation specs like a binary
    # elementwise op; the counter-based mask is position-keyed, so any
    # sharding is mask-consistent
    sx = in_specs[0]
    sr = in_specs[1] if len(in_specs) > 1 else None
    out = sx
    if sx is not None and sr is not None and len(sr) == len(sx):
        out = ctx.join(sx, sr)
    elif sx is None:
        out = sr
    return [out]


_shard.register_rules(_dropout_residual_rule, "FusedDropoutBiasResidual")


def _fused_optimizer_update_rule(op, in_specs, ctx):
    # consumes grads (+ scalar hypers), writes variables through the
    # store — no tensor outputs to propagate; variable-state sharding
    # is owned by the store's committed shardings, not the data edges
    return []


_shard.register_rules(_fused_optimizer_update_rule, "FusedAdamUpdate",
                      "FusedMomentumUpdate")
