"""Misc ops: confusion_matrix, histogram, bitcast, sets, special math
(ref: tensorflow/python/ops/{confusion_matrix,histogram_ops,sets_impl,
special_math_ops}.py, core/kernels/bitcast_op.cc).

Set ops: the reference returns SparseTensors from variable-length set
results; XLA needs static shapes, so set ops here are dense-membership
formulations — results come back as fixed-size masks/padded values, the
TPU-native shape discipline.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from ..framework import op_registry
from ..framework import tensor_shape as shape_mod
from .op_util import make_op

# -- confusion matrix --------------------------------------------------------

op_registry.register_pure(
    "ConfusionMatrix",
    lambda labels, predictions, weights=None, num_classes=0:
        jnp.zeros((num_classes, num_classes),
                  dtypes_mod.narrowed_if_no_x64(dtypes_mod.float64).np_dtype
                  if weights is not None else jnp.int32
                  ).at[labels, predictions].add(
                      1 if weights is None else weights))


def confusion_matrix(labels, predictions, num_classes=None, dtype=None,
                     name=None, weights=None):
    """(ref: confusion_matrix.py:105 ``confusion_matrix``). num_classes must
    be static on TPU (output shape)."""
    from . import math_ops

    labels = ops_mod.convert_to_tensor(labels)
    predictions = ops_mod.convert_to_tensor(predictions)
    if num_classes is None:
        raise ValueError(
            "confusion_matrix on TPU needs static num_classes (dynamic "
            "max(labels)+1 would make the output shape data-dependent)")
    n = int(num_classes)
    inputs = [labels, predictions]
    if weights is not None:
        inputs.append(ops_mod.convert_to_tensor(weights))
    out_dtype = dtypes_mod.as_dtype(dtype) if dtype else (
        dtypes_mod.float64 if weights is not None else dtypes_mod.int32)
    g = ops_mod.get_default_graph()
    op = g.create_op("ConfusionMatrix", inputs,
                     attrs={"num_classes": n},
                     name=name or "confusion_matrix",
                     output_specs=[(shape_mod.TensorShape([n, n]),
                                    out_dtype)])
    result = op.outputs[0]
    if dtype is not None:
        result = math_ops.cast(result, dtypes_mod.as_dtype(dtype))
    return result


# -- histogram ---------------------------------------------------------------

def _histogram_fixed_width(values, lo, hi, nbins=100):
    values = values.reshape(-1).astype(jnp.float32)
    width = (hi - lo) / nbins
    idx = jnp.clip(((values - lo) / width).astype(jnp.int32), 0, nbins - 1)
    return jnp.zeros((nbins,), jnp.int32).at[idx].add(1)


op_registry.register_pure(
    "HistogramFixedWidth",
    lambda values, lo, hi, nbins=100: _histogram_fixed_width(
        values, lo, hi, nbins))


def histogram_fixed_width(values, value_range, nbins=100, dtype=None,
                          name=None):
    """(ref: histogram_ops.py:30)."""
    values = ops_mod.convert_to_tensor(values)
    lo = ops_mod.convert_to_tensor(value_range[0],
                                   dtype=dtypes_mod.float32)
    hi = ops_mod.convert_to_tensor(value_range[1],
                                   dtype=dtypes_mod.float32)
    g = ops_mod.get_default_graph()
    op = g.create_op("HistogramFixedWidth", [values, lo, hi],
                     attrs={"nbins": int(nbins)},
                     name=name or "histogram_fixed_width",
                     output_specs=[(shape_mod.TensorShape([int(nbins)]),
                                    dtypes_mod.int32)])
    return op.outputs[0]


# -- bitcast -----------------------------------------------------------------

def bitcast(input, type, name=None):  # noqa: A002
    """(ref: bitcast_op.cc): reinterpret bytes. Same-size dtypes keep the
    shape; smaller target dtypes append an axis (XLA semantics, which the
    reference matches). Lowers through the math_ops "Bitcast" pure op
    (jax.lax.bitcast_convert_type)."""
    x = ops_mod.convert_to_tensor(input)
    dst = dtypes_mod.as_dtype(type)
    in_shape = x.shape.as_list() if x.shape.rank is not None else None
    if in_shape is not None:
        src_b = np.dtype(x.dtype.as_numpy_dtype).itemsize
        dst_b = np.dtype(dst.as_numpy_dtype).itemsize
        if src_b == dst_b:
            out_shape = in_shape
        elif src_b > dst_b:
            out_shape = in_shape + [src_b // dst_b]
        else:
            out_shape = in_shape[:-1]
        out_shape = shape_mod.TensorShape(out_shape)
    else:
        out_shape = shape_mod.TensorShape(None)
    g = ops_mod.get_default_graph()
    op = g.create_op("Bitcast", [x],
                     attrs={"dtype": dst},
                     name=name or "bitcast",
                     output_specs=[(out_shape, dst)])
    return op.outputs[0]


# -- sets (dense-membership formulations) ------------------------------------

def _pad_val(dtype):
    return jnp.iinfo(dtype).min if jnp.issubdtype(dtype, jnp.integer) \
        else -jnp.inf


def _set_size(a):
    """Count distinct non-pad values per row; a: (..., n) sorted-agnostic."""
    s = jnp.sort(a, axis=-1)
    first = jnp.ones(s.shape[:-1] + (1,), bool)
    new = jnp.concatenate([first, s[..., 1:] != s[..., :-1]], axis=-1)
    valid = s != _pad_val(s.dtype)
    return jnp.sum(new & valid, axis=-1).astype(jnp.int32)


op_registry.register_pure("SetSize", lambda a: _set_size(a))


def _membership(a, b):
    """mask over a's last axis: a[i] in b (rowwise)."""
    return (a[..., :, None] == b[..., None, :]).any(axis=-1)


def _set_intersection(a, b):
    pad = _pad_val(a.dtype)
    keep = _membership(a, b) & (a != pad)
    vals = jnp.where(keep, a, pad)
    s = jnp.sort(vals, axis=-1)  # pad (min) sorts first; dedupe
    dup = jnp.concatenate(
        [jnp.zeros(s.shape[:-1] + (1,), bool), s[..., 1:] == s[..., :-1]],
        axis=-1)
    return jnp.where(dup, pad, s)


def _set_difference(a, b, aminusb=True):
    if not aminusb:
        a, b = b, a
    pad = _pad_val(a.dtype)
    keep = (~_membership(a, b)) & (a != pad)
    vals = jnp.where(keep, a, pad)
    s = jnp.sort(vals, axis=-1)
    dup = jnp.concatenate(
        [jnp.zeros(s.shape[:-1] + (1,), bool), s[..., 1:] == s[..., :-1]],
        axis=-1)
    return jnp.where(dup, pad, s)


def _set_union(a, b):
    pad = _pad_val(a.dtype)
    both = jnp.concatenate([a, b], axis=-1)
    s = jnp.sort(both, axis=-1)
    dup = jnp.concatenate(
        [jnp.zeros(s.shape[:-1] + (1,), bool), s[..., 1:] == s[..., :-1]],
        axis=-1)
    return jnp.where(dup, pad, s)


op_registry.register_pure("SetIntersection",
                          lambda a, b: _set_intersection(a, b))
op_registry.register_pure("SetDifference",
                          lambda a, b, aminusb=True: _set_difference(
                              a, b, aminusb))
op_registry.register_pure("SetUnion", lambda a, b: _set_union(a, b))


def _set_binary(op_type, a, b, extra_attrs=None, width=None, name=None):
    a = ops_mod.convert_to_tensor(a)
    b = ops_mod.convert_to_tensor(b)
    g = ops_mod.get_default_graph()
    if width is None:
        ash = a.shape.as_list() if a.shape.rank is not None else None
        out_shape = shape_mod.TensorShape(ash)
    else:
        out_shape = shape_mod.TensorShape(
            (a.shape.as_list()[:-1] if a.shape.rank else [None]) + [width])
    op = g.create_op(op_type, [a, b], attrs=extra_attrs or {},
                     name=name or op_type,
                     output_specs=[(out_shape, a.dtype)])
    return op.outputs[0]


def set_intersection(a, b, name=None):
    """Padded-dense set intersection (pad = dtype min; ref sets_impl.py
    returns a SparseTensor — see module docstring for the TPU shape rule)."""
    return _set_binary("SetIntersection", a, b, name=name)


def set_difference(a, b, aminusb=True, name=None):
    return _set_binary("SetDifference", a, b,
                       extra_attrs={"aminusb": bool(aminusb)}, name=name)


def set_union(a, b, name=None):
    a_t = ops_mod.convert_to_tensor(a)
    b_t = ops_mod.convert_to_tensor(b)
    w = None
    if a_t.shape.rank is not None and b_t.shape.rank is not None:
        an, bn = a_t.shape.as_list()[-1], b_t.shape.as_list()[-1]
        if an is not None and bn is not None:
            w = an + bn
    return _set_binary("SetUnion", a_t, b_t, width=w, name=name)


def set_size(a, validate_indices=True, name=None):
    a = ops_mod.convert_to_tensor(a)
    g = ops_mod.get_default_graph()
    out_shape = shape_mod.TensorShape(
        a.shape.as_list()[:-1] if a.shape.rank is not None else None)
    op = g.create_op("SetSize", [a], name=name or "set_size",
                     output_specs=[(out_shape, dtypes_mod.int32)])
    return op.outputs[0]


SET_PAD = _pad_val  # exposed for tests/users to identify padding


# -- special math ------------------------------------------------------------

def lbeta(x, name=None):
    """(ref: special_math_ops.py:34 ``lbeta``): log(|Beta(x)|) reduced over
    the last axis."""
    from . import math_ops

    x = ops_mod.convert_to_tensor(x)
    with ops_mod.name_scope(name or "lbeta"):
        log_gamma = math_ops.lgamma(x)
        sum_log_gamma = math_ops.reduce_sum(log_gamma, axis=-1)
        log_gamma_sum = math_ops.lgamma(math_ops.reduce_sum(x, axis=-1))
        return sum_log_gamma - log_gamma_sum


def einsum(equation, *inputs, name=None):
    from . import math_ops

    return math_ops.einsum(equation, *inputs)


def remove_squeezable_dimensions(labels, predictions, name=None):
    """(ref: confusion_matrix.py ``remove_squeezable_dimensions``): if one
    of the pair has exactly one more trailing size-1 dim, squeeze it."""
    from . import array_ops

    labels = ops_mod.convert_to_tensor(labels)
    predictions = ops_mod.convert_to_tensor(predictions)
    lr, pr = labels.shape.rank, predictions.shape.rank
    if lr is not None and pr is not None:
        if pr - lr == 1 and predictions.shape[-1].value == 1:
            predictions = array_ops.squeeze(predictions, axis=[-1])
        elif lr - pr == 1 and labels.shape[-1].value == 1:
            labels = array_ops.squeeze(labels, axis=[-1])
    return labels, predictions
