"""Logging/assert ops (ref: tensorflow/python/ops/logging_ops.py,
core/kernels/logging_ops.cc).

Print lowers to jax.debug.print (works inside the compiled XLA program
via host callback). Assert rides the CheckNumerics flag channel: the
condition evaluates in the compiled step, the flag is fetched with the
results, and the Session raises a typed InvalidArgumentError host-side
BEFORE committing variable updates (ref semantics: ops downstream of a
failed assert never take effect). Inside lax control flow / shard_map a
flag cannot escape the trace, so there a failing assert raises through
the jax callback (surfaces as JaxRuntimeError — catch Exception around
the run call in that case)."""

from __future__ import annotations

import numpy as np

from ..framework import graph as ops_mod
from ..framework import op_registry
from ..framework import tensor_shape as shape_mod
from ..framework import dtypes as dtypes_mod


def _lower_print(ctx, op, inputs):
    import jax

    data = inputs[1:]
    message = op.attrs.get("message", "")
    summarize = op.attrs.get("summarize", 3)
    if data:
        fmt = (message or "") + " ".join("{}" for _ in data)
        jax.debug.print(fmt, *data)
    return [inputs[0]]


op_registry.register("Print", lower=_lower_print,
                     effects=op_registry.Effects(io=True))


def _lower_assert_checked(ctx, op, inputs):
    """Assert rides the CheckNumerics flag channel: the condition is
    evaluated in the compiled step (fused with its producers), the flag
    is fetched with the results, and the SESSION raises a typed
    InvalidArgumentError host-side before committing state — a raise
    from inside a jax callback would surface as an opaque
    JaxRuntimeError that ``except stf.errors.InvalidArgumentError``
    cannot catch. A debug callback still prints the data tensors'
    runtime values on failure (the reference kernel's summarize role)."""
    import jax
    import jax.numpy as jnp

    cond = inputs[0]
    data = inputs[1:]
    summarize = op.attrs.get("summarize") or 3
    message = op.attrs.get("message", "")

    def _format(d_vals):
        vals = " ".join(str(np.asarray(x).ravel()[:summarize])
                        for x in d_vals)
        head = f"assertion failed ({op.name})"
        if message:
            head += f": {message}"
        return f"{head}: {vals}" if vals else head

    if ctx.host:
        if not np.all(np.asarray(cond)):
            from ..framework import errors

            raise errors.InvalidArgumentError(None, op, _format(data))
        return []
    if ctx.in_control_flow or ctx.in_shard_map:
        # a flag cannot escape a lax trace: raise from the callback
        # (surfaces as JaxRuntimeError; see module docstring)
        def _cb_raise(c, *d):
            if not np.all(np.asarray(c)):
                from ..framework import errors

                raise errors.InvalidArgumentError(None, None, _format(d))

        jax.debug.callback(_cb_raise, cond, *data)
        return []
    flag = jnp.logical_not(jnp.all(cond))
    ctx.numeric_checks.append(
        (_format(()) + " — data values in the printed line above", flag))

    def _cb(c, *d):
        if not np.all(np.asarray(c)):
            print("stf.Assert " + _format(d), flush=True)

    jax.debug.callback(_cb, cond, *data)
    return []


op_registry.register("Assert", lower=_lower_assert_checked,
                     effects=op_registry.Effects(io=True), n_outputs=0)


def Print(input_, data, message=None, first_n=None, summarize=None, name=None):
    """(ref: logging_ops.py:37 ``Print``)."""
    x = ops_mod.convert_to_tensor(input_)
    data_t = [ops_mod.convert_to_tensor(d) for d in data]
    g = ops_mod.get_default_graph()
    op = g.create_op("Print", [x] + data_t,
                     attrs={"message": message or "",
                            "summarize": summarize or 3},
                     name=name or "Print",
                     output_specs=[(x.shape, x.dtype)])
    return op.outputs[0]


def Assert(condition, data, summarize=None, name=None):
    """(ref: control_flow_ops.py ``Assert``). String data folds into the
    static message (strings never enter the XLA program)."""
    from ..framework import constant_op

    cond_t = ops_mod.convert_to_tensor(condition)
    msg_parts = []
    data_t = []
    for d in data:
        if isinstance(d, (str, bytes)):
            msg_parts.append(d.decode() if isinstance(d, bytes) else d)
            continue
        t = ops_mod.convert_to_tensor(d)
        if t.dtype.name == "string":
            v = constant_op.constant_value(t)
            msg_parts.append(str(v))
            continue
        data_t.append(t)
    g = ops_mod.get_default_graph()
    op = g.create_op("Assert", [cond_t] + data_t,
                     attrs={"summarize": summarize or 3,
                            "message": " ".join(msg_parts)},
                     name=name or "Assert", output_specs=[])
    return op


def histogram_summary(*a, **k):
    from ..summary import summary

    return summary.histogram(*a, **k)
