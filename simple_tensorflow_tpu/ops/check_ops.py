"""Assertion ops (ref: tensorflow/python/ops/check_ops.py).

Each assert_* returns an Operation suitable for control_dependencies; checks
execute in-graph via a host callback (see logging_ops.Assert).
"""

from __future__ import annotations

from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from . import math_ops
from .logging_ops import Assert


def _binary_assert(check, x, y, data, message, name):
    x = ops_mod.convert_to_tensor(x)
    y = ops_mod.convert_to_tensor(y, dtype=x.dtype.base_dtype)
    cond = math_ops.reduce_all(check(x, y))
    if data is None:
        data = [x, y]
    return Assert(cond, [message or ""] + list(data), name=name)


def assert_equal(x, y, data=None, summarize=None, message=None, name=None):
    return _binary_assert(math_ops.equal, x, y, data, message, name)


def assert_none_equal(x, y, data=None, summarize=None, message=None, name=None):
    return _binary_assert(math_ops.not_equal, x, y, data, message, name)


def assert_less(x, y, data=None, summarize=None, message=None, name=None):
    return _binary_assert(math_ops.less, x, y, data, message, name)


def assert_less_equal(x, y, data=None, summarize=None, message=None, name=None):
    return _binary_assert(math_ops.less_equal, x, y, data, message, name)


def assert_greater(x, y, data=None, summarize=None, message=None, name=None):
    return _binary_assert(math_ops.greater, x, y, data, message, name)


def assert_greater_equal(x, y, data=None, summarize=None, message=None,
                         name=None):
    return _binary_assert(math_ops.greater_equal, x, y, data, message, name)


def _unary_assert(check, x, data, message, name):
    x = ops_mod.convert_to_tensor(x)
    cond = math_ops.reduce_all(check(x))
    return Assert(cond, [message or ""] + list(data or [x]), name=name)


def assert_negative(x, data=None, summarize=None, message=None, name=None):
    zero = ops_mod.convert_to_tensor(0, dtype=ops_mod.convert_to_tensor(x).dtype.base_dtype)
    return _binary_assert(math_ops.less, x, zero, data, message, name)


def assert_positive(x, data=None, summarize=None, message=None, name=None):
    zero = ops_mod.convert_to_tensor(0, dtype=ops_mod.convert_to_tensor(x).dtype.base_dtype)
    return _binary_assert(math_ops.greater, x, zero, data, message, name)


def assert_non_negative(x, data=None, summarize=None, message=None, name=None):
    zero = ops_mod.convert_to_tensor(0, dtype=ops_mod.convert_to_tensor(x).dtype.base_dtype)
    return _binary_assert(math_ops.greater_equal, x, zero, data, message, name)


def assert_non_positive(x, data=None, summarize=None, message=None, name=None):
    zero = ops_mod.convert_to_tensor(0, dtype=ops_mod.convert_to_tensor(x).dtype.base_dtype)
    return _binary_assert(math_ops.less_equal, x, zero, data, message, name)


def assert_rank(x, rank, data=None, summarize=None, message=None, name=None):
    x = ops_mod.convert_to_tensor(x)
    static = x.shape.rank
    if static is not None:
        if static != int(rank):
            raise ValueError(
                message or f"Tensor {x.name} must have rank {rank}, got {static}")
        from . import control_flow_ops

        return control_flow_ops.no_op(name=name)
    from . import array_ops

    return _binary_assert(math_ops.equal, array_ops.rank(x),
                          ops_mod.convert_to_tensor(int(rank)), data, message,
                          name)


def assert_rank_at_least(x, rank, data=None, summarize=None, message=None,
                         name=None):
    x = ops_mod.convert_to_tensor(x)
    static = x.shape.rank
    if static is not None:
        if static < int(rank):
            raise ValueError(
                message or f"Tensor {x.name} must have rank >= {rank}")
        from . import control_flow_ops

        return control_flow_ops.no_op(name=name)
    from . import array_ops

    return _binary_assert(math_ops.greater_equal, array_ops.rank(x),
                          ops_mod.convert_to_tensor(int(rank)), data, message,
                          name)


def assert_rank_in(x, ranks, data=None, summarize=None, message=None, name=None):
    x = ops_mod.convert_to_tensor(x)
    static = x.shape.rank
    if static is not None:
        if static not in [int(r) for r in ranks]:
            raise ValueError(message or f"rank {static} not in {ranks}")
        from . import control_flow_ops

        return control_flow_ops.no_op(name=name)
    raise ValueError("assert_rank_in needs static rank on TPU")


def assert_type(tensor, tf_type, message=None, name=None):
    tensor = ops_mod.convert_to_tensor(tensor)
    if tensor.dtype.base_dtype != dtypes_mod.as_dtype(tf_type).base_dtype:
        raise TypeError(
            message or f"{tensor.name} must be of type {tf_type}")
    from . import control_flow_ops

    return control_flow_ops.no_op(name=name)


def assert_integer(x, message=None, name=None):
    x = ops_mod.convert_to_tensor(x)
    if not x.dtype.is_integer:
        raise TypeError(message or f"{x.name} must be integer")
    from . import control_flow_ops

    return control_flow_ops.no_op(name=name)


def assert_scalar(tensor, name=None, message=None):
    tensor = ops_mod.convert_to_tensor(tensor)
    if tensor.shape.rank not in (None, 0):
        raise ValueError(message or f"{tensor.name} must be scalar")
    return tensor


def assert_proper_iterable(values):
    if isinstance(values, (str, bytes, ops_mod.Tensor)):
        raise TypeError(f"Expected iterable, got {type(values)}")


def is_numeric_tensor(tensor):
    return isinstance(tensor, ops_mod.Tensor) and not (
        tensor.dtype.name == "string" or tensor.dtype.is_bool)


def is_non_decreasing(x, name=None):
    """(ref: check_ops.py ``is_non_decreasing``)."""
    from . import array_ops, math_ops

    x = ops_mod.convert_to_tensor(x)
    flat = array_ops.reshape(x, [-1])
    n = flat.shape[0].value
    if n is not None and n < 2:
        from ..framework import constant_op

        return constant_op.constant(True)
    return math_ops.reduce_all(
        math_ops.greater_equal(flat[1:], flat[:-1]), name=name)


def is_strictly_increasing(x, name=None):
    from . import array_ops, math_ops

    x = ops_mod.convert_to_tensor(x)
    flat = array_ops.reshape(x, [-1])
    n = flat.shape[0].value
    if n is not None and n < 2:
        from ..framework import constant_op

        return constant_op.constant(True)
    return math_ops.reduce_all(
        math_ops.greater(flat[1:], flat[:-1]), name=name)
