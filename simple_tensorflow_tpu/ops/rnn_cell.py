"""RNN cells (ref: tensorflow/python/ops/rnn_cell_impl.py).

Cells are graph-building callables exactly like the reference; the loop
around them (dynamic_rnn) lowers to lax.scan so the whole unrolled
computation is one differentiable XLA while-program with stacked weights
resident in HBM.
"""

from __future__ import annotations

import collections

from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from . import array_ops, init_ops, math_ops, nn_ops
from . import variable_scope as vs

LSTMStateTuple = collections.namedtuple("LSTMStateTuple", ("c", "h"))


class RNNCell:
    """(ref: rnn_cell_impl.py:104 ``class RNNCell``)."""

    @property
    def state_size(self):
        raise NotImplementedError

    @property
    def output_size(self):
        raise NotImplementedError

    def __call__(self, inputs, state, scope=None):
        raise NotImplementedError

    def zero_state(self, batch_size, dtype):
        from ..framework import constant_op

        def mk(size):
            return array_ops.zeros([int(batch_size), int(size)], dtype)

        ss = self.state_size
        if isinstance(ss, LSTMStateTuple):
            return LSTMStateTuple(mk(ss.c), mk(ss.h))
        if isinstance(ss, (list, tuple)):
            return tuple(s.zero_state(batch_size, dtype)
                         if isinstance(s, RNNCell) else mk(s) for s in ss)
        return mk(ss)


def _linear(args, output_size, bias, bias_start=0.0, scope_name="linear"):
    if not isinstance(args, (list, tuple)):
        args = [args]
    total = sum(a.shape[-1].value for a in args)
    dtype = args[0].dtype.base_dtype
    w = vs.get_variable(f"{scope_name}/kernel", [total, output_size],
                        dtype=dtype)
    x = args[0] if len(args) == 1 else array_ops.concat(list(args), 1)
    out = math_ops.matmul(x, w._ref)
    if bias:
        b = vs.get_variable(f"{scope_name}/bias", [output_size], dtype=dtype,
                            initializer=init_ops.Constant(bias_start,
                                                          dtype=dtype))
        out = nn_ops.bias_add(out, b._ref)
    return out


class BasicRNNCell(RNNCell):
    def __init__(self, num_units, activation=math_ops.tanh, reuse=None):
        self._num_units = num_units
        self._activation = activation

    @property
    def state_size(self):
        return self._num_units

    @property
    def output_size(self):
        return self._num_units

    def __call__(self, inputs, state, scope=None):
        with vs.variable_scope(scope or "basic_rnn_cell",
                               reuse=vs.AUTO_REUSE):
            out = self._activation(_linear([inputs, state], self._num_units,
                                           True))
        return out, out


class GRUCell(RNNCell):
    """(ref: rnn_cell_impl.py ``GRUCell``)."""

    def __init__(self, num_units, activation=math_ops.tanh, reuse=None):
        self._num_units = num_units
        self._activation = activation

    @property
    def state_size(self):
        return self._num_units

    @property
    def output_size(self):
        return self._num_units

    def __call__(self, inputs, state, scope=None):
        with vs.variable_scope(scope or "gru_cell", reuse=vs.AUTO_REUSE):
            gates = math_ops.sigmoid(_linear([inputs, state],
                                             2 * self._num_units, True, 1.0,
                                             "gates"))
            r = gates[:, :self._num_units]
            u = gates[:, self._num_units:]
            c = self._activation(_linear([inputs, r * state], self._num_units,
                                         True, 0.0, "candidate"))
            new_h = u * state + (1 - u) * c
        return new_h, new_h


class BasicLSTMCell(RNNCell):
    """(ref: rnn_cell_impl.py ``BasicLSTMCell``)."""

    def __init__(self, num_units, forget_bias=1.0, state_is_tuple=True,
                 activation=math_ops.tanh, reuse=None):
        self._num_units = num_units
        self._forget_bias = forget_bias
        self._state_is_tuple = state_is_tuple
        self._activation = activation

    @property
    def state_size(self):
        if self._state_is_tuple:
            return LSTMStateTuple(self._num_units, self._num_units)
        return 2 * self._num_units

    @property
    def output_size(self):
        return self._num_units

    def __call__(self, inputs, state, scope=None):
        with vs.variable_scope(scope or "basic_lstm_cell",
                               reuse=vs.AUTO_REUSE):
            if self._state_is_tuple:
                c, h = state
            else:
                c = state[:, :self._num_units]
                h = state[:, self._num_units:]
            concat = _linear([inputs, h], 4 * self._num_units, True)
            n = self._num_units
            i, j, f, o = (concat[:, :n], concat[:, n:2 * n],
                          concat[:, 2 * n:3 * n], concat[:, 3 * n:])
            new_c = (c * math_ops.sigmoid(f + self._forget_bias) +
                     math_ops.sigmoid(i) * self._activation(j))
            new_h = self._activation(new_c) * math_ops.sigmoid(o)
            if self._state_is_tuple:
                return new_h, LSTMStateTuple(new_c, new_h)
            return new_h, array_ops.concat([new_c, new_h], 1)


LSTMCell = BasicLSTMCell


class MultiRNNCell(RNNCell):
    def __init__(self, cells, state_is_tuple=True):
        self._cells = list(cells)
        self._state_is_tuple = state_is_tuple

    @property
    def state_size(self):
        return tuple(c.state_size for c in self._cells)

    @property
    def output_size(self):
        return self._cells[-1].output_size

    def zero_state(self, batch_size, dtype):
        return tuple(c.zero_state(batch_size, dtype) for c in self._cells)

    def __call__(self, inputs, state, scope=None):
        new_states = []
        cur = inputs
        with vs.variable_scope(scope or "multi_rnn_cell",
                               reuse=vs.AUTO_REUSE):
            for i, cell in enumerate(self._cells):
                with vs.variable_scope(f"cell_{i}", reuse=vs.AUTO_REUSE):
                    cur, new_s = cell(cur, state[i])
                    new_states.append(new_s)
        return cur, tuple(new_states)


class DropoutWrapper(RNNCell):
    def __init__(self, cell, input_keep_prob=1.0, output_keep_prob=1.0,
                 state_keep_prob=1.0, seed=None):
        self._cell = cell
        self._ikp, self._okp, self._skp = (input_keep_prob, output_keep_prob,
                                           state_keep_prob)
        self._seed = seed

    @property
    def state_size(self):
        return self._cell.state_size

    @property
    def output_size(self):
        return self._cell.output_size

    def zero_state(self, batch_size, dtype):
        return self._cell.zero_state(batch_size, dtype)

    def __call__(self, inputs, state, scope=None):
        if self._ikp < 1.0:
            inputs = nn_ops.dropout(inputs, keep_prob=self._ikp,
                                    seed=self._seed)
        out, new_state = self._cell(inputs, state, scope)
        if self._okp < 1.0:
            out = nn_ops.dropout(out, keep_prob=self._okp, seed=self._seed)
        return out, new_state


class ResidualWrapper(RNNCell):
    def __init__(self, cell):
        self._cell = cell

    @property
    def state_size(self):
        return self._cell.state_size

    @property
    def output_size(self):
        return self._cell.output_size

    def zero_state(self, batch_size, dtype):
        return self._cell.zero_state(batch_size, dtype)

    def __call__(self, inputs, state, scope=None):
        out, new_state = self._cell(inputs, state, scope)
        return inputs + out, new_state
