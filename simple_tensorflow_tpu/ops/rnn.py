"""RNN loops (ref: tensorflow/python/ops/rnn.py).

dynamic_rnn lowers to lax.scan over time — the differentiable XLA loop —
instead of the reference's while_loop + TensorArray machinery
(ref: rnn.py _dynamic_rnn_loop + core/kernels/tensor_array.cc). Variables
created by the first cell invocation live in the root graph and are captured
into the scan body, so weights stay HBM-resident across timesteps.
"""

from __future__ import annotations

import numpy as np

from ..framework import constant_op
from ..framework import graph as ops_mod
from . import array_ops, functional_ops, math_ops
from . import variable_scope as vs
from .control_flow_ops import _flatten, _pack_like


def dynamic_rnn(cell, inputs, sequence_length=None, initial_state=None,
                dtype=None, parallel_iterations=None, swap_memory=False,
                time_major=False, scope=None):
    """(ref: rnn.py:443 ``dynamic_rnn``)."""
    inputs = ops_mod.convert_to_tensor(inputs)
    if not time_major:
        inputs = array_ops.transpose(inputs, [1, 0, 2])  # -> [T, B, D]
    T = inputs.shape[0].value
    batch = inputs.shape[1].value
    if T is None or batch is None:
        raise ValueError("dynamic_rnn needs static [T, B] on TPU")
    if initial_state is not None:
        state = initial_state
    else:
        if dtype is None:
            dtype = inputs.dtype
        state = cell.zero_state(batch, dtype)
    if sequence_length is not None:
        sequence_length = math_ops.cast(
            ops_mod.convert_to_tensor(sequence_length), "int32")

    with vs.variable_scope(scope or "rnn", reuse=vs.AUTO_REUSE):
        # First call creates the variables in the root graph (outside the
        # scan body trace); later calls reuse them.
        out0, _ = cell(inputs[0], state)
        zero_out = array_ops.zeros_like(out0)
        times = constant_op.constant(np.arange(T, dtype=np.int32))

        def body(carry, elem):
            st, _prev_out = carry
            x, t = elem
            out, new_state = cell(x, st)
            if sequence_length is not None:
                active = math_ops.cast(math_ops.less(t, sequence_length),
                                       out.dtype.base_dtype)
                act = array_ops.expand_dims(active, -1)
                out = out * act
                merged = []
                for old, new in zip(_flatten(st), _flatten(new_state)):
                    merged.append(new * act + old * (1.0 - act))
                new_state = _pack_like(new_state, merged)
            return (new_state, out)

        stacked = functional_ops.scan(body, (inputs, times),
                                      initializer=(state, zero_out),
                                      name="rnn_scan")
    state_seq, outputs = stacked
    final_state = _pack_like(state, [s[T - 1] for s in _flatten(state_seq)])
    if not time_major:
        outputs = array_ops.transpose(outputs, [1, 0, 2])
    return outputs, final_state


def static_rnn(cell, inputs, initial_state=None, dtype=None,
               sequence_length=None, scope=None):
    """(ref: rnn.py ``static_rnn``): python-unrolled (XLA still fuses)."""
    if not inputs:
        raise ValueError("inputs must not be empty")
    batch = inputs[0].shape[0].value
    if initial_state is not None:
        state = initial_state
    else:
        if dtype is None:
            dtype = inputs[0].dtype
        state = cell.zero_state(batch, dtype)
    outputs = []
    with vs.variable_scope(scope or "rnn", reuse=vs.AUTO_REUSE):
        for x in inputs:
            out, state = cell(x, state)
            outputs.append(out)
    return outputs, state


def bidirectional_dynamic_rnn(cell_fw, cell_bw, inputs, sequence_length=None,
                              initial_state_fw=None, initial_state_bw=None,
                              dtype=None, parallel_iterations=None,
                              swap_memory=False, time_major=False, scope=None):
    """(ref: rnn.py ``bidirectional_dynamic_rnn``)."""
    with vs.variable_scope(scope or "bidirectional_rnn"):
        with vs.variable_scope("fw"):
            out_fw, st_fw = dynamic_rnn(cell_fw, inputs, sequence_length,
                                        initial_state_fw, dtype,
                                        time_major=time_major)
        inputs_rev = array_ops.reverse(ops_mod.convert_to_tensor(inputs),
                                       [0 if time_major else 1])
        with vs.variable_scope("bw"):
            out_bw, st_bw = dynamic_rnn(cell_bw, inputs_rev, sequence_length,
                                        initial_state_bw, dtype,
                                        time_major=time_major)
        out_bw = array_ops.reverse(out_bw, [0 if time_major else 1])
    return (out_fw, out_bw), (st_fw, st_bw)


def raw_rnn(cell, loop_fn, parallel_iterations=None, swap_memory=False,
            scope=None):
    raise NotImplementedError(
        "raw_rnn's emit-driven loop is inherently dynamic; use dynamic_rnn "
        "or stf.scan on TPU")
