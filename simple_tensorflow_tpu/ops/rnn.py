"""RNN loops (ref: tensorflow/python/ops/rnn.py).

dynamic_rnn lowers to lax.scan over time — the differentiable XLA loop —
instead of the reference's while_loop + TensorArray machinery
(ref: rnn.py _dynamic_rnn_loop + core/kernels/tensor_array.cc). Variables
created by the first cell invocation live in the root graph and are captured
into the scan body, so weights stay HBM-resident across timesteps.
"""

from __future__ import annotations

import numpy as np

from ..framework import constant_op
from ..framework import graph as ops_mod
from . import array_ops, functional_ops, math_ops
from . import variable_scope as vs
from .control_flow_ops import _flatten, _pack_like


def dynamic_rnn(cell, inputs, sequence_length=None, initial_state=None,
                dtype=None, parallel_iterations=None, swap_memory=False,
                time_major=False, scope=None):
    """(ref: rnn.py:443 ``dynamic_rnn``)."""
    inputs = ops_mod.convert_to_tensor(inputs)
    if not time_major:
        inputs = array_ops.transpose(inputs, [1, 0, 2])  # -> [T, B, D]
    T = inputs.shape[0].value
    batch = inputs.shape[1].value
    if T is None or batch is None:
        raise ValueError("dynamic_rnn needs static [T, B] on TPU")
    if initial_state is not None:
        state = initial_state
    else:
        if dtype is None:
            dtype = inputs.dtype
        state = cell.zero_state(batch, dtype)
    if sequence_length is not None:
        sequence_length = math_ops.cast(
            ops_mod.convert_to_tensor(sequence_length), "int32")

    with vs.variable_scope(scope or "rnn", reuse=vs.AUTO_REUSE):
        # First call creates the variables in the root graph (outside the
        # scan body trace); later calls reuse them.
        out0, _ = cell(inputs[0], state)
        zero_out = array_ops.zeros_like(out0)
        times = constant_op.constant(np.arange(T, dtype=np.int32))

        def body(carry, elem):
            st, _prev_out = carry
            x, t = elem
            out, new_state = cell(x, st)
            if sequence_length is not None:
                # select, not arithmetic masking: NaN/Inf from the cell on
                # post-sequence-end steps must not poison frozen values
                # (NaN * 0.0 == NaN)
                active = math_ops.less(t, sequence_length)
                act = array_ops.expand_dims(active, -1)
                out = array_ops.where(act, out, array_ops.zeros_like(out))
                merged = []
                for old, new in zip(_flatten(st), _flatten(new_state)):
                    merged.append(array_ops.where(act, new, old))
                new_state = _pack_like(new_state, merged)
            return (new_state, out)

        stacked = functional_ops.scan(body, (inputs, times),
                                      initializer=(state, zero_out),
                                      name="rnn_scan")
    state_seq, outputs = stacked
    final_state = _pack_like(state, [s[T - 1] for s in _flatten(state_seq)])
    if not time_major:
        outputs = array_ops.transpose(outputs, [1, 0, 2])
    return outputs, final_state


def static_rnn(cell, inputs, initial_state=None, dtype=None,
               sequence_length=None, scope=None):
    """(ref: rnn.py ``static_rnn``): python-unrolled (XLA still fuses)."""
    if not inputs:
        raise ValueError("inputs must not be empty")
    batch = inputs[0].shape[0].value
    if initial_state is not None:
        state = initial_state
    else:
        if dtype is None:
            dtype = inputs[0].dtype
        state = cell.zero_state(batch, dtype)
    outputs = []
    with vs.variable_scope(scope or "rnn", reuse=vs.AUTO_REUSE):
        for x in inputs:
            out, state = cell(x, state)
            outputs.append(out)
    return outputs, state


def bidirectional_dynamic_rnn(cell_fw, cell_bw, inputs, sequence_length=None,
                              initial_state_fw=None, initial_state_bw=None,
                              dtype=None, parallel_iterations=None,
                              swap_memory=False, time_major=False, scope=None):
    """(ref: rnn.py ``bidirectional_dynamic_rnn``)."""
    with vs.variable_scope(scope or "bidirectional_rnn"):
        with vs.variable_scope("fw"):
            out_fw, st_fw = dynamic_rnn(cell_fw, inputs, sequence_length,
                                        initial_state_fw, dtype,
                                        time_major=time_major)
        inputs_rev = array_ops.reverse(ops_mod.convert_to_tensor(inputs),
                                       [0 if time_major else 1])
        with vs.variable_scope("bw"):
            out_bw, st_bw = dynamic_rnn(cell_bw, inputs_rev, sequence_length,
                                        initial_state_bw, dtype,
                                        time_major=time_major)
        out_bw = array_ops.reverse(out_bw, [0 if time_major else 1])
    return (out_fw, out_bw), (st_fw, st_bw)


def raw_rnn(cell, loop_fn, parallel_iterations=None, swap_memory=False,
            scope=None, maximum_iterations=None):
    """(ref: rnn.py ``raw_rnn``). Emit-driven RNN loop over stf.while_loop.

    loop_fn(time, cell_output, cell_state, loop_state) ->
        (finished, next_input, next_cell_state, emit_output, next_loop_state)

    TPU adaptation: the reference's loop grows TensorArrays dynamically
    (ref core/kernels/tensor_array_ops.cc); XLA needs a static bound, so
    ``maximum_iterations`` is required here — the emit TensorArray has
    exactly that many slots and iteration stops early when every sequence
    reports finished. Returns (emit_ta, final_state, final_loop_state).

    Forward-only: XLA cannot reverse-differentiate an unbounded loop, so
    stf.gradients through raw_rnn raises at graph construction — train
    with dynamic_rnn / stf.scan (lax.scan-based) instead.
    """
    from . import control_flow_ops as cf
    from . import tensor_array_ops as ta_ops

    if maximum_iterations is None:
        raise ValueError(
            "raw_rnn on TPU needs maximum_iterations= (XLA loops are "
            "bounded; the reference grows TensorArrays dynamically)")
    T = int(maximum_iterations)

    with vs.variable_scope(scope or "rnn", reuse=vs.AUTO_REUSE):
        time0 = constant_op.constant(0, dtype="int32")
        finished0, input0, state0, emit0, loop_state0 = loop_fn(
            time0, None, None, None)
        finished0 = ops_mod.convert_to_tensor(finished0)
        # trace the cell ONCE outside the loop to create its variables in
        # the enclosing scope (inside, the FuncGraph would own them) and to
        # learn the emit structure when loop_fn(0) returned None for it
        out_probe, _ = cell(input0, state0)
        if emit0 is None:
            emit0 = array_ops.zeros_like(out_probe)
        has_loop_state = loop_state0 is not None

        emit_ta0 = ta_ops.TensorArray(emit0.dtype, size=T,
                                      element_shape=emit0.shape)
        carry0 = [time0, finished0, input0, state0, emit_ta0._buffer]
        if has_loop_state:
            carry0.append(loop_state0)

        def _cond(t, finished, *_rest):
            return math_ops.logical_and(
                t < T, math_ops.logical_not(math_ops.reduce_all(finished)))

        def _body(t, finished, inp, state, emit_buf, *maybe_ls):
            ls = maybe_ls[0] if has_loop_state else None
            # traced inside the enclosing AUTO_REUSE scope (the while_loop
            # call sits within the `with` above), so the cell reuses the
            # probe's variables — re-opening the scope here would nest
            # "rnn/rnn" and create fresh weights
            output, new_state = cell(inp, state)
            (next_finished, next_input, next_state, emit,
             next_ls) = loop_fn(t + 1, output, new_state, ls)
            if emit is None:
                emit = array_ops.zeros_like(output)
            # finished sequences emit zeros and freeze their state —
            # where-select, not arithmetic masking, so a NaN/Inf the cell
            # produces past sequence end cannot poison frozen values
            live = array_ops.reshape(math_ops.logical_not(finished),
                                     [-1] + [1] * (emit.shape.rank - 1))
            emit = array_ops.where(live, emit, array_ops.zeros_like(emit))
            frozen = []
            for old, new in zip(_flatten(state), _flatten(next_state)):
                m = array_ops.reshape(
                    math_ops.logical_not(finished),
                    [-1] + [1] * (new.shape.rank - 1))
                frozen.append(array_ops.where(m, new, old))
            next_state = _pack_like(next_state, frozen)
            ta = ta_ops.TensorArray(emit.dtype, size=T, _buffer=emit_buf)
            new_buf = ta.write(t, emit)._buffer
            next_finished = math_ops.logical_or(
                finished, ops_mod.convert_to_tensor(next_finished))
            out = [t + 1, next_finished, next_input, next_state, new_buf]
            if has_loop_state:
                out.append(next_ls)
            return out

        # the static bound makes the loop reverse-differentiable (the
        # gradient replay lowers it as a masked lax.scan over T steps)
        final = cf.while_loop(_cond, _body, carry0, maximum_iterations=T)
        t_f, _, _, state_f, emit_buf_f = final[:5]
        loop_state_f = final[5] if has_loop_state else None
        emit_ta = ta_ops.TensorArray(emit0.dtype, size=T,
                                     _buffer=emit_buf_f)
        return emit_ta, state_f, loop_state_f
