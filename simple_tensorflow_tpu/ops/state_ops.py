"""Stateful variable ops: reads, assigns, scatter updates.

(ref: tensorflow/python/ops/state_ops.py, core/kernels/assign_op.h,
core/kernels/scatter_op.cc). TPU-native design: a variable is an entry in the
Session's device-resident VariableStore; reads pull the current traced value
from the lowering context, writes replace it. Because the whole step is one
XLA program with donated state buffers, an assign is an in-place HBM update
after compilation — same performance model as the reference's ref-variables,
but functionally pure at trace level. Read/write ordering follows graph
topological order over data + control edges (the reference's contract,
enforced dynamically by its executor; here statically at lowering).
"""

from __future__ import annotations

import builtins

import numpy as np

from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from ..framework import op_registry
from ..framework import tensor_shape as shape_mod


# -- lowerings ---------------------------------------------------------------

def _lower_variable(ctx, op, inputs):
    return [ctx.read_var(op.attrs["var_name"], op)]


op_registry.register("VariableV2", lower=_lower_variable,
                     effects=op_registry.Effects(reads=("var_name",)))
# Fresh read of the current store value at this node's topological position;
# lets `with control_dependencies([assign]): v.read_value()` observe the
# write (TF-1.0 ref-variable deref-at-use semantics).
op_registry.register("ReadVariable", lower=_lower_variable,
                     effects=op_registry.Effects(reads=("var_name",)))


def _lower_assign(ctx, op, inputs):
    import jax.numpy as jnp

    name = op.attrs["var_name"]
    val = inputs[0]
    # use_locking is a concurrency hint in the reference; it never gates
    # validation.
    if ctx.var_exists(name):
        prev = ctx.state[name]
        if op.attrs.get("validate_shape", True) and tuple(prev.shape) != tuple(val.shape):
            raise ValueError(
                f"Assign to {name}: shape {tuple(val.shape)} != variable shape "
                f"{tuple(prev.shape)}")
        if prev.dtype != val.dtype:
            val = val.astype(prev.dtype)
    ctx.write_var(name, val)
    return [val]


op_registry.register("Assign", lower=_lower_assign,
                     effects=op_registry.Effects(writes=("var_name",)))


def _make_aug_assign(fn):
    def lower(ctx, op, inputs):
        name = op.attrs["var_name"]
        cur = ctx.read_var(name, op)
        new = fn(cur, inputs[0].astype(cur.dtype) if hasattr(inputs[0], "astype")
                 else inputs[0])
        ctx.write_var(name, new)
        return [new]

    return lower


op_registry.register("AssignAdd", lower=_make_aug_assign(lambda a, b: a + b),
                     effects=op_registry.Effects(writes=("var_name",),
                                                 update="add"))
op_registry.register("AssignSub", lower=_make_aug_assign(lambda a, b: a - b),
                     effects=op_registry.Effects(writes=("var_name",),
                                                 update="sub"))


def _make_scatter(update):
    def lower(ctx, op, inputs):
        name = op.attrs["var_name"]
        cur = ctx.read_var(name, op)
        indices, updates = inputs
        new = update(cur, indices, updates)
        ctx.write_var(name, new)
        return [new]

    return lower


op_registry.register(
    "ScatterUpdate",
    lower=_make_scatter(lambda v, i, u: v.at[i].set(u)),
    effects=op_registry.Effects(writes=("var_name",),
                                update="update"))
op_registry.register(
    "ScatterAdd",
    lower=_make_scatter(lambda v, i, u: v.at[i].add(u)),
    effects=op_registry.Effects(writes=("var_name",),
                                update="add"))
op_registry.register(
    "ScatterSub",
    lower=_make_scatter(lambda v, i, u: v.at[i].add(-u)),
    effects=op_registry.Effects(writes=("var_name",),
                                update="sub"))
op_registry.register(
    "ScatterMul",
    lower=_make_scatter(lambda v, i, u: v.at[i].mul(u)),
    effects=op_registry.Effects(writes=("var_name",),
                                update="mul"))
op_registry.register(
    "ScatterDiv",
    lower=_make_scatter(lambda v, i, u: v.at[i].divide(u)),
    effects=op_registry.Effects(writes=("var_name",),
                                update="div"))
op_registry.register(
    "ScatterMin",
    lower=_make_scatter(lambda v, i, u: v.at[i].min(u)),
    effects=op_registry.Effects(writes=("var_name",),
                                update="min"))
op_registry.register(
    "ScatterMax",
    lower=_make_scatter(lambda v, i, u: v.at[i].max(u)),
    effects=op_registry.Effects(writes=("var_name",),
                                update="max"))


def _lower_scatter_nd_update(ctx, op, inputs):
    name = op.attrs["var_name"]
    cur = ctx.read_var(name, op)
    indices, updates = inputs
    new = cur.at[tuple(indices[..., k] for k in range(indices.shape[-1]))].set(updates)
    ctx.write_var(name, new)
    return [new]


op_registry.register("ScatterNdUpdate", lower=_lower_scatter_nd_update,
                     effects=op_registry.Effects(writes=("var_name",),
                                                 update="update"))


def _lower_is_initialized(ctx, op, inputs):
    # Host op: answered against the Session's store before device tracing.
    return [np.asarray(ctx.var_exists(op.attrs["var_name"]))]


op_registry.register("IsVariableInitialized", lower=_lower_is_initialized,
                     runs_on_host=True,
                     effects=op_registry.Effects(reads=("var_name",)))


def _lower_count_up_to(ctx, op, inputs):
    # Host-staged: XLA cannot raise, and the whole point of count_up_to is
    # its OutOfRangeError at the limit (ref core/kernels/count_up_to_op.cc).
    from ..framework.errors import OutOfRangeError

    name = op.attrs["var_name"]
    limit = op.attrs["limit"]
    cur = ctx.read_var(name, op)
    if int(np.asarray(cur)) >= limit:
        raise OutOfRangeError(None, op,
                              f"Reached limit of {limit} in CountUpTo")
    ctx.state[name] = np.asarray(cur) + 1
    return [np.asarray(cur)]


op_registry.register("CountUpTo", lower=_lower_count_up_to,
                     runs_on_host=True,
                     effects=op_registry.Effects(writes=("var_name",),
                                                 update="add"))


# -- public API --------------------------------------------------------------

def _var_name_of(ref) -> str:
    op = ref.op if isinstance(ref, ops_mod.Tensor) else ref
    if op.type not in ("VariableV2",):
        raise TypeError(f"Expected a variable ref tensor, got op {op.type}")
    return op.attrs["var_name"]


def _ref_of(x):
    from . import variables as variables_mod

    if isinstance(x, variables_mod.Variable):
        return x._ref
    return x


def assign(ref, value, validate_shape=True, use_locking=True, name=None):
    """(ref: python/ops/state_ops.py ``assign``)."""
    ref = _ref_of(ref)
    g = ops_mod.get_default_graph()
    value = ops_mod.convert_to_tensor(value, dtype=ref.dtype.base_dtype)
    op = g.create_op("Assign", [value],
                     attrs={"var_name": _var_name_of(ref),
                            "validate_shape": validate_shape,
                            "use_locking": use_locking},
                     name=name or "Assign",
                     output_specs=[(value.shape if not validate_shape else ref.shape,
                                    ref.dtype.base_dtype)])
    return op.outputs[0]


def assign_add(ref, value, use_locking=True, name=None):
    ref = _ref_of(ref)
    g = ops_mod.get_default_graph()
    value = ops_mod.convert_to_tensor(value, dtype=ref.dtype.base_dtype)
    op = g.create_op("AssignAdd", [value],
                     attrs={"var_name": _var_name_of(ref)},
                     name=name or "AssignAdd",
                     output_specs=[(ref.shape, ref.dtype.base_dtype)])
    return op.outputs[0]


def assign_sub(ref, value, use_locking=True, name=None):
    ref = _ref_of(ref)
    g = ops_mod.get_default_graph()
    value = ops_mod.convert_to_tensor(value, dtype=ref.dtype.base_dtype)
    op = g.create_op("AssignSub", [value],
                     attrs={"var_name": _var_name_of(ref)},
                     name=name or "AssignSub",
                     output_specs=[(ref.shape, ref.dtype.base_dtype)])
    return op.outputs[0]


def _scatter(op_type, ref, indices, updates, name=None):
    ref = _ref_of(ref)
    g = ops_mod.get_default_graph()
    indices = ops_mod.convert_to_tensor(indices, dtype=dtypes_mod.int32)
    updates = ops_mod.convert_to_tensor(updates, dtype=ref.dtype.base_dtype)
    op = g.create_op(op_type, [indices, updates],
                     attrs={"var_name": _var_name_of(ref)},
                     name=name or op_type,
                     output_specs=[(ref.shape, ref.dtype.base_dtype)])
    return op.outputs[0]


def scatter_update(ref, indices, updates, use_locking=True, name=None):
    return _scatter("ScatterUpdate", ref, indices, updates, name)


def scatter_add(ref, indices, updates, use_locking=False, name=None):
    return _scatter("ScatterAdd", ref, indices, updates, name)


def scatter_sub(ref, indices, updates, use_locking=False, name=None):
    return _scatter("ScatterSub", ref, indices, updates, name)


def scatter_mul(ref, indices, updates, use_locking=False, name=None):
    return _scatter("ScatterMul", ref, indices, updates, name)


def scatter_div(ref, indices, updates, use_locking=False, name=None):
    return _scatter("ScatterDiv", ref, indices, updates, name)


def scatter_min(ref, indices, updates, use_locking=False, name=None):
    return _scatter("ScatterMin", ref, indices, updates, name)


def scatter_max(ref, indices, updates, use_locking=False, name=None):
    return _scatter("ScatterMax", ref, indices, updates, name)


def scatter_nd_update(ref, indices, updates, use_locking=True, name=None):
    return _scatter("ScatterNdUpdate", ref, indices, updates, name)


def is_variable_initialized(ref, name=None):
    ref = _ref_of(ref)
    g = ops_mod.get_default_graph()
    op = g.create_op("IsVariableInitialized", [],
                     attrs={"var_name": _var_name_of(ref)},
                     name=name or "IsVariableInitialized",
                     output_specs=[(shape_mod.scalar(), dtypes_mod.bool_)])
    return op.outputs[0]


def count_up_to(ref, limit, name=None):
    ref = _ref_of(ref)
    g = ops_mod.get_default_graph()
    op = g.create_op("CountUpTo", [],
                     attrs={"var_name": _var_name_of(ref), "limit": limit},
                     name=name or "CountUpTo",
                     output_specs=[(ref.shape, ref.dtype.base_dtype)])
    return op.outputs[0]


def _lower_scatter_nd_aug(fn):
    def lower(ctx, op, inputs):
        name = op.attrs["var_name"]
        cur = ctx.read_var(name, op)
        indices, updates = inputs
        idx = builtins.tuple(indices[..., k]
                             for k in range(indices.shape[-1]))
        new = fn(cur, idx, updates)
        ctx.write_var(name, new)
        return [new]

    return lower


op_registry.register(
    "ScatterNdAdd",
    lower=_lower_scatter_nd_aug(lambda v, i, u: v.at[i].add(u)),
    effects=op_registry.Effects(writes=("var_name",), update="add"))
op_registry.register(
    "ScatterNdSub",
    lower=_lower_scatter_nd_aug(lambda v, i, u: v.at[i].add(-u)),
    effects=op_registry.Effects(writes=("var_name",), update="sub"))


def scatter_nd_add(ref, indices, updates, use_locking=True, name=None):
    """(ref: state_ops.py ``scatter_nd_add``)."""
    return _scatter("ScatterNdAdd", ref, indices, updates, name)


def scatter_nd_sub(ref, indices, updates, use_locking=True, name=None):
    return _scatter("ScatterNdSub", ref, indices, updates, name)


# ---------------------------------------------------------------------------
# sharding propagation rules (stf.analysis.sharding; ISSUE 6): writes
# commit at the variable's declared sharding; mismatched values reshard
# on the way in.
# ---------------------------------------------------------------------------

from ..analysis import sharding as _shard  # noqa: E402

_shard.register_rules(_shard.make_assign_rule(0),
                      "Assign", "AssignAdd", "AssignSub")
_shard.register_rules(_shard.local_rule, "ScatterNdUpdate",
                      "IsVariableInitialized", "CountUpTo")
