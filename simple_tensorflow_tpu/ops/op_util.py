"""Shared helpers for building op wrappers.

The reference generates its Python op wrappers from OpDef protos
(ref: tensorflow/python/framework/python_op_gen.cc); here ops are registered
with a jax ``pure_fn`` (op_registry.register_pure) and these helpers build
the graph nodes.
"""

from __future__ import annotations

from typing import Optional, Sequence

from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from ..framework import op_registry

Tensor = ops_mod.Tensor


def make_op(op_type: str, inputs: Sequence[Tensor], attrs=None,
            name: Optional[str] = None, n_out: int = 1):
    g = ops_mod.get_default_graph()
    op = g.create_op(op_type, inputs, attrs=attrs or {}, name=name or op_type)
    if n_out == 1:
        return op.outputs[0]
    return list(op.outputs)


def unary(op_type: str, x, name=None, dtype=None, attrs=None):
    x = ops_mod.convert_to_tensor(x, dtype=dtype)
    return make_op(op_type, [x], attrs=attrs, name=name)


def binary(op_type: str, x, y, name=None, attrs=None):
    x, y = promote_args(x, y, op_type)
    return make_op(op_type, [x, y], attrs=attrs, name=name)


def promote_args(x, y, op_name=""):
    """TF-1.0 dtype discipline: both operands must have the same base dtype;
    python scalars adopt the tensor operand's dtype
    (ref: python/framework/ops.py convert_to_tensor + strict op signatures)."""
    x_is_t = isinstance(x, Tensor) or hasattr(x, "_as_graph_element")
    y_is_t = isinstance(y, Tensor) or hasattr(y, "_as_graph_element")
    if x_is_t:
        x = ops_mod.convert_to_tensor(x)
    if y_is_t:
        y = ops_mod.convert_to_tensor(y)
    if x_is_t and not y_is_t:
        y = ops_mod.convert_to_tensor(y, dtype=x.dtype.base_dtype)
    elif y_is_t and not x_is_t:
        x = ops_mod.convert_to_tensor(x, dtype=y.dtype.base_dtype)
    elif not x_is_t and not y_is_t:
        x = ops_mod.convert_to_tensor(x)
        y = ops_mod.convert_to_tensor(y, dtype=x.dtype.base_dtype)
    if x.dtype.base_dtype != y.dtype.base_dtype:
        raise TypeError(
            f"{op_name or 'binary op'}: operand dtypes must match, got "
            f"{x.dtype.base_dtype.name} and {y.dtype.base_dtype.name}. "
            f"Use stf.cast explicitly (TF-1.0 semantics).")
    return x, y


def norm_axis(axis):
    """Normalize reduction axis to tuple-or-None for static attrs."""
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    from ..framework import constant_op

    if isinstance(axis, Tensor):
        v = constant_op.constant_value(axis)
        if v is None:
            raise ValueError(
                "Reduction axis must be statically known on TPU (XLA needs "
                "static shapes); got a dynamic tensor.")
        import numpy as np

        return tuple(int(a) for a in np.ravel(v))
    return (int(axis),)
