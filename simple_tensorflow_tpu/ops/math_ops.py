"""Math ops (ref: tensorflow/python/ops/math_ops.py, core/kernels/cwise_op_*.cc,
core/kernels/matmul_op.cc, reduction_ops_*.cc, segment_reduction_ops.cc).

Every op is a graph node whose lowering emits jax.numpy/lax — XLA fuses
elementwise chains into matmul epilogues automatically, which is why there
are no hand-fused variants here (the reference ships ~300 cwise CUDA kernels;
on TPU the fusion is the compiler's job). MatMul output dtype equals the
input dtype (TF semantics); bf16 matmuls still accumulate in f32 INSIDE the
MXU (hardware behavior) — emitting the f32 accumulator as the output
(preferred_element_type) would double activation HBM traffic through every
dense layer, which measured as the dominant bandwidth cost on bf16 models.
"""

from __future__ import annotations

import builtins
import numpy as np

from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from ..framework import op_registry
from ..framework import constant_op
from ..framework import tensor_shape as shape_mod
from .op_util import binary, make_op, norm_axis, promote_args, unary

Tensor = ops_mod.Tensor


def _j():
    import jax.numpy as jnp

    return jnp


# ---------------------------------------------------------------------------
# registrations: unary elementwise
# ---------------------------------------------------------------------------

def _reg_unary(op_type, fn):
    op_registry.register_pure(op_type, fn)


import jax.numpy as jnp  # noqa: E402  (jax is a hard dep; import once)
import jax  # noqa: E402

_reg_unary("Neg", jnp.negative)
_reg_unary("Abs", jnp.abs)
_reg_unary("Sign", jnp.sign)
_reg_unary("Reciprocal", lambda x: 1 / x)
_reg_unary("Square", jnp.square)
_reg_unary("Sqrt", jnp.sqrt)
_reg_unary("Rsqrt", lambda x: jax.lax.rsqrt(x))
_reg_unary("Exp", jnp.exp)
_reg_unary("Expm1", jnp.expm1)
_reg_unary("Log", jnp.log)
_reg_unary("Log1p", jnp.log1p)
_reg_unary("Sin", jnp.sin)
_reg_unary("Cos", jnp.cos)
_reg_unary("Tan", jnp.tan)
_reg_unary("Asin", jnp.arcsin)
_reg_unary("Acos", jnp.arccos)
_reg_unary("Atan", jnp.arctan)
_reg_unary("Sinh", jnp.sinh)
_reg_unary("Cosh", jnp.cosh)
_reg_unary("Tanh", jnp.tanh)
_reg_unary("Asinh", jnp.arcsinh)
_reg_unary("Acosh", jnp.arccosh)
_reg_unary("Atanh", jnp.arctanh)
_reg_unary("Sigmoid", jax.nn.sigmoid)
_reg_unary("Erf", jax.scipy.special.erf)
_reg_unary("Erfc", jax.scipy.special.erfc)
_reg_unary("Lgamma", jax.scipy.special.gammaln)
_reg_unary("Digamma", jax.scipy.special.digamma)
_reg_unary("Floor", jnp.floor)
_reg_unary("Ceil", jnp.ceil)
_reg_unary("Rint", jnp.rint)
_reg_unary("Round", jnp.round)
_reg_unary("IsNan", jnp.isnan)
_reg_unary("IsInf", jnp.isinf)
_reg_unary("IsFinite", jnp.isfinite)
_reg_unary("LogicalNot", jnp.logical_not)
_reg_unary("Invert", jnp.invert)
_reg_unary("Real", jnp.real)
_reg_unary("Imag", jnp.imag)
_reg_unary("Conj", jnp.conj)
_reg_unary("Angle", jnp.angle)
_reg_unary("Softplus", jax.nn.softplus)
_reg_unary("Softsign", jax.nn.soft_sign)

# binary elementwise
op_registry.register_pure("Add", jnp.add)
op_registry.register_pure("Sub", jnp.subtract)
op_registry.register_pure("Mul", jnp.multiply)
# TF-1.0 tf.div: C-style truncating division for integers, true division
# for floats (ref core/kernels/cwise_op_div.cc); truediv is always float.
op_registry.register_pure(
    "Div", lambda x, y: jax.lax.div(x, y)
    if jnp.issubdtype(x.dtype, jnp.integer) else jnp.true_divide(x, y))
op_registry.register_pure("TrueDiv", jnp.true_divide)
op_registry.register_pure("RealDiv", jnp.true_divide)
op_registry.register_pure("FloorDiv", jnp.floor_divide)
op_registry.register_pure("TruncateDiv", lambda x, y: jnp.trunc(x / y).astype(x.dtype)
                          if jnp.issubdtype(x.dtype, jnp.floating)
                          else jax.lax.div(x, y))
op_registry.register_pure("Mod", jnp.mod)
op_registry.register_pure("FloorMod", jnp.mod)
op_registry.register_pure("TruncateMod", lambda x, y: jax.lax.rem(x, y))
op_registry.register_pure("Pow", jnp.power)
op_registry.register_pure("Maximum", jnp.maximum)
op_registry.register_pure("Minimum", jnp.minimum)
op_registry.register_pure("SquaredDifference", lambda x, y: jnp.square(x - y))
op_registry.register_pure("Atan2", jnp.arctan2)
op_registry.register_pure("Xlogy", lambda x, y: jnp.where(
    x == 0, jnp.zeros_like(x), x * jnp.log(y)))
op_registry.register_pure("Xdivy", lambda x, y: jnp.where(
    x == 0, jnp.zeros_like(x), x / y))
op_registry.register_pure("Zeta", lambda x, q: jax.scipy.special.zeta(x, q))
op_registry.register_pure("Polygamma", lambda n, x: jax.scipy.special.polygamma(
    n.astype(jnp.int32), x))
op_registry.register_pure("Igamma", jax.scipy.special.gammainc)
op_registry.register_pure("Igammac", jax.scipy.special.gammaincc)
op_registry.register_pure("Betainc", jax.scipy.special.betainc)
op_registry.register_pure("LogicalAnd", jnp.logical_and)
op_registry.register_pure("LogicalOr", jnp.logical_or)
op_registry.register_pure("LogicalXor", jnp.logical_xor)
op_registry.register_pure("BitwiseAnd", jnp.bitwise_and)
op_registry.register_pure("BitwiseOr", jnp.bitwise_or)
op_registry.register_pure("BitwiseXor", jnp.bitwise_xor)
op_registry.register_pure("LeftShift", jnp.left_shift)
op_registry.register_pure("RightShift", jnp.right_shift)

# comparisons
op_registry.register_pure("Equal", jnp.equal)
op_registry.register_pure("NotEqual", jnp.not_equal)
op_registry.register_pure("Less", jnp.less)
op_registry.register_pure("LessEqual", jnp.less_equal)
op_registry.register_pure("Greater", jnp.greater)
op_registry.register_pure("GreaterEqual", jnp.greater_equal)
op_registry.register_pure("ApproximateEqual", lambda x, y, tolerance=1e-5:
                          jnp.abs(x - y) < tolerance)

# casts / misc
op_registry.register_pure("Cast", lambda x, dtype: x.astype(
    dtypes_mod.narrowed_if_no_x64(dtype).np_dtype))
op_registry.register_pure(
    "Bitcast", lambda x, dtype: jax.lax.bitcast_convert_type(x, dtype.np_dtype))
op_registry.register_pure("AddN", lambda *xs: builtins.sum(xs[1:], xs[0]))
op_registry.register_pure("MatMul", lambda a, b, transpose_a=False,
                          transpose_b=False: _matmul_impl(a, b, transpose_a,
                                                          transpose_b))
op_registry.register_pure("BatchMatMul", lambda a, b, adj_x=False, adj_y=False:
                          jnp.matmul(jnp.swapaxes(a, -1, -2) if adj_x else a,
                                     jnp.swapaxes(b, -1, -2) if adj_y else b))
op_registry.register_pure("Cross", lambda a, b: jnp.cross(a, b))
op_registry.register_pure("Tensordot", lambda a, b, axes: jnp.tensordot(
    a, b, axes=axes))
op_registry.register_pure("Einsum", lambda *xs, equation: jnp.einsum(
    equation, *xs))
op_registry.register_pure("ClipByValue", lambda x, lo, hi: jnp.clip(x, lo, hi))


def _matmul_impl(a, b, transpose_a, transpose_b):
    # no preferred_element_type: output stays in the input dtype (TF
    # semantics). The MXU still accumulates bf16 products in f32 internally;
    # exposing that accumulator as an f32 output doubles HBM write traffic
    # for every layer and forces downstream ops into f32.
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


# reductions: axis/keepdims are static attrs
def _reg_reduce(op_type, fn):
    op_registry.register_pure(
        op_type, lambda x, axis=None, keepdims=False: fn(
            x, axis=axis, keepdims=keepdims))


_reg_reduce("Sum", jnp.sum)
_reg_reduce("Mean", jnp.mean)
_reg_reduce("Prod", jnp.prod)
_reg_reduce("Max", jnp.max)
_reg_reduce("Min", jnp.min)
_reg_reduce("All", jnp.all)
_reg_reduce("Any", jnp.any)
_reg_reduce("LogSumExp", lambda x, axis=None, keepdims=False:
            jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdims))
op_registry.register_pure("EuclideanNorm",
                          lambda x, axis=None, keepdims=False:
                          jnp.sqrt(jnp.sum(jnp.square(x), axis=axis,
                                           keepdims=keepdims)))

# the reference's int64 default narrows without a per-op jax warning
# (one boundary warning per process; docs/MIGRATION.md "64-bit dtypes")
op_registry.register_pure("ArgMax", lambda x, axis=0, output_type=None:
                          jnp.argmax(x, axis=axis).astype(
                              dtypes_mod.narrowed_if_no_x64(
                                  output_type
                                  or dtypes_mod.int64).np_dtype))
op_registry.register_pure("ArgMin", lambda x, axis=0, output_type=None:
                          jnp.argmin(x, axis=axis).astype(
                              dtypes_mod.narrowed_if_no_x64(
                                  output_type
                                  or dtypes_mod.int64).np_dtype))
op_registry.register_pure("Cumsum", lambda x, axis=0, exclusive=False,
                          reverse=False: _cum_impl(jnp.cumsum, x, axis,
                                                   exclusive, reverse, 0))
op_registry.register_pure("Cumprod", lambda x, axis=0, exclusive=False,
                          reverse=False: _cum_impl(jnp.cumprod, x, axis,
                                                   exclusive, reverse, 1))


def _cum_impl(fn, x, axis, exclusive, reverse, ident):
    if reverse:
        x = jnp.flip(x, axis)
    if exclusive:
        pad = [(0, 0)] * x.ndim
        pad[axis] = (1, 0)
        sl = [slice(None)] * x.ndim
        sl[axis] = slice(0, x.shape[axis])
        x = jnp.pad(x, pad, constant_values=ident)[tuple(sl)]
    out = fn(x, axis=axis)
    if reverse:
        out = jnp.flip(out, axis)
    return out


def _seg_ids_static(num_segments):
    if num_segments is None:
        raise ValueError(
            "Segment reductions need a static num_segments on TPU (XLA "
            "static shapes). Pass num_segments, or use sorted segment ops "
            "with statically-known ids.")
    return int(num_segments)


op_registry.register_pure(
    "UnsortedSegmentSum", lambda data, ids, num_segments=None:
    jax.ops.segment_sum(data, ids, _seg_ids_static(num_segments)))
op_registry.register_pure(
    "UnsortedSegmentMax", lambda data, ids, num_segments=None:
    jax.ops.segment_max(data, ids, _seg_ids_static(num_segments)))
op_registry.register_pure(
    "UnsortedSegmentMin", lambda data, ids, num_segments=None:
    jax.ops.segment_min(data, ids, _seg_ids_static(num_segments)))
op_registry.register_pure(
    "UnsortedSegmentProd", lambda data, ids, num_segments=None:
    jax.ops.segment_prod(data, ids, _seg_ids_static(num_segments)))


def _sorted_segment(fn):
    def impl(data, ids, num_segments=None):
        return fn(data, ids, _seg_ids_static(num_segments))

    return impl


op_registry.register_pure("SegmentSum", _sorted_segment(jax.ops.segment_sum))
op_registry.register_pure("SegmentMax", _sorted_segment(jax.ops.segment_max))
op_registry.register_pure("SegmentMin", _sorted_segment(jax.ops.segment_min))
op_registry.register_pure("SegmentProd", _sorted_segment(jax.ops.segment_prod))
op_registry.register_pure(
    "SegmentMean", lambda data, ids, num_segments=None: (
        jax.ops.segment_sum(data, ids, _seg_ids_static(num_segments)) /
        jnp.maximum(jax.ops.segment_sum(jnp.ones_like(data), ids,
                                        _seg_ids_static(num_segments)), 1)))

op_registry.register_pure("Bincount", lambda arr, size=None, weights=None:
                          jnp.bincount(arr, weights=weights,
                                       length=_seg_ids_static(size)))

op_registry.register_pure("LinSpace", lambda start, stop, num: jnp.linspace(
    start, stop, int(num)))
op_registry.register_pure("Range", lambda start, limit, delta: jnp.arange(
    start, limit, delta))


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def add(x, y, name=None):
    return binary("Add", x, y, name)


def subtract(x, y, name=None):
    return binary("Sub", x, y, name)


sub = subtract


def multiply(x, y, name=None):
    return binary("Mul", x, y, name)


mul = multiply


def divide(x, y, name=None):
    # tf.divide is Python-style true division (legacy tf.div truncates ints).
    return binary("TrueDiv", x, y, name)


def div(x, y, name=None):
    return binary("Div", x, y, name)


def truediv(x, y, name=None):
    return binary("TrueDiv", x, y, name)


def realdiv(x, y, name=None):
    return binary("RealDiv", x, y, name)


def floordiv(x, y, name=None):
    return binary("FloorDiv", x, y, name)


def truncatediv(x, y, name=None):
    return binary("TruncateDiv", x, y, name)


def mod(x, y, name=None):
    return binary("Mod", x, y, name)


def floormod(x, y, name=None):
    return binary("FloorMod", x, y, name)


def truncatemod(x, y, name=None):
    return binary("TruncateMod", x, y, name)


def pow(x, y, name=None):  # noqa: A001
    return binary("Pow", x, y, name)


def maximum(x, y, name=None):
    return binary("Maximum", x, y, name)


def minimum(x, y, name=None):
    return binary("Minimum", x, y, name)


def squared_difference(x, y, name=None):
    return binary("SquaredDifference", x, y, name)


def atan2(y, x, name=None):
    return binary("Atan2", y, x, name)


def negative(x, name=None):
    return unary("Neg", x, name)


neg = negative


def abs(x, name=None):  # noqa: A001
    return unary("Abs", x, name)


def sign(x, name=None):
    return unary("Sign", x, name)


def reciprocal(x, name=None):
    return unary("Reciprocal", x, name)


def square(x, name=None):
    return unary("Square", x, name)


def sqrt(x, name=None):
    return unary("Sqrt", x, name)


def rsqrt(x, name=None):
    return unary("Rsqrt", x, name)


def exp(x, name=None):
    return unary("Exp", x, name)


def expm1(x, name=None):
    return unary("Expm1", x, name)


def log(x, name=None):
    return unary("Log", x, name)


def log1p(x, name=None):
    return unary("Log1p", x, name)


def sin(x, name=None):
    return unary("Sin", x, name)


def cos(x, name=None):
    return unary("Cos", x, name)


def tan(x, name=None):
    return unary("Tan", x, name)


def asin(x, name=None):
    return unary("Asin", x, name)


def acos(x, name=None):
    return unary("Acos", x, name)


def atan(x, name=None):
    return unary("Atan", x, name)


def sinh(x, name=None):
    return unary("Sinh", x, name)


def cosh(x, name=None):
    return unary("Cosh", x, name)


def tanh(x, name=None):
    return unary("Tanh", x, name)


def asinh(x, name=None):
    return unary("Asinh", x, name)


def acosh(x, name=None):
    return unary("Acosh", x, name)


def atanh(x, name=None):
    return unary("Atanh", x, name)


def sigmoid(x, name=None):
    return unary("Sigmoid", x, name)


def erf(x, name=None):
    return unary("Erf", x, name)


def erfc(x, name=None):
    return unary("Erfc", x, name)


def lgamma(x, name=None):
    return unary("Lgamma", x, name)


def digamma(x, name=None):
    return unary("Digamma", x, name)


def igamma(a, x, name=None):
    return binary("Igamma", a, x, name)


def igammac(a, x, name=None):
    return binary("Igammac", a, x, name)


def zeta(x, q, name=None):
    return binary("Zeta", x, q, name)


def polygamma(a, x, name=None):
    return binary("Polygamma", a, x, name)


def betainc(a, b, x, name=None):
    a = ops_mod.convert_to_tensor(a)
    b = ops_mod.convert_to_tensor(b, dtype=a.dtype)
    x = ops_mod.convert_to_tensor(x, dtype=a.dtype)
    return make_op("Betainc", [a, b, x], name=name)


def floor(x, name=None):
    return unary("Floor", x, name)


def ceil(x, name=None):
    return unary("Ceil", x, name)


def rint(x, name=None):
    return unary("Rint", x, name)


def round(x, name=None):  # noqa: A001
    return unary("Round", x, name)


def is_nan(x, name=None):
    return unary("IsNan", x, name)


def is_inf(x, name=None):
    return unary("IsInf", x, name)


def is_finite(x, name=None):
    return unary("IsFinite", x, name)


def logical_not(x, name=None):
    return unary("LogicalNot", x, name)


def logical_and(x, y, name=None):
    return binary("LogicalAnd", x, y, name)


def logical_or(x, y, name=None):
    return binary("LogicalOr", x, y, name)


def logical_xor(x, y, name=None):
    return binary("LogicalXor", x, y, name)


def equal(x, y, name=None):
    return binary("Equal", x, y, name)


def not_equal(x, y, name=None):
    return binary("NotEqual", x, y, name)


def less(x, y, name=None):
    return binary("Less", x, y, name)


def less_equal(x, y, name=None):
    return binary("LessEqual", x, y, name)


def greater(x, y, name=None):
    return binary("Greater", x, y, name)


def greater_equal(x, y, name=None):
    return binary("GreaterEqual", x, y, name)


def approximate_equal(x, y, tolerance=1e-5, name=None):
    x, y = promote_args(x, y, "ApproximateEqual")
    return make_op("ApproximateEqual", [x, y], attrs={"tolerance": tolerance},
                   name=name)


def real(x, name=None):
    return unary("Real", x, name)


def imag(x, name=None):
    return unary("Imag", x, name)


def conj(x, name=None):
    return unary("Conj", x, name)


def angle(x, name=None):
    return unary("Angle", x, name)


def cast(x, dtype, name=None):
    from ..framework.indexed_slices import IndexedSlices

    dtype = dtypes_mod.as_dtype(dtype)
    if isinstance(x, IndexedSlices):
        return IndexedSlices(cast(x.values, dtype, name), x.indices,
                             x.dense_shape)
    x = ops_mod.convert_to_tensor(x)
    if x.dtype.base_dtype == dtype.base_dtype:
        return x
    return make_op("Cast", [x], attrs={"dtype": dtype.base_dtype}, name=name)


def to_float(x, name="ToFloat"):
    return cast(x, dtypes_mod.float32, name)


def to_double(x, name="ToDouble"):
    return cast(x, dtypes_mod.float64, name)


def to_int32(x, name="ToInt32"):
    return cast(x, dtypes_mod.int32, name)


def to_int64(x, name="ToInt64"):
    return cast(x, dtypes_mod.int64, name)


def to_bfloat16(x, name="ToBFloat16"):
    return cast(x, dtypes_mod.bfloat16, name)


def saturate_cast(value, dtype, name=None):
    dtype = dtypes_mod.as_dtype(dtype)
    value = ops_mod.convert_to_tensor(value)
    from . import clip_ops

    if value.dtype.min < dtype.min or value.dtype.max > dtype.max:
        value = clip_ops.clip_by_value(
            value,
            ops_mod.convert_to_tensor(builtins.max(value.dtype.min, dtype.min),
                                      dtype=value.dtype),
            ops_mod.convert_to_tensor(builtins.min(value.dtype.max, dtype.max),
                                      dtype=value.dtype))
    return cast(value, dtype, name)


def add_n(inputs, name=None):
    from ..framework.indexed_slices import IndexedSlices

    if not inputs:
        raise ValueError("add_n needs at least one input")
    tensors = []
    for x in inputs:
        if isinstance(x, IndexedSlices):
            from . import array_ops, embedding_ops

            x = _densify_indexed_slices(x)
        tensors.append(ops_mod.convert_to_tensor(x))
    if len(tensors) == 1:
        return tensors[0]
    return make_op("AddN", tensors, name=name)


def _densify_indexed_slices(x):
    from . import array_ops

    return array_ops.scatter_nd(
        array_ops.expand_dims(x.indices, 1), x.values, x.dense_shape)


def accumulate_n(inputs, shape=None, tensor_dtype=None, name=None):
    return add_n(inputs, name=name)


def matmul(a, b, transpose_a=False, transpose_b=False, adjoint_a=False,
           adjoint_b=False, a_is_sparse=False, b_is_sparse=False, name=None):
    a, b = promote_args(a, b, "MatMul")
    if adjoint_a:
        a, transpose_a = conj(a), True
    if adjoint_b:
        b, transpose_b = conj(b), True
    if a.shape.rank is not None and a.shape.rank > 2:
        return make_op("BatchMatMul", [a, b],
                       attrs={"adj_x": transpose_a, "adj_y": transpose_b},
                       name=name)
    return make_op("MatMul", [a, b], attrs={"transpose_a": transpose_a,
                                            "transpose_b": transpose_b},
                   name=name)


def batch_matmul(a, b, adj_x=False, adj_y=False, name=None):
    a, b = promote_args(a, b, "BatchMatMul")
    return make_op("BatchMatMul", [a, b], attrs={"adj_x": adj_x, "adj_y": adj_y},
                   name=name)


def tensordot(a, b, axes, name=None):
    a, b = promote_args(a, b, "Tensordot")
    if isinstance(axes, (list, tuple)) and len(axes) == 2:
        axes = (tuple(np.ravel(axes[0]).tolist()), tuple(np.ravel(axes[1]).tolist()))
    else:
        axes = int(axes)
    return make_op("Tensordot", [a, b], attrs={"axes": axes}, name=name)


def einsum(equation, *inputs, name=None):
    tensors = [ops_mod.convert_to_tensor(x) for x in inputs]
    return make_op("Einsum", tensors, attrs={"equation": equation}, name=name)


def cross(a, b, name=None):
    return binary("Cross", a, b, name)


# -- reductions --------------------------------------------------------------

def _reduce(op_type, input_tensor, axis, keepdims, name,
            reduction_indices=None, keep_dims=None):
    if keep_dims is not None:
        keepdims = keep_dims
    if reduction_indices is not None and axis is None:
        axis = reduction_indices
    x = ops_mod.convert_to_tensor(input_tensor)
    return make_op(op_type, [x], attrs={"axis": norm_axis(axis),
                                        "keepdims": builtins.bool(keepdims)},
                   name=name)


def reduce_sum(input_tensor, axis=None, keepdims=False, name=None,
               reduction_indices=None, keep_dims=None):
    return _reduce("Sum", input_tensor, axis, keepdims, name,
                   reduction_indices, keep_dims)


def reduce_mean(input_tensor, axis=None, keepdims=False, name=None,
                reduction_indices=None, keep_dims=None):
    return _reduce("Mean", input_tensor, axis, keepdims, name,
                   reduction_indices, keep_dims)


def reduce_prod(input_tensor, axis=None, keepdims=False, name=None,
                reduction_indices=None, keep_dims=None):
    return _reduce("Prod", input_tensor, axis, keepdims, name,
                   reduction_indices, keep_dims)


def reduce_max(input_tensor, axis=None, keepdims=False, name=None,
               reduction_indices=None, keep_dims=None):
    return _reduce("Max", input_tensor, axis, keepdims, name,
                   reduction_indices, keep_dims)


def reduce_min(input_tensor, axis=None, keepdims=False, name=None,
               reduction_indices=None, keep_dims=None):
    return _reduce("Min", input_tensor, axis, keepdims, name,
                   reduction_indices, keep_dims)


def reduce_all(input_tensor, axis=None, keepdims=False, name=None,
               reduction_indices=None, keep_dims=None):
    return _reduce("All", input_tensor, axis, keepdims, name,
                   reduction_indices, keep_dims)


def reduce_any(input_tensor, axis=None, keepdims=False, name=None,
               reduction_indices=None, keep_dims=None):
    return _reduce("Any", input_tensor, axis, keepdims, name,
                   reduction_indices, keep_dims)


def reduce_logsumexp(input_tensor, axis=None, keepdims=False, name=None,
                     reduction_indices=None, keep_dims=None):
    return _reduce("LogSumExp", input_tensor, axis, keepdims, name,
                   reduction_indices, keep_dims)


def reduce_euclidean_norm(input_tensor, axis=None, keepdims=False, name=None):
    return _reduce("EuclideanNorm", input_tensor, axis, keepdims, name)


def count_nonzero(input_tensor, axis=None, keepdims=False,
                  dtype=dtypes_mod.int64, name=None):
    x = ops_mod.convert_to_tensor(input_tensor)
    nz = cast(not_equal(x, ops_mod.convert_to_tensor(0, dtype=x.dtype.base_dtype)),
              dtype)
    return reduce_sum(nz, axis=axis, keepdims=keepdims, name=name)


def argmax(input, axis=None, name=None, dimension=None, output_type=dtypes_mod.int64):  # noqa: A002
    if dimension is not None and axis is None:
        axis = dimension
    x = ops_mod.convert_to_tensor(input)
    return make_op("ArgMax", [x], attrs={"axis": int(axis or 0),
                                         "output_type": dtypes_mod.as_dtype(output_type)},
                   name=name)


def argmin(input, axis=None, name=None, dimension=None, output_type=dtypes_mod.int64):  # noqa: A002
    if dimension is not None and axis is None:
        axis = dimension
    x = ops_mod.convert_to_tensor(input)
    return make_op("ArgMin", [x], attrs={"axis": int(axis or 0),
                                         "output_type": dtypes_mod.as_dtype(output_type)},
                   name=name)


def cumsum(x, axis=0, exclusive=False, reverse=False, name=None):
    x = ops_mod.convert_to_tensor(x)
    return make_op("Cumsum", [x], attrs={"axis": int(axis),
                                         "exclusive": exclusive,
                                         "reverse": reverse}, name=name)


def cumprod(x, axis=0, exclusive=False, reverse=False, name=None):
    x = ops_mod.convert_to_tensor(x)
    return make_op("Cumprod", [x], attrs={"axis": int(axis),
                                          "exclusive": exclusive,
                                          "reverse": reverse}, name=name)


# -- segments ----------------------------------------------------------------

def _static_num_segments(num_segments):
    if num_segments is None:
        return None
    v = constant_op.constant_value(ops_mod.convert_to_tensor(num_segments))
    if v is None:
        raise ValueError("num_segments must be statically known on TPU")
    return int(v)


def _segment(op_type, data, segment_ids, num_segments=None, name=None):
    data = ops_mod.convert_to_tensor(data)
    segment_ids = ops_mod.convert_to_tensor(segment_ids)
    if num_segments is None:
        sv = constant_op.constant_value(segment_ids)
        if sv is not None:
            num_segments = int(np.max(sv)) + 1
    return make_op(op_type, [data, segment_ids],
                   attrs={"num_segments": _static_num_segments(num_segments)},
                   name=name)


def segment_sum(data, segment_ids, name=None, num_segments=None):
    return _segment("SegmentSum", data, segment_ids, num_segments, name)


def segment_mean(data, segment_ids, name=None, num_segments=None):
    return _segment("SegmentMean", data, segment_ids, num_segments, name)


def segment_max(data, segment_ids, name=None, num_segments=None):
    return _segment("SegmentMax", data, segment_ids, num_segments, name)


def segment_min(data, segment_ids, name=None, num_segments=None):
    return _segment("SegmentMin", data, segment_ids, num_segments, name)


def segment_prod(data, segment_ids, name=None, num_segments=None):
    return _segment("SegmentProd", data, segment_ids, num_segments, name)


def unsorted_segment_sum(data, segment_ids, num_segments, name=None):
    return _segment("UnsortedSegmentSum", data, segment_ids, num_segments, name)


def unsorted_segment_max(data, segment_ids, num_segments, name=None):
    return _segment("UnsortedSegmentMax", data, segment_ids, num_segments, name)


def unsorted_segment_min(data, segment_ids, num_segments, name=None):
    return _segment("UnsortedSegmentMin", data, segment_ids, num_segments, name)


def unsorted_segment_prod(data, segment_ids, num_segments, name=None):
    return _segment("UnsortedSegmentProd", data, segment_ids, num_segments, name)


def bincount(arr, weights=None, minlength=None, maxlength=None,
             dtype=dtypes_mod.int32, name=None):
    arr_t = ops_mod.convert_to_tensor(arr)
    v = constant_op.constant_value(arr_t)
    size = None
    if v is not None and v.size:
        size = int(np.max(v)) + 1
    if minlength is not None:
        size = builtins.max(size or 0, int(minlength))
    if maxlength is not None:
        size = builtins.min(size or int(maxlength), int(maxlength))
    inputs = [arr_t]
    out = make_op("Bincount", inputs, attrs={"size": size}, name=name)
    return cast(out, dtype)


# -- ranges ------------------------------------------------------------------

def range(start, limit=None, delta=1, dtype=None, name="range"):  # noqa: A001
    if limit is None:
        start, limit = 0, start
    sv = constant_op.constant_value(ops_mod.convert_to_tensor(start))
    lv = constant_op.constant_value(ops_mod.convert_to_tensor(limit))
    dv = constant_op.constant_value(ops_mod.convert_to_tensor(delta))
    if sv is None or lv is None or dv is None:
        raise ValueError("stf.range bounds must be static on TPU")
    arr = np.arange(sv, lv, dv)
    if dtype is not None:
        arr = arr.astype(dtypes_mod.as_dtype(dtype).np_dtype)
    elif arr.dtype == np.int64:
        arr = arr.astype(np.int32)
    elif arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    return constant_op.constant(arr, name=name)


def linspace(start, stop, num, name=None):
    sv = constant_op.constant_value(ops_mod.convert_to_tensor(start))
    ev = constant_op.constant_value(ops_mod.convert_to_tensor(stop))
    if sv is None or ev is None:
        raise ValueError("stf.linspace bounds must be static on TPU")
    return constant_op.constant(
        np.linspace(sv, ev, int(num), dtype=np.asarray(sv).dtype), name=name or "LinSpace")


lin_space = linspace


# -- misc --------------------------------------------------------------------

def logical_ops_placeholder():
    pass


def sigmoid_(x):
    return sigmoid(x)


def l2_normalize(x, axis=None, epsilon=1e-12, name=None, dim=None):
    if dim is not None and axis is None:
        axis = dim
    x = ops_mod.convert_to_tensor(x)
    sq = reduce_sum(square(x), axis=axis, keepdims=True)
    inv = rsqrt(maximum(sq, ops_mod.convert_to_tensor(epsilon, dtype=x.dtype.base_dtype)))
    return multiply(x, inv, name=name)


def scalar_mul(scalar, x, name=None):
    return multiply(ops_mod.convert_to_tensor(scalar), x, name=name)


def trace(x, name=None):
    from . import array_ops

    x = ops_mod.convert_to_tensor(x)
    return reduce_sum(array_ops.matrix_diag_part(x), axis=-1, name=name)


def reduced_shape(input_shape, axes):
    # kept for reference-API parity; rarely used directly
    raise NotImplementedError("reduced_shape is internal in stf")


# ---------------------------------------------------------------------------
# Operator overloads on Tensor (ref: math_ops.py _OverrideBinaryOperatorHelper)
# ---------------------------------------------------------------------------

def _install_operators():
    T = Tensor
    T.__add__ = lambda self, other: add(self, other)
    T.__radd__ = lambda self, other: add(other, self)
    T.__sub__ = lambda self, other: subtract(self, other)
    T.__rsub__ = lambda self, other: subtract(other, self)
    T.__mul__ = lambda self, other: multiply(self, other)
    T.__rmul__ = lambda self, other: multiply(other, self)
    T.__truediv__ = lambda self, other: truediv(self, other)
    T.__rtruediv__ = lambda self, other: truediv(other, self)
    T.__floordiv__ = lambda self, other: floordiv(self, other)
    T.__rfloordiv__ = lambda self, other: floordiv(other, self)
    T.__mod__ = lambda self, other: floormod(self, other)
    T.__rmod__ = lambda self, other: floormod(other, self)
    T.__pow__ = lambda self, other: pow(self, other)
    T.__rpow__ = lambda self, other: pow(other, self)
    T.__matmul__ = lambda self, other: matmul(self, other)
    T.__rmatmul__ = lambda self, other: matmul(other, self)
    T.__neg__ = lambda self: negative(self)
    T.__abs__ = lambda self: abs(self)
    T.__invert__ = lambda self: logical_not(self)
    T.__and__ = lambda self, other: logical_and(self, other)
    T.__rand__ = lambda self, other: logical_and(other, self)
    T.__or__ = lambda self, other: logical_or(self, other)
    T.__ror__ = lambda self, other: logical_or(other, self)
    T.__xor__ = lambda self, other: logical_xor(self, other)
    T.__rxor__ = lambda self, other: logical_xor(other, self)
    T.__lt__ = lambda self, other: less(self, other)
    T.__le__ = lambda self, other: less_equal(self, other)
    T.__gt__ = lambda self, other: greater(self, other)
    T.__ge__ = lambda self, other: greater_equal(self, other)

    from . import variables as variables_mod

    V = variables_mod.Variable
    for dunder in ("__add__", "__radd__", "__sub__", "__rsub__", "__mul__",
                   "__rmul__", "__truediv__", "__rtruediv__", "__floordiv__",
                   "__rfloordiv__", "__mod__", "__rmod__", "__pow__",
                   "__rpow__", "__matmul__", "__rmatmul__", "__neg__",
                   "__abs__", "__lt__", "__le__", "__gt__", "__ge__"):
        def _mk(d):
            def fwd(self, *args):
                return getattr(self._ref, d)(*args)

            return fwd

        setattr(V, dunder, _mk(dunder))


_install_operators()


# -- round-4 parity fills ----------------------------------------------------

floor_div = floordiv  # (ref: math_ops.py ``floor_div``)


op_registry.register_pure(
    "Complex", lambda re, im: jax.lax.complex(re, im))


def complex(real, imag, name=None):  # noqa: A002
    """(ref: math_ops.py ``complex``)."""
    from .op_util import promote_args

    r, i = promote_args(real, imag, "Complex")
    return make_op("Complex", [r, i], name=name)


def _sparse_segment(op_name, jfn):
    def impl(data, indices, segment_ids=None, n_segments=1, mode="sum"):
        import jax

        rows = jnp.take(data, indices.astype(jnp.int32), axis=0)
        seg = jnp.asarray(np.asarray(segment_ids, np.int32))
        s = jax.ops.segment_sum(rows, seg, n_segments)
        if mode == "sum":
            return s
        counts = jax.ops.segment_sum(jnp.ones_like(seg, jnp.float32), seg,
                                     n_segments)
        counts = jnp.maximum(counts, 1.0)
        shape = (-1,) + (1,) * (rows.ndim - 1)
        if mode == "mean":
            return s / counts.reshape(shape).astype(s.dtype)
        return s / jnp.sqrt(counts).reshape(shape).astype(s.dtype)

    op_registry.register_pure(op_name, impl)


_sparse_segment("SparseSegmentSum", None)


def _sparse_segment_api(data, indices, segment_ids, mode, name):
    """(ref: math_ops.py sparse_segment_{sum,mean,sqrt_n}): gather rows by
    ``indices`` then segment-reduce. segment_ids must be static (they set
    the output dim0 — data-dependent otherwise, same tf2xla limit)."""
    data = ops_mod.convert_to_tensor(data)
    idx = ops_mod.convert_to_tensor(indices)
    seg_v = constant_op.constant_value(
        ops_mod.convert_to_tensor(segment_ids))
    if seg_v is None:
        raise ValueError(
            f"sparse_segment_{mode} needs static segment_ids on TPU "
            "(they define the output shape)")
    seg = np.asarray(seg_v, np.int64)
    n = int(seg.max()) + 1 if seg.size else 0
    g = ops_mod.get_default_graph()
    out_shape = shape_mod.TensorShape(
        [n] + [d.value for d in data.shape[1:]])
    op = g.create_op(
        "SparseSegmentSum", [data, idx],
        attrs={"segment_ids": tuple(int(s) for s in seg),
               "n_segments": n, "mode": mode},
        name=name or f"sparse_segment_{mode}",
        output_specs=[(out_shape, data.dtype)])
    return op.outputs[0]


def sparse_segment_sum(data, indices, segment_ids, name=None):
    return _sparse_segment_api(data, indices, segment_ids, "sum", name)


def sparse_segment_mean(data, indices, segment_ids, name=None):
    return _sparse_segment_api(data, indices, segment_ids, "mean", name)


def sparse_segment_sqrt_n(data, indices, segment_ids, name=None):
    return _sparse_segment_api(data, indices, segment_ids, "sqrt_n", name)


# ---------------------------------------------------------------------------
# sharding propagation rules (stf.analysis.sharding; ISSUE 6) — declared
# alongside the op registrations above, same contract as abstract-eval:
# this module knows the math ops' semantics, so it declares how
# PartitionSpecs flow through them.
# ---------------------------------------------------------------------------

from ..analysis import sharding as _shard  # noqa: E402

_shard.register_rules(
    _shard.elementwise_rule,
    # unary
    "Neg", "Abs", "Sign", "Reciprocal", "Square", "Sqrt", "Rsqrt", "Exp",
    "Expm1", "Log", "Log1p", "Sin", "Cos", "Tan", "Asin", "Acos", "Atan",
    "Sinh", "Cosh", "Tanh", "Asinh", "Acosh", "Atanh", "Sigmoid", "Erf",
    "Erfc", "Lgamma", "Digamma", "Floor", "Ceil", "Rint", "Round",
    "IsNan", "IsInf", "IsFinite", "LogicalNot", "Invert", "Real", "Imag",
    "Conj", "Angle", "Softplus", "Softsign", "Cast", "ComplexAbs",
    # binary / n-ary (numpy broadcasting)
    "Add", "Sub", "Mul", "Div", "TrueDiv", "RealDiv", "FloorDiv",
    "TruncateDiv", "Mod", "FloorMod", "TruncateMod", "Pow", "Maximum",
    "Minimum", "SquaredDifference", "Atan2", "Xlogy", "Xdivy", "Zeta",
    "Polygamma", "Igamma", "Igammac", "Betainc", "LogicalAnd",
    "LogicalOr", "LogicalXor", "BitwiseAnd", "BitwiseOr", "BitwiseXor",
    "LeftShift", "RightShift", "Equal", "NotEqual", "Less", "LessEqual",
    "Greater", "GreaterEqual", "ApproximateEqual", "AddN", "ClipByValue",
    "Complex", "Cross", "NextAfter")

_shard.register_rules(_shard.make_reduce_rule("axis", "keepdims"),
                      "Sum", "Mean", "Prod", "Max", "Min", "All", "Any",
                      "LogSumExp", "EuclideanNorm", "ArgMax", "ArgMin",
                      "L2Loss")
_shard.register_rules(_shard.matmul_rule, "MatMul", "BatchMatMul",
                      "SparseMatMul")
_shard.register_rules(_shard.einsum_rule, "Einsum")
_shard.register_rules(_shard.make_axis_unsharded_rule("axis"),
                      "Cumsum", "Cumprod")
# host-small / index-producing results: sharded inputs are consumed
# as-is (the result is metadata-sized, not a gather of the operand)
_shard.register_rules(_shard.local_rule, "Range", "LinSpace", "Bincount")
