"""Embedding lookup (ref: tensorflow/python/ops/embedding_ops.py,
core/kernels/gather_op.cc).

TPU-native: a lookup is an XLA gather on the (possibly mesh-sharded) table;
with a table sharded over the 'ep'/'tp' mesh axis XLA turns the gather into
an all-to-all — the reference's partition_strategy machinery (mod/div over
parameter servers) collapses into sharding annotations. The gradient is an
IndexedSlices-style scatter-add, applied sparsely by optimizers.
"""

from __future__ import annotations

import functools

from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from ..framework import op_registry
from . import array_ops, math_ops
from . import variables as variables_mod


def _emb_mixed_impl(table, ids, compute_dtype):
    import jax
    import jax.numpy as jnp

    # table shape/dtype are closed over as STATICS (custom_vjp residuals
    # may only hold JAX types); only `ids` rides in the residuals
    tshape = tuple(table.shape)
    tdtype = table.dtype

    @functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
    def _lookup(table, ids, dt):
        return jnp.take(table.astype(dt), ids, axis=0)

    def _fwd(table, ids, dt):
        return _lookup(table, ids, dt), ids

    def _bwd(dt, ids, g):
        # upcast the per-row cotangents BEFORE the scatter so repeated ids
        # accumulate in the table's own precision — scatter-adding in bf16
        # loses contributions once the running sum is ~256x an addend
        gf = g.astype(tdtype)
        dtab = jnp.zeros(tshape, tdtype).at[ids].add(gf)
        return dtab, None

    _lookup.defvjp(_fwd, _bwd)
    return _lookup(table, ids, compute_dtype)


op_registry.register_pure(
    "EmbeddingLookupMixed",
    lambda table, ids, compute_dtype: _emb_mixed_impl(
        table, ids, dtypes_mod.as_dtype(compute_dtype).np_dtype))


def embedding_lookup(params, ids, partition_strategy="mod", name=None,
                     validate_indices=True, max_norm=None,
                     compute_dtype=None):
    """(ref: embedding_ops.py:110 ``embedding_lookup``).

    compute_dtype (TPU-native extension): gather rows in this dtype (the
    table is cast BEFORE the gather, so the [batch..., H] activations and
    their VJPs move at half width) while the gradient scatter-add still
    accumulates in the table's own precision."""
    if isinstance(params, variables_mod.PartitionedVariable):
        params = list(params)
    if isinstance(params, (list, tuple)) and len(params) > 1:
        # Reference shards tables across PS; TPU: concat the logical pieces
        # (the mesh shards the single array instead).
        p0 = [p._ref if isinstance(p, variables_mod.Variable) else p
              for p in params]
        table = array_ops.concat(list(p0), axis=0)
    else:
        p = params[0] if isinstance(params, (list, tuple)) else params
        table = p._ref if isinstance(p, variables_mod.Variable) else \
            ops_mod.convert_to_tensor(p)
    ids = ops_mod.convert_to_tensor(ids)
    if (compute_dtype is not None
            and dtypes_mod.as_dtype(compute_dtype) != table.dtype.base_dtype):
        g = ops_mod.get_default_graph()
        dt = dtypes_mod.as_dtype(compute_dtype)
        op = g.create_op(
            "EmbeddingLookupMixed", [table, ids],
            attrs={"compute_dtype": dt.name},
            name=name or "embedding_lookup_mixed",
            output_specs=[(ids.shape.concatenate(table.shape[1:]), dt)])
        out = op.outputs[0]
    else:
        out = array_ops.gather(table, ids, name=name)
    if max_norm is not None:
        norms = math_ops.sqrt(math_ops.reduce_sum(math_ops.square(out),
                                                  axis=-1, keepdims=True))
        clip = ops_mod.convert_to_tensor(max_norm, dtype=out.dtype.base_dtype)
        out = out * (clip / math_ops.maximum(norms, clip))
    return out


def embedding_lookup_sparse(params, sp_ids, sp_weights,
                            partition_strategy="mod", name=None,
                            combiner="mean", max_norm=None):
    """(ref: embedding_ops.py ``embedding_lookup_sparse``). Fixed-capacity
    COO ids; padding rows (id<0) contribute zero weight."""
    from ..framework import constant_op
    import numpy as np

    ids = sp_ids.values
    seg = sp_ids.indices[:, 0]
    emb = embedding_lookup(params, math_ops.maximum(
        ids, ops_mod.convert_to_tensor(0, dtype=ids.dtype.base_dtype)),
        max_norm=max_norm)
    if sp_weights is not None:
        w = math_ops.cast(sp_weights.values, emb.dtype.base_dtype)
    else:
        w = array_ops.ones_like(ids, dtype=emb.dtype.base_dtype)
    valid = math_ops.cast(math_ops.greater_equal(
        ids, ops_mod.convert_to_tensor(0, dtype=ids.dtype.base_dtype)),
        emb.dtype.base_dtype)
    w = w * valid
    weighted = emb * array_ops.expand_dims(w, -1)
    dv = constant_op.constant_value(sp_ids.dense_shape)
    if dv is None:
        raise ValueError("embedding_lookup_sparse needs static dense_shape")
    n_rows = int(np.asarray(dv)[0])
    seg32 = math_ops.cast(seg, "int32")
    summed = math_ops.unsorted_segment_sum(weighted, seg32, n_rows)
    if combiner == "sum":
        return summed
    counts = math_ops.unsorted_segment_sum(w, seg32, n_rows)
    counts = array_ops.expand_dims(counts, -1)
    if combiner == "mean":
        return summed / math_ops.maximum(
            counts, ops_mod.convert_to_tensor(1e-8, dtype=summed.dtype.base_dtype))
    if combiner == "sqrtn":
        sq = math_ops.unsorted_segment_sum(math_ops.square(w), seg32, n_rows)
        return summed / math_ops.maximum(
            math_ops.sqrt(array_ops.expand_dims(sq, -1)),
            ops_mod.convert_to_tensor(1e-8, dtype=summed.dtype.base_dtype))
    raise ValueError(f"unknown combiner {combiner}")


# ---------------------------------------------------------------------------
# sharding propagation rules (stf.analysis.sharding; ISSUE 6): a
# vocab-sharded table gathers via the one-hot contraction -> all-reduce
# of the looked-up activations (the ep-sharding cost the analyzer must
# surface before compile).
# ---------------------------------------------------------------------------

from ..analysis import sharding as _shard  # noqa: E402

_shard.register_rules(_shard.make_gather_rule("axis"),
                      "EmbeddingLookupMixed")
