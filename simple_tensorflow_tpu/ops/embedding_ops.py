"""Embedding lookup (ref: tensorflow/python/ops/embedding_ops.py,
core/kernels/gather_op.cc).

TPU-native: a lookup is an XLA gather on the (possibly mesh-sharded) table;
with a table sharded over the 'ep'/'tp' mesh axis XLA turns the gather into
an all-to-all — the reference's partition_strategy machinery (mod/div over
parameter servers) collapses into sharding annotations. The gradient is an
IndexedSlices-style scatter-add, applied sparsely by optimizers.
"""

from __future__ import annotations

import functools

from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from ..framework import op_registry
from . import array_ops, math_ops
from . import variables as variables_mod


def _emb_mixed_impl(table, ids, compute_dtype):
    import jax
    import jax.numpy as jnp

    # table shape/dtype are closed over as STATICS (custom_vjp residuals
    # may only hold JAX types); only `ids` rides in the residuals
    tshape = tuple(table.shape)
    tdtype = table.dtype

    @functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
    def _lookup(table, ids, dt):
        return jnp.take(table.astype(dt), ids, axis=0)

    def _fwd(table, ids, dt):
        return _lookup(table, ids, dt), ids

    def _bwd(dt, ids, g):
        # upcast the per-row cotangents BEFORE the scatter so repeated ids
        # accumulate in the table's own precision — scatter-adding in bf16
        # loses contributions once the running sum is ~256x an addend
        gf = g.astype(tdtype)
        dtab = jnp.zeros(tshape, tdtype).at[ids].add(gf)
        return dtab, None

    _lookup.defvjp(_fwd, _bwd)
    return _lookup(table, ids, compute_dtype)


op_registry.register_pure(
    "EmbeddingLookupMixed",
    lambda table, ids, compute_dtype: _emb_mixed_impl(
        table, ids, dtypes_mod.as_dtype(compute_dtype).np_dtype))


def embedding_lookup(params, ids, partition_strategy="mod", name=None,
                     validate_indices=True, max_norm=None,
                     compute_dtype=None):
    """(ref: embedding_ops.py:110 ``embedding_lookup``).

    compute_dtype (TPU-native extension): gather rows in this dtype (the
    table is cast BEFORE the gather, so the [batch..., H] activations and
    their VJPs move at half width) while the gradient scatter-add still
    accumulates in the table's own precision."""
    if isinstance(params, variables_mod.PartitionedVariable):
        params = list(params)
    if isinstance(params, (list, tuple)) and len(params) > 1:
        # Reference shards tables across PS; TPU: concat the logical pieces
        # (the mesh shards the single array instead).
        p0 = [p._ref if isinstance(p, variables_mod.Variable) else p
              for p in params]
        table = array_ops.concat(list(p0), axis=0)
    else:
        p = params[0] if isinstance(params, (list, tuple)) else params
        table = p._ref if isinstance(p, variables_mod.Variable) else \
            ops_mod.convert_to_tensor(p)
    ids = ops_mod.convert_to_tensor(ids)
    if (compute_dtype is not None
            and dtypes_mod.as_dtype(compute_dtype) != table.dtype.base_dtype):
        g = ops_mod.get_default_graph()
        dt = dtypes_mod.as_dtype(compute_dtype)
        op = g.create_op(
            "EmbeddingLookupMixed", [table, ids],
            attrs={"compute_dtype": dt.name},
            name=name or "embedding_lookup_mixed",
            output_specs=[(ids.shape.concatenate(table.shape[1:]), dt)])
        out = op.outputs[0]
    else:
        out = array_ops.gather(table, ids, name=name)
    if max_norm is not None:
        norms = math_ops.sqrt(math_ops.reduce_sum(math_ops.square(out),
                                                  axis=-1, keepdims=True))
        clip = ops_mod.convert_to_tensor(max_norm, dtype=out.dtype.base_dtype)
        out = out * (clip / math_ops.maximum(norms, clip))
    return out


def embedding_lookup_sparse(params, sp_ids, sp_weights,
                            partition_strategy="mod", name=None,
                            combiner="mean", max_norm=None):
    """(ref: embedding_ops.py ``embedding_lookup_sparse``). Fixed-capacity
    COO ids; padding rows (id<0) contribute zero weight."""
    from ..framework import constant_op
    import numpy as np

    ids = sp_ids.values
    seg = sp_ids.indices[:, 0]
    emb = embedding_lookup(params, math_ops.maximum(
        ids, ops_mod.convert_to_tensor(0, dtype=ids.dtype.base_dtype)),
        max_norm=max_norm)
    if sp_weights is not None:
        w = math_ops.cast(sp_weights.values, emb.dtype.base_dtype)
    else:
        w = array_ops.ones_like(ids, dtype=emb.dtype.base_dtype)
    valid = math_ops.cast(math_ops.greater_equal(
        ids, ops_mod.convert_to_tensor(0, dtype=ids.dtype.base_dtype)),
        emb.dtype.base_dtype)
    w = w * valid
    weighted = emb * array_ops.expand_dims(w, -1)
    dv = constant_op.constant_value(sp_ids.dense_shape)
    if dv is None:
        raise ValueError("embedding_lookup_sparse needs static dense_shape")
    n_rows = int(np.asarray(dv)[0])
    seg32 = math_ops.cast(seg, "int32")
    summed = math_ops.unsorted_segment_sum(weighted, seg32, n_rows)
    if combiner == "sum":
        return summed
    counts = math_ops.unsorted_segment_sum(w, seg32, n_rows)
    counts = array_ops.expand_dims(counts, -1)
    if combiner == "mean":
        return summed / math_ops.maximum(
            counts, ops_mod.convert_to_tensor(1e-8, dtype=summed.dtype.base_dtype))
    if combiner == "sqrtn":
        sq = math_ops.unsorted_segment_sum(math_ops.square(w), seg32, n_rows)
        return summed / math_ops.maximum(
            math_ops.sqrt(array_ops.expand_dims(sq, -1)),
            ops_mod.convert_to_tensor(1e-8, dtype=summed.dtype.base_dtype))
    raise ValueError(f"unknown combiner {combiner}")


# ===========================================================================
# Fused sharded-embedding fast path (ISSUE 19).
#
# The legacy lowering of a lookup on a vocab-sharded table is whatever
# GSPMD makes of the gather — on TPU the one-hot contraction + all-reduce
# of the looked-up activations (priced by make_gather_rule). The fused
# route below is explicit: dedup-before-lookup on device, then a
# shard_map over the 'ep' axis that routes each distinct id to its
# owning shard with ONE all-to-all, gathers locally, and returns the hit
# rows with a second all-to-all. The backward is a first-class
# EmbeddingScatterAddGrad op (segment_sum over the inverse index, then a
# masked scatter-add into the owning shard — no collective at all, the
# cotangents are replicated over ep by construction of the forward).
#
# Effects: both ops are deliberately PURE (empty effect set). The table
# arrives as a ReadVariable output, so hazard ordering against assigns
# rides the ReadVariable's declared reads; a stateful registration here
# would also break the _gradient_op_type override (framework/gradients
# refuses stateful/host ops). The /stf/embedding/* counters are fed by a
# diagnostic jax.debug.callback, not a graph effect.
# ===========================================================================

from ..platform import monitoring  # noqa: E402

_emb_lookups = monitoring.Counter(
    "/stf/embedding/lookups",
    "Ids looked up through the fused sharded-embedding path", "table")
_emb_unique = monitoring.Counter(
    "/stf/embedding/unique_ids",
    "Distinct ids per fused batch surviving dedup-before-lookup", "table")
_emb_dedup_ratio = monitoring.IntGauge(
    "/stf/embedding/dedup_ratio",
    "unique/total ids of the last fused batch, in basis points "
    "(10000 = every id distinct)", "table")
_emb_bytes = monitoring.Counter(
    "/stf/embedding/bytes_moved",
    "All-to-all payload bytes moved by the fused route (id route + row "
    "return, HLO result-shape accounting; 0 on the single-device "
    "fallback)", "table")


def _record_embedding_stats(table, total, n_unique, nbytes):
    """Host-side counter update behind jax.debug.callback — keep
    defensive: a metrics failure must never kill a training step."""
    try:
        label = str(table)
        total = int(total)
        _emb_lookups.get_cell(label).increase_by(total)
        _emb_unique.get_cell(label).increase_by(int(n_unique))
        if total:
            _emb_dedup_ratio.get_cell(label).set(
                int(round(10000.0 * float(n_unique) / total)))
        _emb_bytes.get_cell(label).increase_by(int(nbytes))
    except Exception:  # pragma: no cover — diagnostics only
        pass


def _fused_route(table_l, uniq, *, axis_name, n):
    """Per-shard body of the fused lookup (runs inside shard_map).

    table_l: (vocab/n, D) local vocab shard; uniq: (b,) deduped ids,
    replicated. Routes each id to its owning shard with one tiled
    all-to-all, gathers the rows locally, and all-to-alls them back.
    Out-of-range ids (incl. the -1 send-buffer sentinel) produce zero
    rows. Returns (b, D), identical on every shard.
    """
    import jax
    import jax.numpy as jnp

    vl = table_l.shape[0]
    b = uniq.shape[0]
    owner = jnp.clip(uniq // vl, 0, n - 1).astype(jnp.int32)
    # rank of each id within its owner's bucket -> fixed-capacity (n, b)
    # send buffer. Dedup cannot shrink the buffer (XLA shapes are
    # static); it shrinks the number of USEFUL slots, observed at
    # runtime through /stf/embedding/dedup_ratio.
    onehot = owner[:, None] == jnp.arange(n, dtype=jnp.int32)[None, :]
    pos = (jnp.cumsum(onehot.astype(jnp.int32), axis=0) - 1)[
        jnp.arange(b), owner]
    send = jnp.full((n, b), -1, uniq.dtype).at[owner, pos].set(uniq)
    # recv[j] = the ids device j asked me (their owner) to resolve
    recv = jax.lax.all_to_all(send, axis_name, 0, 0, tiled=True)
    me = jax.lax.axis_index(axis_name)
    local = recv - me * vl
    valid = (recv >= 0) & (local >= 0) & (local < vl)
    rows = jnp.where(
        valid[..., None],
        jnp.take(table_l, jnp.clip(local, 0, vl - 1), axis=0),
        jnp.zeros((), table_l.dtype))
    # back[k] aligns with send[k]: the rows I requested from owner k
    back = jax.lax.all_to_all(rows, axis_name, 0, 0, tiled=True)
    return back[owner, pos]


def _dedup_ids(ids_flat, dedup):
    """(uniq, inverse-or-None, n_unique) for a flat id vector."""
    import jax.numpy as jnp

    b = ids_flat.shape[0]
    if not dedup or b <= 1:
        return ids_flat, None, jnp.asarray(b, jnp.int32)
    uniq, inv = jnp.unique(ids_flat, size=b, fill_value=0,
                           return_inverse=True)
    inv = inv.reshape(-1)
    return uniq, inv, (jnp.max(inv) + 1).astype(jnp.int32)


def _lower_fused_lookup(ctx, op, inputs):
    import jax
    import jax.numpy as jnp

    from ..parallel.mesh import current_mesh, get_shard_map

    table, ids = inputs
    axis = op.attrs.get("axis", "ep")
    dedup = bool(op.attrs.get("dedup", True))
    cdt = dtypes_mod.as_dtype(op.attrs["compute_dtype"]).np_dtype
    label = op.attrs.get("table", op.name)
    vocab = int(table.shape[0])
    dim = int(table.shape[1])
    ids_shape = tuple(ids.shape)
    ids_flat = ids.reshape(-1)
    b = ids_flat.shape[0]
    tbl = table.astype(cdt)

    uniq, inv, n_unique = _dedup_ids(ids_flat, dedup)

    mesh = current_mesh()
    in_sm = bool(getattr(ctx, "in_shard_map", False))
    nbytes = 0
    if in_sm:
        n = jax.lax.psum(1, axis)
        rows = _fused_route(tbl, uniq, axis_name=axis, n=n)
    elif (mesh is None or axis not in mesh.shape
            or mesh.axis_size(axis) == 1 or vocab % mesh.axis_size(axis)
            or b == 0):
        rows = jnp.take(tbl, jnp.clip(uniq, 0, vocab - 1), axis=0)
    else:
        from jax.sharding import PartitionSpec as JP

        n = mesh.axis_size(axis)
        fn = get_shard_map()(
            functools.partial(_fused_route, axis_name=axis, n=n),
            mesh=mesh.jax_mesh,
            in_specs=(JP(axis, None), JP(None)),
            out_specs=JP(None), check_vma=False)
        rows = fn(tbl, uniq)
        nbytes = n * b * (ids_flat.dtype.itemsize
                          + dim * rows.dtype.itemsize)
    out = rows if inv is None else jnp.take(rows, inv, axis=0)
    if not in_sm:
        jax.debug.callback(_record_embedding_stats, label,
                           jnp.asarray(b, jnp.int32), n_unique,
                           jnp.asarray(float(nbytes), jnp.float32))
    return [out.reshape(ids_shape + (dim,))]


def _lower_scatter_add_grad(ctx, op, inputs):
    import jax
    import jax.numpy as jnp

    from ..parallel.mesh import current_mesh, get_shard_map

    ids, g = inputs
    axis = op.attrs.get("axis", "ep")
    dedup = bool(op.attrs.get("dedup", True))
    vocab, dim = (int(d) for d in op.attrs["table_shape"])
    tdt = dtypes_mod.as_dtype(op.attrs["table_dtype"]).np_dtype
    ids_flat = ids.reshape(-1)
    b = ids_flat.shape[0]
    # upcast BEFORE any accumulation: repeated ids must sum in the
    # table's own precision (same contract as EmbeddingLookupMixed)
    gm = g.reshape(b, dim).astype(tdt)

    uniq, inv, _ = _dedup_ids(ids_flat, dedup)
    if inv is not None:
        gm = jax.ops.segment_sum(gm, inv, num_segments=b)

    mesh = current_mesh()
    in_sm = bool(getattr(ctx, "in_shard_map", False))

    def _scatter_shard(uniq_s, gu_s, *, n):
        vl = vocab // n
        me = jax.lax.axis_index(axis)
        lo = me * vl
        loc = jnp.clip(uniq_s - lo, 0, vl - 1)
        own = (uniq_s >= lo) & (uniq_s < lo + vl)
        add = jnp.where(own[:, None], gu_s, jnp.zeros((), gu_s.dtype))
        return jnp.zeros((vl, dim), tdt).at[loc].add(add)

    if in_sm:
        n = jax.lax.psum(1, axis)
        return [_scatter_shard(uniq, gm, n=n)]
    if (mesh is None or axis not in mesh.shape
            or mesh.axis_size(axis) == 1 or vocab % mesh.axis_size(axis)
            or b == 0):
        dtab = jnp.zeros((vocab, dim), tdt).at[
            jnp.clip(uniq, 0, vocab - 1)].add(
                jnp.where((uniq >= 0)[:, None] & (uniq < vocab)[:, None],
                          gm, jnp.zeros((), gm.dtype)))
        return [dtab]
    from jax.sharding import PartitionSpec as JP

    n = mesh.axis_size(axis)
    fn = get_shard_map()(
        functools.partial(_scatter_shard, n=n),
        mesh=mesh.jax_mesh, in_specs=(JP(None), JP(None)),
        out_specs=JP(axis, None), check_vma=False)
    return [fn(uniq, gm)]


op_registry.register("EmbeddingLookupFused", lower=_lower_fused_lookup)
op_registry.register("EmbeddingScatterAddGrad",
                     lower=_lower_scatter_add_grad)


from ..framework.gradients import RegisterGradient  # noqa: E402


@RegisterGradient("FusedEmbeddingLookupGrad")
def _fused_lookup_grad(op, grad):
    """d(lookup)/d(table) as a first-class EmbeddingScatterAddGrad op;
    ids carry no gradient. Activated through the _gradient_op_type attr
    stamped at op creation (no gradient_override_map needed)."""
    g = ops_mod.get_default_graph()
    table_t, ids_t = op.inputs[0], op.inputs[1]
    node = g.create_op(
        "EmbeddingScatterAddGrad", [ids_t, grad],
        attrs={"axis": op.attrs.get("axis", "ep"),
               "dedup": bool(op.attrs.get("dedup", True)),
               "table_shape": tuple(int(d.value)
                                    for d in table_t.shape.dims),
               "table_dtype": table_t.dtype.base_dtype.name},
        name=op.name + "_scatter_add",
        output_specs=[(table_t.shape, table_t.dtype.base_dtype)])
    return [node.outputs[0], None]


def _resolve_table(params):
    if isinstance(params, variables_mod.PartitionedVariable):
        params = list(params)
    if isinstance(params, (list, tuple)) and len(params) > 1:
        p0 = [p._ref if isinstance(p, variables_mod.Variable) else p
              for p in params]
        return array_ops.concat(list(p0), axis=0)
    p = params[0] if isinstance(params, (list, tuple)) else params
    return (p._ref if isinstance(p, variables_mod.Variable)
            else ops_mod.convert_to_tensor(p))


def embedding_lookup_fused(params, ids, *, axis="ep", dedup=True,
                           compute_dtype=None, name=None):
    """Fused sharded-embedding lookup (ISSUE 19 tentpole).

    Semantics of ``embedding_lookup`` restricted to a rank-2 table with
    statically known shape and in-range ids; with the table
    vocab-sharded over mesh axis ``axis`` the lowering routes distinct
    ids to their owning shard with a single all-to-all instead of the
    one-hot contraction + all-reduce. ``dedup`` runs the per-batch
    unique+inverse pass so each distinct id crosses the wire once.
    Single-device (or no ``axis`` in the mesh): a plain clipped gather.
    """
    table = _resolve_table(params)
    ids = ops_mod.convert_to_tensor(ids)
    if table.shape.rank != 2 or not all(
            d.value for d in table.shape.dims):
        raise ValueError(
            "embedding_lookup_fused needs a statically-shaped rank-2 "
            f"table, got {table.shape}")
    dt = (dtypes_mod.as_dtype(compute_dtype) if compute_dtype is not None
          else table.dtype.base_dtype)
    g = ops_mod.get_default_graph()
    op = g.create_op(
        "EmbeddingLookupFused", [table, ids],
        attrs={"axis": axis, "dedup": bool(dedup),
               "compute_dtype": dt.name,
               "table": table.op.name,
               "_gradient_op_type": "FusedEmbeddingLookupGrad"},
        name=name or "embedding_lookup_fused",
        output_specs=[(ids.shape.concatenate(table.shape[1:]), dt)])
    return op.outputs[0]


def embedding_bag(params, ids, lengths=None, *, combiner="mean",
                  axis="ep", dedup=True, compute_dtype=None, name=None):
    """Pooled bag lookup over padded (B, L) id matrices — the consumer
    of the ragged/varlen Example parse (stf.data DATA.md contract):
    row i pools ids[i, :lengths[i]]; padding slots (any id; the parser
    emits -1) contribute zero. combiner: "sum" | "mean"."""
    import numpy as np

    if combiner not in ("sum", "mean"):
        raise ValueError(f"embedding_bag combiner must be sum|mean, "
                         f"got {combiner!r}")
    ids = ops_mod.convert_to_tensor(ids)
    if ids.shape.rank != 2 or ids.shape.dims[1].value is None:
        raise ValueError(
            f"embedding_bag needs (B, L) ids with static L, got {ids.shape}")
    zero = ops_mod.convert_to_tensor(0, dtype=ids.dtype.base_dtype)
    emb = embedding_lookup_fused(
        params, math_ops.maximum(ids, zero), axis=axis, dedup=dedup,
        compute_dtype=compute_dtype, name=name)  # (B, L, D)
    fdt = emb.dtype.base_dtype
    if lengths is not None:
        seq = ops_mod.convert_to_tensor(
            np.arange(int(ids.shape.dims[1].value)),
            dtype=lengths.dtype.base_dtype)
        mask = math_ops.cast(
            math_ops.less(array_ops.expand_dims(seq, 0),
                          array_ops.expand_dims(lengths, 1)), fdt)
    else:
        mask = math_ops.cast(math_ops.greater_equal(ids, zero), fdt)
    weighted = emb * array_ops.expand_dims(mask, -1)
    summed = math_ops.reduce_sum(weighted, axis=1)  # (B, D)
    if combiner == "sum":
        return summed
    counts = math_ops.reduce_sum(mask, axis=1, keepdims=True)
    one = ops_mod.convert_to_tensor(1.0, dtype=fdt)
    return summed / math_ops.maximum(counts, one)


# ---------------------------------------------------------------------------
# sharding propagation rules (stf.analysis.sharding; ISSUE 6 + 19): the
# legacy lookup lowers through GSPMD's gather -> one-hot contraction +
# all-reduce of the looked-up activations (make_gather_rule). The fused
# ops price their actual wire traffic: two tiled all-to-alls at HLO
# result-shape bytes (make_fused_embedding_rule), nothing for the
# backward scatter (cotangents are ep-replicated by construction).
# ---------------------------------------------------------------------------

from ..analysis import sharding as _shard  # noqa: E402

_shard.register_rules(_shard.make_gather_rule("axis"),
                      "EmbeddingLookupMixed")
_shard.register_rules(_shard.make_fused_embedding_rule("axis"),
                      "EmbeddingLookupFused")
_shard.register_rules(_shard.make_fused_scatter_grad_rule("axis"),
                      "EmbeddingScatterAddGrad")
