"""Reader ops: WholeFileReader, TextLineReader, TFRecordReader, etc.
(ref: tensorflow/python/ops/io_ops.py:189-399,
core/kernels/{whole_file_read_ops,text_line_reader_op,
tf_record_reader_op,fixed_length_record_reader_op,identity_reader_op}.cc).

TPU-native split: readers are HOST-stage resources (the reference pins all
reader kernels to CPU too). ``reader.read(queue)`` dequeues filenames from a
host queue as work units and yields (key, value) string tensors; the values
feed parsing ops (parse_example / decode_raw / decode_image), whose dense
outputs cross into the compiled device step. State (records produced, work
units completed) lives on the Python resource, mirroring the reference's
ReaderBase mutex-guarded state (core/framework/reader_op_kernel.h).
"""

from __future__ import annotations

import builtins
from typing import Any, Dict, List, Optional

import numpy as np

from ..framework import dtypes as dtypes_mod
from ..framework import errors
from ..framework import graph as ops_mod
from ..framework import op_registry
from ..framework import tensor_shape as shape_mod


# -- file-level ops ----------------------------------------------------------

def _lower_read_file(ctx, op, inputs):
    fname = _to_str(inputs[0])
    with open(fname, "rb") as f:
        return [np.asarray(f.read(), dtype=object)]


def _lower_write_file(ctx, op, inputs):
    import os

    fname = _to_str(inputs[0])
    d = os.path.dirname(fname)
    if d:
        os.makedirs(d, exist_ok=True)
    contents = inputs[1]
    data = contents.item() if hasattr(contents, "item") else contents
    mode = "wb" if isinstance(data, bytes) else "w"
    with open(fname, mode) as f:
        f.write(data)
    return []


def _lower_matching_files(ctx, op, inputs):
    from ..lib.io import file_io

    pattern = _to_str(inputs[0])
    return [np.asarray(sorted(file_io.get_matching_files(pattern)),
                       dtype=object)]


def _to_str(x) -> str:
    v = x.item() if hasattr(x, "item") else x
    return v.decode() if isinstance(v, bytes) else builtins.str(v)


op_registry.register("ReadFile", lower=_lower_read_file, runs_on_host=True,
                     n_outputs=1)
op_registry.register("WriteFile", lower=_lower_write_file, runs_on_host=True,
                     is_stateful=True, n_outputs=0)
op_registry.register("MatchingFiles", lower=_lower_matching_files,
                     runs_on_host=True, n_outputs=1)


def read_file(filename, name=None):
    """(ref: python/ops/io_ops.py ``read_file``)."""
    filename = ops_mod.convert_to_tensor(filename, dtype=dtypes_mod.string)
    g = ops_mod.get_default_graph()
    op = g.create_op("ReadFile", [filename], attrs={}, name=name or "ReadFile",
                     output_specs=[(shape_mod.scalar(), dtypes_mod.string)])
    return op.outputs[0]


def write_file(filename, contents, name=None):
    filename = ops_mod.convert_to_tensor(filename, dtype=dtypes_mod.string)
    contents = ops_mod.convert_to_tensor(contents, dtype=dtypes_mod.string)
    g = ops_mod.get_default_graph()
    return g.create_op("WriteFile", [filename, contents], attrs={},
                       name=name or "WriteFile", output_specs=[])


def matching_files(pattern, name=None):
    pattern = ops_mod.convert_to_tensor(pattern, dtype=dtypes_mod.string)
    g = ops_mod.get_default_graph()
    op = g.create_op(
        "MatchingFiles", [pattern], attrs={}, name=name or "MatchingFiles",
        output_specs=[(shape_mod.TensorShape([None]), dtypes_mod.string)])
    return op.outputs[0]


# -- reader resources --------------------------------------------------------

_READERS: Dict[str, "ReaderBase"] = {}
_READER_COUNT = [0]


class ReaderBase:
    """(ref: python/ops/io_ops.py:189 ``class ReaderBase``).

    Subclasses implement ``_records(work_item)`` -> iterator of
    (key, value) pairs for one work unit (a filename dequeued from the
    queue).
    """

    def __init__(self, name: str):
        _READER_COUNT[0] += 1
        self._name = f"{name}_{_READER_COUNT[0]}"
        _READERS[self._name] = self
        self._current: Optional[Any] = None  # active record iterator
        self._records_produced = 0
        self._work_done = 0

    # -- subclass hook -------------------------------------------------------
    def _records(self, work_item: str):
        raise NotImplementedError

    # -- host-side behavior --------------------------------------------------
    def _host_read(self, queue):
        while True:
            if self._current is None:
                item = queue._host_dequeue()
                work = _to_str(item[0] if isinstance(item, tuple) else item)
                self._current = self._records(work)
            try:
                key, value = next(self._current)
                self._records_produced += 1
                return key, value
            except StopIteration:
                self._current = None
                self._work_done += 1

    def _host_read_up_to(self, queue, n):
        keys, values = [], []
        for _ in builtins.range(n):
            try:
                k, v = self._host_read(queue)
            except errors.OutOfRangeError:
                if keys:
                    break  # partial batch at end of input
                raise
            keys.append(k)
            values.append(v)
        return keys, values

    def _host_reset(self):
        self._current = None
        self._records_produced = 0
        self._work_done = 0

    # -- graph endpoints -----------------------------------------------------
    @property
    def reader_ref(self):
        return self._name

    def read(self, queue, name=None):
        """Returns (key, value) string tensors; dequeues filenames from
        ``queue`` as needed (ref io_ops.py:211 ``ReaderBase.read``)."""
        g = ops_mod.get_default_graph()
        op = g.create_op(
            "ReaderRead", [],
            attrs={"reader_name": self._name,
                   "queue_name": _queue_name(queue)},
            name=name or f"{self._name}_read",
            output_specs=[(shape_mod.scalar(), dtypes_mod.string),
                          (shape_mod.scalar(), dtypes_mod.string)])
        return op.outputs[0], op.outputs[1]

    def read_up_to(self, queue, num_records, name=None):
        g = ops_mod.get_default_graph()
        op = g.create_op(
            "ReaderReadUpTo", [],
            attrs={"reader_name": self._name,
                   "queue_name": _queue_name(queue),
                   "num_records": int(num_records)},
            name=name or f"{self._name}_read_up_to",
            output_specs=[(shape_mod.TensorShape([None]), dtypes_mod.string),
                          (shape_mod.TensorShape([None]), dtypes_mod.string)])
        return op.outputs[0], op.outputs[1]

    def num_records_produced(self, name=None):
        g = ops_mod.get_default_graph()
        op = g.create_op(
            "ReaderNumRecordsProduced", [],
            attrs={"reader_name": self._name},
            name=name or f"{self._name}_records_produced",
            output_specs=[(shape_mod.scalar(), dtypes_mod.int64)])
        return op.outputs[0]

    def num_work_units_completed(self, name=None):
        g = ops_mod.get_default_graph()
        op = g.create_op(
            "ReaderNumWorkUnitsCompleted", [],
            attrs={"reader_name": self._name},
            name=name or f"{self._name}_work_units",
            output_specs=[(shape_mod.scalar(), dtypes_mod.int64)])
        return op.outputs[0]

    def reset(self, name=None):
        g = ops_mod.get_default_graph()
        return g.create_op("ReaderReset", [],
                           attrs={"reader_name": self._name},
                           name=name or f"{self._name}_reset",
                           output_specs=[])


def _queue_name(queue) -> str:
    if isinstance(queue, str):
        return queue
    if hasattr(queue, "queue_ref"):
        return queue.queue_ref
    # a dequeue-able tensor was passed (ref accepts queue or its ref)
    raise TypeError(f"Expected a queue, got {type(queue)}")


class WholeFileReader(ReaderBase):
    """One record per file: key=filename, value=contents
    (ref: io_ops.py:326, core/kernels/whole_file_read_ops.cc)."""

    def __init__(self, name="WholeFileReader"):
        super().__init__(name)

    def _records(self, work_item):
        with open(work_item, "rb") as f:
            data = f.read()
        yield work_item, data


class IdentityReader(ReaderBase):
    """key == value == work item (ref: io_ops.py:399)."""

    def __init__(self, name="IdentityReader"):
        super().__init__(name)

    def _records(self, work_item):
        yield work_item, work_item


class TextLineReader(ReaderBase):
    """One record per newline-delimited line (ref: io_ops.py:340,
    core/kernels/text_line_reader_op.cc)."""

    def __init__(self, skip_header_lines=0, name="TextLineReader"):
        super().__init__(name)
        self._skip = int(skip_header_lines or 0)

    def _records(self, work_item):
        with open(work_item, "r") as f:
            for i, line in enumerate(f):
                if i < self._skip:
                    continue
                yield f"{work_item}:{i + 1}", line.rstrip("\n")


class TFRecordReader(ReaderBase):
    """One record per TFRecord entry, via the native C++ reader when
    available (ref: io_ops.py:368, core/kernels/tf_record_reader_op.cc)."""

    def __init__(self, name="TFRecordReader", options=None):
        super().__init__(name)
        self._options = options

    def _records(self, work_item):
        from ..lib.io import tf_record

        for i, rec in enumerate(
                tf_record.tf_record_iterator(work_item, self._options)):
            yield f"{work_item}:{i}", rec


class FixedLengthRecordReader(ReaderBase):
    """Fixed-size binary records (ref: io_ops.py:354,
    core/kernels/fixed_length_record_reader_op.cc)."""

    def __init__(self, record_bytes, header_bytes=None, footer_bytes=None,
                 name="FixedLengthRecordReader"):
        super().__init__(name)
        self._record_bytes = int(record_bytes)
        self._header = int(header_bytes or 0)
        self._footer = int(footer_bytes or 0)

    def _records(self, work_item):
        import os

        size = os.path.getsize(work_item)
        body = size - self._header - self._footer
        n = body // self._record_bytes
        with open(work_item, "rb") as f:
            f.seek(self._header)
            for i in builtins.range(n):
                yield f"{work_item}:{i}", f.read(self._record_bytes)


# -- lowerings ---------------------------------------------------------------

def _get_reader(op) -> ReaderBase:
    return _READERS[op.attrs["reader_name"]]


def _get_queue(op):
    from .data_flow_ops import QueueBase

    return QueueBase._registry[op.attrs["queue_name"]]


def _lower_reader_read(ctx, op, inputs):
    key, value = _get_reader(op)._host_read(_get_queue(op))
    return [np.asarray(key, dtype=object), np.asarray(value, dtype=object)]


def _lower_reader_read_up_to(ctx, op, inputs):
    keys, values = _get_reader(op)._host_read_up_to(
        _get_queue(op), op.attrs["num_records"])
    return [np.asarray(keys, dtype=object), np.asarray(values, dtype=object)]


op_registry.register("ReaderRead", lower=_lower_reader_read,
                     is_stateful=True, runs_on_host=True, n_outputs=2)
op_registry.register("ReaderReadUpTo", lower=_lower_reader_read_up_to,
                     is_stateful=True, runs_on_host=True, n_outputs=2)
op_registry.register(
    "ReaderNumRecordsProduced",
    lower=lambda ctx, op, inputs: [np.int64(_get_reader(op)._records_produced)],
    is_stateful=True, runs_on_host=True, n_outputs=1)
op_registry.register(
    "ReaderNumWorkUnitsCompleted",
    lower=lambda ctx, op, inputs: [np.int64(_get_reader(op)._work_done)],
    is_stateful=True, runs_on_host=True, n_outputs=1)
op_registry.register(
    "ReaderReset",
    lower=lambda ctx, op, inputs: (_get_reader(op)._host_reset(), [])[1],
    is_stateful=True, runs_on_host=True, n_outputs=0)


# declared effect sets (stf.analysis): readers advance per-reader state
# and drain their work queue; file writes touch the filesystem
op_registry.declare_effects("WriteFile", op_registry.Effects(io=True, writes=("=filesystem",)))
for _r_op in ("ReaderRead", "ReaderReadUpTo"):
    op_registry.declare_effects(
        _r_op, op_registry.Effects(io=True, writes=("reader_name", "queue_name")))
op_registry.declare_effects("ReaderReset", op_registry.Effects(writes=("reader_name",)))
for _r_op in ("ReaderNumRecordsProduced", "ReaderNumWorkUnitsCompleted"):
    op_registry.declare_effects(_r_op, op_registry.Effects(reads=("reader_name",)))
