"""Neural-net ops (ref: tensorflow/python/ops/nn_ops.py,
core/kernels/{conv_ops,maxpooling_op,avgpooling_op,softmax_op,relu_op,
bias_op,xent_op}.cc and their *_gpu.cu.cc CUDA kernels).

TPU-native notes:
- conv2d lowers to lax.conv_general_dilated in NHWC with f32 accumulation —
  XLA tiles it onto the MXU (the reference dispatches to cuDNN). NCHW inputs
  are accepted and transposed once; NHWC is the TPU-preferred layout.
- softmax/log_softmax/xent are jax.nn compositions fused by XLA; a Pallas
  fused softmax-xent for large vocabularies lives in ops/pallas/.
- dropout uses the functional RNG stream (see random_ops) so the same mask
  is replayed in the vjp backward pass.
"""

from __future__ import annotations

import builtins
import numpy as np

import jax
import jax.numpy as jnp

from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from ..framework import op_registry
from ..framework import random_seed as random_seed_mod
from ..framework import tensor_shape as shape_mod
from .op_util import make_op, unary

Tensor = ops_mod.Tensor


def _acc32(dtype):
    d = np.dtype(dtype)
    return np.float32 if (d.kind == "f" and d.itemsize <= 2) or str(d) == "bfloat16" \
        else None


# -- registrations -----------------------------------------------------------

op_registry.register_pure("Relu", jax.nn.relu)
op_registry.register_pure("Relu6", jax.nn.relu6)
op_registry.register_pure("Elu", jax.nn.elu)
op_registry.register_pure("Selu", jax.nn.selu)
op_registry.register_pure("Gelu", lambda x, approximate=True: jax.nn.gelu(
    x, approximate=approximate))
op_registry.register_pure("LeakyRelu", lambda x, alpha=0.2: jax.nn.leaky_relu(
    x, negative_slope=alpha))
op_registry.register_pure("Softmax", lambda x, axis=-1: jax.nn.softmax(x, axis=axis))
op_registry.register_pure("LogSoftmax", lambda x, axis=-1: jax.nn.log_softmax(
    x, axis=axis))
op_registry.register_pure("Swish", lambda x: jax.nn.silu(x))
op_registry.register_pure("L2Loss", lambda x: 0.5 * jnp.sum(
    jnp.square(x.astype(jnp.float32))).astype(x.dtype))
op_registry.register_pure("BiasAdd", lambda x, b, data_format="NHWC":
                          x + (b.reshape((1, -1) + (1,) * (x.ndim - 2))
                               if data_format.startswith("NC") and x.ndim > 2
                               else b))


def _softmax_xent(logits, labels):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    loss = -jnp.sum(labels.astype(jnp.float32) * logp, axis=-1)
    return loss.astype(logits.dtype)


def _sparse_softmax_xent(logits, labels):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    loss = -jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32),
                                axis=-1)[..., 0]
    return loss.astype(logits.dtype)


op_registry.register_pure("SoftmaxCrossEntropyWithLogits", _softmax_xent)


def _sparse_xent_pallas(logits, labels):
    """The Pallas streamed-xent route for the composed graph op: same
    contract (per-example loss in the logits dtype)."""
    from .pallas import softmax_cross_entropy

    return softmax_cross_entropy(logits, labels).astype(logits.dtype)


def _sparse_xent_eligible(key):
    # same contract as the FusedSoftmaxXent op — one eligibility
    # implementation (ops/pallas) serves both routes
    from . import pallas as _pallas

    return _pallas._xent_eligible(key)


def _lower_sparse_xent(ctx, op, inputs):
    """nn_ops sparse softmax-xent: routed through stf.kernels — the
    large-vocab Pallas streamed kernel replaces the composed
    log_softmax + gather lowering when the cost model/autotune gates it
    in (ops/pallas/softmax_xent.py); ``off`` mode keeps the composed
    lowering exactly."""
    from ..kernels import registry as _kreg

    logits, labels = inputs
    fn = _kreg.select("SparseSoftmaxCrossEntropyWithLogits",
                      _kreg.aval_key(logits, labels))
    return [fn(logits, labels)]


op_registry.register("SparseSoftmaxCrossEntropyWithLogits",
                     lower=_lower_sparse_xent,
                     pure_fn=_sparse_softmax_xent)


def _register_sparse_xent_kernel():
    from ..kernels import registry as _kreg

    def _gate(key, bk):
        lb_shape, lb_dt = key[0]
        n = 1
        for d in lb_shape:
            n *= int(d)
        try:
            itm = {"bfloat16": 2, "float16": 2}.get(str(lb_dt))
            if itm is None:
                import numpy as _np

                itm = _np.dtype(str(lb_dt)).itemsize
        except TypeError:
            itm = 4
        return _kreg.roofline_gate(5.0 * n, 1.2 * n * itm, 3.0 * n * itm, bk)

    def _case(key):
        import numpy as _np

        (ls, ld), (labs, labd) = key[:2]
        rng = _np.random.RandomState(0)
        logits = rng.randn(*ls).astype(_np.float32)
        labels = rng.randint(0, ls[-1], size=labs).astype(_np.int32)
        return ((logits, labels), {})

    _kreg.register_kernel(
        "SparseSoftmaxCrossEntropyWithLogits",
        impls={"pallas": _sparse_xent_pallas, "xla": _sparse_softmax_xent},
        legacy="xla",
        eligible=_sparse_xent_eligible,
        cost_gate=_gate,
        make_case=_case,
        graph_key=lambda op: _sparse_xent_graph_key(op),
        doc="composed log_softmax+gather vs the Pallas streamed "
            "online-softmax xent kernel")


def _sparse_xent_graph_key(op):
    from . import pallas as _pallas

    return _pallas._simple_graph_key(op)


_register_sparse_xent_kernel()
op_registry.register_pure(
    "SigmoidCrossEntropyWithLogits",
    lambda logits, labels: (jnp.maximum(logits, 0) - logits * labels +
                            jnp.log1p(jnp.exp(-jnp.abs(logits)))))


def _conv2d_impl(x, w, strides=(1, 1, 1, 1), padding="SAME",
                 data_format="NHWC", dilations=(1, 1, 1, 1)):
    if data_format == "NCHW":
        x = jnp.transpose(x, (0, 2, 3, 1))
    sh, sw = strides[1:3] if data_format == "NHWC" else strides[2:4]
    dh, dw = dilations[1:3] if data_format == "NHWC" else dilations[2:4]
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=(sh, sw), padding=padding,
        rhs_dilation=(dh, dw),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    # NOTE: no preferred_element_type here — the MXU accumulates bf16 convs
    # in f32 natively, and an explicit f32 output breaks the vjp transpose
    # (f32 cotangent vs bf16 weights in lax.conv_general_dilated).
    if data_format == "NCHW":
        out = jnp.transpose(out, (0, 3, 1, 2))
    return out


op_registry.register_pure("Conv2D", _conv2d_impl)


def _depthwise_conv2d_impl(x, w, strides=(1, 1, 1, 1), padding="SAME",
                           data_format="NHWC", dilations=(1, 1, 1, 1)):
    if data_format == "NCHW":
        x = jnp.transpose(x, (0, 2, 3, 1))
    c = x.shape[-1]
    kh, kw, cin, mult = w.shape
    w2 = jnp.reshape(jnp.transpose(w, (0, 1, 2, 3)), (kh, kw, 1, cin * mult))
    out = jax.lax.conv_general_dilated(
        x, w2, window_strides=tuple(strides[1:3]), padding=padding,
        rhs_dilation=tuple(dilations[1:3]),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=c)
    if data_format == "NCHW":
        out = jnp.transpose(out, (0, 3, 1, 2))
    return out


op_registry.register_pure("DepthwiseConv2dNative", _depthwise_conv2d_impl)


def _conv3d_impl(x, w, strides=(1, 1, 1, 1, 1), padding="SAME"):
    return jax.lax.conv_general_dilated(
        x, w, window_strides=tuple(strides[1:4]), padding=padding,
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))


op_registry.register_pure("Conv3D", _conv3d_impl)


def _conv_transpose_impl(x, w, output_shape, spatial_strides, padding,
                         dim_nums):
    """Transposed conv. Without output_shape: lax.conv_transpose (SAME
    stride-s output = in*s). WITH output_shape, sizes like in*s-1 are
    ambiguous inverses and the pad split differs by parity — so compute
    it definitionally as the vjp of the FORWARD conv over an
    output_shape-sized input (XLA folds the vjp into one conv). TF
    transpose filter layout (…,OUT,IN) read as the fwd conv's I=OUT,
    O=IN filter."""
    if output_shape is None:
        out = jax.lax.conv_transpose(
            x, w, strides=spatial_strides, padding=padding,
            dimension_numbers=dim_nums, transpose_kernel=True)
        return out.astype(x.dtype)
    output_shape = builtins.tuple(int(d) for d in output_shape)

    def fwd(y):
        return jax.lax.conv_general_dilated(
            y, w, window_strides=spatial_strides, padding=padding,
            dimension_numbers=dim_nums)

    primal = jnp.zeros(output_shape, x.dtype)
    out_aval = jax.eval_shape(fwd, primal)
    if out_aval.shape != x.shape:
        raise ValueError(
            f"conv transpose: output_shape {output_shape} is inconsistent "
            f"— the forward conv would produce {out_aval.shape}, but the "
            f"input has shape {x.shape}")
    _, vjp = jax.vjp(fwd, primal)
    (dx,) = vjp(x)
    return dx.astype(x.dtype)


def _conv2d_transpose_impl(x, w, output_shape=None, strides=(1, 1, 1, 1),
                           padding="SAME"):
    return _conv_transpose_impl(
        x, w, output_shape, builtins.tuple(strides[1:3]), padding,
        ("NHWC", "HWIO", "NHWC"))


op_registry.register_pure("Conv2DBackpropInput", _conv2d_transpose_impl)


def _conv3d_transpose_impl(x, w, output_shape=None,
                           strides=(1, 1, 1, 1, 1), padding="SAME"):
    return _conv_transpose_impl(
        x, w, output_shape, builtins.tuple(strides[1:4]), padding,
        ("NDHWC", "DHWIO", "NDHWC"))


op_registry.register_pure("Conv3DBackpropInput", _conv3d_transpose_impl)


def _dilation2d_impl(x, f, strides=(1, 1, 1, 1), rates=(1, 1, 1, 1),
                     padding="SAME"):
    """Grayscale morphological dilation (ref core/kernels/dilation_ops.cc):
    out[b,y,x,c] = max_{i,j}( in[b, y*s+i*r, x*s+j*r, c] + f[i,j,c] ).

    The additive filter makes this not a plain reduce_window; for the
    small morphology kernels it lowers to kh*kw shifted adds + a max
    tree — all static slices, VPU-friendly."""
    kh, kw, _ = f.shape
    sh, sw = builtins.tuple(strides[1:3])
    rh, rw = builtins.tuple(rates[1:3])
    eh, ew = (kh - 1) * rh + 1, (kw - 1) * rw + 1
    n, h, w_dim, c = x.shape
    if padding == "SAME":
        out_h = -(-h // sh)
        out_w = -(-w_dim // sw)
        pad_h = builtins.max((out_h - 1) * sh + eh - h, 0)
        pad_w = builtins.max((out_w - 1) * sw + ew - w_dim, 0)
        pt, pl = pad_h // 2, pad_w // 2
        pb, pr = pad_h - pt, pad_w - pl
    else:
        out_h = (h - eh) // sh + 1
        out_w = (w_dim - ew) // sw + 1
        pt = pl = pb = pr = 0
    # Padded taps are EXCLUDED via a validity mask, not an additive
    # sentinel: adding f to a signed iinfo.min wraps around and a uint
    # "min" of 0 is not neutral — both would corrupt border outputs.
    sentinel = (-jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
                else jnp.iinfo(x.dtype).min)
    xp = jnp.pad(x, ((0, 0), (pt, pb), (pl, pr), (0, 0)))
    valid = jnp.pad(jnp.ones(x.shape, bool),
                    ((0, 0), (pt, pb), (pl, pr), (0, 0)))
    res = None
    for i in builtins.range(kh):
        for j in builtins.range(kw):
            limits = (n, i * rh + (out_h - 1) * sh + 1,
                      j * rw + (out_w - 1) * sw + 1, c)
            sl = jax.lax.slice(xp, (0, i * rh, j * rw, 0), limits,
                               (1, sh, sw, 1))
            vl = jax.lax.slice(valid, (0, i * rh, j * rw, 0), limits,
                               (1, sh, sw, 1))
            cand = jnp.where(vl, sl + f[i, j, :], sentinel)
            res = cand if res is None else jnp.maximum(res, cand)
    return res


def _erosion2d_impl(x, f, strides=(1, 1, 1, 1), rates=(1, 1, 1, 1),
                    padding="SAME"):
    """erosion2d(v, k) == -dilation2d(-v, flip(k)) (the reference's
    documented duality, ref python/ops/nn_ops.py erosion2d). The duality
    needs a signed domain: unsigned inputs compute in f32 (exact for
    values < 2^24) and cast back."""
    orig = x.dtype
    if jnp.issubdtype(orig, jnp.unsignedinteger):
        x = x.astype(jnp.float32)
        f = f.astype(jnp.float32)
    out = -_dilation2d_impl(-x, jnp.flip(f, axis=(0, 1)),
                            strides=strides, rates=rates, padding=padding)
    return out.astype(orig)


op_registry.register_pure("Dilation2D", _dilation2d_impl)
op_registry.register_pure("Erosion2D", _erosion2d_impl)


def _pool(x, ksize, strides, padding, reducer, init, data_format="NHWC"):
    if data_format == "NCHW":
        x = jnp.transpose(x, (0, 2, 3, 1))
        ksize = (ksize[0], ksize[2], ksize[3], ksize[1])
        strides = (strides[0], strides[2], strides[3], strides[1])
    out = jax.lax.reduce_window(x, init, reducer, tuple(ksize),
                                tuple(strides), padding)
    if data_format == "NCHW":
        out = jnp.transpose(out, (0, 3, 1, 2))
    return out


def _max_pool_impl(x, ksize=None, strides=None, padding="VALID",
                   data_format="NHWC"):
    return _pool(x, ksize, strides, padding, jax.lax.max,
                 -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
                 else jnp.iinfo(x.dtype).min, data_format)


def _avg_pool_impl(x, ksize=None, strides=None, padding="VALID",
                   data_format="NHWC"):
    summed = _pool(x.astype(jnp.float32), ksize, strides, padding,
                   jax.lax.add, 0.0, data_format)
    ones = jnp.ones_like(x, dtype=jnp.float32)
    counts = _pool(ones, ksize, strides, padding, jax.lax.add, 0.0, data_format)
    return (summed / counts).astype(x.dtype)


op_registry.register_pure("MaxPool", _max_pool_impl)
op_registry.register_pure("AvgPool", _avg_pool_impl)
op_registry.register_pure("MaxPool3D", lambda x, ksize=None, strides=None,
                          padding="VALID": jax.lax.reduce_window(
                              x, -jnp.inf, jax.lax.max, tuple(ksize),
                              tuple(strides), padding))
op_registry.register_pure("AvgPool3D", lambda x, ksize=None, strides=None,
                          padding="VALID": jax.lax.reduce_window(
                              x.astype(jnp.float32), 0.0, jax.lax.add,
                              tuple(ksize), tuple(strides), padding) /
                          jax.lax.reduce_window(
                              jnp.ones_like(x, dtype=jnp.float32), 0.0,
                              jax.lax.add, tuple(ksize), tuple(strides),
                              padding))


def _lrn_impl(x, depth_radius=5, bias=1.0, alpha=1.0, beta=0.5):
    squares = jnp.square(x.astype(jnp.float32))
    c = x.shape[-1]
    pad = jnp.pad(squares, [(0, 0)] * (x.ndim - 1) + [(depth_radius, depth_radius)])
    windows = [pad[..., i:i + c] for i in builtins.range(2 * depth_radius + 1)]
    norm = bias + alpha * builtins.sum(windows[1:], windows[0])
    return (x.astype(jnp.float32) / jnp.power(norm, beta)).astype(x.dtype)


op_registry.register_pure("LRN", _lrn_impl)


def _dropout_lower(ctx, op, inputs):
    x = inputs[0]
    keep_prob = op.attrs["keep_prob"]
    if keep_prob is None:  # tensor keep_prob (train/eval via placeholder)
        keep_prob = inputs[1]
    key = ctx.rng_for(op)
    noise_shape = op.attrs.get("noise_shape") or x.shape
    u = jax.random.uniform(key, builtins.tuple(noise_shape), dtype=jnp.float32)
    mask = u < keep_prob  # broadcast against x (noise_shape semantics)
    kp = jnp.asarray(keep_prob, x.dtype)
    return [jnp.where(mask, x / kp, jnp.zeros_like(x))]


op_registry.register("Dropout", lower=_dropout_lower,
                     effects=op_registry.Effects(rng=True))

op_registry.register_pure("InTopK", lambda predictions, targets, k=1:
                          _in_top_k_impl(predictions, targets, k))


def _in_top_k_impl(predictions, targets, k):
    target_scores = jnp.take_along_axis(
        predictions, targets[:, None].astype(jnp.int32), axis=1)[:, 0]
    higher = jnp.sum((predictions > target_scores[:, None]).astype(jnp.int32),
                     axis=1)
    finite = jnp.isfinite(target_scores)
    return jnp.logical_and(higher < k, finite)


op_registry.register_pure("TopKV2", lambda x, k=1, sorted=True:
                          list(jax.lax.top_k(x, k)), n_outputs=2)


# -- public API --------------------------------------------------------------

def relu(features, name=None):
    return unary("Relu", features, name)


def relu6(features, name=None):
    return unary("Relu6", features, name)


def elu(features, name=None):
    return unary("Elu", features, name)


def selu(features, name=None):
    return unary("Selu", features, name)


def gelu(features, approximate=True, name=None):
    return unary("Gelu", features, name, attrs={"approximate": approximate})


def crelu(features, axis=-1, name=None):
    """(ref: nn_ops.py ``crelu``): concat(relu(x), relu(-x))."""
    from . import array_ops
    from . import math_ops

    x = ops_mod.convert_to_tensor(features)
    with ops_mod.name_scope(name or "CRelu"):
        return array_ops.concat([relu(x), relu(math_ops.negative(x))],
                                axis=axis)


def leaky_relu(features, alpha=0.2, name=None):
    return unary("LeakyRelu", features, name, attrs={"alpha": alpha})


def swish(features, name=None):
    return unary("Swish", features, name)


silu = swish


def softplus(features, name=None):
    return unary("Softplus", features, name)


def softsign(features, name=None):
    return unary("Softsign", features, name)


def softmax(logits, axis=-1, name=None, dim=None):
    if dim is not None:
        axis = dim
    return unary("Softmax", logits, name, attrs={"axis": int(axis)})


def log_softmax(logits, axis=-1, name=None, dim=None):
    if dim is not None:
        axis = dim
    return unary("LogSoftmax", logits, name, attrs={"axis": int(axis)})


def l2_loss(t, name=None):
    return unary("L2Loss", t, name)


def bias_add(value, bias, data_format="NHWC", name=None):
    value = ops_mod.convert_to_tensor(value)
    bias = ops_mod.convert_to_tensor(bias, dtype=value.dtype.base_dtype)
    return make_op("BiasAdd", [value, bias],
                   attrs={"data_format": data_format or "NHWC"}, name=name)


def softmax_cross_entropy_with_logits(labels=None, logits=None, dim=-1,
                                      name=None, _sentinel=None):
    if _sentinel is not None:
        raise ValueError("Use named arguments for "
                         "softmax_cross_entropy_with_logits")
    logits = ops_mod.convert_to_tensor(logits)
    labels = ops_mod.convert_to_tensor(labels, dtype=logits.dtype.base_dtype)
    return make_op("SoftmaxCrossEntropyWithLogits", [logits, labels], name=name)


softmax_cross_entropy_with_logits_v2 = softmax_cross_entropy_with_logits


def sparse_softmax_cross_entropy_with_logits(labels=None, logits=None,
                                             name=None, _sentinel=None):
    logits = ops_mod.convert_to_tensor(logits)
    labels = ops_mod.convert_to_tensor(labels)
    if not labels.dtype.is_integer:
        raise TypeError("labels must be integer class ids")
    return make_op("SparseSoftmaxCrossEntropyWithLogits", [logits, labels],
                   name=name)


def sigmoid_cross_entropy_with_logits(labels=None, logits=None, name=None,
                                      _sentinel=None):
    logits = ops_mod.convert_to_tensor(logits)
    labels = ops_mod.convert_to_tensor(labels, dtype=logits.dtype.base_dtype)
    return make_op("SigmoidCrossEntropyWithLogits", [logits, labels], name=name)


def weighted_cross_entropy_with_logits(targets, logits, pos_weight, name=None):
    from . import math_ops

    logits = ops_mod.convert_to_tensor(logits)
    targets = ops_mod.convert_to_tensor(targets, dtype=logits.dtype.base_dtype)
    log_weight = 1 + (pos_weight - 1) * targets
    return math_ops.add(
        (1 - targets) * logits,
        log_weight * (math_ops.log1p(math_ops.exp(-math_ops.abs(logits))) +
                      relu(-logits)), name=name)


def conv2d(input, filter=None, strides=None, padding=None, use_cudnn_on_gpu=True,  # noqa: A002
           data_format="NHWC", dilations=None, name=None, filters=None):
    """2-D convolution (ref: nn_ops.py ``conv2d``; CUDA path
    core/kernels/conv_ops.cc) → lax.conv_general_dilated on the MXU."""
    w = filters if filters is not None else filter
    x = ops_mod.convert_to_tensor(input)
    w = ops_mod.convert_to_tensor(w, dtype=x.dtype.base_dtype)
    strides = strides or [1, 1, 1, 1]
    if isinstance(strides, int):
        strides = [1, strides, strides, 1]
    dilations = dilations or [1, 1, 1, 1]
    if isinstance(dilations, int):
        dilations = [1, dilations, dilations, 1]
    return make_op("Conv2D", [x, w],
                   attrs={"strides": builtins.tuple(strides),
                          "padding": padding or "SAME",
                          "data_format": data_format or "NHWC",
                          "dilations": builtins.tuple(dilations)},
                   name=name)


def depthwise_conv2d(input, filter, strides, padding, rate=None, name=None,  # noqa: A002
                     data_format="NHWC"):
    x = ops_mod.convert_to_tensor(input)
    w = ops_mod.convert_to_tensor(filter, dtype=x.dtype.base_dtype)
    dil = [1, 1, 1, 1]
    if rate is not None:
        r = rate if isinstance(rate, (list, tuple)) else [rate, rate]
        dil = [1, r[0], r[1], 1]
    return make_op("DepthwiseConv2dNative", [x, w],
                   attrs={"strides": builtins.tuple(strides),
                          "padding": padding,
                          "data_format": data_format or "NHWC",
                          "dilations": builtins.tuple(dil)},
                   name=name)


depthwise_conv2d_native = depthwise_conv2d


def separable_conv2d(input, depthwise_filter, pointwise_filter, strides,  # noqa: A002
                     padding, rate=None, name=None, data_format="NHWC"):
    dw = depthwise_conv2d(input, depthwise_filter, strides, padding, rate,
                          data_format=data_format)
    return conv2d(dw, pointwise_filter, [1, 1, 1, 1], "VALID",
                  data_format=data_format, name=name)


def conv3d(input, filter=None, strides=None, padding=None, name=None,  # noqa: A002
           filters=None):
    w = filters if filters is not None else filter
    x = ops_mod.convert_to_tensor(input)
    w = ops_mod.convert_to_tensor(w, dtype=x.dtype.base_dtype)
    return make_op("Conv3D", [x, w],
                   attrs={"strides": builtins.tuple(strides),
                          "padding": padding}, name=name)


def _static_output_shape(output_shape):
    if output_shape is None:
        return None
    if isinstance(output_shape, ops_mod.Tensor):
        from ..framework.constant_op import constant_value

        val = constant_value(output_shape)
        if val is None:
            raise NotImplementedError(
                "conv transpose needs a STATIC output_shape (XLA shapes "
                "are compile-time); pass a list/tuple or a constant")
        output_shape = val
    return builtins.tuple(int(d) for d in np.asarray(output_shape).ravel())


def conv2d_transpose(value, filter=None, output_shape=None, strides=None,  # noqa: A002
                     padding="SAME", data_format="NHWC", name=None,
                     filters=None):
    w = filters if filters is not None else filter
    x = ops_mod.convert_to_tensor(value)
    w = ops_mod.convert_to_tensor(w, dtype=x.dtype.base_dtype)
    return make_op("Conv2DBackpropInput", [x, w],
                   attrs={"strides": builtins.tuple(strides),
                          "padding": padding,
                          "output_shape": _static_output_shape(output_shape)},
                   name=name)


def atrous_conv2d(value, filters, rate, padding, name=None):
    return conv2d(value, filters, [1, 1, 1, 1], padding,
                  dilations=[1, rate, rate, 1], name=name)


def conv3d_transpose(value, filter=None, output_shape=None,  # noqa: A002
                     strides=None, padding="SAME", name=None, filters=None):
    w = filters if filters is not None else filter
    x = ops_mod.convert_to_tensor(value)
    w = ops_mod.convert_to_tensor(w, dtype=x.dtype.base_dtype)
    return make_op("Conv3DBackpropInput", [x, w],
                   attrs={"strides": builtins.tuple(strides),
                          "padding": padding,
                          "output_shape": _static_output_shape(output_shape)},
                   name=name)


def dilation2d(input, filter=None, strides=None, rates=None,  # noqa: A002
               padding="SAME", name=None, filters=None):
    """(ref: python/ops/nn_ops.py ``dilation2d``)."""
    f = filters if filters is not None else filter
    x = ops_mod.convert_to_tensor(input)
    f = ops_mod.convert_to_tensor(f, dtype=x.dtype.base_dtype)
    return make_op("Dilation2D", [x, f],
                   attrs={"strides": builtins.tuple(strides or (1, 1, 1, 1)),
                          "rates": builtins.tuple(rates or (1, 1, 1, 1)),
                          "padding": padding}, name=name)


def erosion2d(value, kernel=None, strides=None, rates=None, padding="SAME",
              name=None, filters=None):
    """(ref: python/ops/nn_ops.py ``erosion2d``)."""
    f = filters if filters is not None else kernel
    x = ops_mod.convert_to_tensor(value)
    f = ops_mod.convert_to_tensor(f, dtype=x.dtype.base_dtype)
    return make_op("Erosion2D", [x, f],
                   attrs={"strides": builtins.tuple(strides or (1, 1, 1, 1)),
                          "rates": builtins.tuple(rates or (1, 1, 1, 1)),
                          "padding": padding}, name=name)


def max_pool(value, ksize, strides, padding, data_format="NHWC", name=None):
    x = ops_mod.convert_to_tensor(value)
    return make_op("MaxPool", [x],
                   attrs={"ksize": builtins.tuple(ksize),
                          "strides": builtins.tuple(strides),
                          "padding": padding,
                          "data_format": data_format or "NHWC"}, name=name)


def avg_pool(value, ksize, strides, padding, data_format="NHWC", name=None):
    x = ops_mod.convert_to_tensor(value)
    return make_op("AvgPool", [x],
                   attrs={"ksize": builtins.tuple(ksize),
                          "strides": builtins.tuple(strides),
                          "padding": padding,
                          "data_format": data_format or "NHWC"}, name=name)


def max_pool3d(input, ksize, strides, padding, name=None):  # noqa: A002
    x = ops_mod.convert_to_tensor(input)
    return make_op("MaxPool3D", [x],
                   attrs={"ksize": builtins.tuple(ksize),
                          "strides": builtins.tuple(strides),
                          "padding": padding}, name=name)


def avg_pool3d(input, ksize, strides, padding, name=None):  # noqa: A002
    x = ops_mod.convert_to_tensor(input)
    return make_op("AvgPool3D", [x],
                   attrs={"ksize": builtins.tuple(ksize),
                          "strides": builtins.tuple(strides),
                          "padding": padding}, name=name)


def dropout(x, keep_prob=None, noise_shape=None, seed=None, name=None,
            rate=None):
    """(ref: nn_ops.py ``dropout``). Mask drawn from the per-step functional
    RNG; identical mask is replayed in the vjp backward."""
    x = ops_mod.convert_to_tensor(x)
    if rate is not None:
        keep_prob = 1.0 - rate if not isinstance(rate, Tensor) else 1.0 - rate
    if keep_prob is None:
        raise ValueError("dropout: pass keep_prob or rate")
    g = ops_mod.get_default_graph()
    graph_seed, op_seed = random_seed_mod.get_seed(seed)
    ns = None
    if noise_shape is not None:
        from ..framework import constant_op as _const

        if isinstance(noise_shape, Tensor):
            v = _const.constant_value(noise_shape)
            if v is None:
                raise ValueError("noise_shape must be static on TPU")
            noise_shape = v
        ns = builtins.tuple(int(d) for d in np.ravel(np.asarray(noise_shape)))
    inputs = [x]
    if isinstance(keep_prob, Tensor):
        # Placeholder keep_prob (train/eval idiom): passed as a tensor input.
        inputs.append(math_ops_cast_float(keep_prob))
        kp_attr = None
    else:
        kp_attr = float(keep_prob)
        if kp_attr == 1.0:
            return x
    op = g.create_op("Dropout", inputs,
                     attrs={"keep_prob": kp_attr, "noise_shape": ns,
                            "seed": op_seed, "_graph_seed": graph_seed},
                     name=name or "dropout",
                     output_specs=[(x.shape, x.dtype)])
    return op.outputs[0]


def math_ops_cast_float(t):
    from . import math_ops

    return math_ops.cast(t, "float32")


def local_response_normalization(input, depth_radius=5, bias=1.0, alpha=1.0,  # noqa: A002
                                 beta=0.5, name=None):
    x = ops_mod.convert_to_tensor(input)
    return make_op("LRN", [x], attrs={"depth_radius": int(depth_radius),
                                      "bias": float(bias),
                                      "alpha": float(alpha),
                                      "beta": float(beta)}, name=name)


lrn = local_response_normalization


def in_top_k(predictions, targets, k, name=None):
    p = ops_mod.convert_to_tensor(predictions)
    t = ops_mod.convert_to_tensor(targets)
    return make_op("InTopK", [p, t], attrs={"k": int(k)}, name=name)


def top_k(input, k=1, sorted=True, name=None):  # noqa: A002
    x = ops_mod.convert_to_tensor(input)
    values, indices = make_op("TopKV2", [x], attrs={"k": int(k),
                                                    "sorted": sorted},
                              name=name, n_out=2)
    return values, indices


def xw_plus_b(x, weights, biases, name=None):
    from . import math_ops

    return bias_add(math_ops.matmul(x, weights), biases, name=name)


def log_poisson_loss(targets, log_input, compute_full_loss=False, name=None):
    from . import math_ops

    loss = math_ops.exp(log_input) - log_input * targets
    return loss


# -- round-4 parity fills ----------------------------------------------------

def conv1d(value, filters, stride, padding, use_cudnn_on_gpu=None,
           data_format="NHWC", name=None):
    """(ref: nn_ops.py ``conv1d``): [B, W, C] conv via a height-1 conv2d
    (exactly the reference's implementation strategy)."""
    from . import array_ops

    x = ops_mod.convert_to_tensor(value)
    w = ops_mod.convert_to_tensor(filters, dtype=x.dtype.base_dtype)
    x4 = array_ops.expand_dims(x, 1)            # [B, 1, W, C]
    w4 = array_ops.expand_dims(w, 0)            # [1, K, C, O]
    s = stride if isinstance(stride, int) else stride[1]
    out = conv2d(x4, w4, [1, 1, s, 1], padding, name=name)
    return array_ops.squeeze(out, axis=[1])


def convolution(input, filter, padding, strides=None,  # noqa: A002
                dilation_rate=None, name=None, data_format=None):
    """(ref: nn_ops.py ``convolution``): rank-dispatching wrapper."""
    x = ops_mod.convert_to_tensor(input)
    rank = x.shape.rank
    if rank == 3:
        return conv1d(x, filter, (strides or [1])[0] if strides else 1,
                      padding, name=name)
    if rank == 4:
        s = [1] + list(strides or [1, 1]) + [1]
        d = [1] + list(dilation_rate or [1, 1]) + [1]
        return conv2d(x, filter, s, padding, dilations=d, name=name)
    if rank == 5:
        s = [1] + list(strides or [1, 1, 1]) + [1]
        return conv3d(x, filter, s, padding, name=name)
    raise ValueError(f"convolution: unsupported input rank {rank}")


def atrous_conv2d_transpose(value, filters, output_shape, rate, padding,
                            name=None):
    """(ref: nn_ops.py ``atrous_conv2d_transpose``): the transpose of the
    dilated conv — lax supports rhs_dilation in the backprop, so this is
    conv2d_transpose with a dilated kernel."""
    from . import array_ops

    w = ops_mod.convert_to_tensor(filters)
    if rate > 1:
        # dilate the kernel spatially (zeros between taps)
        kh, kw = int(w.shape[0].value), int(w.shape[1].value)
        eff_h = kh + (kh - 1) * (rate - 1)
        eff_w = kw + (kw - 1) * (rate - 1)
        import numpy as _np

        from ..framework import constant_op

        idx_h = _np.arange(kh) * rate
        idx_w = _np.arange(kw) * rate
        scat = array_ops.scatter_nd(
            constant_op.constant(
                _np.stack(_np.meshgrid(idx_h, idx_w, indexing="ij"),
                          axis=-1).reshape(-1, 2).astype(_np.int32)),
            array_ops.reshape(w, [kh * kw, int(w.shape[2].value),
                                  int(w.shape[3].value)]),
            [eff_h, eff_w, int(w.shape[2].value),
             int(w.shape[3].value)])
        w = scat
    return conv2d_transpose(value, w, output_shape, [1, 1, 1, 1],
                            padding, name=name)


def conv2d_backprop_input(input_sizes, filter, out_backprop, strides,  # noqa: A002
                          padding, use_cudnn_on_gpu=None,
                          data_format="NHWC", name=None):
    """(ref: nn_ops.py ``conv2d_backprop_input``) — the raw gradient op,
    same lowering as conv2d_transpose."""
    return conv2d_transpose(out_backprop, filter,
                            output_shape=input_sizes, strides=strides,
                            padding=padding, name=name)


def conv2d_backprop_filter(input, filter_sizes, out_backprop, strides,  # noqa: A002
                           padding, use_cudnn_on_gpu=None,
                           data_format="NHWC", name=None):
    """(ref: nn_ops.py ``conv2d_backprop_filter``): derived through the
    SAME autodiff that training uses — d(conv)/d(filter) via stf.gradients
    on a throwaway conv with a zero filter of the right shape."""
    from ..framework import gradients as grads_mod
    from ..framework.constant_op import constant_value
    from . import array_ops

    fs = constant_value(ops_mod.convert_to_tensor(filter_sizes))
    if fs is None:
        raise ValueError("conv2d_backprop_filter needs static filter_sizes")
    x = ops_mod.convert_to_tensor(input)
    w0 = array_ops.zeros([int(d) for d in np.ravel(fs)],
                         dtype=x.dtype.base_dtype)
    y = conv2d(x, w0, strides, padding)
    (gw,) = grads_mod.gradients(y, [w0],
                                grad_ys=[ops_mod.convert_to_tensor(
                                    out_backprop)])
    return gw


def _max_pool_argmax_impl(x, ksize=None, strides=None, padding="VALID"):
    """Correct per-window argmax: iterate the (small, static) window
    offsets, tracking best value + FLAT input index (ref flattening
    (y*W + x)*C + c). Handles overlapping windows and SAME padding."""
    b, h, w, c = x.shape
    kh, kw = ksize[1], ksize[2]
    sy, sx = strides[1], strides[2]
    if padding.upper() == "SAME":
        oh = -(-h // sy)
        ow = -(-w // sx)
        pad_h = builtins.max((oh - 1) * sy + kh - h, 0)
        pad_w = builtins.max((ow - 1) * sx + kw - w, 0)
    else:
        oh = (h - kh) // sy + 1
        ow = (w - kw) // sx + 1
        pad_h = pad_w = 0
    neg = (jnp.asarray(-jnp.inf, x.dtype)
           if jnp.issubdtype(x.dtype, jnp.floating)
           else jnp.iinfo(x.dtype).min)
    xp = jnp.pad(x, ((0, 0), (0, pad_h), (0, pad_w), (0, 0)),
                 constant_values=neg)
    flat = ((jnp.arange(h)[:, None, None] * w
             + jnp.arange(w)[None, :, None]) * c
            + jnp.arange(c)[None, None, :]).astype(
                dtypes_mod.narrowed_if_no_x64(dtypes_mod.int64).np_dtype)
    flat = jnp.pad(flat, ((0, pad_h), (0, pad_w), (0, 0)),
                   constant_values=-1)
    best = jnp.full((b, oh, ow, c), neg, x.dtype)
    best_idx = jnp.zeros(
        (b, oh, ow, c),
        dtypes_mod.narrowed_if_no_x64(dtypes_mod.int64).np_dtype)
    ys = jnp.arange(oh) * sy
    xs = jnp.arange(ow) * sx
    for dy in builtins.range(kh):
        for dx in builtins.range(kw):
            v = xp[:, ys + dy][:, :, xs + dx]
            fi = flat[ys + dy][:, xs + dx][None]
            take = v > best
            best = jnp.where(take, v, best)
            best_idx = jnp.where(take, fi, best_idx)
    return [best, best_idx]


op_registry.register_pure("MaxPoolWithArgmax", _max_pool_argmax_impl,
                          n_outputs=2)


def max_pool_with_argmax(input, ksize, strides, padding,  # noqa: A002
                         Targmax=None, name=None):
    """(ref: nn_ops.py ``max_pool_with_argmax``): pooled values plus the
    FLATTENED per-batch index of each max ((y*W + x)*C + c). Correct for
    overlapping windows (the argmax is tracked per window offset)."""
    from ..framework import tensor_shape as shape_mod

    x = ops_mod.convert_to_tensor(input)
    g = ops_mod.get_default_graph()
    b, h, w, c = (d.value for d in x.shape)
    kh, kw = ksize[1], ksize[2]
    sy, sx = strides[1], strides[2]
    if padding.upper() == "SAME":
        oh, ow = -(-h // sy), -(-w // sx)
    else:
        oh, ow = (h - kh) // sy + 1, (w - kw) // sx + 1
    out_shape = shape_mod.TensorShape([b, oh, ow, c])
    op = g.create_op("MaxPoolWithArgmax", [x],
                     attrs={"ksize": builtins.tuple(ksize),
                            "strides": builtins.tuple(strides),
                            "padding": padding},
                     name=name or "MaxPoolWithArgmax",
                     output_specs=[(out_shape, x.dtype),
                                   (out_shape, dtypes_mod.int64)])
    return op.outputs[0], op.outputs[1]


def _pool_v2_impl(x, window_shape=None, pooling_type="MAX",
                  padding="VALID", dilation_rate=None, strides=None):
    dil = builtins.tuple(dilation_rate or [1] * builtins.len(window_shape))
    st = builtins.tuple(strides or [1] * builtins.len(window_shape))
    wd = (1,) + builtins.tuple(window_shape) + (1,)
    ws = (1,) + st + (1,)
    wdil = (1,) + dil + (1,)
    if pooling_type.upper() == "MAX":
        init = (-jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
                else jnp.iinfo(x.dtype).min)
        return jax.lax.reduce_window(x, init, jax.lax.max, wd, ws,
                                     padding.upper(),
                                     window_dilation=wdil)
    s = jax.lax.reduce_window(x.astype(jnp.float32), 0.0, jax.lax.add,
                              wd, ws, padding.upper(),
                              window_dilation=wdil)
    ones = jnp.ones(x.shape, jnp.float32)
    n = jax.lax.reduce_window(ones, 0.0, jax.lax.add, wd, ws,
                              padding.upper(), window_dilation=wdil)
    return (s / n).astype(x.dtype)


op_registry.register_pure("PoolV2", _pool_v2_impl)


def pool(input, window_shape, pooling_type, padding, dilation_rate=None,  # noqa: A002
         strides=None, name=None, data_format=None):
    """(ref: nn_ops.py ``pool``): generic window pooling WITH dilation —
    lax.reduce_window supports window_dilation natively on TPU."""
    if pooling_type.upper() not in ("MAX", "AVG"):
        raise ValueError(f"pool: unknown pooling_type {pooling_type!r}")
    x = ops_mod.convert_to_tensor(input)
    return make_op("PoolV2", [x],
                   attrs={"window_shape": builtins.tuple(window_shape),
                          "pooling_type": pooling_type.upper(),
                          "padding": padding,
                          "dilation_rate": builtins.tuple(dilation_rate)
                          if dilation_rate else None,
                          "strides": builtins.tuple(strides)
                          if strides else None},
                   name=name)


def with_space_to_batch(input, dilation_rate, padding, op, filter_shape=None,  # noqa: A002
                        spatial_dims=None, data_format=None):
    """(ref: nn_ops.py ``with_space_to_batch``): on TPU, dilated convs are
    native (lax rhs_dilation fuses on the MXU), so the space-to-batch
    dance is unnecessary — this wrapper simply invokes ``op`` with the
    dilation folded in when it is 1, and otherwise applies the reference's
    space-to-batch -> op -> batch-to-space composition."""
    from ..framework.constant_op import constant_value
    from . import array_ops

    rate = np.asarray(constant_value(
        ops_mod.convert_to_tensor(dilation_rate)))
    if (rate == 1).all():
        return op(input, num_spatial_dims=len(rate), padding=padding)
    x = ops_mod.convert_to_tensor(input)
    # pad spatial dims up to multiples of the rate (ref computes this via
    # required_space_to_batch_paddings)
    pads = []
    for d, r in enumerate(rate.ravel()):
        dim = int(x.shape[d + 1].value)
        pads.append([0, (-dim) % int(r)])
    stb = array_ops.space_to_batch_nd(x, list(rate.ravel()), pads)
    y = op(stb, num_spatial_dims=len(rate), padding=padding)
    return array_ops.batch_to_space_nd(y, list(rate.ravel()), pads)


def _fractional_boundaries(n, ratio, seed, pseudo_random):
    """Row boundaries for fractional pooling (ref:
    core/kernels/fractional_pool_common.cc): ~n/ratio output rows with
    window sizes in {floor(ratio), ceil(ratio)}, seeded."""
    out_n = int(n / ratio)
    rng = np.random.RandomState(seed if seed else 0)
    if pseudo_random:
        # a_k = ceil(alpha*(k+u)) (ref pseudorandom sequence)
        u = rng.uniform(0, 1)
        bounds = [0]
        for k in builtins.range(1, out_n):
            bounds.append(builtins.min(int(np.ceil(ratio * (k + u))),
                                       n - 1))
        bounds.append(n)
        return bounds
    # random variant (ref default): shuffle a mix of floor/ceil window
    # sizes that sums to n
    small, big = int(np.floor(ratio)), int(np.ceil(ratio))
    n_big = n - small * out_n
    sizes = [big] * n_big + [small] * (out_n - n_big)
    rng.shuffle(sizes)
    bounds = [0]
    for s in sizes:
        bounds.append(bounds[-1] + s)
    bounds[-1] = n
    return bounds


def _fractional_pool(input, pooling_ratio, kind, pseudo_random,  # noqa: A002
                     overlapping, seed, name):
    from ..framework import constant_op
    from . import array_ops, math_ops

    x = ops_mod.convert_to_tensor(input)
    b, h, w, c = (int(d) for d in x.shape.as_list())
    rh, rw = float(pooling_ratio[1]), float(pooling_ratio[2])
    hb = _fractional_boundaries(h, rh, seed, pseudo_random)
    wb = _fractional_boundaries(w, rw, (seed or 0) + 1, pseudo_random)

    def pool_axis(t, bounds, axis):
        segs = []
        for i in builtins.range(builtins.len(bounds) - 1):
            lo = bounds[i]
            hi = bounds[i + 1] + (1 if overlapping
                                  and bounds[i + 1] < (h if axis == 1
                                                       else w) else 0)
            hi = builtins.max(hi, lo + 1)
            idx = constant_op.constant(
                np.arange(lo, hi, dtype=np.int32))
            sl = array_ops.gather(t, idx, axis=axis)
            red = (math_ops.reduce_max if kind == "max"
                   else math_ops.reduce_mean)
            segs.append(red(sl, axis=axis, keepdims=True))
        return array_ops.concat(segs, axis=axis)

    out = pool_axis(x, hb, 1)
    out = pool_axis(out, wb, 2)
    rs = constant_op.constant(np.asarray(hb, np.int64))
    cs = constant_op.constant(np.asarray(wb, np.int64))
    return out, rs, cs


def fractional_max_pool(value, pooling_ratio, pseudo_random=False,
                        overlapping=False, deterministic=False, seed=0,
                        seed2=0, name=None):
    """(ref: nn_ops.py ``fractional_max_pool``): returns (output,
    row_pooling_sequence, col_pooling_sequence)."""
    return _fractional_pool(value, pooling_ratio, "max", pseudo_random,
                            overlapping, seed, name)


def fractional_avg_pool(value, pooling_ratio, pseudo_random=False,
                        overlapping=False, deterministic=False, seed=0,
                        seed2=0, name=None):
    return _fractional_pool(value, pooling_ratio, "avg", pseudo_random,
                            overlapping, seed, name)


def _requant_range(x):
    from . import math_ops

    return math_ops.reduce_min(x), math_ops.reduce_max(x)


def quantized_conv2d(input, filter, min_input, max_input, min_filter,  # noqa: A002
                     max_filter, strides, padding, out_type=None,
                     name=None):
    """(ref: nn_ops quantized_conv2d, core/kernels/quantized_conv_ops.cc):
    dequantize -> MXU conv -> fresh range. On TPU the int8 fast path is
    the Pallas quantized_matmul (ops/fused_ops.py); this op preserves the
    reference's quantized-graph CONTRACT (value + min/max triple)."""
    from ..ops import quantization_ops as qo

    xf = qo.dequantize(input, min_input, max_input)
    wf = qo.dequantize(filter, min_filter, max_filter)
    y = conv2d(xf, wf, strides, padding, name=name)
    mn, mx = _requant_range(y)
    return y, mn, mx


def quantized_relu_x(features, max_value, min_features, max_features,
                     out_type=None, name=None):
    from ..ops import quantization_ops as qo
    from . import math_ops

    xf = qo.dequantize(features, min_features, max_features)
    y = math_ops.minimum(relu(xf),
                         ops_mod.convert_to_tensor(float(max_value)
                                                   if not isinstance(
                                                       max_value,
                                                       ops_mod.Tensor)
                                                   else max_value))
    mn, mx = _requant_range(y)
    return y, mn, mx


def quantized_max_pool(input, min_input, max_input, ksize, strides,  # noqa: A002
                       padding, name=None):
    from ..ops import quantization_ops as qo

    xf = qo.dequantize(input, min_input, max_input)
    y = max_pool(xf, ksize, strides, padding, name=name)
    mn, mx = _requant_range(y)
    return y, mn, mx


def quantized_avg_pool(input, min_input, max_input, ksize, strides,  # noqa: A002
                       padding, name=None):
    from ..ops import quantization_ops as qo

    xf = qo.dequantize(input, min_input, max_input)
    y = avg_pool(xf, ksize, strides, padding, name=name)
    mn, mx = _requant_range(y)
    return y, mn, mx


def _backprop_filter_via_autodiff(conv_fn, input, filter_sizes,  # noqa: A002
                                  out_backprop, strides, padding):
    from ..framework import gradients as grads_mod
    from ..framework.constant_op import constant_value
    from . import array_ops

    fs = constant_value(ops_mod.convert_to_tensor(filter_sizes))
    if fs is None:
        raise ValueError("backprop_filter needs static filter_sizes")
    x = ops_mod.convert_to_tensor(input)
    w0 = array_ops.zeros([int(d) for d in np.ravel(fs)],
                         dtype=x.dtype.base_dtype)
    y = conv_fn(x, w0, strides, padding)
    (gw,) = grads_mod.gradients(
        y, [w0], grad_ys=[ops_mod.convert_to_tensor(out_backprop)])
    return gw


def conv3d_backprop_filter_v2(input, filter_sizes, out_backprop, strides,  # noqa: A002
                              padding, data_format="NDHWC", name=None):
    """(ref: nn.py ``conv3d_backprop_filter_v2``): derived through the
    same autodiff training uses."""
    return _backprop_filter_via_autodiff(
        lambda x, w, s, p: conv3d(x, w, s, p), input, filter_sizes,
        out_backprop, strides, padding)


def depthwise_conv2d_native_backprop_filter(input, filter_sizes,  # noqa: A002
                                            out_backprop, strides, padding,
                                            data_format="NHWC", name=None):
    return _backprop_filter_via_autodiff(
        lambda x, w, s, p: depthwise_conv2d(x, w, s, p), input,
        filter_sizes, out_backprop, strides, padding)


def depthwise_conv2d_native_backprop_input(input_sizes, filter,  # noqa: A002
                                           out_backprop, strides, padding,
                                           data_format="NHWC", name=None):
    from ..framework import gradients as grads_mod
    from ..framework.constant_op import constant_value
    from . import array_ops

    xs = constant_value(ops_mod.convert_to_tensor(input_sizes))
    if xs is None:
        raise ValueError("backprop_input needs static input_sizes")
    w = ops_mod.convert_to_tensor(filter)
    x0 = array_ops.zeros([int(d) for d in np.ravel(xs)],
                         dtype=w.dtype.base_dtype)
    y = depthwise_conv2d(x0, w, strides, padding)
    (gx,) = grads_mod.gradients(
        y, [x0], grad_ys=[ops_mod.convert_to_tensor(out_backprop)])
    return gx


# ---------------------------------------------------------------------------
# sharding propagation rules (stf.analysis.sharding; ISSUE 6)
# ---------------------------------------------------------------------------

from ..analysis import sharding as _shard  # noqa: E402

_shard.register_rules(_shard.elementwise_rule,
                      "Relu", "Relu6", "Elu", "Selu", "Gelu", "LeakyRelu",
                      "Swish")
_shard.register_rules(_shard.make_softmax_rule("axis"),
                      "Softmax", "LogSoftmax")
_shard.register_rules(_shard.make_last_dim_reduce_rule(),
                      "SoftmaxCrossEntropyWithLogits",
                      "SparseSoftmaxCrossEntropyWithLogits", "InTopK")
_shard.register_rules(_shard.make_conv_rule(2),
                      "Conv2D", "DepthwiseConv2dNative", "Conv2DBackpropInput",
                      "Dilation2D", "Erosion2D")
_shard.register_rules(_shard.make_conv_rule(3), "Conv3D",
                      "Conv3DBackpropInput")
_shard.register_rules(_shard.make_pool_rule(),
                      "MaxPool", "AvgPool", "MaxPool3D", "AvgPool3D",
                      "LRN", "PoolV2", "MaxPoolWithArgmax")
_shard.register_rules(_shard.passthrough_rule, "Dropout")
_shard.register_rules(_shard.make_axis_unsharded_rule("axis", -1),
                      "TopKV2")


def _biasadd_rule(op, in_specs, ctx):
    # the bias aligns with the channel dim (last, or dim 1 under NCHW)
    sx, sb = in_specs[0], in_specs[1] if len(in_specs) > 1 else None
    if sx is None:
        return [None]
    chan = 1 if op.attrs.get("data_format") == "NCHW" else len(sx) - 1
    out = list(sx)
    if sb is not None and len(sb) == 1:
        if sb[0] and not out[chan]:
            out[chan] = sb[0]
        elif sb[0] != out[chan]:
            ctx.require(1, (out[chan],))
    return [_shard._dedupe_axes(tuple(out))]


_shard.register_rules(_biasadd_rule, "BiasAdd")
