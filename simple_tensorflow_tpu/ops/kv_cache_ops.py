"""KV-cache graph ops: device-resident paged decode caches.

(ref: the reference has no KV cache — its serving path re-runs the full
forward per emitted token, tensorflow_serving/servables/tensorflow/.
This module is the TPU-native incremental-decode substrate the
generative engine (stf.serving.generative) and the cached beam search
(models/transformer.py) run on.)

A cache is an entry in the Session's device-resident VariableStore —
the SAME store that holds model weights and optimizer slots — shaped
``(num_slots, max_len, *inner)``. Slots are PAGES: each live sequence
owns one row, a free-list (serving/generative.py CacheSlotPool) hands
rows to joining sequences and reclaims them at EOS, so a retiring
sequence never compacts or copies its neighbors' cache. Because the
store's values are donated into every step exactly like optimizer
state, an append is an in-place HBM scatter after XLA compilation and
the cache NEVER moves device→host between decode steps (the
``lint/serving-decode-cache`` rule makes a host-sink on a cache tensor
a hard error).

Three ops, registered with declared Effects so the hazard engine orders
them like any other variable access (append = read-modify-write on the
cache resource, gather = read):

  KVCacheAlloc   zero-fill the cache storage (engine start / slot-pool
                 reset); also the op that carries the cache's committed
                 sharding declaration (``_cache_sharding`` attr).
  KVCacheAppend  write ``value (B, P, *inner)`` at rows ``slots (B,)``,
                 positions ``positions[b] + [0, P)`` — P is 1 on the
                 decode path, the prompt length on the prefill path.
  KVCacheGather  read rows ``slots (B,)`` → ``(B, max_len, *inner)``;
                 feeds DecodeAttention (query length 1).

Ordering note: a gather has no data edge from the appends that must
precede it; build it under ``stf.control_dependencies([append])`` (the
:class:`KVCache` helper does) — the hazard detector (mode ``raise``)
rejects the unordered RAW otherwise.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from ..framework import op_registry
from ..framework import tensor_shape as shape_mod
from ..kernels import registry as _kreg

# collection-style registry attr markers consumed by the
# lint/serving-decode-cache rule (analysis/lint.py)
CACHE_ATTR = "_kv_cache"
SHARDING_ATTR = "_cache_sharding"
# head-dim sharding declaration suffix: ``"tp:heads"`` shards the
# cache's HEAD dim (dim 2 of (slots, len, heads, head_dim)) over mesh
# axis ``tp`` — the decode-time tensor-parallel layout. A bare axis
# name keeps the legacy meaning (slot-dim sharding); "replicated"/None
# keeps the cache whole on every device.
HEAD_SHARD_SUFFIX = ":heads"
# dim index of the head dim in the canonical cache layout
# (slots, positions, heads, head_dim)
HEAD_DIM = 2
# shared-page layer markers (PR 16): PAGED_ATTR tags ops against a
# cache whose rows are REFCOUNTED shared pages (prefix cache) — a
# host-sink on one leaks another request's prompt state off device;
# VERIFY_ATTR tags cache writes inside a speculative VERIFY plan, which
# must carry GUARD_ATTR (the engine commits only the accepted prefix —
# an unguarded verify write would publish unverified draft state)
PAGED_ATTR = "_kv_paged"
VERIFY_ATTR = "_verify_plan"
GUARD_ATTR = "_refcount_guarded"

_CACHE_OP_TYPES = ("KVCacheAlloc", "KVCacheAppend", "KVCacheGather",
                   "KVCachePageCopy")


# ---------------------------------------------------------------------------
# lowerings
# ---------------------------------------------------------------------------

def _np_dtype(op):
    return dtypes_mod.as_dtype(op.attrs["dtype"]).np_dtype


def parse_cache_sharding(decl) -> Tuple[Optional[int], Optional[str]]:
    """Split a ``_cache_sharding`` declaration into ``(dim, axis)``.

    ``None``/``"replicated"`` -> ``(None, None)``; a bare mesh-axis name
    shards the SLOT dim (legacy form) -> ``(0, axis)``; ``"axis:heads"``
    shards the HEAD dim -> ``(HEAD_DIM, axis)`` — the decode
    tensor-parallel layout (each device owns heads/tp of every slot,
    so slot/page-table gathers stay shard-local)."""
    if not decl or decl == "replicated":
        return None, None
    decl = str(decl)
    if decl.endswith(HEAD_SHARD_SUFFIX):
        return HEAD_DIM, decl[:-len(HEAD_SHARD_SUFFIX)]
    if ":" in decl:
        raise ValueError(
            f"unknown cache sharding declaration {decl!r} "
            f"(want 'replicated', '<axis>', or '<axis>{HEAD_SHARD_SUFFIX}')")
    return 0, decl


def cache_named_sharding(decl, rank, mesh=None):
    """NamedSharding for a cache declared ``decl`` under the active (or
    given) mesh, or None when the declaration stays replicated / the
    mesh lacks the axis / the dim is out of range for ``rank``."""
    from ..parallel.mesh import current_mesh

    mesh = mesh or current_mesh()
    if mesh is None:
        return None
    dim, axis = parse_cache_sharding(decl)
    if axis is None or dim is None or dim >= rank \
            or mesh.shape.get(axis, 1) <= 1:
        return None
    spec = [None] * rank
    spec[dim] = axis
    return mesh.named_sharding(*spec)


def _hint_cache_class(ctx, op):
    """Tag the cache's store entry for the HBM ledger (trace-time
    Python side effect — stf.telemetry.memory classifies the store
    name as kv_cache instead of generic state)."""
    sess = getattr(ctx, "session", None)
    if sess is not None:
        try:
            sess._variable_store.classes[op.attrs["var_name"]] = \
                "kv_cache"
        except Exception:  # noqa: BLE001 — accounting only
            pass


def _lower_kv_alloc(ctx, op, inputs):
    import jax.numpy as jnp

    _hint_cache_class(ctx, op)
    shape = tuple(int(d) for d in op.attrs["shape"])
    val = jnp.zeros(shape, _np_dtype(op))
    ns = None
    if not getattr(ctx, "host", False) \
            and not getattr(ctx, "in_shard_map", False):
        try:
            ns = cache_named_sharding(op.attrs.get(SHARDING_ATTR),
                                      len(shape))
        except ValueError:
            ns = None
    if ns is not None:
        import jax

        # commit the declared layout at birth: the zeros leave the
        # alloc step already sharded, every later step's donated cache
        # input inherits it, and registering the NamedSharding in the
        # store makes checkpoint restore (VariableStore.load) re-place
        # the restored cache at the same layout
        val = jax.lax.with_sharding_constraint(val, ns)
        sess = getattr(ctx, "session", None)
        if sess is not None:
            try:
                sess._variable_store.shardings.setdefault(
                    op.attrs["var_name"], ns)
            except Exception:  # noqa: BLE001 — placement hint only
                pass
    ctx.write_var(op.attrs["var_name"], val)
    return [val]


def _lower_kv_append(ctx, op, inputs):
    import jax.numpy as jnp

    name = op.attrs["var_name"]
    value, slots, positions = inputs
    cache = ctx.read_var(name, op)
    if value.dtype != cache.dtype:
        value = value.astype(cache.dtype)
    p = value.shape[1]
    p_idx = jnp.asarray(positions, jnp.int32)[:, None] + jnp.arange(
        p, dtype=jnp.int32)[None, :]
    new = cache.at[jnp.asarray(slots, jnp.int32)[:, None], p_idx].set(value)
    ctx.write_var(name, new)
    return [new]


def _lower_kv_gather(ctx, op, inputs):
    import jax.numpy as jnp

    cache = ctx.read_var(op.attrs["var_name"], op)
    idx = jnp.asarray(inputs[0], jnp.int32)
    if idx.ndim == 2:
        # page-table gather: slots (B, n_blocks) -> the LOGICAL cache
        # view (B, n_blocks * page_len, *inner) — block b's pages
        # concatenated in table order, so downstream DecodeAttention
        # sees one contiguous per-sequence cache exactly like the 1-D
        # slot path (lengths mask in logical coordinates)
        b, nb = idx.shape
        rows = cache[idx]              # (B, nb, page_len, *inner)
        return [rows.reshape((b, nb * cache.shape[1]) + cache.shape[2:])]
    return [cache[idx]]


def _lower_kv_page_copy(ctx, op, inputs):
    import jax.numpy as jnp

    name = op.attrs["var_name"]
    dst, src = inputs
    cache = ctx.read_var(name, op)
    rows = cache[jnp.asarray(src, jnp.int32)]
    new = cache.at[jnp.asarray(dst, jnp.int32)].set(rows)
    ctx.write_var(name, new)
    return [new]


op_registry.register(
    "KVCacheAlloc", lower=_lower_kv_alloc,
    effects=op_registry.Effects(writes=("var_name",)))
op_registry.register(
    "KVCacheAppend", lower=_lower_kv_append,
    effects=op_registry.Effects(writes=("var_name",), update="update"))
op_registry.register(
    "KVCacheGather", lower=_lower_kv_gather,
    effects=op_registry.Effects(reads=("var_name",)))
op_registry.register(
    "KVCachePageCopy", lower=_lower_kv_page_copy,
    effects=op_registry.Effects(writes=("var_name",), update="update"))


# ---------------------------------------------------------------------------
# public handle
# ---------------------------------------------------------------------------

class KVCache:
    """Handle to one paged cache in the VariableStore.

    Build-time only (holds no device state): methods emit graph ops
    against the default graph. The cache value itself lives in the
    session's store under ``name`` once the :meth:`alloc` op has run.
    """

    def __init__(self, name: str, num_slots: int, max_len: int,
                 inner_shape: Sequence[int], dtype,
                 sharding: Optional[str] = None, paged: bool = False):
        self.name = name
        self.num_slots = int(num_slots)
        self.max_len = int(max_len)
        self.inner_shape = tuple(int(d) for d in inner_shape)
        self.dtype = dtypes_mod.as_dtype(dtype)
        # committed-sharding declaration: cache state commits at this
        # layout in the store ("replicated", a mesh-axis name the slot
        # dim shards over, or "<axis>:heads" — the decode
        # tensor-parallel layout sharding the HEAD dim so each device
        # owns heads/tp of every slot); recorded on every cache op so
        # offline lint (graph_lint --serving) can check it without a
        # session
        self.sharding = sharding or "replicated"
        parse_cache_sharding(self.sharding)  # validate the declaration
        # paged=True: rows are refcounted shared pages (prefix cache) —
        # every op carries PAGED_ATTR so lint can hold the shared-page
        # layer to the stricter host-sink contract
        self.paged = bool(paged)

    @property
    def shape(self) -> Tuple[int, ...]:
        return (self.num_slots, self.max_len) + self.inner_shape

    def _attrs(self):
        a = {"var_name": self.name, "shape": list(self.shape),
             "dtype": self.dtype.name, CACHE_ATTR: True,
             SHARDING_ATTR: self.sharding}
        if self.paged:
            a[PAGED_ATTR] = True
        return a

    def alloc(self, name=None):
        """Zero-fill the cache storage (returns the cache tensor; fetch
        the op — not the tensor — to keep the cache on device)."""
        g = ops_mod.get_default_graph()
        op = g.create_op(
            "KVCacheAlloc", [], attrs=self._attrs(),
            name=name or f"{self.name}_alloc",
            output_specs=[(shape_mod.TensorShape(list(self.shape)),
                           self.dtype)])
        return op.outputs[0]

    def append(self, value, slots, positions, name=None,
               verify_plan=False, refcount_guarded=False):
        """Write ``value (B, P, *inner)`` at ``slots (B,)`` int32 rows,
        positions ``positions (B,) + [0, P)``. Returns the updated cache
        tensor (use it for control deps, never as a fetch).

        ``verify_plan=True`` marks a write inside a speculative VERIFY
        program; it must also set ``refcount_guarded=True`` (the engine
        commits only the accepted prefix) or the
        ``lint/serving-decode-cache`` rule errors."""
        g = ops_mod.get_default_graph()
        value = ops_mod.convert_to_tensor(value, dtype=self.dtype)
        slots = ops_mod.convert_to_tensor(slots, dtype=dtypes_mod.int32)
        positions = ops_mod.convert_to_tensor(positions,
                                              dtype=dtypes_mod.int32)
        attrs = self._attrs()
        if verify_plan:
            attrs[VERIFY_ATTR] = True
            attrs[GUARD_ATTR] = bool(refcount_guarded)
        op = g.create_op(
            "KVCacheAppend", [value, slots, positions], attrs=attrs,
            name=name or f"{self.name}_append",
            output_specs=[(shape_mod.TensorShape(list(self.shape)),
                           self.dtype)])
        return op.outputs[0]

    def gather(self, slots, name=None):
        """Read rows ``slots (B,)`` → ``(B, max_len, *inner)``; or a
        page-table gather ``slots (B, n_blocks)`` → the logical view
        ``(B, n_blocks * max_len, *inner)`` (pages concatenated in
        table order)."""
        g = ops_mod.get_default_graph()
        slots = ops_mod.convert_to_tensor(slots, dtype=dtypes_mod.int32)
        if slots.shape.rank == 2:
            b = slots.shape[0].value
            nb = int(slots.shape[1].value)
            out_shape = [b, nb * self.max_len] + list(self.inner_shape)
        else:
            b = slots.shape[0] if slots.shape.rank == 1 else None
            out_shape = [b, self.max_len] + list(self.inner_shape)
        op = g.create_op(
            "KVCacheGather", [slots], attrs=self._attrs(),
            name=name or f"{self.name}_gather",
            output_specs=[(shape_mod.TensorShape(out_shape), self.dtype)])
        return op.outputs[0]

    def copy_pages(self, dst, src, name=None):
        """Copy whole rows ``cache[dst] = cache[src]`` (``dst``/``src``
        (M,) int32) — the prefix cache's copy-on-write primitive: a
        request diverging inside a shared page copies it before its own
        appends. Returns the updated cache tensor (control deps)."""
        g = ops_mod.get_default_graph()
        dst = ops_mod.convert_to_tensor(dst, dtype=dtypes_mod.int32)
        src = ops_mod.convert_to_tensor(src, dtype=dtypes_mod.int32)
        op = g.create_op(
            "KVCachePageCopy", [dst, src], attrs=self._attrs(),
            name=name or f"{self.name}_page_copy",
            output_specs=[(shape_mod.TensorShape(list(self.shape)),
                           self.dtype)])
        return op.outputs[0]

    def append_and_gather(self, value, slots, positions, name=None,
                          verify_plan=False, refcount_guarded=False):
        """The decode-step idiom: append, then gather the SAME rows
        under a control dependency so the RAW on the cache resource is
        graph-ordered (the hazard engine enforces this)."""
        appended = self.append(value, slots, positions, name=name,
                               verify_plan=verify_plan,
                               refcount_guarded=refcount_guarded)
        with ops_mod.get_default_graph().control_dependencies(
                [appended.op]):
            return self.gather(slots,
                               name=(name + "_gather") if name else None)

    def __repr__(self):
        return (f"KVCache({self.name!r}, slots={self.num_slots}, "
                f"max_len={self.max_len}, inner={self.inner_shape}, "
                f"dtype={self.dtype.name}, sharding={self.sharding!r})")


def kv_cache(name, num_slots, max_len, inner_shape, dtype,
             sharding: Optional[str] = None, paged: bool = False) -> KVCache:
    """Declare one paged KV cache (see module docstring for layout)."""
    return KVCache(name, num_slots, max_len, inner_shape, dtype,
                   sharding=sharding, paged=paged)


def is_cache_op(op) -> bool:
    return op.type in _CACHE_OP_TYPES


# ---------------------------------------------------------------------------
# DecodeAttention graph op (the paged-cache decode kernel's entry)
# ---------------------------------------------------------------------------

def decode_attention(q, k_cache, v_cache, lengths, *, bias=None,
                     sm_scale=None, causal_offset=False, name=None):
    """Attention for one query position — or a query BLOCK — against
    gathered cache rows.

    q: (B, heads, head_dim) single new query per sequence, or
    (B, Kq, heads, head_dim) a block of Kq query positions (speculative
    verify / block prefill); k_cache/v_cache: (B, max_len, heads,
    head_dim) — the :class:`KVCache` gather layout; lengths: (B,) int32
    live prefix per sequence; bias: optional additive (B, max_len) key
    bias (cross-attention padding masks). With a query block,
    ``causal_offset=True`` means ``lengths`` is the committed prefix
    BEFORE the block and query j attends positions < lengths[b]+j+1
    (the block's own K/V already appended at lengths[b]..+Kq-1);
    ``causal_offset=False`` means every query sees exactly
    positions < lengths[b] (cross-attention over a fixed source).
    Routed Pallas vs composed-XLA through stf.kernels like every fused
    op. Inference-only: no registered gradient.
    """
    g = ops_mod.get_default_graph()
    q = ops_mod.convert_to_tensor(q)
    k_cache = ops_mod.convert_to_tensor(k_cache)
    v_cache = ops_mod.convert_to_tensor(v_cache)
    lengths = ops_mod.convert_to_tensor(lengths, dtype=dtypes_mod.int32)
    if causal_offset and q.shape.rank != 4:
        raise ValueError("causal_offset=True requires a query block "
                         f"(B, Kq, H, D); got q rank {q.shape.rank}")
    inputs = [q, k_cache, v_cache, lengths]
    if bias is not None:
        inputs.append(ops_mod.convert_to_tensor(bias))
    op = g.create_op("DecodeAttention", inputs,
                     attrs={"sm_scale": sm_scale,
                            "causal_offset": bool(causal_offset)},
                     name=name or "decode_attention",
                     output_specs=[(q.shape, q.dtype)])
    return op.outputs[0]


def _lower_decode_attention(ctx, op, input_values):
    q, k, v, lengths = input_values[:4]
    bias = input_values[4] if len(input_values) > 4 else None
    fn = _kreg.select(
        "DecodeAttention",
        _kreg.aval_key(q, k, v, bias, has_bias=bias is not None))
    kw = {}
    if op.attrs.get("causal_offset"):
        # only block-query verify/prefill plans set this; keeping the
        # kwarg conditional preserves every pre-existing impl signature
        kw["causal_offset"] = True
    return [fn(q, k, v, lengths, bias=bias,
               sm_scale=op.attrs.get("sm_scale"), **kw)]


op_registry.register("DecodeAttention", lower=_lower_decode_attention)


# ---------------------------------------------------------------------------
# sharding propagation rules (stf.analysis.sharding)
#
# Cache state commits at the layout declared on the cache (slot dim
# shardable; positions/features replicated per shard) — the same
# contract as optimizer slots: the STORE owns the committed sharding,
# data edges adapt to it.
# ---------------------------------------------------------------------------

from ..analysis import sharding as _shard  # noqa: E402


def _cache_spec(op, ctx, rank):
    try:
        dim, axis = parse_cache_sharding(op.attrs.get(SHARDING_ATTR))
    except ValueError:
        dim, axis = None, None
    spec = [()] * rank
    if axis is not None and dim is not None and dim < rank \
            and ctx.mesh_axes.get(axis, 1) > 1:
        spec[dim] = (axis,)
    return tuple(spec)


def _kv_alloc_rule(op, in_specs, ctx):
    return [_cache_spec(op, ctx, len(op.attrs["shape"]))]


def _kv_append_rule(op, in_specs, ctx):
    # the committed cache layout wins; a differently-sharded value
    # reshards on the way in (slot-indexed scatter stays local when the
    # batch rides the same axis as the slot dim)
    spec = _cache_spec(op, ctx, len(op.attrs["shape"]))
    if in_specs and in_specs[0] is not None \
            and len(in_specs[0]) == len(spec) and in_specs[0] != spec:
        ctx.require(0, spec)
    return [spec]


def _kv_gather_rule(op, in_specs, ctx):
    # gather-by-slot over a slot-sharded cache is an all-gather of the
    # touched rows; over a replicated cache it is local. A HEAD-sharded
    # cache (tensor-parallel decode) is ALSO local: slot/page-table
    # indexing never crosses the head dim, each shard gathers its own
    # heads, and the output keeps the committed head sharding (dim 2 of
    # (B, L, heads, head_dim) — same inner dims as the cache).
    rank = len(op.attrs["shape"])
    cache = _cache_spec(op, ctx, rank)
    out_t = op.outputs[0]
    out_rank = rank if out_t.shape.rank is None else out_t.shape.rank
    out = [()] * out_rank
    if cache[0]:
        ctx.collective(
            "all-gather", cache[0],
            _shard.tensor_bytes(out_t) / ctx.shard_factor(cache),
            note="KVCacheGather over slot-sharded cache",
            tensor_name=out_t.name)
    else:
        for d in range(2, min(rank, out_rank)):
            out[d] = cache[d]
    return [tuple(out)]


def _kv_page_copy_rule(op, in_specs, ctx):
    # whole-row copy inside the committed cache layout: stays local on
    # a replicated OR head-sharded cache (each shard copies its own
    # heads of the row); over a slot-sharded cache the rows move
    # between shards (all-to-all of the touched rows) — priced like the
    # gather's collective but over M rows only
    return [_cache_spec(op, ctx, len(op.attrs["shape"]))]


_shard.register_rules(_kv_alloc_rule, "KVCacheAlloc")
_shard.register_rules(_kv_append_rule, "KVCacheAppend")
_shard.register_rules(_kv_gather_rule, "KVCacheGather")
_shard.register_rules(_kv_page_copy_rule, "KVCachePageCopy")


def _decode_attention_rule(op, in_specs, ctx):
    # (B, H, D) q — or a (B, Kq, H, D) query block: batch/head sharding
    # flows through exactly like FlashAttention (attention is
    # embarrassingly parallel over heads — the tensor-parallel decode
    # layout runs per-shard with ZERO collectives here); a sharded
    # cache length would need ring traffic the kernel does not do —
    # consumed gathered. Kq (block position axis) and head_dim never
    # shard.
    sq = in_specs[0]
    if sq is None:
        return [None]
    if len(sq) == 4:
        return [(sq[0], (), sq[2], ())]
    if len(sq) == 3:
        return [(sq[0], sq[1], ())]
    return [sq]


_shard.register_rules(_decode_attention_rule, "DecodeAttention")
