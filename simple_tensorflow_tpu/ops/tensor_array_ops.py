"""TensorArray (ref: tensorflow/python/ops/tensor_array_ops.py,
core/kernels/tensor_array.cc).

The reference's TensorArray is a per-step resource of independently-sized
buffers driven by the dynamic executor. On TPU that representation can't
exist: XLA needs static shapes. The TPU-native TensorArray is a *stacked
dense buffer* (size, *element_shape) threaded functionally — write lowers
to lax.dynamic_update_index_in_dim, read to dynamic_index_in_dim; both are
O(1) in-place updates under XLA (the buffer is donated along the chain).
``size`` must be static; element shapes must agree — the same constraints
lax.scan imposes, because that is what the hardware supports.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from ..framework import op_registry
from ..framework import tensor_shape as shape_mod
from .op_util import make_op

op_registry.register_pure(
    "TensorArrayWrite",
    lambda buf, index, value: jax.lax.dynamic_update_index_in_dim(
        buf, value.astype(buf.dtype), index, axis=0))
op_registry.register_pure(
    "TensorArrayRead",
    lambda buf, index: jax.lax.dynamic_index_in_dim(buf, index, axis=0,
                                                    keepdims=False))
op_registry.register_pure(
    "TensorArrayScatter",
    lambda buf, indices, values: buf.at[indices].set(
        values.astype(buf.dtype)))


class TensorArray:
    """Functional TensorArray; every mutator returns a new TensorArray
    sharing the graph (the reference mutates a resource and returns a flow
    token — ref tensor_array_ops.py:120 — our buffer IS the flow)."""

    def __init__(self, dtype, size=None, element_shape=None,
                 dynamic_size=False, clear_after_read=True,
                 tensor_array_name=None, infer_shape=True, name=None,
                 _buffer=None):
        if dynamic_size:
            raise NotImplementedError(
                "dynamic_size=True needs dynamic shapes; XLA/TPU requires "
                "a static size (use a python list at graph-build time)")
        self._dtype = dtypes_mod.as_dtype(dtype)
        self._name = name or "TensorArray"
        if _buffer is not None:
            self._buffer = _buffer
            self._size = int(_buffer.shape[0])
            return
        if size is None:
            raise ValueError("TensorArray needs a static size")
        self._size = int(size) if not isinstance(size, ops_mod.Tensor) \
            else int(size.op.attrs.get("value"))
        if element_shape is None:
            raise ValueError(
                "TPU TensorArray needs element_shape up front (static "
                "shapes); pass element_shape= or use ta.unstack")
        es = shape_mod.TensorShape(element_shape).as_list()
        from . import array_ops

        self._buffer = array_ops.zeros([self._size] + es, dtype=self._dtype,
                                       name=f"{self._name}_buf")

    @property
    def dtype(self):
        return self._dtype

    @property
    def flow(self):
        """The buffer doubles as the flow token (ref flow_out)."""
        return self._buffer

    def size(self, name=None):
        from ..framework import constant_op

        return constant_op.constant(self._size, dtype=dtypes_mod.int32)

    def _with(self, buffer):
        return TensorArray(self._dtype, name=self._name, _buffer=buffer)

    def write(self, index, value, name=None):
        index = ops_mod.convert_to_tensor(index, dtype=dtypes_mod.int32)
        value = ops_mod.convert_to_tensor(value, dtype=self._dtype)
        buf = make_op("TensorArrayWrite", [self._buffer, index, value],
                      name=name or f"{self._name}_write")
        return self._with(buf)

    def read(self, index, name=None):
        index = ops_mod.convert_to_tensor(index, dtype=dtypes_mod.int32)
        return make_op("TensorArrayRead", [self._buffer, index],
                       name=name or f"{self._name}_read")

    def stack(self, name=None):
        from . import array_ops

        return array_ops.identity(self._buffer,
                                  name=name or f"{self._name}_stack")

    def unstack(self, value, name=None):
        value = ops_mod.convert_to_tensor(value, dtype=self._dtype)
        return self._with(value)

    def gather(self, indices, name=None):
        from . import array_ops

        return array_ops.gather(self._buffer, indices,
                                name=name or f"{self._name}_gather")

    def scatter(self, indices, value, name=None):
        indices = ops_mod.convert_to_tensor(indices, dtype=dtypes_mod.int32)
        value = ops_mod.convert_to_tensor(value, dtype=self._dtype)
        buf = make_op("TensorArrayScatter", [self._buffer, indices, value],
                      name=name or f"{self._name}_scatter")
        return self._with(buf)

    def concat(self, name=None):
        from . import array_ops

        shp = self._buffer.shape.as_list()
        return array_ops.reshape(
            self._buffer, [-1] + shp[2:],
            name=name or f"{self._name}_concat")

    def split(self, value, lengths, name=None):
        """Equal-length split only (static shapes)."""
        from . import array_ops

        value = ops_mod.convert_to_tensor(value, dtype=self._dtype)
        n = self._size
        shp = value.shape.as_list()
        if shp[0] is None or shp[0] % n != 0:
            raise ValueError("TPU TensorArray.split needs equal static "
                             f"lengths; got leading dim {shp[0]} over {n}")
        return self._with(array_ops.reshape(
            value, [n, shp[0] // n] + shp[1:],
            name=name or f"{self._name}_split"))

    def grad(self, source, flow=None, name=None):
        return self  # gradients flow through the buffer (jax.vjp)

    def identity(self):
        return self

    def close(self, name=None):
        from . import control_flow_ops

        return control_flow_ops.no_op(name=name or f"{self._name}_close")
