"""FFT ops (ref: tensorflow/python/ops/spectral_ops.py,
core/kernels/fft_ops.cc)."""

from __future__ import annotations

import jax.numpy as jnp

from ..framework import graph as ops_mod
from ..framework import op_registry
from .op_util import unary, make_op

op_registry.register_pure("FFT", lambda x: jnp.fft.fft(x).astype(jnp.complex64))
op_registry.register_pure("IFFT", lambda x: jnp.fft.ifft(x).astype(jnp.complex64))
op_registry.register_pure("FFT2D", lambda x: jnp.fft.fft2(x).astype(jnp.complex64))
op_registry.register_pure("IFFT2D", lambda x: jnp.fft.ifft2(x).astype(jnp.complex64))
op_registry.register_pure("FFT3D", lambda x: jnp.fft.fftn(
    x, axes=(-3, -2, -1)).astype(jnp.complex64))
op_registry.register_pure("IFFT3D", lambda x: jnp.fft.ifftn(
    x, axes=(-3, -2, -1)).astype(jnp.complex64))
op_registry.register_pure("RFFT", lambda x, fft_length=None: jnp.fft.rfft(
    x, n=fft_length).astype(jnp.complex64))
op_registry.register_pure("IRFFT", lambda x, fft_length=None: jnp.fft.irfft(
    x, n=fft_length).astype(jnp.float32))
op_registry.register_pure("RFFT2D", lambda x, fft_length=None: jnp.fft.rfft2(
    x, s=fft_length).astype(jnp.complex64))
op_registry.register_pure("IRFFT2D", lambda x, fft_length=None: jnp.fft.irfft2(
    x, s=fft_length).astype(jnp.float32))
op_registry.register_pure("RFFT3D", lambda x, fft_length=None: jnp.fft.rfftn(
    x, s=fft_length, axes=(-3, -2, -1)).astype(jnp.complex64))
op_registry.register_pure(
    "IRFFT3D", lambda x, fft_length=None: jnp.fft.irfftn(
        x, s=fft_length, axes=(-3, -2, -1)).astype(jnp.float32))


def fft(input, name=None):  # noqa: A002
    return unary("FFT", input, name)


def ifft(input, name=None):  # noqa: A002
    return unary("IFFT", input, name)


def fft2d(input, name=None):  # noqa: A002
    return unary("FFT2D", input, name)


def ifft2d(input, name=None):  # noqa: A002
    return unary("IFFT2D", input, name)


def fft3d(input, name=None):  # noqa: A002
    return unary("FFT3D", input, name)


def ifft3d(input, name=None):  # noqa: A002
    return unary("IFFT3D", input, name)


def rfft(input, fft_length=None, name=None):  # noqa: A002
    x = ops_mod.convert_to_tensor(input)
    return make_op("RFFT", [x], attrs={"fft_length": fft_length}, name=name)


def irfft(input, fft_length=None, name=None):  # noqa: A002
    x = ops_mod.convert_to_tensor(input)
    return make_op("IRFFT", [x], attrs={"fft_length": fft_length}, name=name)


def rfft2d(input, fft_length=None, name=None):  # noqa: A002
    x = ops_mod.convert_to_tensor(input)
    return make_op("RFFT2D", [x], attrs={"fft_length": fft_length}, name=name)


def irfft2d(input, fft_length=None, name=None):  # noqa: A002
    x = ops_mod.convert_to_tensor(input)
    return make_op("IRFFT2D", [x], attrs={"fft_length": fft_length}, name=name)


def rfft3d(input, fft_length=None, name=None):  # noqa: A002
    x = ops_mod.convert_to_tensor(input)
    return make_op("RFFT3D", [x], attrs={"fft_length": fft_length},
                   name=name)


def irfft3d(input, fft_length=None, name=None):  # noqa: A002
    x = ops_mod.convert_to_tensor(input)
    return make_op("IRFFT3D", [x], attrs={"fft_length": fft_length},
                   name=name)
