"""String ops (ref: tensorflow/python/ops/string_ops.py,
core/kernels/string_*.cc).

Strings never enter the XLA program: all string ops run in the Session's
host stage (runs_on_host), operating on numpy object arrays. This replaces
the reference's CPU-pinned string kernels (placement did the same job there).
"""

from __future__ import annotations

import hashlib
import zlib

import numpy as np

from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from ..framework import op_registry
from ..framework import tensor_shape as shape_mod
from .op_util import make_op


def _host_op(op_type, fn, n_outputs=1):
    def lower(ctx, op, inputs):
        attrs = {k: v for k, v in op.attrs.items() if not k.startswith("_")}
        out = fn(*inputs, **attrs)
        return list(out) if isinstance(out, (list, tuple)) else [out]

    op_registry.register(op_type, lower=lower, is_stateful=True,
                         runs_on_host=True, n_outputs=n_outputs)


def _vec(fn):
    return np.vectorize(fn, otypes=[object])


_host_op("StringJoin", lambda *xs, separator="": _vec(
    lambda *parts: separator.join(str(p) for p in parts))(*xs))
_host_op("StringLower", _vec(lambda s: str(s).lower()))
_host_op("StringUpper", _vec(lambda s: str(s).upper()))
_host_op("StringStrip", _vec(lambda s: str(s).strip()))
_host_op("StringLength", lambda x: np.vectorize(
    lambda s: len(str(s)), otypes=[np.int32])(x))
_host_op("Substr", lambda x, pos=0, length=0: _vec(
    lambda s: str(s)[pos:pos + length])(x))
_host_op("AsString", lambda x, precision=-1: _vec(
    lambda v: (f"%.{precision}f" % v) if precision >= 0 and
    isinstance(v, float) else str(v))(x))
_host_op("StringToNumber", lambda x, out_type=None: np.vectorize(
    lambda s: float(s), otypes=[out_type.np_dtype if out_type
                                else np.float32])(x))
_host_op("StringToHashBucketFast", lambda x, num_buckets=1: np.vectorize(
    lambda s: zlib.crc32(str(s).encode()) % num_buckets,
    otypes=[np.int64])(x))
_host_op("StringToHashBucketStrong", lambda x, num_buckets=1, key=(0, 0):
         np.vectorize(
             lambda s: int(hashlib.sha256(
                 (str(key) + str(s)).encode()).hexdigest(), 16) % num_buckets,
             otypes=[np.int64])(x))
_host_op("RegexReplace", lambda x, pattern="", rewrite="", replace_global=True:
         _vec(lambda s: __import__("re").sub(
             pattern, rewrite, str(s), count=0 if replace_global else 1))(x))
_host_op("EncodeBase64", _vec(
    lambda s: __import__("base64").urlsafe_b64encode(
        s if isinstance(s, bytes) else str(s).encode()).rstrip(b"=").decode()))
_host_op("DecodeBase64", _vec(
    lambda s: __import__("base64").urlsafe_b64decode(
        str(s) + "=" * (-len(str(s)) % 4)).decode()))


def _string_api(op_type, x, name=None, attrs=None, out_dtype=dtypes_mod.string):
    x = ops_mod.convert_to_tensor(x, dtype=None)
    g = ops_mod.get_default_graph()
    op = g.create_op(op_type, [x], attrs=attrs or {}, name=name or op_type,
                     output_specs=[(x.shape, out_dtype)])
    return op.outputs[0]


def string_join(inputs, separator="", name=None):
    ts = [ops_mod.convert_to_tensor(x) for x in inputs]
    g = ops_mod.get_default_graph()
    op = g.create_op("StringJoin", ts, attrs={"separator": separator},
                     name=name or "StringJoin",
                     output_specs=[(ts[0].shape, dtypes_mod.string)])
    return op.outputs[0]


def string_lower(input, name=None):  # noqa: A002
    return _string_api("StringLower", input, name)


def string_upper(input, name=None):  # noqa: A002
    return _string_api("StringUpper", input, name)


def string_strip(input, name=None):  # noqa: A002
    return _string_api("StringStrip", input, name)


def string_length(input, name=None):  # noqa: A002
    return _string_api("StringLength", input, name, out_dtype=dtypes_mod.int32)


def substr(input, pos, len, name=None):  # noqa: A002
    from ..framework import constant_op

    p = int(constant_op.constant_value(ops_mod.convert_to_tensor(pos)))
    l = int(constant_op.constant_value(ops_mod.convert_to_tensor(len)))
    return _string_api("Substr", input, name, attrs={"pos": p, "length": l})


def as_string(input, precision=-1, scientific=False, shortest=False,  # noqa: A002
              width=-1, fill="", name=None):
    return _string_api("AsString", input, name,
                       attrs={"precision": precision})


def string_to_number(string_tensor, out_type=dtypes_mod.float32, name=None):
    return _string_api("StringToNumber", string_tensor, name,
                       attrs={"out_type": dtypes_mod.as_dtype(out_type)},
                       out_dtype=dtypes_mod.as_dtype(out_type))


def string_to_hash_bucket_fast(input, num_buckets, name=None):  # noqa: A002
    return _string_api("StringToHashBucketFast", input, name,
                       attrs={"num_buckets": int(num_buckets)},
                       out_dtype=dtypes_mod.int64)


string_to_hash_bucket = string_to_hash_bucket_fast


def string_to_hash_bucket_strong(input, num_buckets, key, name=None):  # noqa: A002
    return _string_api("StringToHashBucketStrong", input, name,
                       attrs={"num_buckets": int(num_buckets),
                              "key": tuple(key)},
                       out_dtype=dtypes_mod.int64)


def regex_replace(input, pattern, rewrite, replace_global=True, name=None):  # noqa: A002
    return _string_api("RegexReplace", input, name,
                       attrs={"pattern": pattern, "rewrite": rewrite,
                              "replace_global": replace_global})


def encode_base64(input, pad=False, name=None):  # noqa: A002
    return _string_api("EncodeBase64", input, name)


def decode_base64(input, name=None):  # noqa: A002
    return _string_api("DecodeBase64", input, name)


def string_split(source, delimiter=" "):
    from ..framework import constant_op
    from ..framework.sparse_tensor import SparseTensor

    v = constant_op.constant_value(ops_mod.convert_to_tensor(source))
    if v is None:
        raise ValueError("string_split needs static input on TPU "
                         "(dynamic-shape output)")
    indices, values = [], []
    for i, s in enumerate(np.ravel(v)):
        parts = str(s).split(delimiter) if delimiter else list(str(s))
        for j, p in enumerate(parts):
            indices.append([i, j])
            values.append(p)
    max_len = max((i[1] for i in indices), default=-1) + 1
    return SparseTensor(
        constant_op.constant(np.asarray(indices, dtype=np.int64).reshape(-1, 2)),
        constant_op.constant(np.asarray(values, dtype=object)),
        constant_op.constant(np.asarray([v.size, max_len], dtype=np.int64)))


def reduce_join(inputs, axis=None, keep_dims=False, separator="", name=None,
                reduction_indices=None):
    from ..framework import constant_op

    v = constant_op.constant_value(ops_mod.convert_to_tensor(inputs))
    if v is None:
        raise ValueError("reduce_join needs static input on TPU")
    ax = axis if axis is not None else reduction_indices
    out = np.apply_along_axis(lambda row: separator.join(str(s) for s in row),
                              ax if ax is not None else -1, v)
    return constant_op.constant(np.asarray(out, dtype=object))
