"""Control flow: cond, while_loop, case, group — structured, XLA-native.

TPU-native redesign of the reference's dataflow control flow
(ref: tensorflow/python/ops/control_flow_ops.py — ``cond`` builds
Switch/Merge nodes, ``while_loop`` builds Enter/Exit/NextIteration frames
executed by the dynamic executor, core/kernels/control_flow_ops.cc).
Dynamic dataflow control flow cannot run on the MXU pipeline; XLA requires
*structured* control flow. So branches/bodies are built as FuncGraphs
(nested graphs with captures) and lower to lax.cond / lax.while_loop —
single compiled program, compiler-visible control flow.

Differences from the reference, by hardware necessity:
- loop-carried shapes must be invariant (XLA); shape_invariants accepted but
  must equal the input shapes,
- reverse-mode gradients flow through ``cond`` (lax.cond is differentiable);
  gradients through ``while_loop`` require a statically bounded loop — use
  stf.scan / stf.foldl (lax.scan) for differentiable loops, as dynamic_rnn
  does.
"""

from __future__ import annotations

import builtins
from typing import Any, Callable, List, Sequence

import numpy as np

from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from ..framework import lowering as lowering_mod
from ..framework import op_registry
from ..framework import optimizer as optimizer_mod
from ..framework import tensor_shape as shape_mod

Tensor = ops_mod.Tensor
FuncGraph = ops_mod.FuncGraph


# -- structure utils ---------------------------------------------------------

def _flatten(structure):
    """Flatten nested (list/tuple/dict) structures of Tensors."""
    flat: List[Any] = []

    def rec(s):
        if isinstance(s, (list, builtins.tuple)) and not isinstance(s, str):
            for x in s:
                rec(x)
        elif isinstance(s, dict):
            for k in sorted(s):
                rec(s[k])
        else:
            flat.append(s)

    rec(structure)
    return flat


def _pack_like(structure, flat):
    it = iter(flat)

    def rec(s):
        if isinstance(s, (list, builtins.tuple)) and not isinstance(s, str):
            vals = [rec(x) for x in s]
            return type(s)(vals) if not hasattr(s, "_fields") else type(s)(*vals)
        if isinstance(s, dict):
            return {k: rec(s[k]) for k in sorted(s)}
        return next(it)

    return rec(structure)


# -- simple ops --------------------------------------------------------------

def _lower_noop(ctx, op, inputs):
    return []


op_registry.register("NoOp", lower=_lower_noop, is_stateful=True, n_outputs=0)
op_registry.register("Group", lower=_lower_noop, is_stateful=True, n_outputs=0)


def no_op(name=None):
    g = ops_mod.get_default_graph()
    return g.create_op("NoOp", [], name=name or "NoOp", output_specs=[])


def group(*inputs, **kwargs):
    """(ref: control_flow_ops.py:2855 ``group``). An op that completes when
    all inputs complete — here: a node whose control edges force its inputs
    into the pruned program."""
    name = kwargs.pop("name", None)
    g = ops_mod.get_default_graph()
    ctrl = []
    for x in _flatten(list(inputs)):
        if isinstance(x, Tensor):
            ctrl.append(x.op)
        elif isinstance(x, ops_mod.Operation):
            ctrl.append(x)
        elif hasattr(x, "op"):
            ctrl.append(x.op)
        elif x is None:
            continue
        else:
            raise TypeError(f"group: cannot handle {x!r}")
    return g.create_op("Group", [], name=name or "group",
                       output_specs=[], control_inputs=ctrl)


def tuple(tensors, name=None, control_inputs=None):  # noqa: A001
    """(ref: control_flow_ops.py ``tuple``): gate tensors on joint readiness.
    In a single XLA program this is ordering metadata only."""
    g = ops_mod.get_default_graph()
    gate = group(*[t for t in tensors if t is not None],
                 *(control_inputs or []))
    from . import array_ops

    out = []
    with g.control_dependencies([gate]):
        for t in tensors:
            out.append(array_ops.identity(t) if t is not None else None)
    return out


def with_dependencies(dependencies, output_tensor, name=None):
    g = ops_mod.get_default_graph()
    from . import array_ops

    with g.control_dependencies(dependencies):
        return array_ops.identity(output_tensor, name=name)


# -- cond --------------------------------------------------------------------

def _build_branch(fn, name):
    g = ops_mod.get_default_graph()
    fg = FuncGraph(name, outer_graph=g)
    with ops_mod._as_current(fg):
        result = fn()
    flat = [ops_mod.convert_to_tensor(t) if not isinstance(t, Tensor) else t
            for t in _flatten(result)]
    # Convert in fg context so constants land inside the branch graph.
    with ops_mod._as_current(fg):
        flat = [t if t.graph is fg else fg._maybe_capture(t, name)
                for t in flat]
    fg.outputs = flat
    return fg, result


def cond(pred, true_fn=None, false_fn=None, strict=False, name=None,
         fn1=None, fn2=None):
    """(ref: control_flow_ops.py:1806 ``cond``) → lax.cond."""
    true_fn = true_fn or fn1
    false_fn = false_fn or fn2
    if true_fn is None or false_fn is None:
        raise ValueError("cond needs true_fn and false_fn")
    g = ops_mod.get_default_graph()
    pred = ops_mod.convert_to_tensor(pred)
    with g.name_scope(name or "cond"):
        tg, t_struct = _build_branch(true_fn, "cond_true")
        fg, f_struct = _build_branch(false_fn, "cond_false")
        if len(tg.outputs) != len(fg.outputs):
            raise ValueError(
                f"cond branches returned different numbers of tensors: "
                f"{len(tg.outputs)} vs {len(fg.outputs)}")
        for a, b in zip(tg.outputs, fg.outputs):
            if a.dtype.base_dtype != b.dtype.base_dtype:
                raise TypeError(
                    f"cond branch dtypes differ: {a.dtype.name} vs {b.dtype.name}")
        t_caps = [outer for outer, _ in tg.captures]
        f_caps = [outer for outer, _ in fg.captures]
        out_specs = [(a.shape.merge_with(b.shape) if a.shape.is_compatible_with(b.shape)
                      else shape_mod.TensorShape(None), a.dtype)
                     for a, b in zip(tg.outputs, fg.outputs)]
        op = g.create_op(
            "Cond", [pred] + t_caps + f_caps,
            attrs={"true_graph": tg, "false_graph": fg,
                   "n_true_caps": len(t_caps)},
            name="cond_op", output_specs=out_specs)
    if not op.outputs:
        return None
    flat_out = list(op.outputs)
    packed = _pack_like(t_struct, flat_out)
    if not strict and isinstance(packed, (list, builtins.tuple)) \
            and len(packed) == 1:
        # non-strict mode unwraps singleton sequences (reference semantics,
        # ref control_flow_ops.py cond strict= docstring)
        return packed[0]
    return packed


def _lower_cond(ctx, op, inputs):
    import jax

    tg = op.attrs["true_graph"]
    fg = op.attrs["false_graph"]
    n_t = op.attrs["n_true_caps"]
    pred = inputs[0]
    t_caps = inputs[1:1 + n_t]
    f_caps = inputs[1 + n_t:]

    def t_branch(tc, fc):
        return builtins.tuple(lowering_mod.lower_func_graph(ctx, tg, [], tc))

    def f_branch(tc, fc):
        return builtins.tuple(lowering_mod.lower_func_graph(ctx, fg, [], fc))

    if hasattr(pred, "ndim") and getattr(pred, "ndim", 0):
        pred = pred.reshape(())
    out = jax.lax.cond(pred, t_branch, f_branch, builtins.tuple(t_caps),
                       builtins.tuple(f_caps))
    return list(out)


op_registry.register("Cond", lower=_lower_cond, n_outputs=None)

# PassManager anatomy: inputs = [pred] + true-captures + false-captures.
# Branch bodies run at most once, so hoisting out of them would
# SPECULATE work the untaken branch never pays — hoist stays False;
# constants captured by a branch still fold inside it.
optimizer_mod.register_function_op(
    "Cond", mode="branch",
    bodies=lambda a, n: [
        dict(attr="true_graph", start=1, count=a["n_true_caps"],
             hoist=False, count_attr="n_true_caps"),
        dict(attr="false_graph", start=1 + a["n_true_caps"],
             count=n - 1 - a["n_true_caps"], hoist=False,
             count_attr=None),
    ])


def case(pred_fn_pairs, default=None, exclusive=False, strict=False,
         name="case"):
    """(ref: control_flow_ops.py:3211 ``case``) — chained lax.cond."""
    if isinstance(pred_fn_pairs, dict):
        pairs = list(pred_fn_pairs.items())
    else:
        pairs = list(pred_fn_pairs)
    if default is None:
        default = pairs[-1][1]
        pairs = pairs[:-1]

    def build(i):
        if i == len(pairs):
            return default
        p, f = pairs[i]
        return lambda: cond(p, f, build(i + 1))

    with ops_mod.name_scope(name):
        return build(0)()


# -- while_loop --------------------------------------------------------------

def _call_with(fn, loop_vars, flat_args):
    """Rebuild the user's loop_vars structure from flat args and apply fn the
    way the reference does (fn(*top_level_items))."""
    if isinstance(loop_vars, (list, builtins.tuple)):
        packed = _pack_like(builtins.list(loop_vars), flat_args)
        return fn(*packed)
    return fn(_pack_like(loop_vars, flat_args))


def while_loop(cond, body, loop_vars, shape_invariants=None,
               parallel_iterations=10, back_prop=True, swap_memory=False,
               name=None, maximum_iterations=None):
    """(ref: control_flow_ops.py:2775 ``while_loop``) → lax.while_loop.

    Reverse-mode gradients require ``maximum_iterations``: the forward
    pass stays an early-exiting lax.while_loop, and the gradient replay
    re-traces the loop as a masked lax.scan over the static bound, which
    lax can differentiate. (The reference differentiates the UNBOUNDED
    loop by stacking every iteration's intermediates in host memory, ref
    core/kernels/stack_ops.cc — a pattern TPU HBM budgets rule out;
    bounding the loop is the same contract tf2xla imposes.) Without
    maximum_iterations the loop is forward-only — use stf.scan /
    stf.foldl / dynamic_rnn for naturally bounded iteration.
    Loop-carried shapes must be invariant (XLA requirement).
    """
    g = ops_mod.get_default_graph()
    flat_vars = [ops_mod.convert_to_tensor(v) for v in _flatten(loop_vars)]
    with g.name_scope(name or "while"):
        cg = FuncGraph("while_cond", outer_graph=g)
        with ops_mod._as_current(cg):
            c_args = [cg.add_input(v.dtype, v.shape, f"arg{i}")
                      for i, v in enumerate(flat_vars)]
            c_res = _call_with(cond, loop_vars, c_args)
            cg.outputs = [ops_mod.convert_to_tensor(c_res)]
        bg = FuncGraph("while_body", outer_graph=g)
        with ops_mod._as_current(bg):
            b_args = [bg.add_input(v.dtype, v.shape, f"arg{i}")
                      for i, v in enumerate(flat_vars)]
            b_res = _call_with(body, loop_vars, b_args)
            b_flat = [ops_mod.convert_to_tensor(t) for t in _flatten(b_res)]
            bg.outputs = b_flat
        if len(b_flat) != len(flat_vars):
            raise ValueError(
                f"while_loop body returned {len(b_flat)} values for "
                f"{len(flat_vars)} loop vars")
        for v, o in zip(flat_vars, b_flat):
            if v.dtype.base_dtype != o.dtype.base_dtype:
                raise TypeError(
                    f"Loop var dtype changed: {v.dtype.name} -> {o.dtype.name}")
            if (shape_invariants is None and v.shape.is_fully_defined()
                    and o.shape.is_fully_defined()
                    and v.shape.as_list() != o.shape.as_list()):
                raise ValueError(
                    f"Loop var shape changed {v.shape} -> {o.shape}; XLA "
                    "loops need invariant shapes.")
        c_caps = [outer for outer, _ in cg.captures]
        b_caps = [outer for outer, _ in bg.captures]
        if maximum_iterations is not None:
            from ..framework import constant_op as _const

            mi = _const.constant_value(
                ops_mod.convert_to_tensor(maximum_iterations))
            if mi is None:
                raise ValueError("maximum_iterations must be static on TPU")
            maximum_iterations = int(mi)
        op = g.create_op(
            "While", flat_vars + c_caps + b_caps,
            attrs={"cond_graph": cg, "body_graph": bg,
                   "n_vars": len(flat_vars), "n_cond_caps": len(c_caps),
                   "max_iterations": maximum_iterations},
            name="while_op",
            output_specs=[(v.shape, v.dtype) for v in flat_vars])
    outs = builtins.list(op.outputs)
    if isinstance(loop_vars, (list, builtins.tuple)):
        packed = _pack_like(builtins.list(loop_vars), outs)
        if len(loop_vars) == 1:
            return packed[0]
        return builtins.tuple(packed) if isinstance(loop_vars, builtins.tuple) \
            else packed
    return _pack_like(loop_vars, outs)


def _lower_while(ctx, op, inputs):
    import jax
    import jax.numpy as jnp

    n = op.attrs["n_vars"]
    n_cc = op.attrs["n_cond_caps"]
    cg = op.attrs["cond_graph"]
    bg = op.attrs["body_graph"]
    max_iter = op.attrs.get("max_iterations")
    init = builtins.tuple(inputs[:n])
    c_caps = builtins.list(inputs[n:n + n_cc])
    b_caps = builtins.list(inputs[n + n_cc:])

    if max_iter is not None:
        if getattr(ctx, "differentiable", False):
            # Inside the SymbolicGradient replay: lax.while_loop has no
            # reverse-mode rule, but the user gave a static bound, so the
            # loop IS expressible as a lax.scan of max_iter guarded steps
            # — exactly the bounded-loop form XLA wants on TPU. Each step
            # runs the body under lax.cond (differentiable), so
            # iterations past the exit never EVALUATE the body: a body
            # that is only numerically valid while cond holds (Newton
            # steps, sqrt/log of a shrinking quantity) cannot poison the
            # gradient with 0*NaN from post-exit values. Values and
            # gradients therefore match the early-exiting forward.
            def step(carry, _):
                active, vars_ = carry
                c = lowering_mod.lower_func_graph(
                    ctx, cg, builtins.list(vars_), c_caps)[0]
                act = jnp.logical_and(active, jnp.reshape(c, ()))

                def run_body(vs):
                    return builtins.tuple(lowering_mod.lower_func_graph(
                        ctx, bg, builtins.list(vs), b_caps))

                new_vars = jax.lax.cond(act, run_body, lambda vs: vs,
                                        vars_)
                return (act, new_vars), None

            (_, final_vars), _ = jax.lax.scan(
                step, (jnp.asarray(True), init), None, length=max_iter)
            return builtins.list(final_vars)

        init = (jnp.asarray(0, jnp.int32),) + init

        def cond_f(carry):
            c = lowering_mod.lower_func_graph(
                ctx, cg, builtins.list(carry[1:]), c_caps)[0]
            return jnp.logical_and(jnp.reshape(c, ()), carry[0] < max_iter)

        def body_f(carry):
            out = lowering_mod.lower_func_graph(
                ctx, bg, builtins.list(carry[1:]), b_caps)
            return (carry[0] + 1,) + builtins.tuple(out)

        final = jax.lax.while_loop(cond_f, body_f, init)
        return builtins.list(final[1:])

    def cond_f(carry):
        c = lowering_mod.lower_func_graph(ctx, cg, builtins.list(carry), c_caps)[0]
        return jnp.reshape(c, ())

    def body_f(carry):
        return builtins.tuple(
            lowering_mod.lower_func_graph(ctx, bg, builtins.list(carry), b_caps))

    final = jax.lax.while_loop(cond_f, body_f, init)
    return builtins.list(final)


op_registry.register("While", lower=_lower_while, n_outputs=None)

# inputs = loop-vars + cond-captures + body-captures. Both graphs
# re-execute per ITERATION, so capture-only subexpressions hoist out
# (loop-invariant code motion); cost attribution multiplies by the
# static trip bound when the user gave one.
optimizer_mod.register_function_op(
    "While", mode="loop",
    bodies=lambda a, n: [
        dict(attr="cond_graph", start=a["n_vars"], count=a["n_cond_caps"],
             hoist=True, count_attr="n_cond_caps"),
        dict(attr="body_graph", start=a["n_vars"] + a["n_cond_caps"],
             count=n - a["n_vars"] - a["n_cond_caps"], hoist=True,
             count_attr=None),
    ],
    trip=lambda a, inputs: a.get("max_iterations"))


def smart_cond(pred, true_fn, false_fn, name=None):
    from ..framework import constant_op

    if isinstance(pred, Tensor):
        pv = constant_op.constant_value(pred)
    else:
        pv = np.asarray(pred)
    if pv is not None:
        return true_fn() if builtins.bool(pv) else false_fn()
    return cond(pred, true_fn, false_fn, name=name)


class ControlFlowContext:
    """Kept for API parity; structured control flow has no frame contexts."""


# ---------------------------------------------------------------------------
# sharding propagation rules (stf.analysis.sharding; ISSUE 6): specs
# flow into the branch/body FuncGraphs; loop carries iterate to a
# fixpoint; reshards inside a body are trip-weighted (hotspot lint).
# ---------------------------------------------------------------------------

from ..analysis import sharding as _shard  # noqa: E402

_shard.register_rules(_shard.make_loop_rule("cond"), "Cond")
_shard.register_rules(_shard.make_loop_rule("while"), "While")
