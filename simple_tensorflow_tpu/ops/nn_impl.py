"""nn_impl: moments, batch norm, sampled losses
(ref: tensorflow/python/ops/nn_impl.py, core/kernels/fused_batch_norm_op.cc).

fused_batch_norm lowers to one composite that XLA fuses into neighboring
convs (the reference hand-fuses in CUDA); statistics accumulate in f32 even
for bf16 activations (TPU numerics contract).
"""

from __future__ import annotations

import builtins
import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from ..framework import op_registry
from .op_util import make_op
from . import math_ops


def _moments_impl(x, axes=None, keepdims=False):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
    if not keepdims:
        mean = jnp.squeeze(mean, axis=axes)
        var = jnp.squeeze(var, axis=axes)
    return [mean.astype(x.dtype), var.astype(x.dtype)]


op_registry.register_pure("Moments", _moments_impl, n_outputs=2)


def _bn_ch_shape(x, red_axes):
    shape = [1] * x.ndim
    for i in builtins.range(x.ndim):
        if i not in red_axes:
            shape[i] = x.shape[i]
    return shape


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _bn_train(x, scale, offset, epsilon, red_axes):
    out, _, _, mean, var = _bn_train_fwd_impl(x, scale, offset, epsilon,
                                              red_axes)
    return out, mean, var


def _bn_train_fwd_impl(x, scale, offset, epsilon, red_axes):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=red_axes)
    if x.dtype in (jnp.bfloat16, jnp.float16):
        # One-pass f32 statistics: both reductions read x once (XLA emits
        # one multi-output fusion with the convert folded in, so no
        # full-size f32 tensor is materialized). E[x^2]-E[x]^2 in f32 over
        # half-precision data loses ~(mean^2/var)*2^-24 relative accuracy —
        # far below the quantization already present in the activations.
        meansq = jnp.mean(jnp.square(xf), axis=red_axes)
        var = jnp.maximum(meansq - jnp.square(mean), 0.0)
    else:
        # f32+ inputs carry 24-bit mantissas, where E[x^2]-E[x]^2 cancels
        # catastrophically for mean >> std; pay the second read of x for
        # the centered two-pass form. (Safe under the custom VJP: the
        # backward never differentiates through this, so no full-size
        # residual is saved either way.)
        shape = _bn_ch_shape(x, red_axes)
        var = jnp.mean(jnp.square(xf - mean.reshape(shape)), axis=red_axes)
    inv = jax.lax.rsqrt(var + epsilon)
    shape = _bn_ch_shape(x, red_axes)
    # subtract-first in x.dtype: (x - mean) is near-exact for x close to
    # mean (Sterbenz), unlike folding mean into a bias term where x*inv and
    # bias are large same-magnitude values rounded before cancelling
    out = (x - mean.reshape(shape).astype(x.dtype)) \
        * (inv * scale.astype(jnp.float32)).reshape(shape).astype(x.dtype) \
        + offset.reshape(shape).astype(x.dtype)
    return out, mean, inv, mean, var


def _bn_train_fwd(x, scale, offset, epsilon, red_axes):
    out, mean, inv, _, var = _bn_train_fwd_impl(x, scale, offset, epsilon,
                                                red_axes)
    # Residuals are the bf16 activations plus per-channel f32 stats — the
    # default-autodiff path instead saved a full-size f32 (x - mean) tensor
    # per BN layer, which made ResNet-50 HBM-bound (~90 GB/step).
    return (out, mean, var), (x, scale, mean, inv)


def _bn_train_bwd(epsilon, red_axes, res, cts):
    x, scale, mean, inv = res
    dy, dmean_ct, dvar_ct = cts
    n = 1
    for i in red_axes:
        n *= x.shape[i]
    n = jnp.float32(n)
    shape = _bn_ch_shape(x, red_axes)
    scale_f = scale.astype(jnp.float32)
    # x_hat recomputed elementwise from bf16 x (fuses into the reductions;
    # cheaper than storing an f32 residual)
    xc = x.astype(jnp.float32) - mean.reshape(shape)
    x_hat = xc * inv.reshape(shape)
    dyf = dy.astype(jnp.float32)
    sum_dy = jnp.sum(dyf, axis=red_axes)
    sum_dy_xhat = jnp.sum(dyf * x_hat, axis=red_axes)
    # d(out)/dx through the batch statistics (standard BN backward), plus
    # the cotangents that arrive on the mean/var outputs themselves
    # (moving-average updates): d mean/dx = 1/n, d var/dx = 2(x-mean)/n
    # for the one-pass E[x^2]-E[x]^2 form as well.
    dx = (scale_f * inv).reshape(shape) * (
        dyf - (sum_dy / n).reshape(shape) - x_hat * (sum_dy_xhat / n).reshape(shape))
    dx = dx + (dmean_ct.astype(jnp.float32) / n).reshape(shape)
    dx = dx + (2.0 * dvar_ct.astype(jnp.float32) / n).reshape(shape) * xc
    return (dx.astype(x.dtype), sum_dy_xhat.astype(scale.dtype),
            sum_dy.astype(scale.dtype))


_bn_train.defvjp(_bn_train_fwd, _bn_train_bwd)


def _fused_bn_impl(x, scale, offset, mean=None, variance=None, epsilon=1e-3,
                   is_training=True, data_format="NHWC"):
    # Statistics reduce in f32 (TPU numerics contract); the elementwise
    # apply stays in x.dtype. Training mode uses a custom VJP so the only
    # full-size residual is the bf16 input itself — see _bn_train_fwd.
    ch_axis = x.ndim - 1 if data_format == "NHWC" else 1
    red_axes = builtins.tuple(i for i in builtins.range(x.ndim)
                              if i != ch_axis)
    if is_training:
        out, batch_mean, batch_var = _bn_train(x, scale, offset,
                                               builtins.float(epsilon),
                                               red_axes)
        return [out, batch_mean, batch_var]
    shape = _bn_ch_shape(x, red_axes)
    batch_mean = mean.astype(jnp.float32)
    batch_var = variance.astype(jnp.float32)
    inv = jax.lax.rsqrt(batch_var + epsilon) * scale.astype(jnp.float32)
    out = (x - batch_mean.reshape(shape).astype(x.dtype)) \
        * inv.reshape(shape).astype(x.dtype) \
        + offset.reshape(shape).astype(x.dtype)
    return [out, batch_mean, batch_var]


op_registry.register_pure("FusedBatchNorm", _fused_bn_impl, n_outputs=3)


def moments(x, axes, shift=None, name=None, keep_dims=False, keepdims=None):
    """(ref: nn_impl.py ``moments``)."""
    if keepdims is not None:
        keep_dims = keepdims
    x = ops_mod.convert_to_tensor(x)
    from .op_util import norm_axis

    mean, var = make_op("Moments", [x],
                        attrs={"axes": norm_axis(axes),
                               "keepdims": builtins.bool(keep_dims)},
                        name=name, n_out=2)
    return mean, var


def weighted_moments(x, axes, frequency_weights, name=None, keep_dims=False):
    from . import array_ops

    x = ops_mod.convert_to_tensor(x)
    w = math_ops.cast(ops_mod.convert_to_tensor(frequency_weights),
                      x.dtype.base_dtype)
    wsum = math_ops.reduce_sum(w * array_ops.ones_like(x), axis=axes,
                               keepdims=True)
    mean = math_ops.reduce_sum(w * x, axis=axes, keepdims=True) / wsum
    var = math_ops.reduce_sum(w * math_ops.square(x - mean), axis=axes,
                              keepdims=True) / wsum
    if not keep_dims:
        mean = array_ops.squeeze(mean, axes)
        var = array_ops.squeeze(var, axes)
    return mean, var


def fused_batch_norm(x, scale, offset, mean=None, variance=None, epsilon=1e-3,
                     data_format="NHWC", is_training=True, name=None):
    """(ref: nn_impl.py ``fused_batch_norm``)."""
    x = ops_mod.convert_to_tensor(x)
    scale = ops_mod.convert_to_tensor(scale, dtype="float32")
    offset = ops_mod.convert_to_tensor(offset, dtype="float32")
    inputs = [x, scale, offset]
    if not is_training:
        if mean is None or variance is None:
            raise ValueError("fused_batch_norm inference needs mean/variance")
        inputs += [ops_mod.convert_to_tensor(mean, dtype="float32"),
                   ops_mod.convert_to_tensor(variance, dtype="float32")]
    y, m, v = make_op(
        "FusedBatchNorm", inputs,
        attrs={"epsilon": float(epsilon), "is_training": is_training,
               "data_format": data_format}, name=name, n_out=3)
    return y, m, v


def _pure_bn_sig_fix():
    # FusedBatchNorm pure_fn takes (x, scale, offset[, mean, variance]);
    # in inference mode two extra positional inputs arrive. The lambda-based
    # registration handles both arities already.
    pass


def batch_normalization(x, mean, variance, offset, scale,
                        variance_epsilon=1e-3, name=None):
    """(ref: nn_impl.py ``batch_normalization``) — composed form; XLA fuses."""
    x = ops_mod.convert_to_tensor(x)
    inv = math_ops.rsqrt(variance + variance_epsilon)
    if scale is not None:
        inv = inv * scale
    out = x * math_ops.cast(inv, x.dtype.base_dtype) + math_ops.cast(
        (offset - mean * inv) if offset is not None else (-mean * inv),
        x.dtype.base_dtype)
    return out


def batch_norm_with_global_normalization(t, m, v, beta, gamma,
                                         variance_epsilon,
                                         scale_after_normalization,
                                         name=None):
    return batch_normalization(t, m, v, beta,
                               gamma if scale_after_normalization else None,
                               variance_epsilon, name)


def l2_normalize(x, axis=None, epsilon=1e-12, name=None, dim=None):
    return math_ops.l2_normalize(x, axis=axis, epsilon=epsilon, name=name,
                                 dim=dim)


def zero_fraction(value, name=None):
    from . import array_ops

    value = ops_mod.convert_to_tensor(value)
    zero = ops_mod.convert_to_tensor(0, dtype=value.dtype.base_dtype)
    return math_ops.reduce_mean(
        math_ops.cast(math_ops.equal(value, zero), "float32"), name=name)


def normalize_moments(counts, mean_ss, variance_ss, shift, name=None):
    divisor = math_ops.reciprocal(counts)
    if shift is not None:
        shifted_mean = mean_ss * divisor
        mean = shifted_mean + shift
    else:
        shifted_mean = mean_ss * divisor
        mean = shifted_mean
    variance = variance_ss * divisor - math_ops.square(shifted_mean)
    return mean, variance


def sufficient_statistics(x, axes, shift=None, keep_dims=False, name=None):
    from . import array_ops

    x = ops_mod.convert_to_tensor(x)
    counts = 1.0
    for a in axes:
        counts *= float(x.shape[a].value)
    counts_t = ops_mod.convert_to_tensor(counts, dtype=x.dtype.base_dtype)
    if shift is not None:
        m_ss = math_ops.reduce_sum(x - shift, axis=axes, keepdims=keep_dims)
        v_ss = math_ops.reduce_sum(math_ops.square(x - shift), axis=axes,
                                   keepdims=keep_dims)
    else:
        m_ss = math_ops.reduce_sum(x, axis=axes, keepdims=keep_dims)
        v_ss = math_ops.reduce_sum(math_ops.square(x), axis=axes,
                                   keepdims=keep_dims)
    return counts_t, m_ss, v_ss, shift


def _sampled_logits(weights, biases, labels, inputs, num_sampled, num_classes,
                    num_true, sampled_values, subtract_log_q, name):
    """Shared by nce_loss / sampled_softmax_loss
    (ref: nn_impl.py ``_compute_sampled_logits``)."""
    from . import array_ops, embedding_ops, candidate_sampling_ops

    if not isinstance(weights, (list, tuple)):
        weights = [weights]
    inputs = ops_mod.convert_to_tensor(inputs)
    labels = math_ops.cast(ops_mod.convert_to_tensor(labels), "int32")
    if sampled_values is None:
        sampled_values = candidate_sampling_ops.log_uniform_candidate_sampler(
            true_classes=math_ops.cast(labels, "int64"), num_true=num_true,
            num_sampled=num_sampled, unique=True, range_max=num_classes)
    sampled, true_expected, sampled_expected = sampled_values
    sampled = math_ops.cast(sampled, "int32")
    labels_flat = array_ops.reshape(labels, [-1])
    all_ids = array_ops.concat([labels_flat, sampled], 0)
    all_w = embedding_ops.embedding_lookup(weights[0] if len(weights) == 1
                                           else weights, all_ids)
    all_b = embedding_ops.embedding_lookup(biases, all_ids)
    n_true_total = labels_flat.shape[0].value
    true_w = all_w[:n_true_total]
    sampled_w = all_w[n_true_total:]
    true_b = all_b[:n_true_total]
    sampled_b = all_b[n_true_total:]
    dim = inputs.shape[-1].value
    true_w = array_ops.reshape(true_w, [-1, num_true, dim])
    true_logits = math_ops.reduce_sum(
        array_ops.expand_dims(inputs, 1) * true_w, axis=2)
    true_logits += array_ops.reshape(true_b, [-1, num_true])
    sampled_logits = math_ops.matmul(inputs, sampled_w, transpose_b=True)
    sampled_logits += sampled_b
    if subtract_log_q:
        true_logits -= math_ops.log(true_expected)
        sampled_logits -= math_ops.log(sampled_expected)
    out_logits = array_ops.concat([true_logits, sampled_logits], 1)
    out_labels = array_ops.concat([
        array_ops.ones_like(true_logits) / num_true,
        array_ops.zeros_like(sampled_logits)], 1)
    return out_logits, out_labels


def nce_loss(weights, biases, labels, inputs, num_sampled, num_classes,
             num_true=1, sampled_values=None, remove_accidental_hits=False,
             partition_strategy="mod", name="nce_loss"):
    """(ref: nn_impl.py ``nce_loss``)."""
    from . import nn_ops

    logits, labels_out = _sampled_logits(
        weights, biases, labels, inputs, num_sampled, num_classes, num_true,
        sampled_values, subtract_log_q=True, name=name)
    xent = nn_ops.sigmoid_cross_entropy_with_logits(labels=labels_out,
                                                    logits=logits)
    return math_ops.reduce_sum(xent, axis=1)


def sampled_softmax_loss(weights, biases, labels, inputs, num_sampled,
                         num_classes, num_true=1, sampled_values=None,
                         remove_accidental_hits=True,
                         partition_strategy="mod",
                         name="sampled_softmax_loss"):
    """(ref: nn_impl.py ``sampled_softmax_loss``)."""
    from . import nn_ops

    logits, labels_out = _sampled_logits(
        weights, biases, labels, inputs, num_sampled, num_classes, num_true,
        sampled_values, subtract_log_q=True, name=name)
    return nn_ops.softmax_cross_entropy_with_logits(labels=labels_out,
                                                    logits=logits)


# ---------------------------------------------------------------------------
# sharding propagation rules (stf.analysis.sharding; ISSUE 6)
# ---------------------------------------------------------------------------

from ..analysis import sharding as _shard  # noqa: E402

_shard.register_rules(_shard.make_reduce_rule("axes", "keepdims"),
                      "Moments")
_shard.register_rules(_shard.batchnorm_rule, "FusedBatchNorm")
