"""Numerics checking (ref: tensorflow/python/ops/numerics.py).

check_numerics lowers to a jax.lax.cond-free formulation: the value is
passed through jnp.where-based detection and an XLA-side error is raised
via checkify-style host callback only on failure — on TPU a hard assert
would stall the pipeline, so detection happens in the compiled program and
the raise happens host-side at fetch time (Session checks the flag).
"""

from __future__ import annotations

from ..framework import graph as ops_mod
from . import array_ops


def verify_tensor_all_finite(t, msg, name=None):
    """(ref: numerics.py:32 ``verify_tensor_all_finite``)."""
    return array_ops.check_numerics(t, message=msg, name=name)


def add_check_numerics_ops():
    """(ref: numerics.py:51 ``add_check_numerics_ops``): wrap every
    floating-point tensor in the current graph with CheckNumerics; returns
    a group op. TPU-native, each CheckNumerics is fused into the step
    program (no extra launches)."""
    from . import control_flow_ops

    g = ops_mod.get_default_graph()
    checks = []
    for op in list(g.get_operations()):
        if op.type in ("CheckNumerics", "Placeholder", "Const"):
            continue
        for out in op.outputs:
            if out.dtype.is_floating:
                checks.append(array_ops.check_numerics(
                    out, message=f"{op.name}:{out.value_index}"))
    return control_flow_ops.group(*checks, name="check_numerics_all")
