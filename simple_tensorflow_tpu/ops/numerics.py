"""Numerics checking (ref: tensorflow/python/ops/numerics.py).

check_numerics lowers to a jax.lax.cond-free formulation: the value is
passed through jnp.where-based detection and an XLA-side error is raised
via checkify-style host callback only on failure — on TPU a hard assert
would stall the pipeline, so detection happens in the compiled program and
the raise happens host-side at fetch time (Session checks the flag).
"""

from __future__ import annotations

from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from ..framework import op_registry
from ..framework import tensor_shape as shape_mod
from . import array_ops

# Layout of the packed per-tensor stats vector a NumericSummary emits.
STAT_NAMES = ("nonfinite_count", "max_abs", "l2_norm", "zero_fraction")
STATS_WIDTH = len(STAT_NAMES)


def _numeric_summary_pure(x):
    import jax.numpy as jnp

    x = jnp.asarray(x)
    xf = x.astype(jnp.float32)
    if xf.size == 0:
        return jnp.zeros((STATS_WIDTH,), jnp.float32)
    finite = jnp.isfinite(xf)
    nonfinite = jnp.sum(~finite).astype(jnp.float32)
    # mask nonfinites out of the magnitude stats so the summary vector
    # itself is always finite (a NaN-poisoned max would make the packed
    # health tensor useless for *which tensor* forensics)
    safe = jnp.where(finite, xf, 0.0)
    absx = jnp.abs(safe)
    max_abs = jnp.max(absx)
    l2 = jnp.sqrt(jnp.sum(absx * absx))
    zero_frac = jnp.mean(((safe == 0.0) & finite).astype(jnp.float32))
    return jnp.stack([nonfinite, max_abs, l2, zero_frac])


def _numeric_summary_infer(graph, attrs, input_tensors):
    return [(shape_mod.TensorShape([STATS_WIDTH]), dtypes_mod.float32)]


# Pure (empty Effects): the stats vector is a function of its input
# only, so CSE/const-fold stay legal and loop_safety certifies it in a
# fused window like any other arithmetic — the whole point vs the
# CheckNumerics flag channel.
op_registry.register("NumericSummary", pure_fn=_numeric_summary_pure,
                     infer_fn=_numeric_summary_infer,
                     effects=op_registry.Effects())


def _numeric_summary_sharding(op, in_specs, ctx):
    from ..analysis.sharding import tensor_bytes  # noqa: PLC0415

    s = in_specs[0]
    if s:
        axes = tuple(sorted({a for dim in s for a in dim}))
        if axes:
            ctx.collective(
                "all-reduce", axes,
                float(tensor_bytes(op.outputs[0])),
                note="numeric-summary stats over sharded input",
                tensor_name=op.outputs[0].name)
    return [((),)]  # [4] vector, replicated


op_registry.register_sharding_rule("NumericSummary",
                                   _numeric_summary_sharding)


def numeric_summary(tensor, name=None):
    """Packed device-side health stats of ``tensor``: a float32 ``[4]``
    vector ``[nonfinite_count, max_abs, l2_norm, zero_fraction]``
    (stf.debug.numerics tap primitive; the tfdbg ``DebugNumericSummary``
    idea, ref: tensorflow/core/ops/debug_ops.cc, recast as a pure
    fusable graph op)."""
    x = ops_mod.convert_to_tensor(tensor)
    op = ops_mod.get_default_graph().create_op(
        "NumericSummary", [x], attrs={},
        name=name or "NumericSummary",
        output_specs=[(shape_mod.TensorShape([STATS_WIDTH]),
                       dtypes_mod.float32)])
    return op.outputs[0]


def verify_tensor_all_finite(t, msg, name=None):
    """(ref: numerics.py:32 ``verify_tensor_all_finite``)."""
    return array_ops.check_numerics(t, message=msg, name=name)


def add_check_numerics_ops():
    """(ref: numerics.py:51 ``add_check_numerics_ops``): wrap every
    floating-point tensor in the current graph with CheckNumerics; returns
    a group op. TPU-native, each CheckNumerics is fused into the step
    program (no extra launches)."""
    from . import control_flow_ops

    g = ops_mod.get_default_graph()
    checks = []
    for op in list(g.get_operations()):
        if op.type in ("CheckNumerics", "Placeholder", "Const"):
            continue
        for out in op.outputs:
            if out.dtype.is_floating:
                checks.append(array_ops.check_numerics(
                    out, message=f"{op.name}:{out.value_index}"))
    return control_flow_ops.group(*checks, name="check_numerics_all")
