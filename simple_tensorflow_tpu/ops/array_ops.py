"""Array ops (ref: tensorflow/python/ops/array_ops.py, core/kernels/
{concat_op,slice_op,strided_slice_op,pack_op,pad_op,gather_op,one_hot_op,...}.cc).

TPU notes: everything here must keep static shapes for XLA. Ops whose result
shape is data-dependent in the reference (boolean_mask, unique, where with
one arg) are supported only with statically-determinable sizes and raise
actionable errors otherwise — the reference's dynamic-shape behavior does not
exist on TPU hardware either (tf2xla has the same restriction).
"""

from __future__ import annotations

import builtins
import numpy as np

import jax
import jax.numpy as jnp

from ..framework import constant_op
from ..framework import dtypes as dtypes_mod
from ..framework import graph as ops_mod
from ..framework import op_registry
from ..framework import tensor_shape as shape_mod
from .op_util import make_op, unary

Tensor = ops_mod.Tensor
constant = constant_op.constant


# -- registrations -----------------------------------------------------------

op_registry.register_pure("Identity", lambda x: x)
op_registry.register_pure("Snapshot", lambda x: x)
# 64-bit out_types narrow through narrowed_if_no_x64 (one boundary
# warning per process instead of jax's per-callsite truncation warning;
# VERDICT weak #6, docs/MIGRATION.md "64-bit dtypes")
op_registry.register_pure("Shape", lambda x, out_type=None: jnp.asarray(
    x.shape, dtype=(dtypes_mod.narrowed_if_no_x64(out_type).np_dtype
                    if out_type else jnp.int32)))
op_registry.register_pure("Size", lambda x, out_type=None: jnp.asarray(
    x.size, dtype=(dtypes_mod.narrowed_if_no_x64(out_type).np_dtype
                   if out_type else jnp.int32)))
op_registry.register_pure("Rank", lambda x: jnp.asarray(x.ndim, dtype=jnp.int32))
op_registry.register_pure("Reshape", lambda x, shape: jnp.reshape(x, shape))
op_registry.register_pure("Transpose", lambda x, perm=None: jnp.transpose(x, perm))
op_registry.register_pure("ConjugateTranspose",
                          lambda x, perm=None: jnp.conj(jnp.transpose(x, perm)))
op_registry.register_pure("ExpandDims", lambda x, axis: jnp.expand_dims(x, axis))
op_registry.register_pure("Squeeze", lambda x, axis=None: jnp.squeeze(x, axis))
op_registry.register_pure("Fill", lambda value, dims=None: jnp.full(dims, value))
op_registry.register_pure("ZerosLike", lambda x: jnp.zeros_like(x))
op_registry.register_pure("OnesLike", lambda x: jnp.ones_like(x))
op_registry.register_pure("Concat", lambda *xs, axis: jnp.concatenate(xs, axis=axis))
op_registry.register_pure("Split", lambda x, num_or_sections, axis=0:
                          jnp.split(x, num_or_sections, axis=axis),
                          n_outputs=None)
op_registry.register_pure("Pack", lambda *xs, axis=0: jnp.stack(xs, axis=axis))
op_registry.register_pure("Unpack", lambda x, num, axis=0:
                          [jnp.squeeze(s, axis) for s in
                           jnp.split(x, num, axis=axis)], n_outputs=None)
op_registry.register_pure(
    "Pad", lambda x, paddings=None, mode="constant", constant_values=0:
    jnp.pad(x, paddings, mode=mode,
            **({"constant_values": constant_values} if mode == "constant" else {})))
op_registry.register_pure("Tile", lambda x, multiples: jnp.tile(x, multiples))
op_registry.register_pure("Slice", lambda x, begin=None, size=None:
                          jax.lax.slice(x, begin,
                                        [b + s for b, s in zip(begin, size)]))
op_registry.register_pure("Gather", lambda params, indices, axis=0:
                          jnp.take(params, indices, axis=axis))
op_registry.register_pure("GatherNd", lambda params, indices: params[
    tuple(indices[..., k] for k in builtins.range(indices.shape[-1]))])
op_registry.register_pure("ScatterNd", lambda indices, updates, shape=None:
                          jnp.zeros(shape, updates.dtype).at[
                              tuple(indices[..., k]
                                    for k in builtins.range(indices.shape[-1]))
                          ].add(updates))
op_registry.register_pure("OneHot", lambda indices, depth=None, on_value=1.0,
                          off_value=0.0, axis=-1, dtype=None:
                          _one_hot_impl(indices, depth, on_value, off_value,
                                        axis, dtype))
op_registry.register_pure("Select", lambda cond, x, y: jnp.where(cond, x, y))
op_registry.register_pure("Reverse", lambda x, axis: jnp.flip(x, axis))
op_registry.register_pure("ReverseSequence",
                          lambda x, seq_lengths, seq_axis=0, batch_axis=0:
                          _reverse_sequence_impl(x, seq_lengths, seq_axis,
                                                 batch_axis))
op_registry.register_pure("MatrixDiag", lambda x: _batched_diag(x))
op_registry.register_pure("MatrixDiagPart",
                          lambda x: jnp.diagonal(x, axis1=-2, axis2=-1))
op_registry.register_pure("MatrixSetDiag", lambda x, diag: _set_diag(x, diag))
op_registry.register_pure("MatrixBandPart",
                          lambda x, num_lower=-1, num_upper=-1:
                          _band_part(x, num_lower, num_upper))
op_registry.register_pure("Diag", lambda x: _tensor_diag(x))
op_registry.register_pure("DiagPart", lambda x: _tensor_diag_part(x))
op_registry.register_pure("InvertPermutation",
                          lambda x: jnp.zeros_like(x).at[x].set(
                              jnp.arange(x.shape[0], dtype=x.dtype)))
op_registry.register_pure("StopGradient", jax.lax.stop_gradient)
op_registry.register_pure("PreventGradient", jax.lax.stop_gradient)
op_registry.register("CheckNumerics",
                     lower=lambda ctx, op, inputs:
                     [_check_numerics_impl(ctx, op, inputs[0])],
                     infer_fn=lambda g, attrs, ins: [(ins[0].shape,
                                                      ins[0].dtype)])
op_registry.register_pure("StridedSlice", lambda x, *dyn, spec: _strided_impl(
    x, dyn, spec))
op_registry.register_pure("BroadcastTo", lambda x, shape: jnp.broadcast_to(x, shape))
op_registry.register_pure("BroadcastArgs", lambda s0, s1: jnp.asarray(
    np.broadcast_shapes(tuple(np.asarray(s0)), tuple(np.asarray(s1))),
    dtype=jnp.int32))
op_registry.register_pure("SpaceToBatchND", lambda x, block_shape, paddings:
                          _space_to_batch_nd(x, block_shape, paddings))
op_registry.register_pure("BatchToSpaceND", lambda x, block_shape, crops:
                          _batch_to_space_nd(x, block_shape, crops))
op_registry.register_pure("SpaceToDepth", lambda x, block_size:
                          _space_to_depth(x, block_size))
op_registry.register_pure("DepthToSpace", lambda x, block_size:
                          _depth_to_space(x, block_size))
op_registry.register_pure("ExtractImagePatches",
                          lambda x, ksizes, strides, rates, padding:
                          _extract_patches(x, ksizes, strides, rates, padding))
op_registry.register_pure("SequenceMask", lambda lengths, maxlen=None, dtype=None:
                          (jnp.arange(maxlen)[None, :] <
                           lengths[..., None]).astype(
                               dtype.np_dtype if dtype else jnp.bool_))


def _one_hot_impl(indices, depth, on_value, off_value, axis, dtype):
    np_dt = dtype.np_dtype if dtype is not None else jnp.float32
    oh = jax.nn.one_hot(indices, depth, axis=axis, dtype=np_dt)
    if on_value != 1.0 or off_value != 0.0:
        oh = oh * (on_value - off_value) + off_value
    return oh.astype(np_dt)


def _reverse_sequence_impl(x, seq_lengths, seq_axis, batch_axis):
    idx = jnp.arange(x.shape[seq_axis])
    # for each batch b: positions i < len reversed: len-1-i else i
    def fix(b_len):
        return jnp.where(idx < b_len, b_len - 1 - idx, idx)

    rev_idx = jax.vmap(fix)(seq_lengths)  # [B, T]
    x_m = jnp.moveaxis(x, (batch_axis, seq_axis), (0, 1))
    out = jax.vmap(lambda xb, ib: jnp.take(xb, ib, axis=0))(x_m, rev_idx)
    return jnp.moveaxis(out, (0, 1), (batch_axis, seq_axis))


def _batched_diag(x):
    eye = jnp.eye(x.shape[-1], dtype=x.dtype)
    return x[..., None] * eye


def _set_diag(x, diag):
    n = builtins.min(x.shape[-2], x.shape[-1])
    eye = jnp.eye(x.shape[-2], x.shape[-1], dtype=bool)
    d = _batched_diag(diag)
    pad = [(0, 0)] * diag.ndim + [(0, x.shape[-1] - diag.shape[-1])]
    dfull = jnp.zeros_like(x).at[..., :n, :n].set(d[..., :n, :n])
    return jnp.where(eye, dfull, x)


def _band_part(x, num_lower, num_upper):
    m, n = x.shape[-2], x.shape[-1]
    i = jnp.arange(m)[:, None]
    j = jnp.arange(n)[None, :]
    keep = jnp.ones((m, n), dtype=bool)
    if num_lower >= 0:
        keep &= (i - j) <= num_lower
    if num_upper >= 0:
        keep &= (j - i) <= num_upper
    return jnp.where(keep, x, jnp.zeros_like(x))


def _tensor_diag(x):
    flat = jnp.ravel(x)
    out = jnp.zeros((flat.size, flat.size), dtype=x.dtype).at[
        jnp.arange(flat.size), jnp.arange(flat.size)].set(flat)
    return jnp.reshape(out, x.shape + x.shape)


def _tensor_diag_part(x):
    k = x.ndim // 2
    lead = x.shape[:k]
    n = int(np.prod(lead))
    flat = jnp.reshape(x, (n, n))
    return jnp.reshape(jnp.diagonal(flat), lead)


def _check_numerics_impl(ctx, op, x):
    # In-graph numeric check (ref core/kernels/check_numerics_op.cc).
    # TPU-native: a hard device assert would stall the pipeline, so the
    # non-finite flag is computed in the compiled step (fuses with the
    # producer) and fetched with the results; the Session raises
    # InvalidArgumentError host-side when a flag is set. Inside lax control
    # flow / shard_map the flag cannot escape the trace — the check is a
    # pass-through there (matches XLA's structured-control-flow limits).
    message = op.attrs.get("message", "")
    if not jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
        return x
    if ctx.host:
        if not np.all(np.isfinite(np.asarray(x, np.float64))):
            from ..framework import errors

            raise errors.InvalidArgumentError(
                None, op, f"{message} : Tensor had NaN/Inf values")
        return x
    if ctx.in_control_flow or ctx.in_shard_map:
        return x
    flag = jnp.logical_not(jnp.all(jnp.isfinite(x)))
    ctx.numeric_checks.append(
        (f"CheckNumerics {op.name}: {message}: Tensor had NaN/Inf "
         "values", flag))
    return x


def _strided_impl(x, dyn_inputs, spec):
    idx = []
    di = iter(dyn_inputs)
    for item in spec:
        kind = item[0]
        if kind == "idx":
            idx.append(item[1])
        elif kind == "tensor_idx":
            idx.append(next(di))
        elif kind == "slice":
            idx.append(builtins.slice(item[1], item[2], item[3]))
        elif kind == "newaxis":
            idx.append(None)
        elif kind == "ellipsis":
            idx.append(Ellipsis)
    return x[tuple(idx)]


def _space_to_batch_nd(x, block_shape, paddings):
    block_shape = list(block_shape)
    pads = [(0, 0)] + [tuple(p) for p in paddings] + [(0, 0)]
    x = jnp.pad(x, pads)
    b = x.shape[0]
    spatial = x.shape[1:1 + len(block_shape)]
    rest = x.shape[1 + len(block_shape):]
    new_shape = [b]
    for s, bs in zip(spatial, block_shape):
        new_shape += [s // bs, bs]
    new_shape += rest
    x = jnp.reshape(x, new_shape)
    perm = []
    for i in builtins.range(len(block_shape)):
        perm.append(2 + 2 * i)
    perm.append(0)
    for i in builtins.range(len(block_shape)):
        perm.append(1 + 2 * i)
    perm += [len(new_shape) - len(rest) + i for i in builtins.range(len(rest))]
    x = jnp.transpose(x, perm)
    out_b = b * int(np.prod(block_shape))
    out_spatial = [s // bs for s, bs in zip(spatial, block_shape)]
    return jnp.reshape(x, [out_b] + out_spatial + list(rest))


def _batch_to_space_nd(x, block_shape, crops):
    block_shape = list(block_shape)
    prod_b = int(np.prod(block_shape))
    b = x.shape[0] // prod_b
    spatial = x.shape[1:1 + len(block_shape)]
    rest = x.shape[1 + len(block_shape):]
    x = jnp.reshape(x, block_shape + [b] + list(spatial) + list(rest))
    nb = len(block_shape)
    perm = [nb]
    for i in builtins.range(nb):
        perm += [nb + 1 + i, i]
    perm += [1 + 2 * nb + i for i in builtins.range(len(rest))]
    x = jnp.transpose(x, perm)
    x = jnp.reshape(x, [b] + [s * bs for s, bs in zip(spatial, block_shape)]
                    + list(rest))
    sl = [builtins.slice(None)]
    for (c0, c1), s, bs in zip([tuple(c) for c in crops], spatial, block_shape):
        sl.append(builtins.slice(c0, s * bs - c1))
    sl += [builtins.slice(None)] * len(rest)
    return x[tuple(sl)]


def _space_to_depth(x, bs):
    b, h, w, c = x.shape
    x = jnp.reshape(x, (b, h // bs, bs, w // bs, bs, c))
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return jnp.reshape(x, (b, h // bs, w // bs, bs * bs * c))


def _depth_to_space(x, bs):
    b, h, w, c = x.shape
    x = jnp.reshape(x, (b, h, w, bs, bs, c // (bs * bs)))
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return jnp.reshape(x, (b, h * bs, w * bs, c // (bs * bs)))


def _extract_patches(x, ksizes, strides, rates, padding):
    _, kh, kw, _ = ksizes
    _, sh, sw, _ = strides
    _, rh, rw, _ = rates
    b, h, w, c = x.shape
    patches = jax.lax.conv_general_dilated_patches(
        jnp.moveaxis(x, -1, 1), (kh, kw), (sh, sw), padding,
        rhs_dilation=(rh, rw))
    # patches: [B, C*kh*kw, H', W'] with channel-major ordering -> TF wants
    # [B, H', W', kh*kw*C] with patch-major ordering.
    bp, ck, hp, wp = patches.shape
    patches = jnp.reshape(patches, (bp, c, kh * kw, hp, wp))
    patches = jnp.transpose(patches, (0, 3, 4, 2, 1))
    return jnp.reshape(patches, (bp, hp, wp, kh * kw * c))


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def placeholder(dtype, shape=None, name=None):
    """(ref: python/ops/array_ops.py:1620 ``placeholder``)."""
    g = ops_mod.get_default_graph()
    dt = dtypes_mod.as_dtype(dtype)
    sh = shape_mod.as_shape(shape) if shape is not None else shape_mod.TensorShape(None)
    op = g.create_op("Placeholder", [], attrs={"dtype": dt, "shape": sh},
                     name=name or "Placeholder",
                     output_specs=[(sh, dt)])
    return op.outputs[0]


def placeholder_with_default(input, shape, name=None):  # noqa: A002
    x = ops_mod.convert_to_tensor(input)
    op = ops_mod.get_default_graph().create_op(
        "PlaceholderWithDefault", [x], attrs={},
        name=name or "PlaceholderWithDefault",
        output_specs=[(shape_mod.as_shape(shape), x.dtype)])
    return op.outputs[0]


def identity(input, name=None):  # noqa: A002
    return unary("Identity", input, name)


def stop_gradient(input, name=None):  # noqa: A002
    return unary("StopGradient", input, name)


def prevent_gradient(input, message="", name=None):  # noqa: A002
    return unary("PreventGradient", input, name)


def check_numerics(tensor, message="", name=None):
    return unary("CheckNumerics", tensor, name, attrs={"message": message})


def shape(input, name=None, out_type=dtypes_mod.int32):  # noqa: A002
    x = ops_mod.convert_to_tensor(input)
    return make_op("Shape", [x],
                   attrs={"out_type": dtypes_mod.as_dtype(out_type)}, name=name)


def shape_n(inputs, out_type=dtypes_mod.int32, name=None):
    return [shape(x, out_type=out_type) for x in inputs]


def size(input, name=None, out_type=dtypes_mod.int32):  # noqa: A002
    x = ops_mod.convert_to_tensor(input)
    return make_op("Size", [x],
                   attrs={"out_type": dtypes_mod.as_dtype(out_type)}, name=name)


def rank(input, name=None):  # noqa: A002
    return unary("Rank", input, name)


def reshape(tensor, shape, name=None):  # noqa: A002
    x = ops_mod.convert_to_tensor(tensor)
    sh = _static_shape_arg(shape, "reshape")
    return make_op("Reshape", [x], attrs={"shape": sh}, name=name)


def _static_shape_arg(shape, what):
    if isinstance(shape, shape_mod.TensorShape):
        return tuple(shape.as_list())
    if isinstance(shape, Tensor):
        v = constant_op.constant_value(shape)
        if v is None:
            raise ValueError(
                f"stf.{what}: target shape must be static on TPU (XLA "
                "requires static shapes); use -1 for one inferred dim.")
        return tuple(int(d) for d in np.ravel(v))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(d) for d in shape)


def transpose(a, perm=None, name=None, conjugate=False):
    x = ops_mod.convert_to_tensor(a)
    if perm is not None:
        perm = tuple(int(p) for p in
                     (constant_op.constant_value(perm) if isinstance(perm, Tensor)
                      else perm))
    t = "ConjugateTranspose" if conjugate and x.dtype.is_complex else "Transpose"
    return make_op(t, [x], attrs={"perm": perm}, name=name)


def matrix_transpose(a, name=None, conjugate=False):
    x = ops_mod.convert_to_tensor(a)
    r = x.shape.rank
    if r is None:
        raise ValueError("matrix_transpose needs known rank")
    perm = tuple(builtins.range(r - 2)) + (r - 1, r - 2)
    return transpose(x, perm, name=name, conjugate=conjugate)


def expand_dims(input, axis=None, name=None, dim=None):  # noqa: A002
    if dim is not None and axis is None:
        axis = dim
    x = ops_mod.convert_to_tensor(input)
    return make_op("ExpandDims", [x], attrs={"axis": int(axis)}, name=name)


def squeeze(input, axis=None, name=None, squeeze_dims=None):  # noqa: A002
    if squeeze_dims is not None and axis is None:
        axis = squeeze_dims
    x = ops_mod.convert_to_tensor(input)
    if axis is not None and not isinstance(axis, (list, tuple)):
        axis = [axis]
    return make_op("Squeeze", [x],
                   attrs={"axis": tuple(int(a) for a in axis) if axis is not None
                          else None}, name=name)


def zeros(shape, dtype=dtypes_mod.float32, name=None):
    dt = dtypes_mod.as_dtype(dtype)
    sh = _static_shape_arg(shape, "zeros")
    return constant(np.zeros(sh, dtype=dt.np_dtype), name=name or "zeros")


def ones(shape, dtype=dtypes_mod.float32, name=None):
    dt = dtypes_mod.as_dtype(dtype)
    sh = _static_shape_arg(shape, "ones")
    return constant(np.ones(sh, dtype=dt.np_dtype), name=name or "ones")


def fill(dims, value, name=None):
    sh = _static_shape_arg(dims, "fill")
    v = ops_mod.convert_to_tensor(value)
    return make_op("Fill", [v],
                   attrs={"dims": sh},
                   name=name)


op_registry._REGISTRY.pop("Fill", None)
op_registry.register_pure("Fill", lambda value, dims=None: jnp.full(
    dims, value))


def zeros_like(tensor, dtype=None, name=None, optimize=True):
    x = ops_mod.convert_to_tensor(tensor)
    out = unary("ZerosLike", x, name)
    if dtype is not None and dtypes_mod.as_dtype(dtype) != x.dtype.base_dtype:
        from . import math_ops

        out = math_ops.cast(out, dtype)
    return out


def ones_like(tensor, dtype=None, name=None, optimize=True):
    x = ops_mod.convert_to_tensor(tensor)
    out = unary("OnesLike", x, name)
    if dtype is not None and dtypes_mod.as_dtype(dtype) != x.dtype.base_dtype:
        from . import math_ops

        out = math_ops.cast(out, dtype)
    return out


def concat(values, axis, name="concat"):
    if not isinstance(values, (list, tuple)):
        values = [values]
    tensors = [ops_mod.convert_to_tensor(v) for v in values]
    if len(tensors) == 1:
        return identity(tensors[0], name=name)
    if isinstance(axis, Tensor):
        axis = int(constant_op.constant_value(axis))
    return make_op("Concat", tensors, attrs={"axis": int(axis)}, name=name)


def split(value, num_or_size_splits, axis=0, num=None, name="split"):
    x = ops_mod.convert_to_tensor(value)
    if isinstance(num_or_size_splits, Tensor):
        v = constant_op.constant_value(num_or_size_splits)
        if v is None:
            raise ValueError("split sizes must be static on TPU")
        num_or_size_splits = v.tolist() if v.ndim else int(v)
    if isinstance(num_or_size_splits, (list, tuple)):
        sizes = [int(s) for s in num_or_size_splits]
        bounds = np.cumsum(sizes)[:-1].tolist()
        n_out = len(sizes)
        arg = bounds
    else:
        n_out = int(num_or_size_splits)
        arg = n_out
    return make_op("Split", [x], attrs={"num_or_sections": arg,
                                        "axis": int(axis)},
                   name=name, n_out=n_out)


def stack(values, axis=0, name="stack"):
    tensors = [ops_mod.convert_to_tensor(v) for v in values]
    return make_op("Pack", tensors, attrs={"axis": int(axis)}, name=name)


pack = stack


def unstack(value, num=None, axis=0, name="unstack"):
    x = ops_mod.convert_to_tensor(value)
    if num is None:
        if x.shape.rank is None or x.shape[axis].value is None:
            raise ValueError("Cannot infer num from shape; pass num")
        num = x.shape[axis].value
    return make_op("Unpack", [x], attrs={"num": int(num), "axis": int(axis)},
                   name=name, n_out=int(num))


unpack = unstack


def pad(tensor, paddings, mode="CONSTANT", name=None, constant_values=0):
    x = ops_mod.convert_to_tensor(tensor)
    if isinstance(paddings, Tensor):
        v = constant_op.constant_value(paddings)
        if v is None:
            raise ValueError("paddings must be static on TPU")
        paddings = v
    paddings = tuple(tuple(int(p) for p in row) for row in np.asarray(paddings))
    mode_l = {"CONSTANT": "constant", "REFLECT": "reflect",
              "SYMMETRIC": "symmetric"}[mode.upper()]
    return make_op("Pad", [x], attrs={"paddings": paddings, "mode": mode_l,
                                      "constant_values": constant_values},
                   name=name)


op_registry._REGISTRY.pop("Pad", None)
op_registry.register_pure(
    "Pad", lambda x, paddings=None, mode="constant", constant_values=0:
    jnp.pad(x, paddings, mode=mode,
            **({"constant_values": constant_values} if mode == "constant" else {})))


def tile(input, multiples, name=None):  # noqa: A002
    x = ops_mod.convert_to_tensor(input)
    if isinstance(multiples, Tensor):
        v = constant_op.constant_value(multiples)
        if v is None:
            raise ValueError("multiples must be static on TPU")
        multiples = v
    return make_op("Tile", [x],
                   attrs={"multiples": tuple(int(m) for m in np.ravel(multiples))},
                   name=name)


def slice(input_, begin, size, name=None):  # noqa: A001
    x = ops_mod.convert_to_tensor(input_)
    bv = constant_op.constant_value(ops_mod.convert_to_tensor(begin))
    sv = constant_op.constant_value(ops_mod.convert_to_tensor(size))
    if bv is None or sv is None:
        raise ValueError("stf.slice begin/size must be static on TPU; "
                         "use dynamic_slice via __getitem__ with tensors.")
    begin = [int(b) for b in np.ravel(bv)]
    size = [int(s) for s in np.ravel(sv)]
    size = [x.shape[i].value - begin[i] if s == -1 else s
            for i, s in enumerate(size)]
    return make_op("Slice", [x], attrs={"begin": tuple(begin),
                                        "size": tuple(size)}, name=name)


def strided_slice(input_, begin, end, strides=None, begin_mask=0, end_mask=0,
                  ellipsis_mask=0, new_axis_mask=0, shrink_axis_mask=0,
                  name=None):
    # Reference-compatible entry; builds a python slice spec.
    bv = constant_op.constant_value(ops_mod.convert_to_tensor(begin))
    ev = constant_op.constant_value(ops_mod.convert_to_tensor(end))
    strv = (constant_op.constant_value(ops_mod.convert_to_tensor(strides))
            if strides is not None else np.ones_like(bv))
    if bv is None or ev is None or strv is None:
        raise ValueError("strided_slice bounds must be static on TPU")
    spec = []
    for i, (b, e, s) in enumerate(zip(np.ravel(bv), np.ravel(ev), np.ravel(strv))):
        if shrink_axis_mask & (1 << i):
            spec.append(("idx", int(b)))
        elif new_axis_mask & (1 << i):
            spec.append(("newaxis",))
        elif ellipsis_mask & (1 << i):
            spec.append(("ellipsis",))
        else:
            bb = None if begin_mask & (1 << i) else int(b)
            ee = None if end_mask & (1 << i) else int(e)
            spec.append(("slice", bb, ee, int(s)))
    x = ops_mod.convert_to_tensor(input_)
    return make_op("StridedSlice", [x], attrs={"spec": tuple(spec)}, name=name)


def _slice_helper(tensor, sl):
    """Tensor.__getitem__ (ref: array_ops.py:478 ``_SliceHelper``)."""
    if not isinstance(sl, tuple):
        sl = (sl,)
    spec = []
    dyn = []
    for item in sl:
        if isinstance(item, builtins.slice):
            def stat(v):
                if v is None:
                    return None
                if isinstance(v, Tensor):
                    c = constant_op.constant_value(v)
                    if c is None:
                        raise ValueError(
                            "Slice bounds must be static on TPU; for dynamic "
                            "windows use stf.gather / lax-style dynamic slice.")
                    return int(c)
                return int(v)

            spec.append(("slice", stat(item.start), stat(item.stop),
                         stat(item.step)))
        elif item is Ellipsis:
            spec.append(("ellipsis",))
        elif item is None:
            spec.append(("newaxis",))
        elif isinstance(item, Tensor):
            c = constant_op.constant_value(item)
            if c is not None and c.ndim == 0:
                spec.append(("idx", int(c)))
            else:
                spec.append(("tensor_idx",))
                dyn.append(item)
        else:
            spec.append(("idx", int(item)))
    return make_op("StridedSlice", [tensor] + dyn, attrs={"spec": tuple(spec)})


Tensor.__getitem__ = _slice_helper


def gather(params, indices, validate_indices=None, name=None, axis=0):
    from . import variables as variables_mod

    if isinstance(params, variables_mod.Variable):
        params = params._ref
    params = ops_mod.convert_to_tensor(params)
    indices = ops_mod.convert_to_tensor(indices)
    if isinstance(axis, Tensor):
        axis = int(constant_op.constant_value(axis))
    return make_op("Gather", [params, indices], attrs={"axis": int(axis)},
                   name=name)


def gather_nd(params, indices, name=None):
    params = ops_mod.convert_to_tensor(params)
    indices = ops_mod.convert_to_tensor(indices)
    return make_op("GatherNd", [params, indices], name=name)


def scatter_nd(indices, updates, shape, name=None):
    indices = ops_mod.convert_to_tensor(indices)
    updates = ops_mod.convert_to_tensor(updates)
    sh = _static_shape_arg(shape, "scatter_nd")
    return make_op("ScatterNd", [indices, updates], attrs={"shape": sh},
                   name=name)


def one_hot(indices, depth, on_value=None, off_value=None, axis=None,
            dtype=None, name=None):
    indices = ops_mod.convert_to_tensor(indices)
    if isinstance(depth, Tensor):
        depth = int(constant_op.constant_value(depth))
    dt = dtypes_mod.as_dtype(dtype) if dtype is not None else dtypes_mod.float32
    return make_op("OneHot", [indices],
                   attrs={"depth": int(depth),
                          "on_value": 1.0 if on_value is None else on_value,
                          "off_value": 0.0 if off_value is None else off_value,
                          "axis": -1 if axis is None else int(axis),
                          "dtype": dt},
                   name=name)


def where(condition, x=None, y=None, name=None):
    condition = ops_mod.convert_to_tensor(condition)
    if x is None and y is None:
        cv = constant_op.constant_value(condition)
        if cv is None:
            raise ValueError(
                "stf.where(cond) with one argument has a data-dependent "
                "output shape, which XLA/TPU cannot compile (same limit as "
                "the reference's tf2xla bridge). Use where(cond, x, y) or a "
                "static condition.")
        return constant(np.argwhere(cv).astype(np.int64), name=name or "Where")
    if x is None or y is None:
        raise ValueError("x and y must both be set or both None")
    from .op_util import promote_args

    x, y = promote_args(x, y, "Select")
    return make_op("Select", [condition, x, y], name=name)


select = where


def boolean_mask(tensor, mask, name="boolean_mask", axis=None):
    mv = constant_op.constant_value(ops_mod.convert_to_tensor(mask))
    if mv is None:
        raise ValueError(
            "boolean_mask with a dynamic mask produces a data-dependent "
            "shape, which TPU/XLA cannot compile (the reference's tf2xla "
            "bridge has the same limit). Use stf.where + multiply, or a "
            "static mask.")
    idx = np.nonzero(np.ravel(mv) if axis is None else mv)[0]
    t = ops_mod.convert_to_tensor(tensor)
    if axis is None and mv.ndim > 1:
        lead = int(np.prod(mv.shape))
        t = reshape(t, (lead,) + tuple(t.shape.as_list()[mv.ndim:]))
    return gather(t, constant(idx.astype(np.int32)), axis=axis or 0, name=name)


def reverse(tensor, axis, name=None):
    x = ops_mod.convert_to_tensor(tensor)
    if isinstance(axis, Tensor):
        axis = constant_op.constant_value(axis)
    axis = tuple(int(a) for a in np.ravel(axis))
    return make_op("Reverse", [x], attrs={"axis": axis}, name=name)


def reverse_v2(tensor, axis, name=None):
    return reverse(tensor, axis, name)


def reverse_sequence(input, seq_lengths, seq_axis=None, batch_axis=None,  # noqa: A002
                     name=None, seq_dim=None, batch_dim=None):
    seq_axis = seq_axis if seq_axis is not None else seq_dim
    batch_axis = batch_axis if batch_axis is not None else (batch_dim or 0)
    x = ops_mod.convert_to_tensor(input)
    sl = ops_mod.convert_to_tensor(seq_lengths)
    return make_op("ReverseSequence", [x, sl],
                   attrs={"seq_axis": int(seq_axis),
                          "batch_axis": int(batch_axis)}, name=name)


def sequence_mask(lengths, maxlen=None, dtype=dtypes_mod.bool_, name=None):
    lengths = ops_mod.convert_to_tensor(lengths)
    if maxlen is None:
        v = constant_op.constant_value(lengths)
        if v is None:
            raise ValueError("sequence_mask needs static maxlen on TPU")
        maxlen = int(np.max(v))
    elif isinstance(maxlen, Tensor):
        maxlen = int(constant_op.constant_value(maxlen))
    return make_op("SequenceMask", [lengths],
                   attrs={"maxlen": int(maxlen),
                          "dtype": dtypes_mod.as_dtype(dtype)}, name=name)


def matrix_diag(diagonal, name=None):
    return unary("MatrixDiag", diagonal, name)


def matrix_diag_part(input, name=None):  # noqa: A002
    return unary("MatrixDiagPart", input, name)


def matrix_set_diag(input, diagonal, name=None):  # noqa: A002
    x = ops_mod.convert_to_tensor(input)
    d = ops_mod.convert_to_tensor(diagonal)
    return make_op("MatrixSetDiag", [x, d], name=name)


def matrix_band_part(input, num_lower, num_upper, name=None):  # noqa: A002
    x = ops_mod.convert_to_tensor(input)
    return make_op("MatrixBandPart", [x],
                   attrs={"num_lower": int(num_lower),
                          "num_upper": int(num_upper)}, name=name)


def diag(diagonal, name=None):
    return unary("Diag", diagonal, name)


def diag_part(input, name=None):  # noqa: A002
    return unary("DiagPart", input, name)


def eye(num_rows, num_columns=None, batch_shape=None,
        dtype=dtypes_mod.float32, name=None):
    m = np.eye(num_rows, num_columns, dtype=dtypes_mod.as_dtype(dtype).np_dtype)
    if batch_shape:
        m = np.broadcast_to(m, tuple(batch_shape) + m.shape)
    return constant(m, name=name or "eye")


def invert_permutation(x, name=None):
    return unary("InvertPermutation", x, name)


def broadcast_to(input, shape, name=None):  # noqa: A002
    x = ops_mod.convert_to_tensor(input)
    return make_op("BroadcastTo", [x],
                   attrs={"shape": _static_shape_arg(shape, "broadcast_to")},
                   name=name)


def space_to_batch_nd(input, block_shape, paddings, name=None):  # noqa: A002
    x = ops_mod.convert_to_tensor(input)
    bs = tuple(int(b) for b in np.ravel(
        constant_op.constant_value(ops_mod.convert_to_tensor(block_shape))))
    pd = tuple(tuple(int(p) for p in row) for row in
               constant_op.constant_value(ops_mod.convert_to_tensor(paddings)))
    return make_op("SpaceToBatchND", [x], attrs={"block_shape": bs,
                                                 "paddings": pd}, name=name)


def batch_to_space_nd(input, block_shape, crops, name=None):  # noqa: A002
    x = ops_mod.convert_to_tensor(input)
    bs = tuple(int(b) for b in np.ravel(
        constant_op.constant_value(ops_mod.convert_to_tensor(block_shape))))
    cr = tuple(tuple(int(c) for c in row) for row in
               constant_op.constant_value(ops_mod.convert_to_tensor(crops)))
    return make_op("BatchToSpaceND", [x], attrs={"block_shape": bs,
                                                 "crops": cr}, name=name)


def space_to_depth(input, block_size, name=None):  # noqa: A002
    x = ops_mod.convert_to_tensor(input)
    return make_op("SpaceToDepth", [x], attrs={"block_size": int(block_size)},
                   name=name)


def depth_to_space(input, block_size, name=None):  # noqa: A002
    x = ops_mod.convert_to_tensor(input)
    return make_op("DepthToSpace", [x], attrs={"block_size": int(block_size)},
                   name=name)


def extract_image_patches(images, ksizes, strides, rates, padding, name=None):
    x = ops_mod.convert_to_tensor(images)
    return make_op("ExtractImagePatches", [x],
                   attrs={"ksizes": tuple(ksizes), "strides": tuple(strides),
                          "rates": tuple(rates), "padding": padding},
                   name=name)


def unique(x, out_idx=dtypes_mod.int32, name=None):
    xv = constant_op.constant_value(ops_mod.convert_to_tensor(x))
    if xv is None:
        raise ValueError(
            "stf.unique has a data-dependent output shape; on TPU it is only "
            "supported for statically-known inputs (tf2xla parity).")
    vals, idx = np.unique(xv, return_inverse=True)
    return (constant(vals), constant(idx.astype(
        dtypes_mod.as_dtype(out_idx).np_dtype)))


def setdiff1d(x, y, index_dtype=dtypes_mod.int32, name=None):
    xv = constant_op.constant_value(ops_mod.convert_to_tensor(x))
    yv = constant_op.constant_value(ops_mod.convert_to_tensor(y))
    if xv is None or yv is None:
        raise ValueError("setdiff1d needs static inputs on TPU")
    out = np.setdiff1d(xv, yv, assume_unique=False)
    idx = np.asarray([np.where(xv == o)[0][0] for o in out])
    return constant(out), constant(idx.astype(
        dtypes_mod.as_dtype(index_dtype).np_dtype))


def _levenshtein(a, b):
    if len(a) == 0:
        return len(b)
    if len(b) == 0:
        return len(a)
    prev = list(builtins.range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i]
        for j, cb in enumerate(b, 1):
            cur.append(builtins.min(prev[j] + 1, cur[j - 1] + 1,
                                    prev[j - 1] + (ca != cb)))
        prev = cur
    return prev[-1]


def _lower_edit_distance(ctx, op, inputs):
    """Host-stage Levenshtein over COO sequence batches (the reference
    computes this on CPU too — ref core/kernels/edit_distance_op.cc).
    Sequences are grouped by their leading index dims; the last index dim
    is the position within the sequence."""
    h_idx, h_val, h_shape, t_idx, t_val, t_shape = (
        np.asarray(v) for v in inputs)
    normalize = bool(op.attrs.get("normalize", True))
    out_shape = builtins.tuple(
        int(d) for d in np.maximum(h_shape[:-1], t_shape[:-1]))

    def group(idx, val):
        seqs = {}
        order = np.lexsort(idx.T[::-1]) if len(idx) else []
        for r in order:
            key = builtins.tuple(int(x) for x in idx[r][:-1])
            seqs.setdefault(key, []).append(val[r])
        return seqs

    h_seqs = group(h_idx.reshape(-1, builtins.max(1, h_idx.shape[-1])
                                 if h_idx.ndim > 1 else 1), h_val)
    t_seqs = group(t_idx.reshape(-1, builtins.max(1, t_idx.shape[-1])
                                 if t_idx.ndim > 1 else 1), t_val)
    # slots with no entries in EITHER input are 0.0 (reference semantics:
    # edit_distance_op.cc zero-fills and only writes populated groups)
    out = np.zeros(out_shape, np.float32)
    for key in builtins.set(h_seqs) | builtins.set(t_seqs):
        h = h_seqs.get(key, [])
        t = t_seqs.get(key, [])
        d = builtins.float(_levenshtein(h, t))
        if normalize:
            d = d / len(t) if len(t) else (np.inf if len(h) else 0.0)
        out[key] = d
    return [out]


op_registry.register("EditDistance", lower=_lower_edit_distance,
                     runs_on_host=True)


def edit_distance(hypothesis, truth, normalize=True, name="edit_distance"):
    """(ref: python/ops/array_ops.py ``edit_distance``,
    core/kernels/edit_distance_op.cc). Host-stage op: Levenshtein distance
    between corresponding sequences of two SparseTensors with static
    dense_shape ranks; output shape is the leading dims of dense_shape
    (which must be statically known — XLA shapes are compile-time)."""
    from ..framework.sparse_tensor import SparseTensor

    hyp = SparseTensor.from_value(hypothesis)
    tru = SparseTensor.from_value(truth)
    h_shp = constant_op.constant_value(hyp.dense_shape)
    t_shp = constant_op.constant_value(tru.dense_shape)
    if h_shp is None or t_shp is None:
        raise ValueError(
            "edit_distance needs statically-known dense_shapes on TPU "
            "(the output shape is derived from them at graph-build time)")
    out_shape = [int(d) for d in np.maximum(np.asarray(h_shp)[:-1],
                                            np.asarray(t_shp)[:-1])]
    g = ops_mod.get_default_graph()
    op = g.create_op(
        "EditDistance",
        [hyp.indices, hyp.values, hyp.dense_shape,
         tru.indices, tru.values, tru.dense_shape],
        attrs={"normalize": builtins.bool(normalize)}, name=name,
        output_specs=[(shape_mod.TensorShape(out_shape),
                       dtypes_mod.float32)])
    return op.outputs[0]


def meshgrid(*args, **kwargs):
    """(ref: python/ops/array_ops.py ``meshgrid``). Static inputs fold to
    constants; dynamic inputs build via reshape + broadcast (shapes are
    static, only values are runtime — XLA-legal)."""
    indexing = kwargs.get("indexing", "xy")
    if indexing not in ("xy", "ij"):
        raise ValueError(f"indexing must be 'xy' or 'ij': {indexing}")
    tensors = [ops_mod.convert_to_tensor(a) for a in args]
    vals = [constant_op.constant_value(t) for t in tensors]
    if all(v is not None for v in vals):
        grids = np.meshgrid(*vals, indexing=indexing)
        return [constant(g) for g in grids]
    n = len(tensors)
    sizes = []
    for t in tensors:
        dims = t.shape.as_list()
        if len(dims) != 1 or dims[0] is None:
            raise ValueError(
                "meshgrid with runtime values needs 1-D inputs of static "
                f"length on TPU (got shape {t.shape})")
        sizes.append(dims[0])
    order = list(range(n))
    if indexing == "xy" and n >= 2:
        order[0], order[1] = order[1], order[0]
    # grid shape: dimension j of the output varies with input order[j]
    grid_shape = [sizes[i] for i in order]
    outs = []
    for idx, t in enumerate(tensors):
        axis = order.index(idx)
        shp = [1] * n
        shp[axis] = sizes[idx]
        outs.append(broadcast_to(reshape(t, shp), grid_shape))
    return outs


def required_space_to_batch_paddings(input_shape, block_shape,
                                     base_paddings=None):
    """(ref: python/ops/array_ops.py ``required_space_to_batch_paddings``).
    Computes (paddings, crops) so that input + paddings is divisible by
    block_shape; batch_to_space with `crops` undoes the padding. Static
    arithmetic (XLA shapes are compile-time)."""
    ishape = constant_op.constant_value(
        ops_mod.convert_to_tensor(input_shape))
    bshape = constant_op.constant_value(
        ops_mod.convert_to_tensor(block_shape))
    if ishape is None or bshape is None:
        raise ValueError(
            "required_space_to_batch_paddings needs static shapes on TPU")
    ishape = np.asarray(ishape, np.int64).ravel()
    bshape = np.asarray(bshape, np.int64).ravel()
    if base_paddings is None:
        base = np.zeros((len(ishape), 2), np.int64)
    else:
        base = np.asarray(
            constant_op.constant_value(
                ops_mod.convert_to_tensor(base_paddings)),
            np.int64).reshape(len(ishape), 2)
    pad_start = base[:, 0]
    full = ishape + pad_start + base[:, 1]
    rem = (-full) % bshape
    pad_end = base[:, 1] + rem
    paddings = np.stack([pad_start, pad_end], axis=1)
    crops = np.stack([np.zeros_like(rem), rem], axis=1)
    return constant(paddings), constant(crops)


def guarantee_const(input, name=None):  # noqa: A002
    return identity(input, name)


def newaxis():
    return None


# -- round-4 parity fills ----------------------------------------------------

def broadcast_static_shape(shape_x, shape_y):
    """(ref: array_ops.py ``broadcast_static_shape``)."""
    a = shape_mod.as_shape(shape_x)
    b = shape_mod.as_shape(shape_y)
    if a.rank is None or b.rank is None:
        return shape_mod.TensorShape(None)
    out = list(np.broadcast_shapes(
        tuple(1 if d is None else d for d in a.as_list()),
        tuple(1 if d is None else d for d in b.as_list())))
    return shape_mod.TensorShape(out)


def broadcast_dynamic_shape(shape_x, shape_y, name=None):
    """(ref: array_ops.py ``broadcast_dynamic_shape``). Shapes are static
    on TPU, so this folds at construction when both are constants."""
    sx = constant_op.constant_value(ops_mod.convert_to_tensor(shape_x))
    sy = constant_op.constant_value(ops_mod.convert_to_tensor(shape_y))
    if sx is None or sy is None:
        raise ValueError("broadcast_dynamic_shape needs static shape "
                         "tensors on TPU")
    return constant(np.asarray(np.broadcast_shapes(tuple(sx), tuple(sy)),
                               np.int32))


def parallel_stack(values, name=None):
    """(ref: array_ops.py ``parallel_stack``) — the parallel/sequential
    distinction is a CPU-executor scheduling detail; under XLA both
    compile to the same fused concat."""
    return stack(values, axis=0, name=name or "parallel_stack")


def space_to_batch(input, paddings, block_size, name=None):  # noqa: A002
    """2D-specialized wrapper (ref: array_ops.py ``space_to_batch``)."""
    return space_to_batch_nd(input, [block_size, block_size], paddings,
                             name=name)


def batch_to_space(input, crops, block_size, name=None):  # noqa: A002
    return batch_to_space_nd(input, [block_size, block_size], crops,
                             name=name)


def unique_with_counts(x, out_idx=dtypes_mod.int32, name=None):
    """(ref: array_ops.py ``unique_with_counts``) — static inputs only
    (data-dependent output size, tf2xla parity; same rule as unique)."""
    xv = constant_op.constant_value(ops_mod.convert_to_tensor(x))
    if xv is None:
        raise ValueError(
            "stf.unique_with_counts has a data-dependent output shape; on "
            "TPU it is only supported for statically-known inputs.")
    vals, idx, counts = np.unique(xv, return_inverse=True,
                                  return_counts=True)
    np_idx = dtypes_mod.as_dtype(out_idx).np_dtype
    return (constant(vals), constant(idx.astype(np_idx)),
            constant(counts.astype(np_idx)))


# ---------------------------------------------------------------------------
# sharding propagation rules (stf.analysis.sharding; ISSUE 6)
# ---------------------------------------------------------------------------

from ..analysis import sharding as _shard  # noqa: E402

_shard.register_rules(_shard.passthrough_rule,
                      "Identity", "Snapshot", "StopGradient",
                      "PreventGradient", "CheckNumerics", "ZerosLike",
                      "OnesLike")
# shape introspection reads metadata, not data: no gather of the operand
_shard.register_rules(_shard.local_rule, "Shape", "Size", "Rank",
                      "BroadcastArgs", "InvertPermutation",
                      "SequenceMask", "Fill")
_shard.register_rules(_shard.reshape_rule, "Reshape")
_shard.register_rules(_shard.transpose_rule, "Transpose",
                      "ConjugateTranspose")
_shard.register_rules(_shard.expand_dims_rule, "ExpandDims")
_shard.register_rules(_shard.squeeze_rule, "Squeeze")
_shard.register_rules(_shard.make_concat_rule("axis"), "Concat")
_shard.register_rules(_shard.make_stack_rule("axis"), "Pack")
_shard.register_rules(_shard.make_unstack_rule("axis"), "Unpack")
_shard.register_rules(_shard.make_axis_unsharded_rule("axis"), "Split")
_shard.register_rules(_shard.make_slice_rule(),
                      "Slice", "StridedSlice", "Pad", "MirrorPad", "Tile",
                      "Reverse", "ReverseSequence", "BroadcastTo",
                      "MatrixBandPart", "MatrixSetDiag")
_shard.register_rules(_shard.make_gather_rule("axis"), "Gather")
_shard.register_rules(_shard.elementwise_rule, "Select")


def _onehot_rule(op, in_specs, ctx):
    # indices dims pass through; the new class dim is unsharded
    s = in_specs[0]
    r = _shard._out_rank(op)
    if s is None or r is None:
        return [_shard.replicated(r)]
    ax = int(op.attrs.get("axis", -1))
    ax = ax % r
    out = list(s)
    out.insert(ax, ())
    return [tuple(out[:r])]


_shard.register_rules(_onehot_rule, "OneHot")
