"""make_template (ref: tensorflow/python/ops/template.py): wrap a function so
its variables are created once and reused on later calls."""

from __future__ import annotations

from . import variable_scope as vs


class Template:
    def __init__(self, name, func, create_scope_now=False, unique_name=None,
                 custom_getter=None):
        self._func = func
        self._name = name
        self._unique_name = unique_name
        self._custom_getter = custom_getter
        self._scope_name = None
        self._called = False

    def __call__(self, *args, **kwargs):
        if not self._called:
            self._called = True
            with vs.variable_scope(self._unique_name or self._name,
                                   custom_getter=self._custom_getter) as scope:
                self._scope_name = scope.name
                return self._func(*args, **kwargs)
        with vs.variable_scope(vs.VariableScope(self._scope_name, None,
                                                reuse=True,
                                                custom_getter=self._custom_getter)):
            return self._func(*args, **kwargs)

    @property
    def variable_scope_name(self):
        return self._scope_name

    @property
    def name(self):
        return self._name


def make_template(name, func, create_scope_now_=False, unique_name_=None,
                  custom_getter_=None, **kwargs):
    if kwargs:
        import functools

        func = functools.partial(func, **kwargs)
    return Template(name, func, create_scope_now_, unique_name_, custom_getter_)
