"""Queues + dataflow ops (ref: tensorflow/python/ops/data_flow_ops.py,
core/kernels/{fifo_queue,random_shuffle_queue_op,dynamic_stitch_op,
dynamic_partition_op}.cc).

TPU-native split: queues are HOST-stage objects (the reference pins queue
kernels to CPU too) driven by QueueRunner threads; dequeued numpy batches
become boundary feeds of the compiled device step. dynamic_stitch/partition
are device ops (static shapes).
"""

from __future__ import annotations

import builtins
import queue as py_queue
import threading
import weakref

import numpy as np

import jax.numpy as jnp

from ..framework import dtypes as dtypes_mod
from ..platform import sync as _sync
from ..framework import errors
from ..framework import graph as ops_mod
from ..framework import op_registry
from ..framework import tensor_shape as shape_mod
from .op_util import make_op


# -- device ops --------------------------------------------------------------

op_registry.register_pure(
    "DynamicPartition",
    lambda data, partitions, num_partitions=2: [
        jnp.where((partitions == i)[(...,) + (None,) * (data.ndim - partitions.ndim)],
                  data, jnp.zeros_like(data))
        for i in builtins.range(num_partitions)], n_outputs=None)


def _dynamic_stitch_impl(*args, n):
    indices = args[:n]
    data = args[n:]
    total = builtins.max(int(np.max(np.asarray(i.shape))) for i in indices)
    # size = max index + 1 must be static: use sum of sizes
    size = builtins.sum(int(np.prod(i.shape)) for i in indices)
    out_shape = (size,) + data[0].shape[indices[0].ndim:]
    out = jnp.zeros(out_shape, data[0].dtype)
    for idx, d in zip(indices, data):
        flat_idx = jnp.reshape(idx, (-1,))
        flat_d = jnp.reshape(d, (-1,) + out_shape[1:])
        out = out.at[flat_idx].set(flat_d)
    return out


op_registry.register_pure("DynamicStitch", _dynamic_stitch_impl)


def dynamic_partition(data, partitions, num_partitions, name=None):
    """Masked dense partitions (XLA-static; rows not in partition i are
    zero). The reference returns ragged pieces — impossible with static
    shapes; masking gives the common all-reduce/sum use-cases the same
    result."""
    data = ops_mod.convert_to_tensor(data)
    partitions = ops_mod.convert_to_tensor(partitions)
    return make_op("DynamicPartition", [data, partitions],
                   attrs={"num_partitions": int(num_partitions)},
                   name=name, n_out=int(num_partitions))


def dynamic_stitch(indices, data, name=None):
    idx_t = [ops_mod.convert_to_tensor(i, dtype=dtypes_mod.int32)
             for i in indices]
    data_t = [ops_mod.convert_to_tensor(d) for d in data]
    return make_op("DynamicStitch", idx_t + data_t,
                   attrs={"n": len(idx_t)}, name=name)


# -- host queues -------------------------------------------------------------

class QueueBase:
    """(ref: data_flow_ops.py:96 ``class QueueBase``). Host object; its
    graph presence is a set of host ops keyed by queue name."""

    _registry = {}
    _counter = [0]

    def __init__(self, dtypes, shapes, names, queue_ref, name):
        self._dtypes = [dtypes_mod.as_dtype(d) for d in dtypes]
        self._shapes = ([shape_mod.as_shape(s) for s in shapes]
                        if shapes is not None
                        else [shape_mod.TensorShape(None)] * len(self._dtypes))
        self._name = name
        self._closed = False
        QueueBase._registry[name] = self

    # python-side storage defined by subclass: self._q

    @property
    def name(self):
        return self._name

    @property
    def dtypes(self):
        return self._dtypes

    @property
    def shapes(self):
        return self._shapes

    @property
    def queue_ref(self):
        return self._name

    # -- graph endpoints -----------------------------------------------------
    def enqueue(self, vals, name=None):
        tensors = self._normalize(vals)
        g = ops_mod.get_default_graph()
        return g.create_op("QueueEnqueue", list(tensors),
                           attrs={"queue_name": self._name},
                           name=name or f"{self._name}_enqueue",
                           output_specs=[])

    def enqueue_maybe(self, keep_input, vals, name=None):
        """Conditional enqueue (backs train.input.maybe_batch)."""
        tensors = self._normalize(vals)
        keep = ops_mod.convert_to_tensor(keep_input,
                                         dtype=dtypes_mod.bool_)
        g = ops_mod.get_default_graph()
        return g.create_op("QueueEnqueueMaybe", [keep] + list(tensors),
                           attrs={"queue_name": self._name},
                           name=name or f"{self._name}_enqueue_maybe",
                           output_specs=[])

    def enqueue_many(self, vals, name=None):
        tensors = self._normalize(vals)
        g = ops_mod.get_default_graph()
        return g.create_op("QueueEnqueueMany", list(tensors),
                           attrs={"queue_name": self._name},
                           name=name or f"{self._name}_enqueue_many",
                           output_specs=[])

    def _normalize(self, vals):
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        return [ops_mod.convert_to_tensor(v, dtype=dt)
                for v, dt in zip(vals, self._dtypes)]

    def dequeue(self, name=None):
        g = ops_mod.get_default_graph()
        op = g.create_op(
            "QueueDequeue", [], attrs={"queue_name": self._name},
            name=name or f"{self._name}_dequeue",
            output_specs=[(s, d) for s, d in zip(self._shapes, self._dtypes)])
        outs = op.outputs
        return outs[0] if len(outs) == 1 else list(outs)

    def dequeue_many(self, n, name=None):
        g = ops_mod.get_default_graph()
        specs = [(shape_mod.TensorShape([n] + (s.as_list() if s.rank is not None
                                               else [])), d)
                 for s, d in zip(self._shapes, self._dtypes)]
        op = g.create_op("QueueDequeueMany", [],
                         attrs={"queue_name": self._name, "n": int(n)},
                         name=name or f"{self._name}_dequeue_many",
                         output_specs=specs)
        outs = op.outputs
        return outs[0] if len(outs) == 1 else list(outs)

    def close(self, cancel_pending_enqueues=False, name=None):
        g = ops_mod.get_default_graph()
        return g.create_op(
            "QueueClose", [],
            attrs={"queue_name": self._name,
                   "cancel_pending_enqueues":
                       bool(cancel_pending_enqueues)},
            name=name or f"{self._name}_close",
            output_specs=[])

    def size(self, name=None):
        g = ops_mod.get_default_graph()
        op = g.create_op("QueueSize", [], attrs={"queue_name": self._name},
                         name=name or f"{self._name}_size",
                         output_specs=[(shape_mod.scalar(), dtypes_mod.int32)])
        return op.outputs[0]

    # -- host behavior (called by lowerings) --------------------------------
    def _host_enqueue(self, items, timeout=None):
        """Blocks while the queue is full — the reference kernel's
        contract: a producer throttles against a slow consumer forever
        (a 10s-style cliff would kill training whenever the consumer
        pauses for a checkpoint/eval). close() from another thread
        aborts a blocked enqueue with CancelledError; pass ``timeout``
        only when the caller retries (e.g. a runner re-checking its
        coordinator between slices)."""
        import time as _time

        deadline = None if timeout is None else _time.time() + timeout
        while True:
            if self._closed:
                raise errors.CancelledError(
                    None, None, f"Queue {self._name} closed")
            try:
                self._q.put(builtins.tuple(items), timeout=0.05)
                # close-cancel race: the purge in _host_close can free a
                # slot that lets this blocked put complete AFTER the
                # cancel — a cancelled queue must end empty, so drain
                # again and abort (a plain close lets the pending
                # enqueue complete, ref contract)
                if getattr(self, "_cancelled", False):
                    self._host_close(cancel_pending=True)
                    raise errors.CancelledError(
                        None, None, f"Queue {self._name} closed")
                return
            except py_queue.Full:
                if deadline is not None and _time.time() > deadline:
                    raise errors.DeadlineExceededError(
                        None, None,
                        f"Enqueue to {self._name} timed out (queue full)")

    def _host_dequeue(self, timeout=30.0):
        while True:
            try:
                return self._q.get(timeout=0.05)
            except py_queue.Empty:
                if self._closed:
                    raise errors.OutOfRangeError(
                        None, None,
                        f"Queue {self._name} is closed and empty")
                timeout -= 0.05
                if timeout <= 0:
                    raise errors.DeadlineExceededError(
                        None, None, f"Dequeue from {self._name} timed out")

    def _host_close(self, cancel_pending=False):
        self._closed = True
        if cancel_pending:
            # ref semantics: cancel_pending_enqueues purges queued
            # elements so blocked consumers see closed-and-empty
            self._cancelled = True
            try:
                while True:
                    self._q.get_nowait()
            except py_queue.Empty:
                pass

    def _host_size(self):
        return self._q.qsize()


class FIFOQueue(QueueBase):
    """(ref: data_flow_ops.py:611)."""

    def __init__(self, capacity, dtypes, shapes=None, names=None,
                 shared_name=None, name="fifo_queue"):
        QueueBase._counter[0] += 1
        uname = shared_name or f"{name}_{QueueBase._counter[0]}"
        if not isinstance(dtypes, (list, tuple)):
            dtypes = [dtypes]
        self._q = py_queue.Queue(maxsize=capacity)
        super().__init__(dtypes, shapes, names, uname, uname)
        self._capacity = capacity


class RandomShuffleQueue(QueueBase):
    """(ref: data_flow_ops.py:705). Buffered shuffle on the host."""

    def __init__(self, capacity, min_after_dequeue, dtypes, shapes=None,
                 names=None, seed=None, shared_name=None,
                 name="random_shuffle_queue"):
        QueueBase._counter[0] += 1
        uname = shared_name or f"{name}_{QueueBase._counter[0]}"
        if not isinstance(dtypes, (list, tuple)):
            dtypes = [dtypes]
        self._q = py_queue.Queue(maxsize=capacity)
        self._min_after = min_after_dequeue
        self._rng = np.random.RandomState(seed)
        self._buf = []
        self._lock = _sync.Lock("ops/shuffle_queue",
                                rank=_sync.RANK_QUEUE)
        super().__init__(dtypes, shapes, names, uname, uname)
        self._capacity = capacity

    def _host_enqueue(self, items, timeout=None):
        import time as _time

        # BLOCK at capacity (ref semantics): shuffle_batch's producer
        # threads throttle against a slow consumer indefinitely —
        # raising would stop the coordinator and kill the training
        # loop. Close from another thread aborts a blocked enqueue with
        # CancelledError; see QueueBase._host_enqueue for the timeout
        # contract.
        deadline = None if timeout is None else _time.time() + timeout
        while True:
            with self._lock:
                # closed check under the SAME lock as the append: the
                # close-cancel purge cannot interleave between them
                if self._closed:
                    raise errors.CancelledError(
                        None, None, f"Queue {self._name} closed")
                if len(self._buf) < self._capacity:
                    self._buf.append(builtins.tuple(items))
                    return
            if deadline is not None and _time.time() > deadline:
                raise errors.DeadlineExceededError(
                    None, None,
                    f"Enqueue to {self._name} timed out (queue full)")
            _time.sleep(0.01)

    def _host_close(self, cancel_pending=False):
        with self._lock:
            self._closed = True
            if cancel_pending:
                self._buf.clear()

    def _host_dequeue(self, timeout=30.0):
        import time as _time

        deadline = _time.time() + timeout
        while True:
            with self._lock:
                if len(self._buf) > self._min_after or (
                        self._closed and self._buf):
                    i = self._rng.randint(len(self._buf))
                    return self._buf.pop(i)
                if self._closed and not self._buf:
                    raise errors.OutOfRangeError(
                        None, None, f"Queue {self._name} closed and empty")
            if _time.time() > deadline:
                raise errors.DeadlineExceededError(None, None,
                                                   "dequeue timeout")
            _time.sleep(0.01)

    def _host_size(self):
        with self._lock:
            return len(self._buf)


class PaddingFIFOQueue(FIFOQueue):
    pass


class PriorityQueue(FIFOQueue):
    pass


def _get_queue(name) -> QueueBase:
    q = QueueBase._registry.get(name)
    if q is None:
        raise errors.NotFoundError(None, None, f"Queue {name} not found")
    return q


def _lower_enqueue(ctx, op, inputs):
    _get_queue(op.attrs["queue_name"])._host_enqueue(
        [np.asarray(x) for x in inputs])
    return []


def _lower_enqueue_many(ctx, op, inputs):
    q = _get_queue(op.attrs["queue_name"])
    arrays = [np.asarray(x) for x in inputs]
    for i in builtins.range(arrays[0].shape[0]):
        q._host_enqueue([a[i] for a in arrays])
    return []


def _lower_enqueue_maybe(ctx, op, inputs):
    """Conditional enqueue: first input is keep_input (bool); the rest are
    the element. Backs train.input.maybe_batch (ref: input.py
    ``maybe_batch`` — rows with keep_input False never enter the queue)."""
    keep = np.asarray(inputs[0])
    if bool(np.all(keep)):
        _get_queue(op.attrs["queue_name"])._host_enqueue(
            [np.asarray(x) for x in inputs[1:]])
    return []


def _lower_dequeue(ctx, op, inputs):
    item = _get_queue(op.attrs["queue_name"])._host_dequeue()
    return list(item)


def _lower_dequeue_many(ctx, op, inputs):
    q = _get_queue(op.attrs["queue_name"])
    n = op.attrs["n"]
    rows = [q._host_dequeue() for _ in builtins.range(n)]
    return [np.stack([r[i] for r in rows])
            for i in builtins.range(len(rows[0]))]


def _lower_close(ctx, op, inputs):
    _get_queue(op.attrs["queue_name"])._host_close(
        op.attrs.get("cancel_pending_enqueues", False))
    return []


def _lower_size(ctx, op, inputs):
    return [np.asarray(_get_queue(op.attrs["queue_name"])._host_size(),
                       dtype=np.int32)]


for _n, _fn, _nout in [("QueueEnqueue", _lower_enqueue, 0),
                       ("QueueEnqueueMaybe", _lower_enqueue_maybe, 0),
                       ("QueueEnqueueMany", _lower_enqueue_many, 0),
                       ("QueueDequeue", _lower_dequeue, None),
                       ("QueueDequeueMany", _lower_dequeue_many, None),
                       ("QueueClose", _lower_close, 0),
                       ("QueueSize", _lower_size, 1)]:
    op_registry.register(_n, lower=_fn, is_stateful=True, runs_on_host=True,
                         n_outputs=_nout)


# -- StagingArea -------------------------------------------------------------

class StagingArea:
    """Explicit double-buffering primitive (ref: python/ops/data_flow_ops.py
    :1384 ``StagingArea``, core/kernels/stage_op.cc).

    TPU-native: ``put`` stages components into HBM immediately
    (``jax.device_put`` inside the host stage — the same arena-staged
    transfer path ``prefetch_to_device`` uses), so by the time ``get`` feeds
    the compiled step the batch is already device-resident and rides the
    Session's zero-copy device-feed path. Unbounded capacity, exactly-once,
    FIFO order (the reference guarantees no order; FIFO is a superset)."""

    _counter = [0]

    def __init__(self, dtypes, shapes=None, names=None, shared_name=None):
        if not isinstance(dtypes, (list, tuple)):
            dtypes = [dtypes]
        self._dtypes = [dtypes_mod.as_dtype(d) for d in dtypes]
        if shapes is not None:
            if len(shapes) != len(self._dtypes):
                raise ValueError(
                    "StagingArea shapes must be the same length as dtypes")
            self._shapes = [shape_mod.as_shape(s) for s in shapes]
        else:
            self._shapes = [shape_mod.TensorShape(None)
                            for _ in self._dtypes]
        if names is not None:
            if len(names) != len(self._dtypes):
                raise ValueError(
                    "StagingArea names must be the same length as dtypes")
            self._names = list(names)
        else:
            self._names = None
        StagingArea._counter[0] += 1
        self._name = shared_name or f"staging_area_{StagingArea._counter[0]}"
        self._buf = py_queue.Queue()
        g = ops_mod.get_default_graph()
        g._scoped_state.setdefault("__staging_areas__",
                                   {})[self._name] = self

    @property
    def name(self):
        return self._name

    @property
    def dtypes(self):
        return self._dtypes

    @property
    def shapes(self):
        return self._shapes

    @property
    def names(self):
        return self._names

    def _check_put_vals(self, vals):
        if isinstance(vals, dict):
            if not self._names:
                raise ValueError(
                    "Staging areas must have names to enqueue a dictionary")
            if sorted(self._names) != sorted(vals.keys()):
                raise ValueError(
                    f"Keys in dictionary to put do not match names of "
                    f"staging area. Dictionary: {sorted(vals.keys())}, "
                    f"StagingArea: {sorted(self._names)}")
            vals = [vals[k] for k in self._names]
        else:
            if self._names:
                raise ValueError("You must enqueue a dictionary in a "
                                 "staging area with names")
            if not isinstance(vals, (list, tuple)):
                vals = [vals]
        if len(vals) != len(self._dtypes):
            raise ValueError(
                f"Unexpected number of inputs {len(vals)} vs "
                f"{len(self._dtypes)}")
        out = []
        for i, (v, dt, sh) in enumerate(zip(vals, self._dtypes,
                                            self._shapes)):
            t = ops_mod.convert_to_tensor(v, dtype=dt)
            if t.dtype.base_dtype != dt:
                raise ValueError(
                    f"Datatypes do not match. {t.dtype} != {dt}")
            if sh.rank is not None and not sh.is_compatible_with(t.shape):
                raise ValueError(
                    f"Shape {t.shape} not compatible with {sh}")
            out.append(t)
        return out

    def put(self, values, name=None):
        vals = self._check_put_vals(values)
        g = ops_mod.get_default_graph()
        return g.create_op("Stage", vals,
                           attrs={"staging_name": self._name},
                           name=name or f"{self._name}_put",
                           output_specs=[])

    def _get_return_value(self, tensors):
        if self._names:
            return {n: tensors[i] for i, n in enumerate(self._names)}
        if len(tensors) == 1:
            return tensors[0]
        return tensors

    def get(self, name=None):
        g = ops_mod.get_default_graph()
        op = g.create_op(
            "Unstage", [], attrs={"staging_name": self._name},
            name=name or f"{self._name}_get",
            output_specs=[(s, d) for s, d in zip(self._shapes,
                                                 self._dtypes)])
        return self._get_return_value(list(op.outputs))

    def size(self, name=None):
        g = ops_mod.get_default_graph()
        op = g.create_op("StagingSize", [],
                         attrs={"staging_name": self._name},
                         name=name or f"{self._name}_size",
                         output_specs=[(shape_mod.scalar(),
                                        dtypes_mod.int32)])
        return op.outputs[0]

    # -- host behavior -------------------------------------------------------
    def _host_put(self, items):
        import jax

        staged = []
        for x in items:
            a = np.asarray(x)
            if a.dtype == object:
                staged.append(a)      # strings stay host-side
            else:
                staged.append(jax.device_put(a))  # async H2D: in HBM by get
        self._buf.put(builtins.tuple(staged))

    def _host_get(self, timeout=30.0):
        try:
            return self._buf.get(timeout=timeout)
        except py_queue.Empty:
            raise errors.DeadlineExceededError(
                None, None,
                f"StagingArea {self._name} get() timed out (empty)")

    def _host_size(self):
        return self._buf.qsize()


def _get_staging(op) -> StagingArea:
    name = op.attrs["staging_name"]
    s = op.graph._scoped_state.get("__staging_areas__", {}).get(name)
    if s is None:
        raise errors.NotFoundError(None, None,
                                   f"StagingArea {name} not found")
    return s


def _lower_stage(ctx, op, inputs):
    _get_staging(op)._host_put(inputs)
    return []


def _lower_unstage(ctx, op, inputs):
    return list(_get_staging(op)._host_get())


def _lower_staging_size(ctx, op, inputs):
    return [np.asarray(_get_staging(op)._host_size(), np.int32)]


for _n, _fn, _nout in [("Stage", _lower_stage, 0),
                       ("Unstage", _lower_unstage, None),
                       ("StagingSize", _lower_staging_size, 1)]:
    op_registry.register(_n, lower=_fn, is_stateful=True, runs_on_host=True,
                         n_outputs=_nout)


# -- Barrier -----------------------------------------------------------------

class Barrier:
    """Key-value map of partially-filled tuples persisting across steps
    (ref: python/ops/data_flow_ops.py:805 ``Barrier``, kernels
    core/kernels/barrier_ops.cc). Host object: complete elements leave via
    ``take_many`` in first-insertion order; indices count from -2**63."""

    _counter = [0]

    def __init__(self, types, shapes=None, shared_name=None, name="barrier"):
        if not isinstance(types, (list, tuple)):
            types = [types]
        self._types = [dtypes_mod.as_dtype(t) for t in types]
        if shapes is not None:
            if not isinstance(shapes, (list, tuple)):
                shapes = [shapes]
            self._shapes = [shape_mod.as_shape(s) for s in shapes]
            for i, s in enumerate(self._shapes):
                if s.rank is not None and s.num_elements() == 0:
                    raise ValueError(
                        f"Empty tensors are not supported, but received "
                        f"shape {s} at index {i}")
        else:
            self._shapes = [shape_mod.TensorShape(None)
                            for _ in self._types]
        Barrier._counter[0] += 1
        self._name = shared_name or f"{name}_{Barrier._counter[0]}"
        self._lock = _sync.Lock("ops/barrier",
                                rank=_sync.RANK_QUEUE)
        self._elems = {}          # key -> [components or None]
        self._first_index = {}    # key -> insertion index of first insert
        self._next_index = 0
        self._closed = False
        self._cancel_pending = False
        g = ops_mod.get_default_graph()
        g._scoped_state.setdefault("__barriers__", {})[self._name] = self

    @property
    def name(self):
        return self._name

    @property
    def barrier_ref(self):
        return self._name

    def insert_many(self, component_index, keys, values, name=None):
        keys = ops_mod.convert_to_tensor(keys, dtype=dtypes_mod.string)
        values = ops_mod.convert_to_tensor(
            values, dtype=self._types[component_index])
        g = ops_mod.get_default_graph()
        return g.create_op(
            "BarrierInsertMany", [keys, values],
            attrs={"barrier_name": self._name,
                   "component_index": int(component_index)},
            name=name or f"{self._name}_BarrierInsertMany",
            output_specs=[])

    def take_many(self, num_elements, allow_small_batch=False, timeout=None,
                  name=None):
        g = ops_mod.get_default_graph()
        batch = None if allow_small_batch else int(num_elements)
        specs = ([(shape_mod.TensorShape([batch]), dtypes_mod.int64),
                  (shape_mod.TensorShape([batch]), dtypes_mod.string)]
                 + [(shape_mod.TensorShape([batch]).concatenate(s), t)
                    for s, t in zip(self._shapes, self._types)])
        op = g.create_op(
            "BarrierTakeMany", [],
            attrs={"barrier_name": self._name,
                   "num_elements": int(num_elements),
                   "allow_small_batch": bool(allow_small_batch),
                   "timeout_ms": timeout},
            name=name or f"{self._name}_BarrierTakeMany",
            output_specs=specs)
        outs = list(op.outputs)
        return outs[0], outs[1], outs[2:]

    def close(self, cancel_pending_enqueues=False, name=None):
        g = ops_mod.get_default_graph()
        return g.create_op(
            "BarrierClose", [],
            attrs={"barrier_name": self._name,
                   "cancel_pending_enqueues": bool(cancel_pending_enqueues)},
            name=name or f"{self._name}_BarrierClose", output_specs=[])

    def ready_size(self, name=None):
        g = ops_mod.get_default_graph()
        op = g.create_op("BarrierReadySize", [],
                         attrs={"barrier_name": self._name},
                         name=name or f"{self._name}_BarrierReadySize",
                         output_specs=[(shape_mod.scalar(),
                                        dtypes_mod.int32)])
        return op.outputs[0]

    def incomplete_size(self, name=None):
        g = ops_mod.get_default_graph()
        op = g.create_op("BarrierIncompleteSize", [],
                         attrs={"barrier_name": self._name},
                         name=name or f"{self._name}_BarrierIncompleteSize",
                         output_specs=[(shape_mod.scalar(),
                                        dtypes_mod.int32)])
        return op.outputs[0]

    # -- host behavior -------------------------------------------------------
    def _is_complete(self, key):
        return all(c is not None for c in self._elems[key])

    def _host_insert(self, component_index, keys, values):
        keys = np.asarray(keys).reshape(-1)
        values = np.asarray(values)
        if values.shape[:1] != keys.shape:
            raise errors.InvalidArgumentError(
                None, None,
                f"Barrier {self._name}: {keys.shape[0]} keys vs values "
                f"with leading dim {values.shape[:1]}")
        with self._lock:
            for i, k in enumerate(keys.tolist()):
                k = k.decode() if isinstance(k, bytes) else builtins.str(k)
                if k not in self._elems:
                    if self._closed:
                        raise errors.CancelledError(
                            None, None,
                            f"Barrier {self._name} is closed; cannot insert "
                            f"new key {k!r}")
                    self._elems[k] = [None] * len(self._types)
                    self._first_index[k] = self._next_index
                    self._next_index += 1
                elif self._cancel_pending:
                    raise errors.CancelledError(
                        None, None,
                        f"Barrier {self._name} closed with "
                        "cancel_pending_enqueues; completions cancelled")
                if self._elems[k][component_index] is not None:
                    raise errors.InvalidArgumentError(
                        None, None,
                        f"Barrier {self._name}: component {component_index} "
                        f"of key {k!r} already set")
                self._elems[k][component_index] = values[i]

    def _host_take(self, num_elements, allow_small_batch, timeout_ms):
        import time as _time

        deadline = _time.time() + ((timeout_ms / 1000.0)
                                   if timeout_ms else 30.0)
        while True:
            with self._lock:
                ready = sorted(
                    (k for k in self._elems if self._is_complete(k)),
                    key=lambda k: self._first_index[k])
                enough = len(ready) >= num_elements
                if enough or (self._closed and allow_small_batch and ready):
                    take = ready[:num_elements]
                    rows = [self._elems.pop(k) for k in take]
                    idxs = [self._first_index.pop(k) - 2**63 for k in take]
                    keys = np.array(take, dtype=object)
                    comps = [np.stack([np.asarray(r[c]) for r in rows])
                             if rows else
                             np.zeros((0,), self._types[c].np_dtype)
                             for c in builtins.range(len(self._types))]
                    return [np.array(idxs, np.int64), keys] + comps
                if self._closed and not enough and (
                        not allow_small_batch or not ready):
                    # closed + insufficient (or closed + empty even with
                    # allow_small_batch): immediate epoch-end signal, the
                    # same OutOfRange input-pipeline loops catch (ref
                    # barrier_ops.cc TryTakeMany close semantics)
                    raise errors.OutOfRangeError(
                        None, None,
                        f"Barrier {self._name} is closed and has "
                        f"insufficient elements "
                        f"(requested {num_elements}, total size "
                        f"{len(ready)})")
            if _time.time() > deadline:
                raise errors.DeadlineExceededError(
                    None, None, f"Barrier {self._name} take_many timed out")
            _time.sleep(0.01)

    def _host_close(self, cancel_pending):
        with self._lock:
            self._closed = True
            self._cancel_pending = cancel_pending
            if cancel_pending:
                incomplete = [k for k in self._elems
                              if not self._is_complete(k)]
                for k in incomplete:
                    del self._elems[k]
                    del self._first_index[k]

    def _host_ready_size(self):
        with self._lock:
            return builtins.sum(1 for k in self._elems
                                if self._is_complete(k))

    def _host_incomplete_size(self):
        with self._lock:
            return builtins.sum(1 for k in self._elems
                                if not self._is_complete(k))


def _get_barrier(op) -> Barrier:
    name = op.attrs["barrier_name"]
    b = op.graph._scoped_state.get("__barriers__", {}).get(name)
    if b is None:
        raise errors.NotFoundError(None, None, f"Barrier {name} not found")
    return b


op_registry.register(
    "BarrierInsertMany",
    lower=lambda ctx, op, inputs: _get_barrier(op)._host_insert(
        op.attrs["component_index"], inputs[0], inputs[1]) or [],
    is_stateful=True, runs_on_host=True, n_outputs=0)
op_registry.register(
    "BarrierTakeMany",
    lower=lambda ctx, op, inputs: _get_barrier(op)._host_take(
        op.attrs["num_elements"], op.attrs["allow_small_batch"],
        op.attrs["timeout_ms"]),
    is_stateful=True, runs_on_host=True, n_outputs=None)
op_registry.register(
    "BarrierClose",
    lower=lambda ctx, op, inputs: _get_barrier(op)._host_close(
        op.attrs["cancel_pending_enqueues"]) or [],
    is_stateful=True, runs_on_host=True, n_outputs=0)
op_registry.register(
    "BarrierReadySize",
    lower=lambda ctx, op, inputs: [
        np.asarray(_get_barrier(op)._host_ready_size(), np.int32)],
    is_stateful=True, runs_on_host=True, n_outputs=1)
op_registry.register(
    "BarrierIncompleteSize",
    lower=lambda ctx, op, inputs: [
        np.asarray(_get_barrier(op)._host_incomplete_size(), np.int32)],
    is_stateful=True, runs_on_host=True, n_outputs=1)


# -- RecordInput -------------------------------------------------------------

# reader threads poll a condition forever; tests' leak hygiene closes
# stragglers whose graph has been dropped (tests/conftest.py)
_live_record_inputs: "weakref.WeakSet" = weakref.WeakSet()


class RecordInput:
    """Asynchronously reads and randomly yields TFRecords (ref:
    python/ops/data_flow_ops.py:1633, core/kernels/record_yielder.cc).

    Host object: reader thread(s) fill a shuffle buffer from the matched
    files (order shifted by ``shift_ratio`` each epoch); ``get_yield_op``
    is a host op yielding ``batch_size`` records per execution. Yields
    start once buffer_size/2 records are buffered (or the epoch ends)."""

    _counter = [0]

    def __init__(self, file_pattern, batch_size=1, buffer_size=1,
                 parallelism=1, shift_ratio=0, seed=0, name=None):
        import glob as _glob

        RecordInput._counter[0] += 1
        self._files = sorted(_glob.glob(file_pattern))
        if not self._files:
            raise ValueError(f"No files match pattern {file_pattern!r}")
        self._batch_size = int(batch_size)
        self._buffer_size = builtins.max(int(buffer_size), batch_size)
        self._shift_ratio = float(shift_ratio)
        self._rng = np.random.RandomState(seed or None)
        self._name = name or f"record_input_{RecordInput._counter[0]}"
        self._buf = []
        self._lock = _sync.Lock("ops/record_input",
                                rank=_sync.RANK_QUEUE)
        self._have = _sync.Condition(self._lock)
        self._epoch = 0
        self._started = False
        self._closed = False
        g = ops_mod.get_default_graph()
        g._scoped_state.setdefault("__record_inputs__",
                                   {})[self._name] = self
        _live_record_inputs.add(self)

    def get_yield_op(self, name=None):
        g = ops_mod.get_default_graph()
        op = g.create_op(
            "RecordInputYield", [], attrs={"record_input_name": self._name},
            name=name or self._name,
            output_specs=[(shape_mod.TensorShape([self._batch_size]),
                           dtypes_mod.string)])
        return op.outputs[0]

    # -- host behavior -------------------------------------------------------
    def close(self):
        """Stop the reader thread. The yield op raises OutOfRange after
        this; safe to call more than once (and on a never-started
        instance)."""
        with self._have:
            self._closed = True
            self._have.notify_all()

    def _reader_loop(self):
        from ..lib.io import tf_record

        while not self._closed:
            shift = int(len(self._files) * self._shift_ratio *
                        self._epoch) % len(self._files)
            files = self._files[shift:] + self._files[:shift]
            n_records = 0
            for f in files:
                for rec in tf_record.tf_record_iterator(f):
                    n_records += 1
                    with self._have:
                        while len(self._buf) >= self._buffer_size:
                            if self._closed:
                                return
                            self._have.wait(0.05)
                        self._buf.append(rec)
                        self._have.notify_all()
            self._epoch += 1
            with self._have:
                self._epoch_done = True
                if n_records == 0:
                    # matched files hold zero records: yielding can never
                    # succeed — signal instead of spinning forever
                    self._empty_epoch = True
                self._have.notify_all()
                # reference contract (core/kernels/record_yielder.cc):
                # every record yields exactly ONCE per epoch. Hold the
                # next epoch's records out of the buffer until this
                # epoch has fully drained, else a slow consumer can see
                # epoch N+1 duplicates before finishing epoch N.
                while self._buf:
                    if self._closed:
                        return
                    self._have.wait(0.05)

    def _host_yield(self, timeout=30.0):
        import time as _time

        if not self._started:
            self._started = True
            self._epoch_done = False
            self._empty_epoch = False
            t = threading.Thread(target=self._reader_loop, daemon=True,
                                 name=f"stf_data_record_input_{self._name}")
            t.start()
        out = []
        deadline = _time.time() + timeout
        with self._have:
            # randomization warmup: half-full buffer before first yield
            while (len(self._buf) < self._buffer_size // 2
                   and not self._epoch_done):
                self._have.wait(0.05)
            while len(out) < self._batch_size:
                while not self._buf:
                    if self._closed:
                        raise errors.OutOfRangeError(
                            None, None,
                            f"RecordInput {self._name} is closed")
                    if self._empty_epoch:
                        raise errors.OutOfRangeError(
                            None, None,
                            f"RecordInput {self._name}: matched files "
                            "contain no records")
                    if _time.time() > deadline:
                        raise errors.DeadlineExceededError(
                            None, None,
                            f"RecordInput {self._name} yield timed out")
                    self._have.wait(0.05)
                i = self._rng.randint(len(self._buf))
                out.append(self._buf.pop(i))
                self._have.notify_all()
        return np.array(out, dtype=object)


def _lower_record_yield(ctx, op, inputs):
    name = op.attrs["record_input_name"]
    r = op.graph._scoped_state.get("__record_inputs__", {}).get(name)
    if r is None:
        raise errors.NotFoundError(None, None,
                                   f"RecordInput {name} not found")
    return [r._host_yield()]


op_registry.register("RecordInputYield", lower=_lower_record_yield,
                     is_stateful=True, runs_on_host=True, n_outputs=1)


class ConditionalAccumulator:
    """(ref: python/ops/data_flow_ops.py:1384 ``ConditionalAccumulator``,
    kernel core/kernels/conditional_accumulator.h). Host-side dense
    gradient accumulator used by SyncReplicas — on TPU the mesh
    all-reduce is the fast path; this serves the graph-op contract:
    ``apply_grad(symbolic_grad)`` returns an op to run (stale
    local_step < the accumulator's time step is dropped, ref semantics),
    ``take_grad(n)`` returns a tensor that BLOCKS until n fresh grads
    arrived, then yields their average, resets, and advances the time
    step."""

    _counter = [0]

    def __init__(self, dtype, shape=None, shared_name=None,
                 name="conditional_accumulator"):
        ConditionalAccumulator._counter[0] += 1
        self._dtype = dtypes_mod.as_dtype(dtype)
        self._shape = (shape_mod.as_shape(shape)
                       if shape is not None else shape_mod.TensorShape(None))
        self._name = (shared_name
                      or f"{name}_{ConditionalAccumulator._counter[0]}")
        self._sum = None
        self._count = 0
        self._global_step = 0
        self._lock = _sync.Lock("ops/accumulator",
                                rank=_sync.RANK_QUEUE)
        self._cond = _sync.Condition(self._lock)
        g = ops_mod.get_default_graph()
        g._scoped_state.setdefault("__dense_accumulators__",
                                   {})[self._name] = self

    @property
    def name(self):
        return self._name

    @property
    def dtype(self):
        return self._dtype

    @property
    def accumulator_ref(self):
        return self._name

    # -- graph endpoints -----------------------------------------------------
    def apply_grad(self, grad, local_step=0, name=None):
        g = ops_mod.get_default_graph()
        gt = ops_mod.convert_to_tensor(grad, dtype=self._dtype)
        step = ops_mod.convert_to_tensor(local_step)
        return g.create_op("AccumulatorApplyGradient", [gt, step],
                           attrs={"accumulator_name": self._name},
                           name=name or f"{self._name}_apply_grad",
                           output_specs=[])

    def take_grad(self, num_required, name=None):
        if num_required < 1:
            raise errors.InvalidArgumentError(
                None, None, f"num_required must be >= 1, got {num_required}")
        g = ops_mod.get_default_graph()
        op = g.create_op("AccumulatorTakeGradient", [],
                         attrs={"accumulator_name": self._name,
                                "num_required": int(num_required)},
                         name=name or f"{self._name}_take_grad",
                         output_specs=[(self._shape, self._dtype)])
        return op.outputs[0]

    def num_accumulated(self, name=None):
        g = ops_mod.get_default_graph()
        op = g.create_op("AccumulatorNumAccumulated", [],
                         attrs={"accumulator_name": self._name},
                         name=name or f"{self._name}_num_accumulated",
                         output_specs=[(shape_mod.scalar(),
                                        dtypes_mod.int32)])
        return op.outputs[0]

    def set_global_step(self, new_global_step, name=None):
        g = ops_mod.get_default_graph()
        step = ops_mod.convert_to_tensor(new_global_step)
        return g.create_op("AccumulatorSetGlobalStep", [step],
                           attrs={"accumulator_name": self._name},
                           name=name or f"{self._name}_set_global_step",
                           output_specs=[])

    # -- host behavior -------------------------------------------------------
    def _host_apply(self, grad, local_step):
        grad = np.asarray(grad)
        if (self._shape.rank is not None
                and not self._shape.is_compatible_with(grad.shape)):
            raise errors.InvalidArgumentError(
                None, None,
                f"Accumulator {self._name}: gradient shape {grad.shape} "
                f"incompatible with accumulator shape {self._shape}")
        with self._cond:
            if self._sum is not None and self._sum.shape != grad.shape:
                # shape=None: the FIRST applied gradient fixes the shape
                # (ref contract) — without this, numpy would silently
                # broadcast mismatched grads into a wrong-shaped average
                raise errors.InvalidArgumentError(
                    None, None,
                    f"Accumulator {self._name}: gradient shape "
                    f"{grad.shape} incompatible with accumulated shape "
                    f"{self._sum.shape}")
            if int(local_step) < self._global_step:
                return  # stale gradient: silently dropped (ref contract)
            self._sum = grad if self._sum is None else self._sum + grad
            self._count += 1
            self._cond.notify_all()

    def _host_take(self, num_required, timeout=30.0):
        """Blocks until num_required fresh grads arrived (the reference
        kernel's contract — appliers are expected on OTHER threads).
        Fetching take together with its applies in one run call is a
        scheduling ambiguity in the reference too; use a separate run
        call (or control deps) for the take."""
        import time as _time

        deadline = _time.time() + timeout
        with self._cond:
            while self._count < num_required:
                if not self._cond.wait(
                        timeout=max(0.0, deadline - _time.time())):
                    raise errors.DeadlineExceededError(
                        None, None,
                        f"Accumulator {self._name} take_grad timed out")
            avg = (self._sum / self._count).astype(self._dtype.np_dtype)
            self._sum, self._count = None, 0
            self._global_step += 1
            return [avg]

    def _host_num(self):
        with self._lock:
            return np.asarray(self._count, np.int32)

    def _host_set_step(self, step):
        with self._lock:
            self._global_step = int(step)


def _get_dense_acc(op) -> "ConditionalAccumulator":
    name = op.attrs["accumulator_name"]
    a = op.graph._scoped_state.get("__dense_accumulators__", {}).get(name)
    if a is None:
        raise errors.NotFoundError(None, None,
                                   f"Accumulator {name} not found")
    return a


op_registry.register(
    "AccumulatorApplyGradient",
    lower=lambda ctx, op, inputs: _get_dense_acc(op)._host_apply(
        inputs[0], inputs[1]) or [],
    is_stateful=True, runs_on_host=True, n_outputs=0)
op_registry.register(
    "AccumulatorTakeGradient",
    lower=lambda ctx, op, inputs: _get_dense_acc(op)._host_take(
        op.attrs["num_required"]),
    is_stateful=True, runs_on_host=True, n_outputs=1)
op_registry.register(
    "AccumulatorNumAccumulated",
    lower=lambda ctx, op, inputs: [_get_dense_acc(op)._host_num()],
    is_stateful=True, runs_on_host=True, n_outputs=1)
op_registry.register(
    "AccumulatorSetGlobalStep",
    lower=lambda ctx, op, inputs: _get_dense_acc(op)._host_set_step(
        inputs[0]) or [],
    is_stateful=True, runs_on_host=True, n_outputs=0)


class SparseConditionalAccumulator:
    """Accumulates sparse (IndexedSlices) gradients (ref:
    python/ops/data_flow_ops.py:1230, kernel
    core/kernels/sparse_conditional_accumulator.h).

    Host object with graph-op endpoints: ``apply_grad`` is dropped when
    stale (local_step < the accumulator's time step, ref semantics);
    ``take_grad`` blocks until num_required fresh gradients arrived, then
    returns the per-count average as (indices, values, shape), resets, and
    advances the time step. On TPU the mesh all-reduce is the fast path for
    dense grads; this serves embedding-style sparse updates."""

    _counter = [0]

    def __init__(self, dtype, shape=None, shared_name=None,
                 name="sparse_conditional_accumulator"):
        SparseConditionalAccumulator._counter[0] += 1
        self._dtype = dtypes_mod.as_dtype(dtype)
        self._shape = (shape_mod.as_shape(shape)
                       if shape is not None else None)
        self._name = (shared_name
                      or f"{name}_{SparseConditionalAccumulator._counter[0]}")
        self._lock = _sync.Lock("ops/sparse_accumulator",
                                rank=_sync.RANK_QUEUE)
        self._cond = _sync.Condition(self._lock)
        self._sums = {}       # row index -> accumulated value row(s)
        self._counts = {}     # row index -> number of contributions
        self._ngrads = 0
        self._seen_shape = None   # dense_shape from applied gradients
        self._global_step = 0
        g = ops_mod.get_default_graph()
        g._scoped_state.setdefault("__sparse_accumulators__",
                                   {})[self._name] = self

    @property
    def name(self):
        return self._name

    @property
    def dtype(self):
        return self._dtype

    @property
    def accumulator_ref(self):
        return self._name

    # -- graph endpoints -----------------------------------------------------
    def apply_grad(self, grad_indices, grad_values, grad_shape=None,
                   local_step=0, name=None):
        g = ops_mod.get_default_graph()
        idx = ops_mod.convert_to_tensor(grad_indices)
        vals = ops_mod.convert_to_tensor(grad_values, dtype=self._dtype)
        step = ops_mod.convert_to_tensor(local_step)
        inputs = [idx, vals, step]
        attrs = {"accumulator_name": self._name,
                 "has_known_shape": grad_shape is not None}
        if grad_shape is not None:
            inputs.append(ops_mod.convert_to_tensor(grad_shape))
        return g.create_op("SparseAccumulatorApplyGradient", inputs,
                           attrs=attrs,
                           name=name or f"{self._name}_apply_grad",
                           output_specs=[])

    def apply_indexed_slices_grad(self, grad, local_step=0, name=None):
        return self.apply_grad(grad.indices, grad.values, grad.dense_shape,
                               local_step=local_step, name=name)

    def take_grad(self, num_required, name=None):
        if num_required < 1:
            raise errors.InvalidArgumentError(
                None, None, f"num_required must be >= 1, got {num_required}")
        g = ops_mod.get_default_graph()
        op = g.create_op(
            "SparseAccumulatorTakeGradient", [],
            attrs={"accumulator_name": self._name,
                   "num_required": int(num_required)},
            name=name or f"{self._name}_take_grad",
            output_specs=[(shape_mod.TensorShape([None]), dtypes_mod.int64),
                          (shape_mod.TensorShape(None), self._dtype),
                          (shape_mod.TensorShape([None]),
                           dtypes_mod.int64)])
        return op.outputs[0], op.outputs[1], op.outputs[2]

    def take_indexed_slices_grad(self, num_required, name=None):
        from ..framework.indexed_slices import IndexedSlices as _IS

        i, v, s = self.take_grad(num_required, name=name)
        return _IS(values=v, indices=i, dense_shape=s)

    def num_accumulated(self, name=None):
        g = ops_mod.get_default_graph()
        op = g.create_op("SparseAccumulatorNumAccumulated", [],
                         attrs={"accumulator_name": self._name},
                         name=name or f"{self._name}_num_accumulated",
                         output_specs=[(shape_mod.scalar(),
                                        dtypes_mod.int32)])
        return op.outputs[0]

    def set_global_step(self, new_global_step, name=None):
        g = ops_mod.get_default_graph()
        step = ops_mod.convert_to_tensor(new_global_step)
        return g.create_op("SparseAccumulatorSetGlobalStep", [step],
                           attrs={"accumulator_name": self._name},
                           name=name or f"{self._name}_set_global_step",
                           output_specs=[])

    # -- host behavior -------------------------------------------------------
    def _host_apply(self, indices, values, local_step, shape):
        indices = np.asarray(indices).reshape(-1)
        values = np.asarray(values)
        if values.shape[0] != indices.shape[0]:
            raise errors.InvalidArgumentError(
                None, None,
                f"Accumulator {self._name}: {indices.shape[0]} indices vs "
                f"{values.shape[0]} value rows")
        if (self._shape is not None and self._shape.rank is not None
                and shape is not None):
            want = self._shape.as_list()
            got = list(np.asarray(shape).reshape(-1))
            for w, g_ in zip(want, got):
                if w is not None and w != g_:
                    raise errors.InvalidArgumentError(
                        None, None,
                        f"Accumulator {self._name}: gradient shape {got} "
                        f"incompatible with accumulator shape {want}")
        with self._cond:
            if int(local_step) < self._global_step:
                return  # stale gradient: silently dropped (ref contract)
            for i, row in zip(indices.tolist(), values):
                if i in self._sums:
                    self._sums[i] = self._sums[i] + row
                    self._counts[i] += 1
                else:
                    self._sums[i] = np.array(row)
                    self._counts[i] = 1
            if shape is not None:
                self._seen_shape = np.asarray(shape,
                                              np.int64).reshape(-1)
            self._ngrads += 1
            self._cond.notify_all()

    def _host_take(self, num_required, timeout=30.0):
        import time as _time

        deadline = _time.time() + timeout
        with self._cond:
            while self._ngrads < num_required:
                if not self._cond.wait(
                        timeout=max(0.0, deadline - _time.time())):
                    raise errors.DeadlineExceededError(
                        None, None,
                        f"Accumulator {self._name} take_grad timed out")
            idx = sorted(self._sums)
            # ref semantics (kernel DivideAccumGradByCounter): each
            # slice averages over the number of gradients that CONTAINED
            # that index, not the total taken
            vals = np.stack(
                [self._sums[i] / self._counts[i] for i in idx]) \
                if idx else np.zeros((0,), self._dtype.np_dtype)
            if self._seen_shape is not None:
                shape = self._seen_shape
            elif (self._shape is not None
                  and self._shape.is_fully_defined()):
                shape = np.asarray(self._shape.as_list(), np.int64)
            else:
                shape = np.zeros((0,), np.int64)
            self._sums, self._counts = {}, {}
            self._ngrads = 0
            self._global_step += 1
            return [np.asarray(idx, np.int64),
                    vals.astype(self._dtype.np_dtype), shape]

    def _host_num(self):
        with self._lock:
            return np.asarray(self._ngrads, np.int32)

    def _host_set_step(self, step):
        with self._lock:
            self._global_step = int(step)


def _get_sparse_acc(op) -> SparseConditionalAccumulator:
    name = op.attrs["accumulator_name"]
    a = op.graph._scoped_state.get("__sparse_accumulators__", {}).get(name)
    if a is None:
        raise errors.NotFoundError(None, None,
                                   f"Accumulator {name} not found")
    return a


def _lower_sparse_apply(ctx, op, inputs):
    shape = inputs[3] if op.attrs["has_known_shape"] else None
    _get_sparse_acc(op)._host_apply(inputs[0], inputs[1], inputs[2], shape)
    return []


op_registry.register("SparseAccumulatorApplyGradient",
                     lower=_lower_sparse_apply,
                     is_stateful=True, runs_on_host=True, n_outputs=0)
op_registry.register(
    "SparseAccumulatorTakeGradient",
    lower=lambda ctx, op, inputs: _get_sparse_acc(op)._host_take(
        op.attrs["num_required"]),
    is_stateful=True, runs_on_host=True, n_outputs=None)
op_registry.register(
    "SparseAccumulatorNumAccumulated",
    lower=lambda ctx, op, inputs: [_get_sparse_acc(op)._host_num()],
    is_stateful=True, runs_on_host=True, n_outputs=1)
op_registry.register(
    "SparseAccumulatorSetGlobalStep",
    lower=lambda ctx, op, inputs: _get_sparse_acc(op)._host_set_step(
        inputs[0]) or [],
    is_stateful=True, runs_on_host=True, n_outputs=0)


ConditionalAccumulatorBase = ConditionalAccumulator  # ref base-class name


# declared effect sets (stf.analysis): queue/staging/barrier mutations
# are per-resource writes, size probes are reads. These resources are
# host-side (advisory hazard class — warnings, never errors: pipelines
# legitimately stage producers and consumers of one queue in one step).
for _w_op in ("QueueEnqueue", "QueueEnqueueMaybe", "QueueEnqueueMany",
              "QueueDequeue", "QueueDequeueMany", "QueueClose"):
    op_registry.declare_effects(_w_op, op_registry.Effects(io=True, writes=("queue_name",)))
op_registry.declare_effects("QueueSize", op_registry.Effects(reads=("queue_name",)))
for _w_op in ("Stage", "Unstage"):
    op_registry.declare_effects(_w_op, op_registry.Effects(io=True, writes=("staging_name",)))
op_registry.declare_effects("StagingSize", op_registry.Effects(reads=("staging_name",)))
for _w_op in ("BarrierInsertMany", "BarrierTakeMany", "BarrierClose"):
    op_registry.declare_effects(_w_op, op_registry.Effects(io=True, writes=("barrier_name",)))
for _r_op in ("BarrierReadySize", "BarrierIncompleteSize"):
    op_registry.declare_effects(_r_op, op_registry.Effects(reads=("barrier_name",)))
op_registry.declare_effects("RecordInputYield",
                            op_registry.Effects(io=True, writes=("record_input_name",)))
