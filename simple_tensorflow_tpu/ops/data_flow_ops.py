"""Queues + dataflow ops (ref: tensorflow/python/ops/data_flow_ops.py,
core/kernels/{fifo_queue,random_shuffle_queue_op,dynamic_stitch_op,
dynamic_partition_op}.cc).

TPU-native split: queues are HOST-stage objects (the reference pins queue
kernels to CPU too) driven by QueueRunner threads; dequeued numpy batches
become boundary feeds of the compiled device step. dynamic_stitch/partition
are device ops (static shapes).
"""

from __future__ import annotations

import builtins
import queue as py_queue
import threading

import numpy as np

import jax.numpy as jnp

from ..framework import dtypes as dtypes_mod
from ..framework import errors
from ..framework import graph as ops_mod
from ..framework import op_registry
from ..framework import tensor_shape as shape_mod
from .op_util import make_op


# -- device ops --------------------------------------------------------------

op_registry.register_pure(
    "DynamicPartition",
    lambda data, partitions, num_partitions=2: [
        jnp.where((partitions == i)[(...,) + (None,) * (data.ndim - partitions.ndim)],
                  data, jnp.zeros_like(data))
        for i in builtins.range(num_partitions)], n_outputs=None)


def _dynamic_stitch_impl(*args, n):
    indices = args[:n]
    data = args[n:]
    total = builtins.max(int(np.max(np.asarray(i.shape))) for i in indices)
    # size = max index + 1 must be static: use sum of sizes
    size = builtins.sum(int(np.prod(i.shape)) for i in indices)
    out_shape = (size,) + data[0].shape[indices[0].ndim:]
    out = jnp.zeros(out_shape, data[0].dtype)
    for idx, d in zip(indices, data):
        flat_idx = jnp.reshape(idx, (-1,))
        flat_d = jnp.reshape(d, (-1,) + out_shape[1:])
        out = out.at[flat_idx].set(flat_d)
    return out


op_registry.register_pure("DynamicStitch", _dynamic_stitch_impl)


def dynamic_partition(data, partitions, num_partitions, name=None):
    """Masked dense partitions (XLA-static; rows not in partition i are
    zero). The reference returns ragged pieces — impossible with static
    shapes; masking gives the common all-reduce/sum use-cases the same
    result."""
    data = ops_mod.convert_to_tensor(data)
    partitions = ops_mod.convert_to_tensor(partitions)
    return make_op("DynamicPartition", [data, partitions],
                   attrs={"num_partitions": int(num_partitions)},
                   name=name, n_out=int(num_partitions))


def dynamic_stitch(indices, data, name=None):
    idx_t = [ops_mod.convert_to_tensor(i, dtype=dtypes_mod.int32)
             for i in indices]
    data_t = [ops_mod.convert_to_tensor(d) for d in data]
    return make_op("DynamicStitch", idx_t + data_t,
                   attrs={"n": len(idx_t)}, name=name)


# -- host queues -------------------------------------------------------------

class QueueBase:
    """(ref: data_flow_ops.py:96 ``class QueueBase``). Host object; its
    graph presence is a set of host ops keyed by queue name."""

    _registry = {}
    _counter = [0]

    def __init__(self, dtypes, shapes, names, queue_ref, name):
        self._dtypes = [dtypes_mod.as_dtype(d) for d in dtypes]
        self._shapes = ([shape_mod.as_shape(s) for s in shapes]
                        if shapes is not None
                        else [shape_mod.TensorShape(None)] * len(self._dtypes))
        self._name = name
        self._closed = False
        QueueBase._registry[name] = self

    # python-side storage defined by subclass: self._q

    @property
    def name(self):
        return self._name

    @property
    def dtypes(self):
        return self._dtypes

    @property
    def shapes(self):
        return self._shapes

    @property
    def queue_ref(self):
        return self._name

    # -- graph endpoints -----------------------------------------------------
    def enqueue(self, vals, name=None):
        tensors = self._normalize(vals)
        g = ops_mod.get_default_graph()
        return g.create_op("QueueEnqueue", list(tensors),
                           attrs={"queue_name": self._name},
                           name=name or f"{self._name}_enqueue",
                           output_specs=[])

    def enqueue_maybe(self, keep_input, vals, name=None):
        """Conditional enqueue (backs train.input.maybe_batch)."""
        tensors = self._normalize(vals)
        keep = ops_mod.convert_to_tensor(keep_input,
                                         dtype=dtypes_mod.bool_)
        g = ops_mod.get_default_graph()
        return g.create_op("QueueEnqueueMaybe", [keep] + list(tensors),
                           attrs={"queue_name": self._name},
                           name=name or f"{self._name}_enqueue_maybe",
                           output_specs=[])

    def enqueue_many(self, vals, name=None):
        tensors = self._normalize(vals)
        g = ops_mod.get_default_graph()
        return g.create_op("QueueEnqueueMany", list(tensors),
                           attrs={"queue_name": self._name},
                           name=name or f"{self._name}_enqueue_many",
                           output_specs=[])

    def _normalize(self, vals):
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        return [ops_mod.convert_to_tensor(v, dtype=dt)
                for v, dt in zip(vals, self._dtypes)]

    def dequeue(self, name=None):
        g = ops_mod.get_default_graph()
        op = g.create_op(
            "QueueDequeue", [], attrs={"queue_name": self._name},
            name=name or f"{self._name}_dequeue",
            output_specs=[(s, d) for s, d in zip(self._shapes, self._dtypes)])
        outs = op.outputs
        return outs[0] if len(outs) == 1 else list(outs)

    def dequeue_many(self, n, name=None):
        g = ops_mod.get_default_graph()
        specs = [(shape_mod.TensorShape([n] + (s.as_list() if s.rank is not None
                                               else [])), d)
                 for s, d in zip(self._shapes, self._dtypes)]
        op = g.create_op("QueueDequeueMany", [],
                         attrs={"queue_name": self._name, "n": int(n)},
                         name=name or f"{self._name}_dequeue_many",
                         output_specs=specs)
        outs = op.outputs
        return outs[0] if len(outs) == 1 else list(outs)

    def close(self, cancel_pending_enqueues=False, name=None):
        g = ops_mod.get_default_graph()
        return g.create_op("QueueClose", [],
                           attrs={"queue_name": self._name},
                           name=name or f"{self._name}_close",
                           output_specs=[])

    def size(self, name=None):
        g = ops_mod.get_default_graph()
        op = g.create_op("QueueSize", [], attrs={"queue_name": self._name},
                         name=name or f"{self._name}_size",
                         output_specs=[(shape_mod.scalar(), dtypes_mod.int32)])
        return op.outputs[0]

    # -- host behavior (called by lowerings) --------------------------------
    def _host_enqueue(self, items, timeout=10.0):
        if self._closed:
            raise errors.CancelledError(None, None,
                                        f"Queue {self._name} closed")
        self._q.put(builtins.tuple(items), timeout=timeout)

    def _host_dequeue(self, timeout=30.0):
        while True:
            try:
                return self._q.get(timeout=0.05)
            except py_queue.Empty:
                if self._closed:
                    raise errors.OutOfRangeError(
                        None, None,
                        f"Queue {self._name} is closed and empty")
                timeout -= 0.05
                if timeout <= 0:
                    raise errors.DeadlineExceededError(
                        None, None, f"Dequeue from {self._name} timed out")

    def _host_close(self):
        self._closed = True

    def _host_size(self):
        return self._q.qsize()


class FIFOQueue(QueueBase):
    """(ref: data_flow_ops.py:611)."""

    def __init__(self, capacity, dtypes, shapes=None, names=None,
                 shared_name=None, name="fifo_queue"):
        QueueBase._counter[0] += 1
        uname = shared_name or f"{name}_{QueueBase._counter[0]}"
        if not isinstance(dtypes, (list, tuple)):
            dtypes = [dtypes]
        self._q = py_queue.Queue(maxsize=capacity)
        super().__init__(dtypes, shapes, names, uname, uname)
        self._capacity = capacity


class RandomShuffleQueue(QueueBase):
    """(ref: data_flow_ops.py:705). Buffered shuffle on the host."""

    def __init__(self, capacity, min_after_dequeue, dtypes, shapes=None,
                 names=None, seed=None, shared_name=None,
                 name="random_shuffle_queue"):
        QueueBase._counter[0] += 1
        uname = shared_name or f"{name}_{QueueBase._counter[0]}"
        if not isinstance(dtypes, (list, tuple)):
            dtypes = [dtypes]
        self._q = py_queue.Queue(maxsize=capacity)
        self._min_after = min_after_dequeue
        self._rng = np.random.RandomState(seed)
        self._buf = []
        self._lock = threading.Lock()
        super().__init__(dtypes, shapes, names, uname, uname)
        self._capacity = capacity

    def _host_enqueue(self, items, timeout=10.0):
        with self._lock:
            self._buf.append(builtins.tuple(items))
            if len(self._buf) > self._capacity:
                raise errors.ResourceExhaustedError(None, None, "queue full")

    def _host_dequeue(self, timeout=30.0):
        import time as _time

        deadline = _time.time() + timeout
        while True:
            with self._lock:
                if len(self._buf) > self._min_after or (
                        self._closed and self._buf):
                    i = self._rng.randint(len(self._buf))
                    return self._buf.pop(i)
                if self._closed and not self._buf:
                    raise errors.OutOfRangeError(
                        None, None, f"Queue {self._name} closed and empty")
            if _time.time() > deadline:
                raise errors.DeadlineExceededError(None, None,
                                                   "dequeue timeout")
            _time.sleep(0.01)

    def _host_size(self):
        with self._lock:
            return len(self._buf)


class PaddingFIFOQueue(FIFOQueue):
    pass


class PriorityQueue(FIFOQueue):
    pass


def _get_queue(name) -> QueueBase:
    q = QueueBase._registry.get(name)
    if q is None:
        raise errors.NotFoundError(None, None, f"Queue {name} not found")
    return q


def _lower_enqueue(ctx, op, inputs):
    _get_queue(op.attrs["queue_name"])._host_enqueue(
        [np.asarray(x) for x in inputs])
    return []


def _lower_enqueue_many(ctx, op, inputs):
    q = _get_queue(op.attrs["queue_name"])
    arrays = [np.asarray(x) for x in inputs]
    for i in builtins.range(arrays[0].shape[0]):
        q._host_enqueue([a[i] for a in arrays])
    return []


def _lower_enqueue_maybe(ctx, op, inputs):
    """Conditional enqueue: first input is keep_input (bool); the rest are
    the element. Backs train.input.maybe_batch (ref: input.py
    ``maybe_batch`` — rows with keep_input False never enter the queue)."""
    keep = np.asarray(inputs[0])
    if bool(np.all(keep)):
        _get_queue(op.attrs["queue_name"])._host_enqueue(
            [np.asarray(x) for x in inputs[1:]])
    return []


def _lower_dequeue(ctx, op, inputs):
    item = _get_queue(op.attrs["queue_name"])._host_dequeue()
    return list(item)


def _lower_dequeue_many(ctx, op, inputs):
    q = _get_queue(op.attrs["queue_name"])
    n = op.attrs["n"]
    rows = [q._host_dequeue() for _ in builtins.range(n)]
    return [np.stack([r[i] for r in rows])
            for i in builtins.range(len(rows[0]))]


def _lower_close(ctx, op, inputs):
    _get_queue(op.attrs["queue_name"])._host_close()
    return []


def _lower_size(ctx, op, inputs):
    return [np.asarray(_get_queue(op.attrs["queue_name"])._host_size(),
                       dtype=np.int32)]


for _n, _fn, _nout in [("QueueEnqueue", _lower_enqueue, 0),
                       ("QueueEnqueueMaybe", _lower_enqueue_maybe, 0),
                       ("QueueEnqueueMany", _lower_enqueue_many, 0),
                       ("QueueDequeue", _lower_dequeue, None),
                       ("QueueDequeueMany", _lower_dequeue_many, None),
                       ("QueueClose", _lower_close, 0),
                       ("QueueSize", _lower_size, 1)]:
    op_registry.register(_n, lower=_fn, is_stateful=True, runs_on_host=True,
                         n_outputs=_nout)


class ConditionalAccumulator:
    """(ref: core/kernels/conditional_accumulator.h). Host-side gradient
    accumulator used by SyncReplicas — on TPU the mesh all-reduce replaces
    it; kept for API parity."""

    def __init__(self, dtype, shape=None, shared_name=None,
                 name="conditional_accumulator"):
        self._dtype = dtypes_mod.as_dtype(dtype)
        self._sum = None
        self._count = 0
        self._lock = threading.Lock()
        self._name = name

    def apply_grad(self, grad, local_step=0, name=None):
        with self._lock:
            g = np.asarray(grad)
            self._sum = g if self._sum is None else self._sum + g
            self._count += 1
        return None

    def take_grad(self, num_required, name=None):
        with self._lock:
            if self._count < num_required:
                raise errors.FailedPreconditionError(
                    None, None, f"only {self._count} grads accumulated")
            avg = self._sum / self._count
            self._sum, self._count = None, 0
            return avg

    def num_accumulated(self, name=None):
        return self._count
