"""Sampling decode: temperature / top-k / top-p logits transform + a
seeded Gumbel-max draw, composed from existing stf graph ops.

(ref: tensorflow/python/ops/random_ops.py ``multinomial`` — the
reference samples once from full logits; serving decode wants the
standard transform chain in front, and the draw must ride the per-step
RNG stream so ``set_random_seed`` reproduces token streams.)

Design constraints (docs/SERVING.md §sampling):

- the transform is PURE graph math (sort, threshold, mask) — static
  shapes, no data-dependent vocab slicing, so the decode plan stays one
  AOT executable per bucket;
- the only randomness is ONE ``RandomUniform`` per sampled tensor,
  which declares ``Effects(rng=True)`` (ops/random_ops.py): the plan
  reports ``uses_rng`` and the Session advances its run counter per
  execution, folding (graph seed, op seed, run counter) into the key —
  the same fixed-seed contract dropout has, so two processes with the
  same ``set_random_seed`` and submission order emit identical token
  streams, independent of which kernel-registry impl computes the
  logits' surrounding ops;
- Gumbel-max instead of inverse-CDF: ``argmax(logits + g)`` needs no
  renormalization after masking, and ties break deterministically the
  way argmax does.

Masked-out entries are pushed to an additive -1e9 (the same NEG_INF
convention the attention kernels use), never multiplied, so kept
logits pass through bit-unchanged.
"""

from __future__ import annotations

import simple_tensorflow_tpu as stf

_NEG = -1e9


def sampling_logits_transform(logits, temperature=1.0, top_k=0,
                              top_p=1.0):
    """Apply temperature / top-k / top-p to ``logits (B, V)`` f32.

    Returns transformed logits (B, V): scaled by 1/temperature, with
    every filtered token pushed to -1e9. ``top_k=0`` and ``top_p=1.0``
    disable their filters; the argmax token always survives both (the
    top-p prefix keeps at least its first element), so greedy decode is
    the ``temperature -> 0`` limit and sampling never stalls on an
    empty support.
    """
    b = int(logits.shape[0])
    vocab = int(logits.shape[1])
    x = stf.cast(logits, stf.float32)
    temperature = float(temperature)
    if temperature <= 0:
        raise ValueError(f"temperature must be > 0, got {temperature}")
    if temperature != 1.0:
        x = x * (1.0 / temperature)
    top_k = int(top_k or 0)
    if top_k < 0 or top_k > vocab:
        raise ValueError(f"top_k must be in [0, {vocab}], got {top_k}")
    if 0 < top_k < vocab:
        vals, _ = stf.nn.top_k(x, k=top_k)                # (B, k) desc
        kth = stf.slice(vals, [0, top_k - 1], [b, 1])     # (B, 1)
        drop = stf.cast(stf.less(x, kth), stf.float32)
        x = x + drop * _NEG
    top_p = float(top_p)
    if not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if top_p < 1.0:
        vals, _ = stf.nn.top_k(x, k=vocab)                # (B, V) desc
        probs = stf.nn.softmax(vals, axis=-1)
        # exclusive cumsum: entry j is the mass STRICTLY before j, so
        # the first sorted token always has cum 0 < top_p and survives
        cum = stf.cumsum(probs, axis=-1, exclusive=True)
        kept = stf.cast(stf.less(cum, top_p), stf.float32)
        # smallest kept sorted value = the admission threshold; ties at
        # the threshold are all kept (deterministic, seed-independent)
        thresh = stf.reduce_min(vals * kept + (1.0 - kept) * 1e9,
                                axis=-1, keepdims=True)   # (B, 1)
        drop = stf.cast(stf.less(x, thresh), stf.float32)
        x = x + drop * _NEG
    return x


def sample_token(logits, temperature=1.0, top_k=0, top_p=1.0, seed=None,
                 name=None):
    """Draw one token per row from transformed ``logits (B, V)``.

    Returns ``(tok (B,) int32, logp (B,) f32)`` — the log-probability
    is under the TRANSFORMED distribution (what was actually sampled
    from), matching what the greedy path reports for argmax.
    """
    b = int(logits.shape[0])
    vocab = int(logits.shape[1])
    x = sampling_logits_transform(logits, temperature=temperature,
                                  top_k=top_k, top_p=top_p)
    u = stf.random_uniform([b, vocab], minval=1e-7, maxval=1.0,
                           dtype=stf.float32, seed=seed,
                           name=(name or "sample") + "_u")
    gumbel = -stf.log(-stf.log(u))
    tok = stf.cast(stf.argmax(x + gumbel, -1, output_type=stf.int32),
                   stf.int32)
    logp_all = stf.nn.log_softmax(x, axis=-1)
    logp = stf.reduce_sum(
        logp_all * stf.one_hot(tok, vocab, dtype=stf.float32), axis=-1)
    return tok, logp
