"""Fused sparse softmax cross-entropy (Pallas TPU), fwd + custom VJP.

Replaces the reference's two-kernel softmax→xent chain
(ref: tensorflow/core/kernels/xent_op.cc, softmax_op.cc). For LM/BERT-size
vocabularies the [batch, vocab] logits tensor dominates HBM traffic; this
kernel streams each row block once, computing max, logsumexp and the label
logit in a single pass, and the backward emits (softmax - onehot) * g
without re-reading intermediates.

logits: (rows, vocab) any float dtype; labels: (rows,) int32 (carried as
(rows, 1) tiles — Mosaic-legal shapes). Returns per-row loss, f32.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .common import cdiv, pad_dim, round_up, use_interpret

DEFAULT_BLOCK_ROWS = 128


def _fwd_kernel(logits_ref, labels_ref, loss_ref, lse_ref):
    x = logits_ref[:].astype(jnp.float32)           # (br, vocab)
    labels = labels_ref[:]                          # (br, 1)
    m = jnp.max(x, axis=-1, keepdims=True)
    lse = m + jnp.log(jnp.sum(jnp.exp(x - m), axis=-1, keepdims=True))
    cols = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    label_logit = jnp.sum(
        jnp.where(cols == labels, x, 0.0), axis=-1, keepdims=True)
    loss_ref[:] = lse - label_logit
    lse_ref[:] = lse


def _bwd_kernel(logits_ref, labels_ref, lse_ref, g_ref, dx_ref):
    x = logits_ref[:].astype(jnp.float32)
    labels = labels_ref[:]                          # (br, 1)
    lse = lse_ref[:]                                # (br, 1)
    g = g_ref[:]                                    # (br, 1)
    p = jnp.exp(x - lse)
    cols = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    onehot = (cols == labels).astype(jnp.float32)
    dx_ref[:] = ((p - onehot) * g).astype(dx_ref.dtype)


def _fwd(logits, labels, block_rows):
    rows, vocab = logits.shape
    loss, lse = pl.pallas_call(
        _fwd_kernel,
        grid=(cdiv(rows, block_rows),),
        in_specs=[
            pl.BlockSpec((block_rows, vocab), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
        ],
        interpret=use_interpret(),
    )(logits, labels)
    return loss, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _xent_2d(logits, labels, block_rows):
    loss, _ = _fwd(logits, labels, block_rows)
    return loss


def _xent_fwd_rule(logits, labels, block_rows):
    loss, lse = _fwd(logits, labels, block_rows)
    return loss, (logits, labels, lse)


def _xent_bwd_rule(block_rows, res, g):
    logits, labels, lse = res
    rows, vocab = logits.shape
    dx = pl.pallas_call(
        _bwd_kernel,
        grid=(cdiv(rows, block_rows),),
        in_specs=[
            pl.BlockSpec((block_rows, vocab), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_rows, vocab), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, vocab), logits.dtype),
        interpret=use_interpret(),
    )(logits, labels, lse, g)
    return dx, None


_xent_2d.defvjp(_xent_fwd_rule, _xent_bwd_rule)


def softmax_cross_entropy(logits, labels, *,
                          block_rows=DEFAULT_BLOCK_ROWS):
    """Per-example sparse softmax xent. logits: (..., vocab),
    labels: (...,) int. Returns f32 loss of shape (...)."""
    orig = logits.shape
    vocab = orig[-1]
    rows = 1
    for s in orig[:-1]:
        rows *= s
    l2 = logits.reshape(rows, vocab)
    lab = labels.reshape(rows, 1).astype(jnp.int32)
    block_rows = min(block_rows, round_up(rows, 8))
    rp = round_up(rows, block_rows)
    l2 = pad_dim(l2, 0, rp)
    lab = pad_dim(lab, 0, rp)
    loss = _xent_2d(l2, lab, int(block_rows))
    return loss[:rows, 0].reshape(orig[:-1])


def softmax_cross_entropy_reference(logits, labels):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.take_along_axis(
        logp, labels[..., None].astype(jnp.int32), axis=-1)[..., 0]
